"""SELL-C-sigma packing + Pallas row-block SpMV for general (non-banded) CSR.

The bench kernel sweep shows a ~1000x gap between the banded fast path and
the general one: packed-DIA reaches 57.6 GFLOP/s while the segment path
sits at 0.01-0.04 (BENCH_NOTES.md). DIA only covers banded matrices, so
every non-banded workload (eigsh, integrate Jacobians, csgraph, AMG
hierarchies) paid the slow path per matvec. SELL-C-sigma (Kreutzer et al.,
SISC 2014) is the standard SIMD-friendly packing for skewed row profiles
on wide-vector hardware:

  * rows are sorted by degree within sigma-row windows (bounded reordering
    keeps cache locality of x), then sliced into chunks of C rows;
  * each chunk is padded to its OWN max degree — near-zero pad waste even
    under power-law skew, where plain ELL pads every row to the global max;
  * chunks of equal padded width are grouped into **slabs**, each stored as
    plane-major ``[K, R]`` index/value planes, so SpMV is contiguous 1-D
    gathers + VPU adds per plane (the shape TPUs like; no scatter, no
    segment ids) with a bounded number of static shapes per matrix.

Packing is one-time host-side work (the prepare/execute split — the
reference keeps its CSR stores resident across task launches the same
way; legate.sparse ``set_key_partition``, SURVEY §1); the packed operator
is cached library-wide in ``sparse_tpu.plan_cache`` so solvers reuse it
across a whole solve. The pure-XLA formulation (``ops.spmv.csr_spmv_sell``)
is the portable default; the Pallas row-block kernel here additionally
pins x and the slab planes in VMEM (grid over row blocks of chunks) and
runs in interpret mode off-TPU like ``dia_spmv.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.spmv import csr_spmm_sell, csr_spmv_sell


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


# Slab rows pad to a sublane multiple so the Pallas row blocks tile exactly
# (the row-block tile is the largest power-of-two divisor, see
# ``sell_spmv_pallas``); pad rows carry idx 0 / val 0 (contribute 0 * x[0])
# and are dropped by the pos-gather, which only addresses real rows. Kept
# small: slab-count x ROW_ALIGN x K is pure pad storage.
ROW_ALIGN = 8
# Pallas attempt gates (beyond these the XLA formulation is simply better
# suited: x must fit VMEM whole, and every plane is unrolled in the trace).
PALLAS_MAX_X = 1 << 20
PALLAS_MAX_K = 128


class SellPlan:
    """Static geometry of a packed SELL operator (hashable => jit-static).

    ``slab_meta`` is a tuple of ``(K, rows, pad_rows)`` per slab —
    ``rows`` includes the alignment padding, ``pad_rows`` counts it.
    """

    __slots__ = ("m", "n", "C", "sigma", "slab_meta", "zero_rows", "nnz")

    def __init__(self, m, n, C, sigma, slab_meta, zero_rows, nnz):
        self.m, self.n, self.C, self.sigma = m, n, C, sigma
        self.slab_meta = tuple((int(k), int(r), int(p)) for k, r, p in slab_meta)
        self.zero_rows = int(zero_rows)
        self.nnz = int(nnz)

    @property
    def stored_slots(self) -> int:
        return sum(k * r for k, r, _ in self.slab_meta)

    @property
    def pad_ratio(self) -> float:
        """Stored slots per nonzero (1.0 = zero pad waste)."""
        return self.stored_slots / max(self.nnz, 1)

    def _key(self):
        return (self.m, self.n, self.C, self.sigma, self.slab_meta, self.zero_rows)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, SellPlan) and self._key() == other._key()

    def __repr__(self):
        return (
            f"SellPlan(m={self.m}, n={self.n}, C={self.C}, sigma={self.sigma}, "
            f"slabs={len(self.slab_meta)}, pad_ratio={self.pad_ratio:.3f})"
        )


def sell_pack(indptr, indices, data, shape, C=None, sigma=None, max_slabs=None,
              with_srcs=False):
    """Pack host CSR buffers into the SELL-C-sigma slab layout.

    Pure numpy (construction-time, never inside solver loops — the same
    discipline as ``ops.conv``). Returns ``(plan, slabs, pos)`` where
    ``slabs`` is a tuple of plane-major ``(idx_t, val_t)`` jnp pairs and
    ``pos`` maps original row -> packed position. Chunk widths are grouped
    exactly; if that yields more than ``max_slabs`` distinct widths
    (pathological profiles), widths quantize up to powers of two first —
    at most 2x pad on the affected chunks, bounded compile size always.

    ``with_srcs=True`` additionally returns a tuple of per-slab ``[K, R]``
    source maps (packed slot -> original nnz position, -1 for pad slots):
    the pattern-reuse handle of the batched subsystem
    (``sparse_tpu.batch.operator``) — a whole stack of same-pattern value
    vectors repacks on device as one gather through these maps, so the
    host-side pack runs once per *pattern*, not once per matrix.
    """
    from ..config import settings

    C = int(C or settings.sell_chunk)
    sigma = int(sigma if sigma is not None else settings.sell_sigma)
    max_slabs = int(max_slabs or settings.sell_max_slabs)
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    data = np.asarray(data)
    m, n = int(shape[0]), int(shape[1])
    nnz = int(data.shape[0])
    counts = (indptr[1:] - indptr[:-1]).astype(np.int64)

    # sigma-window degree sort (descending, stable): bounded reordering.
    sigma_eff = max(min(sigma if sigma > 0 else m, m), 1) if m else 1
    perm = np.arange(m, dtype=np.int64)
    for lo in range(0, m, sigma_eff):
        hi = min(lo + sigma_eff, m)
        order = np.argsort(-counts[lo:hi], kind="stable")
        perm[lo:hi] = lo + order

    # C-row chunks, each padded to its own max degree.
    nchunks = (m + C - 1) // C
    chunk_w = np.zeros(nchunks, dtype=np.int64)
    for c in range(nchunks):
        rws = perm[c * C : (c + 1) * C]
        chunk_w[c] = counts[rws].max() if rws.size else 0

    widths = np.unique(chunk_w[chunk_w > 0])
    if len(widths) > max_slabs:
        chunk_w = np.where(
            chunk_w > 0, 2 ** np.ceil(np.log2(chunk_w.clip(1))).astype(np.int64), 0
        )
        widths = np.unique(chunk_w[chunk_w > 0])

    idt = indices.dtype if indices.dtype in (np.int32, np.int64) else np.int32
    src_dt = np.int32 if nnz < 2**31 else np.int64
    slabs = []
    srcs = []
    slab_meta = []
    packed_rows = []  # original row ids, slab-major packed order
    for K in widths.tolist():
        chunks = np.nonzero(chunk_w == K)[0]
        rws = np.concatenate([perm[c * C : (c + 1) * C] for c in chunks])
        R = _round_up(len(rws), ROW_ALIGN)
        idx_t = np.zeros((K, R), dtype=idt)
        val_t = np.zeros((K, R), dtype=data.dtype)
        L = counts[rws]
        rr = np.repeat(np.arange(len(rws), dtype=np.int64), L)
        slot = np.arange(int(L.sum()), dtype=np.int64) - np.repeat(
            np.cumsum(L) - L, L
        )
        src = np.repeat(indptr[rws].astype(np.int64), L) + slot
        idx_t[slot, rr] = indices[src]
        val_t[slot, rr] = data[src]
        slabs.append((jnp.asarray(idx_t), jnp.asarray(val_t)))
        if with_srcs:
            src_t = np.full((K, R), -1, dtype=src_dt)
            src_t[slot, rr] = src.astype(src_dt)
            srcs.append(jnp.asarray(src_t))
        slab_meta.append((K, R, R - len(rws)))
        packed_rows.append(rws)
        packed_rows.append(np.full(R - len(rws), -1, dtype=np.int64))  # pad rows

    # trailing zero block for all-empty rows (chunk width 0)
    zero_chunks = np.nonzero(chunk_w == 0)[0]
    zero_rws = (
        np.concatenate([perm[c * C : (c + 1) * C] for c in zero_chunks])
        if len(zero_chunks)
        else np.zeros(0, dtype=np.int64)
    )
    packed_rows.append(zero_rws)

    flat = np.concatenate(packed_rows) if packed_rows else np.zeros(0, np.int64)
    pos = np.zeros(m, dtype=np.int64)
    real = flat >= 0
    pos[flat[real]] = np.nonzero(real)[0]
    pos_dt = np.int32 if len(flat) < 2**31 else np.int64

    plan = SellPlan(m, n, C, sigma_eff, slab_meta, len(zero_rws), nnz)
    if with_srcs:
        return plan, tuple(slabs), jnp.asarray(pos.astype(pos_dt)), tuple(srcs)
    return plan, tuple(slabs), jnp.asarray(pos.astype(pos_dt))


# ---------------------------------------------------------------------------
# Pallas row-block kernel: x + one slab's [K, TM] plane window in VMEM,
# grid over TM-row blocks of the slab (TM rows = TM/C chunks per step).
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("K", "TM", "interpret", "acc_dtype"))
def _sell_slab_pallas(idx_t, val_t, x, K: int, TM: int, interpret: bool = False,
                      acc_dtype=None):
    R = idx_t.shape[1]
    out_dt = acc_dtype or jnp.result_type(val_t.dtype, x.dtype)

    def kernel(x_ref, idx_ref, val_ref, y_ref):
        acc = jnp.zeros((TM,), dtype=out_dt)
        for k in range(K):  # static per slab: plane loads unroll
            # value planes load at their storage width; the in-register
            # convert widens the product to the accumulation dtype
            # (a no-op when acc_dtype is None — ISSUE 15)
            acc = acc + (
                val_ref[k, :].astype(out_dt)
                * x_ref[idx_ref[k, :]].astype(out_dt)
            )
        y_ref[:] = acc

    return pl.pallas_call(
        kernel,
        grid=(R // TM,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # x resident whole
            pl.BlockSpec((K, TM), lambda g: (0, g), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, TM), lambda g: (0, g), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TM,), lambda g: (g,), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R,), out_dt),
        interpret=interpret,
    )(x, idx_t, val_t)


def sell_spmv_pallas(plan: SellPlan, slabs, pos, x, interpret=None,
                     acc_dtype=None):
    """y = A @ x via the per-slab Pallas row-block kernel (+ XLA glue for
    the concat/pos-gather). ``interpret=None`` auto-selects interpret mode
    off-TPU like ``dia_spmv.py``. Raises when Mosaic cannot lower the
    in-VMEM gather — callers go through :class:`PreparedCSR`, which fails
    over to the XLA formulation once and remembers. ``acc_dtype`` is the
    storage/accumulation split (ISSUE 15): narrow value planes, wide
    in-register accumulation."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out_dt = acc_dtype or jnp.result_type(
        slabs[0][1].dtype if slabs else x.dtype, x.dtype
    )
    parts = []
    for (idx_t, val_t), (K, R, _) in zip(slabs, plan.slab_meta):
        TM = ROW_ALIGN  # rows are ROW_ALIGN-padded, so this always divides
        while TM * 2 <= 1024 and R % (TM * 2) == 0:
            TM *= 2
        parts.append(
            _sell_slab_pallas(idx_t, val_t, x, K, TM, interpret,
                              acc_dtype=acc_dtype).astype(out_dt)
        )
    if plan.zero_rows:
        parts.append(jnp.zeros((plan.zero_rows,), dtype=out_dt))
    if not parts:
        return jnp.zeros((plan.m,), dtype=out_dt)
    packed = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return packed[pos]


@partial(jax.jit, static_argnames=("K", "TM", "interpret", "acc_dtype"))
def _sell_slab_pallas_batched(idx_t, val_bt, X, K: int, TM: int,
                              interpret: bool = False, acc_dtype=None):
    """Batched form of :func:`_sell_slab_pallas`: the grid gains a leading
    batch dimension, the shared ``[K, R]`` index planes stay resident while
    value planes ``[B, K, R]`` and per-lane x vectors ``[B, n]`` stream one
    lane at a time — the whole same-pattern stack runs as one kernel launch
    instead of B dispatches. ``acc_dtype`` widens the per-plane products
    in-register (ISSUE 15) while the value planes stream at storage
    width."""
    B, _, R = val_bt.shape
    out_dt = acc_dtype or jnp.result_type(val_bt.dtype, X.dtype)

    def kernel(x_ref, idx_ref, val_ref, y_ref):
        acc = jnp.zeros((TM,), dtype=out_dt)
        for k in range(K):  # static per slab: plane loads unroll
            acc = acc + (
                val_ref[0, k, :].astype(out_dt)
                * x_ref[0, idx_ref[k, :]].astype(out_dt)
            )
        y_ref[0, :] = acc

    return pl.pallas_call(
        kernel,
        grid=(B, R // TM),
        in_specs=[
            # one lane of x resident per grid step
            pl.BlockSpec((1, X.shape[1]), lambda b, g: (b, 0),
                         memory_space=pltpu.VMEM),
            # index planes are PATTERN state: shared by every lane
            pl.BlockSpec((K, TM), lambda b, g: (0, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, K, TM), lambda b, g: (b, 0, g),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, TM), lambda b, g: (b, g),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, R), out_dt),
        interpret=interpret,
    )(X, idx_t, val_bt)


def sell_spmv_pallas_batched(plan: SellPlan, idx_slabs, val_slabs, pos, X,
                             interpret=None, acc_dtype=None):
    """Y = A_b @ x_b per lane via the batch-grid Pallas row-block kernel.

    ``idx_slabs`` are the shared pattern index planes, ``val_slabs`` the
    stacked ``[B, K, R]`` value planes (``sparse_tpu.batch.operator`` packs
    them through the pattern's source maps), ``X`` is ``[B, n]``. Same
    failover contract as :func:`sell_spmv_pallas` — callers catch the
    Mosaic lowering error once and fall back to the XLA formulation.
    ``acc_dtype`` is the storage/accumulation split (ISSUE 15)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B = X.shape[0]
    out_dt = acc_dtype or jnp.result_type(
        val_slabs[0].dtype if val_slabs else X.dtype, X.dtype
    )
    parts = []
    for idx_t, val_bt, (K, R, _) in zip(idx_slabs, val_slabs, plan.slab_meta):
        TM = ROW_ALIGN  # rows are ROW_ALIGN-padded, so this always divides
        while TM * 2 <= 1024 and R % (TM * 2) == 0:
            TM *= 2
        parts.append(
            _sell_slab_pallas_batched(idx_t, val_bt, X, K, TM, interpret,
                                      acc_dtype=acc_dtype)
            .astype(out_dt)
        )
    if plan.zero_rows:
        parts.append(jnp.zeros((B, plan.zero_rows), dtype=out_dt))
    if not parts:
        return jnp.zeros((B, plan.m), dtype=out_dt)
    packed = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
    return packed[:, pos]


class PreparedCSR:
    """A general CSR operator packed once into the SELL slab layout.

    The prepare/execute split for non-banded SpMV (the counterpart of
    round-3's :class:`~sparse_tpu.kernels.dia_spmv.PreparedDia`): one-time
    host packing, then every call is gathers + adds over resident planes.
    Format classes obtain one through ``sparse_tpu.plan_cache`` so solver
    loops (and repeated ``A @ x`` calls) never repack.

    ``__call__`` dispatches per ``settings.spmv_mode``: the Pallas kernel
    under ``'pallas'`` (gated on f32 / VMEM-resident x / bounded plane
    count, failing over to XLA once — remembered — when the backend has no
    lowering), the XLA slab formulation otherwise.
    """

    __slots__ = ("plan", "slabs", "pos", "__weakref__")

    #: failover-registry kernel name (resilience/failover.py)
    KERNEL = "sell_spmv"

    def __init__(self, indptr, indices, data, shape, C=None, sigma=None,
                 max_slabs=None):
        self.plan, self.slabs, self.pos = sell_pack(
            indptr, indices, data, shape, C=C, sigma=sigma, max_slabs=max_slabs
        )
        from .. import telemetry

        telemetry.count("kernel.sell_pack")

    @classmethod
    def from_parts(cls, plan: SellPlan, slabs, pos) -> "PreparedCSR":
        """Reassemble a prepared operator from already-packed parts —
        the vault codec's constructor (``sparse_tpu.vault._codecs``): a
        verified disk artifact re-enters without re-running the host
        pack (and without counting a fresh ``kernel.sell_pack``)."""
        prep = object.__new__(cls)
        prep.plan = plan
        prep.slabs = tuple((it, vt) for it, vt in slabs)
        prep.pos = pos
        return prep

    @property
    def shape(self):
        return (self.plan.m, self.plan.n)

    def _pallas_viable(self, x) -> bool:
        from ..resilience import failover

        if failover.failed(self.KERNEL, self) or not self.slabs:
            return False
        if x.shape[0] > PALLAS_MAX_X:
            return False
        if any(K > PALLAS_MAX_K for K, _, _ in self.plan.slab_meta):
            return False
        dt = jnp.result_type(self.slabs[0][1].dtype, x.dtype)
        return dt == jnp.float32

    def matvec_xla(self, x):
        return csr_spmv_sell(
            self.slabs, self.pos, jnp.asarray(x), self.plan.zero_rows
        )

    def matvec_pallas(self, x, interpret=None):
        return sell_spmv_pallas(
            self.plan, self.slabs, self.pos, jnp.asarray(x), interpret
        )

    def matmat(self, B):
        return csr_spmm_sell(
            self.slabs, self.pos, jnp.asarray(B), self.plan.zero_rows
        )

    def probe_pallas(self, x=None) -> bool:
        """Probe-based reinstate hook: run one real Pallas matvec; on
        success any failover latch for this operator clears
        (``kernel.reinstate`` event) and later calls retry the kernel."""
        from ..resilience import failover

        if x is None:
            x = jnp.zeros((self.plan.n,), dtype=jnp.float32)
        return failover.probe(
            self.KERNEL, self,
            lambda: jax.block_until_ready(self.matvec_pallas(x)),
        )

    def __call__(self, x):
        from .. import telemetry
        from ..config import settings
        from ..resilience import failover

        telemetry.count("kernel.sell_spmv")
        if settings.spmv_mode == "pallas" and self._pallas_viable(x):
            try:
                # forced-failure injection + the shared one-time
                # Pallas->XLA failover ladder (resilience/failover.py)
                failover.maybe_inject(self.KERNEL)
                return self.matvec_pallas(x)
            except (ValueError, NotImplementedError) as e:
                failover.handle(self.KERNEL, self, e)
        return self.matvec_xla(x)
