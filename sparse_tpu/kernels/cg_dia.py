"""Fused CG iteration on a DIA matrix — two Pallas kernels per iteration.

The plain CG loop issues ~7 separate elementwise/reduction XLA kernels plus
an SpMV per iteration; each streams full-length vectors through HBM. Here
one iteration is exactly two fused passes:

  * kernel A: p_new = r + beta*p computed IN the SpMV's halo window
    (redundant halo recompute instead of a barrier), q = A p_new from
    row-indexed diagonal planes, and the partial dot <p_new, q> — one
    window read of r and p, one streamed read of the planes, one write of
    p_new and q, one scalar.
  * kernel B: x += alpha*p, r -= alpha*q and the partial dot <r, r> (the
    next iteration's rho) — tile-local streams, no halos.

Layout: vectors live PADDED at [L] = [(G+2)*TM] with one all-zero block on
each side; the halo B (band rounded to the 1024-element HBM tiling) fits
inside that block for any tile size TM >= B, so out-block index maps shift
by exactly one block while window DMA starts (gg*TM - B) stay 1024-aligned.
Row-indexed planes (data_row[k, i] = coefficient of diagonal k at ROW i)
make the plane stream halo-free.

Reference analog: the fused AXPBY task family (linalg.py:479-496) taken to
its limit — the reference fuses two vector ops per launch; the TPU version
fuses the entire iteration into two memory passes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def _plan(m: int, offsets: tuple, tile: int = 16384):
    """Tile TM and halo B (both multiples of the 1024-element HBM tiling).

    B covers the band; TM is as large as ``tile`` allows (fewer grid steps
    -> less per-step overhead, smaller window/tile overlap) but at least B
    so the one-block [L] padding contains the halo window.
    """
    band = max(max((abs(int(o)) for o in offsets), default=0), 1)
    B = _round_up(band, 1024)
    TM = max(B, min(_round_up(tile, 1024), _round_up(m, 1024)))
    G = (m + TM - 1) // TM
    return TM, B, G


def _row_planes(data, offsets: tuple, TM: int, B: int, G: int, m: int):
    """Column-indexed scipy DIA planes -> flat row-indexed [D * m_pad].

    Flat 1-D packing (not [Dp, m_pad]) so kernel A fetches exactly D
    aligned [TM] plane slices per tile by manual DMA — no ceil8(D) zero
    planes and no halo on the plane stream. Delegates to
    :func:`..dia_spmv.dia_pack` (single source for the packing identity).
    ``m`` is the true row count — the junk-row mask bound — which may be
    smaller than the plane width (scipy accepts over-wide DIA data)."""
    from .dia_spmv import DiaPlan, dia_pack

    return dia_pack(data, DiaPlan(offsets, m, data.shape[1], TM, B, G))


def _pad_vec(v, TM: int, G: int):
    """[m] -> [L] padded with one zero block each side (+ tail zeros)."""
    m = v.shape[0]
    L = (G + 2) * TM
    out = jnp.zeros((L,), dtype=v.dtype)
    return jax.lax.dynamic_update_slice(out, v, (TM,))


def _unpad_vec(vp, m: int, TM: int):
    return jax.lax.dynamic_slice(vp, (TM,), (m,))


def _kernel_a(offsets: tuple, TM: int, B: int, win: int, D: int, m_pad: int):
    """p_new (windowed), q, and the <p, q> partial.

    r/p windows AND the D flat row-indexed plane slices are all manual
    double-buffered DMAs (sem slots: 0=r, 1=p, 2..2+D-1=planes)."""

    def kernel(beta_ref, r_hbm, p_hbm, planes_hbm, pnew_ref, q_ref, pq_ref,
               rwinA, rwinB, pwinA, pwinB, dwinA, dwinB, semA, semB):
        gg = pl.program_id(0)
        Gp2 = pl.num_programs(0)

        @pl.when(gg == 0)
        def _():
            pq_ref[0, 0] = jnp.zeros((), pq_ref.dtype)

        def copies(rwin, pwin, dwin, sem, g2):
            start = g2 * TM - B
            yield pltpu.make_async_copy(
                r_hbm.at[pl.ds(start, win)], rwin, sem.at[0]
            )
            yield pltpu.make_async_copy(
                p_hbm.at[pl.ds(start, win)], pwin, sem.at[1]
            )
            for k in range(D):
                yield pltpu.make_async_copy(
                    planes_hbm.at[pl.ds(k * m_pad + (g2 - 1) * TM, TM)],
                    dwin.at[k],
                    sem.at[2 + k],
                )

        def issue(rwin, pwin, dwin, sem, g2):
            for c in copies(rwin, pwin, dwin, sem, g2):
                c.start()

        def wait(rwin, pwin, dwin, sem, g2):
            for c in copies(rwin, pwin, dwin, sem, g2):
                c.wait()

        def interior(rwin, pwin, dwin, sem, rwin_n, pwin_n, dwin_n, sem_n):
            # windows address padded coords [gg*TM - B, (gg+1)*TM + B);
            # the first interior tile (gg == 1) starts at TM - B >= 0
            @pl.when(gg == 1)
            def _():
                issue(rwin, pwin, dwin, sem, gg)

            @pl.when(gg + 1 < Gp2 - 1)
            def _():
                issue(rwin_n, pwin_n, dwin_n, sem_n, gg + 1)

            wait(rwin, pwin, dwin, sem, gg)
            beta = beta_ref[0, 0]
            pw = rwin[:] + beta * pwin[:]
            acc = jnp.zeros((TM,), dtype=q_ref.dtype)
            for k, o in enumerate(offsets):
                lo = B + int(o)
                acc = acc + dwin[k, :] * pw[lo : lo + TM]
            mid = pw[B : B + TM]
            pnew_ref[:] = mid
            q_ref[:] = acc
            pq_ref[0, 0] += jnp.sum(mid * acc)

        def halo():
            pnew_ref[:] = jnp.zeros((TM,), pnew_ref.dtype)
            q_ref[:] = jnp.zeros((TM,), q_ref.dtype)

        is_halo = (gg == 0) | (gg == Gp2 - 1)

        @pl.when(~is_halo & (gg % 2 == 1))
        def _():
            interior(rwinA, pwinA, dwinA, semA, rwinB, pwinB, dwinB, semB)

        @pl.when(~is_halo & (gg % 2 == 0))
        def _():
            interior(rwinB, pwinB, dwinB, semB, rwinA, pwinA, dwinA, semA)

        @pl.when(is_halo)
        def _():
            halo()

    return kernel


def _kernel_b():
    """x += alpha p, r -= alpha q, <r_new, r_new> partial."""

    def kernel(alpha_ref, x_ref, p_ref, r_ref, q_ref, xo_ref, ro_ref, rr_ref):
        gg = pl.program_id(0)

        @pl.when(gg == 0)
        def _():
            rr_ref[0, 0] = jnp.zeros((), rr_ref.dtype)

        alpha = alpha_ref[0, 0]
        r_new = r_ref[:] - alpha * q_ref[:]
        xo_ref[:] = x_ref[:] + alpha * p_ref[:]
        ro_ref[:] = r_new
        rr_ref[0, 0] += jnp.sum(r_new * r_new)

    return kernel


@partial(
    jax.jit,
    static_argnames=("offsets", "m", "iters", "tile", "interpret"),
)
def cg_dia_fused(
    data, offsets: tuple, b, x0, m: int, iters: int = 300, tile: int = 16384,
    interpret: bool = False
):
    """``iters`` fixed CG iterations on the DIA matrix (throughput mode).

    Returns (x, r, rho) with rho = ||r||^2. Matches ``cg_step_dia``'s
    recurrence exactly (same beta/alpha guards) — two fused passes per
    iteration instead of an SpMV plus a train of elementwise kernels.
    ``x0=None`` starts from zero and skips the setup SpMV (r0 = b).
    """
    dt = jnp.result_type(data.dtype, b.dtype)
    TM, B, G = _plan(m, offsets, tile=tile)
    win = TM + 2 * B
    m_pad = G * TM
    L = (G + 2) * TM
    D = len(offsets)
    Dp = _round_up(D, 8)

    planes_row = _row_planes(data.astype(dt), offsets, TM, B, G, m)
    bp = _pad_vec(b.astype(dt), TM, G)
    xp = (
        jnp.zeros(((G + 2) * TM,), dt)
        if x0 is None
        else _pad_vec(x0.astype(dt), TM, G)
    )

    kA = pl.pallas_call(
        _kernel_a(offsets, TM, B, win, D, m_pad),
        grid=(G + 2,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda gg: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((1, 1), dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((win,), dt),
            pltpu.VMEM((win,), dt),
            pltpu.VMEM((win,), dt),
            pltpu.VMEM((win,), dt),
            pltpu.VMEM((Dp, TM), dt),
            pltpu.VMEM((Dp, TM), dt),
            pltpu.SemaphoreType.DMA((2 + D,)),
            pltpu.SemaphoreType.DMA((2 + D,)),
        ],
        interpret=interpret,
    )

    kB = pl.pallas_call(
        _kernel_b(),
        grid=(G + 2,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda gg: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((1, 1), dt),
        ],
        interpret=interpret,
    )

    if x0 is None:
        rp0 = bp  # r = b - A @ 0
    else:
        from ..ops.dia_spmv import dia_spmv_xla

        r0 = b.astype(dt) - dia_spmv_xla(
            data.astype(dt), offsets, x0.astype(dt), (m, m)
        )
        rp0 = _pad_vec(r0, TM, G)
    rho0 = jnp.vdot(rp0, rp0).real.astype(dt)
    pp0 = jnp.zeros_like(bp)

    def body(_, state):
        xp, rp, pp, rho_prev, rho = state
        beta = jnp.where(rho_prev == 0, 0.0, rho / jnp.where(rho_prev == 0, 1, rho_prev)).astype(dt)
        pnew, q, pq = kA(beta.reshape(1, 1), rp, pp, planes_row)
        alpha = rho / jnp.where(pq[0, 0] == 0, 1, pq[0, 0])
        xp2, rp2, rr = kB(alpha.reshape(1, 1).astype(dt), xp, pnew, rp, q)
        return xp2, rp2, pnew, rho, rr[0, 0]

    state = (xp, rp0, pp0, jnp.zeros((), dt), rho0)
    xp, rp, _, _, rho = jax.lax.fori_loop(0, iters, body, state)
    return _unpad_vec(xp, m, TM), _unpad_vec(rp, m, TM), rho
