"""Fused CG iteration on a DIA matrix — two Pallas kernels per iteration.

The plain CG loop issues ~7 separate elementwise/reduction XLA kernels plus
an SpMV per iteration; each streams full-length vectors through HBM. Here
one iteration is exactly two fused passes:

  * kernel A: p_new = r + beta*p computed IN the SpMV's halo window
    (redundant halo recompute instead of a barrier), q = A p_new from
    row-indexed diagonal planes, and the partial dot <p_new, q> — one
    window read of r and p, one streamed read of the planes, one write of
    p_new and q, one scalar.
  * kernel B: x += alpha*p, r -= alpha*q and the partial dot <r, r> (the
    next iteration's rho) — tile-local streams, no halos.

Layout: vectors live PADDED at [L] = [(G+2)*TM] with one all-zero block on
each side; the halo B (band rounded to the 1024-element HBM tiling) fits
inside that block for any tile size TM >= B, so out-block index maps shift
by exactly one block while window DMA starts (gg*TM - B) stay 1024-aligned.
Row-indexed planes (data_row[k, i] = coefficient of diagonal k at ROW i)
make the plane stream halo-free.

Reference analog: the fused AXPBY task family (linalg.py:479-496) taken to
its limit — the reference fuses two vector ops per launch; the TPU version
fuses the entire iteration into two memory passes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def _plan(m: int, offsets: tuple, tile: int = 16384):
    """Tile TM and halo B (both multiples of the 1024-element HBM tiling).

    B covers the band; TM is as large as ``tile`` allows (fewer grid steps
    -> less per-step overhead, smaller window/tile overlap) but at least B
    so the one-block [L] padding contains the halo window.
    """
    band = max(max((abs(int(o)) for o in offsets), default=0), 1)
    B = _round_up(band, 1024)
    TM = max(B, min(_round_up(tile, 1024), _round_up(m, 1024)))
    G = (m + TM - 1) // TM
    return TM, B, G


def _row_planes(data, offsets: tuple, TM: int, B: int, G: int, m: int):
    """Column-indexed scipy DIA planes -> flat row-indexed [D * m_pad].

    Flat 1-D packing (not [Dp, m_pad]) so kernel A fetches exactly D
    aligned [TM] plane slices per tile by manual DMA — no ceil8(D) zero
    planes and no halo on the plane stream. Delegates to
    :func:`..dia_spmv.dia_pack` (single source for the packing identity).
    ``m`` is the true row count — the junk-row mask bound — which may be
    smaller than the plane width (scipy accepts over-wide DIA data)."""
    from .dia_spmv import DiaPlan, dia_pack

    return dia_pack(data, DiaPlan(offsets, m, data.shape[1], TM, B, G))


def _resolve_plane_dtype(plane_dtype, dt, TM: int = 2048):
    """Stream dtype for the packed planes (bf16 halves matrix traffic;
    callers opt in only when values are exactly representable); alignment
    policy shared with the SpMV kernels (dia_spmv.plane_stream_dtype)."""
    from .dia_spmv import plane_stream_dtype

    return plane_stream_dtype(plane_dtype, dt, TM)


def _pad_vec(v, TM: int, G: int):
    """[m] -> [L] padded with one zero block each side (+ tail zeros)."""
    m = v.shape[0]
    L = (G + 2) * TM
    out = jnp.zeros((L,), dtype=v.dtype)
    return jax.lax.dynamic_update_slice(out, v, (TM,))


def _unpad_vec(vp, m: int, TM: int):
    return jax.lax.dynamic_slice(vp, (TM,), (m,))


def _kernel_a(offsets: tuple, TM: int, B: int, win: int, D: int, m_pad: int):
    """p_new (windowed), q, and the <p, q> partial.

    r/p windows AND the D flat row-indexed plane slices are all manual
    double-buffered DMAs (sem slots: 0=r, 1=p, 2..2+D-1=planes). Planes
    land in D separate 1-D (TM,) VMEM buffers per slot — Mosaic rejects
    DMA into one row of a 2-D (8,128)-tiled scratch."""

    def kernel(beta_ref, r_hbm, p_hbm, planes_hbm, pnew_ref, q_ref, pq_ref,
               *scr):
        rwinA, rwinB, pwinA, pwinB = scr[:4]
        dwinA, dwinB = scr[4 : 4 + D], scr[4 + D : 4 + 2 * D]
        semA, semB = scr[4 + 2 * D :]
        gg = pl.program_id(0)
        Gp2 = pl.num_programs(0)

        @pl.when(gg == 0)
        def _():
            pq_ref[0, 0] = jnp.zeros((), pq_ref.dtype)

        def copies(rwin, pwin, dwin, sem, g2):
            # g2*TM - B is divisible by the 1024-element HBM tiling (TM and
            # B both are), but Mosaic's prover can't see through the
            # subtraction — assert it explicitly or the compile fails.
            start = pl.multiple_of(g2 * TM - B, 1024)
            yield pltpu.make_async_copy(
                r_hbm.at[pl.ds(start, win)], rwin, sem.at[0]
            )
            yield pltpu.make_async_copy(
                p_hbm.at[pl.ds(start, win)], pwin, sem.at[1]
            )
            for k in range(D):
                yield pltpu.make_async_copy(
                    planes_hbm.at[
                        pl.ds(pl.multiple_of(k * m_pad + (g2 - 1) * TM, TM), TM)
                    ],
                    dwin[k],
                    sem.at[2 + k],
                )

        def issue(rwin, pwin, dwin, sem, g2):
            for c in copies(rwin, pwin, dwin, sem, g2):
                c.start()

        def wait(rwin, pwin, dwin, sem, g2):
            for c in copies(rwin, pwin, dwin, sem, g2):
                c.wait()

        def interior(rwin, pwin, dwin, sem, rwin_n, pwin_n, dwin_n, sem_n):
            # windows address padded coords [gg*TM - B, (gg+1)*TM + B);
            # the first interior tile (gg == 1) starts at TM - B >= 0
            @pl.when(gg == 1)
            def _():
                issue(rwin, pwin, dwin, sem, gg)

            @pl.when(gg + 1 < Gp2 - 1)
            def _():
                issue(rwin_n, pwin_n, dwin_n, sem_n, gg + 1)

            wait(rwin, pwin, dwin, sem, gg)
            beta = beta_ref[0, 0]
            pw = rwin[:] + beta * pwin[:]
            acc = jnp.zeros((TM,), dtype=q_ref.dtype)
            for k, o in enumerate(offsets):
                lo = B + int(o)
                acc = acc + dwin[k][:].astype(acc.dtype) * pw[lo : lo + TM]
            mid = pw[B : B + TM]
            pnew_ref[:] = mid
            q_ref[:] = acc
            # the <p, q> partial reduces at pq_ref's dtype — with the
            # acc_dtype split (ISSUE 15) the recurrence scalars stay
            # wide even when the vector planes are narrow; a no-op
            # convert when the dtypes match
            pq_ref[0, 0] += jnp.sum(
                mid.astype(pq_ref.dtype) * acc.astype(pq_ref.dtype)
            )

        def halo():
            pnew_ref[:] = jnp.zeros((TM,), pnew_ref.dtype)
            q_ref[:] = jnp.zeros((TM,), q_ref.dtype)

        is_halo = (gg == 0) | (gg == Gp2 - 1)

        @pl.when(~is_halo & (gg % 2 == 1))
        def _():
            interior(rwinA, pwinA, dwinA, semA, rwinB, pwinB, dwinB, semB)

        @pl.when(~is_halo & (gg % 2 == 0))
        def _():
            interior(rwinB, pwinB, dwinB, semB, rwinA, pwinA, dwinA, semA)

        @pl.when(is_halo)
        def _():
            halo()

    return kernel


def _kernel_b():
    """x += alpha p, r -= alpha q, <r_new, r_new> partial."""

    def kernel(alpha_ref, x_ref, p_ref, r_ref, q_ref, xo_ref, ro_ref, rr_ref):
        gg = pl.program_id(0)

        @pl.when(gg == 0)
        def _():
            rr_ref[0, 0] = jnp.zeros((), rr_ref.dtype)

        alpha = alpha_ref[0, 0]
        r_new = r_ref[:] - alpha * q_ref[:]
        xo_ref[:] = x_ref[:] + alpha * p_ref[:]
        ro_ref[:] = r_new
        # <r, r> reduces at rr_ref's dtype (the acc_dtype split)
        rr = r_new.astype(rr_ref.dtype)
        rr_ref[0, 0] += jnp.sum(rr * rr)

    return kernel


def _kernel_cgcg(offsets: tuple, TM: int, B: int, win: int, D: int, m_pad: int):
    """One-pass Chronopoulos-Gear CG iteration.

    Given beta_j and alpha_j (scalars), one sweep computes
        s_j = w_j + beta s_{j-1}          (in the halo window)
        r_{j+1} = r_j - alpha s_j         (in the halo window)
        w_{j+1} = A r_{j+1}               (row-indexed planes)
        p_j = r_j + beta p_{j-1};  x_{j+1} = x_j + alpha p_j
    plus both reduction partials rho_{j+1} = <r,r> and mu_{j+1} = <w,r>.
    The halo regions of s/r are recomputed redundantly per tile (same
    trade as kernel A: FLOPs for a barrier). Sem slots: 0=r, 1=w, 2=s
    (windows), 3=p, 4=x (tiles), 5..5+D-1=planes. Planes land in D
    separate 1-D (TM,) VMEM buffers per slot (Mosaic DMA alignment)."""

    def kernel(ab_ref, r_hbm, w_hbm, s_hbm, p_hbm, x_hbm, planes_hbm,
               xo_ref, ro_ref, po_ref, so_ref, wo_ref, dots_ref,
               *scr):
        rwinA, wwinA, swinA, ptileA, xtileA = scr[:5]
        dwinA = scr[5 : 5 + D]
        rwinB, wwinB, swinB, ptileB, xtileB = scr[5 + D : 10 + D]
        dwinB = scr[10 + D : 10 + 2 * D]
        semA, semB = scr[10 + 2 * D :]
        bufA = (rwinA, wwinA, swinA, ptileA, xtileA, dwinA)
        bufB = (rwinB, wwinB, swinB, ptileB, xtileB, dwinB)
        gg = pl.program_id(0)
        Gp2 = pl.num_programs(0)

        @pl.when(gg == 0)
        def _():
            dots_ref[0, 0] = jnp.zeros((), dots_ref.dtype)
            dots_ref[0, 1] = jnp.zeros((), dots_ref.dtype)

        def copies(buf, sem, g2):
            # see _kernel_a: assert 1024-divisibility past the subtraction
            start = pl.multiple_of(g2 * TM - B, 1024)
            rwin, wwin, swin, ptile, xtile, dwin = buf
            yield pltpu.make_async_copy(
                r_hbm.at[pl.ds(start, win)], rwin, sem.at[0]
            )
            yield pltpu.make_async_copy(
                w_hbm.at[pl.ds(start, win)], wwin, sem.at[1]
            )
            yield pltpu.make_async_copy(
                s_hbm.at[pl.ds(start, win)], swin, sem.at[2]
            )
            yield pltpu.make_async_copy(
                p_hbm.at[pl.ds(g2 * TM, TM)], ptile, sem.at[3]
            )
            yield pltpu.make_async_copy(
                x_hbm.at[pl.ds(g2 * TM, TM)], xtile, sem.at[4]
            )
            for k in range(D):
                yield pltpu.make_async_copy(
                    planes_hbm.at[
                        pl.ds(pl.multiple_of(k * m_pad + (g2 - 1) * TM, TM), TM)
                    ],
                    dwin[k],
                    sem.at[5 + k],
                )

        def issue(buf, sem, g2):
            for c in copies(buf, sem, g2):
                c.start()

        def wait(buf, sem, g2):
            for c in copies(buf, sem, g2):
                c.wait()

        def interior(buf, sem, buf_n, sem_n):
            @pl.when(gg == 1)
            def _():
                issue(buf, sem, gg)

            @pl.when(gg + 1 < Gp2 - 1)
            def _():
                issue(buf_n, sem_n, gg + 1)

            wait(buf, sem, gg)
            rwin, wwin, swin, ptile, xtile, dwin = buf
            beta = ab_ref[0, 0]
            alpha = ab_ref[0, 1]
            s_new = wwin[:] + beta * swin[:]        # s_j on the window
            r_new = rwin[:] - alpha * s_new         # r_{j+1} on the window
            acc = jnp.zeros((TM,), dtype=wo_ref.dtype)
            for k, o in enumerate(offsets):
                lo = B + int(o)
                acc = acc + dwin[k][:].astype(acc.dtype) * r_new[lo : lo + TM]
            p_new = rwin[B : B + TM] + beta * ptile[:]
            xo_ref[:] = xtile[:] + alpha * p_new
            r_mid = r_new[B : B + TM]
            ro_ref[:] = r_mid
            po_ref[:] = p_new
            so_ref[:] = s_new[B : B + TM]
            wo_ref[:] = acc
            # both recurrence dot partials reduce at dots_ref's dtype
            # (the acc_dtype split — ISSUE 15)
            r_wide = r_mid.astype(dots_ref.dtype)
            dots_ref[0, 0] += jnp.sum(r_wide * r_wide)
            dots_ref[0, 1] += jnp.sum(acc.astype(dots_ref.dtype) * r_wide)

        def halo():
            z = jnp.zeros((TM,), xo_ref.dtype)
            xo_ref[:] = z
            ro_ref[:] = z
            po_ref[:] = z
            so_ref[:] = z
            wo_ref[:] = z

        is_halo = (gg == 0) | (gg == Gp2 - 1)

        @pl.when(~is_halo & (gg % 2 == 1))
        def _():
            interior(bufA, semA, bufB, semB)

        @pl.when(~is_halo & (gg % 2 == 0))
        def _():
            interior(bufB, semB, bufA, semA)

        @pl.when(is_halo)
        def _():
            halo()

    return kernel


@partial(
    jax.jit,
    static_argnames=("offsets", "m", "iters", "tile", "plane_dtype",
                     "interpret", "acc_dtype"),
)
def cg_dia_fused_onepass(
    data, offsets: tuple, b, x0, m: int, iters: int = 300, tile: int = 16384,
    plane_dtype=None, interpret: bool = False, acc_dtype=None
):
    """``iters`` Chronopoulos-Gear CG iterations — ONE fused pass each.

    Mathematically equivalent to CG (exact arithmetic): the two dot
    products <r,r> and <Ar, r> of the NEXT iteration are computed inside
    the same sweep that applies the current update, so each iteration is a
    single kernel launch + one scalar recurrence instead of two passes.
    alpha comes from the CG-CG recurrence
        alpha_j = rho_j / (mu_j - (beta_j / alpha_{j-1}) rho_j)
    Slightly weaker numerically than two-pass CG (classic s-step result);
    the bench checks residual parity before preferring it.

    ``acc_dtype`` splits the recurrence scalars from the vector dtype
    (ISSUE 15): the rho/mu dot partials reduce — and the beta/alpha
    recurrence runs — at ``acc_dtype`` while vectors and plane streams
    stay at ``dt``/``plane_dtype``. ``None`` = historic single-dtype
    behavior, byte-identical.

    Returns (x, r, rho).
    """
    dt = jnp.result_type(data.dtype, b.dtype)
    adt = jnp.dtype(acc_dtype) if acc_dtype is not None else dt
    TM, B, G = _plan(m, offsets, tile=tile)
    win = TM + 2 * B
    m_pad = G * TM
    L = (G + 2) * TM
    D = len(offsets)

    pdt = _resolve_plane_dtype(plane_dtype, dt, TM)
    planes_row = _row_planes(data.astype(pdt), offsets, TM, B, G, m)

    kern = pl.pallas_call(
        _kernel_cgcg(offsets, TM, B, win, D, m_pad),
        grid=(G + 2,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pl.ANY)] * 6,
        out_specs=[
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM)
            for _ in range(5)
        ]
        + [pl.BlockSpec((1, 2), lambda gg: (0, 0), memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((L,), dt) for _ in range(5)]
        + [jax.ShapeDtypeStruct((1, 2), adt)],
        scratch_shapes=(
            [
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((TM,), dt),
                pltpu.VMEM((TM,), dt),
            ]
            + [pltpu.VMEM((TM,), pdt)] * D
            + [
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((TM,), dt),
                pltpu.VMEM((TM,), dt),
            ]
            + [pltpu.VMEM((TM,), pdt)] * D
            + [
                pltpu.SemaphoreType.DMA((5 + D,)),
                pltpu.SemaphoreType.DMA((5 + D,)),
            ]
        ),
        interpret=interpret,
    )

    from ..ops.dia_spmv import dia_spmv_xla

    if x0 is None:
        r0 = b.astype(dt)
        xp = jnp.zeros((L,), dt)
    else:
        r0 = b.astype(dt) - dia_spmv_xla(
            data.astype(dt), offsets, x0.astype(dt), (m, m)
        )
        xp = _pad_vec(x0.astype(dt), TM, G)
    rp0 = _pad_vec(r0, TM, G)
    w0 = dia_spmv_xla(data.astype(dt), offsets, r0, (m, m))
    wp0 = _pad_vec(w0, TM, G)
    rho0 = jnp.vdot(r0, r0).real.astype(adt)
    mu0 = jnp.vdot(w0, r0).real.astype(adt)
    z = jnp.zeros((L,), dt)

    def body(j, state):
        xp, rp, pp, sp, wp, rho, mu, rho_prev, alpha_prev = state
        # Converged-state guards: once rho hits exact zero every later
        # alpha/beta must collapse to 0 (not NaN) so the frozen x survives
        # the remaining fixed iterations. The scalar recurrence runs at
        # adt (the acc_dtype split); only the SMEM kernel inputs cast
        # down to the vector dtype.
        beta = jnp.where(rho_prev == 0, 0.0, rho / jnp.where(rho_prev == 0, 1, rho_prev)).astype(adt)
        ratio = jnp.where(alpha_prev == 0, 0.0, beta / jnp.where(alpha_prev == 0, 1, alpha_prev))
        denom = mu - ratio * rho
        alpha = jnp.where(denom == 0, 0.0, rho / jnp.where(denom == 0, 1, denom)).astype(adt)
        ab = jnp.stack([beta.astype(dt), alpha.astype(dt)]).reshape(1, 2)
        xp2, rp2, pp2, sp2, wp2, dots = kern(ab, rp, wp, sp, pp, xp, planes_row)
        alpha_next = jnp.where(alpha == 0, 1.0, alpha).astype(adt)
        return (
            xp2, rp2, pp2, sp2, wp2,
            dots[0, 0], dots[0, 1], rho, alpha_next,
        )

    state = (xp, rp0, z, z, wp0, rho0, mu0, jnp.zeros((), adt), jnp.ones((), adt))
    xp, rp, _, _, _, rho, _, _, _ = jax.lax.fori_loop(0, iters, body, state)
    return _unpad_vec(xp, m, TM), _unpad_vec(rp, m, TM), rho


@partial(
    jax.jit,
    static_argnames=(
        "offsets", "m", "iters", "tile", "plane_dtype", "interpret",
        "return_state", "acc_dtype",
    ),
)
def cg_dia_fused(
    data, offsets: tuple, b, x0, m: int, iters: int = 300, tile: int = 16384,
    plane_dtype=None, interpret: bool = False, state=None,
    return_state: bool = False, acc_dtype=None,
):
    """``iters`` fixed CG iterations on the DIA matrix (throughput mode).

    Returns (x, r, rho) with rho = ||r||^2. Matches ``cg_step_dia``'s
    recurrence exactly (same beta/alpha guards) — two fused passes per
    iteration instead of an SpMV plus a train of elementwise kernels.
    ``x0=None`` starts from zero and skips the setup SpMV (r0 = b).

    ``state``/``return_state`` thread the FULL padded CG state
    (xp, rp, pp, rho_prev, rho) across calls, so a tolerance-driven caller
    (``linalg.cg``'s fused fast path) can run in conv-test-sized chunks
    with one host rho fetch per chunk — identical iterates to one long
    run, no CG restart between chunks.

    ``acc_dtype`` is the recurrence-scalar split (ISSUE 15): the
    <p, q> / <r, r> dot partials reduce — and rho/beta/alpha carry —
    at ``acc_dtype`` while vectors stream at ``dt`` (and planes at
    ``plane_dtype``). ``None`` = historic single-dtype behavior,
    byte-identical; callers threading ``state`` must keep the same
    ``acc_dtype`` across chunks (the rho entries carry it).
    """
    dt = jnp.result_type(data.dtype, b.dtype)
    adt = jnp.dtype(acc_dtype) if acc_dtype is not None else dt
    TM, B, G = _plan(m, offsets, tile=tile)
    win = TM + 2 * B
    m_pad = G * TM
    L = (G + 2) * TM
    D = len(offsets)

    pdt = _resolve_plane_dtype(plane_dtype, dt, TM)
    planes_row = _row_planes(data.astype(pdt), offsets, TM, B, G, m)
    bp = _pad_vec(b.astype(dt), TM, G)
    xp = (
        jnp.zeros(((G + 2) * TM,), dt)
        if x0 is None
        else _pad_vec(x0.astype(dt), TM, G)
    )

    kA = pl.pallas_call(
        _kernel_a(offsets, TM, B, win, D, m_pad),
        grid=(G + 2,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda gg: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((1, 1), adt),
        ],
        scratch_shapes=(
            [
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((win,), dt),
                pltpu.VMEM((win,), dt),
            ]
            + [pltpu.VMEM((TM,), pdt)] * (2 * D)
            + [
                pltpu.SemaphoreType.DMA((2 + D,)),
                pltpu.SemaphoreType.DMA((2 + D,)),
            ]
        ),
        interpret=interpret,
    )

    kB = pl.pallas_call(
        _kernel_b(),
        grid=(G + 2,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM,), lambda gg: (gg,), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda gg: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((L,), dt),
            jax.ShapeDtypeStruct((1, 1), adt),
        ],
        interpret=interpret,
    )

    if state is None:
        if x0 is None:
            rp0 = bp  # r = b - A @ 0
        else:
            from ..ops.dia_spmv import dia_spmv_xla

            r0 = b.astype(dt) - dia_spmv_xla(
                data.astype(dt), offsets, x0.astype(dt), (m, m)
            )
            rp0 = _pad_vec(r0, TM, G)
        rho0 = jnp.vdot(rp0, rp0).real.astype(adt)
        pp0 = jnp.zeros_like(bp)
        state = (xp, rp0, pp0, jnp.zeros((), adt), rho0)

    def body(_, state):
        xp, rp, pp, rho_prev, rho = state
        # the scalar recurrence runs at adt (the acc_dtype split); only
        # the SMEM kernel inputs cast down to the vector dtype
        beta = jnp.where(rho_prev == 0, 0.0, rho / jnp.where(rho_prev == 0, 1, rho_prev)).astype(adt)
        pnew, q, pq = kA(beta.astype(dt).reshape(1, 1), rp, pp, planes_row)
        alpha = rho / jnp.where(pq[0, 0] == 0, 1, pq[0, 0])
        xp2, rp2, rr = kB(alpha.reshape(1, 1).astype(dt), xp, pnew, rp, q)
        return xp2, rp2, pnew, rho, rr[0, 0]

    out_state = jax.lax.fori_loop(0, iters, body, state)
    xp, rp, _, _, rho = out_state
    x_out = _unpad_vec(xp, m, TM)
    r_out = _unpad_vec(rp, m, TM)
    if return_state:
        return x_out, r_out, rho, out_state
    return x_out, r_out, rho
