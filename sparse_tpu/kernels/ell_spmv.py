"""Pallas TPU kernel: ELL (padded-row) SpMV with in-kernel x-window DMA.

For bounded-degree matrices whose column ids stay within a band B of the
row (every reference benchmark), each grid step DMAs the [TM + 2B] x window
its row tile addresses into VMEM and gathers within the window — the gather
indices are VMEM-local, so HBM sees one x-window load + one ELL tile load +
one y store per tile (the MinMaxImage x-gather of csr.py:960-967, fused
into the kernel).

The ELL tile itself streams through the standard block pipeline; only x
needs the manual halo DMA. Matrices that are not band-limited should use
the XLA gather path (``ops.spmv.csr_spmv_ell``) — enforced by the caller
via the band check in ``ell_band``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def ell_band(ell_indices, ell_data) -> int:
    """Max |col - row| over REAL entries of the ELL plane (padding slots
    carry value 0 and are excluded). One host sync; cache the result."""
    rows = jnp.arange(ell_indices.shape[0], dtype=ell_indices.dtype)[:, None]
    off = jnp.where(ell_data != 0, jnp.abs(ell_indices - rows), 0)
    return int(jnp.max(off)) if ell_indices.size else 0


def ell_spmv_pallas(ell_indices, ell_data, x, band, tile=4096, interpret=None):
    """See ``_ell_spmv_pallas``; ``interpret=None`` auto-selects interpret
    mode off-TPU (Pallas TPU kernels only compile natively on tpu).

    Mosaic's in-VMEM dynamic gather currently lowers only for single-tile
    (8, 128) same-shape ``take_along_axis`` — an arbitrary windowed gather
    (what ELL needs) does not compile on real TPUs yet. Until Mosaic grows
    multi-tile dynamic_gather, the native-TPU path delegates to the XLA
    gather formulation (``ops.spmv.csr_spmv_ell``), which lowers to the
    hardware's HBM gather; the in-kernel-DMA version below remains the
    interpret-mode/reference implementation and the intended kernel once
    the lowering exists. DIA-shaped matrices get the true Pallas schedule
    via ``kernels.dia_spmv`` (static slices, no gather).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and jax.default_backend() == "tpu":
        from ..ops.spmv import csr_spmv_ell

        return csr_spmv_ell(ell_indices, ell_data, x)
    return _ell_spmv_pallas(
        ell_indices, ell_data, x, band=int(band), tile=tile, interpret=interpret
    )


@partial(jax.jit, static_argnames=("band", "tile", "interpret"))
def _ell_spmv_pallas(
    ell_indices, ell_data, x, band: int, tile: int = 4096, interpret: bool = False
):
    """y = A @ x with A in ELL layout [m, k]; |col - row| <= band required."""
    m, k = ell_data.shape
    n = x.shape[0]
    B = _round_up(max(band, 1), 128)
    TM = min(tile, _round_up(max(m, 128), 128))
    G = (m + TM - 1) // TM
    m_pad = G * TM
    win = TM + 2 * B

    # pad x into the halo coordinate system (j' = j + B); pad ELL planes to
    # m_pad rows with self-referencing zero entries
    pad_hi = max(m_pad - n, 0) + B
    x_p = jnp.pad(x, (B, pad_hi))[: m_pad + 2 * B]
    if m_pad > m:
        ell_indices = jnp.pad(
            ell_indices,
            ((0, m_pad - m), (0, 0)),
            constant_values=0,
        )
        ell_data = jnp.pad(ell_data, ((0, m_pad - m), (0, 0)))
    out_dt = jnp.result_type(ell_data.dtype, x.dtype)

    def kernel(x_hbm, idx_ref, val_ref, y_ref, xwin, sem):
        g = pl.program_id(0)
        dma = pltpu.make_async_copy(x_hbm.at[pl.ds(g * TM, win)], xwin, sem)
        dma.start()
        dma.wait()
        acc = jnp.zeros((TM,), dtype=y_ref.dtype)
        for kk in range(k):
            # window-local index: col - (g*TM - B); in-VMEM gather. Padding
            # slots (value 0) may point anywhere — clamp keeps the read in
            # range and the 0 value annihilates it.
            loc = idx_ref[:, kk].astype(jnp.int32) - g * TM + B
            loc = jnp.clip(loc, 0, win - 1)
            acc = acc + val_ref[:, kk] * xwin[loc]
        y_ref[:] = acc

    y = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((TM, k), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TM, k), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TM,), lambda g: (g,), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_pad,), out_dt),
        scratch_shapes=[
            pltpu.VMEM((win,), x.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(x_p, ell_indices, ell_data)
    return y[:m]
