"""Pallas TPU kernels for the hot ops (cuSPARSE-variant analogs).

Populated incrementally; every kernel has a pure-XLA fallback in
``sparse_tpu.ops`` that serves as its test oracle.
"""
