"""scipy.sparse.csgraph drop-in surface (beyond the reference, which has
no graph module at all — but its AMG example builds MIS aggregation on a
tropical-semiring SpMV, ``examples/amg.py``; this module generalizes that
design).

TPU-first formulation: the classic queue/heap graph algorithms are
data-dependent and serial — hostile to XLA. Every distance/label routine
here is instead a **semiring relaxation**: a fixed-shape scatter-min
(min,+ edge relaxation) iterated inside ``lax.while_loop`` until a
fixpoint. One iteration is one vectorized pass over all edges (the same
shape as the library's SpMV), convergence is a single ``jnp.any`` — no
frontier bookkeeping, no host round-trips per step. Inherently
sequential orderings (DFS, RCM) run on host numpy, exactly where the
reference puts its control-plane scans.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .coverage import track_provenance
__all__ = [
    "NegativeCycleError",
    "bellman_ford",
    "breadth_first_order",
    "breadth_first_tree",
    "connected_components",
    "construct_dist_matrix",
    "csgraph_from_dense",
    "csgraph_from_masked",
    "csgraph_masked_from_dense",
    "csgraph_to_dense",
    "csgraph_to_masked",
    "maximum_bipartite_matching",
    "maximum_flow",
    "MaximumFlowResult",
    "min_weight_full_bipartite_matching",
    "yen",
    "depth_first_order",
    "depth_first_tree",
    "dijkstra",
    "floyd_warshall",
    "johnson",
    "laplacian",
    "minimum_spanning_tree",
    "reconstruct_path",
    "reverse_cuthill_mckee",
    "shortest_path",
    "structural_rank",
]


class NegativeCycleError(Exception):
    """scipy.sparse.csgraph.NegativeCycleError alias."""


def _nverts(csgraph):
    return (csgraph.shape[0] if hasattr(csgraph, "shape")
            else np.asarray(csgraph).shape[0])


def _graph_coo(csgraph, directed=True, unweighted=False):
    """(row, col, w, n) host arrays; undirected graphs get both edge
    directions materialized (min weight wins on duplicates downstream)."""
    if hasattr(csgraph, "tocoo"):  # sparse_tpu or scipy sparse
        G = csgraph.tocoo()
        row = np.asarray(G.row, dtype=np.int64)
        col = np.asarray(G.col, dtype=np.int64)
        w = np.asarray(G.data, dtype=np.float64)
        n = G.shape[0]
    else:
        D = np.asarray(csgraph, dtype=np.float64)
        n = D.shape[0]
        row, col = np.nonzero(D)
        w = D[row, col]
    if unweighted:
        w = np.ones_like(w)
    if not directed:
        row, col = np.concatenate([row, col]), np.concatenate([col, row])
        w = np.concatenate([w, w])
    return row, col, w, int(n)


@track_provenance
def laplacian(csgraph, normed=False, return_diag=False, use_out_degree=False,
              *, copy=True, form="array", dtype=None, symmetrized=False):
    """Graph Laplacian L = D - A (scipy.sparse.csgraph.laplacian).
    ``copy`` is accepted and ignored (jax arrays are immutable); only
    ``form='array'`` is implemented."""
    if form != "array":
        raise NotImplementedError(
            f"laplacian: form={form!r} not implemented (only 'array'); "
            "wrap the result with aslinearoperator for the operator form"
        )
    from .csr import csr_array
    from .module import diags

    from .base import SparseArray

    if isinstance(csgraph, SparseArray):
        A = csgraph.tocsr()
    elif hasattr(csgraph, "tocsr"):  # scipy sparse: convert into ours
        A = csr_array(csgraph.tocsr())
    else:
        A = csr_array(np.asarray(csgraph))
    if symmetrized:
        A = (A + A.T.tocsr()).tocsr()
    axis = 1 if use_out_degree else 0
    deg = np.asarray(A.sum(axis=axis)).ravel()
    n = A.shape[0]
    if normed:
        isq = np.where(deg > 0, 1.0 / np.sqrt(np.where(deg > 0, deg, 1)), 0)
        Dhalf = diags([isq], [0], shape=(n, n))
        L = (diags([np.where(deg > 0, 1.0, 0.0)], [0], shape=(n, n))
             - (Dhalf @ A @ Dhalf).tocsr()).tocsr()
        d_out = np.sqrt(deg)
    else:
        L = (diags([deg], [0], shape=(n, n)) - A).tocsr()
        d_out = deg
    if dtype is not None:
        L = L.astype(dtype)
    if return_diag:
        return L, d_out.astype(dtype) if dtype is not None else d_out
    return L


def _relax_scatter_min(row_d, col_d, w_d, n, dist0, maxiter):
    """Iterated (min,+) edge relaxation with predecessor tracking.

    One step: cand[v] = min over edges (u,v) of dist[u] + w(u,v), taken
    simultaneously for every source column; a whole Bellman-Ford pass is
    one scatter-min — the fixed-shape, all-edges-at-once form of the
    frontier algorithms. dist0 is [k, n] (k sources).
    Returns (dist, pred, changed_last) after at most maxiter sweeps.
    """
    inf = jnp.asarray(np.inf, dist0.dtype)

    def step(state):
        dist, pred, it, _ = state
        cand = dist[:, row_d] + w_d[None, :]          # [k, E]
        best = jnp.full_like(dist, inf).at[:, col_d].min(cand)
        improved = best < dist
        new_dist = jnp.where(improved, best, dist)
        # winning edge per (source, vertex): an edge wins if its cand
        # equals the new distance at its head; scatter-max over winners
        # picks one of them (any optimal edge is a valid predecessor).
        # Improved vertices' stale preds are RESET first — a stale larger
        # index would otherwise survive the max.
        wins = cand <= new_dist[:, col_d]
        base = jnp.where(improved, jnp.int32(-9999), pred)
        scat = base.at[:, col_d].max(
            jnp.where(wins, row_d[None, :].astype(pred.dtype), -9999)
        )
        pred = jnp.where(improved, scat, pred)
        return new_dist, pred, it + 1, jnp.any(improved)

    def cond(state):
        _, _, it, changed = state
        return changed & (it < maxiter)

    pred0 = jnp.full(dist0.shape, -9999, dtype=jnp.int32)
    state = (dist0, pred0, jnp.int32(0),
             jnp.asarray(True))
    dist, pred, it, changed = jax.lax.while_loop(cond, step, state)
    return dist, pred, changed


def _prepare_indices(indices, n):
    if indices is None:
        return np.arange(n)
    return np.atleast_1d(np.asarray(indices, dtype=np.int64))


@track_provenance
def bellman_ford(csgraph, directed=True, indices=None,
                 return_predecessors=False, unweighted=False):
    """Bellman-Ford shortest paths (scipy semantics; raises
    NegativeCycleError on a reachable negative cycle). The whole
    algorithm is one ``lax.while_loop`` of scatter-min relaxations."""
    row, col, w, n = _graph_coo(csgraph, directed, unweighted)
    idx = _prepare_indices(indices, n)
    row_d = jnp.asarray(row, dtype=jnp.int32)
    col_d = jnp.asarray(col, dtype=jnp.int32)
    w_d = jnp.asarray(w, dtype=jnp.float64 if jax.config.jax_enable_x64
                      else jnp.float32)
    dist0 = jnp.full((len(idx), n), np.inf, dtype=w_d.dtype)
    dist0 = dist0.at[jnp.arange(len(idx)), jnp.asarray(idx)].set(0.0)
    # n relaxation sweeps reach any shortest path; one extra detects
    # negative cycles
    dist, pred, changed = _relax_scatter_min(
        row_d, col_d, w_d, n, dist0, maxiter=n
    )
    if bool(changed):
        # converged flag false means the n-th sweep still improved:
        # re-run one sweep to confirm a negative cycle
        d2 = jnp.array(dist)
        cand = d2[:, row_d] + w_d[None, :]
        best = jnp.full_like(d2, jnp.inf).at[:, col_d].min(cand)
        if bool(jnp.any(best < d2)):
            raise NegativeCycleError("negative cycle detected")
    dist_np = np.asarray(dist, dtype=np.float64)
    pred_np = np.asarray(pred, dtype=np.int32)
    if indices is not None and np.ndim(indices) == 0:
        dist_np, pred_np = dist_np[0], pred_np[0]
    if return_predecessors:
        return dist_np, pred_np
    return dist_np


def _host_dijkstra(row, col, w, n, sources):
    """Classic binary-heap Dijkstra on host arrays — the high-diameter
    fallback. O(E log n) per source instead of (hop diameter) full-edge
    sweeps; same (dist, pred) contract as the device relaxation (ties
    may pick a different, equally optimal predecessor)."""
    import heapq

    from ._direct import _coo_to_csr_host

    indptr, _, c, wv = _coo_to_csr_host(
        np.asarray(row, dtype=np.int64), np.asarray(col, dtype=np.int64),
        np.asarray(w), n,
    )
    dist = np.full((len(sources), n), np.inf)
    pred = np.full((len(sources), n), -9999, dtype=np.int32)
    for si, s in enumerate(sources):
        d, p = dist[si], pred[si]
        d[s] = 0.0
        heap = [(0.0, int(s))]
        while heap:
            du, u = heapq.heappop(heap)
            if du > d[u]:
                continue
            for e in range(indptr[u], indptr[u + 1]):
                v = int(c[e])
                nd = du + wv[e]
                if nd < d[v]:
                    d[v] = nd
                    p[v] = u
                    heapq.heappush(heap, (nd, v))
    return dist, pred


@track_provenance
def dijkstra(csgraph, directed=True, indices=None,
             return_predecessors=False, unweighted=False, limit=np.inf,
             min_only=False):
    """Shortest paths for non-negative weights (scipy.sparse.csgraph
    .dijkstra surface). TPU-first note: a binary heap is the wrong shape
    for this machine; the same distances come from the fixed-shape
    Bellman-Ford relaxation, which converges in (longest shortest-path
    hop count) sweeps. Mesh-like graphs — the shape this framework
    targets — have hop diameter O(sqrt(n)), so the device attempt is
    BOUNDED at ~2*sqrt(n) sweeps; a high-diameter graph (e.g. a long
    path, which would need ~n full-edge sweeps — the r3 cliff) falls
    back to a classic host binary-heap Dijkstra with a warning."""
    # light-weight negativity check. Skipped in unweighted mode, where
    # stored weights are never consulted (scipy behavior).
    if not unweighted:
        if hasattr(csgraph, "data"):
            wchk = np.asarray(csgraph.data)
        else:
            wchk = np.asarray(csgraph)
        if wchk.size and float(np.min(wchk)) < 0:
            raise ValueError(
                "dijkstra requires non-negative weights; use bellman_ford"
            )
    n = _nverts(csgraph)
    # min_only semantics need the [k, n] form — never the squeezed one
    idx_arr = (np.arange(n) if indices is None
               else np.atleast_1d(np.asarray(indices, dtype=np.int64)))
    row, col, w, n = _graph_coo(csgraph, directed, unweighted)
    dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dist0 = jnp.full((len(idx_arr), n), np.inf, dtype=dt)
    dist0 = dist0.at[
        jnp.arange(len(idx_arr)), jnp.asarray(idx_arr)
    ].set(0.0)
    bound = int(min(n, max(64, 2 * int(np.sqrt(n)) + 16)))
    d_dev, p_dev, changed = _relax_scatter_min(
        jnp.asarray(row, dtype=jnp.int32), jnp.asarray(col, dtype=jnp.int32),
        jnp.asarray(w, dtype=dt), n, dist0, maxiter=bound,
    )
    if bool(changed) and bound < n:
        from .utils import user_warning

        user_warning(
            f"dijkstra: hop diameter exceeds the {bound}-sweep device "
            "bound; falling back to the host binary-heap algorithm"
        )
        dist, pred = _host_dijkstra(row, col, w, n, idx_arr)
    else:
        dist = np.asarray(d_dev, dtype=np.float64)
        pred = np.asarray(p_dev, dtype=np.int32)
    if np.isfinite(limit):
        pruned = dist > limit
        dist = np.where(pruned, np.inf, dist)
        pred = np.where(pruned, np.int32(-9999), pred)  # no stale paths
    if min_only:
        win = np.argmin(dist, axis=0)
        verts = np.arange(n)
        dmin = dist[win, verts]
        if return_predecessors:
            # scipy's 3-tuple: (dist, predecessors, sources)
            predm = pred[win, verts]
            sources = np.where(np.isfinite(dmin), idx_arr[win], -9999)
            return dmin, predm, sources
        return dmin
    if indices is not None and np.ndim(indices) == 0:
        dist, pred = dist[0], pred[0]
    if return_predecessors:
        return dist, pred
    return dist


@track_provenance
def floyd_warshall(csgraph, directed=True, return_predecessors=False,
                   unweighted=False, overwrite=False):
    """All-pairs shortest paths on the dense distance matrix: n pivot
    steps inside ``lax.fori_loop``, each a fully vectorized [n, n]
    min-plus rank-1 update."""
    row, col, w, n = _graph_coo(csgraph, directed, unweighted)
    dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    D0 = np.full((n, n), np.inf)
    # scipy keeps the MINIMUM parallel edge
    np.minimum.at(D0, (row, col), w)
    np.fill_diagonal(D0, 0.0)
    P0 = np.full((n, n), -9999, dtype=np.int32)
    P0[row, col] = row
    D0_d, P0_d = jnp.asarray(D0, dt), jnp.asarray(P0)

    def pivot(k, state):
        D, P = state
        through = D[:, k][:, None] + D[k, :][None, :]
        better = through < D
        P = jnp.where(better, jnp.broadcast_to(P[k, :][None, :], P.shape), P)
        D = jnp.where(better, through, D)
        return D, P

    D, P = jax.lax.fori_loop(0, n, pivot, (D0_d, P0_d))
    if bool(jnp.any(jnp.diagonal(D) < 0)):
        raise NegativeCycleError("negative cycle detected")
    D_np = np.asarray(D, dtype=np.float64)
    if return_predecessors:
        return D_np, np.asarray(P)
    return D_np


@track_provenance
def johnson(csgraph, directed=True, indices=None,
            return_predecessors=False, unweighted=False):
    """All-pairs shortest paths with negative edges (scipy surface).
    The relaxation form handles negative edges directly, so this shares
    :func:`bellman_ford` (no reweighting pass needed)."""
    return bellman_ford(csgraph, directed=directed, indices=indices,
                        return_predecessors=return_predecessors,
                        unweighted=unweighted)


@track_provenance
def shortest_path(csgraph, method="auto", directed=True,
                  return_predecessors=False, unweighted=False,
                  overwrite=False, indices=None):
    """scipy.sparse.csgraph.shortest_path dispatcher."""
    if method == "auto":
        n = (csgraph.shape[0] if hasattr(csgraph, "shape")
             else np.asarray(csgraph).shape[0])
        method = "FW" if indices is None and n <= 1024 else "BF"
    if method == "FW":
        if indices is not None:
            D = floyd_warshall(csgraph, directed, return_predecessors,
                               unweighted)
            idx = np.atleast_1d(indices)
            if return_predecessors:
                out = (D[0][idx], D[1][idx])
                if np.ndim(indices) == 0:
                    return out[0][0], out[1][0]
                return out
            return D[idx][0] if np.ndim(indices) == 0 else D[idx]
        return floyd_warshall(csgraph, directed, return_predecessors,
                              unweighted)
    if method in ("D", "BF", "J"):
        fn = {"D": dijkstra, "BF": bellman_ford, "J": johnson}[method]
        return fn(csgraph, directed=directed, indices=indices,
                  return_predecessors=return_predecessors,
                  unweighted=unweighted)
    raise ValueError(f"unrecognized method {method!r}")


@track_provenance
def connected_components(csgraph, directed=True, connection="weak",
                         return_labels=True):
    """Connected components via min-label propagation: each sweep is one
    scatter-min over all edges; converges in O(diameter) sweeps inside a
    single ``lax.while_loop``."""
    if directed and connection == "strong":
        raise NotImplementedError(
            "connection='strong' is not implemented; the weak form and "
            "undirected graphs are supported"
        )
    row, col, w, n = _graph_coo(csgraph, directed=False)  # weak: both dirs
    row_d = jnp.asarray(row, dtype=jnp.int32)
    col_d = jnp.asarray(col, dtype=jnp.int32)

    def step(state):
        lab, _ = state
        cand = lab[row_d]
        new = lab.at[col_d].min(cand)
        return new, jnp.any(new < lab)

    def cond(state):
        return state[1]

    lab0 = jnp.arange(n, dtype=jnp.int32)
    lab, _ = jax.lax.while_loop(
        cond, step, (lab0, jnp.asarray(True))
    )
    lab_np = np.asarray(lab)
    roots, labels = np.unique(lab_np, return_inverse=True)
    if return_labels:
        return len(roots), labels.astype(np.int32)
    return len(roots)


@track_provenance
def breadth_first_order(csgraph, i_start, directed=True,
                        return_predecessors=True):
    """BFS order via level-synchronous relaxation: hop distances come
    from the unweighted scatter-min; the order is (level, node) — a valid
    BFS ordering (scipy's intra-level order may differ)."""
    dist, pred = bellman_ford(csgraph, directed=directed, indices=i_start,
                              return_predecessors=True, unweighted=True)
    reach = np.isfinite(dist)
    nodes = np.nonzero(reach)[0]
    order = nodes[np.lexsort((nodes, dist[nodes]))]
    node_array = order.astype(np.int32)
    if return_predecessors:
        pred = pred.astype(np.int32)
        pred[~reach] = -9999
        pred[int(np.atleast_1d(i_start)[0])] = -9999
        return node_array, pred
    return node_array


def _tree_from_pred(pred, csgraph, n):
    """CSR tree of the predecessor array with original edge weights."""
    from .coo import coo_array

    row, col, w, _ = _graph_coo(csgraph, directed=True)
    wmap = {}
    for r, c, ww in zip(row, col, w):
        key = (int(r), int(c))
        if key not in wmap or ww < wmap[key]:
            wmap[key] = ww
    tr, tc, tw = [], [], []
    for v in range(n):
        p = int(pred[v])
        if p >= 0:
            tr.append(p)
            tc.append(v)
            tw.append(wmap.get((p, v), wmap.get((v, p), 1.0)))
    return coo_array(
        (np.asarray(tw), (np.asarray(tr, dtype=np.int64),
                          np.asarray(tc, dtype=np.int64))),
        shape=(n, n),
    ).tocsr()


@track_provenance
def breadth_first_tree(csgraph, i_start, directed=True):
    n = _nverts(csgraph)
    _, pred = breadth_first_order(csgraph, i_start, directed=directed,
                                  return_predecessors=True)
    return _tree_from_pred(pred, csgraph, n)


@track_provenance
def depth_first_order(csgraph, i_start, directed=True,
                      return_predecessors=True):
    """DFS is inherently sequential — host control-plane implementation
    (numpy stack), like the reference's host-side scans."""
    row, col, w, n = _graph_coo(csgraph, directed)
    order_csr = np.argsort(row, kind="stable")
    srow, scol = row[order_csr], col[order_csr]
    starts = np.searchsorted(srow, np.arange(n + 1))
    visited = np.zeros(n, dtype=bool)
    pred = np.full(n, -9999, dtype=np.int32)
    node_array = []
    stack = [int(i_start)]
    visited[int(i_start)] = True
    while stack:
        u = stack.pop()
        node_array.append(u)
        nbrs = scol[starts[u]:starts[u + 1]]
        # push in REVERSE index order so the smallest neighbor pops first
        for v in np.unique(nbrs)[::-1]:
            if not visited[v]:
                visited[v] = True
                pred[v] = u
                stack.append(int(v))
    node_array = np.asarray(node_array, dtype=np.int32)
    if return_predecessors:
        return node_array, pred
    return node_array


@track_provenance
def depth_first_tree(csgraph, i_start, directed=True):
    n = _nverts(csgraph)
    _, pred = depth_first_order(csgraph, i_start, directed=directed,
                                return_predecessors=True)
    return _tree_from_pred(pred, csgraph, n)


@track_provenance
def minimum_spanning_tree(csgraph, overwrite=False):
    """Kruskal on host (sort + union-find: O(E log E) control-plane
    work; the edge sort is the only heavy step and runs on numpy)."""
    from .coo import coo_array

    row, col, w, n = _graph_coo(csgraph, directed=True)
    # undirected: canonicalize and keep min parallel edge
    lo, hi = np.minimum(row, col), np.maximum(row, col)
    keep = lo != hi
    lo, hi, w = lo[keep], hi[keep], w[keep]
    order = np.lexsort((hi, lo))
    lo, hi, w = lo[order], hi[order], w[order]
    same = np.flatnonzero(
        (np.diff(lo) == 0) & (np.diff(hi) == 0)
    )
    # min weight among duplicates
    wmin = w.copy()
    for i in same[::-1]:
        wmin[i] = min(wmin[i], wmin[i + 1])
    first = np.ones(len(lo), dtype=bool)
    first[same + 1] = False
    lo, hi, w = lo[first], hi[first], wmin[first]
    parent = np.arange(n)

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    tr, tc, tw = [], [], []
    for e in np.argsort(w, kind="stable"):
        ra, rb = find(lo[e]), find(hi[e])
        if ra != rb:
            parent[ra] = rb
            tr.append(lo[e])
            tc.append(hi[e])
            tw.append(w[e])
    return coo_array(
        (np.asarray(tw), (np.asarray(tr, dtype=np.int64),
                          np.asarray(tc, dtype=np.int64))),
        shape=(n, n),
    ).tocsr()


@track_provenance
def reverse_cuthill_mckee(csgraph, symmetric_mode=False):
    """Bandwidth-reducing RCM ordering (host BFS; feeds this library's
    banded DIA fast path — reorder, then convert to DIA)."""
    row, col, w, n = _graph_coo(csgraph, directed=True)
    # the ordering always works on the symmetrized pattern
    row, col = np.concatenate([row, col]), np.concatenate([col, row])
    deg = np.bincount(row, minlength=n)
    order_csr = np.argsort(row, kind="stable")
    srow, scol = row[order_csr], col[order_csr]
    starts = np.searchsorted(srow, np.arange(n + 1))
    visited = np.zeros(n, dtype=bool)
    out = []
    for seed in np.argsort(deg, kind="stable"):
        if visited[seed]:
            continue
        visited[seed] = True
        queue = [int(seed)]
        qi = 0
        while qi < len(queue):
            u = queue[qi]
            qi += 1
            out.append(u)
            nbrs = np.unique(scol[starts[u]:starts[u + 1]])
            nbrs = nbrs[~visited[nbrs]]
            visited[nbrs] = True
            queue.extend(nbrs[np.argsort(deg[nbrs], kind="stable")].tolist())
    return np.asarray(out[::-1], dtype=np.int32)


def _bipartite_matching(csgraph):
    """Augmenting-path maximum matching on the bipartite row/col graph
    (host control-plane). Returns (rank, match_col) with match_col[c] =
    matched row or -1."""
    row, col, w, n = _graph_coo(csgraph, directed=True)
    shp = (csgraph.shape if hasattr(csgraph, "shape")
           else np.asarray(csgraph).shape)
    m, ncols = int(shp[0]), int(shp[1])
    adj = [[] for _ in range(m)]
    for r, c in zip(row, col):
        adj[int(r)].append(int(c))
    match_col = np.full(ncols, -1, dtype=np.int64)

    def augment(u, seen):
        for v in adj[u]:
            if not seen[v]:
                seen[v] = True
                if match_col[v] < 0 or augment(int(match_col[v]), seen):
                    match_col[v] = u
                    return True
        return False

    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old, m * 2 + 100))
    try:
        rank = 0
        for u in range(m):
            if augment(u, np.zeros(ncols, dtype=bool)):
                rank += 1
    finally:
        sys.setrecursionlimit(old)
    return rank, match_col


@track_provenance
def structural_rank(csgraph):
    """Maximum-matching structural rank (host augmenting paths on the
    bipartite row/col graph)."""
    return _bipartite_matching(csgraph)[0]


@track_provenance
def maximum_bipartite_matching(graph, perm_type="row"):
    """scipy.sparse.csgraph.maximum_bipartite_matching: perm_type='row'
    returns, per column, the matched row (-1 if unmatched);
    'column' returns, per row, the matched column."""
    rank, match_col = _bipartite_matching(graph)
    if perm_type == "row":
        return match_col.astype(np.int32)
    if perm_type == "column":
        m = graph.shape[0]
        match_row = np.full(m, -1, dtype=np.int32)
        matched = match_col >= 0
        match_row[match_col[matched]] = np.nonzero(matched)[0]
        return match_row
    raise ValueError("perm_type must be 'row' or 'column'")


@track_provenance
def construct_dist_matrix(graph, predecessors, directed=True,
                          null_value=np.inf):
    """Rebuild the all-pairs distance matrix from an [n, n] predecessor
    matrix + edge weights (scipy.sparse.csgraph.construct_dist_matrix;
    row i's source is vertex i)."""
    row, col, w, n = _graph_coo(graph, directed)
    pred = np.asarray(predecessors)
    if pred.shape != (n, n):
        raise ValueError("predecessors must be [n, n] (all-pairs form)")
    W = np.full((n, n), np.inf)
    np.minimum.at(W, (row, col), w)
    out = np.full((n, n), float(null_value))
    for s in range(n):
        out[s, s] = 0.0
        for v in range(n):
            if v == s:
                continue
            total, cur, hops = 0.0, v, 0
            while pred[s, cur] >= 0 and hops <= n:
                p = int(pred[s, cur])
                total += W[p, cur]
                cur = p
                hops += 1
            if cur == s and hops <= n:
                out[s, v] = total
    return out


@track_provenance
def csgraph_masked_from_dense(graph, null_value=0, nan_null=True,
                              infinity_null=True):
    D = np.asarray(graph, dtype=np.float64)
    mask = np.zeros_like(D, dtype=bool)
    if null_value is not None:
        mask |= D == null_value
    if nan_null:
        mask |= np.isnan(D)
    if infinity_null:
        mask |= np.isinf(D)
    return np.ma.masked_array(np.where(mask, 0.0, D), mask)


@track_provenance
def csgraph_from_masked(graph):
    from .csr import csr_array

    D = np.ma.asarray(graph)
    filled = np.where(np.ma.getmaskarray(D), 0.0, np.ma.filled(D, 0.0))
    return csr_array(np.asarray(filled, dtype=np.float64))


@track_provenance
def csgraph_to_masked(csgraph):
    G = csgraph.tocoo()
    n, m = csgraph.shape
    data = np.zeros((n, m))
    mask = np.ones((n, m), dtype=bool)
    data[np.asarray(G.row), np.asarray(G.col)] = np.asarray(G.data)
    mask[np.asarray(G.row), np.asarray(G.col)] = False
    return np.ma.masked_array(data, mask)


@track_provenance
def csgraph_from_dense(graph, null_value=0, nan_null=True,
                       infinity_null=True):
    from .csr import csr_array

    D = np.array(graph, dtype=np.float64, copy=True)
    mask = np.ones_like(D, dtype=bool)
    if null_value is not None:
        mask &= D != null_value
    if nan_null:
        mask &= ~np.isnan(D)
    if infinity_null:
        mask &= ~np.isinf(D)
    D = np.where(mask, D, 0.0)
    out = csr_array(D)
    return out


@track_provenance
def csgraph_to_dense(csgraph, null_value=0):
    G = csgraph.tocoo()
    out = np.full(csgraph.shape, float(null_value))
    out[np.asarray(G.row), np.asarray(G.col)] = np.asarray(G.data)
    return out


@track_provenance
def reconstruct_path(csgraph, predecessors, directed=True):
    """Tree of the predecessor array (scipy surface)."""
    n = _nverts(csgraph)
    return _tree_from_pred(np.asarray(predecessors), csgraph, n)


def _masked_sssp(row, col, w, n, src, edge_ok, node_ok):
    """Single-source shortest path by vectorized (min,+) sweeps over a
    masked edge list (host numpy — yen's spur searches mutate the edge
    mask every call, so this stays on the control plane like the other
    inherently sequential orderings). Returns (dist, pred)."""
    dist = np.full(n, np.inf)
    pred = np.full(n, -9999, dtype=np.int64)
    if not node_ok[src]:
        return dist, pred
    dist[src] = 0.0
    ok = edge_ok & node_ok[row] & node_ok[col]
    r, c, ww = row[ok], col[ok], w[ok]
    for _ in range(n):
        cand = dist[r] + ww
        best = np.full(n, np.inf)
        np.minimum.at(best, c, cand)
        improved = best < dist
        if not improved.any():
            break
        dist = np.where(improved, best, dist)
        win = cand <= dist[c]
        p = np.full(n, -9999, dtype=np.int64)
        np.maximum.at(p, c[win], r[win])
        pred = np.where(improved, p, pred)
    return dist, pred


def _walk_pred(pred, src, dst):
    """Vertex list src..dst from a predecessor array (None if no path)."""
    path = [int(dst)]
    cur = int(dst)
    for _ in range(len(pred) + 1):
        if cur == src:
            return path[::-1]
        cur = int(pred[cur])
        if cur < 0:
            return None
        path.append(cur)
    return None


@track_provenance
def yen(csgraph, source, sink, K, *, directed=True,
        return_predecessors=False, unweighted=False):
    """K-shortest loopless paths (scipy.sparse.csgraph.yen).

    Yen's algorithm: the candidate spur searches run on a masked edge
    list via :func:`_masked_sssp` (each spur masks the root-path edges
    of previously accepted paths), so no graph copies are built per
    candidate. Beyond the reference (which has no graph module)."""
    row, col, w, n = _graph_coo(csgraph, directed, unweighted)
    source, sink = int(source), int(sink)
    if w.size and float(np.min(w)) < 0:
        raise ValueError("yen requires non-negative weights")
    edge_ok = np.ones(len(row), dtype=bool)
    node_ok = np.ones(n, dtype=bool)

    def mask_edge(u, v):
        sel = (row == u) & (col == v)
        if not directed:
            sel |= (row == v) & (col == u)
        edge_ok[sel] = False

    # weight lookup for root-path costs (min over parallel edges,
    # matching the relaxation's choice)
    def edge_w(u, v):
        sel = (row == u) & (col == v)
        return float(np.min(w[sel]))

    dist, pred = _masked_sssp(row, col, w, n, source, edge_ok, node_ok)
    first = _walk_pred(pred, source, sink)
    A, A_cost = [], []
    if first is not None and np.isfinite(dist[sink]):
        A.append(first)
        A_cost.append(float(dist[sink]))
    B = {}  # path tuple -> cost
    while first is not None and len(A) < int(K):
        prev = A[-1]
        for i in range(len(prev) - 1):
            spur = prev[i]
            root = prev[: i + 1]
            edge_ok[:] = True
            node_ok[:] = True
            for p in A:
                if len(p) > i + 1 and p[: i + 1] == root:
                    mask_edge(p[i], p[i + 1])
            node_ok[root[:-1]] = False
            sd, sp = _masked_sssp(row, col, w, n, spur, edge_ok, node_ok)
            tail = _walk_pred(sp, spur, sink)
            if tail is None or not np.isfinite(sd[sink]):
                continue
            cand = root[:-1] + tail
            key = tuple(cand)
            if key in B or cand in A:
                continue
            root_cost = sum(edge_w(root[j], root[j + 1])
                            for j in range(len(root) - 1))
            B[key] = root_cost + float(sd[sink])
        if not B:
            break
        key = min(B, key=lambda t: (B[t], t))
        A.append(list(key))
        A_cost.append(B.pop(key))
    costs = np.asarray(A_cost, dtype=np.float64)
    if not return_predecessors:
        return costs
    preds = np.full((len(A), n), -9999, dtype=np.int32)
    for k, p in enumerate(A):
        for j in range(len(p) - 1):
            preds[k, p[j + 1]] = p[j]
    return costs, preds


class MaximumFlowResult:
    """Result of :func:`maximum_flow` (scipy.sparse.csgraph surface):
    ``flow_value`` plus the per-edge net ``flow`` matrix."""

    def __init__(self, flow_value, flow):
        self.flow_value = flow_value
        self.flow = flow

    def __repr__(self):
        return f"MaximumFlowResult with value of {self.flow_value}"


@track_provenance
def maximum_flow(csgraph, source, sink, *, method="dinic"):
    """Maximum s-t flow (scipy.sparse.csgraph.maximum_flow semantics:
    integer capacities; returns net flows on the pattern of
    ``csgraph + csgraph.T``). Dinic's blocking-flow algorithm on the
    host control plane — level BFS and augmentation are inherently
    sequential; capacities stay in compact numpy edge arrays."""
    if method not in ("dinic", "edmonds_karp"):
        raise ValueError(f"method expected 'dinic' or 'edmonds_karp', got {method!r}")
    if hasattr(csgraph, "tocoo"):
        G = csgraph.tocoo()
        data = np.asarray(G.data)
        urow = np.asarray(G.row, dtype=np.int64)
        ucol = np.asarray(G.col, dtype=np.int64)
        n = int(G.shape[0])
        if G.shape[0] != G.shape[1]:
            raise ValueError("csgraph must be square")
    else:
        D = np.asarray(csgraph)
        if D.ndim != 2 or D.shape[0] != D.shape[1]:
            raise ValueError("csgraph must be square")
        n = D.shape[0]
        urow, ucol = np.nonzero(D)
        data = D[urow, ucol]
    if not np.issubdtype(data.dtype, np.integer):
        raise ValueError("csgraph must have an integer dtype")
    if data.size and int(data.min()) < 0:
        raise ValueError("capacities must be non-negative")
    source, sink = int(source), int(sink)
    if not (0 <= source < n and 0 <= sink < n):
        raise ValueError("source/sink out of range")
    if source == sink:
        raise ValueError("source and sink must differ")

    # residual edge arrays: stored edge 2e = forward(cap), 2e+1 = reverse(0)
    E = len(urow)
    head = np.empty(2 * E, dtype=np.int64)
    cap = np.zeros(2 * E, dtype=np.int64)
    head[0::2], head[1::2] = ucol, urow
    cap[0::2] = data.astype(np.int64)
    tail = np.empty(2 * E, dtype=np.int64)
    tail[0::2], tail[1::2] = urow, ucol
    order = np.argsort(tail, kind="stable")
    adj_start = np.searchsorted(tail[order], np.arange(n + 1))

    total = 0
    INF = np.iinfo(np.int64).max
    while True:
        # BFS level graph on residual capacities
        level = np.full(n, -1, dtype=np.int64)
        level[source] = 0
        frontier = [source]
        while frontier:
            nxt = []
            for u in frontier:
                for t in order[adj_start[u]:adj_start[u + 1]]:
                    v = head[t]
                    if cap[t] > 0 and level[v] < 0:
                        level[v] = level[u] + 1
                        nxt.append(int(v))
            frontier = nxt
        if level[sink] < 0:
            break
        # blocking flow: iterative DFS with per-vertex edge cursors
        it = adj_start[:-1].copy()
        while True:
            # find one augmenting path in the level graph
            path = []
            u = source
            while u != sink:
                advanced = False
                while it[u] < adj_start[u + 1]:
                    t = order[it[u]]
                    v = head[t]
                    if cap[t] > 0 and level[v] == level[u] + 1:
                        path.append(t)
                        u = int(v)
                        advanced = True
                        break
                    it[u] += 1
                if not advanced:
                    if not path:
                        u = None
                        break
                    # dead end: retreat, exhaust the edge that led here
                    dead = path.pop()
                    u = int(tail[dead])
                    it[u] += 1
            if u is None:
                break
            pushed = int(min(INF, min(cap[t] for t in path)))
            for t in path:
                cap[t] -= pushed
                cap[t ^ 1] += pushed
            total += pushed
    fwd_flow = data.astype(np.int64) - cap[0::2]  # flow on each stored edge

    # net flow matrix on pattern(csgraph) ∪ pattern(csgraph.T)
    from .coo import coo_array

    rows = np.concatenate([urow, ucol])
    cols = np.concatenate([ucol, urow])
    vals = np.concatenate([fwd_flow, -fwd_flow])
    flow = coo_array((vals, (rows, cols)), shape=(n, n))
    flow.sum_duplicates()
    return MaximumFlowResult(int(total), flow.tocsr())


@track_provenance
def min_weight_full_bipartite_matching(biadjacency, maximize=False):
    """Sparse assignment problem (scipy.sparse.csgraph
    .min_weight_full_bipartite_matching): full matching of the smaller
    side minimizing total weight; explicit zeros count as edges.
    Successive shortest augmenting paths with dual potentials (the
    LAPJVsp recurrence) on the host control plane."""
    import heapq

    if not hasattr(biadjacency, "tocsr"):
        raise TypeError("biadjacency must be a sparse array")
    B = biadjacency.tocsr()
    m, n = (int(s) for s in B.shape)
    transposed = m > n
    if transposed:
        B = B.T.tocsr()
        m, n = n, m
    indptr = np.asarray(B.indptr, dtype=np.int64)
    indices = np.asarray(B.indices, dtype=np.int64)
    data = np.asarray(B.data, dtype=np.float64)
    if maximize:
        data = -data
    # a constant shift moves every full matching's cost equally: safe way
    # to make reduced-cost Dijkstra's nonnegativity invariant hold
    shift = float(np.min(data)) if data.size else 0.0
    if shift < 0:
        data = data - shift
    u = np.zeros(m)
    v = np.zeros(n)
    row4col = np.full(n, -1, dtype=np.int64)
    col4row = np.full(m, -1, dtype=np.int64)
    for cur in range(m):
        dist = np.full(n, np.inf)
        prev_row = np.full(n, -1, dtype=np.int64)
        seen = np.zeros(n, dtype=bool)
        heap = []

        def relax(i, d0):
            for t in range(indptr[i], indptr[i + 1]):
                j = int(indices[t])
                if seen[j]:
                    continue
                nd = d0 + data[t] - u[i] - v[j]
                if nd < dist[j]:
                    dist[j] = nd
                    prev_row[j] = i
                    heapq.heappush(heap, (nd, j))

        relax(cur, 0.0)
        sink = -1
        while heap:
            d, j = heapq.heappop(heap)
            if seen[j]:
                continue
            seen[j] = True
            if row4col[j] < 0:
                sink = j
                break
            relax(int(row4col[j]), d)
        if sink < 0:
            raise ValueError("no full matching exists")
        # dual update keeps all reduced costs nonnegative
        minv = dist[sink]
        u[cur] += minv
        scanned = np.nonzero(seen)[0]
        for j in scanned:
            if j == sink:
                continue
            v[j] += dist[j] - minv
            u[int(row4col[j])] += minv - dist[j]
        # augment along the alternating path
        j = sink
        while True:
            i = int(prev_row[j])
            row4col[j] = i
            col4row[i], j = j, col4row[i]
            if i == cur:
                break
    row_ind = np.arange(m, dtype=np.int64)
    col_ind = col4row
    if transposed:
        order = np.argsort(col_ind)
        row_ind, col_ind = col_ind[order], row_ind[order]
    return row_ind, col_ind
