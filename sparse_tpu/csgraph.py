"""scipy.sparse.csgraph drop-in surface (beyond the reference, which has
no graph module at all — but its AMG example builds MIS aggregation on a
tropical-semiring SpMV, ``examples/amg.py``; this module generalizes that
design).

TPU-first formulation: the classic queue/heap graph algorithms are
data-dependent and serial — hostile to XLA. Every distance/label routine
here is instead a **semiring relaxation**: a fixed-shape scatter-min
(min,+ edge relaxation) iterated inside ``lax.while_loop`` until a
fixpoint. One iteration is one vectorized pass over all edges (the same
shape as the library's SpMV), convergence is a single ``jnp.any`` — no
frontier bookkeeping, no host round-trips per step. Inherently
sequential orderings (DFS, RCM) run on host numpy, exactly where the
reference puts its control-plane scans.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .coverage import track_provenance
__all__ = [
    "NegativeCycleError",
    "bellman_ford",
    "breadth_first_order",
    "breadth_first_tree",
    "connected_components",
    "construct_dist_matrix",
    "csgraph_from_dense",
    "csgraph_from_masked",
    "csgraph_masked_from_dense",
    "csgraph_to_dense",
    "csgraph_to_masked",
    "maximum_bipartite_matching",
    "depth_first_order",
    "depth_first_tree",
    "dijkstra",
    "floyd_warshall",
    "johnson",
    "laplacian",
    "minimum_spanning_tree",
    "reconstruct_path",
    "reverse_cuthill_mckee",
    "shortest_path",
    "structural_rank",
]


class NegativeCycleError(Exception):
    """scipy.sparse.csgraph.NegativeCycleError alias."""


def _nverts(csgraph):
    return (csgraph.shape[0] if hasattr(csgraph, "shape")
            else np.asarray(csgraph).shape[0])


def _graph_coo(csgraph, directed=True, unweighted=False):
    """(row, col, w, n) host arrays; undirected graphs get both edge
    directions materialized (min weight wins on duplicates downstream)."""
    if hasattr(csgraph, "tocoo"):  # sparse_tpu or scipy sparse
        G = csgraph.tocoo()
        row = np.asarray(G.row, dtype=np.int64)
        col = np.asarray(G.col, dtype=np.int64)
        w = np.asarray(G.data, dtype=np.float64)
        n = G.shape[0]
    else:
        D = np.asarray(csgraph, dtype=np.float64)
        n = D.shape[0]
        row, col = np.nonzero(D)
        w = D[row, col]
    if unweighted:
        w = np.ones_like(w)
    if not directed:
        row, col = np.concatenate([row, col]), np.concatenate([col, row])
        w = np.concatenate([w, w])
    return row, col, w, int(n)


@track_provenance
def laplacian(csgraph, normed=False, return_diag=False, use_out_degree=False,
              *, copy=True, form="array", dtype=None, symmetrized=False):
    """Graph Laplacian L = D - A (scipy.sparse.csgraph.laplacian).
    ``copy`` is accepted and ignored (jax arrays are immutable); only
    ``form='array'`` is implemented."""
    if form != "array":
        raise NotImplementedError(
            f"laplacian: form={form!r} not implemented (only 'array'); "
            "wrap the result with aslinearoperator for the operator form"
        )
    from .csr import csr_array
    from .module import diags

    from .base import SparseArray

    if isinstance(csgraph, SparseArray):
        A = csgraph.tocsr()
    elif hasattr(csgraph, "tocsr"):  # scipy sparse: convert into ours
        A = csr_array(csgraph.tocsr())
    else:
        A = csr_array(np.asarray(csgraph))
    if symmetrized:
        A = (A + A.T.tocsr()).tocsr()
    axis = 1 if use_out_degree else 0
    deg = np.asarray(A.sum(axis=axis)).ravel()
    n = A.shape[0]
    if normed:
        isq = np.where(deg > 0, 1.0 / np.sqrt(np.where(deg > 0, deg, 1)), 0)
        Dhalf = diags([isq], [0], shape=(n, n))
        L = (diags([np.where(deg > 0, 1.0, 0.0)], [0], shape=(n, n))
             - (Dhalf @ A @ Dhalf).tocsr()).tocsr()
        d_out = np.sqrt(deg)
    else:
        L = (diags([deg], [0], shape=(n, n)) - A).tocsr()
        d_out = deg
    if dtype is not None:
        L = L.astype(dtype)
    if return_diag:
        return L, d_out.astype(dtype) if dtype is not None else d_out
    return L


def _relax_scatter_min(row_d, col_d, w_d, n, dist0, maxiter):
    """Iterated (min,+) edge relaxation with predecessor tracking.

    One step: cand[v] = min over edges (u,v) of dist[u] + w(u,v), taken
    simultaneously for every source column; a whole Bellman-Ford pass is
    one scatter-min — the fixed-shape, all-edges-at-once form of the
    frontier algorithms. dist0 is [k, n] (k sources).
    Returns (dist, pred, changed_last) after at most maxiter sweeps.
    """
    inf = jnp.asarray(np.inf, dist0.dtype)

    def step(state):
        dist, pred, it, _ = state
        cand = dist[:, row_d] + w_d[None, :]          # [k, E]
        best = jnp.full_like(dist, inf).at[:, col_d].min(cand)
        improved = best < dist
        new_dist = jnp.where(improved, best, dist)
        # winning edge per (source, vertex): an edge wins if its cand
        # equals the new distance at its head; scatter-max over winners
        # picks one of them (any optimal edge is a valid predecessor).
        # Improved vertices' stale preds are RESET first — a stale larger
        # index would otherwise survive the max.
        wins = cand <= new_dist[:, col_d]
        base = jnp.where(improved, jnp.int32(-9999), pred)
        scat = base.at[:, col_d].max(
            jnp.where(wins, row_d[None, :].astype(pred.dtype), -9999)
        )
        pred = jnp.where(improved, scat, pred)
        return new_dist, pred, it + 1, jnp.any(improved)

    def cond(state):
        _, _, it, changed = state
        return changed & (it < maxiter)

    pred0 = jnp.full(dist0.shape, -9999, dtype=jnp.int32)
    state = (dist0, pred0, jnp.int32(0),
             jnp.asarray(True))
    dist, pred, it, changed = jax.lax.while_loop(cond, step, state)
    return dist, pred, changed


def _prepare_indices(indices, n):
    if indices is None:
        return np.arange(n)
    return np.atleast_1d(np.asarray(indices, dtype=np.int64))


@track_provenance
def bellman_ford(csgraph, directed=True, indices=None,
                 return_predecessors=False, unweighted=False):
    """Bellman-Ford shortest paths (scipy semantics; raises
    NegativeCycleError on a reachable negative cycle). The whole
    algorithm is one ``lax.while_loop`` of scatter-min relaxations."""
    row, col, w, n = _graph_coo(csgraph, directed, unweighted)
    idx = _prepare_indices(indices, n)
    row_d = jnp.asarray(row, dtype=jnp.int32)
    col_d = jnp.asarray(col, dtype=jnp.int32)
    w_d = jnp.asarray(w, dtype=jnp.float64 if jax.config.jax_enable_x64
                      else jnp.float32)
    dist0 = jnp.full((len(idx), n), np.inf, dtype=w_d.dtype)
    dist0 = dist0.at[jnp.arange(len(idx)), jnp.asarray(idx)].set(0.0)
    # n relaxation sweeps reach any shortest path; one extra detects
    # negative cycles
    dist, pred, changed = _relax_scatter_min(
        row_d, col_d, w_d, n, dist0, maxiter=n
    )
    if bool(changed):
        # converged flag false means the n-th sweep still improved:
        # re-run one sweep to confirm a negative cycle
        d2 = jnp.array(dist)
        cand = d2[:, row_d] + w_d[None, :]
        best = jnp.full_like(d2, jnp.inf).at[:, col_d].min(cand)
        if bool(jnp.any(best < d2)):
            raise NegativeCycleError("negative cycle detected")
    dist_np = np.asarray(dist, dtype=np.float64)
    pred_np = np.asarray(pred, dtype=np.int32)
    if indices is not None and np.ndim(indices) == 0:
        dist_np, pred_np = dist_np[0], pred_np[0]
    if return_predecessors:
        return dist_np, pred_np
    return dist_np


@track_provenance
def dijkstra(csgraph, directed=True, indices=None,
             return_predecessors=False, unweighted=False, limit=np.inf,
             min_only=False):
    """Shortest paths for non-negative weights (scipy.sparse.csgraph
    .dijkstra surface). TPU-first note: a binary heap is the wrong shape
    for this machine; the same distances come from the fixed-shape
    Bellman-Ford relaxation, which converges in (longest shortest-path
    hop count) sweeps — so this delegates to :func:`bellman_ford` and
    applies ``limit``/``min_only`` on the result."""
    # light-weight negativity check (no duplicate edge extraction:
    # bellman_ford immediately redoes _graph_coo). Skipped in unweighted
    # mode, where stored weights are never consulted (scipy behavior).
    if not unweighted:
        if hasattr(csgraph, "data"):
            wchk = np.asarray(csgraph.data)
        else:
            wchk = np.asarray(csgraph)
        if wchk.size and float(np.min(wchk)) < 0:
            raise ValueError(
                "dijkstra requires non-negative weights; use bellman_ford"
            )
    n = _nverts(csgraph)
    # min_only semantics need the [k, n] form — never the squeezed one
    idx_arr = (np.arange(n) if indices is None
               else np.atleast_1d(np.asarray(indices, dtype=np.int64)))
    out = bellman_ford(csgraph, directed=directed, indices=idx_arr,
                       return_predecessors=True, unweighted=unweighted)
    dist, pred = out
    if np.isfinite(limit):
        pruned = dist > limit
        dist = np.where(pruned, np.inf, dist)
        pred = np.where(pruned, np.int32(-9999), pred)  # no stale paths
    if min_only:
        win = np.argmin(dist, axis=0)
        verts = np.arange(n)
        dmin = dist[win, verts]
        if return_predecessors:
            # scipy's 3-tuple: (dist, predecessors, sources)
            predm = pred[win, verts]
            sources = np.where(np.isfinite(dmin), idx_arr[win], -9999)
            return dmin, predm, sources
        return dmin
    if indices is not None and np.ndim(indices) == 0:
        dist, pred = dist[0], pred[0]
    if return_predecessors:
        return dist, pred
    return dist


@track_provenance
def floyd_warshall(csgraph, directed=True, return_predecessors=False,
                   unweighted=False, overwrite=False):
    """All-pairs shortest paths on the dense distance matrix: n pivot
    steps inside ``lax.fori_loop``, each a fully vectorized [n, n]
    min-plus rank-1 update."""
    row, col, w, n = _graph_coo(csgraph, directed, unweighted)
    dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    D0 = np.full((n, n), np.inf)
    # scipy keeps the MINIMUM parallel edge
    np.minimum.at(D0, (row, col), w)
    np.fill_diagonal(D0, 0.0)
    P0 = np.full((n, n), -9999, dtype=np.int32)
    P0[row, col] = row
    D0_d, P0_d = jnp.asarray(D0, dt), jnp.asarray(P0)

    def pivot(k, state):
        D, P = state
        through = D[:, k][:, None] + D[k, :][None, :]
        better = through < D
        P = jnp.where(better, jnp.broadcast_to(P[k, :][None, :], P.shape), P)
        D = jnp.where(better, through, D)
        return D, P

    D, P = jax.lax.fori_loop(0, n, pivot, (D0_d, P0_d))
    if bool(jnp.any(jnp.diagonal(D) < 0)):
        raise NegativeCycleError("negative cycle detected")
    D_np = np.asarray(D, dtype=np.float64)
    if return_predecessors:
        return D_np, np.asarray(P)
    return D_np


@track_provenance
def johnson(csgraph, directed=True, indices=None,
            return_predecessors=False, unweighted=False):
    """All-pairs shortest paths with negative edges (scipy surface).
    The relaxation form handles negative edges directly, so this shares
    :func:`bellman_ford` (no reweighting pass needed)."""
    return bellman_ford(csgraph, directed=directed, indices=indices,
                        return_predecessors=return_predecessors,
                        unweighted=unweighted)


@track_provenance
def shortest_path(csgraph, method="auto", directed=True,
                  return_predecessors=False, unweighted=False,
                  overwrite=False, indices=None):
    """scipy.sparse.csgraph.shortest_path dispatcher."""
    if method == "auto":
        n = (csgraph.shape[0] if hasattr(csgraph, "shape")
             else np.asarray(csgraph).shape[0])
        method = "FW" if indices is None and n <= 1024 else "BF"
    if method == "FW":
        if indices is not None:
            D = floyd_warshall(csgraph, directed, return_predecessors,
                               unweighted)
            idx = np.atleast_1d(indices)
            if return_predecessors:
                out = (D[0][idx], D[1][idx])
                if np.ndim(indices) == 0:
                    return out[0][0], out[1][0]
                return out
            return D[idx][0] if np.ndim(indices) == 0 else D[idx]
        return floyd_warshall(csgraph, directed, return_predecessors,
                              unweighted)
    if method in ("D", "BF", "J"):
        fn = {"D": dijkstra, "BF": bellman_ford, "J": johnson}[method]
        return fn(csgraph, directed=directed, indices=indices,
                  return_predecessors=return_predecessors,
                  unweighted=unweighted)
    raise ValueError(f"unrecognized method {method!r}")


@track_provenance
def connected_components(csgraph, directed=True, connection="weak",
                         return_labels=True):
    """Connected components via min-label propagation: each sweep is one
    scatter-min over all edges; converges in O(diameter) sweeps inside a
    single ``lax.while_loop``."""
    if directed and connection == "strong":
        raise NotImplementedError(
            "connection='strong' is not implemented; the weak form and "
            "undirected graphs are supported"
        )
    row, col, w, n = _graph_coo(csgraph, directed=False)  # weak: both dirs
    row_d = jnp.asarray(row, dtype=jnp.int32)
    col_d = jnp.asarray(col, dtype=jnp.int32)

    def step(state):
        lab, _ = state
        cand = lab[row_d]
        new = lab.at[col_d].min(cand)
        return new, jnp.any(new < lab)

    def cond(state):
        return state[1]

    lab0 = jnp.arange(n, dtype=jnp.int32)
    lab, _ = jax.lax.while_loop(
        cond, step, (lab0, jnp.asarray(True))
    )
    lab_np = np.asarray(lab)
    roots, labels = np.unique(lab_np, return_inverse=True)
    if return_labels:
        return len(roots), labels.astype(np.int32)
    return len(roots)


@track_provenance
def breadth_first_order(csgraph, i_start, directed=True,
                        return_predecessors=True):
    """BFS order via level-synchronous relaxation: hop distances come
    from the unweighted scatter-min; the order is (level, node) — a valid
    BFS ordering (scipy's intra-level order may differ)."""
    dist, pred = bellman_ford(csgraph, directed=directed, indices=i_start,
                              return_predecessors=True, unweighted=True)
    reach = np.isfinite(dist)
    nodes = np.nonzero(reach)[0]
    order = nodes[np.lexsort((nodes, dist[nodes]))]
    node_array = order.astype(np.int32)
    if return_predecessors:
        pred = pred.astype(np.int32)
        pred[~reach] = -9999
        pred[int(np.atleast_1d(i_start)[0])] = -9999
        return node_array, pred
    return node_array


def _tree_from_pred(pred, csgraph, n):
    """CSR tree of the predecessor array with original edge weights."""
    from .coo import coo_array

    row, col, w, _ = _graph_coo(csgraph, directed=True)
    wmap = {}
    for r, c, ww in zip(row, col, w):
        key = (int(r), int(c))
        if key not in wmap or ww < wmap[key]:
            wmap[key] = ww
    tr, tc, tw = [], [], []
    for v in range(n):
        p = int(pred[v])
        if p >= 0:
            tr.append(p)
            tc.append(v)
            tw.append(wmap.get((p, v), wmap.get((v, p), 1.0)))
    return coo_array(
        (np.asarray(tw), (np.asarray(tr, dtype=np.int64),
                          np.asarray(tc, dtype=np.int64))),
        shape=(n, n),
    ).tocsr()


@track_provenance
def breadth_first_tree(csgraph, i_start, directed=True):
    n = _nverts(csgraph)
    _, pred = breadth_first_order(csgraph, i_start, directed=directed,
                                  return_predecessors=True)
    return _tree_from_pred(pred, csgraph, n)


@track_provenance
def depth_first_order(csgraph, i_start, directed=True,
                      return_predecessors=True):
    """DFS is inherently sequential — host control-plane implementation
    (numpy stack), like the reference's host-side scans."""
    row, col, w, n = _graph_coo(csgraph, directed)
    order_csr = np.argsort(row, kind="stable")
    srow, scol = row[order_csr], col[order_csr]
    starts = np.searchsorted(srow, np.arange(n + 1))
    visited = np.zeros(n, dtype=bool)
    pred = np.full(n, -9999, dtype=np.int32)
    node_array = []
    stack = [int(i_start)]
    visited[int(i_start)] = True
    while stack:
        u = stack.pop()
        node_array.append(u)
        nbrs = scol[starts[u]:starts[u + 1]]
        # push in REVERSE index order so the smallest neighbor pops first
        for v in np.unique(nbrs)[::-1]:
            if not visited[v]:
                visited[v] = True
                pred[v] = u
                stack.append(int(v))
    node_array = np.asarray(node_array, dtype=np.int32)
    if return_predecessors:
        return node_array, pred
    return node_array


@track_provenance
def depth_first_tree(csgraph, i_start, directed=True):
    n = _nverts(csgraph)
    _, pred = depth_first_order(csgraph, i_start, directed=directed,
                                return_predecessors=True)
    return _tree_from_pred(pred, csgraph, n)


@track_provenance
def minimum_spanning_tree(csgraph, overwrite=False):
    """Kruskal on host (sort + union-find: O(E log E) control-plane
    work; the edge sort is the only heavy step and runs on numpy)."""
    from .coo import coo_array

    row, col, w, n = _graph_coo(csgraph, directed=True)
    # undirected: canonicalize and keep min parallel edge
    lo, hi = np.minimum(row, col), np.maximum(row, col)
    keep = lo != hi
    lo, hi, w = lo[keep], hi[keep], w[keep]
    order = np.lexsort((hi, lo))
    lo, hi, w = lo[order], hi[order], w[order]
    same = np.flatnonzero(
        (np.diff(lo) == 0) & (np.diff(hi) == 0)
    )
    # min weight among duplicates
    wmin = w.copy()
    for i in same[::-1]:
        wmin[i] = min(wmin[i], wmin[i + 1])
    first = np.ones(len(lo), dtype=bool)
    first[same + 1] = False
    lo, hi, w = lo[first], hi[first], wmin[first]
    parent = np.arange(n)

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    tr, tc, tw = [], [], []
    for e in np.argsort(w, kind="stable"):
        ra, rb = find(lo[e]), find(hi[e])
        if ra != rb:
            parent[ra] = rb
            tr.append(lo[e])
            tc.append(hi[e])
            tw.append(w[e])
    return coo_array(
        (np.asarray(tw), (np.asarray(tr, dtype=np.int64),
                          np.asarray(tc, dtype=np.int64))),
        shape=(n, n),
    ).tocsr()


@track_provenance
def reverse_cuthill_mckee(csgraph, symmetric_mode=False):
    """Bandwidth-reducing RCM ordering (host BFS; feeds this library's
    banded DIA fast path — reorder, then convert to DIA)."""
    row, col, w, n = _graph_coo(csgraph, directed=True)
    # the ordering always works on the symmetrized pattern
    row, col = np.concatenate([row, col]), np.concatenate([col, row])
    deg = np.bincount(row, minlength=n)
    order_csr = np.argsort(row, kind="stable")
    srow, scol = row[order_csr], col[order_csr]
    starts = np.searchsorted(srow, np.arange(n + 1))
    visited = np.zeros(n, dtype=bool)
    out = []
    for seed in np.argsort(deg, kind="stable"):
        if visited[seed]:
            continue
        visited[seed] = True
        queue = [int(seed)]
        qi = 0
        while qi < len(queue):
            u = queue[qi]
            qi += 1
            out.append(u)
            nbrs = np.unique(scol[starts[u]:starts[u + 1]])
            nbrs = nbrs[~visited[nbrs]]
            visited[nbrs] = True
            queue.extend(nbrs[np.argsort(deg[nbrs], kind="stable")].tolist())
    return np.asarray(out[::-1], dtype=np.int32)


def _bipartite_matching(csgraph):
    """Augmenting-path maximum matching on the bipartite row/col graph
    (host control-plane). Returns (rank, match_col) with match_col[c] =
    matched row or -1."""
    row, col, w, n = _graph_coo(csgraph, directed=True)
    shp = (csgraph.shape if hasattr(csgraph, "shape")
           else np.asarray(csgraph).shape)
    m, ncols = int(shp[0]), int(shp[1])
    adj = [[] for _ in range(m)]
    for r, c in zip(row, col):
        adj[int(r)].append(int(c))
    match_col = np.full(ncols, -1, dtype=np.int64)

    def augment(u, seen):
        for v in adj[u]:
            if not seen[v]:
                seen[v] = True
                if match_col[v] < 0 or augment(int(match_col[v]), seen):
                    match_col[v] = u
                    return True
        return False

    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old, m * 2 + 100))
    try:
        rank = 0
        for u in range(m):
            if augment(u, np.zeros(ncols, dtype=bool)):
                rank += 1
    finally:
        sys.setrecursionlimit(old)
    return rank, match_col


@track_provenance
def structural_rank(csgraph):
    """Maximum-matching structural rank (host augmenting paths on the
    bipartite row/col graph)."""
    return _bipartite_matching(csgraph)[0]


@track_provenance
def maximum_bipartite_matching(graph, perm_type="row"):
    """scipy.sparse.csgraph.maximum_bipartite_matching: perm_type='row'
    returns, per column, the matched row (-1 if unmatched);
    'column' returns, per row, the matched column."""
    rank, match_col = _bipartite_matching(graph)
    if perm_type == "row":
        return match_col.astype(np.int32)
    if perm_type == "column":
        m = graph.shape[0]
        match_row = np.full(m, -1, dtype=np.int32)
        matched = match_col >= 0
        match_row[match_col[matched]] = np.nonzero(matched)[0]
        return match_row
    raise ValueError("perm_type must be 'row' or 'column'")


@track_provenance
def construct_dist_matrix(graph, predecessors, directed=True,
                          null_value=np.inf):
    """Rebuild the all-pairs distance matrix from an [n, n] predecessor
    matrix + edge weights (scipy.sparse.csgraph.construct_dist_matrix;
    row i's source is vertex i)."""
    row, col, w, n = _graph_coo(graph, directed)
    pred = np.asarray(predecessors)
    if pred.shape != (n, n):
        raise ValueError("predecessors must be [n, n] (all-pairs form)")
    W = np.full((n, n), np.inf)
    np.minimum.at(W, (row, col), w)
    out = np.full((n, n), float(null_value))
    for s in range(n):
        out[s, s] = 0.0
        for v in range(n):
            if v == s:
                continue
            total, cur, hops = 0.0, v, 0
            while pred[s, cur] >= 0 and hops <= n:
                p = int(pred[s, cur])
                total += W[p, cur]
                cur = p
                hops += 1
            if cur == s and hops <= n:
                out[s, v] = total
    return out


@track_provenance
def csgraph_masked_from_dense(graph, null_value=0, nan_null=True,
                              infinity_null=True):
    D = np.asarray(graph, dtype=np.float64)
    mask = np.zeros_like(D, dtype=bool)
    if null_value is not None:
        mask |= D == null_value
    if nan_null:
        mask |= np.isnan(D)
    if infinity_null:
        mask |= np.isinf(D)
    return np.ma.masked_array(np.where(mask, 0.0, D), mask)


@track_provenance
def csgraph_from_masked(graph):
    from .csr import csr_array

    D = np.ma.asarray(graph)
    filled = np.where(np.ma.getmaskarray(D), 0.0, np.ma.filled(D, 0.0))
    return csr_array(np.asarray(filled, dtype=np.float64))


@track_provenance
def csgraph_to_masked(csgraph):
    G = csgraph.tocoo()
    n, m = csgraph.shape
    data = np.zeros((n, m))
    mask = np.ones((n, m), dtype=bool)
    data[np.asarray(G.row), np.asarray(G.col)] = np.asarray(G.data)
    mask[np.asarray(G.row), np.asarray(G.col)] = False
    return np.ma.masked_array(data, mask)


@track_provenance
def csgraph_from_dense(graph, null_value=0, nan_null=True,
                       infinity_null=True):
    from .csr import csr_array

    D = np.array(graph, dtype=np.float64, copy=True)
    mask = np.ones_like(D, dtype=bool)
    if null_value is not None:
        mask &= D != null_value
    if nan_null:
        mask &= ~np.isnan(D)
    if infinity_null:
        mask &= ~np.isinf(D)
    D = np.where(mask, D, 0.0)
    out = csr_array(D)
    return out


@track_provenance
def csgraph_to_dense(csgraph, null_value=0):
    G = csgraph.tocoo()
    out = np.full(csgraph.shape, float(null_value))
    out[np.asarray(G.row), np.asarray(G.col)] = np.asarray(G.data)
    return out


@track_provenance
def reconstruct_path(csgraph, predecessors, directed=True):
    """Tree of the predecessor array (scipy surface)."""
    n = _nverts(csgraph)
    return _tree_from_pred(np.asarray(predecessors), csgraph, n)
