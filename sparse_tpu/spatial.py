"""Spatial distance computations.

Reference analog: ``sparse/spatial.py:33-85`` — euclidean ``cdist`` via the
EUCLIDEAN_CDIST task (``src/sparse/spatial/euclidean_distance.*``) launched on
a 2-D manual processor grid with XA row-tiled over grid-i and XB row-tiled
over grid-j.

TPU-first redesign: the pairwise-distance matrix is exactly an MXU workload:
``||a - b||^2 = ||a||^2 + ||b||^2 - 2 a.b`` — one [m, k] x [k, n] matmul plus
rank-1 row/col corrections, all fused by XLA. The 2-D grid distribution
becomes a 2-D mesh sharding of the output (see ``parallel.mesh.get_mesh_2d``);
single-chip here, sharded when inputs carry shardings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .utils import asjnp


@jax.jit
def _cdist_euclidean(XA, XB):
    sqa = jnp.sum(XA * XA, axis=1)[:, None]
    sqb = jnp.sum(XB * XB, axis=1)[None, :]
    # the MXU term; bf16/f32 inputs hit the systolic array directly
    cross = XA @ XB.T
    d2 = jnp.maximum(sqa + sqb - 2.0 * cross, 0.0)
    return jnp.sqrt(d2)


@jax.jit
def _cdist_sqeuclidean(XA, XB):
    sqa = jnp.sum(XA * XA, axis=1)[:, None]
    sqb = jnp.sum(XB * XB, axis=1)[None, :]
    return jnp.maximum(sqa + sqb - 2.0 * (XA @ XB.T), 0.0)


def cdist(XA, XB, metric: str = "euclidean", mesh=None):
    """Pairwise distances between rows of XA [m, k] and XB [n, k].

    Reference supports euclidean only (spatial.py:39-43); sqeuclidean and
    cityblock are cheap extensions. ``mesh``: optional 2-D device mesh — the
    output is computed in disjoint 2-D tiles over it, XA rows along grid-x
    and XB rows along grid-y (the reference's manual launch grid,
    spatial.py:48-84; see ``parallel.grid2d.cdist_2d``).
    """
    if mesh is not None:
        from .parallel.grid2d import cdist_2d

        return cdist_2d(XA, XB, mesh=mesh, metric=metric)
    XA = asjnp(XA)
    XB = asjnp(XB)
    if XA.ndim != 2 or XB.ndim != 2:
        raise ValueError("XA and XB must be 2-dimensional")
    if XA.shape[1] != XB.shape[1]:
        raise ValueError(
            f"XA and XB must have the same number of columns "
            f"({XA.shape[1]} != {XB.shape[1]})"
        )
    if metric == "euclidean":
        return _cdist_euclidean(XA, XB)
    if metric == "sqeuclidean":
        return _cdist_sqeuclidean(XA, XB)
    if metric == "cityblock":
        return _cdist_cityblock(XA, XB)
    raise ValueError(f"unsupported metric {metric!r}")


@jax.jit
def _cdist_cityblock(XA, XB):
    # accumulate one [m, n] plane per feature — O(m*n) peak memory instead
    # of materializing the [m, n, k] broadcast difference tensor
    XA_t, XB_t = XA.T, XB.T  # [k, m], [k, n]

    def body(i, acc):
        return acc + jnp.abs(XA_t[i][:, None] - XB_t[i][None, :])

    acc0 = jnp.zeros((XA.shape[0], XB.shape[0]), dtype=XA.dtype)
    return jax.lax.fori_loop(0, XA.shape[1], body, acc0)
