"""Pattern-shared batched Jacobi preconditioners (point and block).

The Ginkgo batched recipe (PAPERS.md §2) split into the repo's
prepare/execute idiom: everything that depends only on the
*sparsity pattern* — which nnz position holds each row's diagonal,
which positions fall inside each diagonal block — is computed ONCE per
:class:`~sparse_tpu.batch.operator.SparsityPattern` on the host, lives
in :mod:`sparse_tpu.plan_cache` (vault-persisted, so a warm restart
skips it), and enters the compiled bucket programs as replicated
closure constants. The *numeric* half — extracting the diagonal /
blocks from a ``(B, nnz)`` value stack and inverting the small dense
blocks — is pure batched jnp executed inside the jitted program, so
every dispatch factorizes its fresh coefficients at device speed with
no host round trip.

* **Point Jacobi** (``jacobi``): ``M r = r / diag(A)`` per lane — one
  gather through the pattern's diagonal position map plus a broadcast
  multiply per application.
* **Block Jacobi** (``bjacobi``): the diagonal ``bs x bs`` blocks
  gather through a pattern-shared ``(blocks, bs, bs)`` source map into
  a ``(B, blocks, bs, bs)`` stack, invert with one batched
  ``jnp.linalg.inv``, and apply as a batched block matmul. Rows past
  ``n`` (the ragged last block) and structurally missing diagonal
  entries are patched with identity on the host map, so the inverses
  are well-defined for any pattern.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import plan_cache
from ..utils import commit_to_exec_device, host_scope


def _pattern_rows(pattern) -> np.ndarray:
    counts = pattern.indptr[1:] - pattern.indptr[:-1]
    return np.repeat(np.arange(pattern.shape[0], dtype=np.int64), counts)


def diag_map(pattern):
    """Per-pattern diagonal position map, via the plan cache: device
    arrays ``(dpos (n,), has (n,))`` where ``values[:, dpos]`` gathers
    each row's diagonal entry (``has`` False where the pattern has no
    structural diagonal — those rows precondition as identity)."""

    def build():
        import time

        from . import _build_event

        t0 = time.perf_counter()
        with host_scope():
            n = pattern.shape[0]
            rows = _pattern_rows(pattern)
            cols = pattern.indices.astype(np.int64)
            dpos = np.full(n, -1, dtype=np.int64)
            on_diag = rows == cols
            dpos[rows[on_diag]] = np.nonzero(on_diag)[0]
            has = dpos >= 0
        out = commit_to_exec_device((
            jnp.asarray(np.maximum(dpos, 0).astype(np.int32)),
            jnp.asarray(has),
        ))
        _build_event("jacobi", pattern, time.perf_counter() - t0,
                     stage="diag_map")
        return out

    def vault_key():
        from ..vault import _codecs

        return _codecs.digest("preconddiag", pattern.fingerprint[2])

    return plan_cache.get(
        pattern, "precond.diag", build,
        vault_kind="precond_diag", vault_key=vault_key,
    )


def _safe_recip(d):
    one = jnp.ones((), dtype=d.dtype)
    return jnp.where(d == 0, one, one / jnp.where(d == 0, one, d))


def diag_of(pattern, values):
    """``(B, n)`` diagonal stack of a ``(B, nnz)`` value stack (1 where
    the pattern has no diagonal entry) — jit-safe given a warm map."""
    dpos, has = diag_map(pattern)
    d = values[..., dpos]
    return jnp.where(has, d, jnp.ones((), dtype=values.dtype))


def jacobi_factory(pattern, storage_dtype=None, acc_dtype=None):
    """Point-Jacobi numeric factory: ``factory(values, matvec) -> Mvec``
    with ``Mvec(R) = R / diag(A)`` per lane. The map build (host) runs
    here, once per pattern; the returned factory is pure jnp.

    ``storage_dtype`` / ``acc_dtype`` (ISSUE 16): the reciprocal is
    computed at ``acc_dtype`` and STORED at ``storage_dtype`` — the
    apply's multiply widens back through jnp promotion, so a bf16
    factor under an f32 sweep costs bf16 memory traffic and f32 math.
    ``None`` (default) is byte-identical to the historic factory."""
    diag_map(pattern)  # host build outside any trace
    sdt = None if storage_dtype is None else jnp.dtype(storage_dtype)
    adt = None if acc_dtype is None else jnp.dtype(acc_dtype)

    def factory(values, matvec=None):
        d = diag_of(pattern, values)
        if adt is not None:
            d = d.astype(adt)
        dinv = _safe_recip(d)
        if sdt is not None:
            dinv = dinv.astype(sdt)

        def Mvec(R):
            return R * dinv

        return Mvec

    return factory


def block_map(pattern, bs: int):
    """Pattern-shared block extraction map for ``bs x bs`` diagonal
    blocks, via the plan cache (vault-persisted): device arrays
    ``(src (nb, bs, bs) int32, fix (nb, bs, bs))`` where ``src`` holds
    the nnz position feeding each in-block slot (0 where absent — the
    gathered value is masked by ``src >= 0`` pre-clip) and ``fix`` adds
    identity at padded rows (beyond ``n``) and structurally missing
    diagonal slots so every block inverts."""
    bs = int(bs)

    def build():
        import time

        from . import _build_event

        t0 = time.perf_counter()
        with host_scope():
            n = pattern.shape[0]
            nb = -(-n // bs)
            rows = _pattern_rows(pattern)
            cols = pattern.indices.astype(np.int64)
            inblk = (rows // bs) == (cols // bs)
            src = np.full((nb, bs, bs), -1, dtype=np.int64)
            r, c, p = rows[inblk], cols[inblk], np.nonzero(inblk)[0]
            src[r // bs, r % bs, c % bs] = p
            fix = np.zeros((nb, bs, bs), dtype=np.float64)
            # identity at ragged pad rows and missing structural diagonals
            flat = np.arange(nb * bs)
            missing = (flat >= n) | (src[flat // bs, flat % bs, flat % bs] < 0)
            fix[flat[missing] // bs, flat[missing] % bs, flat[missing] % bs] = 1.0
        out = commit_to_exec_device((
            jnp.asarray(src.astype(np.int32)), jnp.asarray(fix),
        ))
        _build_event("bjacobi", pattern, time.perf_counter() - t0,
                     stage="block_map", bs=bs)
        return out

    def vault_key():
        from ..vault import _codecs

        return _codecs.digest("precondblk", pattern.fingerprint[2], bs)

    return plan_cache.get(
        pattern, f"precond.block.{bs}", build,
        vault_kind="precond_block", vault_key=vault_key,
    )


def bjacobi_factory(pattern, bs: int | None = None, storage_dtype=None,
                    acc_dtype=None):
    """Block-Jacobi numeric factory over ``bs x bs`` diagonal blocks:
    gathers the block stack from the value stack through the
    pattern-shared map, inverts it batched, and applies as a batched
    block matmul. ``factory(values, matvec) -> Mvec``.

    ``storage_dtype`` / ``acc_dtype`` (ISSUE 16): the block inversion
    runs at ``acc_dtype`` (a bf16 ``linalg.inv`` would lose the
    factorization's whole point), the inverse STACK is stored at
    ``storage_dtype``, and the apply einsum accumulates at
    ``acc_dtype`` — narrow memory, wide math. ``None`` (default) is
    byte-identical to the historic factory."""
    from ..config import settings

    n = pattern.shape[0]
    bs = max(min(int(bs or settings.precond_block), max(n, 1)), 1)
    if bs == 1:
        return jacobi_factory(pattern, storage_dtype=storage_dtype,
                              acc_dtype=acc_dtype)
    block_map(pattern, bs)  # host build outside any trace
    nb = -(-n // bs)
    n_pad = nb * bs
    sdt = None if storage_dtype is None else jnp.dtype(storage_dtype)
    adt = None if acc_dtype is None else jnp.dtype(acc_dtype)

    def factory(values, matvec=None):
        src, fix = block_map(pattern, bs)
        gathered = jnp.where(
            src >= 0,
            values[..., jnp.maximum(src, 0)],
            jnp.zeros((), dtype=values.dtype),
        )  # (B, nb, bs, bs)
        blocks = gathered + fix.astype(values.dtype)
        if adt is not None:
            blocks = blocks.astype(adt)
        inv = jnp.linalg.inv(blocks)
        if sdt is not None:
            inv = inv.astype(sdt)

        def Mvec(R):
            B = R.shape[0]
            Rp = jnp.pad(R, ((0, 0), (0, n_pad - n)))
            Z = jnp.einsum(
                "bkij,bkj->bki", inv, Rp.reshape(B, nb, bs),
                **({} if adt is None
                   else {"preferred_element_type": adt}),
            )
            return Z.reshape(B, n_pad)[:, :n]

        return Mvec

    return factory
