"""PrecondPolicy: which preconditioner a bucket program gets.

The resolution ladder (most specific wins):

1. per-ticket override (``SolveSession.submit(precond=...)``) — lanes
   with different overrides never share a bucket (the group key carries
   the override, like the dtype);
2. per-session (``SolveSession(precond=...)``);
3. the environment (``SPARSE_TPU_PRECOND`` — '' / 'off' keeps every
   historic program key and jaxpr byte-identical).

A resolved choice is per ``(pattern, solver, bucket, dtype)`` — the
same axes as the bucket programs themselves — and joins the program's
plan-cache key (``.M<kind>`` suffix; absent for 'none', so
unpreconditioned keys are unchanged) and the vault warm-start manifest
(back-compatible ``_entry_key`` extension, like Fleet's ``mesh``).

``auto`` picks by solver and pattern shape: block-Jacobi for CG
(the SPD serving shape the bench targets), point Jacobi for
BiCGStab/GMRES, none for non-square patterns. Kinds that cannot apply
to a pattern (IC(0) on a structurally asymmetric pattern) degrade one
rung (to point Jacobi) with a ``coverage.fallback`` breadcrumb rather
than failing the dispatch.
"""

from __future__ import annotations

import numpy as np

from .. import telemetry
from ..config import settings

#: the forceable kinds (the SPARSE_TPU_PRECOND grammar minus auto/off)
KINDS = ("jacobi", "bjacobi", "ilu0", "ic0", "cheby", "neumann")

NONE = "none"

_OFF = ("", "0", "off", "false", "no", "none")


def canonical_kind(kind, allow_auto: bool = True) -> str:
    """Normalize a kind spelling; raises on unknown values (a typo'd
    ``SPARSE_TPU_PRECOND`` must not silently serve unpreconditioned)."""
    s = str("" if kind is None else kind).strip().lower()
    if s in _OFF:
        return NONE
    if s == "auto":
        if not allow_auto:
            raise ValueError("'auto' is not a concrete preconditioner kind")
        return "auto"
    if s not in KINDS:
        raise ValueError(
            f"precond kind {kind!r} not one of {('off', 'auto') + KINDS}"
        )
    return s


def key_suffix(kind: str | None) -> str:
    """What a resolved kind contributes to the bucket-program plan-cache
    key — empty for 'none' so unpreconditioned keys, programs and vault
    manifests are byte-compatible with every earlier release."""
    if not kind or kind == NONE:
        return ""
    return f".M{kind}"


#: the precond storage-dtype grammar (ISSUE 16): 'compute' factorizes
#: and stores M at the inner sweep's COMPUTE dtype (the historic
#: reduced-precision behavior — and the only behavior on exact
#: buckets); 'storage' factorizes wide but STORES the factors at the
#: policy's reduced storage dtype, applications widened back through
#: ``acc_dtype`` — the precond x mixed compounding arm.
PRECOND_DTYPES = ("compute", "storage")


def canonical_precond_dtype(value) -> str:
    """Normalize a precond storage-dtype spelling; raises on unknown
    values (a typo'd ``SPARSE_TPU_PRECOND_DTYPE`` must not silently
    serve the wrong memory footprint)."""
    s = str("" if value is None else value).strip().lower()
    if s in _OFF or s == "compute":
        return "compute"
    if s == "storage":
        return "storage"
    raise ValueError(
        f"precond dtype {value!r} not one of {('compute', 'storage')}"
    )


def dtype_suffix(precond_dtype: str | None) -> str:
    """What a resolved precond storage dtype contributes to the
    bucket-program plan-cache key — empty for 'compute' (the historic
    behavior) so every pre-existing key stays byte-identical."""
    if not precond_dtype or precond_dtype == "compute":
        return ""
    return f".W{precond_dtype}"


class PrecondPolicy:
    """Per-session preconditioner selector (constructed by
    ``SolveSession``; also usable standalone).

    Parameters
    ----------
    mode : '' / 'off' | 'auto' | one of :data:`KINDS`. ``None`` =
        ``settings.precond`` (``SPARSE_TPU_PRECOND``).
    block_size / sweeps / tri_sweeps / degree : knob overrides for the
        respective factories (defaults from settings).
    """

    def __init__(self, mode=None, block_size: int | None = None,
                 sweeps: int | None = None, tri_sweeps: int | None = None,
                 degree: int | None = None):
        self.mode = canonical_kind(
            settings.precond if mode is None else mode
        )
        self.block_size = block_size
        self.sweeps = sweeps
        self.tri_sweeps = tri_sweeps
        self.degree = degree
        # resolved (id(pattern), solver, bucket, dtype, override) -> kind
        self._decisions: dict = {}

    @classmethod
    def resolve(cls, precond=None, **knobs) -> "PrecondPolicy":
        """The ``SolveSession`` constructor hook: ``precond`` may be a
        ready policy, a kind/mode string, ``True`` (= 'auto'),
        ``False`` (= off regardless of env), or ``None`` (= env)."""
        if isinstance(precond, cls):
            return precond
        if precond is True:
            precond = "auto"
        elif precond is False:
            precond = NONE
        return cls(precond, **knobs)

    @property
    def enabled(self) -> bool:
        return self.mode != NONE

    def decide(self, pattern, solver: str, bucket: int, dtype,
               override=None) -> str:
        """Resolved concrete kind for one bucket program (cached per
        (pattern, solver, bucket, dtype, override))."""
        ov = None if override is None else canonical_kind(override)
        key = (id(pattern), solver, int(bucket), np.dtype(dtype).str, ov)
        hit = self._decisions.get(key)
        if hit is not None:
            return hit
        kind = ov if ov is not None else self.mode
        if kind == "auto":
            kind = self._auto(pattern, solver)
        kind = self._validate(pattern, kind)
        self._decisions[key] = kind
        return kind

    def _auto(self, pattern, solver: str) -> str:
        if pattern.shape[0] != pattern.shape[1] or pattern.nnz == 0:
            return NONE
        return "bjacobi" if solver == "cg" else "jacobi"

    def _validate(self, pattern, kind: str) -> str:
        """Degrade kinds the pattern cannot support (breadcrumbed, never
        a dispatch failure)."""
        if kind == NONE:
            return kind
        if pattern.shape[0] != pattern.shape[1] or pattern.nnz == 0:
            self._fallback(kind, NONE, "non-square-or-empty pattern")
            return NONE
        if kind == "ic0":
            from .ilu import ilu0_symbolic

            sym = ilu0_symbolic(pattern, "ic0")
            if not sym.symmetric:
                self._fallback(kind, "jacobi", "asymmetric pattern")
                return "jacobi"
        return kind

    @staticmethod
    def _fallback(kind: str, to: str, reason: str) -> None:
        if telemetry.enabled():
            telemetry.record(
                "coverage.fallback", op=f"precond.{kind}", reason=reason,
                to=to,
            )

    def factory(self, pattern, kind: str, storage_dtype=None,
                acc_dtype=None):
        """The numeric factory for a resolved kind (``None`` for
        'none'): host-side pattern work (plan-cached, vault-persisted)
        happens here; the returned ``factory(values, matvec) -> Mvec``
        is pure jnp. When a fault clause targets the ``precond`` site
        the returned apply is corruption-wrapped (resilience.faults) —
        absent otherwise, so clean traces are byte-identical.

        ``storage_dtype`` / ``acc_dtype`` (ISSUE 16, the precond x
        mixed compounding): when set, the Jacobi/ILU factories store
        their factors at ``storage_dtype`` and widen factorization
        math and applications to ``acc_dtype`` — the same
        storage-narrow/accumulate-wide contract the SELL/DIA kernels
        carry. ``None`` (the default) is byte-identical to the
        historic factories."""
        from ..resilience import faults as _faults

        if kind is None or kind == NONE:
            return None
        dtk = (
            {} if storage_dtype is None
            else {"storage_dtype": storage_dtype, "acc_dtype": acc_dtype}
        )
        if kind == "jacobi":
            from .jacobi import jacobi_factory

            base = jacobi_factory(pattern, **dtk)
        elif kind == "bjacobi":
            from .jacobi import bjacobi_factory

            base = bjacobi_factory(pattern, bs=self.block_size, **dtk)
        elif kind in ("ilu0", "ic0"):
            from .ilu import ilu_factory

            base = ilu_factory(
                pattern, kind, sweeps=self.sweeps,
                tri_sweeps=self.tri_sweeps, **dtk,
            )
        elif kind == "cheby":
            from .poly import cheby_factory

            base = cheby_factory(pattern, degree=self.degree)
        elif kind == "neumann":
            from .poly import neumann_factory

            base = neumann_factory(pattern, degree=self.degree)
        else:  # pragma: no cover - canonical_kind guards
            raise ValueError(f"unknown precond kind {kind!r}")

        if not (_faults.ACTIVE and _faults.targets("precond")):
            return base

        def faulty(values, matvec=None):
            return _faults.wrap_precond(base(values, matvec))

        return faulty

    def describe(self) -> dict:
        """JSON-friendly block for ``session_stats()``."""
        return {
            "mode": self.mode,
            "enabled": self.enabled,
            "block_size": self.block_size or settings.precond_block,
            "sweeps": self.sweeps or settings.precond_sweeps,
            "tri_sweeps": self.tri_sweeps or settings.precond_tri_sweeps,
            "degree": self.degree or settings.precond_degree,
        }
