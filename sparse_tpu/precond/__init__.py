"""Precond: pattern-shared batched preconditioners as a serving subsystem.

Every bench gain before this subsystem was per-iteration throughput;
this package attacks iteration *count* (ROADMAP item 3) the way the
Ginkgo batched line pairs every batched Krylov solver with a batched
preconditioner built once per sparsity pattern:

* pattern-level (symbolic) work — diagonal maps, block extraction
  indices, ILU(0)/IC(0) dependency closures — happens ONCE per
  :class:`~sparse_tpu.batch.operator.SparsityPattern` on the host,
  lives in :mod:`sparse_tpu.plan_cache` and persists as vault artifact
  kinds (``precond_diag`` / ``precond_block`` / ``ilu_symbolic``), so a
  warm restart skips it;
* numeric work — extracting diagonals/blocks, inverting the small
  dense block stack, Chow–Patel factorization sweeps — is pure batched
  jnp over the ``(B, nnz)`` value stack, executed INSIDE the compiled
  bucket programs (replicated closure constants under the fleet's
  ``shard_map`` programs — lane-local, no collectives);
* application is jit-safe and fixed-shape: diagonal scaling, batched
  block matmul, fixed-sweep Jacobi–Richardson triangular solves, or
  polynomial matvec chains — no data-dependent control flow anywhere.

:class:`~sparse_tpu.precond.policy.PrecondPolicy` resolves
``SPARSE_TPU_PRECOND`` / ``SolveSession(precond=...)`` / per-ticket
overrides into a per-(pattern, solver, bucket, dtype) choice that joins
the bucket-program plan-cache key and the vault warm-start manifest —
docs/preconditioners.md for the choice table and operational notes.
"""

from __future__ import annotations

import numpy as np

from .. import telemetry
from ..telemetry import _metrics
from .ilu import (  # noqa: F401
    IluSymbolic,
    factorize,
    ilu0_reference,
    ilu0_symbolic,
    ilu_factory,
)
from .jacobi import (  # noqa: F401
    bjacobi_factory,
    block_map,
    diag_map,
    diag_of,
    jacobi_factory,
)
from .policy import (  # noqa: F401
    KINDS,
    NONE,
    PRECOND_DTYPES,
    PrecondPolicy,
    canonical_kind,
    canonical_precond_dtype,
    dtype_suffix,
    key_suffix,
)
from .poly import cheby_factory, estimate_lmax, neumann_factory  # noqa: F401

__all__ = [
    "KINDS", "NONE", "PRECOND_DTYPES", "PrecondPolicy",
    "bjacobi_factory", "block_map", "canonical_kind",
    "canonical_precond_dtype", "cheby_factory", "diag_map", "diag_of",
    "dtype_suffix", "estimate_lmax", "factorize", "ilu0_reference",
    "ilu0_symbolic", "ilu_factory", "jacobi_factory", "key_suffix",
    "make_M", "make_factory", "neumann_factory",
]

# always-on build accounting (telemetry/_metrics.py): one count per
# pattern-level build by kind, plus the cumulative host build seconds —
# the cold-start share preconditioning adds (next to plan_cache's
# compile_s)
_BUILD_SECONDS = _metrics.counter(
    "precond.build_seconds",
    help="cumulative host-side pattern-level preconditioner build "
    "seconds (symbolic factorizations, extraction maps)",
)


def _build_event(kind: str, pattern, build_s: float = 0.0, **fields) -> None:
    """One pattern-level build: always-on counters + cost attribution +
    (telemetry on) a ``precond.build`` event. Called from the
    plan-cache build closures, so the cadence is exactly one per
    (pattern, kind) per vault — the same instrument the bench row's
    one-symbolic-factorization assertion reads."""
    _metrics.counter(
        "precond.builds", kind=kind,
        help="pattern-level preconditioner builds by kind",
    ).inc()
    _BUILD_SECONDS.add(float(build_s))
    from ..telemetry import _cost

    _cost.record_pack(
        f"precond.{kind}.{pattern.fingerprint[2][:12]}", float(build_s),
        precond=kind, n=int(pattern.shape[0]), nnz=int(pattern.nnz),
    )
    if telemetry.enabled():
        telemetry.record(
            "precond.build", precond=kind, n=int(pattern.shape[0]),
            nnz=int(pattern.nnz),
            build_ms=round(float(build_s) * 1e3, 3), **fields,
        )


def make_factory(pattern, kind: str, policy: PrecondPolicy | None = None):
    """Resolve ``kind`` to a numeric factory over ``pattern`` (``None``
    for 'none'/off) — the module-level form of
    :meth:`PrecondPolicy.factory`."""
    pol = policy or PrecondPolicy(kind)
    return pol.factory(pattern, canonical_kind(kind, allow_auto=False))


def make_M(A, kind: str = "jacobi", solver: str = "cg",
           policy: PrecondPolicy | None = None):
    """Unbatched convenience: build a preconditioner for ONE CSR-shaped
    matrix as a :class:`~sparse_tpu.linalg.LinearOperator` usable as the
    ``M=`` of :func:`sparse_tpu.linalg.cg` / ``gmres`` (and the recovery
    ladder). Internally the B=1 lane of the batched machinery — the
    same maps, factors and apply code the bucket programs run, so the
    B=1 parity contract holds by construction."""
    from ..batch.operator import BatchedCSR, SparsityPattern
    from ..linalg import LinearOperator
    from ..utils import asjnp

    pattern = SparsityPattern.from_csr(A)
    data = A.data if hasattr(A, "data") else A
    values = asjnp(np.asarray(data))[None, :]
    pol = policy or PrecondPolicy(kind)
    resolved = pol.decide(pattern, solver, 1, values.dtype, override=kind)
    fac = pol.factory(pattern, resolved)
    if fac is None:
        raise ValueError(f"precond kind {kind!r} resolves to none here")
    bmv = BatchedCSR(pattern, values).matvec
    Mvec = fac(values, bmv)

    def mv(x):
        return Mvec(asjnp(x)[None, :])[0]

    n = pattern.shape[0]
    return LinearOperator((n, n), matvec=mv, dtype=np.dtype(values.dtype))
