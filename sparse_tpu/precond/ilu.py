"""Pattern-shared batched ILU(0)/IC(0): symbolic once, numeric per stack.

The classical incomplete factorizations are sequential (row-by-row
elimination in dependency order) — hostile to both batching
and TPUs. This module follows the Ginkgo batched line (PAPERS.md §2)
and the Chow–Patel fixed-point formulation instead:

* **Symbolic, once per pattern** (:func:`ilu0_symbolic`): every nnz
  position ``p = (i, j)`` of the shared pattern gets its dependency
  pairs ``{(pos(i,k), pos(k,j)) : k < min(i, j)}`` flattened into
  padded ``(P, K)`` gather maps, plus the diagonal lookup each update
  divides by. Pure host work, cached in :mod:`sparse_tpu.plan_cache`
  and persisted as a vault artifact kind (``ilu_symbolic``), so a warm
  restart — and every same-pattern bucket — skips it entirely.
* **Numeric, batched, on device** (:func:`factorize`): ``sweeps``
  Chow–Patel iterations over the whole ``(B, nnz)`` value stack — each
  sweep is two gathers, a masked multiply-sum and a divide, identical
  work for every lane, no data-dependent control flow. A handful of
  sweeps reproduces the exact ILU(0)/IC(0) factors on the diagonally
  dominant PDE profiles this subsystem targets (the parity tests drive
  sweeps high to pin exactness).
* **Application** (:func:`make_apply`): the triangular solves become
  fixed-sweep Jacobi–Richardson iterations (``y <- D^{-1}(r - N y)``
  with ``N`` the strict triangle) — each sweep one batched SpMV through
  the pattern's shared SELL plan, so the apply is jit-safe inside the
  masked-Krylov loops and TPU-friendly (no sequential substitution).

IC(0) additionally requires a structurally symmetric pattern (checked
symbolically; the policy falls back to point Jacobi otherwise) and
applies ``M = L^{-T} L^{-1}`` with the transpose realized as a
pattern-shared position permutation — no transposed matrix is ever
materialized.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import plan_cache
from ..utils import commit_to_exec_device, host_scope


class IluSymbolic:
    """Device-resident symbolic half of a pattern's incomplete
    factorization (the vault-persisted artifact)."""

    __slots__ = ("variant", "dep_a", "dep_b", "dep_mask", "udiag",
                 "udiag_ok", "lower", "isdiag", "upper", "tpos", "dpos",
                 "has_diag", "symmetric")

    def __init__(self, variant, dep_a, dep_b, dep_mask, udiag, udiag_ok,
                 lower, isdiag, upper, tpos, dpos, has_diag, symmetric):
        self.variant = variant
        self.dep_a, self.dep_b, self.dep_mask = dep_a, dep_b, dep_mask
        self.udiag, self.udiag_ok = udiag, udiag_ok
        self.lower, self.isdiag, self.upper = lower, isdiag, upper
        self.tpos, self.dpos, self.has_diag = tpos, dpos, has_diag
        self.symmetric = bool(symmetric)


def _build_symbolic(pattern, variant: str) -> IluSymbolic:
    """Host symbolic factorization: dependency closure of the fixed
    pattern. ``variant`` is 'ilu0' (deps k < min(i, j)) or 'ic0'
    (lower-triangle deps k < j)."""
    with host_scope():
        n = pattern.shape[0]
        indptr = pattern.indptr.astype(np.int64)
        cols = pattern.indices.astype(np.int64)
        counts = indptr[1:] - indptr[:-1]
        rows = np.repeat(np.arange(n, dtype=np.int64), counts)
        P = int(cols.shape[0])
        pos = {(int(r), int(c)): p for p, (r, c) in enumerate(zip(rows, cols))}
        lower = rows > cols
        isdiag = rows == cols
        upper = rows < cols
        symmetric = all((int(c), int(r)) in pos for r, c in zip(rows, cols))
        tpos = np.zeros(P, dtype=np.int64)
        if symmetric:
            for p, (r, c) in enumerate(zip(rows, cols)):
                tpos[p] = pos[(int(c), int(r))]
        dpos = np.full(n, -1, dtype=np.int64)
        dpos[rows[isdiag]] = np.nonzero(isdiag)[0]
        has_diag = dpos >= 0
        row_sets = [
            set(cols[indptr[i]:indptr[i + 1]].tolist()) for i in range(n)
        ]
        deps_a: list = []
        deps_b: list = []
        for p in range(P):
            i, j = int(rows[p]), int(cols[p])
            kmax = min(i, j) if variant == "ilu0" else j
            da, db = [], []
            if variant == "ic0" and not (i >= j):
                deps_a.append(da)
                deps_b.append(db)
                continue
            for k in sorted(row_sets[i]):
                if k >= kmax:
                    break
                if variant == "ilu0":
                    if j in row_sets[k]:
                        da.append(pos[(i, k)])
                        db.append(pos[(k, j)])
                else:  # ic0: sum_k l_ik * conj(l_jk), k < j
                    if k in row_sets[j]:
                        da.append(pos[(i, k)])
                        db.append(pos[(j, k)])
            deps_a.append(da)
            deps_b.append(db)
        K = max(1, max((len(d) for d in deps_a), default=1))
        dep_a = np.zeros((P, K), dtype=np.int64)
        dep_b = np.zeros((P, K), dtype=np.int64)
        mask = np.zeros((P, K), dtype=bool)
        for p, (da, db) in enumerate(zip(deps_a, deps_b)):
            dep_a[p, : len(da)] = da
            dep_b[p, : len(db)] = db
            mask[p, : len(da)] = True
        # the divisor position of each update: u_jj (ilu0 lower) / l_jj
        # (ic0 strict lower) — the diagonal position of column j
        udiag = np.where(has_diag[cols], np.maximum(dpos[cols], 0), 0)
        udiag_ok = has_diag[cols]
    arrays = commit_to_exec_device((
        jnp.asarray(dep_a.astype(np.int32)),
        jnp.asarray(dep_b.astype(np.int32)),
        jnp.asarray(mask),
        jnp.asarray(udiag.astype(np.int32)),
        jnp.asarray(udiag_ok),
        jnp.asarray(lower),
        jnp.asarray(isdiag),
        jnp.asarray(upper),
        jnp.asarray(tpos.astype(np.int32)),
        jnp.asarray(np.maximum(dpos, 0).astype(np.int32)),
        jnp.asarray(has_diag),
    ))
    return IluSymbolic(variant, *arrays, symmetric)


def ilu0_symbolic(pattern, variant: str = "ilu0") -> IluSymbolic:
    """The pattern's symbolic factorization via the two-tier plan cache:
    ONE host-side build per pattern ever (per *vault* when the
    persistent tier is on — the artifact kind ``ilu_symbolic`` replays
    across restarts)."""
    if variant not in ("ilu0", "ic0"):
        raise ValueError(f"variant must be 'ilu0' or 'ic0'; got {variant!r}")

    def build():
        import time

        from . import _build_event

        t0 = time.perf_counter()
        sym = _build_symbolic(pattern, variant)
        _build_event(variant, pattern, time.perf_counter() - t0,
                     stage="symbolic", P=int(pattern.nnz))
        return sym

    def vault_key():
        from ..vault import _codecs

        return _codecs.digest("ilusym", variant, pattern.fingerprint[2])

    return plan_cache.get(
        pattern, f"precond.{variant}.symbolic", build,
        vault_kind="ilu_symbolic", vault_key=vault_key,
        expect={"variant": variant},
    )


def _safe(d):
    one = jnp.ones((), dtype=d.dtype)
    return jnp.where(d == 0, one, d)


def factorize(sym: IluSymbolic, values, sweeps: int):
    """Batched Chow–Patel numeric factorization of a ``(B, nnz)`` value
    stack over a shared symbolic structure. Returns ``F`` in the same
    ``(B, nnz)`` layout: for 'ilu0' strict-lower positions hold L
    (unit diagonal implied) and upper-plus-diagonal positions hold U;
    for 'ic0' the lower triangle (diagonal included) holds L and upper
    positions are unused."""
    a = values
    # the standard Chow-Patel initial guess: lower entries pre-scaled by
    # the column diagonal (sqrt of it for IC) — the naive F0 = A can
    # diverge the fixed point on matrices with large diagonals
    dcol = jnp.where(sym.udiag_ok, a[..., sym.udiag],
                     jnp.ones((), dtype=a.dtype))
    if sym.variant == "ic0":
        sdcol = jnp.sqrt(jnp.maximum(
            jnp.real(dcol),
            jnp.asarray(np.finfo(np.dtype(jnp.real(a).dtype).type).tiny),
        )).astype(a.dtype)
        F = jnp.where(sym.isdiag, sdcol,
                      jnp.where(sym.lower, a / sdcol, a))
    else:
        F = jnp.where(sym.lower, a / _safe(dcol), a)
    conj = jnp.conj if sym.variant == "ic0" else (lambda x: x)
    for _ in range(max(int(sweeps), 1)):
        s = jnp.sum(
            F[..., sym.dep_a] * conj(F[..., sym.dep_b])
            * sym.dep_mask.astype(jnp.real(a).dtype),
            axis=-1,
        )
        num = a - s
        if sym.variant == "ilu0":
            div = jnp.where(sym.udiag_ok, _safe(F[..., sym.udiag]),
                            jnp.ones((), dtype=F.dtype))
            F = jnp.where(sym.lower, num / div, num)
        else:
            diag_new = jnp.sqrt(
                jnp.maximum(jnp.real(num), jnp.asarray(
                    np.finfo(np.dtype(jnp.real(a).dtype).type).tiny
                ))
            ).astype(F.dtype)
            div = jnp.where(sym.udiag_ok, _safe(F[..., sym.udiag]),
                            jnp.ones((), dtype=F.dtype))
            F = jnp.where(
                sym.isdiag, diag_new,
                jnp.where(sym.lower, num / div, F),
            )
    return F


def ilu0_reference(indptr, indices, vals):
    """Host reference ILU(0) (IKJ, exact): the oracle the parity tests
    and the chaos rebuild drill compare the fixed-sweep factorization
    against. Returns the factor in the same nnz layout as
    :func:`factorize` ('ilu0' convention)."""
    indptr = np.asarray(indptr, dtype=np.int64)
    indices = np.asarray(indices, dtype=np.int64)
    F = np.array(vals, copy=True)
    n = indptr.shape[0] - 1
    pos = {}
    for i in range(n):
        for p in range(indptr[i], indptr[i + 1]):
            pos[(i, int(indices[p]))] = p
    for i in range(1, n):
        for p in range(indptr[i], indptr[i + 1]):
            k = int(indices[p])
            if k >= i:
                continue
            dk = pos.get((k, k))
            if dk is None or F[dk] == 0:
                continue
            F[p] = F[p] / F[dk]
            for q in range(indptr[i], indptr[i + 1]):
                j = int(indices[q])
                if j <= k:
                    continue
                kj = pos.get((k, j))
                if kj is not None:
                    F[q] = F[q] - F[p] * F[kj]
    return F


def make_apply(pattern, sym: IluSymbolic, F, tri_sweeps: int,
               acc_dtype=None):
    """Batched triangular application ``Mvec(R) ~= (LU)^{-1} R`` (ilu0)
    or ``(L L^H)^{-1} R`` (ic0) via fixed Jacobi–Richardson sweeps —
    each sweep ONE batched SpMV through the pattern's shared SELL plan,
    no data-dependent control flow. Returns the jit-safe ``Mvec``.

    ``acc_dtype`` (ISSUE 16): when the factor stack ``F`` is stored at
    a reduced dtype, the sweep SpMVs accumulate at ``acc_dtype`` (the
    same widening the inner Krylov matvec carries) and the diagonal
    reciprocals are computed wide — so a bf16-stored factor costs bf16
    streaming and f32 math. ``None`` (default) is byte-identical to
    the historic apply."""
    from ..ops import spmv as spmv_ops

    pack = pattern.sell_pack()
    idx_slabs, pos, zero_rows = pack.idx_slabs, pack.pos, pack.plan.zero_rows
    K = max(int(tri_sweeps), 1)
    zero = jnp.zeros((), dtype=F.dtype)
    adt = None if acc_dtype is None else jnp.dtype(acc_dtype)

    def spmv(vals_packed, X):
        return spmv_ops.csr_spmv_sell_batched(
            idx_slabs, vals_packed, pos, X, zero_rows,
            **({} if adt is None else {"acc_dtype": adt}),
        )

    def _wide(x):
        return x if adt is None else x.astype(adt)

    if sym.variant == "ilu0":
        Ls = pack.pack_values(jnp.where(sym.lower, F, zero))
        Us = pack.pack_values(jnp.where(sym.upper, F, zero))
        ud = _wide(jnp.where(sym.has_diag, F[..., sym.dpos],
                             jnp.ones((), dtype=F.dtype)))
        ud_inv = jnp.ones((), dtype=ud.dtype) / _safe(ud)

        def Mvec(R):
            y = R
            for _ in range(K):
                y = R - spmv(Ls, y)  # unit-diagonal L
            z = y * ud_inv
            for _ in range(K):
                z = (y - spmv(Us, z)) * ud_inv
            return z

        return Mvec

    # ic0: M = L^{-H} L^{-1}; the transpose of the strict-lower factor
    # is the SAME pattern's strict-upper positions through `tpos`
    Ls = pack.pack_values(jnp.where(sym.lower, F, zero))
    Lts = pack.pack_values(
        jnp.where(sym.upper, jnp.conj(F[..., sym.tpos]), zero)
    )
    ld = _wide(jnp.where(sym.has_diag, F[..., sym.dpos],
                         jnp.ones((), dtype=F.dtype)))
    ld_inv = jnp.ones((), dtype=ld.dtype) / _safe(ld)
    ld_inv_h = jnp.conj(ld_inv)

    def Mvec(R):
        y = R * ld_inv
        for _ in range(K):
            y = (R - spmv(Ls, y)) * ld_inv
        z = y * ld_inv_h
        for _ in range(K):
            z = (y - spmv(Lts, z)) * ld_inv_h
        return z

    return Mvec


def ilu_factory(pattern, variant: str = "ilu0", sweeps: int | None = None,
                tri_sweeps: int | None = None, storage_dtype=None,
                acc_dtype=None):
    """The service-facing numeric factory: symbolic build (cached/
    vaulted) happens HERE, on the host; the returned
    ``factory(values, matvec) -> Mvec`` is pure jnp and runs inside the
    compiled bucket programs.

    ``storage_dtype`` / ``acc_dtype`` (ISSUE 16): the Chow–Patel
    fixed point runs at ``acc_dtype`` (its convergence needs the
    bits), the factor stack is STORED at ``storage_dtype``, and the
    triangular sweeps widen back through the SpMV's ``acc_dtype`` —
    narrow streaming, wide math. ``None`` (default) is byte-identical
    to the historic factory."""
    from ..config import settings

    sym = ilu0_symbolic(pattern, variant)
    pattern.sell_pack()  # the apply's SpMV plan, warmed outside traces
    sweeps = int(sweeps if sweeps is not None else settings.precond_sweeps)
    tri = int(
        tri_sweeps if tri_sweeps is not None else settings.precond_tri_sweeps
    )
    sdt = None if storage_dtype is None else jnp.dtype(storage_dtype)
    adt = None if acc_dtype is None else jnp.dtype(acc_dtype)

    def factory(values, matvec=None):
        a = values if adt is None else values.astype(adt)
        F = factorize(sym, a, sweeps)
        if sdt is not None:
            F = F.astype(sdt)
        return make_apply(pattern, sym, F, tri, acc_dtype=adt)

    return factory
