"""Polynomial (Chebyshev / Neumann) batched preconditioning.

The matvec-only option: no factorization, no new kernel — the
preconditioner is a fixed-degree polynomial in ``A`` evaluated with the
*existing* batched SpMV, so it works for every pattern the batch
subsystem serves (including ones block extraction or incomplete
factorization cannot help) and costs exactly ``degree`` extra matvecs
per application.

* **Chebyshev** (``cheby``): the degree-``d`` Chebyshev approximation
  of ``A^{-1}`` on a per-lane spectral interval ``[lmax/ratio, lmax]``.
  ``lmax`` comes from a short per-bucket power iteration (fixed count,
  jit-safe, deterministic start vector) run INSIDE the compiled program
  against the same batched matvec the solver uses — so every dispatch
  estimates its own stack's spectrum with no host round trip.
* **Neumann** (``neumann``): the truncated Neumann series
  ``sum_k (I - D^{-1}A)^k D^{-1}`` — the diagonally scaled variant that
  needs only the point-Jacobi map plus matvecs.

Both are SPD-preserving for SPD ``A`` (a positive polynomial of an SPD
operator), so they are CG-safe.
"""

from __future__ import annotations

import jax.numpy as jnp

from .jacobi import diag_of, _safe_recip


def estimate_lmax(matvec, like, iters: int = 8, safety: float = 1.05):
    """Per-lane largest-eigenvalue estimate by fixed-count power
    iteration (jit-safe: deterministic start, static trip count).
    ``like`` supplies the ``(B, n)`` shape/dtype. Returns ``(B,)`` in
    the real dtype, floored at tiny positive."""
    rdt = jnp.real(like).dtype
    B, n = like.shape
    # deterministic non-degenerate start: varying positive entries so
    # the iterate is never orthogonal to the dominant eigenvector of a
    # structured stencil
    v = (1.0 + 0.5 * jnp.cos(jnp.arange(n, dtype=rdt)))[None, :]
    v = jnp.broadcast_to(v, (B, n)).astype(like.dtype)
    lam = jnp.ones((B,), dtype=rdt)
    for _ in range(max(int(iters), 1)):
        w = matvec(v)
        nrm = jnp.sqrt(jnp.sum(jnp.abs(w) ** 2, axis=-1))
        lam = jnp.maximum(nrm / jnp.maximum(
            jnp.sqrt(jnp.sum(jnp.abs(v) ** 2, axis=-1)), 1e-30
        ), 1e-30)
        v = w / jnp.maximum(nrm, 1e-30)[:, None].astype(like.dtype)
    return lam * safety


def cheby_factory(pattern=None, degree: int | None = None,
                  ratio: float = 30.0, power_iters: int = 8):
    """Chebyshev numeric factory: ``factory(values, matvec) -> Mvec``.
    ``pattern`` is unused (matvec-only) and accepted for the uniform
    factory signature."""
    from ..config import settings

    d = max(int(degree if degree is not None else settings.precond_degree), 1)

    def factory(values, matvec):
        if matvec is None:
            raise ValueError("cheby preconditioning needs the matvec")

        def Mvec(R):
            lmax = estimate_lmax(matvec, R, iters=power_iters)
            lmin = lmax / float(ratio)
            rdt = jnp.real(R).dtype
            theta = ((lmax + lmin) / 2).astype(rdt)[:, None]
            delta = ((lmax - lmin) / 2).astype(rdt)[:, None]
            sigma = theta / delta
            # standard Chebyshev semi-iteration on A z = R from z0 = 0
            rho = 1.0 / sigma
            dvec = R / theta.astype(R.dtype)
            z = dvec
            for _ in range(d - 1):
                rho_new = 1.0 / (2.0 * sigma - rho)
                dvec = (rho_new * rho).astype(R.dtype) * dvec + (
                    2.0 * rho_new / delta
                ).astype(R.dtype) * (R - matvec(z))
                z = z + dvec
                rho = rho_new
            return z

        return Mvec

    return factory


def neumann_factory(pattern, degree: int | None = None):
    """Truncated Neumann-series factory over the diagonally scaled
    operator: ``factory(values, matvec) -> Mvec``."""
    from ..config import settings
    from .jacobi import diag_map

    d = max(int(degree if degree is not None else settings.precond_degree), 1)
    diag_map(pattern)  # host build outside any trace

    def factory(values, matvec):
        if matvec is None:
            raise ValueError("neumann preconditioning needs the matvec")
        dinv = _safe_recip(diag_of(pattern, values))

        def Mvec(R):
            y = dinv * R
            z = y
            for _ in range(d):
                y = y - dinv * matvec(y)
                z = z + y
            return z

        return Mvec

    return factory
