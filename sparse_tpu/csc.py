"""CSC sparse array.

Reference analog: ``sparse/csc.py`` (682 LoC; class at csc.py:78, col-split SpMV
csc.py:523, SpMM csc.py:630, SDDMM csc.py:556, dot csc.py:368). Shares all
machinery with CSR through transposition: a CSC matrix is the CSR encoding of
its transpose, so most ops route through zero-copy reinterpretation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import SparseArray
from .ops import conv, sddmm as sddmm_ops, spmv as spmv_ops
from .utils import asjnp, host_int


@jax.tree_util.register_pytree_node_class
class csc_array(SparseArray):
    format = "csc"

    def __init__(self, arg, shape=None, dtype=None, copy=False):
        from .coo import coo_array

        if isinstance(arg, csc_array):
            data, indices, indptr, shape = arg.data, arg.indices, arg.indptr, arg.shape
        elif isinstance(arg, SparseArray):
            c = arg.tocsc()
            data, indices, indptr, shape = c.data, c.indices, c.indptr, c.shape
        elif isinstance(arg, tuple) and len(arg) == 3:
            data, indices, indptr = (asjnp(a) for a in arg)
            if shape is None:
                nrows = host_int(indices.max()) + 1 if indices.shape[0] else 0
                shape = (nrows, indptr.shape[0] - 1)
        elif isinstance(arg, tuple) and len(arg) == 2 and isinstance(arg[1], tuple):
            c = coo_array(arg, shape=shape).tocsc()
            data, indices, indptr, shape = c.data, c.indices, c.indptr, c.shape
        elif isinstance(arg, tuple) and len(arg) == 2:
            shape = (int(arg[0]), int(arg[1]))
            indptr = jnp.zeros((shape[1] + 1,), dtype=np.int32)
            indices = jnp.zeros((0,), dtype=np.int32)
            data = jnp.zeros((0,), dtype=dtype or np.float32)
        elif hasattr(arg, "tocsc"):  # scipy
            s = arg.tocsc()
            data, indices, indptr = asjnp(s.data), asjnp(s.indices), asjnp(s.indptr)
            shape = s.shape
        else:  # dense
            d = asjnp(arg)
            if d.ndim != 2:
                raise ValueError("CSC arrays must be 2-D")
            indptr, indices, data, _ = conv.dense_to_csc(d)
            shape = d.shape
        if dtype is not None:
            data = data.astype(dtype)
        self.data = asjnp(data)
        self.indices = asjnp(indices)
        self.indptr = asjnp(indptr)
        self._shape = (int(shape[0]), int(shape[1]))
        self._dtype = np.dtype(self.data.dtype)

    @classmethod
    def from_parts(cls, data, indices, indptr, shape):
        obj = object.__new__(cls)
        obj.data = asjnp(data)
        obj.indices = asjnp(indices)
        obj.indptr = asjnp(indptr)
        obj._shape = (int(shape[0]), int(shape[1]))
        obj._dtype = np.dtype(obj.data.dtype)
        return obj

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.indices, self.indptr), self._shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        data, indices, indptr = children
        return cls.from_parts(data, indices, indptr, shape)

    # ----------------------------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(self.data.shape[0])

    def _data_array(self):
        return self.data

    def _with_data(self, data):
        return csc_array.from_parts(data, self.indices, self.indptr, self.shape)

    # -- products ----------------------------------------------------------
    def dot(self, other, out=None):
        """A @ other with A CSC: column-split SpMV/SpMM (csc.py:368,523,630)."""
        if isinstance(other, SparseArray):
            return self.tocsr().dot(other)
        x = asjnp(other)
        if x.ndim == 1:
            if x.shape[0] != self.shape[1]:
                raise ValueError(f"dimension mismatch: {self.shape} @ {x.shape}")
            y = spmv_ops.csc_spmv(
                self.indptr, self.indices, self.data, x, self.shape[0]
            )
        elif x.ndim == 2:
            if x.shape[0] != self.shape[1]:
                raise ValueError(f"dimension mismatch: {self.shape} @ {x.shape}")
            # C = A @ B with A CSC == (rspmm of B.T through A-as-CSR-of-A.T).T
            y = spmv_ops.rspmm(
                self.indptr, self.indices, self.data, x.T, self.shape[0]
            ).T
        else:
            raise ValueError("can only multiply by 1-D or 2-D arrays")
        if out is not None and out.shape != y.shape:
            raise ValueError("out has the wrong shape")
        return y

    def _rdot(self, other):
        B = asjnp(other)
        # B @ A where A [m,n] CSC == CSR of A.T [n,m]: (A.T @ B.T).T
        if B.ndim == 1:
            return spmv_ops.csr_spmv_segment(
                self.indptr, self.indices, self.data, B, self.shape[1]
            )
        return spmv_ops.csr_spmm_segment(
            self.indptr, self.indices, self.data, B.T, self.shape[1]
        ).T

    def matvec(self, x, out=None):
        return self.dot(x, out=out)

    def sddmm(self, C, D):
        vals = sddmm_ops.csc_sddmm(
            self.indptr, self.indices, self.data, asjnp(C), asjnp(D)
        )
        return self._with_data(vals)

    # -- elementwise / reductions ------------------------------------------
    def __add__(self, other):
        if isinstance(other, SparseArray):
            return (self.tocsr() + other).tocsc()
        return self.tocsr() + other  # scalar raises there; dense densifies there

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", 1) == 0:
            return self._with_data(self.data * other)
        return self.tocsr().multiply(other).tocsc()

    def multiply(self, other):
        return self.__mul__(other)

    def sum(self, axis=None):
        if axis is None:
            return self.data.sum()
        # CSC of A == CSR of A.T: flip the axis and reuse CSR reduction
        from .ops import elementwise

        flip = {0: 1, -2: 1, 1: 0, -1: 0}[axis]
        return elementwise.csr_sum(
            self.indptr, self.indices, self.data,
            (self.shape[1], self.shape[0]), axis=flip,
        )

    def diagonal(self, k=0):
        from .ops import elementwise

        return elementwise.csr_diagonal(
            self.indptr, self.indices, self.data,
            (self.shape[1], self.shape[0]), k=-k,
        )

    # -- conversions -------------------------------------------------------
    def tocsc(self):
        return self

    def tocsr(self):
        from .csr import csr_array

        indptr, indices, data = conv.csr_to_csc(
            self.indptr, self.indices, self.data, (self.shape[1], self.shape[0])
        )
        return csr_array.from_parts(data, indices, indptr, self.shape)

    def tocoo(self):
        from .coo import coo_array
        from .ops.coords import expand_rows

        cols = expand_rows(self.indptr, self.nnz)
        out = coo_array(
            (self.data, (self.indices, cols)), shape=self.shape
        )
        # column-major order, not row-major: scipy's canonical flag would
        # overclaim (it means lex-sorted + deduped), so mark only the
        # duplicate-freeness that reductions need
        out._duplicate_free = True
        return out

    def todia(self):
        from .dia import dia_array

        return dia_array(self.tocoo())

    def toarray(self):
        return conv.csr_to_dense(
            self.indptr, self.indices, self.data, (self.shape[1], self.shape[0])
        ).T

    def transpose(self, axes=None):
        if axes is not None:
            raise ValueError("transpose with axes != None is unsupported")
        from .csr import csr_array

        return csr_array.from_parts(
            self.data, self.indices, self.indptr, (self.shape[1], self.shape[0])
        )

    @property
    def T(self):
        return self.transpose()

    def balance(self, num_shards=None):
        return self

    def __str__(self):
        return (
            f"<{self.shape[0]}x{self.shape[1]} CSC array, nnz={self.nnz},"
            f" dtype={self.dtype}>"
        )

    __repr__ = __str__
