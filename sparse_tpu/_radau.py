"""Radau IIA(5) — implicit Runge-Kutta for stiff ODEs
(scipy.integrate.Radau semantics: 3-stage, order 5, L-stable, with the
Hairer-Wanner real/complex factorization split).

Beyond the reference (explicit RK only). TPU notes mirror _bdf.py: each
Newton iteration is two device triangular-solve applies (one real LU,
one complex LU of dimension n — the 3n-stage system decouples through
the eigenbasis of the RK coefficient inverse), refactored only when the
Jacobian or step size changes.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .utils import asjnp
from ._bdf import BDF as _BDFBase  # reuse jacobian plumbing

S6 = 6 ** 0.5

# Butcher/collocation data (Hairer & Wanner V.8, analytic)
C_NODES = np.array([(4 - S6) / 10, (4 + S6) / 10, 1.0])
A_BUTCHER = np.array([
    [11 / 45 - 7 * S6 / 360, 37 / 225 - 169 * S6 / 1800,
     -2 / 225 + S6 / 75],
    [37 / 225 + 169 * S6 / 1800, 11 / 45 + 7 * S6 / 360,
     -2 / 225 - S6 / 75],
    [4 / 9 - S6 / 36, 4 / 9 + S6 / 36, 1 / 9],
])
E_ERR = np.array([-13 - 7 * S6, -13 + 7 * S6, -1]) / 3
# interpolator coefficients (collocation polynomial, analytic)
P_INTERP = np.array([
    [13 / 3 + 7 * S6 / 3, -23 / 3 - 22 * S6 / 3, 10 / 3 + 5 * S6],
    [13 / 3 - 7 * S6 / 3, -23 / 3 + 22 * S6 / 3, 10 / 3 - 5 * S6],
    [1 / 3, -8 / 3, 10 / 3]])


def _transform_constants():
    """Eigen-split of inv(A): one real eigenvalue + a conjugate pair.
    Derived numerically from the analytic Butcher matrix so the
    left-eigenvector relations TI_x @ inv(A) = mu_x * TI_x hold exactly
    (the scaling of the eigenvectors is arbitrary; T = inv(TI) keeps the
    pair consistent). Left eigenvectors of Ainv are right eigenvectors
    of Ainv.T — plain numpy, no import-time scipy dependency."""
    Ainv = np.linalg.inv(A_BUTCHER)
    w, v = np.linalg.eig(Ainv.T)  # v[:, i]^T @ Ainv = w[i] * v[:, i]^T
    real_i = int(np.argmin(np.abs(w.imag)))
    cplx_i = int(np.argmax(np.abs(w.imag)))
    mu_real = float(w[real_i].real)
    mu_complex = complex(w[cplx_i])
    if abs(mu_complex.imag) < 1e-12:
        raise RuntimeError("radau: complex pair not found")
    ti_real = v[:, real_i].real.copy()
    ti_complex = v[:, cplx_i].copy()
    TI = np.vstack([ti_real, ti_complex.real, ti_complex.imag])
    T = np.linalg.inv(TI)
    return mu_real, mu_complex, T, TI, ti_real, ti_complex


(MU_REAL, MU_COMPLEX, T_MAT, TI_MAT, TI_REAL, TI_COMPLEX) = (
    _transform_constants()
)

NEWTON_MAXITER = 6
MIN_FACTOR = 0.2
MAX_FACTOR = 10.0

from ._bdf import _norm_rms  # noqa: E402  (shared scaled-RMS helper)


class Radau:
    """Radau IIA order-5 solver (``solve_ivp(..., method='Radau')``)."""

    def __init__(self, fun, t0, y0, t_bound, max_step=np.inf, rtol=1e-3,
                 atol=1e-6, jac=None, jac_sparsity=None, vectorized=False,
                 first_step=None, **extraneous):
        from .integrate import (
            OdeSolver, select_initial_step, validate_max_step, validate_tol,
        )

        OdeSolver.__init__(self, fun, t0, y0, t_bound, vectorized,
                           support_complex=False)
        self.max_step = validate_max_step(max_step)
        self.rtol, self.atol = validate_tol(rtol, atol, self.n)
        self.f = np.asarray(self.fun(self.t, self.y))
        self.nfev += 1
        if first_step is None:
            self.h_abs = select_initial_step(
                self.fun, self.t, self.y, asjnp(self.f), self.direction, 3,
                self.rtol, self.atol,
            )
        else:
            self.h_abs = float(first_step)
        self.h_abs_old = None
        self.error_norm_old = None
        self.newton_tol = max(
            10 * np.finfo(np.float64).eps / self.rtol,
            min(0.03, self.rtol ** 0.5),
        )
        self.sol = None
        # reuse BDF's jacobian handling (callable / constant / numeric)
        self._jac_arg = jac
        self._jac_callable = None
        self.J = _BDFBase._validate_jac(self, self.t, self.y, asjnp(self.f))
        self.current_jac = True
        self.LU_real = None
        self.LU_complex = None
        self.Z = None

    _validate_jac = _BDFBase._validate_jac
    _as_dense = staticmethod(_BDFBase._as_dense)
    _num_jac = _BDFBase._num_jac
    _refresh_jac = _BDFBase._refresh_jac

    def _lu_pair(self, h):
        from jax.scipy.linalg import lu_factor

        self.nlu += 2
        J = jnp.asarray(self.J)
        n = self.n
        lu_r = lu_factor(
            MU_REAL / h * jnp.eye(n, dtype=J.dtype) - J
        )
        lu_c = lu_factor(
            MU_COMPLEX / h * jnp.eye(n, dtype=jnp.complex128
                                     if J.dtype == jnp.float64
                                     else jnp.complex64) - J.astype(
                jnp.complex128 if J.dtype == jnp.float64 else jnp.complex64
            )
        )
        return lu_r, lu_c

    @staticmethod
    def _solve_lu(LU, b):
        from jax.scipy.linalg import lu_solve

        return np.asarray(lu_solve(LU, jnp.asarray(b)))

    def _solve_collocation(self, t, y, h, Z0, scale):
        """Newton on the transformed collocation system (Hairer-Wanner):
        the 3n system splits into one real and one complex n-system."""
        n = self.n
        M_real = MU_REAL / h
        M_complex = MU_COMPLEX / h
        W = TI_MAT.dot(Z0)
        Z = Z0.copy()
        F = np.empty((3, n))
        ch = h * C_NODES
        dW_norm_old = None
        converged = False
        rate = None
        for k in range(NEWTON_MAXITER):
            for i in range(3):
                F[i] = np.asarray(self.fun(t + ch[i], asjnp(y + Z[i])))
            self.nfev += 3
            if not np.all(np.isfinite(F)):
                break
            f_real = F.T.dot(TI_REAL) - M_real * W[0]
            f_complex = F.T.dot(TI_COMPLEX) - M_complex * (W[1] + 1j * W[2])
            dW_real = self._solve_lu(self.LU_real, f_real)
            dW_complex = self._solve_lu(self.LU_complex, f_complex)
            dW = np.vstack([dW_real, dW_complex.real, dW_complex.imag])
            dW_norm = _norm_rms(dW.ravel(), np.tile(scale, 3))
            rate = None if dW_norm_old is None else dW_norm / dW_norm_old
            if rate is not None and (
                rate >= 1
                or rate ** (NEWTON_MAXITER - k) / (1 - rate) * dW_norm
                > self.newton_tol
            ):
                break
            W += dW
            Z = T_MAT.dot(W)
            if dW_norm == 0 or (
                rate is not None
                and rate / (1 - rate) * dW_norm < self.newton_tol
            ):
                converged = True
                break
            dW_norm_old = dW_norm
        return converged, k + 1, Z, rate

    def _step_impl(self):
        t = self.t
        y = np.asarray(self.y)
        f = self.f
        max_step = self.max_step
        min_step = 10 * np.abs(np.nextafter(t, self.direction * np.inf) - t)
        h_abs = min(max(self.h_abs, min_step), max_step)
        if h_abs != self.h_abs:
            self.LU_real = self.LU_complex = None

        rejected = False
        step_accepted = False
        while not step_accepted:
            if h_abs < min_step:
                return False, self.TOO_SMALL_STEP
            h = h_abs * self.direction
            t_new = t + h
            if self.direction * (t_new - self.t_bound) > 0:
                t_new = self.t_bound
            h = t_new - t
            h_abs = np.abs(h)

            if self.sol is None:
                Z0 = np.zeros((3, y.shape[0]))
            else:
                Z0 = np.asarray(
                    self.sol(t + h * C_NODES)
                ).T - y[None, :]

            scale = self.atol + np.abs(y) * self.rtol
            converged = False
            while not converged:
                if self.LU_real is None:
                    self.LU_real, self.LU_complex = self._lu_pair(h)
                converged, n_iter, Z, rate = self._solve_collocation(
                    t, y, h, Z0, scale
                )
                if not converged:
                    if self.current_jac:
                        break
                    self.J = self._refresh_jac(t, asjnp(y), asjnp(f))
                    self.current_jac = True
                    self.LU_real = self.LU_complex = None
            if not converged:
                h_abs *= 0.5
                self.LU_real = self.LU_complex = None
                continue

            y_new = y + Z[2]
            # embedded error estimate (Hairer-Wanner): filter the lower-
            # order defect through the real factor for L-stable damping
            ZE = Z.T.dot(E_ERR) / h
            error = self._solve_lu(self.LU_real, np.asarray(f) + ZE)
            scale_new = self.atol + np.maximum(np.abs(y), np.abs(y_new)) * self.rtol
            error_norm = _norm_rms(error, scale_new)
            safety = 0.9 * (2 * NEWTON_MAXITER + 1) / (
                2 * NEWTON_MAXITER + n_iter
            )
            if rejected and error_norm > 1:
                # stiff-accurate re-estimate after a rejection
                F0 = np.asarray(self.fun(t, asjnp(y + error)))
                self.nfev += 1
                error = self._solve_lu(self.LU_real, F0 + ZE)
                error_norm = _norm_rms(error, scale_new)
            if error_norm > 1:
                factor = max(MIN_FACTOR, safety * error_norm ** -0.25)
                h_abs *= factor
                self.LU_real = self.LU_complex = None
                rejected = True
                continue
            step_accepted = True

        # predictive step controller (scipy's form)
        if error_norm == 0:
            factor = MAX_FACTOR
        elif self.error_norm_old is None or self.h_abs_old is None:
            factor = min(MAX_FACTOR, safety * error_norm ** -0.25)
        else:
            mult = (h_abs / self.h_abs_old
                    * (self.error_norm_old / error_norm) ** 0.25)
            factor = min(
                MAX_FACTOR,
                max(MIN_FACTOR,
                    safety * min(1.0, mult) * error_norm ** -0.25),
            )
        self.h_abs_old = h_abs
        self.error_norm_old = error_norm

        f_new = np.asarray(self.fun(t_new, asjnp(y_new)))
        self.nfev += 1
        self.Z = Z
        self.t = t_new
        self.y = asjnp(y_new)
        self.f = f_new
        # scipy's controller tail: modest growth is snapped to 1 so the
        # LU pair is REUSED across runs of similar steps (the whole point
        # of the "refactor only on step-size/Jacobian change" design)
        if factor < 1.2:
            factor = 1.0
        else:
            self.LU_real = self.LU_complex = None
        self.h_abs = h_abs * factor
        if self._jac_callable is not None or self._jac_arg is None:
            self.current_jac = False
        # built from the step's OWN bounds (t, t_new): the base class
        # updates self.t_old only after _step_impl returns
        self.sol = _RadauDenseOutput(t, t_new, y, self.Z.T.dot(P_INTERP))
        return True, None

    def _dense_output_impl(self):
        return self.sol


_DENSE_CLS_CACHE = []


def _make_dense_output_cls():
    if _DENSE_CLS_CACHE:  # one class, many instances
        return _DENSE_CLS_CACHE[0]
    from .integrate import DenseOutput

    class _RadauDenseOutputCls(DenseOutput):
        """Collocation-polynomial interpolant over one accepted step."""

        def __init__(s, t_old, t, y_old, Q):
            super().__init__(t_old, t)
            s.h = t - t_old
            s.Q = Q
            s.order = Q.shape[1] - 1
            s.y_start = np.asarray(y_old)

        def _call_impl(s, t):
            t = np.asarray(t)
            x = (t - s.t_old) / s.h
            if t.ndim == 0:
                p = np.cumprod(np.tile(x, s.order + 1))
                y = s.y_start + np.dot(s.Q, p)
            else:
                p = np.cumprod(np.tile(x, (s.order + 1, 1)), axis=0)
                y = s.y_start[:, None] + np.dot(s.Q, p)
            return asjnp(y)

    _DENSE_CLS_CACHE.append(_RadauDenseOutputCls)
    return _RadauDenseOutputCls


def _RadauDenseOutput(t_old, t, y_old, Q):
    return _make_dense_output_cls()(t_old, t, y_old, Q)
