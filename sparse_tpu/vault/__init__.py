"""Vault: the crash-safe persistent tier of the plan cache (ISSUE 9).

The bench's ``batched_cg`` row shows the serving tax of a cold process:
16x cold vs ~109x warm — everything between those numbers is SELL
packs, DIA preps and per-bucket compiles a fresh process re-derives
from scratch. The vault persists those prepared artifacts across
processes (ROADMAP item 4's second cache tier), and treats persistence
as a *robustness* feature: a server killed mid-traffic comes back warm
by replaying the warm-start manifest, and no corrupt, truncated or
stale on-disk artifact can ever crash or mis-serve the process — every
read is verify-then-load, every failure quarantines and degrades to a
rebuild (docs/performance.md for the layout and operational recipe,
docs/resilience.md for the failure contract and the ``io:*`` chaos
grammar).

Layout under ``SPARSE_TPU_VAULT=<dir>``::

    objects/<kind>/<content-key>.stv   verified artifacts (_store.py)
    manifest.json                      warm-start manifest (_manifest.py)
    quarantine/                        failed-verification sidecar
    tmp/                               per-process atomic-write staging

Integration points:

* ``plan_cache.get(..., vault_kind=, vault_key=)`` — the two-tier read
  path: in-process weak-ref LRU first, then this disk tier
  (``plan_cache.stats()['disk_hits']``), then build + deposit.
* ``SolveSession(warm_start=...)`` — manifest replay on construction
  plus per-program noting at every bucket-program build.
* ``scripts/vault_gc.py`` / :func:`gc` — size-budgeted LRU sweep
  (``SPARSE_TPU_VAULT_CAP_MB``).
"""

from __future__ import annotations

from . import _codecs, _manifest, _store
from ._manifest import clear as clear_manifest  # noqa: F401
from ._manifest import entries as manifest_entries  # noqa: F401
from ._store import (  # noqa: F401
    FORMAT,
    MAGIC,
    SUFFIX,
    artifact_path,
    enabled,
    gc,
    load,
    quarantine,
    quarantine_dir,
    reset_stats,
    stats,
    store,
    vault_dir,
)

__all__ = [
    "artifact_path", "clear_manifest", "deposit", "enabled", "fetch",
    "gc", "load", "load_pattern", "manifest_entries", "note_program",
    "quarantine", "quarantine_dir", "reset_stats", "stats", "store",
    "store_pattern", "vault_dir",
]


def fetch(kind: str, key: str, expect: dict | None = None):
    """Decode one artifact through its registered codec; ``None`` on any
    miss/verify failure (the caller rebuilds)."""
    c = _codecs.codec(kind)
    if c is None:
        return None
    out = _store.load(kind, key, expect=expect)
    if out is None:
        return None
    meta, arrays = out
    try:
        return c[1](meta, arrays)
    except Exception:
        # decodable bytes that don't reconstruct (codec drift within one
        # format version) are corruption too: quarantine what we read
        _store.quarantine(_store.artifact_path(kind, key), "decode-error",
                          kind)
        return None


def deposit(kind: str, key: str, obj) -> bool:
    """Encode + persist one object through its registered codec;
    best-effort (False on any failure, never raises)."""
    c = _codecs.codec(kind)
    if c is None or not _store.enabled():
        return False
    try:
        meta, arrays = c[0](obj)
    except Exception:
        return False
    return _store.store(kind, key, meta, arrays)


# -- warm-start manifest helpers (SolveSession) -----------------------------
def store_pattern(pattern) -> str:
    """Persist a pattern's raw structure (idempotent); returns its key."""
    key = _codecs.pattern_key(pattern)
    import os

    if not os.path.exists(_store.artifact_path("pattern", key)):
        deposit("pattern", key, pattern)
    return key


def load_pattern(key: str):
    """The manifest replay's pattern loader: a verified
    ``SparsityPattern`` or ``None``."""
    if not key:
        return None
    return fetch("pattern", key)


def note_program(pattern, solver: str, bucket: int, dtype: str,
                 mesh: str | None = None,
                 strategy: str | None = None,
                 precond: str | None = None,
                 dtype_policy: str | None = None,
                 precond_dtype: str | None = None) -> None:
    """Record one freshly built bucket program in the warm-start
    manifest (and ensure its pattern artifact exists). Best-effort.

    ``mesh``/``strategy`` are the fleet tier's topology fingerprint and
    sharding strategy (ISSUE 10): a mesh-keyed entry only replays in a
    process whose serving mesh carries the SAME fingerprint — a restart
    on a different topology skips it (clean cold start) instead of
    compiling a program the new mesh cannot dispatch. ``None`` (the
    default) marks a single-device program, replayable anywhere.

    ``precond`` is the program's resolved preconditioner kind
    (ISSUE 14): recorded so the replay rebuilds the SAME precond-keyed
    program — its pattern-level maps load from their own vault artifact
    kinds, so a warm restart pays zero symbolic factorizations. ``None``
    (the default) marks an unpreconditioned program (pre-precond
    manifests stay valid).

    ``dtype_policy`` is the program's resolved mixed-precision policy
    (ISSUE 15): recorded so the replay rebuilds the SAME
    precision-keyed (``.P``-suffixed) program and a warm restart serves
    the reduced-precision fast path at zero plan-cache misses. ``None``
    (the default) marks an exact program (pre-mixed manifests stay
    valid).

    ``precond_dtype`` is the program's resolved preconditioner storage
    dtype (ISSUE 16): ``'storage'`` marks the compounding arm whose
    factors live at the reduced storage dtype (``.W``-suffixed key);
    ``None`` (the default) marks compute-dtype factors (pre-autopilot
    manifests stay valid)."""
    if not _store.enabled():
        return
    try:
        key = store_pattern(pattern)
        entry = {
            "pattern": key,
            "solver": solver,
            "bucket": int(bucket),
            "dtype": dtype,
            "n": int(pattern.shape[0]),
            "nnz": int(pattern.nnz),
        }
        if mesh:
            entry["mesh"] = str(mesh)
            entry["strategy"] = str(strategy or "batch")
        if precond:
            entry["precond"] = str(precond)
        if dtype_policy:
            entry["dtype_policy"] = str(dtype_policy)
        if precond_dtype:
            entry["precond_dtype"] = str(precond_dtype)
        _manifest.note(entry)
    except Exception:
        return
