"""Warm-start manifest: the vault's record of hot serving programs.

One JSON file (``<vault>/manifest.json``) listing the
``(pattern-fingerprint, solver, bucket, dtype)`` bucket programs a
``SolveSession`` has built, most-recently-noted last. A fresh process
replays it on session construction (``SolveSession(warm_start=...)``):
each entry's pattern structure loads from its ``pattern`` artifact, the
SELL pack loads from the disk tier, and the bucket program re-builds /
re-compiles ahead of traffic (hitting jax's persistent compilation cache
when ``SPARSE_TPU_COMPILE_CACHE`` is set) — so a killed server comes
back warm instead of paying its whole cold start on the first request.

Same trust model as artifacts: writes are atomic (tmp + fsync +
rename, per-process tmp names) and loads verify before use — a
checksum over the canonical entries JSON plus a format version. A
missing or empty manifest is a clean miss; a corrupt one is quarantined
(``vault.quarantine`` evidence) and replay degrades to nothing — a
fresh process can ALWAYS construct a session, warm or cold. Entries are
bounded (:data:`MANIFEST_KEEP`, LRU by note order); noting is
best-effort under concurrency (two servers sharing a vault may each
drop the other's freshest note; both files stay valid).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time

from . import _store

MANIFEST_FORMAT = 1
MANIFEST_KEEP = 64

_LOCK = threading.RLock()
_SEQ = itertools.count()


def path() -> str:
    return os.path.join(_store.vault_dir(), "manifest.json")


def _entries_checksum(entries: list) -> str:
    blob = json.dumps(entries, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def _entry_key(e: dict) -> tuple:
    # `mesh` is the fleet tier's topology fingerprint (ISSUE 10): the
    # same (pattern, solver, bucket, dtype) program compiled for a
    # different mesh is a DIFFERENT executable and must dedup separately
    # (absent == single-device, so pre-fleet manifests stay valid).
    # `precond` (ISSUE 14), `dtype_policy` (ISSUE 15) and
    # `precond_dtype` (ISSUE 16) extend the key the same back-compatible
    # way: absent == unpreconditioned / exact / compute-dtype factors,
    # and a precond-, precision- or storage-factor-keyed program dedups
    # apart from its plain sibling.
    return (e.get("pattern"), e.get("solver"), e.get("bucket"),
            e.get("dtype"), e.get("mesh"), e.get("precond"),
            e.get("dtype_policy"), e.get("precond_dtype"))


def entries() -> list:
    """Verified manifest entries, oldest first. Missing/empty file =>
    ``[]`` (a clean miss); invalid content => quarantine + ``[]``."""
    if not _store.enabled():
        return []
    p = path()
    try:
        with open(p, "rb") as f:
            raw = f.read()
    except OSError:
        return []
    if not raw.strip():
        return []  # empty manifest: a miss, not corruption
    try:
        doc = json.loads(raw.decode())
        if not isinstance(doc, dict):
            raise ValueError("manifest not a dict")
        if doc.get("format") != MANIFEST_FORMAT:
            raise ValueError("stale manifest format")
        ents = doc.get("entries")
        if not isinstance(ents, list):
            raise ValueError("entries not a list")
        if doc.get("sha256") != _entries_checksum(ents):
            raise ValueError("manifest checksum mismatch")
    except Exception:
        _store.quarantine(p, "manifest", "manifest")
        return []
    return [e for e in ents if isinstance(e, dict)]


def _write(ents: list) -> bool:
    import jax

    doc = {
        "format": MANIFEST_FORMAT,
        "jax": jax.__version__,
        "updated": time.time(),
        "entries": ents,
        "sha256": _entries_checksum(ents),
    }
    blob = json.dumps(doc, sort_keys=True, indent=1).encode() + b"\n"
    p = path()
    tmp = None
    try:
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with _LOCK:
            seq = next(_SEQ)
        tmp = f"{p}.{os.getpid()}.{seq}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
        return True
    except Exception:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def note(entry: dict) -> bool:
    """Upsert one program entry (dedup on pattern/solver/bucket/dtype,
    moved to the fresh end; bounded to :data:`MANIFEST_KEEP`). Atomic
    rewrite; best-effort — a failed note never raises."""
    if not _store.enabled():
        return False
    with _LOCK:
        ents = [e for e in entries() if _entry_key(e) != _entry_key(entry)]
        ents.append(dict(entry, noted=time.time()))
        return _write(ents[-MANIFEST_KEEP:])


def clear() -> None:
    try:
        os.unlink(path())
    except OSError:
        pass
