"""Artifact codecs: prepared-operator objects <-> (meta, arrays).

Each codec maps one plan-cache-able object to a numpy-array payload plus
a small JSON meta dict (plan geometry, dtype), and back. Decodes mirror
the build sites they replace: arrays re-enter as jnp arrays committed to
the execution device (``utils.commit_to_exec_device``), so a disk hit
hands the caller exactly what a fresh pack would have — same types, same
residency — without the host-side pack.

Keys are CONTENT fingerprints (sha256 over the exact buffers plus every
setting the pack depends on), computed lazily only when the vault is
enabled. Two operators with equal content share one artifact; any
content or settings change is a different key, so the disk tier can
never serve a stale layout — the in-process tier's weak-ref identity
semantics are unaffected.

Registered kinds:

* ``pattern``       — raw ``SparsityPattern`` structure (indptr/indices/
                      shape): what the warm-start manifest replays.
* ``sell_pattern``  — a pattern's ``_SellPatternPack`` (plan, idx slabs,
                      pos, per-slab nnz source maps).
* ``prepared_csr``  — a full ``PreparedCSR`` (plan, idx+val slabs, pos).
* ``prepared_dia``  — a ``PreparedDia`` (DiaPlan geometry incl. the
                      autotuned row tile, packed plane buffer) — the
                      tile choice persists across sessions, so a warm
                      restart also skips the autotune probe.
* ``precond_diag`` / ``precond_block`` / ``ilu_symbolic`` — the
                      pattern-level preconditioner maps and symbolic
                      factorizations (``sparse_tpu.precond``, ISSUE 14):
                      structure-only, one artifact per (pattern, knobs),
                      so warm restarts skip every symbolic build.
* ``autopilot_policy`` — a converged autopilot :class:`PolicyDecision`
                      (``sparse_tpu.autopilot``, ISSUE 16): pure-meta
                      (no arrays), keyed by (pattern fingerprint,
                      solver, bucket, dtype, SLO class, mesh
                      fingerprint, candidate-grid fingerprint), so a
                      restart serves the tuned policy from the first
                      request instead of re-exploring.
* ``ingest_fpindex`` — the ingest dedup index
                      (``sparse_tpu.ingest.fingerprint``, ISSUE 18):
                      pure-meta ``structure key -> pattern key`` map
                      under the single well-known key ``fpindex``, so a
                      fresh process recognizes a re-arriving matrix
                      structure before ever holding it in memory.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..config import settings

_CODECS: dict = {}


def register(kind: str, encode, decode) -> None:
    _CODECS[kind] = (encode, decode)


def codec(kind: str):
    return _CODECS.get(kind)


def digest(*parts) -> str:
    """Content fingerprint over arrays (dtype+shape+bytes) and scalars."""
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, np.ndarray):
            h.update(str(p.dtype).encode())
            h.update(str(p.shape).encode())
            h.update(np.ascontiguousarray(p).tobytes())
        else:
            h.update(str(p).encode())
        h.update(b"|")
    return h.hexdigest()[:40]


def _sell_settings() -> tuple:
    return (
        "C", settings.sell_chunk, "sigma", settings.sell_sigma,
        "slabs", settings.sell_max_slabs,
    )


# -- keys -------------------------------------------------------------------
def pattern_key(pattern) -> str:
    """Structure-only key (``SparsityPattern.fingerprint`` already hashes
    shape+indptr+indices)."""
    return "p" + pattern.fingerprint[2][:39]


def sell_pattern_key(pattern) -> str:
    return digest("sellpat", pattern.fingerprint[2], *_sell_settings())


def prepared_csr_key(indptr, indices, data, shape) -> str:
    return digest(
        "prepcsr", np.asarray(indptr), np.asarray(indices),
        np.asarray(data), int(shape[0]), int(shape[1]), *_sell_settings(),
    )


def prepared_dia_key(data, offsets, shape) -> str:
    return digest(
        "prepdia", np.asarray(data),
        tuple(int(o) for o in offsets),
        int(shape[0]), int(shape[1]),
    )


# -- SellPlan / DiaPlan meta ------------------------------------------------
def _sell_plan_meta(plan) -> dict:
    return {
        "m": plan.m, "n": plan.n, "C": plan.C, "sigma": plan.sigma,
        "slab_meta": [list(t) for t in plan.slab_meta],
        "zero_rows": plan.zero_rows, "nnz": plan.nnz,
    }


def _sell_plan_from_meta(meta: dict):
    from ..kernels.sell_spmv import SellPlan

    return SellPlan(
        int(meta["m"]), int(meta["n"]), int(meta["C"]), int(meta["sigma"]),
        [tuple(t) for t in meta["slab_meta"]],
        int(meta["zero_rows"]), int(meta["nnz"]),
    )


def _commit(arrays):
    import jax.numpy as jnp

    from ..utils import commit_to_exec_device, host_scope

    with host_scope():
        out = tuple(jnp.asarray(a) for a in arrays)
    return commit_to_exec_device(out)


# -- pattern (raw structure) ------------------------------------------------
def _enc_pattern(pattern):
    meta = {"shape": [pattern.shape[0], pattern.shape[1]],
            "dtype": "structure", "nnz": pattern.nnz}
    return meta, {"indptr": pattern.indptr, "indices": pattern.indices}


def _dec_pattern(meta, arrays):
    from ..batch.operator import SparsityPattern

    return SparsityPattern(
        arrays["indptr"], arrays["indices"], tuple(meta["shape"])
    )


# -- sell_pattern (_SellPatternPack) ----------------------------------------
def _enc_sell_pattern(pack):
    meta = {"plan": _sell_plan_meta(pack.plan), "dtype": "structure",
            "nslabs": len(pack.idx_slabs), "nsrcs": len(pack.srcs)}
    arrays = {"pos": np.asarray(pack.pos)}
    for i, it in enumerate(pack.idx_slabs):
        arrays[f"idx{i}"] = np.asarray(it)
    for i, s in enumerate(pack.srcs):
        arrays[f"src{i}"] = np.asarray(s)
    return meta, arrays


def _dec_sell_pattern(meta, arrays):
    from ..batch.operator import _SellPatternPack

    plan = _sell_plan_from_meta(meta["plan"])
    ns = int(meta["nslabs"])
    idx_slabs = _commit([arrays[f"idx{i}"] for i in range(ns)])
    srcs = _commit([arrays[f"src{i}"] for i in range(int(meta["nsrcs"]))])
    (pos,) = _commit([arrays["pos"]])
    return _SellPatternPack(plan, idx_slabs, pos, srcs)


# -- prepared_csr (PreparedCSR) ---------------------------------------------
def _enc_prepared_csr(prep):
    vdt = str(prep.slabs[0][1].dtype) if prep.slabs else "none"
    meta = {"plan": _sell_plan_meta(prep.plan), "dtype": vdt,
            "nslabs": len(prep.slabs)}
    arrays = {"pos": np.asarray(prep.pos)}
    for i, (it, vt) in enumerate(prep.slabs):
        arrays[f"idx{i}"] = np.asarray(it)
        arrays[f"val{i}"] = np.asarray(vt)
    return meta, arrays


def _dec_prepared_csr(meta, arrays):
    from ..kernels.sell_spmv import PreparedCSR

    plan = _sell_plan_from_meta(meta["plan"])
    slabs = []
    for i in range(int(meta["nslabs"])):
        slabs.append(_commit([arrays[f"idx{i}"], arrays[f"val{i}"]]))
    (pos,) = _commit([arrays["pos"]])
    return PreparedCSR.from_parts(plan, tuple(slabs), pos)


# -- prepared_dia (PreparedDia) ---------------------------------------------
def _enc_prepared_dia(prep):
    p = prep.plan
    meta = {
        "plan": {"offsets": list(p.offsets), "m": p.m, "n": p.n,
                 "TM": p.TM, "B": p.B, "G": p.G},
        "dtype": str(prep.planes.dtype),
    }
    return meta, {"planes": np.asarray(prep.planes)}


def _dec_prepared_dia(meta, arrays):
    from ..kernels.dia_spmv import DiaPlan, PreparedDia

    pm = meta["plan"]
    plan = DiaPlan(
        tuple(int(o) for o in pm["offsets"]), int(pm["m"]), int(pm["n"]),
        int(pm["TM"]), int(pm["B"]), int(pm["G"]),
    )
    (planes,) = _commit([arrays["planes"]])
    return PreparedDia.from_parts(plan, planes)


# -- precond maps (sparse_tpu.precond, ISSUE 14) ----------------------------
# Pattern-level preconditioner artifacts: the diagonal position map
# (point Jacobi), the block extraction map (block Jacobi) and the
# ILU(0)/IC(0) symbolic dependency closure. All structure-only (keyed on
# the pattern fingerprint plus the variant/block knobs), so one artifact
# serves every value stack and dtype over the pattern.
def _enc_precond_diag(pack):
    dpos, has = pack
    return {"dtype": "structure"}, {
        "dpos": np.asarray(dpos), "has": np.asarray(has),
    }


def _dec_precond_diag(meta, arrays):
    return _commit([arrays["dpos"], arrays["has"]])


def _enc_precond_block(pack):
    src, fix = pack
    return {"dtype": "structure"}, {
        "src": np.asarray(src), "fix": np.asarray(fix),
    }


def _dec_precond_block(meta, arrays):
    return _commit([arrays["src"], arrays["fix"]])


_ILU_FIELDS = ("dep_a", "dep_b", "dep_mask", "udiag", "udiag_ok", "lower",
               "isdiag", "upper", "tpos", "dpos", "has_diag")


def _enc_ilu_symbolic(sym):
    meta = {"variant": sym.variant, "symmetric": bool(sym.symmetric),
            "dtype": "structure"}
    return meta, {f: np.asarray(getattr(sym, f)) for f in _ILU_FIELDS}


def _dec_ilu_symbolic(meta, arrays):
    from ..precond.ilu import IluSymbolic

    committed = _commit([arrays[f] for f in _ILU_FIELDS])
    return IluSymbolic(
        str(meta["variant"]), *committed, bool(meta["symmetric"])
    )


def _enc_autopilot_policy(obj):
    return dict(obj), {}


def _dec_autopilot_policy(meta, arrays):
    return dict(meta)


def _enc_ingest_fpindex(obj):
    return {str(k): str(v) for k, v in dict(obj).items()}, {}


def _dec_ingest_fpindex(meta, arrays):
    return {str(k): str(v) for k, v in dict(meta).items()}


register("pattern", _enc_pattern, _dec_pattern)
register("sell_pattern", _enc_sell_pattern, _dec_sell_pattern)
register("prepared_csr", _enc_prepared_csr, _dec_prepared_csr)
register("prepared_dia", _enc_prepared_dia, _dec_prepared_dia)
register("precond_diag", _enc_precond_diag, _dec_precond_diag)
register("precond_block", _enc_precond_block, _dec_precond_block)
register("ilu_symbolic", _enc_ilu_symbolic, _dec_ilu_symbolic)
register("autopilot_policy", _enc_autopilot_policy, _dec_autopilot_policy)
register("ingest_fpindex", _enc_ingest_fpindex, _dec_ingest_fpindex)
