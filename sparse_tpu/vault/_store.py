"""On-disk artifact store: atomic writes, verify-then-load, quarantine.

The disk tier of the two-tier plan cache (``sparse_tpu.plan_cache``).
One artifact = one file under ``<vault>/objects/<kind>/<key>.stv``:

    MAGIC  header-JSON  "\\n"  payload (npz bytes)

The header carries the contract every load re-verifies *before* any
payload byte is interpreted: format version, the writing process's jax /
numpy versions, the artifact kind and key, the payload length and its
sha256. A verify failure of ANY step — bad magic, unparseable header,
stale format/jax, key mismatch, truncation, checksum, npz decode, or an
``expect=`` field mismatch — NEVER raises into the caller: the file is
moved into ``<vault>/quarantine/`` (bounded; oldest pruned), counted
(``vault.verify_failed`` / ``vault.quarantined``), optionally recorded
(``vault.quarantine`` event), and the load returns ``None`` — a miss the
caller answers by rebuilding. Worst case is recompute, never a crash or
a wrong artifact.

Writes are crash-safe and concurrency-safe: the blob lands in
``<vault>/tmp/<name>.<pid>.<seq>.tmp`` (per-process names — concurrent
servers sharing a vault never collide), is flushed + fsync'd, then
``os.replace``'d into place (atomic on POSIX; readers see the old file
or the new file, never a torn one). A failed write (``ENOSPC``,
permissions, injected ``io`` faults) cleans up its tmp file, counts
``vault.write_failed``, and the process continues without persistence.

Chaos hooks: the ``io`` fault site (``resilience.faults``, grammar
``truncate:io`` / ``stale:io`` / ``enospc:io`` on the write path and
``bitflip:io`` on the read path) injects exactly the disk failure modes
the verify ladder exists for — docs/resilience.md.
"""

from __future__ import annotations

import errno
import hashlib
import io
import itertools
import json
import os
import threading
import time

import numpy as np

from ..config import settings
from ..telemetry import _metrics

MAGIC = b"STPUVAULT\x01"
#: bump on any incompatible artifact layout change; old files quarantine
FORMAT = 1
SUFFIX = ".stv"
#: max files kept in quarantine/ before the oldest are pruned
QUARANTINE_KEEP = 32

_LOCK = threading.RLock()
_SEQ = itertools.count()

_COUNTERS = {
    "hits": _metrics.counter("vault.hits"),
    "misses": _metrics.counter("vault.misses"),
    "writes": _metrics.counter("vault.writes"),
    "write_failed": _metrics.counter("vault.write_failed"),
    "verify_failed": _metrics.counter("vault.verify_failed"),
    "quarantined": _metrics.counter("vault.quarantined"),
    "evictions": _metrics.counter("vault.evictions"),
    "replayed": _metrics.counter("vault.replayed"),
}
_SIZE_GAUGE = _metrics.gauge("vault.size_bytes")


def _telemetry():
    """The telemetry facade iff events are enabled (lazy import — the
    vault must stay importable before the package facade exists)."""
    if not settings.telemetry:
        return None
    from .. import telemetry

    return telemetry


def enabled() -> bool:
    """True when a persistent tier is configured (``SPARSE_TPU_VAULT``)."""
    return bool(settings.vault)


def vault_dir() -> str:
    return os.path.abspath(settings.vault)


def _objects_dir(kind: str) -> str:
    return os.path.join(vault_dir(), "objects", kind)


def _tmp_dir() -> str:
    return os.path.join(vault_dir(), "tmp")


def quarantine_dir() -> str:
    return os.path.join(vault_dir(), "quarantine")


def artifact_path(kind: str, key: str) -> str:
    return os.path.join(_objects_dir(kind), key + SUFFIX)


# ---------------------------------------------------------------------------
# encode / decode
# ---------------------------------------------------------------------------
def _encode(kind: str, key: str, meta: dict, arrays: dict) -> bytes:
    """Serialize one artifact to its on-disk blob (see module doc)."""
    import jax

    buf = io.BytesIO()
    # deterministic member order so equal artifacts are byte-comparable
    np.savez(buf, **{k: np.asarray(arrays[k]) for k in sorted(arrays)})
    payload = buf.getvalue()
    header = {
        "format": FORMAT,
        "kind": kind,
        "key": key,
        "jax": jax.__version__,
        "numpy": np.__version__,
        "meta": meta,
        "payload_len": len(payload),
        "payload_sha256": hashlib.sha256(payload).hexdigest(),
        "writer_pid": os.getpid(),
        "created": time.time(),
    }
    return MAGIC + json.dumps(header, sort_keys=True).encode() + b"\n" + payload


def _verify(blob: bytes, kind: str, key: str, expect: dict | None):
    """Verify-then-decode one artifact blob.

    Returns ``(meta, arrays)`` on success or a problem string — every
    failure mode gets a distinct reason (the quarantine file name and the
    ``vault.quarantine`` event carry it)."""
    import jax

    if not blob.startswith(MAGIC):
        return "bad-magic"
    try:
        nl = blob.index(b"\n", len(MAGIC))
        header = json.loads(blob[len(MAGIC):nl].decode())
        if not isinstance(header, dict):
            raise ValueError("header not a dict")
    except Exception:
        return "bad-header"
    if header.get("format") != FORMAT:
        return "stale-format"
    if header.get("jax") != jax.__version__:
        # a jax upgrade invalidates traced/packed layouts wholesale
        return "stale-jax"
    if header.get("kind") != kind or header.get("key") != key:
        return "key-mismatch"
    payload = blob[nl + 1:]
    if header.get("payload_len") != len(payload):
        return "truncated"
    if header.get("payload_sha256") != hashlib.sha256(payload).hexdigest():
        return "checksum"
    meta = header.get("meta")
    if not isinstance(meta, dict):
        return "bad-header"
    if expect:
        for k, v in expect.items():
            if meta.get(k) != v:
                return f"expect-{k}"
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception:
        return "decode-error"
    return meta, arrays


# ---------------------------------------------------------------------------
# store / load
# ---------------------------------------------------------------------------
def _io_actions(op: str) -> list:
    from ..resilience import faults

    if not faults.ACTIVE:
        return []
    return faults.io_actions(op)


def store(kind: str, key: str, meta: dict, arrays: dict) -> bool:
    """Atomically persist one artifact; returns True on success.

    Never raises: any failure (real ENOSPC, permissions, injected ``io``
    faults) counts ``vault.write_failed`` and leaves the vault exactly as
    it was (the tmp file is removed; the previous artifact version, if
    any, stays in place)."""
    if not enabled():
        return False
    tmp = None
    try:
        blob = _encode(kind, key, meta, arrays)
        for act in _io_actions("write"):
            if act[0] == "enospc":
                raise OSError(errno.ENOSPC, "injected ENOSPC (io fault)")
            if act[0] == "truncate":
                # models a torn write that survived on disk: the verify
                # ladder must catch it on the next load
                blob = blob[: max(len(blob) // 2, len(MAGIC) + 1)]
            if act[0] == "stale":
                # models an artifact left behind by an older build
                head, _, payload = blob.partition(b"\n")
                hdr = json.loads(head[len(MAGIC):].decode())
                hdr["format"] = FORMAT - 1
                blob = (
                    MAGIC + json.dumps(hdr, sort_keys=True).encode()
                    + b"\n" + payload
                )
        final = artifact_path(kind, key)
        os.makedirs(os.path.dirname(final), exist_ok=True)
        os.makedirs(_tmp_dir(), exist_ok=True)
        with _LOCK:
            seq = next(_SEQ)
        tmp = os.path.join(
            _tmp_dir(),
            f"{key}{SUFFIX}.{os.getpid()}.{seq}.tmp",
        )
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        tmp = None
        _fsync_dir(os.path.dirname(final))
    except Exception as e:
        _COUNTERS["write_failed"].inc()
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        tel = _telemetry()
        if tel is not None:
            tel.record(
                "vault.store", artifact=kind, key=key, ok=False,
                bytes=0, error=repr(e)[:200],
            )
        return False
    _COUNTERS["writes"].inc()
    tel = _telemetry()
    if tel is not None:
        tel.record(
            "vault.store", artifact=kind, key=key, ok=True, bytes=len(blob)
        )
    gc()  # size-budgeted LRU sweep; no-op while under the cap
    return True


def load(kind: str, key: str, expect: dict | None = None):
    """Verify-then-load one artifact; ``(meta, arrays)`` or ``None``.

    A missing file is a plain miss. An unreadable or invalid file is a
    miss PLUS a quarantine — the bad bytes are moved aside so they can
    never be re-read, and the caller's rebuild re-deposits a good copy."""
    if not enabled():
        return None
    path = artifact_path(kind, key)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        _COUNTERS["misses"].inc()
        return None
    for act in _io_actions("read"):
        if act[0] == "bitflip" and blob:
            idx = min(int(act[1] * len(blob)), len(blob) - 1)
            b = bytearray(blob)
            b[idx] ^= 0x40
            blob = bytes(b)
    out = _verify(blob, kind, key, expect)
    if isinstance(out, str):
        _COUNTERS["misses"].inc()
        quarantine(path, out, kind)
        return None
    _COUNTERS["hits"].inc()
    try:
        os.utime(path, None)  # LRU touch for the mtime-ordered GC sweep
    except OSError:
        pass
    tel = _telemetry()
    if tel is not None:
        tel.record("vault.load", artifact=kind, key=key, hit=True)
    return out


def quarantine(path: str, reason: str, kind: str = "?") -> None:
    """Move a failed-verification file into the quarantine sidecar dir
    (named ``<basename>.<reason>.<pid>.<seq>``), bounded to
    ``QUARANTINE_KEEP`` files. Best-effort: a racing reader may have
    quarantined it first."""
    _COUNTERS["verify_failed"].inc()
    _metrics.counter("vault.verify_failed.by_reason", reason=reason).inc()
    qdir = quarantine_dir()
    try:
        os.makedirs(qdir, exist_ok=True)
        with _LOCK:
            seq = next(_SEQ)
        dest = os.path.join(
            qdir,
            f"{os.path.basename(path)}.{reason}.{os.getpid()}.{seq}",
        )
        os.replace(path, dest)
        _COUNTERS["quarantined"].inc()
    except OSError:
        return  # already moved/removed by a concurrent process
    tel = _telemetry()
    if tel is not None:
        tel.record("vault.quarantine", artifact=kind, reason=reason,
                   path=os.path.basename(dest))
    # bound the sidecar: quarantined files are debugging evidence, not an
    # unbounded archive
    try:
        entries = sorted(
            (e for e in os.scandir(qdir) if e.is_file()),
            key=lambda e: e.stat().st_mtime,
        )
        for e in entries[:-QUARANTINE_KEEP]:
            os.unlink(e.path)
    except OSError:
        pass


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync so the rename itself is durable."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# GC
# ---------------------------------------------------------------------------
def _artifacts():
    """Every artifact file as ``(path, size, mtime)``."""
    root = os.path.join(vault_dir(), "objects")
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            if not name.endswith(SUFFIX):
                continue
            p = os.path.join(dirpath, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((p, st.st_size, st.st_mtime))
    return out


def gc(cap_mb: float | None = None, dry_run: bool = False) -> int:
    """Size-budgeted LRU sweep: evict oldest-mtime artifacts until the
    vault fits ``cap_mb`` (default ``settings.vault_cap_mb``; loads
    touch mtime, so recently-used artifacts survive). Returns the number
    of evicted files; stale tmp files (> 1 h — a crashed writer's
    leftovers) are always pruned."""
    if not enabled():
        return 0
    cap = float(settings.vault_cap_mb if cap_mb is None else cap_mb)
    try:
        now = time.time()
        for e in os.scandir(_tmp_dir()):
            if e.is_file() and now - e.stat().st_mtime > 3600:
                os.unlink(e.path)
    except OSError:
        pass
    files = _artifacts()
    total = sum(s for _, s, _ in files)
    _SIZE_GAUGE.set(total)
    if total <= cap * 2**20:
        return 0
    evicted = 0
    for path, size, _mt in sorted(files, key=lambda t: t[2]):
        if total <= cap * 2**20:
            break
        if not dry_run:
            try:
                os.unlink(path)
            except OSError:
                continue
        total -= size
        evicted += 1
        _COUNTERS["evictions"].inc()
    _SIZE_GAUGE.set(max(total, 0))
    tel = _telemetry()
    if evicted and tel is not None:
        tel.record("vault.gc", evicted=evicted, bytes=int(total),
                   cap_mb=cap, dry_run=bool(dry_run))
    return evicted


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------
def stats() -> dict:
    """Always-on vault counters (the same numbers a Prometheus scrape of
    ``telemetry.metrics_text()`` sees as ``sparse_tpu_vault_*``)."""
    out = {k: int(c.value) for k, c in _COUNTERS.items()}
    out["enabled"] = enabled()
    out["size_bytes"] = int(_SIZE_GAUGE.value)
    return out


def reset_stats() -> None:
    for c in _COUNTERS.values():
        c.reset()
    _SIZE_GAUGE.reset()
