"""LinearOperator framework + iterative solvers.

Reference analog: ``sparse/linalg.py`` (1569 LoC) — LinearOperator protocol with
out= params (linalg.py:128-459), cg linalg.py:499 with the fused AXPBY task
(linalg.py:479-496), cgs :570, bicg :620, gmres :670, bicgstab :796, lsqr :937,
eigsh (Lanczos) :1450, spsolve(=CG) :88.

TPU-first redesign: the reference keeps its Python solver loops asynchronous via
Legion futures and blocks once every ``conv_test_iters`` iterations. On TPU the
same effect is achieved more strongly: the entire solver loop is a
``lax.while_loop`` compiled into one XLA program — scalars (rho, alpha, |r|)
live on device, the convergence test costs one compare, and the host syncs
exactly once, at the end. The fused AXPBY task is subsumed by XLA fusion.
When a Python ``callback`` is requested we fall back to a host-driven loop with
the reference's periodic-sync behavior.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry
from .base import SparseArray
from .coverage import track_provenance
from .resilience import faults as _faults
from .utils import asjnp, host_int
from ._direct import (  # noqa: F401  (re-exported scipy.sparse.linalg surface)
    SpILU,
    SuperLU,
    expm,
    factorized,
    ic0,
    ilu0,
    inv,
    is_sptriangular,
    spbandwidth,
    spilu,
    splu,
    spsolve_triangular,
)
from ._eigen import (  # noqa: F401
    ArpackError,
    ArpackNoConvergence,
    eigs,
    funm_multiply_krylov,
    lobpcg,
)


class MatrixRankWarning(UserWarning):
    """scipy.sparse.linalg.MatrixRankWarning alias."""


def use_solver(**kwargs):
    """scipy API no-op: there is no UMFPACK toggle here — the direct path
    is always the device dense LU (see ``splu``)."""


# ---------------------------------------------------------------------------
# LinearOperator protocol (linalg.py:128-459)
# ---------------------------------------------------------------------------
class LinearOperator:
    def __init__(self, shape, matvec=None, rmatvec=None, matmat=None, dtype=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype) if dtype is not None else np.dtype(np.float32)
        self._matvec_impl = matvec
        self._rmatvec_impl = rmatvec
        self._matmat_impl = matmat

    def matvec(self, x, out=None):
        """out= is advisory (jax arrays are immutable); kept for API parity."""
        if self._matvec_impl is None:
            raise NotImplementedError
        return self._matvec_impl(x)

    def rmatvec(self, x, out=None):
        if self._rmatvec_impl is None:
            raise NotImplementedError
        return self._rmatvec_impl(x)

    def matmat(self, X, out=None):
        if self._matmat_impl is not None:
            return self._matmat_impl(X)
        cols = [self.matvec(X[:, i]) for i in range(X.shape[1])]
        return jnp.stack(cols, axis=1)

    def __matmul__(self, x):
        if isinstance(x, LinearOperator):
            return _ProductOperator(self, x)
        x = asjnp(x)
        if x.ndim == 0:
            raise ValueError(
                "Scalar operands are not allowed, use '*' instead"
            )
        if x.ndim == 1:
            return self.matvec(x)
        return self.matmat(x)

    # -- operator algebra (scipy's _SumLinearOperator family) -------------
    def __add__(self, other):
        if isinstance(other, LinearOperator):
            return _SumOperator(self, other)
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, LinearOperator):
            return _SumOperator(self, _ScaledOperator(other, -1.0))
        return NotImplemented

    def __mul__(self, x):
        # scipy semantics: operator -> composition, scalar -> scaling,
        # array -> application (A * v == A.matvec(v))
        if isinstance(x, LinearOperator):
            return _ProductOperator(self, x)
        if np.isscalar(x) or getattr(x, "ndim", 1) == 0:
            return _ScaledOperator(self, x)
        x = asjnp(x)
        return self.matvec(x) if x.ndim == 1 else self.matmat(x)

    def __rmul__(self, alpha):
        if np.isscalar(alpha) or getattr(alpha, "ndim", 1) == 0:
            return _ScaledOperator(self, alpha)
        return NotImplemented

    def dot(self, x):
        """scipy LinearOperator.dot: scalar scales, operator composes,
        vector/matrix applies."""
        if isinstance(x, LinearOperator):
            return _ProductOperator(self, x)
        if np.isscalar(x) or getattr(x, "ndim", 1) == 0:
            return _ScaledOperator(self, x)
        x = asjnp(x)
        return self.matvec(x) if x.ndim == 1 else self.matmat(x)

    def __neg__(self):
        return _ScaledOperator(self, -1.0)

    def __pow__(self, p):
        if not isinstance(p, (int, np.integer)) or p < 0:
            raise ValueError("operator power requires a non-negative int")
        if self.shape[0] != self.shape[1]:
            raise ValueError("operator power requires a square operator")
        if p == 0:
            return IdentityOperator(self.shape, dtype=self.dtype)
        return _PowerOperator(self, int(p))  # flat loop, O(1) stack

    @property
    def H(self):
        """Adjoint (conjugate transpose): matvec = this operator's rmatvec."""
        return LinearOperator(
            (self.shape[1], self.shape[0]),
            matvec=self.rmatvec,  # bound methods: works for subclasses that
            rmatvec=self.matvec,  # override matvec/rmatvec directly
            dtype=self.dtype,
        )

    @property
    def T(self):
        """Transpose. For complex operators: conj . rmatvec . conj."""
        if np.issubdtype(self.dtype, np.complexfloating):
            return LinearOperator(
                (self.shape[1], self.shape[0]),
                matvec=lambda x: jnp.conj(self.rmatvec(jnp.conj(x))),
                rmatvec=lambda x: jnp.conj(self.matvec(jnp.conj(x))),
                dtype=self.dtype,
            )
        return self.H


class IdentityOperator(LinearOperator):
    def __init__(self, shape, dtype=None):
        super().__init__(shape, dtype=dtype)

    def matvec(self, x, out=None):
        return x

    def rmatvec(self, x, out=None):
        return x


class _SumOperator(LinearOperator):
    def __init__(self, a, b):
        if a.shape != b.shape:
            raise ValueError(f"operator shape mismatch: {a.shape} + {b.shape}")
        super().__init__(a.shape, dtype=np.result_type(a.dtype, b.dtype))
        self._a, self._b = a, b

    def matvec(self, x, out=None):
        return self._a.matvec(x) + self._b.matvec(x)

    def rmatvec(self, x, out=None):
        return self._a.rmatvec(x) + self._b.rmatvec(x)

    def matmat(self, X, out=None):
        return self._a.matmat(X) + self._b.matmat(X)


class _ScaledOperator(LinearOperator):
    def __init__(self, a, alpha):
        super().__init__(
            a.shape, dtype=np.result_type(a.dtype, np.asarray(alpha).dtype)
        )
        self._a, self._alpha = a, alpha

    def matvec(self, x, out=None):
        return self._alpha * self._a.matvec(x)

    def rmatvec(self, x, out=None):
        return np.conj(self._alpha) * self._a.rmatvec(x)

    def matmat(self, X, out=None):
        return self._alpha * self._a.matmat(X)


class _ProductOperator(LinearOperator):
    def __init__(self, a, b):
        if a.shape[1] != b.shape[0]:
            raise ValueError(f"operator shape mismatch: {a.shape} @ {b.shape}")
        super().__init__(
            (a.shape[0], b.shape[1]), dtype=np.result_type(a.dtype, b.dtype)
        )
        self._a, self._b = a, b

    def matvec(self, x, out=None):
        return self._a.matvec(self._b.matvec(x))

    def rmatvec(self, x, out=None):
        return self._b.rmatvec(self._a.rmatvec(x))

    def matmat(self, X, out=None):
        return self._a.matmat(self._b.matmat(X))


class _PowerOperator(LinearOperator):
    """A ** p via a flat application loop (scipy's _PowerLinearOperator;
    nesting _ProductOperator p-deep would recurse O(p) frames)."""

    def __init__(self, a, p):
        super().__init__(a.shape, dtype=a.dtype)
        self._a, self._p = a, p

    def matvec(self, x, out=None):
        for _ in range(self._p):
            x = self._a.matvec(x)
        return x

    def rmatvec(self, x, out=None):
        for _ in range(self._p):
            x = self._a.rmatvec(x)
        return x

    def matmat(self, X, out=None):
        for _ in range(self._p):
            X = self._a.matmat(X)
        return X


class _SparseMatrixLinearOperator(LinearOperator):
    def __init__(self, A):
        super().__init__(A.shape, dtype=A.dtype)
        self.A = A
        # Prepare/execute split: warm the operator's layout plan (DIA/ELL
        # detection, SELL pack — all plan-cached) eagerly at wrap time, so
        # solvers whose first matvec happens inside a compiled loop still
        # run the whole solve on the prepared path. Advisory: any failure
        # leaves per-matvec dispatch to its own fallbacks. A warm that
        # actually BUILT a plan (plan-cache miss moved) is attributed as
        # cold-start cost (telemetry._cost), so `axon_report`'s compile
        # budget covers eager-path prepares, not just bucket programs.
        prepare = getattr(A, "prepare", None)
        if prepare is not None:
            from . import plan_cache
            from .telemetry import _cost

            snap = plan_cache.snapshot()
            t0 = time.perf_counter()
            try:
                prepare()
            except Exception:  # pragma: no cover - backend-dependent
                pass
            if plan_cache.delta(snap).get("misses"):
                _cost.record_pack(
                    f"prepare.{type(A).__name__}.{np.dtype(A.dtype).str}"
                    f".n{A.shape[0]}",
                    time.perf_counter() - t0,
                    n=int(A.shape[0]),
                    nnz=int(getattr(A, "nnz", 0)),
                    dtype=np.dtype(A.dtype).str,
                )

    def matvec(self, x, out=None):
        return self.A.dot(x)

    def rmatvec(self, x, out=None):
        # rmatvec is the ADJOINT (A^H x), matching scipy's protocol and the
        # dense operator; conjugate x instead of the matrix data (O(n), and
        # A.T stays the zero-copy CSC reinterpretation)
        if np.issubdtype(self.dtype, np.complexfloating):
            return jnp.conj(self.A.T.dot(jnp.conj(x)))
        return self.A.T.dot(x)

    def matmat(self, X, out=None):
        return self.A.dot(X)  # one SpMM, not a column loop


class _DenseMatrixLinearOperator(LinearOperator):
    def __init__(self, A):
        A = asjnp(A)
        super().__init__(A.shape, dtype=A.dtype)
        self.A = A

    def matvec(self, x, out=None):
        return self.A @ x

    def rmatvec(self, x, out=None):
        return self.A.T.conj() @ x

    def matmat(self, X, out=None):
        return self.A @ X


class _FaultyOperator(LinearOperator):
    """Fault-injection wrapper (resilience.faults): matvec outputs pass
    through the seeded corruption callback. Only ever constructed when a
    matvec fault clause is active — clean builds never see this class in
    a trace (the zero-code-path-change contract)."""

    _fault_wrapped = True

    def __init__(self, base):
        super().__init__(base.shape, dtype=base.dtype)
        self._base = base

    def matvec(self, x, out=None):
        return _faults.corrupt_traced(self._base.matvec(x))

    def rmatvec(self, x, out=None):
        return self._base.rmatvec(x)

    def matmat(self, X, out=None):
        return self._base.matmat(X)


def _maybe_faulty(op: LinearOperator) -> LinearOperator:
    if getattr(op, "_fault_wrapped", False) or not _faults.targets("matvec"):
        return op
    return _FaultyOperator(op)


def make_linear_operator(A) -> LinearOperator:
    if isinstance(A, LinearOperator):
        return _maybe_faulty(A) if _faults.ACTIVE else A
    if isinstance(A, SparseArray):
        op = _SparseMatrixLinearOperator(A)
    else:
        from .batch.operator import BatchedOperator

        if isinstance(A, BatchedOperator):
            # a batch of B independent systems IS one (B*m, B*n) block-
            # diagonal system: the unbatched solver surface keeps working
            # on batched operators through this view (docs/batching.md)
            op = A.as_block_operator()
        else:
            op = _DenseMatrixLinearOperator(A)
    return _maybe_faulty(op) if _faults.ACTIVE else op


aslinearoperator = make_linear_operator


def cg_axpby(y, x, a, b, isalpha=True, negate=False):
    """y = y + (a/b) x (isalpha) or y (a/b) + x (not isalpha); sign optional.

    Reference: the fused AXPBY task (linalg.py:479-496). Under jit XLA fuses
    this into a single elementwise kernel with the division broadcast — the
    task exists here only for API parity.
    """
    s = a / b
    if negate:
        s = -s
    return y + s * x if isalpha else y * s + x


def _vdot(a, b):
    """Inner product with the first argument conjugated (scipy's
    ``dotprod = np.vdot`` choice for its Krylov solvers): for hermitian
    systems the conjugated form is what makes complex CG/CGS/BiCG(STAB)
    converge; for real dtypes it is plain dot."""
    return jnp.vdot(a, b)


# -- telemetry plumbing ------------------------------------------------------
# Per-iteration solver events reach the recorder three ways, matching the
# three loop disciplines: host loops record directly (their per-iteration
# dispatch already syncs), compiled lax.while_loop bodies tap out through
# jax.debug.callback (concrete values arrive host-side; the tap — and its
# extra ||r||^2 — exists only when telemetry is enabled, so the disabled
# trace is unchanged), and the fused-CG chunk loop reuses the rho scalar
# it already fetches per conv-test chunk (zero extra syncs).


def _solve_event(
    solver: str, n, iters, path: str, resid2=None, converged=None
) -> None:
    """One ``solver.solve`` event per completed solve (any path); also
    finalizes the health monitor's report for this solve
    (``telemetry.last_solve_report()``)."""
    if not telemetry.enabled():
        return
    fields = {"solver": solver, "n": int(n), "iters": int(iters), "path": path}
    if resid2 is not None:
        fields["resid2"] = float(resid2)
    if converged is not None:
        fields["converged"] = bool(converged)
    telemetry.record("solver.solve", **fields)
    telemetry.health.end_solve(
        solver, iters, resid2=resid2, converged=converged, path=path
    )


def _make_iter_tap(solver: str, path: str = "device"):
    """Host-side tap for jax.debug.callback inside compiled solver loops,
    or None when tapping is off. Taps run on the CPU backend only: host
    callbacks out of device loops are an unproven class through the
    remote-tunnel TPU backend (host/eager traffic is its documented
    wedge trigger), and the TPU-relevant solve paths (fused CG chunks,
    GMRES restart cycles) already report through scalars they fetch
    anyway."""
    if not telemetry.enabled() or jax.default_backend() != "cpu":
        return None

    def tap(i, rn2):
        telemetry.record(
            "solver.iter", solver=solver, path=path,
            iter=int(i), resid2=float(rn2),
        )
        # same concrete scalars feed the health monitor's residual
        # history + NaN/stall/divergence detectors (telemetry/_health.py)
        telemetry.health.observe(solver, int(i), float(rn2), path=path)

    return tap


def _effects_barrier() -> None:
    """Drain pending debug-callback effects so tapped iteration events are
    recorded before the solve returns (best-effort across jax versions)."""
    try:
        jax.effects_barrier()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# CG (linalg.py:499)
# ---------------------------------------------------------------------------
@track_provenance
def cg(
    A,
    b,
    x0=None,
    tol=1e-08,
    maxiter=None,
    M=None,
    callback=None,
    atol=None,
    conv_test_iters=25,
):
    """Conjugate gradient. Returns (x, iters), reference semantics:
    absolute ||r|| < tol tested every conv_test_iters iterations."""
    assert atol is None, "atol is not supported."
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = n * 10
    if M is None and callback is None:
        fused = _try_fused_cg(A, b, x0, tol, maxiter, conv_test_iters)
        if fused is not None:
            x_f, it_f, rho_f, info_f = fused
            # info_f != 0 distinguishes a nonfinite-rho exit (-1) and a
            # maxiter exit (iters) from convergence (0) — the final rho
            # rides the health report so the recovery policy engine sees
            # breakdowns even on paths with no per-iter taps (ISSUE 5)
            _solve_event(
                "cg", n, it_f, "fused", resid2=rho_f, converged=info_f == 0
            )
            return x_f, it_f
    A = make_linear_operator(A)
    M = IdentityOperator(A.shape, dtype=A.dtype) if M is None else make_linear_operator(M)
    x = jnp.zeros_like(b) if x0 is None else asjnp(x0)

    if callback is not None:
        out = _cg_host_loop(A, b, x, tol, maxiter, M, callback, conv_test_iters)
        _solve_event("cg", n, out[1], "host")
        return out

    r = b - A.matvec(x)
    try:
        # warm the preconditioner EAGERLY once: layout detection
        # (_maybe_dia/_maybe_ell) host-syncs on first use and is skipped
        # inside a trace, so an M first applied inside the compiled loop
        # (multigrid R/P operators) would silently run on its slowest
        # kernel path for the whole solve
        if not isinstance(M, IdentityOperator):
            M.matvec(r)
        out = _cg_device_loop(A, b, x, r, tol, maxiter, M, conv_test_iters)
        _solve_event("cg", n, out[1], "device")
        return out
    except (
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
        jax.errors.ConcretizationTypeError,
    ):
        # A or M is a host-side Python operator (e.g. a numpy-based
        # preconditioner): run the reference-style host loop instead
        out = _cg_host_loop(A, b, x, tol, maxiter, M, None, conv_test_iters)
        _solve_event("cg", n, out[1], "host")
        return out


def _try_fused_cg(A, b, x0, tol, maxiter, conv_test_iters):
    """Fused-iteration fast path for unpreconditioned CG on banded f32
    operators (the PDE/GMG shape): runs ``kernels.cg_dia.cg_dia_fused``
    in conv-test-sized chunks with one host rho fetch per chunk — the
    same iterates and stopping rule as ``_cg_device_loop`` (absolute
    ||r|| < tol every conv_test_iters), at ~2x the step-loop throughput
    on real TPUs (BENCH_NOTES.md). Returns ``(x, iters, rho_f, info)`` —
    ``info`` 0 = converged, -1 = nonfinite rho (breakdown/corruption; NOT
    the same exit as convergence — ISSUE 5 satellite), iters = maxiter
    exhausted — or None when the path doesn't apply.
    """
    import jax

    from .config import settings

    mode = settings.fused_cg
    if not mode:
        return None
    if _faults.ACTIVE and _faults.targets("matvec"):
        # matvec corruption injects through the operator wrapper, which
        # the fused kernel bypasses — take the standard loop so the
        # chaos spec actually applies to this solve
        return None
    interpret = False
    if jax.default_backend() != "tpu":
        if mode != "force":  # tests: run the chunk logic in interpret mode
            return None
        interpret = True
    from .csr import csr_array
    from .dia import dia_array

    planes = offsets = None
    if isinstance(A, dia_array):
        planes, offsets = A.data, tuple(int(o) for o in A.offsets)
    elif isinstance(A, csr_array):
        dia = A._maybe_dia()  # cached banded auto-detection
        if dia is not None:
            planes, offsets = dia
    if planes is None:
        return None
    m, n_ = A.shape
    if m != n_ or b.ndim != 1 or b.shape[0] != m or maxiter < 1:
        return None
    band = max((abs(int(o)) for o in offsets), default=0)
    if band > settings.pallas_max_band:
        return None
    dt = jnp.result_type(planes.dtype, b.dtype)
    if dt != jnp.float32:  # Mosaic kernel is f32; f64/complex take the loop
        return None
    if x0 is not None:
        x0 = asjnp(x0)

    from .kernels.cg_dia import cg_dia_fused

    # RESIDENCY: the planes are jit ARGUMENTS of the fused kernel, so a
    # host-resident layout (matrices built in a CPU-scoped construction
    # phase) would re-transfer the whole matrix through the accelerator
    # link on EVERY chunk (~720 MB at 6000^2 — measured as a 10x
    # slowdown through the tunnel). Commit once; cache back on the csr
    # so later solves skip even that. device_put is a no-op when the
    # array is already resident.
    dev = jax.devices()[0]
    if dev.platform != "cpu":
        planes = jax.device_put(planes, dev)
        if getattr(A, "_dia", None):
            A._dia = (planes, offsets)
        elif isinstance(A, dia_array):
            A.data = planes  # dia storage IS the planes: commit in place
        b = jax.device_put(b, dev)
        if x0 is not None:
            x0 = jax.device_put(x0, dev)

    # Known-best tile from the hardware sweeps (settings.fused_cg_tile,
    # 65536), clamped so the kernel's VMEM plane scratch (2 * D double-
    # buffered [TM] streams + ~10 vector buffers) stays ~<= 6 MB — a
    # 32-diagonal operator at 65536 would need 17+ MB and fail Mosaic
    # compilation outright, and cg() has no fallback past this gate.
    D = len(offsets)
    tile = max(16384, min(int(settings.fused_cg_tile),
                          (6 << 20) // (max(2 * D + 10, 1) * 4)))

    tol2 = float(tol) ** 2
    chunk = max(int(conv_test_iters), 1)
    state = None
    iters = 0
    x = None
    rho_f = None
    while iters < maxiter:
        if _faults.ACTIVE:
            # chunk boundaries are the preemption points this loop
            # survives (the carry state is host-visible here)
            _faults.check_preempt("cg.fused.chunk")
        # mirror _cg_device_loop's test points exactly: every conv_test
        # iterations AND at iters == maxiter - 1 (so a solve converging at
        # the last test reports maxiter-1, not maxiter). The off-size last
        # chunks add at most two extra trace shapes, only for solves that
        # actually reach maxiter.
        k = min(chunk, max(maxiter - 1 - iters, 1))
        k = min(k, maxiter - iters)
        x, _r, rho, state = cg_dia_fused(
            planes, offsets, b, x0, m, iters=k, tile=tile,
            state=state, return_state=True, interpret=interpret,
        )
        iters += k
        rho_f = float(rho)
        if telemetry.enabled():
            # one event per conv-test chunk, reusing the rho scalar this
            # loop already fetches — per-chunk granularity, zero extra
            # syncs on the fused fast path
            telemetry.record(
                "solver.iter", solver="cg", path="fused", iter=iters,
                resid2=rho_f, chunk=k,
            )
            telemetry.health.observe("cg", iters, rho_f, path="fused")
        if not np.isfinite(rho_f):
            # a nonfinite rho is a BREAKDOWN exit, not convergence: flag
            # it so callers (and the recovery policy via the health
            # report) can tell the two apart (ISSUE 5 satellite)
            return x, iters, rho_f, -1
        if rho_f < tol2:
            return x, iters, rho_f, 0
    info = 0 if (rho_f is not None and rho_f < tol2) else iters
    return x, iters, rho_f, info


def _cg_device_loop(A, b, x, r, tol, maxiter, M, conv_test_iters):
    """Whole-solve lax.while_loop: scalars stay on device, one final sync.

    With telemetry enabled, each iteration taps (iter, ||r||^2) out to the
    recorder through ``jax.debug.callback`` — the loop stays one compiled
    program; the extra reduction exists only in the instrumented trace.
    """
    tol2 = jnp.asarray(tol, dtype=jnp.real(r).dtype) ** 2
    tap = _make_iter_tap("cg")

    def body(state):
        x, r, p, rho, iters = state
        z = M.matvec(r)
        rho1 = rho
        rho_new = _vdot(r, z)
        p = jnp.where(iters == 0, z, z + (rho_new / jnp.where(rho1 == 0, 1, rho1)) * p)
        q = A.matvec(p)
        pq = _vdot(p, q)
        alpha = rho_new / jnp.where(pq == 0, 1, pq)  # 0/0 guard: b=0 or exact x0
        x = x + alpha * p
        r = r - alpha * q
        if tap is not None:
            jax.debug.callback(tap, iters + 1, jnp.real(_vdot(r, r)))
        return x, r, p, rho_new, iters + 1

    def cond(state):
        x, r, p, rho, iters = state
        rnorm2 = jnp.real(_vdot(r, r))
        tested = (iters % conv_test_iters == 0) | (iters == maxiter - 1)
        converged = tested & (iters > 0) & (rnorm2 < tol2)
        return (iters < maxiter) & ~converged

    p0 = jnp.zeros_like(b)
    rho0 = jnp.zeros((), dtype=b.dtype)
    state = (x, r, p0, rho0, jnp.zeros((), dtype=jnp.int32))
    x, r, p, rho, iters = jax.lax.while_loop(cond, body, state)
    out = x, host_int(iters)
    if tap is not None:
        _effects_barrier()
    return out


def _cg_host_loop(A, b, x, tol, maxiter, M, callback, conv_test_iters):
    """Host-driven CG matching the reference's periodic-blocking loop.

    Telemetry mode records a ``solver.iter`` event per iteration; the
    residual fetch adds one scalar sync per iteration on this (already
    host-driven) path — the documented cost of observability here.
    """
    r = b - A.matvec(x)
    iters = 0
    rho = None
    p = None
    while iters < maxiter:
        z = M.matvec(r)
        rho1 = rho
        rho = _vdot(r, z)
        p = z if iters == 0 else cg_axpby(p, z, rho, rho1, isalpha=False)
        q = A.matvec(p)
        pq = _vdot(p, q)
        pq = jnp.where(pq == 0, 1, pq)
        x = cg_axpby(x, p, rho, pq, isalpha=True)
        r = cg_axpby(r, q, rho, pq, isalpha=True, negate=True)
        iters += 1
        if telemetry.enabled():
            from .utils import in_trace

            # under an OUTER jit trace the residual is a tracer; skip the
            # event rather than change where/whether the loop fails (the
            # loop's own conv-test float() governs, telemetry never does)
            if not in_trace():
                rn2 = float(jnp.real(_vdot(r, r)))
                telemetry.record(
                    "solver.iter", solver="cg", path="host", iter=iters,
                    resid2=rn2,
                )
                telemetry.health.observe("cg", iters, rn2, path="host")
        if callback is not None:
            callback(x)
        if (iters % conv_test_iters == 0 or iters == maxiter - 1) and float(
            jnp.linalg.norm(r)
        ) < tol:
            break
    return x, iters


@track_provenance
def spsolve(A, b, **kwargs):
    """Sparse solve via CG (reference linalg.py:88)."""
    x, _ = cg(A, b, **kwargs)
    return x


# ---------------------------------------------------------------------------
# Batched entry points (sparse_tpu.batch.krylov) — B independent systems
# sharing one sparsity pattern, solved by one masked compiled loop with
# per-lane convergence (docs/batching.md). Batch-of-1 matches the
# unbatched solvers above.
# ---------------------------------------------------------------------------
def batched_cg(A, b, **kwargs):
    """Batched CG over a lane stack; see
    :func:`sparse_tpu.batch.krylov.batched_cg`."""
    from .batch.krylov import batched_cg as _impl

    return _impl(A, b, **kwargs)


def batched_bicgstab(A, b, **kwargs):
    """Batched BiCGStab; see
    :func:`sparse_tpu.batch.krylov.batched_bicgstab`."""
    from .batch.krylov import batched_bicgstab as _impl

    return _impl(A, b, **kwargs)


def batched_gmres(A, b, **kwargs):
    """Batched restarted GMRES; see
    :func:`sparse_tpu.batch.krylov.batched_gmres`."""
    from .batch.krylov import batched_gmres as _impl

    return _impl(A, b, **kwargs)


def batched_ir(A, b, **kwargs):
    """Batched mixed-precision iterative refinement; see
    :func:`sparse_tpu.batch.krylov.batched_ir`."""
    from .batch.krylov import batched_ir as _impl

    return _impl(A, b, **kwargs)


# ---------------------------------------------------------------------------
# Mixed-precision iterative refinement (sparse_tpu.mixed, ISSUE 15)
# ---------------------------------------------------------------------------
def ir(A, b, x0=None, tol=1e-08, maxiter=None, M=None, policy="f32ir",
       conv_test_iters=25, **kwargs):
    """Mixed-precision solve: reduced-precision Krylov sweeps inside an
    f64 iterative-refinement outer loop (``sparse_tpu.mixed.ir_solve``).

    ``policy`` picks the inner storage/compute width: ``'f32ir'`` (f32
    sweep, the serving fast path) or ``'bf16ir'`` (bfloat16 value
    storage with f32 accumulation — well-conditioned operators only,
    docs/performance.md "Mixed precision"). Stopping rule matches
    :func:`cg`: absolute ``||r|| < tol``, evaluated in f64 — the
    verification is built into every solve. Returns ``(x, iters)`` with
    ``iters`` the total inner iterations (the unbatched-driver
    convention)."""
    from .mixed import ir_solve

    x, info = ir_solve(A, b, x0=x0, tol=tol, maxiter=maxiter, M=M,
                       policy=policy, conv_test_iters=conv_test_iters,
                       **kwargs)
    return x, int(np.asarray(info.iters).max(initial=0))


# ---------------------------------------------------------------------------
# CGS (linalg.py:570)
# ---------------------------------------------------------------------------
@track_provenance
def cgs(A, b, x0=None, tol=1e-08, maxiter=None, callback=None, conv_test_iters=25):
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = n * 10
    A = make_linear_operator(A)
    x = jnp.zeros_like(b) if x0 is None else asjnp(x0)
    r = b - A.matvec(x)
    rtilde = r
    tol2 = jnp.asarray(tol, dtype=jnp.real(r).dtype) ** 2

    # CGS carries two directions (u, p) plus q; explicit while_loop state.
    def body2(state):
        x, r, u, p, q, rho, iters = state
        rho_new = _vdot(rtilde, r)
        beta = rho_new / jnp.where(rho == 0, 1, rho)
        first = iters == 0
        u_n = jnp.where(first, r, r + beta * q)
        p_n = jnp.where(first, u_n, u_n + beta * (q + beta * p))
        v = A.matvec(p_n)
        sigma = _vdot(rtilde, v)
        alpha = rho_new / jnp.where(sigma == 0, 1, sigma)
        q_n = u_n - alpha * v
        uq = u_n + q_n
        x_n = x + alpha * uq
        r_n = r - alpha * A.matvec(uq)
        return x_n, r_n, u_n, p_n, q_n, rho_new, iters + 1

    def cond(state):
        x, r, u, p, q, rho, iters = state
        rnorm2 = jnp.real(_vdot(r, r))
        tested = (iters % conv_test_iters == 0) | (iters == maxiter - 1)
        converged = tested & (iters > 0) & (rnorm2 < tol2)
        return (iters < maxiter) & ~converged

    z = jnp.zeros_like(b)
    rho0 = jnp.zeros((), dtype=b.dtype)
    state = (x, r, z, z, z, rho0, jnp.zeros((), dtype=jnp.int32))
    out = jax.lax.while_loop(cond, body2, state)
    x, r = out[0], out[1]
    iters = out[-1]
    if callback is not None:
        callback(x)
    return x, host_int(iters)


# ---------------------------------------------------------------------------
# BiCG (linalg.py:620)
# ---------------------------------------------------------------------------
@track_provenance
def bicg(A, b, x0=None, tol=1e-08, maxiter=None, callback=None, conv_test_iters=25):
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = n * 10
    A = make_linear_operator(A)
    x = jnp.zeros_like(b) if x0 is None else asjnp(x0)
    r = b - A.matvec(x)
    rtilde = r
    tol2 = jnp.asarray(tol, dtype=jnp.real(r).dtype) ** 2

    def body(state):
        x, r, rt, p, pt, rho, iters = state
        rho_new = _vdot(rt, r)
        beta = rho_new / jnp.where(rho == 0, 1, rho)
        first = iters == 0
        p_n = jnp.where(first, r, r + beta * p)
        pt_n = jnp.where(first, rt, rt + beta * pt)
        q = A.matvec(p_n)
        qt = A.rmatvec(pt_n)
        ptq = _vdot(pt_n, q)
        alpha = rho_new / jnp.where(ptq == 0, 1, ptq)  # 0/0 guard: b=0/exact x0
        x_n = x + alpha * p_n
        r_n = r - alpha * q
        rt_n = rt - alpha * qt
        return x_n, r_n, rt_n, p_n, pt_n, rho_new, iters + 1

    def cond(state):
        x, r, rt, p, pt, rho, iters = state
        rnorm2 = jnp.real(_vdot(r, r))
        tested = (iters % conv_test_iters == 0) | (iters == maxiter - 1)
        converged = tested & (iters > 0) & (rnorm2 < tol2)
        return (iters < maxiter) & ~converged

    z = jnp.zeros_like(b)
    rho0 = jnp.zeros((), dtype=b.dtype)
    state = (x, r, rtilde, z, z, rho0, jnp.zeros((), dtype=jnp.int32))
    out = jax.lax.while_loop(cond, body, state)
    x, iters = out[0], out[-1]
    if callback is not None:
        callback(x)
    return x, host_int(iters)


# ---------------------------------------------------------------------------
# BiCGSTAB (linalg.py:796 — marked broken in the reference; working here)
# ---------------------------------------------------------------------------
@track_provenance
def bicgstab(A, b, x0=None, tol=1e-08, maxiter=None, callback=None, conv_test_iters=25):
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = n * 10
    A = make_linear_operator(A)
    x = jnp.zeros_like(b) if x0 is None else asjnp(x0)
    r = b - A.matvec(x)
    rtilde = r
    tol2 = jnp.asarray(tol, dtype=jnp.real(r).dtype) ** 2
    base_tap = _make_iter_tap("bicgstab")
    tap = None
    if base_tap is not None:
        # same tap cadence, two more scalars: |rho|, |omega| feed the
        # health monitor's breakdown detector — the rho/omega breakdowns
        # the recurrence silently where-guards become observable
        # `solver.anomaly reason=breakdown` events the recovery policy
        # escalates on (ISSUE 5)
        def tap(i, rn2, abs_rho, abs_omega):
            base_tap(i, rn2)
            telemetry.health.observe_breakdown(
                "bicgstab", int(i), float(abs_rho), float(abs_omega),
                resid2=float(rn2),
            )

    def body(state):
        x, r, p, v, rho, alpha, omega, iters = state
        rho_new = _vdot(rtilde, r)
        first = iters == 0
        beta = (rho_new / jnp.where(rho == 0, 1, rho)) * (
            alpha / jnp.where(omega == 0, 1, omega)
        )
        p_n = jnp.where(first, r, r + beta * (p - omega * v))
        v_n = A.matvec(p_n)
        rv = _vdot(rtilde, v_n)
        alpha_n = rho_new / jnp.where(rv == 0, 1, rv)  # 0/0 guard: b=0/exact x0
        s = r - alpha_n * v_n
        t = A.matvec(s)
        omega_n = _vdot(t, s) / jnp.where(_vdot(t, t) == 0, 1, _vdot(t, t))
        x_n = x + alpha_n * p_n + omega_n * s
        r_n = s - omega_n * t
        if tap is not None:
            jax.debug.callback(
                tap, iters + 1, jnp.real(_vdot(r_n, r_n)),
                jnp.abs(rho_new), jnp.abs(omega_n),
            )
        return x_n, r_n, p_n, v_n, rho_new, alpha_n, omega_n, iters + 1

    def cond(state):
        r = state[1]
        iters = state[-1]
        rnorm2 = jnp.real(_vdot(r, r))
        tested = (iters % conv_test_iters == 0) | (iters == maxiter - 1)
        converged = tested & (iters > 0) & (rnorm2 < tol2)
        return (iters < maxiter) & ~converged

    z = jnp.zeros_like(b)
    one = jnp.ones((), dtype=b.dtype)
    state = (x, r, z, z, jnp.zeros((), b.dtype), one, one, jnp.zeros((), jnp.int32))
    out = jax.lax.while_loop(cond, body, state)
    x, iters = out[0], out[-1]
    if callback is not None:
        callback(x)
    iters = host_int(iters)
    if tap is not None:
        _effects_barrier()
    _solve_event("bicgstab", n, iters, "device")
    return x, iters


# ---------------------------------------------------------------------------
# GMRES (linalg.py:670) — restarted, Givens-rotation least squares
# ---------------------------------------------------------------------------
# Counts device->host scalar fetches made by the solver drivers below —
# the test hook for the "one sync per restart cycle" guarantee
# (VERDICT r2 #5). Reset it, run a solve, read it.
HOST_SYNCS = 0


def _sync_fetch(x):
    """Fetch a device value to host, counting the round trip."""
    global HOST_SYNCS
    HOST_SYNCS += 1
    return np.asarray(x)


@track_provenance
def gmres(
    A,
    b,
    x0=None,
    tol=1e-08,
    restart=None,
    maxiter=None,
    M=None,
    callback=None,
    atol=None,
):
    b = asjnp(b)
    n = b.shape[0]
    A = make_linear_operator(A)
    M = IdentityOperator(A.shape, dtype=A.dtype) if M is None else make_linear_operator(M)
    # promote b to the result dtype of A AND x0 BEFORE sizing the Krylov
    # basis: a real b with a complex A (or a complex warm-start x0) must
    # build a complex basis — the jitted cycle would otherwise cast every
    # Arnoldi vector to real
    dt = jnp.result_type(b.dtype, A.dtype)
    if x0 is not None:
        x0 = asjnp(x0)
        dt = jnp.result_type(dt, x0.dtype)
    b = b.astype(dt)
    if restart is None:
        restart = min(20, n)
    restart = min(restart, n)
    if maxiter is None:
        maxiter = max(n // restart, 1) * 10
    x = jnp.zeros_like(b) if x0 is None else x0.astype(dt)
    bnorm = jnp.linalg.norm(b)
    target = jnp.maximum(tol * bnorm, atol if atol is not None else 0.0)
    target = jnp.maximum(target, 1e-30)

    try:
        # warm host-side format dispatch (e.g. csr_array._maybe_dia) with
        # one eager matvec so the traced cycle sees pure jnp paths
        r0 = b - A.matvec(x)
        # warm a non-identity preconditioner EAGERLY as well, aligned
        # with cg's warm-up (ISSUE 14 satellite): M's layout detection
        # (_maybe_dia/_maybe_ell) host-syncs on first use and is skipped
        # inside a trace, so an M first applied inside the first
        # compiled cycle would silently take its slowest kernel path for
        # the whole solve — the host-sync-count test in
        # tests/test_precond.py pins that no M syncs land per cycle
        if not isinstance(M, IdentityOperator):
            M.matvec(r0)
        cycle = _make_gmres_cycle(A, M, restart, jnp.dtype(b.dtype))
        total_iters = 0
        for _outer in range(maxiter):
            x, info = cycle(x, b, target)
            # ONE host sync per restart cycle (VERDICT r2 #5): the packed
            # (inner-count, residual-norm, breakdown) triple — the whole
            # Arnoldi cycle, Givens recurrences and triangular solve ran
            # on device
            inner, _beta, bdown = _sync_fetch(info)
            inner = int(inner.real)
            if inner == 0 and not bdown:
                break  # converged on entry (beta <= target)
            # a breakdown stage did a matvec but contributes no column to
            # the solve; count it (like the host path) so iters reflects
            # work and the outer loop stays bounded by maxiter
            total_iters += inner + (1 if bdown else 0)
            if telemetry.enabled():
                # restart-cycle granularity, reusing the one packed fetch
                # the cycle already makes (no extra syncs)
                telemetry.record(
                    "solver.iter", solver="gmres", path="device",
                    iter=total_iters, resid=float(abs(_beta)), inner=inner,
                )
                # cycle granularity: the entry residual the cycle already
                # fetched, squared to the monitor's resid2 convention
                telemetry.health.observe(
                    "gmres", total_iters, float(abs(_beta)) ** 2,
                    path="device",
                )
            if callback is not None:
                callback(x)
        _solve_event("gmres", n, total_iters, "device")
        return x, total_iters
    except (
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
        jax.errors.ConcretizationTypeError,
    ):
        pass
    # A or M is a host-side Python operator: reference-style host cycles
    total_iters = 0
    for _outer in range(maxiter):
        r = M.matvec(b - A.matvec(x))
        beta = jnp.linalg.norm(r)
        if float(beta) <= float(target):
            break
        x, inner = _gmres_cycle_host(A, M, x, r, beta, restart, target)
        total_iters += inner
        if telemetry.enabled():
            telemetry.record(
                "solver.iter", solver="gmres", path="host",
                iter=total_iters, resid=float(beta), inner=inner,
            )
            telemetry.health.observe(
                "gmres", total_iters, float(beta) ** 2, path="host"
            )
        if callback is not None:
            callback(x)
    _solve_event("gmres", n, total_iters, "host")
    return x, total_iters


def _gmres_cycle_host(A, M, x, r, beta, restart, target):
    """Host-driven Arnoldi cycle — fallback for untraceable operators.

    The [restart x n] Krylov basis stays on device; the [restart x restart]
    Hessenberg lives on host.
    """
    n = r.shape[0]
    dt = r.dtype
    V = jnp.zeros((restart + 1, n), dtype=dt)
    V = V.at[0].set(r / beta)
    H = np.zeros((restart + 1, restart), dtype=np.dtype(dt))
    cs = np.zeros((restart,), dtype=np.dtype(dt))
    sn = np.zeros((restart,), dtype=np.dtype(dt))
    g = np.zeros((restart + 1,), dtype=np.dtype(dt))
    g[0] = float(jnp.real(beta))
    k_used = 0
    for k in range(restart):
        w = M.matvec(A.matvec(V[k]))
        # modified Gram-Schmidt against V[:k+1] (batched on device)
        hcol = V[: k + 1].conj() @ w
        w = w - hcol @ V[: k + 1]
        h2 = V[: k + 1].conj() @ w  # one reorthogonalization pass
        w = w - h2 @ V[: k + 1]
        hcol = hcol + h2
        hkk = jnp.linalg.norm(w)
        H[: k + 1, k] = np.asarray(hcol)
        H[k + 1, k] = float(hkk)
        if float(hkk) > 1e-30:
            V = V.at[k + 1].set(w / hkk)
        # apply accumulated Givens rotations to the new column
        # (real cs, possibly-complex sn: [c, s; -conj(s), c] is unitary)
        for i in range(k):
            t = cs[i] * H[i, k] + sn[i] * H[i + 1, k]
            H[i + 1, k] = -np.conj(sn[i]) * H[i, k] + cs[i] * H[i + 1, k]
            H[i, k] = t
        denom = np.hypot(abs(H[k, k]), abs(H[k + 1, k]))
        if denom == 0:
            k_used = k + 1
            break
        if H[k, k] == 0:
            cs[k] = 0.0
            sn[k] = np.conj(H[k + 1, k]) / abs(H[k + 1, k])
        else:
            cs[k] = abs(H[k, k]) / denom
            sn[k] = (H[k, k] / abs(H[k, k])) * np.conj(H[k + 1, k]) / denom
        H[k, k] = cs[k] * H[k, k] + sn[k] * H[k + 1, k]
        H[k + 1, k] = 0.0
        g[k + 1] = -np.conj(sn[k]) * g[k]
        g[k] = cs[k] * g[k]
        k_used = k + 1
        if abs(g[k + 1]) < float(target):
            break
    # solve the small triangular system on host
    k = k_used
    y = np.linalg.lstsq(H[:k, :k], g[:k], rcond=None)[0] if k else np.zeros((0,))
    if k:
        x = x + jnp.asarray(y, dtype=dt) @ V[:k]
    return x, k


def _make_gmres_cycle(A, M, restart: int, dt):
    """Build the fully device-resident restart cycle (VERDICT r2 #5).

    The reference keeps its Hessenberg recurrences asynchronous via futures
    (linalg.py:670-795); here the [restart]^2 scalar Givens/Hessenberg math
    runs in ``lax`` control flow INSIDE the compiled cycle — beaten, not
    tied: zero mid-cycle host round trips (the old implementation paid 2
    device->host fetches per Arnoldi stage, ~100x a kernel on a
    remote-tunnel backend).

    Returns ``cycle(x, b, target) -> (x', info)`` with ``info = [inner
    iterations, entry residual norm, breakdown flag]``; ``inner == 0``
    with no breakdown means converged on entry. (The compiled cycle is
    built once per gmres() call and reused across all outer restarts; it
    is not cached across calls — the jitted closure captures the
    operator's buffers, see make_dist_cg's same convention.)"""
    rdt = jnp.zeros((), dt).real.dtype

    @jax.jit
    def cycle(x, b, target):
        n = b.shape[0]
        r = M.matvec(b - A.matvec(x))
        beta = jnp.linalg.norm(r)
        start_ok = beta > target
        beta_safe = jnp.where(start_ok, beta, 1.0)
        V = jnp.zeros((restart + 1, n), dtype=dt).at[0].set(r / beta_safe)
        H = jnp.zeros((restart + 1, restart), dtype=dt)
        cs = jnp.zeros((restart,), dtype=rdt)
        sn = jnp.zeros((restart,), dtype=dt)
        g = jnp.zeros((restart + 1,), dtype=dt).at[0].set(beta.astype(dt))

        def cond(st):
            _V, _H, _cs, _sn, _g, k, done, _bd = st
            return (k < restart) & ~done

        def body(st):
            V, H, cs, sn, g, k, done, bd = st
            w = M.matvec(A.matvec(V[k]))
            # modified Gram-Schmidt + one reorthogonalization pass against
            # V[:k+1], batched as masked full-basis matmuls (MXU-shaped;
            # 2x the triangular FLOPs, zero host involvement)
            mask = (jnp.arange(restart + 1) <= k).astype(rdt)
            hcol = (V.conj() @ w) * mask
            w = w - hcol @ V
            h2 = (V.conj() @ w) * mask
            w = w - h2 @ V
            hcol = hcol + h2
            hkk = jnp.linalg.norm(w)
            grew = hkk > 1e-30
            V = V.at[k + 1].set(
                jnp.where(grew, w / jnp.where(grew, hkk, 1.0), 0.0)
            )
            col = hcol.at[k + 1].set(hkk.astype(dt))

            # apply the k accumulated Givens rotations (masked fori —
            # [restart]^2 scalars, exactly the lax.fori_loop case)
            def giv(i, c):
                t = cs[i] * c[i] + sn[i] * c[i + 1]
                bt = -jnp.conj(sn[i]) * c[i] + cs[i] * c[i + 1]
                app = i < k
                c = c.at[i].set(jnp.where(app, t, c[i]))
                return c.at[i + 1].set(jnp.where(app, bt, c[i + 1]))

            col = jax.lax.fori_loop(0, restart, giv, col)
            hk, hk1 = col[k], col[k + 1]
            ahk = jnp.abs(hk)
            ahk1 = jnp.abs(hk1)
            denom = jnp.sqrt(ahk * ahk + ahk1 * ahk1)
            breakdown = denom <= 0
            denom_s = jnp.where(breakdown, 1.0, denom)
            # new rotation: real c, possibly-complex s ([c, s; -conj(s), c])
            ck = jnp.where(ahk == 0, 0.0, ahk / denom_s)
            hk_unit = jnp.where(ahk == 0, 1.0, hk / jnp.where(ahk == 0, 1.0, ahk))
            sk = jnp.where(
                ahk == 0,
                jnp.conj(hk1) / jnp.where(ahk1 == 0, 1.0, ahk1),
                hk_unit * jnp.conj(hk1) / denom_s,
            )
            col = col.at[k].set(ck * hk + sk * hk1)
            col = col.at[k + 1].set(0.0)
            H = H.at[:, k].set(col)
            cs = cs.at[k].set(ck.real)
            sn = sn.at[k].set(sk)
            gk1 = -jnp.conj(sk) * g[k]
            g = g.at[k + 1].set(jnp.where(breakdown, g[k + 1], gk1))
            g = g.at[k].set(jnp.where(breakdown, g[k], ck * g[k]))
            conv = jnp.abs(gk1) < target
            k_next = jnp.where(breakdown, k, k + 1)
            return (
                V, H, cs, sn, g, k_next, done | breakdown | conv,
                bd | breakdown,
            )

        V, H, cs, sn, g, k, _done, bdown = jax.lax.while_loop(
            cond, body,
            (V, H, cs, sn, g, jnp.int32(0), ~start_ok, jnp.bool_(False)),
        )
        # masked triangular solve of H[:k, :k] y = g[:k] on device: columns
        # past k are zeroed and given a unit diagonal, their rhs zeroed
        idx = jnp.arange(restart)
        mk = (idx < k).astype(rdt)
        Hs = H[:restart, :restart] * (mk[:, None] * mk[None, :])
        Hs = Hs + jnp.diag(1.0 - mk).astype(dt)
        gv = g[:restart] * mk
        y = jax.scipy.linalg.solve_triangular(Hs, gv, lower=False)
        x = x + y @ V[:restart]
        info = jnp.stack(
            [k.astype(rdt), beta.astype(rdt), bdown.astype(rdt)]
        )
        return x, info

    return cycle


# ---------------------------------------------------------------------------
# LSQR (linalg.py:937) — Golub-Kahan bidiagonalization
# ---------------------------------------------------------------------------
@track_provenance
def lsqr(
    A, b, damp=0.0, atol=1e-08, btol=1e-08, conlim=1e8, iter_lim=None,
    calc_var=False,
):
    """Golub-Kahan bidiagonalization least squares (reference linalg.py:937).

    The whole solve — bidiagonalization matvecs AND the O(1) rotation/norm
    recurrences (Paige & Saunders' stopping estimates, as in scipy) — runs
    as one compiled ``lax.while_loop`` with a single host sync at the end;
    untraceable operators fall back to a host-driven loop. Returns scipy's
    full 10-tuple
    (x, istop, itn, r1norm, r2norm, anorm, acond, arnorm, xnorm, var);
    ``var`` is estimated only under ``calc_var=True`` (zeros otherwise).
    """
    b = asjnp(b)
    A = make_linear_operator(A)
    # promote to the operator's result dtype: the device while_loop carry
    # must be dtype-stable (a real b with complex A would otherwise mix
    # real x/u with complex v/w and fail to trace)
    b = b.astype(jnp.result_type(b.dtype, A.dtype))
    m, n = A.shape
    if iter_lim is None:
        iter_lim = 2 * n
    try:
        A.rmatvec(A.matvec(jnp.zeros((n,), dtype=b.dtype)))  # warm dispatch
        return _lsqr_device(
            A, b, damp, atol, btol, conlim, iter_lim, calc_var
        )
    except (
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
        jax.errors.ConcretizationTypeError,
    ):
        return _lsqr_host(A, b, damp, atol, btol, conlim, iter_lim, calc_var)


def _lsqr_device(A, b, damp, atol, btol, conlim, iter_lim, calc_var):
    """Whole-solve ``lax.while_loop``: the Paige & Saunders scalar
    recurrences ride along as device scalars; the host syncs ONCE at the
    end (VERDICT r2 #5 — the old driver fetched 2-3 norms per iteration).
    """
    m, n = A.shape
    rdt = jnp.zeros((), b.dtype).real.dtype
    eps = jnp.asarray(
        np.finfo(np.dtype(rdt)).eps
        if np.issubdtype(np.dtype(rdt), np.floating)
        else np.finfo(np.float64).eps,
        rdt,
    )
    dampsq = jnp.asarray(damp * damp, rdt)
    ctol = jnp.asarray(1.0 / conlim if conlim > 0 else 0.0, rdt)
    atol_d = jnp.asarray(atol, rdt)
    btol_d = jnp.asarray(btol, rdt)

    x0 = jnp.zeros((n,), dtype=b.dtype)
    var0 = jnp.zeros((n,), dtype=b.dtype)
    bnorm = jnp.linalg.norm(b)

    @jax.jit
    def run(b):
        beta0 = jnp.linalg.norm(b)
        ok0 = beta0 > 0
        u = b / jnp.where(ok0, beta0, 1.0)
        v = A.rmatvec(u)
        alpha0 = jnp.linalg.norm(v)
        v = v / jnp.where(alpha0 > 0, alpha0, 1.0)
        w = v
        zero = jnp.zeros((), rdt)
        # state scalars, Paige & Saunders' names
        init = dict(
            x=x0, u=u, v=v, w=w, var=var0,
            alpha=alpha0.astype(rdt), phibar=beta0.astype(rdt),
            rhobar=alpha0.astype(rdt),
            anorm=zero, ddnorm=zero, res2=zero, xxnorm=zero, z=zero,
            cs2=jnp.asarray(-1.0, rdt), sn2=zero,
            rnorm=beta0.astype(rdt), r1norm=beta0.astype(rdt),
            xnorm=zero, acond=zero,
            arnorm=(alpha0 * beta0).astype(rdt),
            itn=jnp.int32(0), istop=jnp.int32(0),
        )
        # degenerate entries (b == 0 or A^T b == 0): never enter the loop
        dead = ~ok0 | (init["arnorm"] == 0)

        def cond(s):
            return (s["istop"] == 0) & (s["itn"] < iter_lim) & ~dead

        def body(s):
            itn = s["itn"] + 1
            u = A.matvec(s["v"]) - s["alpha"].astype(b.dtype) * s["u"]
            beta = jnp.linalg.norm(u).astype(rdt)
            bpos = beta > 0
            u = u / jnp.where(bpos, beta, 1.0).astype(b.dtype)
            anorm = jnp.where(
                bpos,
                jnp.sqrt(
                    s["anorm"] ** 2 + s["alpha"] ** 2 + beta**2 + dampsq
                ),
                s["anorm"],
            )
            v_new = A.rmatvec(u) - beta.astype(b.dtype) * s["v"]
            alpha_new = jnp.linalg.norm(v_new).astype(rdt)
            v_new = v_new / jnp.where(alpha_new > 0, alpha_new, 1.0).astype(
                b.dtype
            )
            v = jnp.where(bpos, v_new, s["v"])
            alpha = jnp.where(bpos, alpha_new, s["alpha"])
            # eliminate the damping diagonal with its own rotation; with no
            # damping rhobar1 IS rhobar (signed — sqrt would drop the sign)
            damped = dampsq > 0
            rhobar1 = jnp.where(
                damped, jnp.sqrt(s["rhobar"] ** 2 + dampsq), s["rhobar"]
            )
            psi = jnp.where(damped, (dampsq**0.5 / rhobar1) * s["phibar"], zero)
            phibar = jnp.where(
                damped, (s["rhobar"] / rhobar1) * s["phibar"], s["phibar"]
            )
            # plane rotation annihilating beta
            rho = jnp.sqrt(rhobar1**2 + beta**2)
            cs = rhobar1 / rho
            sn = beta / rho
            theta = sn * alpha
            rhobar = -cs * alpha
            phi = cs * phibar
            phibar = sn * phibar
            tau = sn * phi
            x = s["x"] + (phi / rho).astype(b.dtype) * s["w"]
            ddnorm = s["ddnorm"] + jnp.vdot(s["w"], s["w"]).real.astype(
                rdt
            ) / rho**2
            var = (
                s["var"] + (s["w"] / rho.astype(b.dtype)) ** 2
                if calc_var
                else s["var"]
            )
            w = v - (theta / rho).astype(b.dtype) * s["w"]
            # estimate ||x||, cond(A), residual norms (Paige & Saunders)
            delta = s["sn2"] * rho
            gambar = -s["cs2"] * rho
            rhs = phi - delta * s["z"]
            zbar = rhs / gambar
            xnorm = jnp.sqrt(s["xxnorm"] + zbar**2)
            gamma = jnp.sqrt(gambar**2 + theta**2)
            cs2 = gambar / gamma
            sn2 = theta / gamma
            z = rhs / gamma
            xxnorm = s["xxnorm"] + z**2
            acond = anorm * jnp.sqrt(ddnorm)
            res2 = s["res2"] + psi**2
            rnorm = jnp.sqrt(phibar**2 + res2)
            arnorm = alpha * jnp.abs(tau)
            r1sq = rnorm**2 - dampsq * xxnorm
            r1norm = jnp.sqrt(jnp.abs(r1sq)) * jnp.where(
                r1sq >= 0, 1.0, -1.0
            ).astype(rdt)
            # convergence tests, scipy's cascade (later tests take priority)
            test1 = rnorm / bnorm.astype(rdt)
            test2 = arnorm / (anorm * rnorm + eps)
            test3 = 1.0 / (acond + eps)
            t1 = test1 / (1 + anorm * xnorm / bnorm.astype(rdt))
            rtol = btol_d + atol_d * anorm * xnorm / bnorm.astype(rdt)
            istop = jnp.int32(0)
            istop = jnp.where(itn >= iter_lim, 7, istop)
            istop = jnp.where(1 + test3 <= 1, 6, istop)
            istop = jnp.where(1 + test2 <= 1, 5, istop)
            istop = jnp.where(1 + t1 <= 1, 4, istop)
            istop = jnp.where(test3 <= ctol, 3, istop)
            istop = jnp.where(test2 <= atol_d, 2, istop)
            istop = jnp.where(test1 <= rtol, 1, istop)
            return dict(
                x=x, u=u, v=v, w=w, var=var, alpha=alpha, phibar=phibar,
                rhobar=rhobar, anorm=anorm, ddnorm=ddnorm, res2=res2,
                xxnorm=xxnorm, z=z, cs2=cs2, sn2=sn2, rnorm=rnorm,
                r1norm=r1norm, xnorm=xnorm, acond=acond, arnorm=arnorm,
                itn=itn, istop=istop.astype(jnp.int32),
            )

        out = jax.lax.while_loop(cond, body, init)
        stats = jnp.stack(
            [
                out["istop"].astype(rdt), out["itn"].astype(rdt),
                out["r1norm"], out["rnorm"], out["anorm"], out["acond"],
                out["arnorm"], out["xnorm"],
                jnp.where(dead, 1.0, 0.0).astype(rdt),
            ]
        )
        return out["x"], out["var"], stats

    x, var, stats = run(b)
    st = _sync_fetch(stats)  # the ONE host sync
    if st[8]:  # degenerate: b == 0 or A^T b == 0
        bn = float(np.asarray(bnorm))
        if bn == 0.0:
            return x0, 0, 0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, var0
        return x0, 0, 0, bn, bn, 0.0, 0.0, 0.0, 0.0, var0
    return (
        x, int(st[0]), int(st[1]), float(st[2]), float(st[3]), float(st[4]),
        float(st[5]), float(st[6]), float(st[7]), var,
    )


def _lsqr_host(A, b, damp, atol, btol, conlim, iter_lim, calc_var):
    """Host-driven fallback for untraceable operators (reference-style
    future-per-iteration behavior)."""
    m, n = A.shape
    dampsq = damp * damp
    eps = float(np.finfo(np.dtype(b.dtype)).eps) if np.issubdtype(
        np.dtype(b.dtype), np.floating
    ) else float(np.finfo(np.float64).eps)
    ctol = 1.0 / conlim if conlim > 0 else 0.0

    x = jnp.zeros((n,), dtype=b.dtype)
    var = jnp.zeros((n,), dtype=b.dtype)
    bnorm = float(jnp.linalg.norm(b))
    if bnorm == 0.0:
        return x, 0, 0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, var
    beta = bnorm
    u = b / beta
    v = A.rmatvec(u)
    alpha = float(jnp.linalg.norm(v))
    if alpha > 0:
        v = v / alpha
    w = v
    phibar, rhobar = beta, alpha
    rnorm = r1norm = beta
    anorm = acond = ddnorm = res2 = xxnorm = xnorm = z = 0.0
    cs2, sn2 = -1.0, 0.0
    arnorm = alpha * beta
    if arnorm == 0.0:
        return x, 0, 0, r1norm, rnorm, anorm, acond, arnorm, 0.0, var
    istop = itn = 0
    while itn < iter_lim:
        itn += 1
        u = A.matvec(v) - alpha * u
        beta = float(jnp.linalg.norm(u))
        if beta > 0:
            u = u / beta
            anorm = np.sqrt(anorm**2 + alpha**2 + beta**2 + dampsq)
            v = A.rmatvec(u) - beta * v
            alpha = float(jnp.linalg.norm(v))
            if alpha > 0:
                v = v / alpha
        # eliminate the damping diagonal with its own rotation
        if damp:
            rhobar1 = np.sqrt(rhobar**2 + dampsq)
            cs1 = rhobar / rhobar1
            sn1 = damp / rhobar1
            psi = sn1 * phibar
            phibar = cs1 * phibar
        else:
            rhobar1, psi = rhobar, 0.0
        # plane rotation annihilating beta
        rho = np.sqrt(rhobar1**2 + beta**2)
        cs = rhobar1 / rho
        sn = beta / rho
        theta = sn * alpha
        rhobar = -cs * alpha
        phi = cs * phibar
        phibar = sn * phibar
        tau = sn * phi
        x = x + (phi / rho) * w
        ddnorm = ddnorm + float(jnp.vdot(w, w).real) / rho**2
        if calc_var:
            var = var + (w / rho) ** 2
        w = v - (theta / rho) * w
        # estimate ||x||, cond(A), residual norms (Paige & Saunders)
        delta = sn2 * rho
        gambar = -cs2 * rho
        rhs = phi - delta * z
        zbar = rhs / gambar
        xnorm = np.sqrt(xxnorm + zbar**2)
        gamma = np.sqrt(gambar**2 + theta**2)
        cs2 = gambar / gamma
        sn2 = theta / gamma
        z = rhs / gamma
        xxnorm = xxnorm + z**2
        acond = anorm * np.sqrt(ddnorm)
        res1 = phibar**2
        res2 = res2 + psi**2
        rnorm = np.sqrt(res1 + res2)
        arnorm = alpha * abs(tau)
        r1sq = rnorm**2 - dampsq * xxnorm
        r1norm = np.sqrt(abs(r1sq)) * (1.0 if r1sq >= 0 else -1.0)
        # convergence tests
        test1 = rnorm / bnorm
        test2 = arnorm / (anorm * rnorm + eps)
        test3 = 1.0 / (acond + eps)
        t1 = test1 / (1 + anorm * xnorm / bnorm)
        rtol = btol + atol * anorm * xnorm / bnorm
        if itn >= iter_lim:
            istop = 7
        if 1 + test3 <= 1:
            istop = 6
        if 1 + test2 <= 1:
            istop = 5
        if 1 + t1 <= 1:
            istop = 4
        if test3 <= ctol:
            istop = 3
        if test2 <= atol:
            istop = 2
        if test1 <= rtol:
            istop = 1
        if istop != 0:
            break
    return (
        x, istop, itn, r1norm, rnorm, anorm, acond, arnorm, xnorm, var,
    )


# ---------------------------------------------------------------------------
# MINRES / LSMR / TFQMR / QMR — beyond the reference's solver menu
# (linalg.py:499-1017 stops at lsqr); added for scipy.sparse.linalg drop-in
# completeness. All four follow the repo's device-resident shape: the whole
# recurrence is one compiled lax.while_loop, zero host syncs inside.
# ---------------------------------------------------------------------------
def _while_with_callback(cond, body, state, callback, key="x"):
    """lax.while_loop when no callback is requested; otherwise an eager
    host-driven loop invoking ``callback(x)`` each iteration — the
    module's documented callback contract (matching cg/gmres)."""
    if callback is None:
        return jax.lax.while_loop(cond, body, state)
    while bool(cond(state)):
        state = body(state)
        callback(state[key])
    return state


def _sym_ortho(a, b):
    """Stable Givens (c, s, r) with r = hypot(a, b); c=1, s=0 when r=0.
    Scaled like hypot so squaring cannot overflow/underflow in f32."""
    scale = jnp.maximum(jnp.abs(a), jnp.abs(b))
    sscale = jnp.where(scale == 0, 1, scale)
    an, bn = a / sscale, b / sscale
    r = scale * jnp.sqrt(an * an + bn * bn)
    safe = jnp.where(r == 0, 1, r)
    return (
        jnp.where(r == 0, 1.0, a / safe),
        jnp.where(r == 0, 0.0, b / safe),
        r,
    )


@track_provenance
def minres(A, b, x0=None, shift=0.0, tol=1e-5, maxiter=None, M=None,
           callback=None, conv_test_iters=1):
    """MINRES for symmetric (possibly indefinite) systems, Paige-Saunders
    Lanczos + Givens recurrence (scipy.sparse.linalg.minres semantics;
    solves (A - shift*I) x = b, ``M`` a symmetric positive-definite
    preconditioner). Converges on ||r||_pre <= tol * ||b|| (the
    M-preconditioned residual norm, as in scipy). Returns (x, iters)."""
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = 5 * n
    A = make_linear_operator(A)
    b = b.astype(jnp.result_type(b.dtype, A.dtype))
    rdt = jnp.zeros((), b.dtype).real.dtype
    x = jnp.zeros_like(b) if x0 is None else asjnp(x0).astype(b.dtype)
    shift_d = jnp.asarray(shift, b.dtype)
    Mop = None if M is None else make_linear_operator(M)

    def op(v):
        return A.matvec(v) - shift_d * v

    def precond(v):
        return v if Mop is None else Mop.matvec(v)

    r1 = b - op(x)
    y1 = precond(r1)
    b1sq = jnp.real(_vdot(r1, y1))
    if Mop is not None and float(b1sq) < 0:
        raise ValueError("minres: indefinite preconditioner")
    beta1 = jnp.sqrt(jnp.maximum(b1sq, 0)).astype(rdt)
    bnorm = jnp.sqrt(jnp.real(_vdot(b, b))).astype(rdt)
    if x0 is not None and float(bnorm) == 0:
        # b == 0: the solution of Ax = 0 is x = 0 (scipy), not x0
        return jnp.zeros_like(b), 0
    # the documented test is relative to ||b|| (NOT ||r0||: a warm x0 must
    # not tighten the target, scipy semantics)
    target = jnp.asarray(tol, rdt) * jnp.maximum(
        bnorm, jnp.asarray(np.finfo(np.dtype(rdt)).tiny, rdt)
    )

    zero = jnp.zeros((), rdt)
    zvec = jnp.zeros_like(b)
    init = dict(
        x=x, r1=r1, r2=r1, y=y1, w=zvec, w2=zvec,
        oldb=zero, beta=beta1, dbar=zero, epsln=zero,
        phibar=beta1, cs=jnp.asarray(-1.0, rdt), sn=zero,
        itn=jnp.int32(0),
    )
    dead = (beta1 == 0) | (bnorm == 0)

    def cond(s):
        tested = ((s["itn"] % conv_test_iters) == 0) | (s["itn"] >= maxiter)
        converged = tested & (s["itn"] > 0) & (s["phibar"] <= target)
        return (s["itn"] < maxiter) & ~converged & ~dead

    def body(s):
        itn = s["itn"] + 1
        beta = s["beta"]
        v = s["y"] / jnp.where(beta == 0, 1, beta).astype(b.dtype)
        y = op(v)
        y = jnp.where(
            itn >= 2,
            y - (beta / jnp.where(s["oldb"] == 0, 1, s["oldb"])).astype(
                b.dtype
            ) * s["r1"],
            y,
        )
        alfa = jnp.real(_vdot(v, y)).astype(rdt)
        y = y - (alfa / jnp.where(beta == 0, 1, beta)).astype(b.dtype) * s["r2"]
        r1_n, r2_n = s["r2"], y
        y_n = precond(y)
        oldb = beta
        beta_n = jnp.sqrt(
            jnp.maximum(jnp.real(_vdot(y, y_n)), 0)
        ).astype(rdt)
        # previous rotation applied to the new column of T
        oldeps = s["epsln"]
        delta = s["cs"] * s["dbar"] + s["sn"] * alfa
        gbar = s["sn"] * s["dbar"] - s["cs"] * alfa
        epsln = s["sn"] * beta_n
        dbar = -s["cs"] * beta_n
        # current rotation
        cs, sn, gamma = _sym_ortho(gbar, beta_n)
        gamma = jnp.maximum(gamma, jnp.asarray(np.finfo(np.dtype(rdt)).tiny, rdt))
        phi = cs * s["phibar"]
        phibar = sn * s["phibar"]
        w1, w2 = s["w2"], s["w"]
        w = (v - oldeps.astype(b.dtype) * w1 - delta.astype(b.dtype) * w2) / (
            gamma.astype(b.dtype)
        )
        x_n = s["x"] + phi.astype(b.dtype) * w
        return dict(
            x=x_n, r1=r1_n, r2=r2_n, y=y_n, w=w, w2=w2,
            oldb=oldb, beta=beta_n, dbar=dbar, epsln=epsln,
            phibar=phibar, cs=cs, sn=sn, itn=itn,
        )

    out = _while_with_callback(cond, body, init, callback)
    return out["x"], host_int(out["itn"])


@track_provenance
def lsmr(A, b, damp=0.0, atol=1e-6, btol=1e-6, conlim=1e8, maxiter=None,
         x0=None):
    """LSMR (Fong & Saunders): least squares via Golub-Kahan
    bidiagonalization with a MINRES-shaped recurrence. Same device-resident
    design as ``lsqr``; returns scipy's 8-tuple
    (x, istop, itn, normr, normar, norma, conda, normx). With ``x0`` the
    bidiagonalization starts from b - A x0 (scipy semantics: the stopping
    norms then describe the residual system)."""
    b = asjnp(b)
    A = make_linear_operator(A)
    b = b.astype(jnp.result_type(b.dtype, A.dtype))
    m, n = A.shape
    if maxiter is None:
        maxiter = min(m, n) * 5
    rdt = jnp.zeros((), b.dtype).real.dtype
    damp_d = jnp.asarray(damp, rdt)
    ctol = jnp.asarray(1.0 / conlim if conlim > 0 else 0.0, rdt)
    atol_d = jnp.asarray(atol, rdt)
    btol_d = jnp.asarray(btol, rdt)

    @jax.jit
    def run(b):
        normb = jnp.linalg.norm(b).astype(rdt)
        u = b
        beta = normb
        u = u / jnp.where(beta > 0, beta, 1).astype(b.dtype)
        v = jnp.where(beta > 0, A.rmatvec(u), jnp.zeros((n,), b.dtype))
        alpha = jnp.linalg.norm(v).astype(rdt)
        v = v / jnp.where(alpha > 0, alpha, 1).astype(b.dtype)
        zero = jnp.zeros((), rdt)
        one = jnp.ones((), rdt)
        init = dict(
            x=jnp.zeros((n,), b.dtype), u=u, v=v,
            h=v, hbar=jnp.zeros((n,), b.dtype),
            alpha=alpha, beta=beta, alphabar=alpha, zetabar=alpha * beta,
            rho=one, rhobar=one, cbar=one, sbar=zero, zeta=zero,
            # residual-estimate recurrence (Fong & Saunders §5)
            betadd=beta, betad=zero, rhodold=one, tautildeold=zero,
            thetatilde=zero, d=zero,
            norma2=alpha * alpha, maxrbar=zero,
            minrbar=jnp.asarray(np.finfo(np.dtype(rdt)).max, rdt),
            normr=beta, normar=alpha * beta, norma=alpha, conda=one,
            normx=zero, itn=jnp.int32(0), istop=jnp.int32(0),
        )
        dead = (normb == 0) | (init["normar"] == 0)

        def cond(s):
            return (s["istop"] == 0) & (s["itn"] < maxiter) & ~dead

        def body(s):
            itn = s["itn"] + 1
            u = A.matvec(s["v"]) - s["alpha"].astype(b.dtype) * s["u"]
            beta = jnp.linalg.norm(u).astype(rdt)
            u = u / jnp.where(beta > 0, beta, 1).astype(b.dtype)
            v = A.rmatvec(u) - beta.astype(b.dtype) * s["v"]
            alpha = jnp.linalg.norm(v).astype(rdt)
            v = v / jnp.where(alpha > 0, alpha, 1).astype(b.dtype)
            # rotation P-hat eliminates damping
            chat, shat, alphahat = _sym_ortho(s["alphabar"], damp_d)
            # rotation P
            rhoold = s["rho"]
            c, sgiv, rho = _sym_ortho(alphahat, beta)
            thetanew = sgiv * alpha
            alphabar = c * alpha
            # rotation P-bar
            rhobarold = s["rhobar"]
            zetaold = s["zeta"]
            thetabar = s["sbar"] * rho
            rhotemp = s["cbar"] * rho
            cbar, sbar, rhobar = _sym_ortho(s["cbar"] * rho, thetanew)
            zeta = cbar * s["zetabar"]
            zetabar = -sbar * s["zetabar"]
            # update h, hbar, x
            denom1 = jnp.where(rhoold * rhobarold == 0, 1, rhoold * rhobarold)
            hbar = s["h"] - (thetabar * rho / denom1).astype(b.dtype) * s["hbar"]
            denom2 = jnp.where(rho * rhobar == 0, 1, rho * rhobar)
            x = s["x"] + (zeta / denom2).astype(b.dtype) * hbar
            h = v - (thetanew / jnp.where(rho == 0, 1, rho)).astype(b.dtype) * s["h"]
            # ||r|| estimate
            betaacute = chat * s["betadd"]
            betacheck = -shat * s["betadd"]
            betahat = c * betaacute
            betadd = -sgiv * betaacute
            thetatildeold = s["thetatilde"]
            ctildeold, stildeold, rhotildeold = _sym_ortho(s["rhodold"], thetabar)
            thetatilde = stildeold * rhobar
            rhodold = ctildeold * rhobar
            betad = -stildeold * s["betad"] + ctildeold * betahat
            tautildeold = (zetaold - thetatildeold * s["tautildeold"]) / jnp.where(
                rhotildeold == 0, 1, rhotildeold
            )
            taud = (zeta - thetatilde * tautildeold) / jnp.where(
                rhodold == 0, 1, rhodold
            )
            d = s["d"] + betacheck * betacheck
            normr = jnp.sqrt(d + (betad - taud) ** 2 + betadd * betadd)
            norma2 = s["norma2"] + beta * beta
            norma = jnp.sqrt(norma2)
            norma2 = norma2 + alpha * alpha
            normar = jnp.abs(zetabar)
            maxrbar = jnp.maximum(s["maxrbar"], rhobarold)
            minrbar = jnp.where(
                itn > 1, jnp.minimum(s["minrbar"], rhobarold), s["minrbar"]
            )
            conda = jnp.maximum(maxrbar, rhotemp) / jnp.where(
                jnp.minimum(minrbar, rhotemp) == 0,
                1,
                jnp.minimum(minrbar, rhotemp),
            )
            normx = jnp.linalg.norm(x).astype(rdt)
            # stopping (scipy's istop 1-7)
            test1 = normr / jnp.where(normb == 0, 1, normb)
            denom3 = jnp.where(norma * normr == 0, 1, norma * normr)
            test2 = normar / denom3
            test3 = 1.0 / jnp.where(conda == 0, 1, conda)
            t1 = test1 / (1 + norma * normx / jnp.where(normb == 0, 1, normb))
            rtol_ = btol_d + atol_d * norma * normx / jnp.where(
                normb == 0, 1, normb
            )
            istop = jnp.int32(0)
            istop = jnp.where(itn >= maxiter, 7, istop)
            istop = jnp.where(1 + test3 <= 1, 6, istop)
            istop = jnp.where(1 + test2 <= 1, 5, istop)
            istop = jnp.where(1 + t1 <= 1, 4, istop)
            istop = jnp.where(test3 <= ctol, 3, istop)
            istop = jnp.where(test2 <= atol_d, 2, istop)
            istop = jnp.where(test1 <= rtol_, 1, istop)
            return dict(
                x=x, u=u, v=v, h=h, hbar=hbar,
                alpha=alpha, beta=beta, alphabar=alphabar, zetabar=zetabar,
                rho=rho, rhobar=rhobar, cbar=cbar, sbar=sbar, zeta=zeta,
                betadd=betadd, betad=betad, rhodold=rhodold,
                tautildeold=tautildeold, thetatilde=thetatilde, d=d,
                norma2=norma2, maxrbar=maxrbar, minrbar=minrbar,
                normr=normr, normar=normar, norma=norma, conda=conda,
                normx=normx, itn=itn, istop=istop.astype(jnp.int32),
            )

        return jax.lax.while_loop(cond, body, init)

    x_off = None
    if x0 is not None:
        x_off = asjnp(x0).astype(b.dtype)
    try:
        # warm the kernel-dispatch caches (e.g. CSR banded auto-detection
        # runs host-side numpy on first call) OUTSIDE the trace
        A.rmatvec(A.matvec(jnp.zeros((n,), dtype=b.dtype)))
        b_eff = b if x_off is None else b - A.matvec(x_off)
        out = run(b_eff)
    except (
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
        jax.errors.ConcretizationTypeError,
    ):
        with jax.disable_jit():  # untraceable operator: eager loop
            b_eff = b if x_off is None else b - A.matvec(x_off)
            out = run(b_eff)
    x = out["x"] if x_off is None else out["x"] + x_off
    stats = jnp.stack(
        [
            out["istop"].astype(rdt), out["itn"].astype(rdt),
            out["normr"], out["normar"], out["norma"], out["conda"],
            jnp.linalg.norm(x).astype(rdt) if x_off is not None
            else out["normx"],
        ]
    )
    st = _sync_fetch(stats)  # the ONE host sync (lsqr's idiom)
    return (
        x, int(st[0]), int(st[1]), float(st[2]), float(st[3]),
        float(st[4]), float(st[5]), float(st[6]),
    )


@track_provenance
def tfqmr(A, b, x0=None, tol=1e-8, maxiter=None, M=None, callback=None,
          atol=0.0):
    """Transpose-free QMR (Freund 1993; scipy.sparse.linalg.tfqmr).

    One (preconditioned) matvec per half-iteration, no rmatvec. Even/odd
    branches are merged with ``jnp.where`` so the whole solve is one
    while_loop. ``M`` is applied as a left preconditioner (solves MAx=Mb);
    converges on tau * sqrt(m+1) <= max(atol, tol * ||M r0||)."""
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = 2 * n * 10
    A = make_linear_operator(A)
    b = b.astype(jnp.result_type(b.dtype, A.dtype))
    rdt = jnp.zeros((), b.dtype).real.dtype
    x = jnp.zeros_like(b) if x0 is None else asjnp(x0).astype(b.dtype)
    Mop = None if M is None else make_linear_operator(M)

    def opmv(v):
        av = A.matvec(v)
        return av if Mop is None else Mop.matvec(av)

    r = b - A.matvec(x)
    if Mop is not None:
        r = Mop.matvec(r)
    r0norm = jnp.sqrt(jnp.real(_vdot(r, r))).astype(rdt)
    target = jnp.maximum(
        jnp.asarray(atol, rdt), jnp.asarray(tol, rdt) * r0norm
    )
    uhat0 = opmv(r)
    one = jnp.ones((), b.dtype)
    init = dict(
        x=x, u=r, w=r, v=uhat0, uhat=uhat0, d=jnp.zeros_like(b),
        rho=_vdot(r, r), alpha=one, theta=jnp.zeros((), rdt),
        eta=jnp.zeros((), b.dtype), tau=r0norm, m=jnp.int32(0),
    )
    dead = r0norm == 0

    def cond(s):
        converged = s["tau"] * jnp.sqrt(s["m"].astype(rdt) + 1) <= target
        return (s["m"] < maxiter) & ~converged & ~dead

    def body(s):
        even = (s["m"] % 2) == 0
        vtr = _vdot(r, s["v"])  # r is rstar (frozen shadow residual)
        alpha = jnp.where(
            even, s["rho"] / jnp.where(vtr == 0, 1, vtr), s["alpha"]
        )
        u_even = s["u"] - alpha * s["v"]
        w_n = s["w"] - alpha * s["uhat"]
        denom = jnp.where(alpha == 0, 1, alpha)
        d_n = s["u"] + ((s["theta"] ** 2).astype(b.dtype) / denom) * s["eta"] * s["d"]
        wnorm = jnp.sqrt(jnp.real(_vdot(w_n, w_n))).astype(rdt)
        theta_n = wnorm / jnp.where(s["tau"] == 0, 1, s["tau"])
        c2 = 1.0 / (1.0 + theta_n * theta_n)
        tau_n = s["tau"] * theta_n * jnp.sqrt(c2)
        eta_n = c2.astype(b.dtype) * alpha
        x_n = s["x"] + eta_n * d_n
        # odd half: new rho/beta, u, v
        rho_new = _vdot(r, w_n)
        beta = rho_new / jnp.where(s["rho"] == 0, 1, s["rho"])
        u_odd = w_n + beta * s["u"]
        v_partial = beta * s["uhat"] + beta * beta * s["v"]
        u_n = jnp.where(even, u_even, u_odd)
        uhat_n = opmv(u_n)
        v_n = jnp.where(even, s["v"], v_partial + uhat_n)
        rho_n = jnp.where(even, s["rho"], rho_new)
        return dict(
            x=x_n, u=u_n, w=w_n, v=v_n, uhat=uhat_n, d=d_n,
            rho=rho_n, alpha=alpha, theta=theta_n, eta=eta_n,
            tau=tau_n, m=s["m"] + 1,
        )

    out = _while_with_callback(cond, body, init, callback)
    return out["x"], host_int(out["m"])


@track_provenance
def qmr(A, b, x0=None, tol=1e-8, maxiter=None, M1=None, M2=None,
        callback=None, conv_test_iters=25):
    """Quasi-minimal residual (Freund & Nachtigal, no look-ahead; the
    Templates formulation scipy.sparse.linalg.qmr implements). Uses one
    matvec + one rmatvec per iteration. ``M1``/``M2`` are the left/right
    preconditioner factors (operators applying the INVERSE, as in scipy;
    their ``rmatvec`` must apply the inverse adjoint). Returns (x, iters)."""
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = n * 10
    A = make_linear_operator(A)
    b = b.astype(jnp.result_type(b.dtype, A.dtype))
    rdt = jnp.zeros((), b.dtype).real.dtype
    x = jnp.zeros_like(b) if x0 is None else asjnp(x0).astype(b.dtype)
    M1op = None if M1 is None else make_linear_operator(M1)
    M2op = None if M2 is None else make_linear_operator(M2)

    def m1(v):
        return v if M1op is None else M1op.matvec(v)

    def m1h(v):
        return v if M1op is None else M1op.rmatvec(v)

    def m2(v):
        return v if M2op is None else M2op.matvec(v)

    def m2h(v):
        return v if M2op is None else M2op.rmatvec(v)

    r = b - A.matvec(x)
    tol2 = jnp.asarray(tol, rdt) ** 2 * jnp.real(_vdot(b, b))

    y0 = m1(r)
    z0 = m2h(r)
    rho0 = jnp.sqrt(jnp.real(_vdot(y0, y0))).astype(rdt)
    xi0 = jnp.sqrt(jnp.real(_vdot(z0, z0))).astype(rdt)
    one = jnp.ones((), rdt)
    zvec = jnp.zeros_like(b)
    init = dict(
        x=x, r=r, vtilde=r, wtilde=r, y=y0, z=z0, p=zvec, q=zvec,
        d=zvec, s=zvec,
        rho=rho0, xi=xi0, gamma=one, eta=jnp.asarray(-1.0, b.dtype),
        theta=jnp.zeros((), rdt), epsq=jnp.ones((), b.dtype),
        itn=jnp.int32(0),
    )
    dead = rho0 == 0

    def cond(s):
        rnorm2 = jnp.real(_vdot(s["r"], s["r"]))
        tested = ((s["itn"] % conv_test_iters) == 0) | (s["itn"] >= maxiter)
        converged = tested & (s["itn"] > 0) & (rnorm2 <= tol2)
        return (s["itn"] < maxiter) & ~converged & ~dead

    def body(s):
        itn = s["itn"] + 1
        rho_c = jnp.where(s["rho"] == 0, 1, s["rho"]).astype(b.dtype)
        xi_c = jnp.where(s["xi"] == 0, 1, s["xi"]).astype(b.dtype)
        v = s["vtilde"] / rho_c
        yn = s["y"] / rho_c
        w = s["wtilde"] / xi_c
        zn = s["z"] / xi_c
        delta = _vdot(zn, yn)  # bilinear form (conj per scipy convention)
        eps_c = jnp.where(s["epsq"] == 0, 1, s["epsq"])
        first = itn == 1
        pcoef = jnp.where(first, 0.0, s["xi"].astype(b.dtype) * delta / eps_c)
        qcoef = jnp.where(first, 0.0, s["rho"].astype(b.dtype) * delta / eps_c)
        p = m2(yn) - pcoef * s["p"]
        q = m1h(zn) - qcoef * s["q"]
        ptilde = A.matvec(p)
        epsq = _vdot(q, ptilde)
        beta = epsq / jnp.where(delta == 0, 1, delta)
        vtilde = ptilde - beta * v
        y_new = m1(vtilde)
        rho_new = jnp.sqrt(jnp.real(_vdot(y_new, y_new))).astype(rdt)
        wtilde = A.rmatvec(q) - jnp.conj(beta) * w
        z_new = m2h(wtilde)
        xi_new = jnp.sqrt(jnp.real(_vdot(z_new, z_new))).astype(rdt)
        absbeta = jnp.abs(beta).astype(rdt)
        theta_new = rho_new / jnp.where(
            s["gamma"] * absbeta == 0, 1, s["gamma"] * absbeta
        )
        gamma_new = 1.0 / jnp.sqrt(1.0 + theta_new * theta_new)
        eta_new = (
            -s["eta"]
            * s["rho"].astype(b.dtype)
            * (gamma_new * gamma_new).astype(b.dtype)
            / jnp.where(
                beta * (s["gamma"] * s["gamma"]).astype(b.dtype) == 0,
                1,
                beta * (s["gamma"] * s["gamma"]).astype(b.dtype),
            )
        )
        tg2 = ((s["theta"] * gamma_new) ** 2).astype(b.dtype)
        d = eta_new * p + jnp.where(first, 0.0, 1.0) * tg2 * s["d"]
        snew = eta_new * ptilde + jnp.where(first, 0.0, 1.0) * tg2 * s["s"]
        x_n = s["x"] + d
        r_n = s["r"] - snew
        return dict(
            x=x_n, r=r_n, vtilde=vtilde, wtilde=wtilde, y=y_new, z=z_new,
            p=p, q=q, d=d, s=snew, rho=rho_new, xi=xi_new, gamma=gamma_new,
            eta=eta_new, theta=theta_new, epsq=epsq, itn=itn,
        )

    out = _while_with_callback(cond, body, init, callback)
    return out["x"], host_int(out["itn"])


# ---------------------------------------------------------------------------
# LGMRES / GCROT(m,k): augmented-subspace Krylov (scipy drop-in surface
# beyond the reference). Both share one skeleton: per outer cycle, build a
# Krylov basis, augment it with recycled directions, and solve ONE
# minimal-residual least-squares over the whole augmented block — a tall
# [n, m+k] QR, which is exactly the MXU-shaped formulation (the classical
# per-vector Givens update is scalar-serial; the block least squares is a
# matmul). Recycled directions carry their A-images so augmentation costs
# no extra matvecs.
# ---------------------------------------------------------------------------
def _augmented_cycle(A, Mop, r, inner_m, aug):
    """One cycle: Krylov directions from r (right-preconditioned) plus
    ``aug`` = list of (z, Az) pairs. Returns (dx, Adx) minimizing
    ||r - A dx|| over the augmented subspace."""
    n = r.shape[0]
    inner_m = max(1, min(int(inner_m), n - len(aug)))  # subspace <= n
    rnorm = jnp.linalg.norm(r)
    v = r / jnp.where(rnorm == 0, 1, rnorm)
    vs = [v]
    Zs, AZs = [], []
    for _ in range(inner_m):
        z = Mop.matvec(vs[-1]) if Mop is not None else vs[-1]
        w = A.matvec(z)
        Zs.append(z)
        AZs.append(w)
        # two-pass MGS against the Krylov basis (masked-matmul shape)
        Vstack = jnp.stack(vs, axis=1)
        for _ in range(2):
            w = w - Vstack @ (Vstack.conj().T @ w)
        wn = jnp.linalg.norm(w)
        if float(wn) <= 1e-12 * float(rnorm):
            break  # breakdown: subspace is invariant
        vs.append(w / wn)
    for z, az in aug:
        Zs.append(z)
        AZs.append(az)
    Z = jnp.stack(Zs, axis=1)
    AZ = jnp.stack(AZs, axis=1)
    # least squares min ||r - AZ y||: lstsq, not QR+solve — the augmented
    # block can be numerically rank-deficient (converged directions)
    y = jnp.linalg.lstsq(AZ, r)[0]
    dx = Z @ y
    return dx, AZ @ y


@track_provenance
def lgmres(A, b, x0=None, tol=1e-5, atol=0.0, maxiter=1000, M=None,
           callback=None, inner_m=30, outer_k=3):
    """LGMRES (Baker/Jessup/Manteuffel; scipy.sparse.linalg.lgmres
    semantics): restarted GMRES whose restart space is augmented with the
    last ``outer_k`` correction directions, curing restart stagnation.
    Returns (x, info) — info=0 on convergence, else the iteration count
    (scipy's >0 convention)."""
    b = asjnp(b)
    A = make_linear_operator(A)
    if x0 is not None:
        x0 = asjnp(x0)
    b = b.astype(jnp.result_type(
        b.dtype, A.dtype, *(() if x0 is None else (x0.dtype,))
    ))
    Mop = None if M is None else make_linear_operator(M)
    x = jnp.zeros_like(b) if x0 is None else x0.astype(b.dtype)
    bnorm = float(jnp.linalg.norm(b))
    target = max(float(atol), float(tol) * (bnorm if bnorm > 0 else 1.0))
    aug = []  # (z, Az) correction pairs, newest first
    for it in range(int(maxiter)):
        r = b - A.matvec(x)
        if float(jnp.linalg.norm(r)) <= target:
            return x, 0
        dx, adx = _augmented_cycle(A, Mop, r, int(inner_m), aug)
        x = x + dx
        if callback is not None:
            callback(x)
        dn = jnp.linalg.norm(dx)
        if float(dn) > 0 and int(outer_k) > 0:
            # adx IS A dx from the cycle's own images: no extra matvec
            aug = [(dx / dn, adx / dn)] + aug[: int(outer_k) - 1]
    r = b - A.matvec(x)
    return x, (0 if float(jnp.linalg.norm(r)) <= target else int(maxiter))


@track_provenance
def gcrotmk(A, b, x0=None, tol=1e-5, atol=0.0, maxiter=1000, M=None,
            callback=None, m=20, k=None, truncate="oldest"):
    """GCROT(m, k) (Hicken & Zingg / de Sturler; scipy.sparse.linalg
    .gcrotmk semantics): GMRES(m) with a recycled outer subspace U whose
    images C = A U are kept orthonormal; each cycle first projects the
    residual onto C, then runs the inner cycle on the complement.
    Returns (x, info) like scipy (0 = converged)."""
    if k is None:
        k = m
    if truncate not in ("oldest", "smallest"):
        raise ValueError("truncate must be 'oldest' or 'smallest'")
    b = asjnp(b)
    A = make_linear_operator(A)
    if x0 is not None:
        x0 = asjnp(x0)
    b = b.astype(jnp.result_type(
        b.dtype, A.dtype, *(() if x0 is None else (x0.dtype,))
    ))
    Mop = None if M is None else make_linear_operator(M)
    x = jnp.zeros_like(b) if x0 is None else x0.astype(b.dtype)
    bnorm = float(jnp.linalg.norm(b))
    target = max(float(atol), float(tol) * (bnorm if bnorm > 0 else 1.0))
    recycled = []  # (u, c) with c = A u / ||A u||, newest LAST
    for it in range(int(maxiter)):
        r = b - A.matvec(x)
        # oblique projection onto the recycled image space
        for u, c in recycled:
            alpha = jnp.vdot(c, r)
            x = x + alpha * u
            r = r - alpha * c
        if float(jnp.linalg.norm(r)) <= target:
            return x, 0
        dx, adx = _augmented_cycle(
            A, Mop, r, int(m), [(u, c) for u, c in recycled]
        )
        x = x + dx
        if callback is not None:
            callback(x)
        # maintain C orthonormal: Gram-Schmidt the new image against the
        # kept ones, applying the same combination to u so c == A u holds
        unew, cnew = dx, adx
        for u, c in recycled:
            beta = jnp.vdot(c, cnew)
            cnew = cnew - beta * c
            unew = unew - beta * u
        an = jnp.linalg.norm(cnew)
        if float(an) > 1e-12:
            recycled.append((unew / an, cnew / an))
            if len(recycled) > int(k):
                if truncate == "oldest":
                    recycled = recycled[1:]
                else:  # 'smallest': drop the image direction least
                    # aligned with the current correction (heuristic form
                    # of de Sturler's smallest-coefficient truncation;
                    # the newest pair is always kept)
                    scores = [
                        abs(float(jnp.vdot(c, adx))) for _, c in
                        recycled[:-1]
                    ]
                    drop = int(np.argmin(scores))
                    recycled = (
                        recycled[:drop] + recycled[drop + 1:]
                    )
    r = b - A.matvec(x)
    return x, (0 if float(jnp.linalg.norm(r)) <= target else int(maxiter))


# ---------------------------------------------------------------------------
# eigsh (linalg.py:1450) — Lanczos with full reorthogonalization
# ---------------------------------------------------------------------------
def _lanczos_factorization(A, V0, start, ncv, rng, cache):
    """Continue a Lanczos factorization from row ``start`` of ``V0``.

    Rows 0..start of ``V0`` are assumed orthonormal: the locked (thick)
    Ritz block plus the restart residual vector at index ``start`` (plain
    Lanczos is the ``start == 0`` case). Full reorthogonalization against
    ALL previous rows makes the thick-restart couplings implicit — the
    three-term recurrence only ever sees alpha/beta.

    Runs fully ON DEVICE (one compiled fori_loop; VERDICT r2 #5 — the old
    cycle fetched 2 host scalars per step): the [ncv, n] basis lives on
    device, projections are batched dense matvecs (MXU-shaped), and the
    alpha/beta recurrence rides along as device arrays. The host reads the
    (alphas, betas) pair ONCE per cycle — which the projected eigh needs
    on host anyway. Breakdown (an invariant subspace, beta ~ 0) is
    detected from that same read and retried on the host path with a
    random restart vector.

    ``cache`` is the PER-SOLVE dict holding the compiled cycle per
    (start, ncv, dtype) and the dispatch-warm flag — restart cycles reuse
    one XLA program instead of retracing each cycle. (Not cached across
    solves: the jitted closure captures the operator's buffers as
    constants, so a cross-call cache would go stale if the matrix is
    mutated in place between solves.)

    Returns (V, alphas, betas, vres, nmv): ``vres`` is the normalized
    (ncv+1)-th vector — the next cycle's restart residual direction —
    and ``nmv`` the number of operator applications actually performed
    (including warm-up and any breakdown redo)."""
    start = int(start)
    nmv = 0
    try:
        if not cache.get("warm"):
            A.matvec(V0[start])  # warm host-side format dispatch ONCE
            cache["warm"] = True
            nmv += 1
        key = (start, ncv, jnp.dtype(V0.dtype).name)
        run = cache.get(key)
        if run is None:
            run = _build_lanczos_device(A, start, ncv, V0.dtype)
            cache[key] = run
        V, alphas, betas, vres = run(V0)
        nmv += ncv - start
    except (
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
        jax.errors.ConcretizationTypeError,
    ):
        out = _lanczos_host(A, V0, start, ncv, rng)
        return (*out, nmv + (ncv - start))
    ab = _sync_fetch(jnp.stack([alphas, betas]))  # the one per-cycle sync
    alphas, betas = np.real(ab[0]), np.real(ab[1])
    if ncv - start > 1 and float(np.min(betas[start : ncv - 1])) < 1e-12:
        out = _lanczos_host(A, V0, start, ncv, rng)
        return (*out, nmv + (ncv - start))
    return V, alphas, betas, vres, nmv


def _build_lanczos_device(A, start: int, ncv: int, dt):
    rdt = jnp.zeros((), dt).real.dtype

    @jax.jit
    def run(V):
        alphas = jnp.zeros((ncv,), dtype=rdt)
        betas = jnp.zeros((ncv,), dtype=rdt)
        vres = jnp.zeros_like(V[0])

        def body(j, st):
            V, alphas, betas, vres = st
            w = A.matvec(V[j])
            a = jnp.real(jnp.vdot(V[j], w)).astype(rdt)
            alphas = alphas.at[j].set(a)
            w = w - a.astype(dt) * V[j]
            bprev = jnp.where(j > start, betas[jnp.maximum(j - 1, 0)], 0.0)
            w = w - bprev.astype(dt) * V[jnp.maximum(j - 1, 0)]
            mask = (jnp.arange(ncv) <= j).astype(rdt)
            proj = (V.conj() @ w) * mask  # full reorth (+ thick couplings)
            w = w - proj @ V
            bnorm = jnp.linalg.norm(w).astype(rdt)
            betas = betas.at[j].set(bnorm)
            nxt = w / jnp.where(bnorm > 0, bnorm, 1.0).astype(dt)
            jn = jnp.minimum(j + 1, ncv - 1)
            V = V.at[jn].set(jnp.where(j + 1 < ncv, nxt, V[jn]))
            vres = jnp.where(j + 1 < ncv, vres, nxt)
            return V, alphas, betas, vres

        return jax.lax.fori_loop(start, ncv, body, (V, alphas, betas, vres))

    return run


def _lanczos_host(A, V0, start: int, ncv: int, rng):
    """Host-driven fallback: handles breakdown with a random orthonormal
    restart vector (rare — invariant subspace hit)."""
    V = V0
    alphas = np.zeros((ncv,))
    betas = np.zeros((ncv,))
    vres = jnp.zeros_like(V0[0])
    n = V0.shape[1]
    for j in range(start, ncv):
        w = A.matvec(V[j])
        a = float(jnp.real(jnp.vdot(V[j], w)))
        alphas[j] = a
        w = w - a * V[j]
        if j > start:
            w = w - betas[j - 1] * V[j - 1]
        proj = V[: j + 1].conj() @ w  # full reorth (+ thick couplings)
        w = w - proj @ V[: j + 1]
        bnorm = float(jnp.linalg.norm(w))
        betas[j] = bnorm
        if bnorm < 1e-12:
            vv = jnp.asarray(rng.standard_normal(n), dtype=V0.dtype)
            pv = V[: j + 1].conj() @ vv
            vv = vv - pv @ V[: j + 1]
            vv = vv / jnp.linalg.norm(vv)
            betas[j] = 0.0
            if j + 1 < ncv:
                V = V.at[j + 1].set(vv)
            else:
                vres = vv
        elif j + 1 < ncv:
            V = V.at[j + 1].set(w / bnorm)
        else:
            vres = w / bnorm
    return V, alphas, betas, vres


def _select_ritz(w_all, which, k):
    if which in ("LM", "LA"):
        sel = np.argsort(np.abs(w_all) if which == "LM" else w_all)[::-1][:k]
    elif which in ("SM", "SA"):
        sel = np.argsort(np.abs(w_all) if which == "SM" else w_all)[:k]
    else:
        raise ValueError(f"unknown which={which}")
    return np.sort(sel)


@track_provenance
def eigsh(A, k=6, which="LM", v0=None, maxiter=None, tol=0.0, return_eigenvectors=True):
    """Symmetric eigensolver: THICK-restart Lanczos (Wu & Simon) with full
    reorthogonalization.

    Reference analog: thick-restart Lanczos (linalg.py:1450). Each cycle
    continues the factorization past the locked Ritz block; the projected
    matrix is diag(locked thetas) + an arrowhead of residual couplings +
    the new tridiagonal block. Ritz residual estimates |beta_m * s[last]|
    gate convergence against ``tol`` (0 -> machine precision), up to
    ``maxiter`` total matvecs. Keeping the whole wanted block across
    restarts is what makes k > 1 converge in few cycles — a single-vector
    restart rebuilds the other k-1 directions from scratch every cycle.
    """
    A = make_linear_operator(A)
    n = A.shape[0]
    k = min(k, n - 1) if n > 1 else 1
    ncv = min(max(2 * k + 1, 20), n)
    if maxiter is None:
        maxiter = 10 * n
    rng = np.random.default_rng(0)
    # basis dtype follows the operator (and any user v0): a Hermitian
    # complex A needs a complex Lanczos basis — a real one would silently
    # project onto Re(A)'s action
    base = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dt = jnp.result_type(base, A.dtype)
    if v0 is not None:
        dt = jnp.result_type(dt, asjnp(v0).dtype)
    if v0 is None:
        v = jnp.asarray(rng.standard_normal(n), dtype=dt)
    else:
        v = asjnp(v0).astype(dt)
    v = v / jnp.linalg.norm(v)
    eff_tol = tol if tol > 0 else float(np.finfo(np.dtype(dt)).eps) * 10
    matvecs = 0
    w = s_sel = V = None
    thetas = barr = None  # locked Ritz values + arrowhead couplings
    l = 0  # thick block size (0 = plain first cycle)
    V0 = jnp.zeros((ncv, n), dtype=dt).at[0].set(v)
    prev_worst = np.inf
    cycle_cache: dict = {}  # compiled cycles per (start, ncv) for THIS solve
    while matvecs < int(maxiter) or w is None:
        V, alphas, betas, vres, nmv = _lanczos_factorization(
            A, V0, l, ncv, rng, cycle_cache
        )
        matvecs += nmv
        # projected matrix: locked diag + arrowhead couplings + new tridiag
        T = np.zeros((ncv, ncv))
        if l:
            T[:l, :l] = np.diag(thetas)
            T[:l, l] = barr
            T[l, :l] = barr
        aa, bb = alphas[l:], betas[l : ncv - 1]
        T[l:, l:] = np.diag(aa)
        if bb.size:
            T[l:, l:] += np.diag(bb, 1) + np.diag(bb, -1)
        w_all, s_full = np.linalg.eigh(T)
        sel = _select_ritz(w_all, which, k)
        w = w_all[sel]
        s_sel = s_full[:, sel]
        # Ritz residual estimates: ||A y - theta y|| = |beta_m| * |s[last]|
        resid = np.abs(betas[ncv - 1]) * np.abs(s_sel[-1, :])
        scale = max(np.max(np.abs(w_all)), 1e-30)
        if np.all(resid <= eff_tol * scale) or ncv >= n:
            break
        # stall safety valve: thick restarts converge fast, but if the worst
        # residual stops shrinking, grow the basis — at ncv == n the cycle
        # is an exact dense factorization, so termination is guaranteed
        worst = float(np.max(resid))
        if worst > 0.5 * prev_worst:
            ncv = min(2 * ncv, n)
        prev_worst = worst
        # THICK restart: lock the k wanted Ritz vectors, put the residual
        # direction right after them, continue from there
        l = min(k, ncv - 2)
        lock = s_full[:, sel[:l]]
        Y = jnp.asarray(lock.T, dtype=dt) @ V  # [l, n] locked Ritz block
        thetas = w_all[sel[:l]]
        barr = betas[V.shape[0] - 1] * np.real(lock[-1, :])  # couplings
        V0 = jnp.zeros((ncv, n), dtype=dt)
        V0 = V0.at[:l].set(Y)
        V0 = V0.at[l].set(vres)
    if not return_eigenvectors:
        return w
    Y = jnp.asarray(s_sel.T) @ V  # [k, n]
    return w, Y.T


@track_provenance
def norm(A, ord=None, axis=None):
    """Sparse matrix/vector norms (scipy.sparse.linalg.norm surface).

    Beyond the reference (which exposes no norm): Frobenius (default),
    ord in {1, -1, inf, -inf, 'fro'} for matrices, and the standard
    vector norms when ``axis`` selects one dimension. Computed from the
    stored entries — implicit zeros contribute nothing to any of these.
    """
    from .base import SparseArray

    if not isinstance(A, SparseArray):
        raise TypeError("norm expects a sparse array")
    from .ops.elementwise import csr_sum

    C = A.tocsr()
    data = jnp.abs(asjnp(C.data))
    indptr, indices = asjnp(C.indptr), asjnp(C.indices)
    m, n = C.shape
    if axis is None:
        if ord in (None, "fro", "f"):
            return jnp.sqrt(jnp.sum(data * data))
        if ord in (1, -1):
            sums = csr_sum(indptr, indices, data, C.shape, axis=0)
        elif ord in (np.inf, -np.inf):
            sums = csr_sum(indptr, indices, data, C.shape, axis=1)
        else:
            raise ValueError(f"invalid norm order {ord!r} for sparse matrices")
        return jnp.max(sums) if ord in (1, np.inf) else jnp.min(sums)
    # vector norm along one axis -> dense 1-D result
    if axis not in (0, -2, 1, -1):
        raise ValueError(f"invalid axis {axis}")
    ax = 0 if axis in (0, -2) else 1
    if ord in (None, 2):
        return jnp.sqrt(csr_sum(indptr, indices, data * data, C.shape, axis=ax))
    if ord == 1:
        return csr_sum(indptr, indices, data, C.shape, axis=ax)
    if ord == np.inf:
        if ax == 0:
            ids, length = indices.astype(jnp.int32), n
        else:
            from .ops.coords import expand_rows

            ids = expand_rows(indptr, data.shape[0]).astype(jnp.int32)
            length = m
        # empty lines: segment_max fills dtype-min; the implicit-zero
        # answer is 0 (data is |.|, so clamping at 0 is exact)
        return jnp.maximum(
            jax.ops.segment_max(data, ids, num_segments=length), 0
        )
    raise ValueError(f"invalid norm order {ord!r} along an axis")


def _onenorm_est(A_op, dt, iters: int = 4):
    """Higham/Hager 1-norm power estimator for a LinearOperator (the core
    of onenormest, without the parallel-column refinement): alternate
    x -> y = A x, xi = sign(y), z = A^H xi, move x to the unit vector at
    argmax |z|. A lower bound that is almost always tight in practice.
    Returns (est, j): the estimate and the COLUMN achieving the best
    unit-vector probe (the certificate column — ||A e_j||_1 == est
    whenever the best probe was a unit vector; the uniform warm-up probe
    never exceeds the best column by convexity)."""
    n = A_op.shape[1]
    x = jnp.full((n,), 1.0 / n, dtype=dt)
    est = 0.0
    best_j = 0
    cur_j = None  # which unit column x currently is (None: uniform start)
    for it in range(iters):
        y = A_op.matvec(x)
        est_new = float(jnp.sum(jnp.abs(y)))
        # always take the first argmax move: the uniform start vector can
        # cancel to est 0 on sign-alternating operators, and breaking
        # before probing a unit vector would report ~0 for ||A||_1 = 4
        if it > 0 and est_new <= est:
            break
        if est_new >= est and cur_j is not None:
            best_j = cur_j
        est = max(est, est_new)
        xi = jnp.where(
            y == 0, 1.0, y / jnp.where(jnp.abs(y) == 0, 1.0, jnp.abs(y))
        ).conj()
        z = A_op.rmatvec(xi.astype(dt))
        cur_j = int(jnp.argmax(jnp.abs(z)))
        x = jnp.zeros((n,), dtype=dt).at[cur_j].set(1.0)
        if it == 0:
            best_j = cur_j  # first candidate even if the uniform est wins
    return max(est, 1e-300), best_j


@track_provenance
def onenormest(A, t: int = 2, itmax: int = 5, compute_v: bool = False, compute_w: bool = False):
    """Estimate the 1-norm of A (scipy.sparse.linalg.onenormest subset).

    Sparse inputs get the EXACT 1-norm (one column-sum reduction — cheaper
    than any estimate); LinearOperator inputs run the Higham/Hager power
    estimation. ``compute_v``/``compute_w`` return scipy's certificate:
    v a unit vector with w = A v and est == ||w||_1.
    """
    from .base import SparseArray

    A_op = make_linear_operator(A)
    n = A_op.shape[1]
    if isinstance(A, SparseArray):
        C = A.tocsr()
        sums = jax.ops.segment_sum(
            jnp.abs(asjnp(C.data)), asjnp(C.indices).astype(jnp.int32),
            num_segments=n,
        )
        j = int(jnp.argmax(sums))
        est = float(jnp.max(sums))
    else:
        dt = jnp.dtype(A_op.dtype)
        est, j = _onenorm_est(A_op, dt, iters=itmax)
    if not (compute_v or compute_w):
        return est
    v = jnp.zeros((n,), dtype=A_op.dtype).at[j].set(1.0)
    w = A_op.matvec(v)
    out = [float(jnp.sum(jnp.abs(w)))]  # certified: est == ||A v||_1
    if compute_v:
        out.append(v)
    if compute_w:
        out.append(w)
    return tuple(out)


# Al-Mohy & Higham (2011) theta values for the truncated Taylor degrees
# used by expm_multiply's (m*, s) selection — public constants (the same
# table scipy carries).
_EXPM_THETA = {
    5: 2.4e-1, 10: 1.0, 15: 2.2, 20: 3.6, 25: 4.9, 30: 6.3,
    35: 7.7, 40: 9.1, 45: 10.0, 50: 11.0, 55: 12.0,
}


@track_provenance
def matrix_power(A, power: int):
    """A**power for sparse A (scipy.sparse.linalg.matrix_power subset:
    nonnegative integer powers), via binary exponentiation over the
    device SpGEMM — log2(power) sparse products."""
    from .base import SparseArray
    from .module import identity

    import operator

    if not isinstance(A, SparseArray):
        raise TypeError("matrix_power expects a sparse array")
    m, n = A.shape
    if m != n:
        raise ValueError("matrix_power expects a square matrix")
    power = operator.index(power)  # rejects 2.5 etc. like scipy
    if power < 0:
        raise ValueError("negative powers are not supported (no sparse inv)")
    if power == 0:
        return identity(n, dtype=A.dtype, format="csr")
    result = None
    base = A.tocsr()
    while power:
        if power & 1:
            result = base if result is None else (result @ base).tocsr()
        power >>= 1
        if power:
            base = (base @ base).tocsr()
    # power == 1 aliases the input (csr.tocsr() returns self): copy so
    # callers mutating the result cannot corrupt A
    if result is A or result is A.tocsr():
        result = result.copy()
    return result


@track_provenance
def expm_multiply(A, B, t: float = 1.0, start=None, stop=None, num=None, endpoint=True, _a1=None):
    """``e^(tA) @ B`` without forming the matrix exponential.

    Beyond the reference: the action of the exponential is THE quantum
    time-evolution primitive (psi(t) = e^{-iHt} psi0 — an alternative to
    the RK integrator in ``integrate``). Truncated-Taylor with the
    Al-Mohy & Higham (m*, s) selection driven by the exact sparse 1-norm
    (one column-sum reduction); each of the s stages runs m SpMV steps on
    device. Handles complex t*A; B may be a vector or a matrix.

    scipy's time-grid form: with ``start``/``stop``/``num`` the result is
    stacked over ``numpy.linspace(start, stop, num, endpoint=endpoint)``
    — each interval advances the previous state, so a whole evolution
    trajectory costs one pass.
    """
    from .base import SparseArray

    if start is not None or stop is not None or num is not None:
        if num is None or stop is None:
            raise ValueError("the time-grid form needs stop= and num=")
        if t != 1.0:
            raise ValueError(
                "t= cannot be combined with the start/stop/num grid form"
            )
        start = 0.0 if start is None else start
        ts = np.linspace(start, stop, int(num), endpoint=endpoint)
        # one 1-norm evaluation serves every interval (uniform linspace:
        # all the chained dt's are identical)
        A_op0 = make_linear_operator(A)
        dt0 = jnp.result_type(asjnp(B).dtype, A_op0.dtype, type(float(np.real(ts[-1]))))
        if isinstance(A, SparseArray):
            a1 = float(np.asarray(jnp.real(norm(A, ord=1))))
        else:
            a1 = _onenorm_est(A_op0, dt0)[0]
        out = [expm_multiply(A, B, t=float(ts[0]), _a1=a1)]
        for i in range(1, len(ts)):
            out.append(
                expm_multiply(A, out[-1], t=float(ts[i] - ts[i - 1]), _a1=a1)
            )
        return jnp.stack(out)

    A_op = make_linear_operator(A)
    B = asjnp(B)
    dt = jnp.result_type(B.dtype, A_op.dtype, type(t))
    B = B.astype(dt)
    if _a1 is not None:
        a_norm = _a1 * abs(t)
    elif isinstance(A, SparseArray):
        a_norm = float(np.asarray(jnp.real(norm(A, ord=1)))) * abs(t)
    else:
        # LinearOperator input: Higham-style 1-norm power estimation on
        # |.|-structure (matvec of ones would cancel signs and can
        # underestimate arbitrarily — e.g. [[2,-2],[-2,2]] @ ones == 0)
        a_norm = _onenorm_est(A_op, dt)[0] * abs(t)
    if a_norm == 0 or B.size == 0:
        return B
    # pick (m, s): smallest cost s*m with ||tA||_1 / s <= theta_m
    best = None
    for mdeg, theta in _EXPM_THETA.items():
        s = max(int(np.ceil(a_norm / theta)), 1)
        cost = s * mdeg
        if best is None or cost < best[0]:
            best = (cost, mdeg, s)
    _, mdeg, s = best
    scale = jnp.asarray(t / s, dtype=dt)
    tol = float(np.finfo(np.dtype(jnp.zeros((), dt).real.dtype)).eps) / 2

    try:
        # device-resident stage: the m-term Taylor loop (with the AH
        # two-consecutive-term stopping test) runs as one lax.while_loop
        # per stage — zero mid-series host syncs; stages chain on device
        apply = A_op.matvec if B.ndim == 1 else A_op.matmat
        apply(jnp.zeros_like(B))  # warm dispatch with the operand shape
        # (probing matvec on a matmat-only operator would raise)

        @jax.jit
        def stage(F):
            def cond(st):
                _term, _out, _c_prev, j, done = st
                return (j <= mdeg) & ~done

            def body(st):
                term, out, c_prev, j, done = st
                term = apply(term) * (scale / j.astype(scale.dtype))
                out = out + term
                # Al-Mohy & Higham's TWO-consecutive-term test (as in
                # scipy): a single dipping term must not truncate early
                c = jnp.max(jnp.abs(term))
                done = (c_prev + c) <= tol * jnp.max(jnp.abs(out))
                return term, out, c, j + 1, done

            big = jnp.asarray(np.inf, jnp.zeros((), dt).real.dtype)
            _t, out, _c, _j, _d = jax.lax.while_loop(
                cond, body, (F, F, big, jnp.int32(1), jnp.bool_(False))
            )
            return out

        F = B
        for _ in range(s):
            F = stage(F)
        return F
    except (
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
        jax.errors.ConcretizationTypeError,
    ):
        pass
    # host-driven fallback for untraceable operators
    F = B
    for _ in range(s):
        term = F
        out = F
        c_prev = np.inf
        for j in range(1, mdeg + 1):
            term = A_op.matvec(term) if term.ndim == 1 else A_op.matmat(term)
            term = term * (scale / j)
            out = out + term
            c = float(jnp.max(jnp.abs(term)))
            if c_prev + c <= tol * float(jnp.max(jnp.abs(out))):
                break
            c_prev = c
        F = out
    return F


@track_provenance
def svds(A, k: int = 6, which: str = "LM", return_singular_vectors: bool = True):
    """Largest-k singular triplets via thick-restart Lanczos on the normal
    operator (beyond the reference's surface; scipy.sparse.linalg.svds
    API subset — which='LM' only, the well-conditioned direction).

    Tall matrices run eigsh on C = A^H A (n x n, matvec = two sparse
    products), take sigma = sqrt(max(eig, 0)) and recover U = A V / sigma;
    wide matrices delegate to the adjoint. Two hygiene rules an iterative
    normal-operator approach needs: (a) when min(m, n) is no bigger than
    the Lanczos basis would be anyway, a DENSE SVD is exact and cheaper —
    and avoids accepting unconverged Ritz junk when k exceeds rank(A);
    (b) singular values below the numpy rank cutoff are reported as
    exactly 0 with zeroed vector columns.
    """
    if which != "LM":
        raise NotImplementedError("svds supports which='LM'")
    A_op = make_linear_operator(A)
    m, n = A_op.shape
    if not 1 <= k <= min(m, n) - 1:  # scipy's bound, raised loudly
        raise ValueError(
            f"k={k} must satisfy 1 <= k <= min(M, N) - 1 = {min(m, n) - 1}"
        )
    if m < n:
        # wide: svds of the adjoint, mapped back (A = (U' s Vh')^H of A^H)
        adj = LinearOperator(
            (n, m), matvec=A_op.rmatvec, rmatvec=A_op.matvec,
            dtype=A_op.dtype,
        )
        out = svds(adj, k=k, return_singular_vectors=return_singular_vectors)
        if not return_singular_vectors:
            return out
        Ua, s, Vha = out
        return Vha.conj().T, s, Ua.conj().T

    rdt = np.dtype(jnp.zeros((), A_op.dtype).real.dtype)
    ncv_would_be = min(max(2 * k + 1, 20), n)
    if n <= ncv_would_be:
        # the Lanczos basis would span the whole space: dense SVD is exact
        eye = jnp.eye(n, dtype=A_op.dtype)
        dense = A_op.matmat(eye)
        U, s, Vh = jnp.linalg.svd(dense, full_matrices=False)
        U, s, Vh = U[:, :k], np.asarray(s[:k]), Vh[:k]
        cutoff = max(m, n) * np.finfo(rdt).eps * (float(s[0]) if len(s) else 0.0)
        live = s > cutoff
        s = np.where(live, s, 0.0)
        if not return_singular_vectors:
            return s
    else:
        C = LinearOperator(
            (n, n),
            matvec=lambda x: A_op.rmatvec(A_op.matvec(x)),
            dtype=A_op.dtype,
        )
        w, V = eigsh(C, k=k, which="LA")
        w = np.maximum(np.asarray(w), 0.0)
        order = np.argsort(w)[::-1]
        s = np.sqrt(w[order])
        # rank cutoff BEFORE the vector recovery: sub-cutoff values are
        # zeros and their vectors meaningless junk
        cutoff = max(m, n) * np.finfo(rdt).eps * (float(s[0]) if len(s) else 0.0)
        live = s > cutoff
        s = np.where(live, s, 0.0)
        if not return_singular_vectors:
            return s
        V = jnp.asarray(np.asarray(V)[:, order])
        safe = jnp.asarray(np.where(live, np.where(s > 0, s, 1.0), 1.0), dtype=A_op.dtype)
        U = A_op.matmat(V) / safe[None, :]
        Vh = V.conj().T
    keep = jnp.asarray(live.astype(rdt))
    return U * keep[None, :], s, Vh * keep[:, None]


__all__ = [
    "LinearOperator",
    "IdentityOperator",
    "aslinearoperator",
    "make_linear_operator",
    "cg",
    "cgs",
    "bicg",
    "bicgstab",
    "gmres",
    "ir",
    "batched_ir",
    "lsqr",
    "eigsh",
    "spsolve",
    "cg_axpby",
    "norm",
    "expm_multiply",
    "matrix_power",
    "svds",
    "onenormest",
    # round-3 scipy.sparse.linalg drop-in surface
    "minres",
    "lsmr",
    "tfqmr",
    "qmr",
    "SuperLU",
    "splu",
    "spilu",
    "SpILU",
    "ilu0",
    "ic0",
    "factorized",
    "inv",
    "expm",
    "spsolve_triangular",
    "is_sptriangular",
    "spbandwidth",
    "eigs",
    "lobpcg",
    "LaplacianNd",
    "ArpackError",
    "ArpackNoConvergence",
    "MatrixRankWarning",
    "use_solver",
    "lgmres",
    "gcrotmk",
    "funm_multiply_krylov",
]

from ._laplacian import LaplacianNd  # noqa: F401,E402


def _legacy_namespace(name, symbols):
    """scipy.sparse.linalg keeps deprecated submodule namespaces
    (``linalg.isolve.cg`` etc.); mirror them as module objects so
    drop-in callers that still import through them keep working."""
    import sys
    import types

    mod = types.ModuleType(f"{__name__}.{name}")
    g = globals()
    for s in symbols:
        if s in g:
            setattr(mod, s, g[s])
    # register so `from sparse_tpu.linalg.isolve import cg` resolves even
    # though linalg is a plain module, not a package
    sys.modules[mod.__name__] = mod
    return mod


isolve = _legacy_namespace(
    "isolve",
    ["cg", "cgs", "bicg", "bicgstab", "gmres", "lgmres", "gcrotmk",
     "minres", "qmr", "tfqmr", "lsqr", "lsmr"],
)
dsolve = _legacy_namespace(
    "dsolve",
    ["spsolve", "splu", "spilu", "factorized", "spsolve_triangular",
     "MatrixRankWarning", "use_solver"],
)
eigen = _legacy_namespace(
    "eigen",
    ["eigs", "eigsh", "lobpcg", "svds", "ArpackError",
     "ArpackNoConvergence"],
)
interface = _legacy_namespace(
    "interface", ["LinearOperator", "aslinearoperator"]
)
matfuncs = _legacy_namespace(
    "matfuncs", ["expm", "inv", "expm_multiply", "matrix_power"]
)
