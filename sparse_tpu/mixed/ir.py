"""Batched mixed-precision iterative refinement: the ``ir`` solver.

The classic three-precision scheme (Wilkinson; the Ginkgo batched line,
PAPERS.md §2) as ONE fixed-shape compiled program per bucket:

    repeat (outer, f64):
        R = b - A x                      # wide residual, wide values
        freeze lanes with ||R|| < tol    # per-lane masks, bit-stable
        solve A d = R / ||R||  (inner)   # reduced storage/compute
        x += ||R|| * d                   # wide correction

The inner solve is the SAME masked batched Krylov loop the exact
bucket programs run (:func:`sparse_tpu.batch.krylov._cg_loop` /
``_bicgstab_loop``) — at the policy's storage/compute dtypes, with a
fixed per-sweep iteration budget and a constant absolute tolerance
``eta`` (the residual is scaled to unit norm before the downcast, so
f32/bf16 dynamic range is never the limit). Lanes frozen by the outer
loop enter the inner sweep with an instant-converge tolerance (the pad
lane trick from :mod:`sparse_tpu.batch.bucket`), so a finished lane's
iterate is bit-stable while its neighbors refine.

Everything is ``lax.while_loop`` over fixed shapes: the whole
refinement — outer residuals, downcasts, inner sweeps, corrections —
compiles into one bucket program, so the serving dispatch/caching/vault
machinery see it exactly like any other solver loop.

Accuracy contract (docs/performance.md "Mixed precision"): IR converges
to the f64-accurate solution while ``cond(A) * eps_storage < 1`` —
always true for f32 storage on anything CG itself can solve, true for
bf16 storage only on well-conditioned (or strongly preconditioned)
operators. ``scripts/f64_oracle.py`` is the pinned oracle; the outer
loop's per-lane f64 residual test is the verification built into every
solve.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .policy import EXACT, default_eta, inner_dtypes, outer_dtype

#: instant-converge inner tolerance for outer-frozen lanes (the pad-lane
#: contract of batch.bucket: any residual passes at the first test)
BIG_TOL = 1e30


def ir_loop(matvec_wide, matvec_low, b, X0, tol, maxiter,
            conv_test_iters, inner_iters: int, max_outer: int,
            eta: float, inner_dt, Mvec=None, solver: str = "cg",
            lane_reduce=None):
    """Masked batched iterative-refinement core (pure jnp, jit-safe).

    ``matvec_wide`` applies A at f64 (the outer residual), ``matvec_low``
    at the policy's reduced storage/compute dtypes (the inner sweep).
    ``b``/``X0`` are ``(B, n)``; ``tol`` is the per-lane ABSOLUTE
    residual-norm target (the same contract as the exact loops).
    ``maxiter`` bounds total inner iterations per lane; ``max_outer``
    statically bounds refinement sweeps. ``Mvec`` right-preconditions
    the inner sweep at the inner dtype. ``lane_reduce`` is the
    mesh-sharded all-converged exit hook (see ``krylov._cg_loop``) and
    is threaded into BOTH the outer loop's exit and the inner sweeps.

    Returns ``(X, iters, resid2, converged, outer)``: per-lane total
    inner iterations, final f64 squared residual norms, convergence
    flags, and the shared outer sweep count (a ``()`` int32 — the
    ``mixed.ir_outer_iters`` evidence).

    Divergence safeguard: refinement contracts only while
    ``cond(A) * eps_storage < 1``; outside that regime (bf16 storage on
    an ill-conditioned operator) the corrections GROW the residual. The
    loop therefore keeps each lane's best-so-far iterate and freezes a
    lane whose f64 residual stops improving — it returns the best
    iterate, reported unconverged, instead of a diverged one. On the
    serving path that unconverged flag is exactly what trips the
    promote_dtype requeue rung.
    """
    from ..batch import krylov

    wdt = outer_dtype()
    idt = jnp.dtype(inner_dt)
    rdt = jnp.zeros((), idt).real.dtype  # inner tolerance dtype
    bw = jnp.asarray(b).astype(wdt)
    Xw = jnp.asarray(X0).astype(wdt)
    B = bw.shape[0]
    tol2 = jnp.broadcast_to(jnp.asarray(tol, wdt), (B,)) ** 2
    inner_loop = (
        krylov._cg_loop if solver == "cg" else krylov._bicgstab_loop
    )
    any_active = jnp.any if lane_reduce is None else lane_reduce
    eta_t = jnp.asarray(eta, rdt)

    def body(st):
        Xw, Xb, rb2, active, iters, outer = st
        R = bw - matvec_wide(Xw)
        rn2 = jnp.real(krylov._bdot(R, R))
        # accept-if-better: the best iterate/residual pair is what the
        # loop ultimately returns
        improved = rn2 < rb2
        am_i = (active & improved)[:, None]
        Xb = jnp.where(am_i, Xw, Xb)
        rb2 = jnp.where(active & improved, rn2, rb2)
        active = active & ~(rb2 < tol2)
        # divergence/stagnation freeze: no f64 progress this sweep —
        # the reduced-precision correction is not contracting
        active = active & improved
        nrm = jnp.sqrt(rn2)
        nrm_safe = jnp.where(nrm == 0, 1.0, nrm)
        # unit-norm downcast: the inner sweep always sees an O(1)
        # right-hand side, so reduced dynamic range never underflows
        Rs = (R / nrm_safe[:, None]).astype(idt)
        # adaptive inner target: stop the sweep at the OUTER target
        # (with a 2x safety margin for the downcast error) when that is
        # looser than eta — the last sweep never over-solves a digit
        # the caller didn't ask for
        need = (0.5 * jnp.sqrt(tol2) / nrm_safe).astype(rdt)
        in_tol = jnp.maximum(eta_t, need)
        in_tol = jnp.where(active, in_tol, jnp.asarray(BIG_TOL, rdt))
        D, it_in, _r2, _cv = inner_loop(
            matvec_low, Rs, jnp.zeros_like(Rs), in_tol,
            inner_iters, conv_test_iters, Mvec=Mvec,
            lane_reduce=lane_reduce,
        )
        dw = D.astype(wdt) * nrm[:, None]
        am = active[:, None]
        Xw = jnp.where(am, Xw + dw, Xw)
        iters = iters + jnp.where(active, it_in, 0)
        # budget freeze: a lane out of total inner budget stops
        # correcting (it keeps its best iterate, reported unconverged)
        active = active & (iters < maxiter)
        return Xw, Xb, rb2, active, iters, outer + 1

    def cond(st):
        active, outer = st[3], st[5]
        return (outer < max_outer) & any_active(active)

    st = (
        Xw,
        Xw,
        jnp.full((B,), jnp.inf, wdt),
        jnp.ones((B,), bool),
        jnp.zeros((B,), jnp.int32),
        jnp.zeros((), jnp.int32),
    )
    Xw, Xb, rb2, _active, iters, outer = jax.lax.while_loop(cond, body, st)
    # final accept-if-better over the last (un-evaluated) correction
    Rf = bw - matvec_wide(Xw)
    rnf = jnp.real(krylov._bdot(Rf, Rf))
    better = rnf < rb2
    X_out = jnp.where(better[:, None], Xw, Xb)
    r2_out = jnp.where(better, rnf, rb2)
    return X_out, iters, r2_out, r2_out < tol2, outer


def _shared_csr_matvecs(A, storage_dt):
    """``(mv_wide, mv_low)`` for ONE host CSR matrix shared by every
    lane: f64 values for the outer residual, policy-storage values for
    the inner sweep, both through the jit-safe segment SpMV (explicit
    ``acc_dtype`` widening on the reduced side)."""
    from ..ops import spmv as spmv_ops
    from ..utils import asjnp

    if hasattr(A, "tocsr") and not hasattr(A, "indptr"):
        A = A.tocsr()
    indptr = asjnp(np.asarray(A.indptr))
    indices = asjnp(np.asarray(A.indices))
    data = np.asarray(A.data)
    if np.dtype(data.dtype).kind == "c":
        raise ValueError("iterative refinement is real-arithmetic; "
                         "complex operators solve under policy 'exact'")
    m = int(A.shape[0])
    vals_w = jnp.asarray(data.astype(np.float64))
    vals_l = jnp.asarray(data.astype(np.float32)).astype(
        jnp.dtype(storage_dt)
    )
    _storage, compute_dt = (storage_dt, np.float32)

    def mk(vals, acc_dt):
        def mv(X):
            return jax.vmap(
                lambda x: spmv_ops.csr_spmv_segment(
                    indptr, indices, vals, x, m, acc_dtype=acc_dt
                )
            )(X)

        return mv

    return mk(vals_w, None), mk(vals_l, compute_dt)


def _operator_matvecs(A, policy: str):
    """``(matvec_wide, matvec_low)``: a csr_array/scipy matrix (shared
    by all lanes), a :class:`~sparse_tpu.batch.operator.BatchedCSR`
    (per-lane values, downcast through ``with_values``), or an explicit
    ``(A_wide, A_low)`` pair of callables/batched operators for callers
    that build the two precisions themselves (the f64_oracle's DIA
    planes)."""
    from ..batch.operator import BatchedCSR, as_batched_matvec

    storage_dt, _compute_dt = inner_dtypes(policy)
    if isinstance(A, tuple) and len(A) == 2:
        wide, low = A
        return as_batched_matvec(wide), as_batched_matvec(low)
    if isinstance(A, BatchedCSR):
        try:
            # pack the pattern EAGERLY (host context): the traced
            # matvec's kernel choice then never depends on whether an
            # earlier call already packed — repeat solves are
            # bit-reproducible kernel-wise
            A.pattern.sell_pack()
        except Exception:  # noqa: BLE001 - segment path still works
            pass
        wdt = outer_dtype()
        return (
            A.with_values(A.values.astype(wdt)).matvec,
            A.with_values(A.values.astype(jnp.dtype(storage_dt))).matvec,
        )
    if hasattr(A, "indptr") or hasattr(A, "tocsr"):
        return _shared_csr_matvecs(A, storage_dt)
    raise TypeError(
        f"cannot build mixed-precision matvecs from {type(A).__name__}; "
        "pass a CSR matrix, a BatchedCSR, or an (A_wide, A_low) pair"
    )


def ir_solve(A, b, x0=None, tol=1e-8, maxiter=None, M=None,
             policy: str = "f32ir", conv_test_iters: int = 25,
             inner_iters: int | None = None, max_outer: int | None = None,
             eta: float | None = None, solver: str = "cg"):
    """One-shot (B=1 or batched) mixed-precision IR solve.

    ``A`` is a csr_array/scipy matrix (downcast internally), a
    ``BatchedCSR`` stack, or an explicit ``(A_wide, A_low)`` pair of
    batched matvecs; ``b`` is ``(n,)`` or ``(B, n)``. Absolute
    ``||r|| < tol`` per lane, tested in f64 — the same stopping
    contract as :func:`sparse_tpu.linalg.cg`.

    Returns ``(X, info)`` with
    :class:`~sparse_tpu.batch.krylov.BatchedSolveInfo` extended by
    ``info.outer`` (refinement sweeps). 1-D ``b`` returns 1-D ``x``.
    """
    from ..batch import krylov
    from ..config import settings
    from ..telemetry import _metrics

    policy = str(policy)
    if policy == EXACT:
        raise ValueError("ir_solve needs a reduced policy ('f32ir' | "
                         "'bf16ir'); exact solves go through linalg.cg")
    if solver not in ("cg", "bicgstab"):
        raise ValueError("ir wraps 'cg' or 'bicgstab' inner sweeps")
    mv_w, mv_l = _operator_matvecs(A, policy)
    b = jnp.asarray(b)
    if jnp.dtype(b.dtype).kind == "c":
        raise ValueError("iterative refinement is real-arithmetic; "
                         "complex systems solve under policy 'exact'")
    squeeze = b.ndim == 1
    if squeeze:
        b = b[None, :]
    _B, n = b.shape
    if maxiter is None:
        maxiter = n * 10
    X0 = (
        jnp.zeros(b.shape, outer_dtype()) if x0 is None
        else jnp.asarray(x0).astype(outer_dtype())
    )
    if X0.ndim == 1:
        X0 = X0[None, :]
    _storage_dt, compute_dt = inner_dtypes(policy)
    if inner_iters is None:
        inner_iters = settings.ir_inner or max(
            8 * conv_test_iters, min(int(n), 4000)
        )
    if max_outer is None:
        max_outer = settings.ir_outer
    if eta is None:
        eta = default_eta(policy)
    Mvec = None
    if M is not None:
        from ..batch.operator import as_batched_matvec

        Mvec = as_batched_matvec(M)
    X, iters, rn2, conv, outer = ir_loop(
        mv_w, mv_l, b, X0, tol, int(maxiter), int(conv_test_iters),
        int(inner_iters), int(max_outer), float(eta), compute_dt,
        Mvec=Mvec, solver=solver,
    )
    _metrics.counter(
        "mixed.ir_outer_iters",
        help="iterative-refinement outer sweeps across all IR solves",
    ).inc(int(outer))
    info = krylov.BatchedSolveInfo(iters, rn2, conv)
    info.outer = int(outer)
    krylov._solve_event("ir", info, n)
    if squeeze:
        return X[0], info
    return X, info
