"""Mixed precision as the fast path (ISSUE 15, ROADMAP item 2).

Two halves:

* :mod:`~sparse_tpu.mixed.policy` — :class:`DtypePolicy`, the
  per-(pattern, solver, bucket, dtype) precision selector
  (``SPARSE_TPU_DTYPE`` / ``SolveSession(dtype_policy=)`` /
  ``submit(dtype_policy=)``), its ``.P<policy>`` program-key suffix
  ('exact' keeps historic keys byte-identical) and the promote rung the
  health-monitor escalation rides.
* :mod:`~sparse_tpu.mixed.ir` — the batched f64 iterative-refinement
  outer loop over reduced-precision inner Krylov sweeps, compiled as
  one fixed-shape bucket program, plus the one-shot :func:`ir_solve`
  entry point (``linalg.ir`` / ``batch.krylov.batched_ir`` wrap it).

See docs/performance.md "Mixed precision" for the policy table and the
accuracy contract.
"""

from .ir import ir_loop, ir_solve  # noqa: F401
from .policy import (  # noqa: F401
    EXACT,
    IR_SOLVERS,
    POLICIES,
    DtypePolicy,
    add_promote_listener,
    canonical_policy,
    default_eta,
    inner_dtypes,
    key_suffix,
    outer_dtype,
    remove_promote_listener,
)

__all__ = [
    "DtypePolicy", "EXACT", "IR_SOLVERS", "POLICIES",
    "add_promote_listener", "canonical_policy", "default_eta",
    "inner_dtypes", "ir_loop", "ir_solve", "key_suffix", "outer_dtype",
    "remove_promote_listener",
]
