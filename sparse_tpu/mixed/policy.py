"""DtypePolicy: which precision a bucket program solves at.

Mixed precision as the fast path (ISSUE 15, ROADMAP item 2): instead of
solving end-to-end at the request dtype — and only *promoting* precision
as a resilience fallback — the serving stack can run the Krylov sweep at
a REDUCED storage/compute precision and recover full accuracy through an
f64 iterative-refinement outer loop (:mod:`sparse_tpu.mixed.ir`). The
policy object resolves which buckets get that treatment.

The resolution ladder (most specific wins), mirroring
:class:`~sparse_tpu.precond.policy.PrecondPolicy`:

1. per-ticket override (``SolveSession.submit(dtype_policy=...)``) —
   lanes with different overrides never share a bucket (the flush group
   key carries the override, like the dtype and the precond override);
2. per-session (``SolveSession(dtype_policy=...)``);
3. the environment (``SPARSE_TPU_DTYPE`` — '' / 'exact' keeps every
   historic program key, jaxpr and numeric byte-identical).

Policies:

``exact``
    Solve at the request dtype (the historic path; no key suffix).
``f32ir``
    Inner Krylov sweep stored AND computed in f32, outer f64
    residual-and-correct loop. The serving default under ``auto`` for
    f64 requests: half the HBM traffic per inner iteration, full f64
    accuracy from the refinement loop.
``bf16ir``
    Values stored in bfloat16 (quarter traffic vs f64), inner compute
    accumulates in f32 (``acc_dtype`` widening in the SELL/DIA
    kernels), outer f64 refinement. Accuracy contract: iterative
    refinement contracts only while ``cond(A) * 2**-8 < 1`` (bf16 has
    an 8-bit mantissa), so this policy is for well-conditioned or
    strongly preconditioned operators — ``auto`` never picks it.

A resolved choice is per ``(pattern, solver, bucket, dtype)`` — the
bucket-program axes — and joins the program's plan-cache key
(``.P<policy>`` suffix; absent for 'exact', so historic keys are
unchanged) and the vault warm-start manifest (back-compatible
``dtype_policy`` field, like Fleet's ``mesh`` and Precond's
``precond``).

Policies that cannot apply degrade to ``exact`` with a
``coverage.fallback`` breadcrumb rather than failing the dispatch:
complex request dtypes (the IR loop is real-arithmetic), gmres buckets
(its host-driven restart cycle has no fused refinement form), x64
disabled (no f64 outer loop to refine in), and non-square patterns.

The promote rung (the health-monitor escalation, docs/resilience.md):
:meth:`DtypePolicy.promote` pins a (pattern, solver, bucket, dtype)
group to ``exact`` for the rest of the session — the serving loop calls
it when a reduced-precision bucket comes back anomalous (nonfinite or
unconverged lanes), right before requeueing those lanes at ``exact``
(``action=promote_dtype``, ahead of the classic solver-escalation
rung). Promotions count into the always-on ``mixed.promotions{reason}``
metric.
"""

from __future__ import annotations

import numpy as np

from .. import telemetry
from ..config import settings
from ..telemetry import _metrics

#: the forceable reduced-precision policies (the SPARSE_TPU_DTYPE
#: grammar minus auto/exact)
POLICIES = ("f32ir", "bf16ir")

EXACT = "exact"

_EXACT_SPELLINGS = ("", "0", "off", "false", "no", "none", "exact")

#: solvers the fused IR loop wraps (pure-jnp inner loops; gmres's
#: host-driven restart cycle degrades to exact)
IR_SOLVERS = ("cg", "bicgstab")

# process-global promote listeners (ISSUE 16): callbacks offered every
# promote-rung firing — the autopilot's promote-spike drift signal.
# Same contract as the watchdog alert hooks: best-effort, exceptions
# swallowed, every DtypePolicy instance fires them.
_PROMOTE_LISTENERS: list = []


def add_promote_listener(fn) -> None:
    """Register a callback invoked on every :meth:`DtypePolicy.promote`
    with keyword fields ``solver``/``bucket``/``dtype``/``reason``."""
    if fn not in _PROMOTE_LISTENERS:
        _PROMOTE_LISTENERS.append(fn)


def remove_promote_listener(fn) -> None:
    """Unregister a previously added listener (idempotent)."""
    try:
        _PROMOTE_LISTENERS.remove(fn)
    except ValueError:
        pass


def canonical_policy(policy, allow_auto: bool = True) -> str:
    """Normalize a policy spelling; raises on unknown values (a typo'd
    ``SPARSE_TPU_DTYPE`` must not silently serve at reduced precision —
    or silently fail to)."""
    s = str("" if policy is None else policy).strip().lower()
    if s in _EXACT_SPELLINGS:
        return EXACT
    if s == "auto":
        if not allow_auto:
            raise ValueError("'auto' is not a concrete dtype policy")
        return "auto"
    if s not in POLICIES:
        raise ValueError(
            f"dtype policy {policy!r} not one of "
            f"{('exact', 'auto') + POLICIES}"
        )
    return s


def key_suffix(policy: str | None) -> str:
    """What a resolved policy contributes to the bucket-program
    plan-cache key — empty for 'exact' so historic keys, programs and
    vault manifests are byte-compatible with every earlier release."""
    if not policy or policy == EXACT:
        return ""
    return f".P{policy}"


def inner_dtypes(policy: str) -> tuple:
    """``(storage_dtype, compute_dtype)`` of the inner Krylov sweep: the
    width the packed value planes upload/stream at, and the width the
    sweep's vectors and recurrence scalars carry (the ``acc_dtype`` the
    kernels widen chunk-reductions to)."""
    if policy == "f32ir":
        return np.dtype(np.float32), np.dtype(np.float32)
    if policy == "bf16ir":
        import jax.numpy as jnp

        return np.dtype(jnp.bfloat16), np.dtype(np.float32)
    raise ValueError(f"policy {policy!r} has no reduced inner dtypes")


def outer_dtype() -> np.dtype:
    """The refinement loop's residual/correction dtype (always f64 —
    the whole point of the outer loop)."""
    return np.dtype(np.float64)


def default_eta(policy: str) -> float:
    """Per-sweep inner residual-reduction target: how far the inner
    sweep pushes the (scaled, unit-norm) correction residual before the
    outer loop re-evaluates in f64. Bounded by the inner precision —
    f32 can earn ~4 digits per sweep, bf16 storage ~2."""
    if settings.ir_eta > 0:
        return settings.ir_eta
    return 1e-4 if policy == "f32ir" else 1e-2


class DtypePolicy:
    """Per-session precision selector (constructed by ``SolveSession``;
    also usable standalone).

    Parameters
    ----------
    mode : '' / 'exact' | 'auto' | 'f32ir' | 'bf16ir'. ``None`` =
        ``settings.dtype_policy`` (``SPARSE_TPU_DTYPE``).
    inner_iters / max_outer / eta : IR-loop knob overrides
        (defaults from settings / :func:`default_eta`).
    """

    def __init__(self, mode=None, inner_iters: int | None = None,
                 max_outer: int | None = None, eta: float | None = None):
        self.mode = canonical_policy(
            settings.dtype_policy if mode is None else mode
        )
        self.inner_iters = inner_iters
        self.max_outer = max_outer
        self.eta = eta
        # resolved (id(pattern), solver, bucket, dtype, override) -> policy
        self._decisions: dict = {}
        # groups the promote rung pinned to exact (health-monitor
        # escalation; never un-promotes within a session)
        self._promoted: set = set()

    @classmethod
    def resolve(cls, policy=None, **knobs) -> "DtypePolicy":
        """The ``SolveSession`` constructor hook: ``policy`` may be a
        ready policy object, a mode string, ``True`` (= 'auto'),
        ``False`` (= exact regardless of env), or ``None`` (= env)."""
        if isinstance(policy, cls):
            return policy
        if policy is True:
            policy = "auto"
        elif policy is False:
            policy = EXACT
        return cls(policy, **knobs)

    @property
    def enabled(self) -> bool:
        return self.mode != EXACT

    @staticmethod
    def _group(pattern, solver: str, bucket: int, dtype) -> tuple:
        return (id(pattern), solver, int(bucket), np.dtype(dtype).str)

    def decide(self, pattern, solver: str, bucket: int, dtype,
               override=None) -> str:
        """Resolved concrete policy for one bucket program (cached per
        (pattern, solver, bucket, dtype, override)); a promoted group
        always resolves to 'exact'."""
        group = self._group(pattern, solver, bucket, dtype)
        if group in self._promoted:
            return EXACT
        ov = None if override is None else canonical_policy(override)
        key = group + (ov,)
        hit = self._decisions.get(key)
        if hit is not None:
            return hit
        policy = ov if ov is not None else self.mode
        if policy == "auto":
            policy = self._auto(solver, dtype)
        policy = self._validate(pattern, solver, dtype, policy)
        self._decisions[key] = policy
        return policy

    def promote(self, pattern, solver: str, bucket: int, dtype,
                reason: str = "anomaly") -> None:
        """Pin one bucket group to 'exact' (the health-monitor
        escalation rung): every later dispatch of this (pattern,
        solver, bucket, dtype) solves at the request dtype. Counts
        into the always-on ``mixed.promotions{reason}`` metric."""
        self._promoted.add(self._group(pattern, solver, bucket, dtype))
        _metrics.counter(
            "mixed.promotions", reason=reason,
            help="reduced-precision bucket groups escalated to the "
            "'exact' dtype policy, by anomaly reason",
        ).inc()
        for fn in list(_PROMOTE_LISTENERS):
            try:
                fn(solver=solver, bucket=int(bucket),
                   dtype=np.dtype(dtype).str, reason=reason)
            except Exception:  # noqa: BLE001 - listeners never break serving
                pass

    def _auto(self, solver: str, dtype) -> str:
        """f32+IR for f64 requests on the fused-loop solvers; everything
        else exact. bf16 storage is opt-in only (see the module
        docstring's accuracy contract)."""
        if solver in IR_SOLVERS and np.dtype(dtype) == np.float64:
            return "f32ir"
        return EXACT

    def _validate(self, pattern, solver: str, dtype, policy: str) -> str:
        """Degrade policies the bucket cannot support (breadcrumbed,
        never a dispatch failure)."""
        if policy == EXACT:
            return policy
        dt = np.dtype(dtype)
        if dt.kind == "c":
            self._fallback(policy, "complex request dtype")
            return EXACT
        if solver not in IR_SOLVERS:
            self._fallback(policy, f"solver {solver} has no fused IR loop")
            return EXACT
        if pattern is not None and pattern.shape[0] != pattern.shape[1]:
            self._fallback(policy, "non-square pattern")
            return EXACT
        import jax

        if not jax.config.jax_enable_x64:
            self._fallback(policy, "x64 disabled: no f64 outer loop")
            return EXACT
        return policy

    @staticmethod
    def _fallback(policy: str, reason: str) -> None:
        if telemetry.enabled():
            telemetry.record(
                "coverage.fallback", op=f"mixed.{policy}", reason=reason,
                to=EXACT,
            )

    def ir_knobs(self, policy: str, n: int, conv_test_iters: int) -> dict:
        """The IR loop's static knobs for one bucket program."""
        inner = self.inner_iters or settings.ir_inner
        if inner <= 0:
            # auto: scale the per-sweep budget with the system — a
            # too-small budget forces restart churn (each restart
            # throws away the Krylov space), while a generous one costs
            # nothing (the sweep exits on its inner tolerance). Capped
            # so a stalling sweep cannot burn unbounded work.
            inner = max(8 * int(conv_test_iters), min(int(n), 4000))
        return {
            "inner_iters": int(inner),
            "max_outer": int(self.max_outer or settings.ir_outer),
            "eta": float(self.eta if self.eta is not None
                         else default_eta(policy)),
        }

    def describe(self) -> dict:
        """JSON-friendly block for ``session_stats()``."""
        return {
            "mode": self.mode,
            "enabled": self.enabled,
            "promoted_groups": len(self._promoted),
            "inner_iters": self.inner_iters or settings.ir_inner,
            "max_outer": self.max_outer or settings.ir_outer,
        }
