"""CSR sparse array — the workhorse format.

Reference analog: ``sparse/csr.py`` (1731 LoC; class at csr.py:99, op free
functions spmv csr.py:863 / add csr.py:972 / mult csr.py:1033 / spmm csr.py:1151 /
rspmm csr.py:1209 / sddmm csr.py:1244 / spgemm csr.py:1317,1495 / tropical
csr.py:366). The Legion pos/crd/vals stores become plain ``indptr/indices/data``
jax.Arrays; partition constraints become either XLA GSPMD shardings or explicit
``shard_map`` row-blocks (``sparse_tpu.parallel``).

TPU-first detail: construction optionally caches a padded-row (ELL) layout when
the row-length profile is tight (all reference benchmarks are banded), switching
SpMV/SpMM from scatter-shaped to gather-shaped kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import SparseArray
from .coverage import track_provenance
from .config import settings
from .ops import conv, elementwise, sddmm as sddmm_ops, spgemm as spgemm_ops, spmv as spmv_ops
from .ops.coords import expand_rows
from .utils import (
    asjnp, commit_to_exec_device, host_int, host_scope, in_trace, user_warning,
)


@jax.tree_util.register_pytree_node_class
class csr_array(SparseArray):
    format = "csr"

    def __init__(self, arg, shape=None, dtype=None, copy=False):
        from .coo import coo_array

        if isinstance(arg, csr_array):
            data, indices, indptr, shape = arg.data, arg.indices, arg.indptr, arg.shape
        elif isinstance(arg, SparseArray):
            c = arg.tocsr()
            data, indices, indptr, shape = c.data, c.indices, c.indptr, c.shape
        elif isinstance(arg, tuple) and len(arg) == 3:
            data, indices, indptr = (asjnp(a) for a in arg)
            if shape is None:
                ncols = host_int(indices.max()) + 1 if indices.shape[0] else 0
                shape = (indptr.shape[0] - 1, ncols)
        elif isinstance(arg, tuple) and len(arg) == 2 and isinstance(arg[1], tuple):
            c = coo_array(arg, shape=shape).tocsr()
            data, indices, indptr, shape = c.data, c.indices, c.indptr, c.shape
        elif isinstance(arg, tuple) and len(arg) == 2:
            shape = (int(arg[0]), int(arg[1]))
            indptr = jnp.zeros((shape[0] + 1,), dtype=np.int32)
            indices = jnp.zeros((0,), dtype=np.int32)
            data = jnp.zeros((0,), dtype=dtype or np.float32)
        elif hasattr(arg, "tocsr") and hasattr(arg, "indptr"):  # scipy csr
            s = arg.tocsr()
            data, indices, indptr = asjnp(s.data), asjnp(s.indices), asjnp(s.indptr)
            shape = s.shape
        elif hasattr(arg, "tocsr"):  # other scipy formats
            s = arg.tocsr()
            data, indices, indptr = asjnp(s.data), asjnp(s.indices), asjnp(s.indptr)
            shape = s.shape
        else:  # dense
            d = asjnp(arg)
            if d.ndim != 2:
                raise ValueError("CSR arrays must be 2-D")
            indptr, indices, data, _ = conv.dense_to_csr(d)
            shape = d.shape
        if dtype is not None:
            data = data.astype(dtype)
        self.data = asjnp(data)
        self.indices = asjnp(indices)
        self.indptr = asjnp(indptr)
        self._shape = (int(shape[0]), int(shape[1]))
        self._dtype = np.dtype(self.data.dtype)
        self._ell = None  # lazy (ell_indices, ell_data) cache
        self._dia = False  # False = unchecked, None = not banded, else planes
        self._balanced_splits = None

    @classmethod
    def from_parts(cls, data, indices, indptr, shape):
        obj = object.__new__(cls)
        obj.data = asjnp(data)
        obj.indices = asjnp(indices)
        obj.indptr = asjnp(indptr)
        obj._shape = (int(shape[0]), int(shape[1]))
        obj._dtype = np.dtype(obj.data.dtype)
        obj._ell = None
        obj._dia = False
        obj._balanced_splits = None
        return obj

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.indices, self.indptr), self._shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        data, indices, indptr = children
        return cls.from_parts(data, indices, indptr, shape)

    # ----------------------------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(self.data.shape[0])

    def _data_array(self):
        return self.data

    def _with_data(self, data):
        out = csr_array.from_parts(data, self.indices, self.indptr, self.shape)
        out._balanced_splits = self._balanced_splits
        return out

    # -- ELL fast path -----------------------------------------------------
    def _ell_width(self) -> int | None:
        """Max row length; host-synced once and cached (None: unknowable)."""
        if not hasattr(self, "_ell_width_cache") or self._ell_width_cache is None:
            try:
                with host_scope():  # never eager-dispatch via a tunnel
                    counts = self.indptr[1:] - self.indptr[:-1]
                    self._ell_width_cache = (
                        host_int(counts.max()) if self.shape[0] else 0
                    )
            except jax.errors.JaxRuntimeError:
                # backend can't execute/fetch (see _maybe_dia): fall back
                # to a host-side count from the (plain-buffer) indptr; if
                # even that transfer fails, report width-unknown
                try:
                    p = np.asarray(self.indptr)
                except jax.errors.JaxRuntimeError:
                    return None
                self._ell_width_cache = int((p[1:] - p[:-1]).max()) if len(p) > 1 else 0
        return self._ell_width_cache

    def _maybe_ell(self):
        """Build/cache the padded-row layout when profitable (settings.spmv_mode)."""
        mode = settings.spmv_mode
        if mode in ("segment", "sell"):
            return None
        m = self.shape[0]
        if m == 0 or self.nnz == 0:
            return None
        if self._ell is None and in_trace():
            # in-trace first use: no host sync, and no cache write — a
            # width cache may already exist (eager call under a different
            # spmv_mode), but building ELL here would store TRACER arrays
            # on self._ell and poison every later eager matvec
            return None
        k = self._ell_width()
        if k is None:  # width unknowable on this backend: no ELL layout
            return None
        mean = max(self.nnz / m, 1.0)
        if mode in ("ell", "pallas") or k <= settings.ell_max_ratio * mean:
            if self._ell is None:
                with host_scope():  # one-time layout build, not via tunnel
                    self._ell = conv.csr_to_ell(
                        self.indptr, self.indices, self.data, m, max(k, 1)
                    )
            return self._ell
        return None

    # -- SELL-C-sigma prepared path ----------------------------------------
    def _maybe_sell(self):
        """Packed SELL-C-sigma operator via the library-wide plan cache.

        The prepared general-SpMV path for skewed row profiles
        (kernels/sell_spmv.py): under ``spmv_mode='sell'``/``'pallas'`` it
        applies whenever the matrix has nonzeros; under ``'auto'`` only
        when the padded-row (ELL) gate declined (max degree beyond
        ``ell_max_ratio`` x mean — exactly where the segment path used to
        be the only option). One host-side pack on first eager use,
        cached in ``sparse_tpu.plan_cache`` keyed on this object; in-trace
        first use degrades to the jit-safe segment path without caching
        (same discipline as ``_maybe_ell``/``_maybe_dia``).
        """
        from . import plan_cache

        mode = settings.spmv_mode
        if mode not in ("auto", "sell", "pallas"):
            return None
        if self.shape[0] == 0 or self.nnz == 0:
            return None
        if in_trace():
            # trace-safe lookup: an eagerly-warmed plan is reusable (its
            # planes become compile-time constants, like the ELL cache);
            # packing here would need host syncs, so a cold cache skips
            return plan_cache.lookup(self, "sell")
        if mode == "auto":
            k = self._ell_width()
            if k is None:
                return None
            mean = max(self.nnz / self.shape[0], 1.0)
            if k <= settings.ell_max_ratio * mean:
                return None  # tight profile: the ELL path takes it

        def build():
            from .kernels.sell_spmv import PreparedCSR

            with host_scope():  # one-time pack, never via a tunnel
                prep = PreparedCSR(
                    self.indptr, self.indices, self.data, self.shape
                )
            # layouts are BUILT under host_scope; commit to the execution
            # device once so accelerator hot paths don't re-ship the
            # planes per matvec (same discipline as the DIA/ELL caches)
            prep.slabs = tuple(
                commit_to_exec_device((it, vt)) for it, vt in prep.slabs
            )
            (prep.pos,) = commit_to_exec_device((prep.pos,))
            return prep

        def vault_key():
            # content fingerprint: exact buffers + the SELL geometry
            # settings the pack depends on (sparse_tpu.vault._codecs)
            from .vault import _codecs

            return _codecs.prepared_csr_key(
                self.indptr, self.indices, self.data, self.shape
            )

        return plan_cache.get(
            self, "sell", build,
            vault_kind="prepared_csr", vault_key=vault_key,
            # canonicalized: the packed planes carry jax's dtype (f64
            # narrows to f32 without x64), and that is what a loaded
            # artifact must agree with
            expect={"dtype": str(jax.dtypes.canonicalize_dtype(self.dtype))},
        )

    def prepare(self, mode: str | None = None):
        """One-time eager layout/pack warm for the current (or given)
        ``spmv_mode``; returns ``self`` for chaining.

        The prepare half of the prepare/execute split: solvers whose first
        matvec happens inside a compiled loop (multigrid operators, eigsh
        Lanczos bodies) would otherwise pin the slowest kernel path for
        the whole solve — ``make_linear_operator`` calls this eagerly so
        every ``linalg`` solver starts from a packed operator.
        """
        if in_trace():
            return self  # layout detection needs host syncs; no-op in-trace
        prev = settings.spmv_mode
        try:
            if mode is not None:
                settings.spmv_mode = mode
            if settings.spmv_mode in ("auto", "pallas"):
                self._maybe_dia()
            if settings.plan_cache:
                # with the plan cache DISABLED the pack has nowhere to
                # live — plan_cache.get builds and discards — so an eager
                # warm would charge every one-shot solve the full SELL
                # pack cost for nothing (tests/test_plan_cache.py pins
                # this). Execute-time _maybe_sell still packs when a
                # matvec actually needs it.
                self._maybe_sell()
            self._maybe_ell()
        finally:
            settings.spmv_mode = prev
        return self

    # -- products ----------------------------------------------------------
    @track_provenance
    def dot(self, other, out=None, spmv_domain_part=False):
        """A @ other. Vector -> SpMV; dense 2-D -> SpMM; sparse -> SpGEMM.

        ``spmv_domain_part`` mirrors the reference's column-split SpMV flag
        (csr.py:442/869-927): the contraction dimension is split into
        ``parallel.mesh.num_procs()`` domains reduced separately
        (ops.spmv.csr_spmv_colsplit). The mesh version of the same strategy
        is ``parallel.dist.shard_csr_cols`` (psum_scatter over ICI).
        """
        from .csc import csc_array

        if isinstance(other, SparseArray):
            if out is not None:
                raise ValueError("out= is not supported for spgemm")
            if self.shape[1] != other.shape[0]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {other.shape}"
                )
            b = other.tocsr()
            indptr, indices, data = spgemm_ops.spgemm_csr_csr(
                self.indptr, self.indices, self.data,
                b.indptr, b.indices, b.data,
                self.shape, b.shape,
            )
            return csr_array.from_parts(
                data, indices, indptr, (self.shape[0], b.shape[1])
            )
        x = asjnp(other)
        if x.ndim == 1:
            if x.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {x.shape}"
                )
            if spmv_domain_part:
                from .parallel.mesh import num_procs

                y = spmv_ops.csr_spmv_colsplit(
                    self.indptr, self.indices, self.data, x, self.shape[0],
                    max(num_procs(), 1),
                )
            else:
                y = self._spmv(x)
        elif x.ndim == 2:
            if x.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {x.shape}"
                )
            y = self._spmm(x)
        else:
            raise ValueError("can only multiply by 1-D or 2-D arrays")
        if out is not None:
            # The reference writes into a pre-allocated store (csr.py:501-503);
            # jax arrays are immutable, so out= is advisory — we just check shape.
            if out.shape != y.shape:
                raise ValueError("out has the wrong shape")
        return y

    def _maybe_dia(self):
        """Detect banded structure and cache DIA planes for zero-gather SpMV.

        Matrices living on a handful of diagonals (every reference
        benchmark: Laplacians, the 11-diag microbench) skip index gathers
        entirely — SpMV becomes shifted vector adds (ops.dia_spmv). Pure
        structure detection (mode-independent; _spmv applies the mode);
        one host sync at first use, result cached (None = not banded).
        """
        if self._dia is not False:
            return self._dia
        if in_trace():
            # first use is INSIDE a trace (e.g. a multigrid prolongator
            # applied only in the compiled V-cycle): detection needs a
            # host sync, which would raise and silently demote the whole
            # solver to its host loop. Skip WITHOUT caching — an eager
            # warm call (linalg.cg does one) can still detect later.
            return None
        self._dia = None
        m, n = self.shape
        nnz = self.nnz
        if nnz == 0:
            return None
        with host_scope():  # one-time eager analysis: never via a tunnel
            return self._maybe_dia_detect(m, n, nnz)

    @staticmethod
    def _fetch_offsets(offs_dev):
        """Host fetch of the bounded-unique diagonal offsets — the one
        device->host transfer of banded detection, split out so tests can
        simulate backends where it fails (the axon-tunnel class)."""
        return np.unique(np.asarray(offs_dev))

    def _maybe_dia_detect(self, m, n, nnz):
        rows = expand_rows(self.indptr, nnz)
        # bounded-size unique: >max_diags distinct offsets still yields
        # max_diags+1 values, which the gate below rejects
        # col - row fits int32 whenever both dims do (values < 2**31 each,
        # difference in (-2**31, 2**31)); int64 here would just warn-and-
        # truncate under the default no-x64 config
        offs_dev = jnp.unique(self.indices.astype(jnp.int32) - rows.astype(jnp.int32),
                              size=min(settings.dia_max_diags + 1, nnz),
                              fill_value=jnp.iinfo(jnp.int32).max)
        try:
            offs = self._fetch_offsets(offs_dev)
        except jax.errors.JaxRuntimeError as e:
            # experimental backends (the axon tunnel) can fail to execute
            # or transfer the bounded-unique — treat as not banded rather
            # than crash the matvec; the SpMV still runs on ELL/segment.
            # NOT silently (the old behavior): a matrix that should ride
            # the zero-gather DIA kernel degrading to gathers/segment is
            # a perf cliff worth a breadcrumb, so record the degradation
            # as a coverage event (tested by tests/test_sell_spmv.py).
            from . import telemetry

            telemetry.record(
                "coverage.fallback", op="csr._maybe_dia",
                reason="detection-fetch-failed", to="ell/segment",
                error=repr(e)[:200], shape=[int(m), int(n)],
            )
            user_warning(
                "banded (DIA) structure detection could not fetch its "
                f"result on this backend ({e!r}); SpMV degrades to the "
                "gather/segment path for this matrix"
            )
            return None
        offs = offs[offs != np.iinfo(np.int32).max]
        D = len(offs)
        if D > settings.dia_max_diags or D * n > settings.dia_max_fill * nnz:
            return None
        from .dia import _coo_to_dia  # duplicate-summing plane build

        planes, offsets, _ = _coo_to_dia(self.tocoo())
        self._dia = (planes, tuple(int(o) for o in offsets))
        return self._dia

    def _spmv(self, x):
        mode = settings.spmv_mode
        if mode in ("auto", "pallas"):
            dia = self._maybe_dia()
            if dia is not None:
                if not in_trace():
                    # layouts are BUILT under host_scope; on accelerator
                    # hot paths commit them to the execution device once
                    # (they are jit arguments — CPU-resident planes would
                    # re-transfer per matvec) and re-cache
                    planes = commit_to_exec_device((dia[0],))[0]
                    if planes is not dia[0]:
                        dia = (planes, dia[1])
                        self._dia = dia
                if mode == "pallas":
                    from .kernels.dia_spmv import cached_prepared_spmv

                    y = cached_prepared_spmv(
                        self, "_dia_prepared", dia[0], dia[1], self.shape, x
                    )
                    if y is not None:  # None: band too wide for VMEM
                        return y
                from .ops.dia_spmv import dia_spmv_xla

                return dia_spmv_xla(dia[0], dia[1], x, self.shape)
        # prepared SELL-C-sigma path: forced by mode 'sell', attempted for
        # non-banded matrices under 'pallas', and the 'auto' fallthrough
        # for skewed row profiles where the ELL gate declines (the shapes
        # that used to pay the scatter-shaped segment path per matvec)
        prep = self._maybe_sell()
        if prep is not None:
            return prep(x)
        ell = self._maybe_ell()
        if ell is not None:
            if not in_trace():
                ell2 = commit_to_exec_device(ell)
                if ell2[0] is not ell[0]:
                    ell = self._ell = ell2
            # spmv_mode='pallas' accelerates DIA-profiled matrices only
            # (kernels/dia_spmv above). A Pallas ELL kernel needs a
            # windowed in-VMEM gather, which Mosaic cannot lower yet
            # (single-tile take_along_axis only) — general bounded-degree
            # matrices take XLA's HBM-gather formulation, the fastest
            # path that actually runs on hardware (VERDICT r2 #8:
            # the dead interpret-only kernel was removed, not shipped).
            return spmv_ops.csr_spmv_ell(ell[0], ell[1], x)
        return spmv_ops.csr_spmv_segment(
            self.indptr, self.indices, self.data, x, self.shape[0]
        )

    def _spmm(self, B):
        ell = self._maybe_ell()
        if ell is not None:
            return spmv_ops.csr_spmm_ell(ell[0], ell[1], B)
        prep = self._maybe_sell()  # skewed profiles: slab gathers, XLA form
        if prep is not None:
            return prep.matmat(B)
        return spmv_ops.csr_spmm_segment(
            self.indptr, self.indices, self.data, B, self.shape[0]
        )

    def _rdot(self, other):
        """other @ A for dense other (SPMM_DENSE_CSR, csr.py:1209)."""
        B = asjnp(other)
        if B.ndim == 1:
            return spmv_ops.rspmm(
                self.indptr, self.indices, self.data, B[None, :], self.shape[1]
            )[0]
        return spmv_ops.rspmm(
            self.indptr, self.indices, self.data, B, self.shape[1]
        )

    def matvec(self, x, out=None):
        return self.dot(x, out=out)

    @track_provenance
    def sddmm(self, C, D):
        """Structure-preserving sampled dense-dense matmul (csr.py:1244)."""
        vals = sddmm_ops.csr_sddmm(
            self.indptr, self.indices, self.data, asjnp(C), asjnp(D)
        )
        return self._with_data(vals)

    @track_provenance
    def tropical_spmv(self, x):
        """(max, +) semiring SpMV over 3-tuple vectors (csr.py:366).

        Powers AMG MIS aggregation. x is [n, 3]; comparison is lexicographic on
        (x0 + a, x1, x2)? — see ops.tropical for the exact semiring.
        """
        from .ops import tropical

        ell = self._maybe_ell()
        return tropical.tropical_spmv(
            self.indptr, self.indices, self.data, asjnp(x), self.shape[0],
            ell_idx=ell[0] if ell is not None else None,
        )

    @track_provenance
    def mis_tropical(self, k=1, invalid=None, seed=0):
        """Maximal independent set MIS(k) flags, one compiled tournament.

        Device-side analog of the AMG aggregation driver (reference
        amg.py:199-257): the whole round loop is a ``lax.while_loop``
        over tropical SpMV hops — no host fetch per round. Returns the
        [m] int32 flag vector (2 = MIS, 0 = dominated, -1 = invalid).
        """
        from .ops import tropical

        ell = self._maybe_ell()
        return tropical.mis_flags(
            self.indptr, self.indices, self.data, self.shape[0], k=k,
            invalid=invalid, seed=seed,
            ell_idx=ell[0] if ell is not None else None,
        )

    @track_provenance
    def mis_aggregate_cols(self, flags):
        """(aggregate column per node, n_coarse) from MIS flags — the
        nearest-root routing (reference amg.py:259-283), on device."""
        from .ops import tropical

        ell = self._maybe_ell()
        return tropical.mis_aggregate_cols(
            self.indptr, self.indices, self.data, self.shape[0], flags,
            ell_idx=ell[0] if ell is not None else None,
        )

    # -- elementwise -------------------------------------------------------
    @track_provenance
    def __add__(self, other):
        if np.isscalar(other):
            if other == 0:
                return self.copy()
            raise NotImplementedError("adding a nonzero scalar densifies")
        if isinstance(other, SparseArray):
            b = other.tocsr()
            indptr, indices, data = elementwise.csr_add_csr(
                self.indptr, self.indices, self.data,
                b.indptr, b.indices, b.data, self.shape,
            )
            return csr_array.from_parts(data, indices, indptr, self.shape)
        # dense other -> dense result
        return self.toarray() + asjnp(other)

    def __radd__(self, other):
        return self.__add__(other)

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", 1) == 0:
            return self._with_data(self.data * other)
        return self.multiply(other)

    @track_provenance
    def multiply(self, other):
        if np.isscalar(other) or getattr(other, "ndim", 1) == 0:
            return self._with_data(self.data * other)
        if isinstance(other, SparseArray):
            b = other.tocsr()
            indptr, indices, data = elementwise.csr_mult_csr(
                self.indptr, self.indices, self.data,
                b.indptr, b.indices, b.data, self.shape,
            )
            return csr_array.from_parts(data, indices, indptr, self.shape)
        d = asjnp(other)
        m, n = self.shape
        if d.ndim == 1:
            d = d[None, :]
        if d.ndim != 2 or d.shape[0] not in (1, m) or d.shape[1] not in (1, n):
            raise ValueError(
                f"inconsistent shapes: {self.shape} and {np.shape(other)}"
            )
        # broadcast operands stay per-nnz: materializing the [m, n]
        # broadcast of a column vector is O(m*n) memory (512 GB at the
        # AMG example's 512^2 grid); scale rows/columns directly instead
        if d.shape == (1, 1):
            return self._with_data(self.data * d[0, 0])
        if d.shape[1] == 1:  # column vector: scale rows
            rows = expand_rows(self.indptr, int(self.data.shape[0]))
            return self._with_data(self.data * d[rows, 0])
        if d.shape[0] == 1:  # row vector: scale columns
            return self._with_data(self.data * d[0, self.indices])
        vals = elementwise.csr_mult_dense(
            self.indptr, self.indices, self.data, d, self.shape
        )
        return self._with_data(vals)

    # -- reductions / extraction -------------------------------------------
    def sum(self, axis=None):
        return elementwise.csr_sum(
            self.indptr, self.indices, self.data, self.shape, axis=axis
        )

    def diagonal(self, k=0):
        return elementwise.csr_diagonal(
            self.indptr, self.indices, self.data, self.shape, k=k
        )

    # -- conversions -------------------------------------------------------
    def tocsr(self):
        return self

    def tocoo(self):
        from .coo import coo_array

        rows, cols, data = conv.csr_to_coo(
            self.indptr, self.indices, self.data, self.shape
        )
        out = coo_array((data, (rows, cols)), shape=self.shape)
        # CSR expands to row-major-sorted, duplicate-free triples — mark
        # canonical so reductions skip the re-canonicalization pass
        out.has_sorted_indices = True
        out.has_canonical_format = True
        return out

    def tocsc(self):
        from .csc import csc_array

        indptr, indices, data = conv.csr_to_csc(
            self.indptr, self.indices, self.data, self.shape
        )
        return csc_array.from_parts(data, indices, indptr, self.shape)

    def todia(self):
        return self.tocoo().todia()

    def toarray(self):
        return conv.csr_to_dense(self.indptr, self.indices, self.data, self.shape)

    def transpose(self, axes=None):
        """Zero-copy transpose: reinterpret the same buffers as CSC (like scipy)."""
        if axes is not None:
            raise ValueError("transpose with axes != None is unsupported")
        from .csc import csc_array

        return csc_array.from_parts(
            self.data, self.indices, self.indptr, (self.shape[1], self.shape[0])
        )

    @property
    def T(self):
        return self.transpose()

    # -- distribution ------------------------------------------------------
    def balance(self, num_shards=None):
        """Compute nnz-balanced row-block boundaries and cache them.

        Reference: ``DenseSparseBase.balance`` (base.py:198-282) — preimage of an
        equal nnz split back to rows. On TPU: one host-side searchsorted over
        indptr; the splits are consumed by ``sparse_tpu.parallel`` when sharding.
        """
        from .parallel.partition import balanced_row_splits

        if num_shards is None:
            num_shards = len(jax.devices())
        self._balanced_splits = balanced_row_splits(self.indptr, num_shards)
        return self

    def __str__(self):
        return (
            f"<{self.shape[0]}x{self.shape[1]} CSR array, nnz={self.nnz},"
            f" dtype={self.dtype}>"
        )

    __repr__ = __str__


def spmv(A: csr_array, x, y=None):
    """Free-function SpMV, mirroring the reference's ``spmv`` (csr.py:863)."""
    return A.dot(x, out=y)
