"""Distribution layer: device meshes, sharded containers, collectives.

Reference analog: L0/L3 of SURVEY — Legion partitioning + NCCL/coll become
`jax.sharding.Mesh` + `shard_map` + XLA collectives (psum/all_gather/
ppermute/all_to_all) over ICI/DCN.
"""

from .partition import balanced_row_splits, column_windows, equal_row_splits  # noqa: F401
from . import comm  # noqa: F401  (measured collective accounting)
from .dist import DistCSR, DistCSRCol, comm_stats, dist_cg, shard_csr, shard_csr_cols  # noqa: F401
from .spgemm import dist_spgemm, dist_spgemm_2d  # noqa: F401
from .grid2d import cdist_2d, lookup_2d  # noqa: F401
from .mesh import get_mesh, get_mesh_2d, initialize_distributed  # noqa: F401
