"""Distributed SpGEMM over the device mesh.

Reference analogs:
  * row-gather CSR x CSR (``/root/reference/sparse/csr.py:1317-1490``): each
    rank computes a LOCAL CSR tile of ``A_rowblock @ B`` (GPU path: per-rank
    cuSPARSE SpGEMM), then a Python-side FutureMap scan stitches the local
    ``pos`` arrays into the global CSR (csr.py:1377-1389).
  * 3-phase 2-D CSR x CSC (``csr.py:1495-1728``): a (gx, gy) processor grid;
    B's rows replicated along grid-j, C's columns along grid-i; local tiles
    -> comm plan -> shuffle gather.

TPU-native redesign: sparse output sizes are data-dependent, so SpGEMM is a
setup-phase op here exactly as in the reference (which blocks on nnz futures
at csr.py:996 and scans pos on the control thread). Each tile is computed by
the single-device ESC kernel (``ops.spgemm``) ON ITS OWN DEVICE of the mesh
— per-shard inputs are committed to device s, so XLA dispatches the tile
programs concurrently across the mesh — and the host performs the pos-scan
stitch. The solver-facing hot path stays in ``parallel.dist`` (static-shape
SPMD); this module is how distributed hierarchies (AMG's Galerkin R@A@P)
get BUILT.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import get_mesh, get_mesh_2d
from .partition import balanced_row_splits, equal_row_splits

try:  # jax>=0.8 top-level; older releases keep it in experimental
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


# Diagnostic record of the last dist_spgemm's per-shard memory footprint
# (entries, not bytes). Tests assert the image gather keeps per-device B
# size ~nnz(B)/S instead of nnz(B); benchmarks report it.
LAST_STATS: dict = {}


def _bucket(v: int, bits: int = 3) -> int:
    """Shape bucket: pow2 envelope quantized to 2**bits steps per octave
    (≤ 1/2**bits padding) — compile-shape reuse without pow2's up-to-2x
    memory overshoot."""
    from ..ops.spgemm import _next_pow2

    v = max(int(v), 1)
    step = max(_next_pow2(v) >> bits, 1)
    return -(-v // step) * step


def _row_block(indptr, indices, data, r0: int, r1: int):
    """Host-side zero-copy row slice of a CSR triple."""
    lo, hi = int(indptr[r0]), int(indptr[r1])
    return indptr[r0 : r1 + 1] - indptr[r0], indices[lo:hi], data[lo:hi]


def _pad_block(ip, ix, dv, rows_pad: int, nnz_pad: int):
    """Pad a CSR triple to (rows_pad, nnz_pad): appended rows are empty
    (indptr extends flat), appended nnz slots sit beyond indptr[-1] and are
    masked out by ``ops.spgemm.spgemm_csr_csr``. Uniform tile shapes mean
    all shards of a product — and nearby levels of a hierarchy — share one
    compiled ESC program instead of compiling per exact tile size."""
    nr = ip.shape[0] - 1
    nnz = ix.shape[0]
    ip_p = np.concatenate([ip, np.full(rows_pad - nr, ip[-1], dtype=ip.dtype)])
    ix_p = np.concatenate([ix, np.zeros(nnz_pad - nnz, dtype=ix.dtype)])
    dv_p = np.concatenate([dv, np.zeros(nnz_pad - nnz, dtype=dv.dtype)])
    return ip_p, ix_p, dv_p


@partial(
    jax.jit, static_argnames=("mesh", "axis", "n", "T", "dt", "m_real")
)
def _esc_sharded(
    ipA, ixA, dvA, ipB, ixB, dvB, mesh, axis, n, T, dt, m_real
):
    """All S tiles in ONE compiled shard_map program: A tiles AND each
    shard's image-gathered B tile sharded on the mesh — so the grid runs
    concurrently and the compile is shared across shards AND across calls
    with the same bucket shapes (successive AMG levels, repeated Galerkin
    products). The per-shard body is the shared traced ESC core
    (``ops.spgemm.esc_expand_sort_compress``, the row-gather SpGEMM tile of
    reference csr.py:1390-1490); A's column ids arrive pre-remapped into
    the local B row space."""
    from ..ops.spgemm import esc_expand_sort_compress

    def shard_fn(ipA_l, ixA_l, dvA_l, ipB_l, ixB_l, dvB_l):
        ur, uc, uv, nu = esc_expand_sort_compress(
            ipA_l.squeeze(0), ixA_l.squeeze(0), dvA_l.squeeze(0),
            ipB_l.squeeze(0), ixB_l.squeeze(0), dvB_l.squeeze(0),
            n=n, T=T, U=T, dt=dt, m_real=m_real,
        )
        return ur[None], uc[None], uv[None], nu.astype(jnp.int64)[None]

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(axis, None), P(axis, None), P(axis, None),
            P(axis, None), P(axis, None), P(axis, None),
        ),
        out_specs=(P(axis, None), P(axis, None), P(axis, None), P(axis)),
        check_vma=False,
    )(ipA, ixA, dvA, ipB, ixB, dvB)


def dist_spgemm(A, B, mesh=None, balanced: bool = True):
    """C = A @ B (both ``csr_array``) with A row-split over the mesh.

    The row-gather algorithm (csr.py:1390-1490): shard s computes
    ``A[rows_s] @ B_image_s`` as a local tile, where ``B_image_s`` holds
    ONLY the B rows reachable from shard s's A columns (the image
    partition of reference csr.py:1447-1465) — per-shard B memory scales
    as nnz(B)/S for banded operators, never as nnz(B). All S tiles are
    padded to one bucket shape and launched as a single shard_map
    program, then the host stitches tiles with one pos scan. Returns a
    ``csr_array``.
    """
    import sparse_tpu

    if mesh is None:
        mesh = get_mesh()
    axis = mesh.axis_names[0]
    S = int(mesh.devices.size)
    m, k = A.shape
    k2, n = B.shape
    if k != k2:
        raise ValueError(f"dimension mismatch: {A.shape} @ {B.shape}")

    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    b_indptr = np.asarray(B.indptr)
    b_indices = np.asarray(B.indices)
    b_data = np.asarray(B.data)
    dt = np.result_type(A.dtype, B.dtype)
    splits = (
        balanced_row_splits(indptr, S) if balanced else equal_row_splits(m, S)
    )

    if A.nnz == 0 or B.nnz == 0:
        return sparse_tpu.csr_array.from_parts(
            np.zeros(0, dtype=dt),
            np.zeros(0, dtype=np.int32),
            np.zeros(m + 1, dtype=np.int64),
            (m, n),
        )

    from ..ops.spgemm import _next_pow2

    # Uniform padded tile shape across shards -> one compile for all S.
    rows_real = max(int(splits[s + 1] - splits[s]) for s in range(S))
    rows_pad = _next_pow2(rows_real)
    nnz_pad = _next_pow2(
        max(int(indptr[splits[s + 1]] - indptr[splits[s]]) for s in range(S))
    )
    bcounts = np.diff(b_indptr).astype(np.int64)

    # Image of B per shard: the sorted unique B rows this shard's A columns
    # touch. One host pass (the expansion bucket below reuses its slices) —
    # the reference computes the same set as a Legion image partition.
    kb_rows = []
    totals = []
    for s in range(S):
        lo, hi = int(indptr[splits[s]]), int(indptr[splits[s + 1]])
        cols_s = np.unique(indices[lo:hi])
        kb_rows.append(cols_s)
        # expansion bucket from the same pass (the reference's NNZ phase)
        totals.append(int(bcounts[indices[lo:hi]].sum()))
    T = _next_pow2(max(totals) + 1)
    kb_real = max((r.size for r in kb_rows), default=1)
    # B image tiles use a FINER shape bucket than pow2 (pow2 envelope, 1/8
    # steps): a banded operator's image is ~nnz(B)/S + halo, and rounding
    # that up to a full power of two could double per-device B memory —
    # exactly what the image gather exists to avoid. ≤12.5% padding keeps
    # the per-device footprint ∝ nnz(B)/S while still bucketing shapes.
    kb_pad = _bucket(kb_real)
    bnnz_pad = _bucket(
        max(
            (int(bcounts[r].sum()) for r in kb_rows if r.size),
            default=1,
        )
    )

    # indices stay in their native width (int32 when the inputs fit) — the
    # B index gathers dominate the tile's memory traffic
    idx_dt = np.int32 if max(n, k, int(indptr[-1]), int(b_indptr[-1])) < 2**31 else np.int64
    ipA = np.zeros((S, rows_pad + 1), dtype=idx_dt)
    ixA = np.zeros((S, nnz_pad), dtype=idx_dt)
    dvA = np.zeros((S, nnz_pad), dtype=data.dtype)
    ipB = np.zeros((S, kb_pad + 1), dtype=idx_dt)
    ixB = np.zeros((S, bnnz_pad), dtype=idx_dt)
    dvB = np.zeros((S, bnnz_pad), dtype=b_data.dtype)
    for s in range(S):
        r0, r1 = int(splits[s]), int(splits[s + 1])
        ip, ix, dv = _row_block(indptr, indices, data, r0, r1)
        # remap A's column ids into the local (gathered) B row space
        ix = np.searchsorted(kb_rows[s], ix).astype(idx_dt)
        ipA[s], ixA[s], dvA[s] = _pad_block(ip, ix, dv, rows_pad, nnz_pad)
        rws = kb_rows[s]
        cnts = bcounts[rws]
        local_ip = np.zeros(rws.size + 1, dtype=np.int64)
        np.cumsum(cnts, out=local_ip[1:])
        nb = int(local_ip[-1])
        # vectorized nnz gather of the image rows
        src = (
            np.arange(nb, dtype=np.int64)
            - np.repeat(local_ip[:-1], cnts)
            + np.repeat(b_indptr[rws].astype(np.int64), cnts)
        )
        ipB[s, : rws.size + 1] = local_ip
        ipB[s, rws.size + 1 :] = nb
        ixB[s, :nb] = b_indices[src]
        dvB[s, :nb] = b_data[src]

    LAST_STATS.clear()
    LAST_STATS.update(
        S=S,
        nnz_B=int(b_indptr[-1]),
        kb_pad=kb_pad,
        bnnz_pad=bnnz_pad,
        rows_pad=rows_pad,
        nnz_pad=nnz_pad,
        T=T,
    )

    sh = NamedSharding(mesh, P(axis, None))
    urows, ucols, uvals, nuniques = _esc_sharded(
        jax.device_put(ipA, sh),
        jax.device_put(ixA, sh),
        jax.device_put(dvA, sh),
        jax.device_put(ipB, sh),
        jax.device_put(ixB, sh),
        jax.device_put(dvB, sh),
        mesh=mesh, axis=axis, n=int(n), T=T, dt=jnp.dtype(dt),
        m_real=rows_real,
    )

    # Host pos-scan stitch (scan_local_results_and_scale_pos analog).
    urows = np.asarray(urows)
    ucols = np.asarray(ucols)
    uvals = np.asarray(uvals)
    nuniques = np.asarray(nuniques)
    out_indptr = np.zeros(m + 1, dtype=np.int64)
    parts_ix, parts_dv = [], []
    offset = 0
    for s in range(S):
        r0, r1 = int(splits[s]), int(splits[s + 1])
        nu = int(nuniques[s])
        lrows = urows[s, :nu]
        lcols = ucols[s, :nu]
        counts = np.bincount(lrows, minlength=rows_pad)[: r1 - r0]
        out_indptr[r0 + 1 : r1 + 1] = np.cumsum(counts) + offset
        offset += nu
        parts_ix.append(lcols)
        parts_dv.append(uvals[s, :nu])
    out_indices = (
        np.concatenate(parts_ix) if parts_ix else np.zeros(0, dtype=np.int64)
    )
    out_data = (
        np.concatenate(parts_dv) if parts_dv else np.zeros(0, dtype=dt)
    )
    return sparse_tpu.csr_array.from_parts(
        out_data, out_indices, out_indptr, (m, n)
    )


def dist_spgemm_2d(A, B, mesh2d=None):
    """C = A @ B on a 2-D (gx, gy) processor grid — the CSR x CSC analog.

    Tile (i, j) = ``A[rowblock_i] @ B[:, colblock_j]`` computed on device
    (i, j): A's row blocks are replicated along grid-j and B's column blocks
    along grid-i, matching the reference's 2-D replicated layout
    (csr.py:1495-1571). B may be ``csc_array`` (column slicing is an indptr
    slice) or ``csr_array`` (converted once). The shuffle phase
    (csr.py:1592-1728) collapses into the host stitch: tiles of one row
    block concatenate in grid-j order, already column-sorted.
    """
    import sparse_tpu

    if mesh2d is None:
        mesh2d = get_mesh_2d()
    grid = mesh2d.devices
    gx, gy = grid.shape
    m, k = A.shape
    k2, n = B.shape
    if k != k2:
        raise ValueError(f"dimension mismatch: {A.shape} @ {B.shape}")

    Bcsc = B.tocsc()
    b_indptr = np.asarray(Bcsc.indptr)
    b_indices = np.asarray(Bcsc.indices)
    b_data = np.asarray(Bcsc.data)

    a_indptr = np.asarray(A.indptr)
    a_indices = np.asarray(A.indices)
    a_data = np.asarray(A.data)
    row_splits = balanced_row_splits(a_indptr, gx)
    col_splits = equal_row_splits(n, gy)

    from ..ops.conv import csr_to_csc
    from ..ops.spgemm import spgemm_csr_csr

    from ..ops.spgemm import _next_pow2

    # Uniform padded tile shapes -> one csr_to_csc + one ESC compile for
    # the whole (gx, gy) grid.
    rows_real = max(int(row_splits[i + 1] - row_splits[i]) for i in range(gx))
    rows_pad = _next_pow2(rows_real)
    annz_pad = _next_pow2(
        max(
            int(a_indptr[row_splits[i + 1]] - a_indptr[row_splits[i]])
            for i in range(gx)
        )
    )
    cols_pad = _next_pow2(
        max(int(col_splits[j + 1] - col_splits[j]) for j in range(gy))
    )
    bnnz_pad = _next_pow2(
        max(
            int(b_indptr[col_splits[j + 1]] - b_indptr[col_splits[j]])
            for j in range(gy)
        )
    )
    tiles = {}
    real_rows = {}
    for i in range(gx):
        r0, r1 = int(row_splits[i]), int(row_splits[i + 1])
        if r1 <= r0:
            continue
        aip, aix, adv = _pad_block(
            *_row_block(a_indptr, a_indices, a_data, r0, r1), rows_pad, annz_pad
        )
        for j in range(gy):
            c0, c1 = int(col_splits[j]), int(col_splits[j + 1])
            if c1 <= c0:
                continue
            dev = grid[i, j]
            # column block of B as a CSC triple, then to CSR on-device
            bip, bix, bdv = _pad_block(
                *_row_block(b_indptr, b_indices, b_data, c0, c1),
                cols_pad,
                bnnz_pad,
            )
            dev_put = lambda a: jax.device_put(np.ascontiguousarray(a), dev)
            # the CSC triple of B[:, c0:c1] is the CSR of its transpose
            # [c, k]; csr_to_csc of that transpose is the CSR of the block
            tb_ip, tb_ix, tb_dv = csr_to_csc(
                dev_put(bip), dev_put(bix), dev_put(bdv), (cols_pad, k)
            )
            tiles[(i, j)] = spgemm_csr_csr(
                dev_put(aip), dev_put(aix), dev_put(adv),
                tb_ip, tb_ix, tb_dv,
                (rows_pad, k), (k, cols_pad),
                m_real=rows_real,
            )
            real_rows[(i, j)] = r1 - r0

    # Stitch: per row block, merge grid-j tiles row-by-row (vectorized
    # lexsort assembly — the host-side analog of the 3-phase shuffle).
    # Padded tile rows are empty; slice to the real row count.
    rows_all, cols_all, vals_all = [], [], []
    for (i, j), (tip, tix, tdv) in tiles.items():
        nr = real_rows[(i, j)]
        tip = np.asarray(tip).astype(np.int64)[: nr + 1]
        nreal = int(tip[-1])
        tix = np.asarray(tix).astype(np.int64)[:nreal]
        tdv = np.asarray(tdv)[:nreal]
        cnt = np.diff(tip)
        trows = np.repeat(np.arange(len(cnt), dtype=np.int64), cnt)
        rows_all.append(trows + int(row_splits[i]))
        cols_all.append(tix + int(col_splits[j]))
        vals_all.append(tdv)
    if rows_all:
        rows = np.concatenate(rows_all)
        cols = np.concatenate(cols_all)
        vals = np.concatenate(vals_all)
        order = np.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], vals[order]
    else:
        rows = cols = np.zeros(0, dtype=np.int64)
        vals = np.zeros(0, dtype=np.result_type(A.dtype, B.dtype))
    indptr = np.zeros(m + 1, dtype=np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr)
    return sparse_tpu.csr_array.from_parts(vals, cols, indptr, (m, n))
