"""Distributed SpGEMM over the device mesh.

Reference analogs:
  * row-gather CSR x CSR (``/root/reference/sparse/csr.py:1317-1490``): each
    rank computes a LOCAL CSR tile of ``A_rowblock @ B`` (GPU path: per-rank
    cuSPARSE SpGEMM), then a Python-side FutureMap scan stitches the local
    ``pos`` arrays into the global CSR (csr.py:1377-1389).
  * 3-phase 2-D CSR x CSC (``csr.py:1495-1728``): a (gx, gy) processor grid;
    B's rows replicated along grid-j, C's columns along grid-i; local tiles
    -> comm plan -> shuffle gather.

TPU-native redesign: sparse output sizes are data-dependent, so SpGEMM is a
setup-phase op here exactly as in the reference (which blocks on nnz futures
at csr.py:996 and scans pos on the control thread). Each tile is computed by
the single-device ESC kernel (``ops.spgemm``) ON ITS OWN DEVICE of the mesh
— per-shard inputs are committed to device s, so XLA dispatches the tile
programs concurrently across the mesh — and the host performs the pos-scan
stitch. The solver-facing hot path stays in ``parallel.dist`` (static-shape
SPMD); this module is how distributed hierarchies (AMG's Galerkin R@A@P)
get BUILT.
"""

from __future__ import annotations

import jax
import numpy as np

from .mesh import get_mesh, get_mesh_2d
from .partition import balanced_row_splits, equal_row_splits


def _row_block(indptr, indices, data, r0: int, r1: int):
    """Host-side zero-copy row slice of a CSR triple."""
    lo, hi = int(indptr[r0]), int(indptr[r1])
    return indptr[r0 : r1 + 1] - indptr[r0], indices[lo:hi], data[lo:hi]


def dist_spgemm(A, B, mesh=None, balanced: bool = True):
    """C = A @ B (both ``csr_array``) with A row-split over the mesh.

    The row-gather algorithm (csr.py:1390-1490): shard s computes
    ``A[rows_s] @ B`` as a local CSR tile on device s (B replicated, like
    the reference's gathered-C), then the host stitches tiles with one pos
    scan. Returns a ``csr_array``.
    """
    import sparse_tpu

    if mesh is None:
        mesh = get_mesh()
    devs = list(mesh.devices.reshape(-1))
    S = len(devs)
    m, k = A.shape
    k2, n = B.shape
    if k != k2:
        raise ValueError(f"dimension mismatch: {A.shape} @ {B.shape}")

    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    splits = (
        balanced_row_splits(indptr, S) if balanced else equal_row_splits(m, S)
    )

    from ..ops.spgemm import spgemm_csr_csr

    tiles = []
    for s in range(S):
        r0, r1 = int(splits[s]), int(splits[s + 1])
        if r1 <= r0:
            tiles.append(None)
            continue
        ip, ix, dv = _row_block(indptr, indices, data, r0, r1)
        dev = devs[s]
        args = [jax.device_put(np.ascontiguousarray(a), dev) for a in (ip, ix, dv)]
        bargs = [jax.device_put(np.asarray(a), dev) for a in (B.indptr, B.indices, B.data)]
        tiles.append(
            spgemm_csr_csr(
                args[0], args[1], args[2],
                bargs[0], bargs[1], bargs[2],
                (r1 - r0, k), (k, n),
            )
        )
    # Host pos-scan stitch (scan_local_results_and_scale_pos analog).
    out_indptr = np.zeros(m + 1, dtype=np.int64)
    parts_ix, parts_dv = [], []
    offset = 0
    for s in range(S):
        r0, r1 = int(splits[s]), int(splits[s + 1])
        if tiles[s] is None:
            out_indptr[r0 + 1 : r1 + 1] = offset
            continue
        tip, tix, tdv = (np.asarray(t) for t in tiles[s])
        out_indptr[r0 + 1 : r1 + 1] = tip[1:].astype(np.int64) + offset
        offset += int(tip[-1])
        parts_ix.append(tix)
        parts_dv.append(tdv)
    out_indices = (
        np.concatenate(parts_ix) if parts_ix else np.zeros(0, dtype=np.int32)
    )
    out_data = (
        np.concatenate(parts_dv)
        if parts_dv
        else np.zeros(0, dtype=np.result_type(A.dtype, B.dtype))
    )
    return sparse_tpu.csr_array.from_parts(
        out_data, out_indices, out_indptr, (m, n)
    )


def dist_spgemm_2d(A, B, mesh2d=None):
    """C = A @ B on a 2-D (gx, gy) processor grid — the CSR x CSC analog.

    Tile (i, j) = ``A[rowblock_i] @ B[:, colblock_j]`` computed on device
    (i, j): A's row blocks are replicated along grid-j and B's column blocks
    along grid-i, matching the reference's 2-D replicated layout
    (csr.py:1495-1571). B may be ``csc_array`` (column slicing is an indptr
    slice) or ``csr_array`` (converted once). The shuffle phase
    (csr.py:1592-1728) collapses into the host stitch: tiles of one row
    block concatenate in grid-j order, already column-sorted.
    """
    import sparse_tpu

    if mesh2d is None:
        mesh2d = get_mesh_2d()
    grid = mesh2d.devices
    gx, gy = grid.shape
    m, k = A.shape
    k2, n = B.shape
    if k != k2:
        raise ValueError(f"dimension mismatch: {A.shape} @ {B.shape}")

    Bcsc = B.tocsc()
    b_indptr = np.asarray(Bcsc.indptr)
    b_indices = np.asarray(Bcsc.indices)
    b_data = np.asarray(Bcsc.data)

    a_indptr = np.asarray(A.indptr)
    a_indices = np.asarray(A.indices)
    a_data = np.asarray(A.data)
    row_splits = balanced_row_splits(a_indptr, gx)
    col_splits = equal_row_splits(n, gy)

    from ..ops.conv import csr_to_csc
    from ..ops.spgemm import spgemm_csr_csr

    tiles = {}
    for i in range(gx):
        r0, r1 = int(row_splits[i]), int(row_splits[i + 1])
        if r1 <= r0:
            continue
        aip, aix, adv = _row_block(a_indptr, a_indices, a_data, r0, r1)
        for j in range(gy):
            c0, c1 = int(col_splits[j]), int(col_splits[j + 1])
            if c1 <= c0:
                continue
            dev = grid[i, j]
            # column block of B as a CSC triple, then to CSR on-device
            bip, bix, bdv = _row_block(b_indptr, b_indices, b_data, c0, c1)
            dev_put = lambda a: jax.device_put(np.ascontiguousarray(a), dev)
            # the CSC triple of B[:, c0:c1] is the CSR of its transpose
            # [c, k]; csr_to_csc of that transpose is the CSR of the block
            tb_ip, tb_ix, tb_dv = csr_to_csc(
                dev_put(bip), dev_put(bix), dev_put(bdv), (c1 - c0, k)
            )
            tiles[(i, j)] = spgemm_csr_csr(
                dev_put(aip), dev_put(aix), dev_put(adv),
                tb_ip, tb_ix, tb_dv,
                (r1 - r0, k), (k, c1 - c0),
            )

    # Stitch: per row block, merge grid-j tiles row-by-row (vectorized
    # lexsort assembly — the host-side analog of the 3-phase shuffle).
    rows_all, cols_all, vals_all = [], [], []
    for (i, j), (tip, tix, tdv) in tiles.items():
        tip = np.asarray(tip).astype(np.int64)
        tix = np.asarray(tix).astype(np.int64)
        tdv = np.asarray(tdv)
        cnt = np.diff(tip)
        trows = np.repeat(np.arange(len(cnt), dtype=np.int64), cnt)
        rows_all.append(trows + int(row_splits[i]))
        cols_all.append(tix + int(col_splits[j]))
        vals_all.append(tdv)
    if rows_all:
        rows = np.concatenate(rows_all)
        cols = np.concatenate(cols_all)
        vals = np.concatenate(vals_all)
        order = np.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], vals[order]
    else:
        rows = cols = np.zeros(0, dtype=np.int64)
        vals = np.zeros(0, dtype=np.result_type(A.dtype, B.dtype))
    indptr = np.zeros(m + 1, dtype=np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr)
    return sparse_tpu.csr_array.from_parts(vals, cols, indptr, (m, n))
