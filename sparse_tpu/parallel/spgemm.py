"""Distributed SpGEMM over the device mesh.

Reference analogs:
  * row-gather CSR x CSR (``/root/reference/sparse/csr.py:1317-1490``): each
    rank computes a LOCAL CSR tile of ``A_rowblock @ B`` (GPU path: per-rank
    cuSPARSE SpGEMM), then a Python-side FutureMap scan stitches the local
    ``pos`` arrays into the global CSR (csr.py:1377-1389).
  * 3-phase 2-D CSR x CSC (``csr.py:1495-1728``): a (gx, gy) processor grid;
    B's rows replicated along grid-j, C's columns along grid-i; local tiles
    -> comm plan -> shuffle gather.

TPU-native redesign: sparse output sizes are data-dependent, so SpGEMM is a
setup-phase op here exactly as in the reference (which blocks on nnz futures
at csr.py:996 and scans pos on the control thread). Each tile is computed by
the single-device ESC kernel (``ops.spgemm``) ON ITS OWN DEVICE of the mesh
— per-shard inputs are committed to device s, so XLA dispatches the tile
programs concurrently across the mesh — and one compiled compaction performs
the pos-scan stitch (host reads only the S tile counts).
The solver-facing hot path stays in ``parallel.dist`` (static-shape
SPMD); this module is how distributed hierarchies (AMG's Galerkin R@A@P)
get BUILT.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import get_mesh, get_mesh_2d
from .partition import balanced_row_splits, equal_row_splits

from .mesh import shard_map  # version-portable (check_vma/check_rep shim)


# Diagnostic record of the last dist_spgemm's per-shard memory footprint
# (entries, not bytes). Tests assert the image gather keeps per-device B
# size ~nnz(B)/S instead of nnz(B); benchmarks report it.
LAST_STATS: dict = {}


def _bucket(v: int, bits: int = 3) -> int:
    """Shape bucket: pow2 envelope quantized to 2**bits steps per octave
    (≤ 1/2**bits padding) — compile-shape reuse without pow2's up-to-2x
    memory overshoot."""
    from ..ops.spgemm import _next_pow2

    v = max(int(v), 1)
    step = max(_next_pow2(v) >> bits, 1)
    return -(-v // step) * step


def _row_block(indptr, indices, data, r0: int, r1: int):
    """Host-side zero-copy row slice of a CSR triple."""
    lo, hi = int(indptr[r0]), int(indptr[r1])
    return indptr[r0 : r1 + 1] - indptr[r0], indices[lo:hi], data[lo:hi]


def _pad_block(ip, ix, dv, rows_pad: int, nnz_pad: int):
    """Pad a CSR triple to (rows_pad, nnz_pad): appended rows are empty
    (indptr extends flat), appended nnz slots sit beyond indptr[-1] and are
    masked out by ``ops.spgemm.spgemm_csr_csr``. Uniform tile shapes mean
    all shards of a product — and nearby levels of a hierarchy — share one
    compiled ESC program instead of compiling per exact tile size."""
    nr = ip.shape[0] - 1
    nnz = ix.shape[0]
    ip_p = np.concatenate([ip, np.full(rows_pad - nr, ip[-1], dtype=ip.dtype)])
    ix_p = np.concatenate([ix, np.zeros(nnz_pad - nnz, dtype=ix.dtype)])
    dv_p = np.concatenate([dv, np.zeros(nnz_pad - nnz, dtype=dv.dtype)])
    return ip_p, ix_p, dv_p


@partial(
    jax.jit, static_argnames=("mesh", "axis", "n", "T", "dt", "m_real")
)
def _esc_sharded(
    ipA, ixA, dvA, ipB, ixB, dvB, mesh, axis, n, T, dt, m_real
):
    """All S tiles in ONE compiled shard_map program: A tiles AND each
    shard's image-gathered B tile sharded on the mesh — so the grid runs
    concurrently and the compile is shared across shards AND across calls
    with the same bucket shapes (successive AMG levels, repeated Galerkin
    products). The per-shard body is the shared traced ESC core
    (``ops.spgemm.esc_expand_sort_compress``, the row-gather SpGEMM tile of
    reference csr.py:1390-1490); A's column ids arrive pre-remapped into
    the local B row space."""
    from ..ops.spgemm import esc_expand_sort_compress

    def shard_fn(ipA_l, ixA_l, dvA_l, ipB_l, ixB_l, dvB_l):
        ur, uc, uv, nu = esc_expand_sort_compress(
            ipA_l.squeeze(0), ixA_l.squeeze(0), dvA_l.squeeze(0),
            ipB_l.squeeze(0), ixB_l.squeeze(0), dvB_l.squeeze(0),
            n=n, T=T, U=T, dt=dt, m_real=m_real,
        )
        return ur[None], uc[None], uv[None], nu.astype(jnp.int32)[None]

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(axis, None), P(axis, None), P(axis, None),
            P(axis, None), P(axis, None), P(axis, None),
        ),
        out_specs=(P(axis, None), P(axis, None), P(axis, None), P(axis)),
        check_vma=False,
    )(ipA, ixA, dvA, ipB, ixB, dvB)


@partial(jax.jit, static_argnames=("m", "Tout"))
def _stitch_tiles(urows, ucols, uvals, nuniques, splits_dev, *, m, Tout):
    """Pack S padded ESC tiles into one canonical CSR, on device.

    Tile s's first ``nuniques[s]`` slots are valid, already sorted by
    (local row, col); shard-major flattening therefore preserves global
    (row, col) order because shards own disjoint ascending row blocks.
    Scatter positions come from one exclusive scan of the valid mask;
    indptr from a segment count over global rows.
    """
    S, Pp = urows.shape
    cdt = splits_dev.dtype  # caller-chosen index width (no-x64 safe)
    valid = jnp.arange(Pp, dtype=jnp.int32)[None, :] < nuniques[:, None]
    grows = urows.astype(cdt) + splits_dev[:S, None]
    flat_valid = valid.reshape(-1)
    # scatter target: pos-scan slot for valid entries; invalid slots all
    # land on the sacrificial Tout slot, trimmed below
    pos = jnp.cumsum(flat_valid.astype(cdt)) - 1
    tgt = jnp.where(flat_valid, pos, Tout)
    out_ix = jnp.zeros(Tout + 1, dtype=ucols.dtype).at[tgt].set(
        ucols.reshape(-1)
    )[:Tout]
    out_dv = jnp.zeros(Tout + 1, dtype=uvals.dtype).at[tgt].set(
        uvals.reshape(-1)
    )[:Tout]
    row_counts = jax.ops.segment_sum(
        flat_valid.astype(cdt),
        jnp.where(flat_valid, grows.reshape(-1), m).astype(cdt),
        num_segments=m + 1,
    )[:m]
    out_ip = jnp.concatenate(
        [jnp.zeros((1,), cdt), jnp.cumsum(row_counts)]
    )
    return out_ip, out_ix, out_dv


def dist_spgemm(A, B, mesh=None, balanced: bool = True):
    """C = A @ B (both ``csr_array``) with A row-split over the mesh.

    The row-gather algorithm (csr.py:1390-1490): shard s computes
    ``A[rows_s] @ B_image_s`` as a local tile, where ``B_image_s`` holds
    ONLY the B rows reachable from shard s's A columns (the image
    partition of reference csr.py:1447-1465) — per-shard B memory scales
    as nnz(B)/S for banded operators, never as nnz(B). All S tiles are
    padded to one bucket shape and launched as a single shard_map
    program, then ONE compiled compaction packs the tiles into canonical
    CSR (the host reads only the S tile counts — the reference's O(S)
    future scan, csr.py:827-859). Returns a ``csr_array``.
    """
    import sparse_tpu

    if mesh is None:
        mesh = get_mesh()
    axis = mesh.axis_names[0]
    S = int(mesh.devices.size)
    m, k = A.shape
    k2, n = B.shape
    if k != k2:
        raise ValueError(f"dimension mismatch: {A.shape} @ {B.shape}")

    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    b_indptr = np.asarray(B.indptr)
    b_indices = np.asarray(B.indices)
    b_data = np.asarray(B.data)
    dt = np.result_type(A.dtype, B.dtype)
    splits = (
        balanced_row_splits(indptr, S) if balanced else equal_row_splits(m, S)
    )

    if A.nnz == 0 or B.nnz == 0:
        return sparse_tpu.csr_array.from_parts(
            np.zeros(0, dtype=dt),
            np.zeros(0, dtype=np.int32),
            np.zeros(m + 1, dtype=np.int32),
            (m, n),
        )

    from ..ops.spgemm import _next_pow2

    # Uniform padded tile shape across shards -> one compile for all S.
    rows_real = max(int(splits[s + 1] - splits[s]) for s in range(S))
    rows_pad = _next_pow2(rows_real)
    nnz_pad = _next_pow2(
        max(int(indptr[splits[s + 1]] - indptr[splits[s]]) for s in range(S))
    )
    bcounts = np.diff(b_indptr).astype(np.int64)

    # Image of B per shard: the sorted unique B rows this shard's A columns
    # touch. One host pass (the expansion bucket below reuses its slices) —
    # the reference computes the same set as a Legion image partition.
    kb_rows = []
    totals = []
    for s in range(S):
        lo, hi = int(indptr[splits[s]]), int(indptr[splits[s + 1]])
        cols_s = np.unique(indices[lo:hi])
        kb_rows.append(cols_s)
        # expansion bucket from the same pass (the reference's NNZ phase)
        totals.append(int(bcounts[indices[lo:hi]].sum()))
    T = _next_pow2(max(totals) + 1)
    kb_real = max((r.size for r in kb_rows), default=1)
    # B image tiles use a FINER shape bucket than pow2 (pow2 envelope, 1/8
    # steps): a banded operator's image is ~nnz(B)/S + halo, and rounding
    # that up to a full power of two could double per-device B memory —
    # exactly what the image gather exists to avoid. ≤12.5% padding keeps
    # the per-device footprint ∝ nnz(B)/S while still bucketing shapes.
    kb_pad = _bucket(kb_real)
    bnnz_pad = _bucket(
        max(
            (int(bcounts[r].sum()) for r in kb_rows if r.size),
            default=1,
        )
    )

    # indices stay in their native width (int32 when the inputs fit) — the
    # B index gathers dominate the tile's memory traffic
    idx_dt = np.int32 if max(n, k, int(indptr[-1]), int(b_indptr[-1])) < 2**31 else np.int64
    ipA = np.zeros((S, rows_pad + 1), dtype=idx_dt)
    ixA = np.zeros((S, nnz_pad), dtype=idx_dt)
    dvA = np.zeros((S, nnz_pad), dtype=data.dtype)
    ipB = np.zeros((S, kb_pad + 1), dtype=idx_dt)
    ixB = np.zeros((S, bnnz_pad), dtype=idx_dt)
    dvB = np.zeros((S, bnnz_pad), dtype=b_data.dtype)
    for s in range(S):
        r0, r1 = int(splits[s]), int(splits[s + 1])
        ip, ix, dv = _row_block(indptr, indices, data, r0, r1)
        # remap A's column ids into the local (gathered) B row space
        ix = np.searchsorted(kb_rows[s], ix).astype(idx_dt)
        ipA[s], ixA[s], dvA[s] = _pad_block(ip, ix, dv, rows_pad, nnz_pad)
        rws = kb_rows[s]
        cnts = bcounts[rws]
        local_ip = np.zeros(rws.size + 1, dtype=np.int64)
        np.cumsum(cnts, out=local_ip[1:])
        nb = int(local_ip[-1])
        # vectorized nnz gather of the image rows
        src = (
            np.arange(nb, dtype=np.int64)
            - np.repeat(local_ip[:-1], cnts)
            + np.repeat(b_indptr[rws].astype(np.int64), cnts)
        )
        ipB[s, : rws.size + 1] = local_ip
        ipB[s, rws.size + 1 :] = nb
        ixB[s, :nb] = b_indices[src]
        dvB[s, :nb] = b_data[src]

    LAST_STATS.clear()
    LAST_STATS.update(
        S=S,
        nnz_B=int(b_indptr[-1]),
        kb_pad=kb_pad,
        bnnz_pad=bnnz_pad,
        rows_pad=rows_pad,
        nnz_pad=nnz_pad,
        T=T,
    )

    sh = NamedSharding(mesh, P(axis, None))
    urows, ucols, uvals, nuniques = _esc_sharded(
        jax.device_put(ipA, sh),
        jax.device_put(ixA, sh),
        jax.device_put(dvA, sh),
        jax.device_put(ipB, sh),
        jax.device_put(ixB, sh),
        jax.device_put(dvB, sh),
        mesh=mesh, axis=axis, n=int(n), T=T, dt=jnp.dtype(dt),
        m_real=rows_real,
    )

    # DEVICE-side stitch (scan_local_results_and_scale_pos analog): the
    # host reads only the S tile counts — the reference's O(S) future
    # scan — while the O(nnz) compaction (masked scatter into pos-scan
    # slots + per-row counts) runs as one compiled program. The packed
    # output stays device-resident for downstream mesh ops.
    counts_host = np.asarray(nuniques)          # O(S) host fetch
    total = int(counts_host.sum())
    if total == 0:
        return sparse_tpu.csr_array.from_parts(
            np.zeros(0, dtype=dt), np.zeros(0, dtype=np.int32),
            np.zeros(m + 1, dtype=np.int32), (m, n),
        )
    Tout = _next_pow2(total)  # pow-2 bucket: bounded retrace count
    # index width for the scans: int32 unless the problem genuinely needs
    # more (raise-loudly per-dimension policy; int64 requires x64). The
    # scatter bound is Tout (pow-2 >= total) and the sentinel segment id
    # is m, so BOTH must fit the chosen width.
    if max(Tout, m + 1) < 2**31:
        sdt = np.int32
    elif jax.config.jax_enable_x64:
        sdt = np.int64
    else:
        raise ValueError(
            "dist_spgemm output exceeds int32 indexing; enable x64"
        )
    splits_dev = jnp.asarray(np.asarray(splits, dtype=sdt))
    # land the sharded tiles on ONE device first: jitting directly over
    # the mesh-sharded inputs makes GSPMD distribute the pos-scan as
    # cross-device cumsum collectives — 64-participant rendezvous chains
    # that abort under load on virtual CPU meshes (and buy nothing: the
    # packed CSR is a single logical array either way). An explicit
    # device_put is a plain device-to-device copy, no collectives.
    d0 = mesh.devices.flat[0]
    out_ip, out_ix, out_dv = _stitch_tiles(
        jax.device_put(urows, d0), jax.device_put(ucols, d0),
        jax.device_put(uvals, d0), jax.device_put(nuniques, d0),
        splits_dev, m=m, Tout=Tout,
    )
    return sparse_tpu.csr_array.from_parts(
        out_dv[:total], out_ix[:total], out_ip, (m, n)
    )


@partial(
    jax.jit,
    static_argnames=("mesh", "k_dim", "cols_pad", "T", "dt", "rows_real"),
)
def _spgemm2d_tiles(
    aip, aix, adv, bip, bix, bdv, col_starts, subsplits,
    mesh, k_dim, cols_pad, T, dt, rows_real,
):
    """Phase 1 (reference LOCAL_TILES, csr.py:1513-1571) as ONE compiled
    shard_map program over the whole (gx, gy) grid: A row blocks sharded on
    gx (replicated over gy), B column blocks sharded on gy (replicated over
    gx). Each device converts its B column block to row-major form and runs
    the shared ESC tile. Returns per-device sorted COO triples (rows local
    to the A row block, GLOBAL columns, values) padded to T with sentinel
    rows == rows_real."""
    from ..ops.conv import csr_to_csc
    from ..ops.spgemm import esc_expand_sort_compress

    ax_x, ax_y = mesh.axis_names

    def body(aip_l, aix_l, adv_l, bip_l, bix_l, bdv_l, cst, sub):
        # the CSC triple of B[:, c0:c1] is the CSR of its transpose
        # [c, k]; csr_to_csc of that transpose is the CSR of the block
        tb_ip, tb_ix, tb_dv = csr_to_csc(
            bip_l.squeeze(0), bix_l.squeeze(0), bdv_l.squeeze(0),
            (cols_pad, k_dim),
        )
        ur, uc, uv, _nu = esc_expand_sort_compress(
            aip_l.squeeze(0), aix_l.squeeze(0), adv_l.squeeze(0),
            tb_ip, tb_ix, tb_dv,
            n=cols_pad, T=T, U=T, dt=dt, m_real=rows_real,
        )
        ucg = uc + cst.reshape(()).astype(uc.dtype)  # block-local -> global
        # send bounds for the shuffle: entries of sub-block j' are rows in
        # [sub[j'], sub[j'+1]); sentinels (row == rows_real) fall past the
        # last boundary and are never sent
        bounds = jnp.searchsorted(ur, sub.reshape(-1), side="left").astype(
            jnp.int32
        )
        return (
            ur[None, None],
            ucg[None, None],
            uv[None, None],
            bounds[None, None],
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(ax_x, None), P(ax_x, None), P(ax_x, None),
            P(ax_y, None), P(ax_y, None), P(ax_y, None),
            P(ax_y), P(ax_x, None),
        ),
        out_specs=(
            P(ax_x, ax_y, None), P(ax_x, ax_y, None), P(ax_x, ax_y, None),
            P(ax_x, ax_y, None),
        ),
        check_vma=False,
    )(aip, aix, adv, bip, bix, bdv, col_starts, subsplits)


@partial(
    jax.jit,
    static_argnames=(
        "mesh", "cap", "U", "gy", "rows_real", "R_out", "S_out", "C_out",
        "native",
    ),
)
def _spgemm2d_shuffle(
    r, c, v, subsplits, row_off, col_splits_out,
    mesh, cap, U, gy, rows_real, R_out, S_out, C_out, native,
):
    """Phase 2+3 (reference COMM_COMPUTE + SHUFFLE, csr.py:1592-1728) on
    device: each device slices its tile by destination row sub-block and a
    ``ragged_all_to_all`` along the gy axis lands every row block's tiles
    on its owner device — tile (i, j') sends the rows of sub-block (i, j)
    to device (i, j). The received chunks (one per source j', col-disjoint
    and ordered) merge with ONE stable row sort. Output: per-device local
    COO in the DistCSR padded coordinate space ([S_out*C_out] columns) plus
    per-device valid counts and column-window stats."""
    from . import comm
    from .sort import _ragged_a2a

    ax_x, ax_y = mesh.axis_names
    # geometry-keyed: jit caches this program per static-arg combo, so the
    # committed bytes must come from the ledger THIS geometry traced
    led = comm.ledger("spgemm2d.shuffle", key=(mesh, gy, cap, U))

    def body(r_l, c_l, v_l, sub, roff, csp):
        r1 = r_l.reshape(-1)
        c1 = c_l.reshape(-1)
        v1 = v_l.reshape(-1)
        bounds = jnp.searchsorted(r1, sub.reshape(-1), side="left").astype(
            jnp.int32
        )
        starts, send = bounds[:-1], bounds[1:] - bounds[:-1]
        recv = comm.all_to_all(
            send[:, None], ax_y, 0, 0, axis_size=gy, ledger=led, tag="counts",
        ).reshape(-1)
        out_off = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(recv)[:-1].astype(jnp.int32)]
        )
        sent_row = jnp.asarray(rows_real, r1.dtype)  # > any real local row
        r2 = _ragged_a2a(
            r1, jnp.full((cap,), sent_row), starts, send, out_off, recv,
            ax_y, gy, U, native, ledger=led, tag="rows",
        )
        c2 = _ragged_a2a(
            c1, jnp.zeros((cap,), c1.dtype), starts, send, out_off, recv,
            ax_y, gy, U, native, ledger=led, tag="cols",
        )
        v2 = _ragged_a2a(
            v1, jnp.zeros((cap,), v1.dtype), starts, send, out_off, recv,
            ax_y, gy, U, native, ledger=led, tag="vals",
        )
        # chunks arrive in source order (out_off is cumsum over j') with
        # disjoint ascending column ranges, and each chunk is (row, col)
        # sorted — ONE stable sort by row is a full (row, col) merge
        order = jnp.argsort(r2, stable=True)
        r2, c2, v2 = r2[order], c2[order], v2[order]
        nvalid = jnp.sum(recv).astype(jnp.int32)
        slot = jnp.arange(cap, dtype=jnp.int32)
        valid = slot < nvalid
        rloc = jnp.where(
            valid,
            jnp.clip(r2 - roff.reshape(()).astype(r2.dtype), 0, R_out - 1),
            R_out - 1,
        ).astype(jnp.int32)
        # global column -> DistCSR padded coordinate space (int32 when the
        # padded space fits — int64 under no-x64 would silently truncate)
        pdt = jnp.int64 if S_out * C_out > 2**31 - 1 else jnp.int32
        csp = csp.reshape(-1)
        cshard = jnp.clip(
            jnp.searchsorted(csp, c2, side="right") - 1, 0, S_out - 1
        )
        pcol = cshard.astype(pdt) * C_out + (
            c2.astype(pdt) - csp[cshard].astype(pdt)
        )
        pcol = jnp.where(valid, pcol, 0)
        v2 = jnp.where(valid, v2, 0)
        big = jnp.asarray(S_out * C_out, pcol.dtype)
        cmin = jnp.min(jnp.where(valid, pcol, big))
        cmax = jnp.max(jnp.where(valid, pcol, -1))
        return (
            rloc[None, None],
            pcol[None, None],
            v2[None, None],
            nvalid[None, None],
            cmin[None, None],
            cmax[None, None],
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(ax_x, ax_y, None), P(ax_x, ax_y, None), P(ax_x, ax_y, None),
            P(ax_x, None), P(ax_x, ax_y), P(None),
        ),
        out_specs=(
            P(ax_x, ax_y, None), P(ax_x, ax_y, None), P(ax_x, ax_y, None),
            P(ax_x, ax_y), P(ax_x, ax_y), P(ax_x, ax_y),
        ),
        check_vma=False,
    )(r, c, v, subsplits, row_off, col_splits_out)


@partial(jax.jit, static_argnames=("S_out", "cap", "W", "lidt", "sh1"))
def _flatten_adjust(r3, c3, v3, offs, S_out, cap, W, lidt, sh1):
    """[gx, gy, cap] 2-D-mesh tiles -> [S, cap] row-sharded on the 1-D mesh
    (device-to-device resharding) with columns shifted into the DistCSR
    window space. Module-level so repeated products with one bucket shape
    share the compile."""
    r2 = jax.lax.with_sharding_constraint(r3.reshape(S_out, cap), sh1)
    c2 = jax.lax.with_sharding_constraint(
        jnp.clip(c3.reshape(S_out, cap) - offs, 0, W - 1).astype(lidt), sh1
    )
    v2 = jax.lax.with_sharding_constraint(v3.reshape(S_out, cap), sh1)
    return r2, c2, v2


def dist_spgemm_2d(A, B, mesh2d=None, as_dist: bool = False):
    """C = A @ B on a 2-D (gx, gy) processor grid — the CSR x CSC analog.

    Tile (i, j) = ``A[rowblock_i] @ B[:, colblock_j]`` computed on device
    (i, j): A's row blocks are replicated along grid-j and B's column
    blocks along grid-i, matching the reference's 2-D replicated layout
    (csr.py:1495-1571). The shuffle phase (csr.py:1592-1728) runs ON
    DEVICE: a ``ragged_all_to_all`` along the gy axis lands each row
    sub-block's entries on its owner device, where one stable row sort
    merges them — the host only ever sees the O(S * gy) send-count matrix
    (to size the exchange buffer) and O(S) window scalars, never the nnz.

    ``as_dist=True`` returns the result as a row-sharded ``DistCSR``
    (sub-block (i, j) of the row space owned by device (i, j), flattened
    row-major); the default materializes a host ``csr_array`` by
    concatenating the per-shard already-sorted blocks (no global lexsort).
    """
    import sparse_tpu

    from ..ops.spgemm import _next_pow2
    from .dist import DistCSR, windows_to_halo

    if mesh2d is None:
        mesh2d = get_mesh_2d()
    grid = mesh2d.devices
    gx, gy = grid.shape
    m, k = A.shape
    k2, n = B.shape
    if k != k2:
        raise ValueError(f"dimension mismatch: {A.shape} @ {B.shape}")
    if max(m, n, k) >= 2**31:
        raise ValueError("dist_spgemm_2d uses int32/int64-mixed indices; "
                         f"dimensions {A.shape} @ {B.shape} exceed int32")

    Bcsc = B.tocsc()
    b_indptr = np.asarray(Bcsc.indptr)
    b_indices = np.asarray(Bcsc.indices)
    b_data = np.asarray(Bcsc.data)

    a_indptr = np.asarray(A.indptr)
    a_indices = np.asarray(A.indices)
    a_data = np.asarray(A.data)
    dt = np.result_type(A.dtype, B.dtype)
    row_splits = balanced_row_splits(a_indptr, gx)
    col_splits = equal_row_splits(n, gy)

    # Uniform padded tile shapes -> one compile for the whole grid.
    rows_real = max(
        max(int(row_splits[i + 1] - row_splits[i]) for i in range(gx)), 1
    )
    rows_pad = _next_pow2(rows_real)
    annz_pad = _next_pow2(
        max(
            int(a_indptr[row_splits[i + 1]] - a_indptr[row_splits[i]])
            for i in range(gx)
        )
    )
    cols_pad = _next_pow2(
        max(int(col_splits[j + 1] - col_splits[j]) for j in range(gy))
    )
    bnnz_pad = _next_pow2(
        max(
            int(b_indptr[col_splits[j + 1]] - b_indptr[col_splits[j]])
            for j in range(gy)
        )
    )
    # expansion bucket: per column block j, the B row-length histogram over
    # k, then per tile the sum at A's column ids (the reference's NNZ phase)
    T = 1
    for j in range(gy):
        c0, c1 = int(col_splits[j]), int(col_splits[j + 1])
        cnt_j = np.bincount(b_indices[b_indptr[c0] : b_indptr[c1]], minlength=k)
        for i in range(gx):
            lo, hi = int(a_indptr[row_splits[i]]), int(a_indptr[row_splits[i + 1]])
            T = max(T, int(cnt_j[a_indices[lo:hi]].sum()))
    T = _next_pow2(T + 1)

    idx_dt = np.int32  # guarded above: every dimension fits int32
    aipA = np.zeros((gx, rows_pad + 1), dtype=idx_dt)
    aixA = np.zeros((gx, annz_pad), dtype=idx_dt)
    advA = np.zeros((gx, annz_pad), dtype=a_data.dtype)
    for i in range(gx):
        aipA[i], aixA[i], advA[i] = _pad_block(
            *_row_block(a_indptr, a_indices, a_data, int(row_splits[i]),
                        int(row_splits[i + 1])),
            rows_pad, annz_pad,
        )
    bipB = np.zeros((gy, cols_pad + 1), dtype=idx_dt)
    bixB = np.zeros((gy, bnnz_pad), dtype=idx_dt)
    bdvB = np.zeros((gy, bnnz_pad), dtype=b_data.dtype)
    for j in range(gy):
        bipB[j], bixB[j], bdvB[j] = _pad_block(
            *_row_block(b_indptr, b_indices, b_data, int(col_splits[j]),
                        int(col_splits[j + 1])),
            cols_pad, bnnz_pad,
        )
    # row sub-splits: block i's rows split into gy owner sub-blocks
    subsplits = np.zeros((gx, gy + 1), dtype=idx_dt)
    for i in range(gx):
        h = int(row_splits[i + 1] - row_splits[i])
        subsplits[i] = equal_row_splits(h, gy)

    ax_x, ax_y = mesh2d.axis_names
    shx = NamedSharding(mesh2d, P(ax_x, None))
    shy = NamedSharding(mesh2d, P(ax_y, None))
    ur, uc, uv, bounds = _spgemm2d_tiles(
        jax.device_put(aipA, shx),
        jax.device_put(aixA, shx),
        jax.device_put(advA, shx),
        jax.device_put(bipB, shy),
        jax.device_put(bixB, shy),
        jax.device_put(bdvB, shy),
        jax.device_put(
            col_splits[:-1].astype(idx_dt), NamedSharding(mesh2d, P(ax_y))
        ),
        jax.device_put(subsplits, shx),
        mesh=mesh2d, k_dim=int(k), cols_pad=cols_pad, T=T,
        dt=jnp.dtype(dt), rows_real=rows_real,
    )

    # Host sees ONLY the O(gx*gy*gy) send-count matrix: size the exchange
    # buffer to the tightest bucket over actual per-device receive totals.
    bnds = np.asarray(bounds)  # [gx, gy, gy+1]
    sends = bnds[:, :, 1:] - bnds[:, :, :-1]  # [gx, src j', dest j]
    recv_tot = sends.sum(axis=1)  # [gx, dest j]
    cap = _bucket(max(int(recv_tot.max()), 1))

    S_out = gx * gy
    R_out = max(
        max(
            int(subsplits[i, j + 1] - subsplits[i, j])
            for i in range(gx)
            for j in range(gy)
        ),
        1,
    )
    col_splits_out = equal_row_splits(n, S_out)
    C_out = max(int(np.max(np.diff(col_splits_out))), 1)
    lidt = np.int32 if S_out * C_out < 2**31 else np.int64
    if lidt is np.int64 and not jax.config.jax_enable_x64:
        raise ValueError(
            f"padded column space S*C = {S_out * C_out} needs int64; "
            "enable x64 with jax.config.update('jax_enable_x64', True)"
        )
    native = jax.default_backend() == "tpu"
    row_off = subsplits[:, :-1].astype(idx_dt)  # [gx, gy]
    rloc, pcol, vals, nvalid, cmin, cmax = _spgemm2d_shuffle(
        ur, uc, uv,
        jax.device_put(subsplits, shx),
        jax.device_put(row_off, NamedSharding(mesh2d, P(ax_x, ax_y))),
        jax.device_put(
            col_splits_out.astype(lidt), NamedSharding(mesh2d, P(None))
        ),
        mesh=mesh2d, cap=cap, U=T, gy=gy, rows_real=rows_real, R_out=R_out,
        S_out=S_out, C_out=C_out, native=native,
    )
    from . import comm as _comm

    _shuffle_led = _comm.ledger(
        "spgemm2d.shuffle", key=(mesh2d, gy, cap, T)
    )
    _shuffle_led.commit(1, S_out)

    # O(S) window stats -> halo widths via the policy shared with shard_csr
    cmin_h = np.asarray(cmin).reshape(-1)
    cmax_h = np.asarray(cmax).reshape(-1)
    nvalid_h = np.asarray(nvalid).reshape(-1).astype(np.int64)
    windows = [(int(cmin_h[s]), int(cmax_h[s]) + 1) for s in range(S_out)]
    HL, HR, mode = windows_to_halo(windows, C_out, S_out)

    # flatten (i, j) row-major onto the 1-D mesh: sub-block (i, j) covers
    # monotonically increasing global row ranges, so this IS row-sharding
    mesh1d = Mesh(grid.reshape(-1), ("shards",))
    sh1 = NamedSharding(mesh1d, P("shards", None))
    W = C_out + HL + HR if mode == "halo" else S_out * C_out
    offs = (
        (np.arange(S_out, dtype=lidt) * C_out - HL)[:, None]
        if mode == "halo"
        else np.zeros((S_out, 1), dtype=lidt)
    )
    nz_rows, nz_cols, nz_vals = _flatten_adjust(
        rloc, pcol, vals, jax.device_put(offs, NamedSharding(mesh1d, P("shards", None))),
        S_out=S_out, cap=cap, W=W, lidt=jnp.dtype(lidt), sh1=sh1,
    )

    row_splits_out = np.zeros(S_out + 1, dtype=np.int64)
    for i in range(gx):
        for j in range(gy):
            row_splits_out[i * gy + j + 1] = (
                int(row_splits[i]) + int(subsplits[i, j + 1])
            )

    dist = DistCSR(
        mesh=mesh1d,
        axis="shards",
        shape=(int(m), int(n)),
        row_splits=row_splits_out,
        col_splits=col_splits_out,
        R=R_out,
        C=C_out,
        HL=HL,
        HR=HR,
        mode=mode,
        layout="csr",
        dtype=np.dtype(dt),
        nz_rows=nz_rows,
        nz_cols=nz_cols,
        nz_vals=nz_vals,
    )
    LAST_STATS.clear()
    LAST_STATS.update(
        S=S_out, cap=cap, T=T, R=R_out, C=C_out, HL=HL, HR=HR, mode=mode,
        host_counts=int(sends.size),
    )
    from .. import telemetry

    if telemetry.enabled():
        # exact volumes from THIS product's host-visible send counts (not
        # the structural model in spgemm2d_comm_stats, which recomputes
        # the product): replication envelope + gy-axis shuffle entries
        # actually leaving each device
        iw = np.dtype(idx_dt).itemsize
        repl = (
            annz_pad * (iw + a_data.dtype.itemsize) + (rows_pad + 1) * iw
            + bnnz_pad * (iw + b_data.dtype.itemsize) + (cols_pad + 1) * iw
        )
        crossing = sends.sum(axis=2) - np.einsum("ijj->ij", sends)
        entry_bytes = iw + np.dtype(lidt).itemsize + np.dtype(dt).itemsize
        telemetry.record(
            "comm.spgemm2d", grid=[gx, gy],
            replicate_bytes_per_device=int(repl),
            shuffle_entries_sent=int(crossing.sum()),
            shuffle_entries_sent_max=int(crossing.max()),
            exchange_cap_entries=int(cap),
            bytes=int(repl) * S_out + int(crossing.sum()) * entry_bytes,
        )
        # shuffle-phase reconciliation (capacity-accounted: exact=False);
        # the model side here is the shuffle volume only — replication is
        # host device_put traffic, not a wrapped collective
        _comm.record_measured(
            "spgemm2d.shuffle", _shuffle_led,
            executions=1, shards=S_out,
            model_bytes=int(crossing.sum()) * entry_bytes or None,
            grid=[gx, gy],
        )
    if as_dist:
        return dist

    # host materialization: per-shard blocks are already (row, col) sorted —
    # concatenate and count, NO global lexsort
    nzr = np.asarray(nz_rows)
    nzc = np.asarray(nz_cols)
    nzv = np.asarray(nz_vals)
    row_counts = np.zeros(m, dtype=np.int64)
    parts_ix, parts_dv = [], []
    for s in range(S_out):
        nv = int(nvalid_h[s])
        r0 = int(row_splits_out[s])
        r1 = int(row_splits_out[s + 1])
        row_counts[r0:r1] = np.bincount(nzr[s, :nv], minlength=R_out)[: r1 - r0]
        # local/window col -> padded space -> global column id
        pc = nzc[s, :nv].astype(np.int64) + int(offs[s, 0])
        cshard = pc // C_out
        parts_ix.append(pc - cshard * C_out + col_splits_out[cshard])
        parts_dv.append(nzv[s, :nv])
    indptr = np.zeros(m + 1, dtype=np.int64)
    np.cumsum(row_counts, out=indptr[1:])
    out_indices = (
        np.concatenate(parts_ix) if parts_ix else np.zeros(0, dtype=np.int64)
    )
    out_data = np.concatenate(parts_dv) if parts_dv else np.zeros(0, dtype=dt)
    return sparse_tpu.csr_array.from_parts(out_data, out_indices, indptr, (m, n))


def spgemm2d_comm_stats(A, B, grid: tuple) -> dict:
    """Structural collective cost model for :func:`dist_spgemm_2d` on a
    (gx, gy) grid — exact, derived from the algorithm, never measured
    (the ``comm_stats`` discipline), so 2-D weak-scaling regressions show
    up without hardware.

    Exactness without simulating the tiles: tile (i, j) computes
    ``A[rowblock_i] @ B[:, colblock_j]``, which IS the (rowblock_i x
    colblock_j) sub-block of C — so ONE host product (native Gustavson)
    plus 2-D histograms yields every tile's nnz and every shuffle
    send/recv count the device-side ``_spgemm2d_shuffle`` would produce.

    Modeled, per device: the A row-block / B col-block replication
    broadcasts (CSR bytes landing on each device), the gy-axis
    ``ragged_all_to_all`` shuffle (entries leaving each device, and the
    capacity bucket actually used to size the exchange buffer), and the
    O(gx*gy*gy) host count fetch.

    Reference analog: the 2-D replicated layout + shuffle volumes of
    ``sparse/csr.py:1495-1728``.
    """
    import sparse_tpu

    gx, gy = (int(g) for g in grid)
    m, k = A.shape
    _, n = B.shape
    a_indptr = np.asarray(A.indptr)
    row_splits = np.asarray(balanced_row_splits(a_indptr, gx))
    col_splits = np.asarray(equal_row_splits(n, gy))
    b_csc_indptr = np.asarray(B.tocsc().indptr)
    iw = 4 if max(m, n, k) < 2**31 else 8
    vw = np.result_type(A.dtype, B.dtype).itemsize

    from ..ops.spgemm import _next_pow2

    a_nnz = a_indptr[row_splits[1:]] - a_indptr[row_splits[:-1]]  # [gx]
    b_nnz = b_csc_indptr[col_splits[1:]] - b_csc_indptr[col_splits[:-1]]
    a_rows = np.diff(row_splits)
    b_cols = np.diff(col_splits)
    # what MOVES is the pow2-padded uniform tile buffers (dist_spgemm_2d
    # pads every block to the max block's envelope for one compile), each
    # input in its OWN dtype (advA/bdvB stream as a_data/b_data dtypes) —
    # identical bytes on every device by construction
    rows_pad = _next_pow2(max(int(a_rows.max()), 1))
    annz_pad = _next_pow2(max(int(a_nnz.max()), 1))
    cols_pad = _next_pow2(max(int(b_cols.max()), 1))
    bnnz_pad = _next_pow2(max(int(b_nnz.max()), 1))
    avw = np.dtype(A.dtype).itemsize
    bvw = np.dtype(B.dtype).itemsize
    repl_device_bytes = (
        annz_pad * (iw + avw) + (rows_pad + 1) * iw
        + bnnz_pad * (iw + bvw) + (cols_pad + 1) * iw
    )

    C = (sparse_tpu.csr_array(A) @ sparse_tpu.csr_array(B)).tocsr()
    c_indptr = np.asarray(C.indptr)
    c_indices = np.asarray(C.indices)
    rows = np.repeat(np.arange(m), np.diff(c_indptr))
    iblk = np.searchsorted(row_splits, rows, side="right") - 1
    jsrc = np.searchsorted(col_splits, c_indices, side="right") - 1
    # destination owner: local row bucketed by block i's equal sub-splits
    local = rows - row_splits[iblk]
    jdst = np.zeros_like(local)
    for i in range(gx):
        sub = np.asarray(equal_row_splits(int(a_rows[i]), gy))
        sel = iblk == i
        jdst[sel] = np.searchsorted(sub, local[sel], side="right") - 1
    sends = np.zeros((gx, gy, gy), dtype=np.int64)  # [i, src j, dest j]
    np.add.at(sends, (iblk, jsrc, jdst), 1)
    tile_nnz = sends.sum(axis=2)  # [gx, gy]
    recv_tot = sends.sum(axis=1)  # [gx, dest j]
    crossing = tile_nnz - np.einsum("ijj->ij", sends)  # leaves device (i, j)
    cap = _bucket(max(int(recv_tot.max()), 1))
    # padded-column width mirrors dist_spgemm_2d's lidt selection exactly:
    # int32 iff S_out * C_out fits, with the UN-bucketed window width
    S_out = gx * gy
    C_out = max(int(np.max(np.diff(equal_row_splits(n, S_out)))), 1)
    pcol_w = 4 if S_out * C_out < 2**31 else 8
    entry_bytes = iw + pcol_w + vw  # r, padded col, value streams

    return {
        "grid": [gx, gy],
        "c_nnz": int(c_indices.shape[0]),
        "tile_nnz_max": int(tile_nnz.max()),
        "replicate_bytes_per_device": int(repl_device_bytes),
        "shuffle_entries_sent_max": int(crossing.max()),
        "shuffle_entries_sent_mean": float(crossing.mean()),
        "shuffle_bytes_per_device_max": int(crossing.max() * entry_bytes),
        "exchange_cap_entries": int(cap),
        "host_sync_bytes": int(gx * gy * gy * 4 + 2 * gx * gy * 8),
    }
