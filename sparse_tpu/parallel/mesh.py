"""Device mesh discovery and creation — the runtime singleton analog.

Reference analog: ``sparse/runtime.py:56-130`` (proc/GPU counts from mapper
tunables, eager NCCL init, store creation). On TPU the "runtime" collapses to:
``jax.distributed.initialize`` (the NCCL-init analog, runtime.py:85-87) plus a
``jax.sharding.Mesh`` over the visible devices. XLA owns placement and
collective routing over ICI/DCN; there is no mapper.

The mesh axis naming convention used throughout ``sparse_tpu.parallel``:
  * ``"shards"`` — the 1-D row-block data-parallel axis (the key-partition
    analog, csr.py:242-246).
  * 2-D grids for SpGEMM/cdist/quantum use ``("gx", "gy")`` shaped by
    ``utils.factor_int`` (utils.py:144-150 analog).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.8 top-level; older releases keep it in experimental
    from jax import shard_map as _shard_map_raw
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map_raw


def _make_shard_map():
    """Version-portable shard_map: the replication-check kwarg was renamed
    check_rep -> check_vma across jax releases; every call site in this
    package writes the new name and this shim translates for older jax.
    Single source — all of ``sparse_tpu.parallel`` imports from here."""
    import inspect

    try:
        params = inspect.signature(_shard_map_raw).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        return _shard_map_raw
    if "check_vma" in params or "check_rep" not in params:
        return _shard_map_raw

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_raw(*args, **kwargs)

    return shard_map


shard_map = _make_shard_map()

_initialized = False


def initialize_distributed(**kwargs) -> None:
    """Multi-host bring-up: the ``jax.distributed.initialize`` wrapper.

    The NCCL/coll eager-initialization analog (runtime.py:75-87). Idempotent;
    no-op for single-process runs (the common case under pytest and on a
    single chip).
    """
    global _initialized
    if _initialized:
        return
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or kwargs.get("coordinator_address"):
        jax.distributed.initialize(**kwargs)
    _initialized = True


def num_procs() -> int:
    """Total device count (the NUM_PROCS/NUM_GPUS tunable analog, mapper.cc:64-84).

    Env-overridable like LEGATE_SPARSE_NUM_PROCS (runtime.py:61-63).
    """
    env = os.environ.get("SPARSE_TPU_NUM_PROCS")
    if env is not None:
        return int(env)
    return len(jax.devices())


def get_mesh(num_shards: int | None = None, axis: str = "shards") -> Mesh:
    """A 1-D mesh over the first ``num_shards`` devices (default: all)."""
    devs = jax.devices()
    if num_shards is None:
        num_shards = len(devs)
    if num_shards > len(devs):
        raise ValueError(
            f"requested {num_shards} shards but only {len(devs)} devices"
        )
    return Mesh(np.array(devs[:num_shards]), (axis,))


def get_mesh_2d(num_procs_: int | None = None, axes=("gx", "gy")) -> Mesh:
    """A near-square 2-D mesh (factor_int analog) for 2-D-grid algorithms."""
    from ..utils import factor_int

    devs = jax.devices()
    if num_procs_ is None:
        num_procs_ = len(devs)
    gx, gy = factor_int(num_procs_)
    return Mesh(np.array(devs[: gx * gy]).reshape(gx, gy), axes)


def mesh_fingerprint(mesh: Mesh) -> str:
    """Deterministic topology identity of a mesh: platform kinds, grid
    shape and axis names — ``"cpu:8:shards"`` for an 8-way 1-D CPU mesh.

    Stable across processes on the same topology (device *kinds* and
    counts, never volatile ids), so it can key persisted artifacts: the
    fleet serving tier (``sparse_tpu.fleet``) bakes it into plan-cache
    keys and the vault warm-start manifest, ensuring a restart on a
    DIFFERENT topology cold-starts cleanly instead of replaying programs
    compiled for the old mesh."""
    devs = mesh.devices
    kinds = sorted({str(getattr(d, "platform", "?")) for d in devs.flat})
    shape = "x".join(str(int(s)) for s in devs.shape)
    return f"{'+'.join(kinds)}:{shape}:{','.join(mesh.axis_names)}"


def mesh_device_key(mesh: Mesh) -> tuple:
    """Concrete device identity of a mesh: the ordered tuple of device
    ids. Complements :func:`mesh_fingerprint` for LIVE topology-change
    detection (``sparse_tpu.fleet.elastic``): a *swap* — same platform,
    same count, different physical devices — keeps the fingerprint but
    changes this key, so the elastic tier can tell "same shape" from
    "same devices". Never persisted (ids are volatile across
    processes); the vault manifest keys on the fingerprint alone."""
    return tuple(
        int(getattr(d, "id", i))
        for i, d in enumerate(mesh.devices.flat)
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis: str = "shards") -> NamedSharding:
    return NamedSharding(mesh, P(axis))
