"""Row-block partitioning policies for the device mesh.

Reference analog: ``sparse/partition.py`` (CompressedImagePartition
partition.py:56-137, MinMaxImagePartition partition.py:139-214, DensePreimage
partition.py:216-280) and ``DenseSparseBase.balance`` (base.py:198-282).

On TPU, Legion's dependent partitioning collapses into static host-side
decisions made once per matrix:
  * equal row tiles            -> `equal_row_splits`
  * nnz-balanced row tiles     -> `balanced_row_splits` (the balance() analog)
  * per-shard column windows   -> `column_windows` (the MinMaxImage analog:
    what slice of x each shard's SpMV needs)
The splits feed ``sparse_tpu.parallel.dist`` which materializes padded,
mesh-sharded arrays.
"""

from __future__ import annotations

import numpy as np


def equal_row_splits(m: int, num_shards: int) -> np.ndarray:
    """Row-tile boundaries [0, ..., m], equal rows per shard (the default key
    partition, csr.py:242-246)."""
    return np.linspace(0, m, num_shards + 1).astype(np.int64)


def balanced_row_splits(indptr, num_shards: int) -> np.ndarray:
    """nnz-balanced row boundaries: preimage of an equal nnz split (base.py:198).

    One host-side searchsorted over the monotone indptr."""
    iptr = np.asarray(indptr)
    m = iptr.shape[0] - 1
    nnz = int(iptr[-1])
    targets = np.linspace(0, nnz, num_shards + 1)
    splits = np.searchsorted(iptr, targets, side="left").astype(np.int64)
    splits[0], splits[-1] = 0, m
    return np.maximum.accumulate(splits)


def column_windows(indptr, indices, splits) -> np.ndarray:
    """Per-shard [lo, hi) bounds of the column ids touched by each row block.

    The MinMaxImagePartition analog (partition.py:139-214): what window of x a
    shard's SpMV must gather. For banded matrices the windows are narrow and
    overlap only with mesh neighbors -> halo exchange over ICI.
    """
    iptr = np.asarray(indptr)
    idx = np.asarray(indices)
    S = len(splits) - 1
    out = np.zeros((S, 2), dtype=np.int64)
    for s in range(S):
        lo, hi = int(iptr[splits[s]]), int(iptr[splits[s + 1]])
        if hi > lo:
            seg = idx[lo:hi]
            out[s, 0] = int(seg.min())
            out[s, 1] = int(seg.max()) + 1
        else:
            out[s] = (0, 0)
    return out
