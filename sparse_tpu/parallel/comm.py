"""Measured collective accounting: thin wrappers over the XLA collectives.

Every explicit collective this package issues (``psum``, ``psum_scatter``,
``ppermute``, ``all_gather``, ``all_to_all``, ``ragged_all_to_all``) goes
through a wrapper here. At trace time the wrapper computes the exact
payload bytes from the operand's static shape and notes them on a
:class:`SiteLedger` — the per-program record of what one *execution* of
that program moves over the interconnect. Host-side call sites then
``commit()`` the ledger with the execution count they observed (one per
eager SpMV, ``iters + 1`` per distributed CG solve), which feeds the
always-on ``comm.collectives{op,site}`` / ``comm.collective_bytes{op,site}``
metric families, and — with telemetry on — emit a ``comm.measured`` event
reconciled against the analytic ``model=True`` estimates the same sites
have recorded since PR 1 (``comm_stats`` / ``sort_comm_stats`` /
``spgemm2d_comm_stats``). Divergence between the two is itself a signal:
the model drifted from the implementation, or a collective was added
without accounting.

Why trace-time accounting counts as *measured*: shard_map bodies run with
static shapes, so the payload of each collective is exact at trace time —
unlike the analytic models, which re-derive the volumes from the matrix
structure and can silently disagree with what was actually compiled.
Two caveats, both carried on the events:

* GSPMD-inserted collectives (the ``psum`` behind a ``jnp.vdot`` on
  sharded operands) are invisible to wrappers — the scalar reduction
  traffic of a Krylov iteration is counted only by the model (a few
  itemsizes per iteration; the documented expected divergence).
* ``ragged_all_to_all`` payloads are runtime-dynamic; the wrapper
  accounts the operand *capacity* as an upper bound and marks the entry
  ``exact=False`` (the ledger's ``exact`` flag goes false with it).

Sites (beyond the PR-7 originals): the fleet serving tier
(:mod:`sparse_tpu.fleet`) accounts its batch-sharded programs' only
collective — the per-iteration all-converged lane-count ``psum`` —
under the ``fleet.batch`` site, one ledger per (mesh fingerprint,
solver, bucket, dtype) geometry; ``SolveSession`` commits the observed
execution count after every sharded dispatch.

Byte conventions (bytes **per shard** per execution, chosen to match the
analytic models'):

=================  =======================================================
``ppermute``       payload nbytes (each shard sends/receives one payload)
``all_gather``     ``(S - 1) *`` local-block nbytes (received from peers)
``psum``           logical payload nbytes (the models count a reduced
                   scalar as one itemsize, not the ring's ``2(S-1)/S`` x)
``psum_scatter``   ``nbytes * (S - 1) / S`` (ring reduce-scatter)
``all_to_all``     ``nbytes * (S - 1) / S`` (off-diagonal chunks)
``ragged_a2a``     operand capacity nbytes (upper bound, ``exact=False``)
=================  =======================================================

Metrics are ALWAYS ON (the plan-cache discipline: plain counter bumps);
only the ``comm.measured`` events are telemetry-gated.
"""

from __future__ import annotations

import threading

import jax

from ..telemetry import _metrics

_LOCK = threading.RLock()
#: site name -> the most recently constructed ledger for it (observability
#: snapshot surface; per-object ledgers stay authoritative for commits)
_SITES: dict = {}
#: (site, key) -> ledger, for :func:`ledger`'s get-or-create form
_LEDGERS: dict = {}

#: always-on metric family names
BYTES_METRIC = "comm.collective_bytes"
CALLS_METRIC = "comm.collectives"


def _nbytes(x) -> int:
    """Static payload bytes of an array/tracer (shape x itemsize)."""
    import numpy as np

    return int(np.prod(x.shape, dtype=np.int64)) * int(
        np.dtype(x.dtype).itemsize
    )


class SiteLedger:
    """Per-program collective accounting for one instrumentation site.

    ``note()`` is idempotent per ``(op, tag)`` — a re-trace of the same
    program (new shapes after a width change, jit cache miss) overwrites
    rather than double-counts, so the ledger always describes ONE
    execution of the most recently traced program.
    """

    __slots__ = ("site", "_entries", "_exact")

    def __init__(self, site: str):
        self.site = str(site)
        self._entries: dict = {}  # (op, tag) -> bytes per shard per exec
        self._exact: dict = {}  # (op, tag) -> bool
        with _LOCK:
            _SITES[self.site] = self

    def note(self, op: str, tag: str, nbytes: int, exact: bool = True) -> None:
        """Record one collective call site's per-execution payload."""
        with _LOCK:
            self._entries[(op, tag)] = int(nbytes)
            self._exact[(op, tag)] = bool(exact)

    @property
    def entries(self) -> dict:
        with _LOCK:
            return dict(self._entries)

    @property
    def exact(self) -> bool:
        """True when every noted payload is exact (no capacity bounds)."""
        with _LOCK:
            return all(self._exact.values())

    def bytes_per_shard(self) -> int:
        """Interconnect bytes one shard moves per program execution."""
        with _LOCK:
            return sum(self._entries.values())

    def per_op(self) -> dict:
        """``{op: {"calls": k, "bytes": b}}`` per program execution."""
        out: dict = {}
        with _LOCK:
            items = list(self._entries.items())
        for (op, _tag), b in items:
            d = out.setdefault(op, {"calls": 0, "bytes": 0})
            d["calls"] += 1
            d["bytes"] += b
        return out

    def commit(self, executions: int = 1, shards: int = 1) -> None:
        """Fold ``executions`` runs of this program into the always-on
        metrics registry. ``shards`` scales per-shard bytes to the total
        across the mesh (the convention the model events use)."""
        if executions <= 0 or not self._entries:
            return
        for op, d in self.per_op().items():
            _metrics.counter(
                CALLS_METRIC, op=op, site=self.site,
                help="collective launches accounted by sparse_tpu.parallel.comm",
            ).inc(d["calls"] * executions)
            _metrics.counter(
                BYTES_METRIC, op=op, site=self.site,
                help="measured collective payload bytes (all shards)",
            ).add(d["bytes"] * executions * shards)


def ledger(site: str, key=None) -> SiteLedger:
    """Get-or-create the shared ledger for ``(site, key)``.

    ``key`` distinguishes geometries that trace through the same code
    site (mesh size, exchange capacity): a jit-cached program for
    geometry A must never commit against bytes a later geometry-B trace
    noted. Per-layout objects (``DistCSR``) construct their own
    :class:`SiteLedger` instead; call sites whose program re-traces on
    every call may share a keyless ledger (each trace fully overwrites
    the same tag set)."""
    k = (site, key)
    with _LOCK:
        led = _LEDGERS.get(k)
    if led is None:
        led = SiteLedger(site)
        with _LOCK:
            _LEDGERS[k] = led
    return led


def sites() -> dict:
    """Snapshot of every known site's per-execution accounting."""
    with _LOCK:
        leds = list(_SITES.values())
    return {
        led.site: {
            "bytes_per_shard": led.bytes_per_shard(),
            "exact": led.exact,
            "ops": led.per_op(),
        }
        for led in leds
        if led.entries
    }


def metrics_snapshot() -> dict:
    """``{site: {op: bytes}}`` of the committed always-on byte totals."""
    with _LOCK:
        items = [
            m for (n, _), m in _metrics._REGISTRY.items() if n == BYTES_METRIC
        ]
    out: dict = {}
    for m in items:
        out.setdefault(m.labels.get("site", "?"), {})[
            m.labels.get("op", "?")
        ] = int(m.value)
    return out


# ---------------------------------------------------------------------------
# the wrappers — drop-in signatures over jax.lax, plus ledger/tag/axis_size
# ---------------------------------------------------------------------------
def ppermute(x, axis_name, perm, *, ledger=None, tag=""):
    if ledger is not None:
        ledger.note("ppermute", tag, _nbytes(x))
    return jax.lax.ppermute(x, axis_name, perm)


def all_gather(x, axis_name, *, axis_size, ledger=None, tag="", **kwargs):
    if ledger is not None:
        ledger.note("all_gather", tag, (int(axis_size) - 1) * _nbytes(x))
    return jax.lax.all_gather(x, axis_name, **kwargs)


def psum(x, axis_name, *, ledger=None, tag=""):
    if ledger is not None:
        ledger.note("psum", tag, _nbytes(x))
    return jax.lax.psum(x, axis_name)


def psum_scatter(x, axis_name, *, axis_size, ledger=None, tag="", **kwargs):
    if ledger is not None:
        S = int(axis_size)
        ledger.note("psum_scatter", tag, _nbytes(x) * (S - 1) // max(S, 1))
    return jax.lax.psum_scatter(x, axis_name, **kwargs)


def all_to_all(
    x, axis_name, split_axis, concat_axis, *, axis_size, ledger=None, tag=""
):
    if ledger is not None:
        S = int(axis_size)
        ledger.note("all_to_all", tag, _nbytes(x) * (S - 1) // max(S, 1))
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis)


def ragged_all_to_all(
    operand, output, input_offsets, send_sizes, output_offsets, recv_sizes,
    *, axis_name, ledger=None, tag="",
):
    if ledger is not None:
        # runtime-ragged payload: account the send-buffer capacity as an
        # upper bound and flag the entry inexact (docs/telemetry.md)
        ledger.note("ragged_all_to_all", tag, _nbytes(operand), exact=False)
    return jax.lax.ragged_all_to_all(
        operand, output, input_offsets, send_sizes, output_offsets,
        recv_sizes, axis_name=axis_name,
    )


# ---------------------------------------------------------------------------
# reconciliation: the measured-vs-model event
# ---------------------------------------------------------------------------
def record_measured(
    site: str,
    led: SiteLedger,
    *,
    executions: int,
    shards: int,
    model_bytes=None,
    solve_s=None,
    **fields,
):
    """Emit one ``comm.measured`` event (telemetry-gated): the ledger's
    trace-derived bytes scaled by the observed execution count, reconciled
    against the analytic ``model_bytes`` when given (``divergence_pct`` —
    expected small-positive: the model omits setup executions, the
    measurement omits GSPMD-inserted scalar psums). ``solve_s`` adds the
    achieved per-shard GB/s the report's ``--peak-ici-gbs`` roofline
    consumes. Returns the event dict or ``None`` when disabled."""
    from .. import telemetry

    if not telemetry.enabled() or not led.entries:
        return None
    per_shard = led.bytes_per_shard() * int(executions)
    total = per_shard * int(shards)
    ev = dict(
        site=site,
        bytes=total,
        bytes_per_shard=per_shard,
        executions=int(executions),
        S=int(shards),
        ops=led.per_op(),
        exact=led.exact,
        **fields,
    )
    if isinstance(model_bytes, (int, float)) and model_bytes > 0:
        ev["model_bytes"] = int(model_bytes)
        ev["divergence_pct"] = round(
            100.0 * (total - model_bytes) / model_bytes, 3
        )
    if isinstance(solve_s, (int, float)) and solve_s > 0:
        ev["solve_s"] = round(float(solve_s), 6)
        ev["gbs_per_shard"] = round(per_shard / solve_s / 1e9, 6)
    return telemetry.record("comm.measured", **ev)
