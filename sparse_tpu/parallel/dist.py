"""Distributed CSR: mesh-sharded matrices, halo-exchange SpMV, padded vectors.

This is the TPU-native replacement for the reference's partitioning layer
(``sparse/partition.py`` + ``sparse/base.py:194-296``): Legion's dependent
partitioning (CompressedImagePartition / MinMaxImagePartition / DensePreimage)
becomes a one-time host-side layout decision, after which every operation is a
static-shape SPMD program over a ``jax.sharding.Mesh``.

Layout (S = mesh size):
  * rows are split into S blocks at ``row_splits`` (equal or nnz-balanced —
    the ``DenseSparseBase.balance`` analog, base.py:198-282), each padded to
    ``R = max`` rows so shards are uniform;
  * dense vectors live in **padded row-block layout**: shape ``[S*R]`` sharded
    ``P('shards')``, entries beyond a block's real rows are zero;
  * column ids are remapped into the same padded coordinate space at
    construction, so x-gathers are direct indexed loads;
  * per-shard nonzeros are stored either as stacked ELL planes
    ``[S, R, k]`` (banded/bounded-degree: pure gather + VPU reduce — the shape
    TPUs like) or stacked padded CSR ``[S, K]`` + row ids (general profile);
  * the x-window each shard needs (the MinMaxImagePartition analog,
    partition.py:139-214) becomes a **static halo width H**: SpMV fetches the
    H-wide tails of its mesh neighbors with ``lax.ppermute`` over ICI and runs
    a purely local kernel. Matrices whose windows exceed the halo budget fall
    back to an ``all_gather`` of x (the replicate-x fallback).

All comms are XLA collectives (ppermute / all_gather / psum) riding ICI; the
only host work is the one-time layout construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import asjnp
from .mesh import get_mesh
from .partition import balanced_row_splits, equal_row_splits

try:  # jax>=0.8 top-level; older releases keep it in experimental
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


@dataclass(eq=False)
class DistCSR:
    """A CSR matrix laid out over a 1-D device mesh.

    Square solver-facing matrices (m == n) share a single padded coordinate
    space for rows and columns; rectangular matrices keep separate row/column
    splits (columns follow the equal split of the x vector they multiply).
    """

    mesh: Mesh
    axis: str
    shape: tuple  # logical (m, n)
    row_splits: np.ndarray  # [S+1] host
    col_splits: np.ndarray  # [S+1] host (x-vector layout)
    R: int  # padded rows per shard
    C: int  # padded cols (x entries) per shard
    H: int  # halo width (cols), 0 when mode == "gather"
    mode: str  # "halo" | "gather"
    layout: str  # "ell" | "csr"
    dtype: np.dtype
    # device arrays, all sharded P(axis) on their leading dim:
    ell_idx: jax.Array | None = None  # [S, R, k] padded-space col ids (rel. to window)
    ell_val: jax.Array | None = None  # [S, R, k]
    nz_rows: jax.Array | None = None  # [S, K] local row ids (csr layout)
    nz_cols: jax.Array | None = None  # [S, K] padded-space col ids (rel. to window)
    nz_vals: jax.Array | None = None  # [S, K]
    _spmv_fn: object = field(default=None, repr=False, compare=False)

    @property
    def S(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def m_pad(self) -> int:
        return self.S * self.R

    @property
    def n_pad(self) -> int:
        return self.S * self.C

    # -- vector layout helpers --------------------------------------------
    def pad_vector(self, x, splits=None, width=None) -> jax.Array:
        """Host/global vector [n] -> padded row-block layout [S*width], sharded."""
        splits = self.col_splits if splits is None else splits
        width = self.C if width is None else width
        x = np.asarray(x)
        S = self.S
        out = np.zeros((S, width), dtype=x.dtype)
        for s in range(S):
            lo, hi = int(splits[s]), int(splits[s + 1])
            out[s, : hi - lo] = x[lo:hi]
        return jax.device_put(
            out.reshape(S * width), NamedSharding(self.mesh, P(self.axis))
        )

    def pad_out_vector(self, y) -> jax.Array:
        """Pad a vector living in the *row* space (length m)."""
        return self.pad_vector(y, splits=self.row_splits, width=self.R)

    def unpad_vector(self, xp, splits=None, width=None) -> np.ndarray:
        splits = self.row_splits if splits is None else splits
        width = self.R if width is None else width
        xs = np.asarray(xp).reshape(self.S, width)
        return np.concatenate(
            [
                xs[s, : int(splits[s + 1]) - int(splits[s])]
                for s in range(self.S)
            ]
        )

    # -- SpMV --------------------------------------------------------------
    def spmv_padded(self, xp: jax.Array) -> jax.Array:
        """y = A @ x entirely in padded layout ([n_pad] -> [m_pad]).

        This is the jit-safe inner-loop primitive; solvers call it inside
        ``lax.while_loop`` without any host sync.
        """
        if self._spmv_fn is None:
            self._spmv_fn = _build_spmv(self)
        return self._spmv_fn(
            xp,
            *(
                (self.ell_idx, self.ell_val)
                if self.layout == "ell"
                else (self.nz_rows, self.nz_cols, self.nz_vals)
            ),
        )

    def dot(self, x) -> np.ndarray:
        """Convenience global-vector SpMV (pads, multiplies, unpads)."""
        xp = self.pad_vector(np.asarray(x))
        yp = self.spmv_padded(xp)
        return self.unpad_vector(yp)

    def matvec(self, x, out=None):
        return self.dot(x)


def _build_spmv(A: DistCSR):
    """Compile the shard_map SpMV for this matrix's layout/mode."""
    mesh, axis, S, R, C, H = A.mesh, A.axis, A.S, A.R, A.C, A.H
    mode, layout = A.mode, A.layout
    perm_right = [(i, i + 1) for i in range(S - 1)]  # tail -> right neighbor
    perm_left = [(i + 1, i) for i in range(S - 1)]  # head -> left neighbor

    def gather_x(x_l):
        """Produce each shard's addressable x slab from its local block [C]."""
        if mode == "gather":
            # Replicate-x fallback: one all_gather over the mesh axis.
            return jax.lax.all_gather(x_l, axis, tiled=True)  # [S*C]
        if S == 1 or H == 0:
            return x_l
        left = jax.lax.ppermute(x_l[-H:], axis, perm_right)  # from left nbr
        right = jax.lax.ppermute(x_l[:H], axis, perm_left)  # from right nbr
        return jnp.concatenate([left, x_l, right])  # [C + 2H]

    if layout == "ell":

        from ..ops.spmv import csr_spmv_ell

        def local_kernel(x_slab, ell_idx_l, ell_val_l):
            # k unrolled 1-D gathers + VPU adds (see csr_spmv_ell).
            return csr_spmv_ell(ell_idx_l, ell_val_l, x_slab)

        def shard_fn(x_l, ell_idx_l, ell_val_l):
            return local_kernel(
                gather_x(x_l), ell_idx_l.squeeze(0), ell_val_l.squeeze(0)
            )[None]

        in_specs = (P(axis), P(axis, None, None), P(axis, None, None))
    else:

        def local_kernel(x_slab, rows_l, cols_l, vals_l):
            prod = vals_l * x_slab[cols_l]
            return jax.ops.segment_sum(
                prod, rows_l, num_segments=R, indices_are_sorted=True
            )

        def shard_fn(x_l, rows_l, cols_l, vals_l):
            return local_kernel(
                gather_x(x_l),
                rows_l.squeeze(0),
                cols_l.squeeze(0),
                vals_l.squeeze(0),
            )[None]

        in_specs = (P(axis), P(axis, None), P(axis, None), P(axis, None))

    smapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(axis, None),
        check_vma=False,
    )

    @jax.jit
    def spmv(xp, *blocks):
        return smapped(xp, *blocks).reshape(S * R)

    return spmv


def shard_csr(
    A,
    mesh: Mesh | None = None,
    axis: str = "shards",
    balanced: bool = True,
    layout: str = "auto",
    halo_max_ratio: float = 1.0,
) -> DistCSR:
    """Lay a ``csr_array`` out over a mesh.

    ``balanced`` selects nnz-balanced row splits (the balance() analog);
    ``layout`` is 'ell' | 'csr' | 'auto' (ELL when max row degree is within
    ``settings.ell_max_ratio`` of the mean, mirroring the single-chip
    heuristic); a shard's column window overhang beyond ``halo_max_ratio * C``
    forces the all_gather fallback.
    """
    from ..config import settings

    if mesh is None:
        mesh = get_mesh()
    S = int(mesh.devices.size)
    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    m, n = A.shape
    nnz = data.shape[0]

    if balanced and nnz > 0:
        row_splits = balanced_row_splits(indptr, S)
    else:
        row_splits = equal_row_splits(m, S)
    # x follows an equal split of the column space; for square matrices this
    # is aligned with the row space so solver vectors live in one layout.
    if m == n:
        col_splits = row_splits
    else:
        col_splits = equal_row_splits(n, S)

    R = max(int(np.max(np.diff(row_splits))), 1)
    C = max(int(np.max(np.diff(col_splits))), 1)

    # Remap global column ids -> padded coordinate space.
    col_shard = np.clip(
        np.searchsorted(col_splits, indices, side="right") - 1, 0, S - 1
    )
    pad_cols = col_shard.astype(np.int64) * C + (
        indices.astype(np.int64) - col_splits[col_shard]
    )

    # Per-shard window -> halo width (MinMaxImage analog).
    H = 0
    mode = "halo"
    for s in range(S):
        lo, hi = int(indptr[row_splits[s]]), int(indptr[row_splits[s + 1]])
        if hi <= lo:
            continue
        seg = pad_cols[lo:hi]
        H = max(H, int(s * C - seg.min()), int(seg.max() + 1 - (s + 1) * C))
    if S == 1:
        H = 0
    if H > halo_max_ratio * C:
        mode = "gather"
        H = 0

    # Row degree stats for layout choice.
    counts = np.diff(indptr)
    kmax = int(counts.max()) if m else 0
    mean = max(nnz / max(m, 1), 1.0)
    if layout == "auto":
        layout = "ell" if kmax <= settings.ell_max_ratio * mean else "csr"

    shard_nnz = np.array(
        [
            int(indptr[row_splits[s + 1]]) - int(indptr[row_splits[s]])
            for s in range(S)
        ]
    )
    dt = data.dtype
    idt = np.int32 if S * max(R, C) + 2 * H < 2**31 else np.int64
    sharding2 = NamedSharding(mesh, P(axis, None))
    sharding3 = NamedSharding(mesh, P(axis, None, None))

    dist = DistCSR(
        mesh=mesh,
        axis=axis,
        shape=(int(m), int(n)),
        row_splits=row_splits,
        col_splits=col_splits,
        R=R,
        C=C,
        H=H,
        mode=mode,
        layout=layout,
        dtype=np.dtype(dt),
    )

    def to_local(pc, s):
        """Padded-space col ids -> the shard's slab coordinates."""
        if mode == "gather":
            return pc  # slab is the full [S*C] gathered x
        return pc - (s * C - H)  # slab is [C + 2H] starting at s*C - H

    if layout == "ell":
        k = max(kmax, 1)
        ell_idx = np.zeros((S, R, k), dtype=idt)
        ell_val = np.zeros((S, R, k), dtype=dt)
        for s in range(S):
            r0, r1 = int(row_splits[s]), int(row_splits[s + 1])
            for li, r in enumerate(range(r0, r1)):
                lo, hi = int(indptr[r]), int(indptr[r + 1])
                if hi > lo:
                    ell_idx[s, li, : hi - lo] = to_local(pad_cols[lo:hi], s)
                    ell_val[s, li, : hi - lo] = data[lo:hi]
        dist.ell_idx = jax.device_put(ell_idx, sharding3)
        dist.ell_val = jax.device_put(ell_val, sharding3)
    else:
        K = max(int(shard_nnz.max()), 1)
        nz_rows = np.full((S, K), R - 1, dtype=idt)  # pad rows -> last row
        nz_cols = np.zeros((S, K), dtype=idt)
        nz_vals = np.zeros((S, K), dtype=dt)
        for s in range(S):
            r0, r1 = int(row_splits[s]), int(row_splits[s + 1])
            lo, hi = int(indptr[r0]), int(indptr[r1])
            cnt = hi - lo
            if cnt:
                local_rows = (
                    np.searchsorted(indptr, np.arange(lo, hi), side="right")
                    - 1
                    - r0
                )
                nz_rows[s, :cnt] = local_rows
                nz_cols[s, :cnt] = to_local(pad_cols[lo:hi], s)
                nz_vals[s, :cnt] = data[lo:hi]
            # padding entries: row R-1, col 0, val 0 (sorted order preserved
            # because padding rows come after all real rows only when the last
            # block is full; use row R-1 which is >= any local row id)
        dist.nz_rows = jax.device_put(nz_rows, sharding2)
        dist.nz_cols = jax.device_put(nz_cols, sharding2)
        dist.nz_vals = jax.device_put(nz_vals, sharding2)
    return dist


# ---------------------------------------------------------------------------
# Distributed CG — the full "training step" over the mesh (solver north star).
# ---------------------------------------------------------------------------
def dist_cg(
    A: DistCSR,
    b,
    x0=None,
    tol: float = 1e-8,
    maxiter: int | None = None,
    conv_test_iters: int = 25,
):
    """Conjugate gradient over the mesh.

    Mirrors ``linalg.cg`` (reference linalg.py:499) but every vector is a
    padded mesh-sharded array and every reduction (dot products, norms) is a
    GSPMD ``psum`` inserted by XLA. One compiled ``lax.while_loop``; the host
    syncs once at the end — strictly less blocking than the reference's
    every-25-iterations future read.
    """
    bp = b if isinstance(b, jax.Array) and b.shape == (A.m_pad,) else A.pad_out_vector(np.asarray(b))
    n = A.shape[0]
    if maxiter is None:
        maxiter = n * 10
    xp = (
        jnp.zeros_like(bp)
        if x0 is None
        else (x0 if isinstance(x0, jax.Array) and x0.shape == (A.m_pad,) else A.pad_out_vector(np.asarray(x0)))
    )

    @jax.jit
    def run(bp, xp):
        r = bp - A.spmv_padded(xp)
        tol2 = jnp.asarray(tol, dtype=r.dtype) ** 2

        def body(state):
            x, r, p, rho, iters = state
            rho_new = jnp.vdot(r, r)
            beta = rho_new / jnp.where(rho == 0, 1, rho)
            p = jnp.where(iters == 0, r, r + beta * p)
            q = A.spmv_padded(p)
            pq = jnp.vdot(p, q)
            alpha = rho_new / jnp.where(pq == 0, 1, pq)
            return x + alpha * p, r - alpha * q, p, rho_new, iters + 1

        def cond(state):
            _, r, _, _, iters = state
            rnorm2 = jnp.real(jnp.vdot(r, r))
            tested = (iters % conv_test_iters == 0) | (iters == maxiter - 1)
            converged = tested & (iters > 0) & (rnorm2 < tol2)
            return (iters < maxiter) & ~converged

        state = (xp, r, jnp.zeros_like(bp), jnp.zeros((), bp.dtype), jnp.zeros((), jnp.int32))
        x, r, _, _, iters = jax.lax.while_loop(cond, body, state)
        return x, iters

    xp, iters = run(bp, xp)
    return xp, int(iters)
