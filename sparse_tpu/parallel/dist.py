"""Distributed CSR: mesh-sharded matrices, halo-exchange SpMV, padded vectors.

This is the TPU-native replacement for the reference's partitioning layer
(``sparse/partition.py`` + ``sparse/base.py:194-296``): Legion's dependent
partitioning (CompressedImagePartition / MinMaxImagePartition / DensePreimage)
becomes a one-time host-side layout decision, after which every operation is a
static-shape SPMD program over a ``jax.sharding.Mesh``.

Layout (S = mesh size):
  * rows are split into S blocks at ``row_splits`` (equal or nnz-balanced —
    the ``DenseSparseBase.balance`` analog, base.py:198-282), each padded to
    ``R = max`` rows so shards are uniform;
  * dense vectors live in **padded row-block layout**: shape ``[S*R]`` sharded
    ``P('shards')``, entries beyond a block's real rows are zero;
  * column ids are remapped into the same padded coordinate space at
    construction, so x-gathers are direct indexed loads;
  * per-shard nonzeros are stored either as stacked ELL planes
    ``[S, R, k]`` (banded/bounded-degree: pure gather + VPU reduce — the shape
    TPUs like) or stacked padded CSR ``[S, K]`` + row ids (general profile);
  * the x-window each shard needs (the MinMaxImagePartition analog,
    partition.py:139-214) becomes a **static halo width H**: SpMV fetches the
    H-wide tails of its mesh neighbors with ``lax.ppermute`` over ICI and runs
    a purely local kernel. Matrices whose windows exceed the halo budget fall
    back to an ``all_gather`` of x (the replicate-x fallback).

All comms are XLA collectives (ppermute / all_gather / psum) riding ICI; the
only host work is the one-time layout construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import asjnp
from . import comm
from .mesh import get_mesh
from .partition import balanced_row_splits, column_windows, equal_row_splits

from .mesh import shard_map  # version-portable (check_vma/check_rep shim)


@dataclass(eq=False)
class DistCSR:
    """A CSR matrix laid out over a 1-D device mesh.

    Square solver-facing matrices (m == n) share a single padded coordinate
    space for rows and columns; rectangular matrices keep separate row/column
    splits (columns follow the equal split of the x vector they multiply).
    """

    mesh: Mesh
    axis: str
    shape: tuple  # logical (m, n)
    row_splits: np.ndarray  # [S+1] host
    col_splits: np.ndarray  # [S+1] host (x-vector layout)
    R: int  # padded rows per shard
    C: int  # padded cols (x entries) per shard
    HL: int  # left halo width (cols), 0 when mode == "gather"
    HR: int  # right halo width; == HL unless settings.precise_windows
    mode: str  # "halo" | "gather"
    layout: str  # "ell" | "csr"
    dtype: np.dtype
    # device arrays, all sharded P(axis) on their leading dim:
    ell_idx: jax.Array | None = None  # [S, R, k] padded-space col ids (rel. to window)
    ell_val: jax.Array | None = None  # [S, R, k]
    nz_rows: jax.Array | None = None  # [S, K] local row ids (csr layout)
    nz_cols: jax.Array | None = None  # [S, K] padded-space col ids (rel. to window)
    nz_vals: jax.Array | None = None  # [S, K]
    _spmv_fn: object = field(default=None, repr=False, compare=False)
    _spmm_fn: object = field(default=None, repr=False, compare=False)
    _rspmm_fn: object = field(default=None, repr=False, compare=False)

    @property
    def S(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def m_pad(self) -> int:
        return self.S * self.R

    @property
    def n_pad(self) -> int:
        return self.S * self.C

    @property
    def H(self) -> int:
        return max(self.HL, self.HR)

    # -- compiled-program plans -------------------------------------------
    def _plan_fn(self, field_name: str, kind: str, build):
        """Resolve a compiled SPMD program through the library-wide plan
        cache (``sparse_tpu.plan_cache``) — the distributed opt-in: eager
        local-shard matvecs account one cache hit each, and the plan dies
        with this layout object. The per-object field stays authoritative
        for build-once semantics (a compiled ``shard_map`` program must
        never be rebuilt per call — ``jax.jit`` keys on the wrapper
        object), so disabling the cache costs only the counters."""
        from .. import plan_cache

        if getattr(self, field_name) is None:
            setattr(self, field_name, build())
        fn = getattr(self, field_name)
        cached = plan_cache.get(self, kind, lambda: fn)
        return cached if cached is not None else fn

    # -- vector layout helpers --------------------------------------------
    def pad_vector(self, x, splits=None, width=None) -> jax.Array:
        """Host/global vector [n] -> padded row-block layout [S*width], sharded."""
        splits = self.col_splits if splits is None else splits
        width = self.C if width is None else width
        x = np.asarray(x)
        S = self.S
        out = np.zeros((S, width), dtype=x.dtype)
        for s in range(S):
            lo, hi = int(splits[s]), int(splits[s + 1])
            out[s, : hi - lo] = x[lo:hi]
        return jax.device_put(
            out.reshape(S * width), NamedSharding(self.mesh, P(self.axis))
        )

    def pad_out_vector(self, y) -> jax.Array:
        """Pad a vector living in the *row* space (length m)."""
        return self.pad_vector(y, splits=self.row_splits, width=self.R)

    def unpad_vector(self, xp, splits=None, width=None) -> np.ndarray:
        splits = self.row_splits if splits is None else splits
        width = self.R if width is None else width
        xs = np.asarray(xp).reshape(self.S, width)
        return np.concatenate(
            [
                xs[s, : int(splits[s + 1]) - int(splits[s])]
                for s in range(self.S)
            ]
        )

    # -- SpMV --------------------------------------------------------------
    def _spmv_comm_bytes(self) -> int:
        """Structural per-SpMV collective volume (bytes across all shards),
        memoized — the counter ``spmv_padded`` accumulates per eager call."""
        b = getattr(self, "_spmv_bytes_cache", None)
        if b is None:
            b = int(comm_stats(self)["spmv_collective_bytes_per_shard"]) * self.S
            self._spmv_bytes_cache = b
        return b

    def _commit_comm(self, attr: str) -> None:
        """Fold one eager execution of a compiled program into the
        always-on measured-comm metrics (``comm.collective_bytes{op,site}``,
        ``parallel/comm.py``). Traced inner-loop calls are accounted at
        the solver level instead (``dist_cg``)."""
        led = getattr(self, attr, None)
        if led is not None and led.entries:
            from ..utils import in_trace

            if not in_trace():
                led.commit(1, self.S)

    def spmv_padded(self, xp: jax.Array) -> jax.Array:
        """y = A @ x entirely in padded layout ([n_pad] -> [m_pad]).

        This is the jit-safe inner-loop primitive; solvers call it inside
        ``lax.while_loop`` without any host sync. Telemetry counts eager
        dispatches and their structural comm volume (traced inner-loop
        calls are accounted at the solver level instead — ``comm.cg``).
        """
        from .. import telemetry

        if telemetry.enabled():
            from ..utils import in_trace

            if not in_trace():
                telemetry.count("comm.spmv.calls")
                telemetry.add_bytes("comm.spmv.total", self._spmv_comm_bytes())
        fn = self._plan_fn("_spmv_fn", "dist.spmv", lambda: _build_spmv(self))
        out = fn(
            xp,
            *(
                (self.ell_idx, self.ell_val)
                if self.layout == "ell"
                else (self.nz_rows, self.nz_cols, self.nz_vals)
            ),
        )
        # measured accounting: the trace populated the ledger by the time
        # the dispatch returns, so an eager call commits exactly one
        # program execution's collective volume
        self._commit_comm("_comm_ledger")
        return out

    # -- SpMM --------------------------------------------------------------
    def pad_matrix(self, B, splits=None, width=None) -> jax.Array:
        """Host [n, nB] -> padded row-block layout [S*width, nB], sharded."""
        splits = self.col_splits if splits is None else splits
        width = self.C if width is None else width
        B = np.asarray(B)
        S = self.S
        out = np.zeros((S, width, B.shape[1]), dtype=B.dtype)
        for s in range(S):
            lo, hi = int(splits[s]), int(splits[s + 1])
            out[s, : hi - lo] = B[lo:hi]
        return jax.device_put(
            out.reshape(S * width, B.shape[1]),
            NamedSharding(self.mesh, P(self.axis, None)),
        )

    def unpad_matrix(self, Cp, splits=None, width=None) -> np.ndarray:
        splits = self.row_splits if splits is None else splits
        width = self.R if width is None else width
        Cs = np.asarray(Cp).reshape(self.S, width, -1)
        return np.concatenate(
            [Cs[s, : int(splits[s + 1]) - int(splits[s])] for s in range(self.S)]
        )

    def spmm_padded(self, Bp: jax.Array) -> jax.Array:
        """C = A @ B in padded layout ([n_pad, nB] -> [m_pad, nB]).

        Row-split SpMM (reference SPMM_CSR_DENSE, csr.py:1151-1205): B rows
        follow x's layout; each shard halo-exchanges (or all_gathers) the B
        row-window it needs, then runs the local ELL/segment kernel.
        """
        # one jitted wrapper for all widths — jax.jit caches per shape
        fn = self._plan_fn(
            "_spmm_fn", "dist.spmm", lambda: _build_spmv(self, matrix=True)
        )
        out = fn(Bp, *self._blocks())
        self._commit_comm("_comm_ledger_spmm")
        return out

    def rspmm_padded(self, Bp: jax.Array) -> jax.Array:
        """C = B @ A with dense B in padded *row-space* layout [p, m_pad].

        k-split with output reduction (reference SPMM_DENSE_CSR,
        csr.py:1209-1240): each shard contracts its row block of A against
        its column slice of B and scatters into a full [p, n_pad] output;
        one ``psum`` over the mesh replicates the result — exactly the
        reference's ADD-reduction into a broadcast C.
        """
        fn = self._plan_fn("_rspmm_fn", "dist.rspmm", lambda: _build_rspmm(self))
        out = fn(Bp)
        self._commit_comm("_comm_ledger_rspmm")
        return out

    def _blocks(self):
        return (
            (self.ell_idx, self.ell_val)
            if self.layout == "ell"
            else (self.nz_rows, self.nz_cols, self.nz_vals)
        )

    def dot(self, x) -> np.ndarray:
        """Convenience global SpMV/SpMM (pads, multiplies, unpads)."""
        x = np.asarray(x)
        if x.ndim == 2:
            Bp = self.pad_matrix(x)
            Cp = self.spmm_padded(Bp)
            return self.unpad_matrix(Cp)
        xp = self.pad_vector(x)
        yp = self.spmv_padded(xp)
        return self.unpad_vector(yp)

    def rdot(self, B) -> np.ndarray:
        """B @ A for dense host B ([p, m] -> [p, n])."""
        B = np.asarray(B)
        squeeze = B.ndim == 1
        if squeeze:
            B = B[None]
        Bp = self.pad_matrix(B.T, splits=self.row_splits, width=self.R).T
        Cp = self.rspmm_padded(Bp)
        Cs = np.asarray(Cp)  # [p, n_pad] replicated
        out = np.concatenate(
            [
                Cs[:, s * self.C : s * self.C + int(self.col_splits[s + 1]) - int(self.col_splits[s])]
                for s in range(self.S)
            ],
            axis=1,
        )
        return out[0] if squeeze else out

    def matvec(self, x, out=None):
        return self.dot(x)

    def as_operator(self, with_rmatvec: bool = False, source=None):
        """A LinearOperator over PADDED mesh-sharded vectors.

        This is how the generic Krylov solvers (``linalg.cg``, ``bicgstab``,
        ``gmres``, ...) run distributed WITHOUT dedicated mesh variants: the
        operator maps [n_pad] -> [m_pad] sharded arrays, the solver's whole
        ``lax.while_loop`` traces over them, and GSPMD turns every vdot/norm
        into a ``psum`` automatically — the reference gets the same effect
        from Legion's implicit partitioning of its task launches. Square
        matrices only (solver iterates live in one coordinate space).

        ``with_rmatvec`` additionally shards the TRANSPOSE layout (from
        ``source``, the host ``csr_array`` this layout was built from) on
        the swapped splits, so adjoint-needing solvers (``bicg``, ``lsqr``)
        run on the mesh too.
        """
        from ..linalg import LinearOperator

        if self.shape[0] != self.shape[1]:
            raise ValueError("as_operator() needs a square matrix")

        rmatvec = None
        if with_rmatvec:
            if source is None:
                raise ValueError(
                    "with_rmatvec needs the source csr_array to build the "
                    "transpose layout"
                )
            Dt = shard_csr(
                source.T.tocsr(),
                mesh=self.mesh,
                axis=self.axis,
                row_splits=self.col_splits,
                col_splits=self.row_splits,
            )
            if np.issubdtype(self.dtype, np.complexfloating):
                rmatvec = lambda x: jnp.conj(Dt.spmv_padded(jnp.conj(x)))
            else:
                rmatvec = Dt.spmv_padded

        return LinearOperator(
            (self.m_pad, self.n_pad),
            matvec=self.spmv_padded,
            rmatvec=rmatvec,
            dtype=self.dtype,
        )


def _build_spmv(A: DistCSR, matrix: bool = False):
    """Compile the shard_map SpMV/SpMM for this matrix's layout/mode.

    ``matrix=False`` -> vector SpMV ([n_pad] -> [m_pad]);
    ``matrix=True``  -> row-split SpMM ([n_pad, nB] -> [m_pad, nB]).
    """
    mesh, axis, S, R, C = A.mesh, A.axis, A.S, A.R, A.C
    HL, HR = A.HL, A.HR
    mode, layout = A.mode, A.layout
    perm_right = [(i, i + 1) for i in range(S - 1)]  # tail -> right neighbor
    perm_left = [(i + 1, i) for i in range(S - 1)]  # head -> left neighbor
    is_mat = matrix
    # measured-comm ledger: populated at trace time with the exact payload
    # bytes of every collective this program issues (parallel/comm.py);
    # per-object so distinct layouts/geometries never collide
    led = comm.SiteLedger("dist.spmm" if matrix else "dist.spmv")
    setattr(A, "_comm_ledger_spmm" if matrix else "_comm_ledger", led)

    def gather_x(x_l):
        """Each shard's addressable x/B slab from its local block (leading
        axis = the n dimension; halo/all_gather both slice it)."""
        if mode == "gather":
            # Replicate fallback: one all_gather over the mesh axis.
            return comm.all_gather(
                x_l, axis, axis_size=S, ledger=led, tag="x", tiled=True
            )  # [S*C, ...]
        if S == 1 or HL + HR == 0:
            return x_l
        parts = []
        if HL:
            parts.append(
                comm.ppermute(
                    x_l[-HL:], axis, perm_right, ledger=led, tag="halo_l"
                )
            )
        parts.append(x_l)
        if HR:
            parts.append(
                comm.ppermute(
                    x_l[:HR], axis, perm_left, ledger=led, tag="halo_r"
                )
            )
        return jnp.concatenate(parts)  # [HL + C + HR, ...]

    if layout == "ell":

        from ..ops.spmv import csr_spmm_ell, csr_spmv_ell

        def shard_fn(x_l, ell_idx_l, ell_val_l):
            slab = gather_x(x_l)
            idx, val = ell_idx_l.squeeze(0), ell_val_l.squeeze(0)
            if is_mat:
                return csr_spmm_ell(idx, val, slab)  # [R, nB]
            return csr_spmv_ell(idx, val, slab)[None]

        in_specs = (P(axis), P(axis, None, None), P(axis, None, None))
    else:

        def shard_fn(x_l, rows_l, cols_l, vals_l):
            slab = gather_x(x_l)
            rows, cols, vals = (
                rows_l.squeeze(0),
                cols_l.squeeze(0),
                vals_l.squeeze(0),
            )
            prod = (
                vals[:, None] * slab[cols] if is_mat else vals * slab[cols]
            )
            out = jax.ops.segment_sum(
                prod, rows, num_segments=R, indices_are_sorted=True
            )
            return out if is_mat else out[None]

        in_specs = (P(axis), P(axis, None), P(axis, None), P(axis, None))

    smapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(axis, None),
        check_vma=False,
    )

    if is_mat:
        return jax.jit(smapped)

    @jax.jit
    def spmv(xp, *blocks):
        return smapped(xp, *blocks).reshape(S * R)

    return spmv


def _build_rspmm(A: DistCSR):
    """Compile the k-split dense x sparse SpMM: C = B @ A with B [p, m_pad]
    sharded on its column (contraction) axis; each shard scatters its local
    contribution into [p, n_pad] and one ``psum`` replicates C (the
    reference's ADD reduction into a broadcast store, csr.py:1209-1240)."""
    mesh, axis, S, R, C, HL = A.mesh, A.axis, A.S, A.R, A.C, A.HL
    mode, layout = A.mode, A.layout
    n_pad = S * C
    led = comm.SiteLedger("dist.rspmm")
    A._comm_ledger_rspmm = led

    def shard_fn(B_l, *blocks):
        s = jax.lax.axis_index(axis)
        if layout == "ell":
            ell_idx, ell_val = (b.squeeze(0) for b in blocks)
            k = ell_idx.shape[1]
            rows = jnp.repeat(jnp.arange(R, dtype=jnp.int32), k)
            cols = ell_idx.reshape(-1)
            vals = ell_val.reshape(-1)
        else:
            rows, cols, vals = (b.squeeze(0) for b in blocks)
        # window-local col ids -> padded global col ids
        if mode != "gather":
            cols = cols.astype(jnp.int32) + s * C - HL
        cols = jnp.clip(cols, 0, n_pad - 1)  # padding entries carry val 0
        contrib = B_l[:, rows] * vals  # [p, Kf]
        out = jax.ops.segment_sum(contrib.T, cols, num_segments=n_pad)
        # [p, n_pad] replicated (ADD-reduction into a broadcast C)
        return comm.psum(out.T, axis, ledger=led, tag="reduce")

    if layout == "ell":
        block_specs = (P(axis, None, None), P(axis, None, None))
    else:
        block_specs = (P(axis, None), P(axis, None), P(axis, None))

    smapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(None, axis), *block_specs),
        out_specs=P(None, None),
        check_vma=False,
    )

    @jax.jit
    def rspmm(Bp):
        return smapped(Bp, *A._blocks())

    return rspmm


# ---------------------------------------------------------------------------
# Column-split SpMV — the contraction-dim ("TP-style") strategy.
# ---------------------------------------------------------------------------
@dataclass(eq=False)
class DistCSRCol:
    """A CSR matrix laid out over the mesh by COLUMN blocks.

    The reference's domain-partitioned SpMV (csr.py:869-927,
    ``spmv_domain_part``; SURVEY §2c-4): x is sharded on the contraction
    dimension, each shard owns the nonzeros whose column falls in its x
    block, computes a full-height partial y, and a ``psum_scatter`` over
    the mesh both reduces and re-shards y into row-block layout — the
    ring-reduction shape (this is the framework's reduce-scatter analog of
    sequence parallelism).
    """

    mesh: Mesh
    axis: str
    shape: tuple
    row_splits: np.ndarray  # [S+1] layout of the OUTPUT y
    col_splits: np.ndarray  # [S+1] layout of the INPUT x (ownership)
    R: int
    C: int
    dtype: np.dtype
    nz_rows: jax.Array | None = None  # [S, K] padded-space global row ids
    nz_cols: jax.Array | None = None  # [S, K] local col ids in [0, C)
    nz_vals: jax.Array | None = None  # [S, K]
    _spmv_fn: object = field(default=None, repr=False, compare=False)

    @property
    def S(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def m_pad(self) -> int:
        return self.S * self.R

    @property
    def n_pad(self) -> int:
        return self.S * self.C

    pad_vector = DistCSR.pad_vector
    pad_out_vector = DistCSR.pad_out_vector
    unpad_vector = DistCSR.unpad_vector

    _plan_fn = DistCSR._plan_fn
    _commit_comm = DistCSR._commit_comm

    def spmv_padded(self, xp: jax.Array) -> jax.Array:
        fn = self._plan_fn(
            "_spmv_fn", "dist.spmv_col", lambda: _build_spmv_col(self)
        )
        out = fn(xp, self.nz_rows, self.nz_cols, self.nz_vals)
        self._commit_comm("_comm_ledger")
        return out

    def dot(self, x) -> np.ndarray:
        xp = self.pad_vector(np.asarray(x))
        yp = self.spmv_padded(xp)
        return self.unpad_vector(yp)

    def matvec(self, x, out=None):
        return self.dot(x)


def _build_spmv_col(A: DistCSRCol):
    mesh, axis, S, R = A.mesh, A.axis, A.S, A.R
    m_pad = S * R
    led = comm.SiteLedger("dist.spmv_col")
    A._comm_ledger = led

    def shard_fn(x_l, rows_l, cols_l, vals_l):
        x = x_l.reshape(-1)
        rows, cols, vals = (
            rows_l.squeeze(0),
            cols_l.squeeze(0),
            vals_l.squeeze(0),
        )
        prod = vals * x[cols]
        y_full = jax.ops.segment_sum(
            prod, rows, num_segments=m_pad, indices_are_sorted=True
        )
        if S == 1:
            return y_full
        # reduce partial sums across the mesh AND re-shard to row blocks in
        # one collective (rides ICI as a ring reduce-scatter)
        return comm.psum_scatter(
            y_full, axis, axis_size=S, ledger=led, tag="y", tiled=True
        )

    smapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis), P(axis, None), P(axis, None), P(axis, None)),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(smapped)


def windows_to_halo(windows, C: int, S: int, halo_max_ratio: float = 1.0):
    """Per-shard [lo, hi) padded-column windows -> (HL, HR, mode).

    The single window-to-halo policy shared by ``shard_csr`` and the 2-D
    SpGEMM's DistCSR output. ``settings.precise_windows`` keeps the
    left/right overhangs separate (tighter slabs on asymmetric bands — the
    LEGATE_SPARSE_PRECISE_IMAGES analog); the default collapses them to one
    symmetric width. Overhang beyond ``halo_max_ratio * C`` total flips to
    the all_gather fallback ('gather').
    """
    from ..config import settings

    HL = HR = 0
    mode = "halo"
    for s in range(S):
        lo, hi = windows[s]
        if hi <= lo:
            continue
        HL = max(HL, int(s * C - lo))
        HR = max(HR, int(hi - (s + 1) * C))
    if not settings.precise_windows:
        HL = HR = max(HL, HR)
    if S == 1:
        HL = HR = 0
    if HL + HR > 2 * halo_max_ratio * C:
        mode = "gather"
        HL = HR = 0
    return HL, HR, mode


def shard_csr_cols(
    A,
    mesh: Mesh | None = None,
    axis: str = "shards",
    row_splits: np.ndarray | None = None,
) -> DistCSRCol:
    """Lay a ``csr_array`` out over the mesh by column blocks (domain split).

    ``row_splits`` fixes the output layout (defaults to equal row tiles) so
    the result vector can feed a row-split matrix without repacking.
    """
    if mesh is None:
        mesh = get_mesh()
    S = int(mesh.devices.size)
    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    m, n = A.shape
    nnz = data.shape[0]

    col_splits = equal_row_splits(n, S)
    if row_splits is None:
        row_splits = equal_row_splits(m, S)
    R = max(int(np.max(np.diff(row_splits))), 1)
    C = max(int(np.max(np.diff(col_splits))), 1)

    counts = np.diff(indptr)
    nnz_row = np.repeat(np.arange(m, dtype=np.int64), counts)
    row_shard = np.clip(
        np.searchsorted(row_splits, nnz_row, side="right") - 1, 0, S - 1
    )
    pad_rows = row_shard * R + (nnz_row - row_splits[row_shard])
    col_shard = np.clip(
        np.searchsorted(col_splits, indices, side="right") - 1, 0, S - 1
    )
    local_cols = indices.astype(np.int64) - col_splits[col_shard]

    # Bucket nonzeros by owning column shard, row-sorted within each bucket
    # (CSR order is already row-sorted; a stable bucket argsort keeps it).
    order = np.argsort(col_shard, kind="stable")
    shard_counts = np.bincount(col_shard, minlength=S)
    K = max(int(shard_counts.max()), 1) if nnz else 1
    starts = np.zeros(S, dtype=np.int64)
    starts[1:] = np.cumsum(shard_counts)[:-1]
    slot = np.arange(nnz, dtype=np.int64) - starts[col_shard[order]]

    idt = np.int32 if S * max(R, C) < 2**31 else np.int64
    # padding: row m_pad-1 (keeps sortedness), col 0, val 0
    nz_rows = np.full((S, K), S * R - 1, dtype=idt)
    nz_cols = np.zeros((S, K), dtype=idt)
    nz_vals = np.zeros((S, K), dtype=data.dtype)
    nz_rows[col_shard[order], slot] = pad_rows[order]
    nz_cols[col_shard[order], slot] = local_cols[order]
    nz_vals[col_shard[order], slot] = data[order]

    sharding2 = NamedSharding(mesh, P(axis, None))
    return DistCSRCol(
        mesh=mesh,
        axis=axis,
        shape=(int(m), int(n)),
        row_splits=np.asarray(row_splits),
        col_splits=col_splits,
        R=R,
        C=C,
        dtype=np.dtype(data.dtype),
        nz_rows=jax.device_put(nz_rows, sharding2),
        nz_cols=jax.device_put(nz_cols, sharding2),
        nz_vals=jax.device_put(nz_vals, sharding2),
    )


def shard_csr(
    A,
    mesh: Mesh | None = None,
    axis: str = "shards",
    balanced: bool = True,
    layout: str = "auto",
    halo_max_ratio: float = 1.0,
    row_splits: np.ndarray | None = None,
    col_splits: np.ndarray | None = None,
) -> DistCSR:
    """Lay a ``csr_array`` out over a mesh.

    ``balanced`` selects nnz-balanced row splits (the balance() analog);
    ``layout`` is 'ell' | 'csr' | 'auto' (ELL when max row degree is within
    ``settings.ell_max_ratio`` of the mean, mirroring the single-chip
    heuristic); a shard's column window overhang beyond ``halo_max_ratio * C``
    forces the all_gather fallback. Explicit ``row_splits``/``col_splits``
    pin the layout so chains of rectangular operators (AMG's R/A/P) share
    vector spaces without repacking.
    """
    from ..config import settings

    if mesh is None:
        mesh = get_mesh()
    S = int(mesh.devices.size)
    indptr = np.asarray(A.indptr)
    indices = np.asarray(A.indices)
    data = np.asarray(A.data)
    m, n = A.shape
    nnz = data.shape[0]

    if row_splits is None:
        if balanced and nnz > 0:
            row_splits = balanced_row_splits(indptr, S)
        else:
            row_splits = equal_row_splits(m, S)
    else:
        row_splits = np.asarray(row_splits, dtype=np.int64)
    # x follows an equal split of the column space; for square matrices this
    # is aligned with the row space so solver vectors live in one layout.
    if col_splits is None:
        col_splits = row_splits if m == n else equal_row_splits(n, S)
    else:
        col_splits = np.asarray(col_splits, dtype=np.int64)

    R = max(int(np.max(np.diff(row_splits))), 1)
    C = max(int(np.max(np.diff(col_splits))), 1)

    # Remap global column ids -> padded coordinate space.
    col_shard = np.clip(
        np.searchsorted(col_splits, indices, side="right") - 1, 0, S - 1
    )
    pad_cols = col_shard.astype(np.int64) * C + (
        indices.astype(np.int64) - col_splits[col_shard]
    )

    # Per-shard column windows -> halo widths (MinMaxImage analog,
    # partition.py:139-214).
    windows = column_windows(indptr, pad_cols, row_splits)
    HL, HR, mode = windows_to_halo(windows, C, S, halo_max_ratio)

    # Row degree stats for layout choice.
    counts = np.diff(indptr)
    kmax = int(counts.max()) if m else 0
    mean = max(nnz / max(m, 1), 1.0)
    if layout == "auto":
        layout = "ell" if kmax <= settings.ell_max_ratio * mean else "csr"

    shard_nnz = np.array(
        [
            int(indptr[row_splits[s + 1]]) - int(indptr[row_splits[s]])
            for s in range(S)
        ]
    )
    dt = data.dtype
    idt = np.int32 if S * max(R, C) + HL + HR < 2**31 else np.int64
    sharding2 = NamedSharding(mesh, P(axis, None))
    sharding3 = NamedSharding(mesh, P(axis, None, None))

    dist = DistCSR(
        mesh=mesh,
        axis=axis,
        shape=(int(m), int(n)),
        row_splits=row_splits,
        col_splits=col_splits,
        R=R,
        C=C,
        HL=HL,
        HR=HR,
        mode=mode,
        layout=layout,
        dtype=np.dtype(dt),
    )

    # Vectorized layout construction: one pass of repeat/searchsorted/scatter
    # over the nnz (no per-row Python loops — a 36M-row matrix lays out in
    # seconds of host time, like ops/conv.csr_to_ell).
    counts = np.diff(indptr)
    nnz_row = np.repeat(np.arange(m, dtype=np.int64), counts)  # global row/nnz
    nnz_shard = np.clip(
        np.searchsorted(row_splits, nnz_row, side="right") - 1, 0, S - 1
    )
    local_row = nnz_row - row_splits[nnz_shard]
    if mode == "gather":
        local_col = pad_cols  # slab is the full [S*C] gathered x
    else:  # slab is [C + 2H] starting at shard*C - H
        local_col = pad_cols - (nnz_shard * C - HL)

    if layout == "ell":
        k = max(kmax, 1)
        pos_in_row = np.arange(nnz, dtype=np.int64) - np.repeat(
            indptr[:-1].astype(np.int64), counts
        )
        ell_idx = np.zeros((S, R, k), dtype=idt)
        ell_val = np.zeros((S, R, k), dtype=dt)
        ell_idx[nnz_shard, local_row, pos_in_row] = local_col
        ell_val[nnz_shard, local_row, pos_in_row] = data
        dist.ell_idx = jax.device_put(ell_idx, sharding3)
        dist.ell_val = jax.device_put(ell_val, sharding3)
    else:
        K = max(int(shard_nnz.max()), 1)
        shard_nnz_start = indptr[row_splits[:-1]].astype(np.int64)
        slot = np.arange(nnz, dtype=np.int64) - shard_nnz_start[nnz_shard]
        # padding entries: row R-1 (>= any real local row id, keeps sorted
        # order for segment_sum), col 0, val 0
        nz_rows = np.full((S, K), R - 1, dtype=idt)
        nz_cols = np.zeros((S, K), dtype=idt)
        nz_vals = np.zeros((S, K), dtype=dt)
        nz_rows[nnz_shard, slot] = local_row
        nz_cols[nnz_shard, slot] = local_col
        nz_vals[nnz_shard, slot] = data
        dist.nz_rows = jax.device_put(nz_rows, sharding2)
        dist.nz_cols = jax.device_put(nz_cols, sharding2)
        dist.nz_vals = jax.device_put(nz_vals, sharding2)
    from .. import telemetry

    if telemetry.enabled():
        # one event per sharded operator: the structural per-SpMV comm
        # model (the introspection the reference gets from Legion's
        # partition analysis) — eager SpMVs then accumulate against it
        cs = comm_stats(dist)
        telemetry.record(
            "comm.spmv", model=True, shape=[int(m), int(n)], S=S,
            mode=mode, layout=layout,
            halo_entries_per_spmv=cs["halo_entries_per_spmv"],
            bytes=int(cs["spmv_collective_bytes_per_shard"]) * S,
        )
    return dist


# ---------------------------------------------------------------------------
# Distributed CG — the full "training step" over the mesh (solver north star).
# ---------------------------------------------------------------------------
def make_dist_cg(
    A: DistCSR,
    tol: float = 1e-8,
    atol: float = 0.0,
    maxiter: int | None = None,
    conv_test_iters: int = 25,
    M=None,
):
    """Build the compiled mesh-CG program once; returns run(bp, xp).

    Callers that time repeated solves (benchmarks) should hold on to the
    returned function — each call to :func:`dist_cg` builds a fresh
    ``jax.jit`` wrapper and therefore recompiles.
    """
    if maxiter is None:
        maxiter = A.shape[0] * 10
    # M may be a padded-vector callable (the historic contract) or a
    # LinearOperator-shaped object (ISSUE 14: e.g. a multigrid V-cycle
    # promoted via parallel.multigrid.vcycle_operator) — resolve to the
    # traceable apply either way
    if M is None:
        precond = lambda r: r  # noqa: E731 - identity, traced away
    elif hasattr(M, "matvec"):
        precond = M.matvec
    else:
        precond = M

    @jax.jit
    def run(bp, xp):
        r = bp - A.spmv_padded(xp)
        bnorm2 = jnp.real(jnp.vdot(bp, bp))
        tol2 = jnp.maximum(
            jnp.asarray(tol, dtype=bnorm2.dtype) ** 2 * bnorm2,
            jnp.asarray(atol, dtype=bnorm2.dtype) ** 2,
        )

        def body(state):
            x, r, p, rho, iters = state
            z = precond(r)
            rho_new = jnp.vdot(r, z)
            beta = rho_new / jnp.where(rho == 0, 1, rho)
            p = jnp.where(iters == 0, z, z + beta * p)
            q = A.spmv_padded(p)
            pq = jnp.vdot(p, q)
            alpha = rho_new / jnp.where(pq == 0, 1, pq)
            return x + alpha * p, r - alpha * q, p, rho_new, iters + 1

        def cond(state):
            _, r, _, _, iters = state
            rnorm2 = jnp.real(jnp.vdot(r, r))
            tested = (iters % conv_test_iters == 0) | (iters == maxiter - 1)
            converged = tested & (iters > 0) & (rnorm2 < tol2)
            return (iters < maxiter) & ~converged

        state = (xp, r, jnp.zeros_like(bp), jnp.zeros((), bp.dtype), jnp.zeros((), jnp.int32))
        x, r, _, _, iters = jax.lax.while_loop(cond, body, state)
        rnorm2 = jnp.real(jnp.vdot(r, r))
        return x, iters, rnorm2 < tol2

    return run


def dist_cg(
    A: DistCSR,
    b,
    x0=None,
    tol: float = 1e-8,
    atol: float = 0.0,
    maxiter: int | None = None,
    conv_test_iters: int = 25,
    M=None,
):
    """(Preconditioned) conjugate gradient over the mesh.

    Mirrors ``linalg.cg`` (reference linalg.py:499) but every vector is a
    padded mesh-sharded array and every reduction (dot products, norms) is a
    GSPMD ``psum`` inserted by XLA. One compiled ``lax.while_loop``; the host
    syncs once at the end — strictly less blocking than the reference's
    every-25-iterations future read.

    ``M``: optional traceable preconditioner on padded vectors
    (zp = M(rp)) — e.g. a distributed AMG V-cycle. Convergence uses scipy
    semantics: ||r|| < max(tol * ||b||, atol). Returns (xp, iters, converged).
    """
    bp = b if isinstance(b, jax.Array) and b.shape == (A.m_pad,) else A.pad_out_vector(np.asarray(b))
    xp = (
        jnp.zeros_like(bp)
        if x0 is None
        else (x0 if isinstance(x0, jax.Array) and x0.shape == (A.m_pad,) else A.pad_out_vector(np.asarray(x0)))
    )
    run = make_dist_cg(
        A, tol=tol, atol=atol, maxiter=maxiter,
        conv_test_iters=conv_test_iters, M=M,
    )
    import time as _time

    t0 = _time.perf_counter()
    xp, iters, converged = run(bp, xp)
    iters, converged = int(iters), bool(converged)  # host fetch = fence
    solve_s = _time.perf_counter() - t0
    # the compiled loop runs one SpMV per iteration plus the initial
    # residual SpMV; commit that many executions of the traced program's
    # measured collective volume into the always-on metrics
    executions = iters + 1
    led = getattr(A, "_comm_ledger", None)
    if led is not None and led.entries:
        led.commit(executions, A.S)
    from .. import telemetry

    if telemetry.enabled():
        # whole-solve collective volume from the structural model x the
        # measured iteration count — the Legion-profiler-style comm
        # attribution for the compiled while_loop (which is opaque to
        # per-call counters by design)
        cs = comm_stats(A, conv_test_iters)
        model_bytes = (
            int(cs["cg_iter_collective_bytes_per_shard"]) * iters * A.S
        )
        telemetry.record(
            "comm.cg", S=A.S, iters=iters, mode=A.mode,
            bytes=model_bytes,
            bytes_per_iter_per_shard=int(
                cs["cg_iter_collective_bytes_per_shard"]
            ),
        )
        if led is not None and led.entries:
            # trace-derived measured bytes reconciled against the model:
            # divergence is the drift signal (expected residue: the model
            # counts the GSPMD scalar psums the wrappers cannot see, the
            # measurement counts the initial-residual SpMV the model
            # omits — both shrink with iteration count)
            comm.record_measured(
                "dist.cg", led, executions=executions, shards=A.S,
                model_bytes=model_bytes, solve_s=solve_s,
                mode=A.mode, iters=iters,
            )
        telemetry.record(
            "solver.solve", solver="dist_cg", n=int(A.shape[0]),
            iters=iters, path="device", converged=converged,
        )
        # the compiled mesh loop has no per-iteration visibility, but the
        # health monitor still closes a report (outcome + anomaly sweep
        # on the final residual) so last_solve_report() covers dist too
        telemetry.health.end_solve(
            "dist_cg", iters, converged=converged, path="device"
        )
    return xp, iters, converged


def comm_stats(A: DistCSR, conv_test_iters: int = 25) -> dict:
    """Per-CG-iteration collective cost model (VERDICT r2 #4).

    Derived from the compiled program's structure, not measured: one SpMV
    per iteration moves the halo (two ``ppermute`` payloads of HL/HR x
    entries per shard, ``_build_spmv.gather_x``) or, in gather mode, an
    ``all_gather`` of every other shard's x block; the CG recurrence
    ``psum``s 2 scalars per iteration (rho, p.q) plus one norm every
    ``conv_test_iters``. Weak-scaling regressions (halo width growing with
    n/S instead of the matrix band) show up here without hardware.
    """
    it = np.dtype(A.dtype).itemsize
    if A.mode == "halo":
        halo_entries = A.HL + A.HR
        spmv_bytes = halo_entries * it
    else:
        halo_entries = 0
        spmv_bytes = (A.S - 1) * A.C * it  # all_gather receives per shard
    psum_scalars = 2 + 1.0 / max(conv_test_iters, 1)
    return {
        "mode": A.mode,
        "S": A.S,
        "halo_entries_per_spmv": halo_entries,
        "spmv_collective_bytes_per_shard": spmv_bytes,
        "psum_scalars_per_iter": psum_scalars,
        "cg_iter_collective_bytes_per_shard": spmv_bytes
        + int(psum_scalars * it),
    }
