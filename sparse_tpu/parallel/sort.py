"""Distributed sort over the device mesh — the SORT_BY_KEY analog.

Reference analog: ``src/sparse/sort/`` (1101 LoC): per-rank thrust sort →
sample allgather → splitter selection → **NCCL/coll alltoallv** exchange →
merge (``sort_template.inl:224-283``, ``sort.cu:163-318``). Powers the
distributed COO->CSR/CSC conversions (coo.py:233-349) and the quantum
group sorts.

Two TPU-native algorithms:

* ``dist_sort`` — **odd-even transposition block sort**: each shard keeps a
  sorted block of L elements (padded with +inf sentinels); S rounds of
  neighbor ``ppermute`` + local 2L merge-split (left keeps the low half,
  right the high half) yield a globally sorted distribution. Fully static
  shapes, one compiled XLA program, no host round-trips — but S rounds of
  2L-element neighbor traffic.
* ``dist_sort_sample`` — the reference's actual **samplesort** shape:
  local sort -> regular-sample allgather -> splitter selection -> a
  ``jax.lax.ragged_all_to_all`` bucket exchange (the NCCL alltoallv
  analog) -> local merge -> one more ragged exchange restoring the exact
  block-rank layout. Two exchanges total; one tiny [S, S] host count fetch
  (the reference equally syncs counts to size its alltoallv buffers), with
  a fallback to the odd-even sort when heavy duplicate keys break the
  regular-sampling 2L bucket bound.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import comm
from .mesh import get_mesh

from .mesh import shard_map  # version-portable (check_vma/check_rep shim)


def dist_sort(keys, payloads, mesh: Mesh | None = None, axis: str = "shards"):
    """Globally sort sharded ``keys`` (with payloads) across the mesh.

    keys: [S*L] mesh-sharded along ``axis`` (pad with a +max sentinel).
    payloads: tuple of [S*L] arrays carried through the permutation.
    Returns (keys, payloads) with the same sharding, globally sorted:
    shard s holds elements of global rank [s*L, (s+1)*L).
    """
    if mesh is None:
        mesh = get_mesh()
    S = int(mesh.devices.size)
    payloads = tuple(payloads)
    # fresh ledger per call: the shard_map closure below re-traces every
    # call, and the tag set varies with S — a shared ledger would keep
    # stale round tags from a larger previous mesh
    led = comm.SiteLedger("sort.oddeven")

    def shard_fn(k_l, *p_l):
        k = k_l.reshape(-1)
        ps = [p.reshape(-1) for p in p_l]
        L = k.shape[0]
        order = jnp.argsort(k, stable=True)
        k = k[order]
        ps = [p[order] for p in ps]
        me = jax.lax.axis_index(axis)
        for r in range(S):
            start = r % 2
            pairs = [(i, i + 1) for i in range(start, S - 1, 2)]
            if not pairs:
                continue
            perm = pairs + [(j, i) for i, j in pairs]
            other_k = comm.ppermute(k, axis, perm, ledger=led, tag=f"k{r}")
            other_ps = [
                comm.ppermute(p, axis, perm, ledger=led, tag=f"p{r}.{i}")
                for i, p in enumerate(ps)
            ]
            q = me - start
            paired = (q >= 0) & (q < len(pairs) * 2)
            is_left = paired & (q % 2 == 0)
            # Build the 2L merge input in canonical global (left, right)
            # order on BOTH partners, so the stable argsort breaks ties
            # identically and the two halves partition the pair's payloads
            # exactly (duplicate keys straddling the boundary stay attached
            # to their own payloads).
            both_k = jnp.concatenate(
                [jnp.where(is_left, k, other_k), jnp.where(is_left, other_k, k)]
            )
            order2 = jnp.argsort(both_k, stable=True)
            lows, highs = order2[:L], order2[L:]
            idx = jnp.where(is_left, lows, highs)
            k = jnp.where(paired, both_k[idx], k)
            new_ps = []
            for p, op in zip(ps, other_ps):
                both_p = jnp.concatenate(
                    [jnp.where(is_left, p, op), jnp.where(is_left, op, p)]
                )
                new_ps.append(jnp.where(paired, both_p[idx], p))
            ps = new_ps
        return (k[None], *[p[None] for p in ps])

    in_specs = tuple(P(axis) for _ in range(1 + len(payloads)))
    out_specs = tuple(P(axis, None) for _ in range(1 + len(payloads)))
    out = shard_map(
        shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )(keys, *payloads)
    led.commit(1, S)  # always-on measured-comm metrics (one sort pass)
    skeys = out[0].reshape(-1)
    spayloads = tuple(o.reshape(-1) for o in out[1:])
    return skeys, spayloads


def _sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.iinfo(dtype).max
    return jnp.inf


# ---------------------------------------------------------------------------
# Samplesort — the reference's actual algorithm shape (sample -> splitters ->
# alltoallv -> merge), now expressible because jax.lax.ragged_all_to_all is
# the NCCL alltoallv analog. Two exchanges total (bucket + rebalance) instead
# of the odd-even sort's S neighbor rounds.
# ---------------------------------------------------------------------------
@lru_cache(maxsize=None)
def _sample_phase1(mesh, axis, S, n_payloads):
    """Local sort + splitter selection + per-destination send counts.

    The returned callable carries its own ``comm_ledger`` (one per cached
    build) so a geometry's committed bytes can never come from another
    build's trace."""
    led = comm.SiteLedger("sort.sample1")

    def shard_fn(k_l, *p_l):
        k = k_l.reshape(-1)
        L = k.shape[0]
        order = jnp.argsort(k, stable=True)
        k = k[order]
        ps = [p.reshape(-1)[order] for p in p_l]
        # regular sampling: S evenly spaced samples per shard
        pos = jnp.array([(j + 1) * L // (S + 1) for j in range(S)])
        samples = k[jnp.clip(pos, 0, L - 1)]
        all_samples = jnp.sort(
            comm.all_gather(
                samples, axis, axis_size=S, ledger=led, tag="samples",
                tiled=True,
            )
        )
        splitters = all_samples[jnp.arange(1, S) * S]  # [S-1]
        bounds = jnp.searchsorted(k, splitters, side="left").astype(jnp.int32)
        starts = jnp.concatenate([jnp.zeros((1,), jnp.int32), bounds])
        ends = jnp.concatenate([bounds, jnp.full((1,), L, jnp.int32)])
        send = ends - starts  # [S] counts to each destination
        return (k[None], *[p[None] for p in ps], send[None], splitters[None])

    in_specs = tuple(P(axis) for _ in range(1 + n_payloads))
    out_specs = (
        *[P(axis, None)] * (1 + n_payloads),
        P(axis, None),
        P(axis, None),
    )
    jitted = jax.jit(
        shard_map(
            shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )

    def phase1(*args):
        return jitted(*args)

    phase1.comm_ledger = led
    return phase1


def _ragged_a2a(
    x, out_buf, in_off, send, out_off, recv, axis, S, pair_cap, native,
    ledger=None, tag="",
):
    """ragged_all_to_all, with a dense-slot emulation for backends that
    don't implement the HLO (XLA:CPU — the virtual test mesh).

    The emulation exchanges a fixed [S, pair_cap] slot matrix (pair_cap
    bounds any single source->dest chunk; both samplesort exchanges send at
    most a full L-block to one destination) and compacts received chunks to
    ``out_off`` with an out-of-bounds-dropping scatter. Only the native
    path's traffic is the alltoallv shape; the emulation is for
    correctness-testing the algorithm on the CPU mesh. ``ledger``/``tag``
    route the measured-comm accounting (parallel/comm.py): the native
    ragged payload is accounted at send-buffer capacity (``exact=False``),
    the emulation at its actual dense-slot wire volume.
    """
    if native:
        # jax.lax.ragged_all_to_all's output_offsets are SENDER-side: entry
        # i is the offset in peer i's output where MY chunk lands. The
        # caller passes receiver-side offsets (where peer j's chunk lands in
        # MY buffer — what the emulation consumes); one all_to_all of the
        # offset vector is exactly that transpose.
        out_off_send = comm.all_to_all(
            out_off[:, None], axis, 0, 0, axis_size=S,
            ledger=ledger, tag=f"{tag}.off",
        ).reshape(-1)
        return comm.ragged_all_to_all(
            x, out_buf, in_off, send, out_off_send, recv, axis_name=axis,
            ledger=ledger, tag=tag,
        )
    idx = jnp.arange(pair_cap, dtype=jnp.int32)
    gathered = x[jnp.clip(in_off[:, None] + idx[None, :], 0, x.shape[0] - 1)]
    slots = jnp.where(idx[None, :] < send[:, None], gathered, 0)
    # row j = chunk from source j
    ex = comm.all_to_all(slots, axis, 0, 0, axis_size=S, ledger=ledger, tag=tag)
    pos = jnp.where(
        idx[None, :] < recv[:, None],
        out_off[:, None] + idx[None, :],
        out_buf.shape[0],  # out of bounds -> dropped
    )
    return out_buf.at[pos.reshape(-1)].set(ex.reshape(-1), mode="drop")


@lru_cache(maxsize=None)
def _sample_phase2(mesh, axis, S, L, cap, n_payloads, key_dtype, p_dtypes, native):
    """Bucket exchange -> local merge -> exact-rank rebalance exchange.

    Like phase 1, the returned callable carries its own ``comm_ledger``
    (one per cached build — the geometry args ARE the cache key)."""
    sent = _sentinel(jnp.dtype(key_dtype))
    led = comm.SiteLedger("sort.sample2")

    def shard_fn(k_l, *rest):
        p_l = rest[:n_payloads]
        splitters = rest[n_payloads].reshape(-1)
        k = k_l.reshape(-1)
        ps = [p.reshape(-1) for p in p_l]
        bounds = jnp.searchsorted(k, splitters, side="left").astype(jnp.int32)
        starts = jnp.concatenate([jnp.zeros((1,), jnp.int32), bounds])
        ends = jnp.concatenate([bounds, jnp.full((1,), L, jnp.int32)])
        send = ends - starts
        recv = comm.all_to_all(
            send[:, None], axis, 0, 0, axis_size=S, ledger=led, tag="counts",
        ).reshape(-1)
        out_off = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(recv)[:-1].astype(jnp.int32)]
        )
        buf = jnp.full((cap,), sent, dtype=k.dtype)
        k2 = _ragged_a2a(
            k, buf, starts, send, out_off, recv, axis, S, L, native,
            ledger=led, tag="bucket.k",
        )
        ps2 = [
            _ragged_a2a(
                p, jnp.zeros((cap,), dtype=p.dtype), starts, send, out_off,
                recv, axis, S, L, native, ledger=led, tag=f"bucket.p{i}",
            )
            for i, p in enumerate(ps)
        ]
        # merge: one stable sort applies the same permutation to keys and
        # payloads, so duplicate keys keep their own payloads
        order = jnp.argsort(k2, stable=True)
        k2 = k2[order]
        ps2 = [p[order] for p in ps2]
        # rebalance to exact global ranks [s*L, (s+1)*L)
        nvalid = jnp.sum(recv).astype(jnp.int32)
        counts_all = comm.all_gather(
            nvalid, axis, axis_size=S, ledger=led, tag="nvalid"
        )  # [S]
        me = jax.lax.axis_index(axis)
        gstart = jnp.sum(jnp.where(jnp.arange(S) < me, counts_all, 0))
        slot = jnp.arange(cap, dtype=jnp.int32)
        dest = jnp.where(
            slot < nvalid,
            jnp.clip((gstart + slot) // L, 0, S - 1).astype(jnp.int32),
            jnp.int32(S),
        )
        bnds2 = jnp.searchsorted(dest, jnp.arange(S + 1), side="left").astype(
            jnp.int32
        )
        starts2 = bnds2[:-1]
        send2 = bnds2[1:] - bnds2[:-1]
        recv2 = comm.all_to_all(
            send2[:, None], axis, 0, 0, axis_size=S, ledger=led,
            tag="counts2",
        ).reshape(-1)
        off2 = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(recv2)[:-1].astype(jnp.int32)]
        )
        k3 = _ragged_a2a(
            k2, jnp.full((L,), sent, dtype=k.dtype), starts2, send2, off2,
            recv2, axis, S, L, native, ledger=led, tag="restore.k",
        )
        ps3 = [
            _ragged_a2a(
                p, jnp.zeros((L,), dtype=p.dtype), starts2, send2, off2,
                recv2, axis, S, L, native, ledger=led, tag=f"restore.p{i}",
            )
            for i, p in enumerate(ps2)
        ]
        # chunks arrive ordered by source rank and sources hold ascending
        # rank ranges, so the concatenation is already globally sorted
        return (k3[None], *[p[None] for p in ps3])

    in_specs = (
        *[P(axis)] * (1 + n_payloads),  # flat [S*L] sharded vectors
        P(axis, None),  # splitters [S, S-1] (identical rows)
    )
    out_specs = tuple(P(axis, None) for _ in range(1 + n_payloads))
    jitted = jax.jit(
        shard_map(
            shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )

    def phase2(*args):
        return jitted(*args)

    phase2.comm_ledger = led
    return phase2


def dist_sort_sample(keys, payloads=(), mesh: Mesh | None = None, axis: str = "shards"):
    """Samplesort across the mesh — same contract as :func:`dist_sort`.

    Reference analog: the full samplesort pipeline of ``src/sparse/sort``
    (local sort -> sample allgather -> splitter selection -> alltoallv ->
    merge), with ``jax.lax.ragged_all_to_all`` playing alltoallv and one
    extra ragged exchange restoring the exact [s*L, (s+1)*L) rank layout.

    Regular sampling bounds every destination bucket by 2L ONLY for
    mostly-unique keys; the per-destination totals are checked on the host
    (a tiny [S, S] fetch — the reference equally syncs counts to size its
    alltoallv buffers) and pathological duplicate distributions fall back
    to the odd-even transposition sort.
    """
    if mesh is None:
        mesh = get_mesh()
    S = int(mesh.devices.size)
    payloads = tuple(payloads)
    if S == 1:
        return dist_sort(keys, payloads, mesh=mesh, axis=axis)
    L = keys.shape[0] // S
    cap = 2 * L

    phase1 = _sample_phase1(mesh, axis, S, len(payloads))
    out = phase1(keys, *payloads)
    phase1.comm_ledger.commit(1, S)
    k_sorted = out[0].reshape(-1)
    ps_sorted = [o.reshape(-1) for o in out[1 : 1 + len(payloads)]]
    send_matrix = np.asarray(out[1 + len(payloads)])  # [S, S]
    splitters = out[2 + len(payloads)]  # [S, S-1] (identical rows)

    from .. import telemetry

    model_bytes = None
    if telemetry.enabled():
        # exact bucket-exchange volume from the send matrix this function
        # already fetches to size the alltoallv buffers — zero extra syncs
        kit = np.dtype(keys.dtype).itemsize
        entry_bytes = kit + sum(np.dtype(p.dtype).itemsize for p in payloads)
        off_diag = int(send_matrix.sum() - np.trace(send_matrix))
        model_bytes = off_diag * entry_bytes + int(S * S * S * kit)
        telemetry.record(
            "comm.sort", S=S, n=int(keys.shape[0]),
            bucket_entries_sent=off_diag,
            sample_allgather_bytes=int(S * S * S * kit),
            fallback_odd_even=bool(send_matrix.sum(axis=0).max() > cap),
            bytes=model_bytes,
        )

    if int(send_matrix.sum(axis=0).max()) > cap:
        # heavy duplicates around a splitter: capacity bound violated
        return dist_sort(k_sorted, tuple(ps_sorted), mesh=mesh, axis=axis)

    native = jax.default_backend() == "tpu"
    phase2 = _sample_phase2(
        mesh, axis, S, L, cap, len(payloads), keys.dtype,
        tuple(p.dtype for p in payloads), native,
    )
    try:
        out2 = phase2(k_sorted, *ps_sorted, splitters)
    except Exception:  # pragma: no cover - backend-dependent collective
        # e.g. a backend without (working) ragged-all-to-all support:
        # correctness over speed — finish with the odd-even sort
        from ..utils import user_warning

        user_warning(
            "samplesort exchange unavailable on this backend; falling back "
            "to the odd-even transposition sort"
        )
        return dist_sort(k_sorted, tuple(ps_sorted), mesh=mesh, axis=axis)
    led2 = phase2.comm_ledger
    led2.commit(1, S)
    # measured-vs-model reconciliation: capacity-accounted (the ragged
    # exchange payload is runtime-dynamic), so exact=False and the
    # divergence is a bound check rather than a drift alarm
    comm.record_measured(
        "sort.sample", led2, executions=1, shards=S,
        model_bytes=model_bytes, n=int(keys.shape[0]),
    )
    return out2[0].reshape(-1), tuple(o.reshape(-1) for o in out2[1:])


def dist_sort_host(keys, payloads=(), num_shards: int | None = None):
    """Convenience wrapper: host arrays in, globally sorted host arrays out.

    Pads to a shard-divisible length with sentinels, runs ``dist_sort`` over
    the default mesh, strips padding. ``settings.force_serial`` pins the
    sort to a single shard (the reference's force_serial special case for
    tiny inputs / debugging, coo.py:242).
    """
    from ..config import settings

    if settings.force_serial:
        num_shards = 1
    mesh = get_mesh(num_shards)
    S = int(mesh.devices.size)
    keys = np.asarray(keys)
    nvalid = keys.shape[0]
    L = (nvalid + S - 1) // S if nvalid else 1
    total = S * L
    dt = keys.dtype
    sent = np.iinfo(dt).max if np.issubdtype(dt, np.integer) else np.inf
    kp = np.full(total, sent, dtype=dt)
    kp[:nvalid] = keys
    sharding = NamedSharding(mesh, P("shards"))
    kd = jax.device_put(kp, sharding)
    pds = []
    for p in payloads:
        p = np.asarray(p)
        pp = np.zeros(total, dtype=p.dtype)
        pp[:nvalid] = p
        pds.append(jax.device_put(pp, sharding))
    # samplesort (2 ragged exchanges); falls back to the odd-even
    # transposition sort internally when duplicates break its bucket bound
    sk, sp = dist_sort_sample(kd, tuple(pds), mesh=mesh)
    sk = np.asarray(sk)[:nvalid]
    return sk, tuple(np.asarray(p)[:nvalid] for p in sp)


def coo_to_csr_distributed(rows, cols, vals, shape, num_shards: int | None = None):
    """Distributed COO->CSR conversion (the coo.tocsr path of coo.py:233).

    Lexicographically sorts the (row, col) pairs across the mesh, then
    performs the dedup + indptr build. Returns a ``csr_array``. The sharded
    sort is the scale-out stage; the final assembly mirrors the reference's
    SORTED_COORDS_TO_COUNTS + nnz_to_pos scan.

    Small shapes fuse row*n+col into one int32 key (single sort pass); past
    the int32 key range the pair sorts as TWO stable distributed passes
    (by col, then by row — LSD radix composition; both ``dist_sort`` and
    ``dist_sort_sample`` are stable: canonical-order merges, rank-ordered
    exchanges), so no int64 keys and no x64 requirement anywhere.
    """
    import sparse_tpu

    from ..ops.coords import require_x64_index

    m, n = int(shape[0]), int(shape[1])
    vals = np.asarray(vals)
    if m * n <= np.iinfo(np.int32).max:
        keys = np.asarray(rows, np.int32) * np.int32(n) + np.asarray(
            cols, np.int32
        )
        skeys, (svals,) = dist_sort_host(keys, (vals,), num_shards)
        srows = skeys // n
        scols = skeys % n
    else:
        # a DIMENSION past int32 still needs int64 coordinates (and x64 —
        # require_x64_index raises loudly when it's off); coordinates for
        # dims <= int32max stay clear of the int32 sentinel (dim-1 < max)
        cdt = (
            np.int64
            if require_x64_index(max(m, n))
            else np.int32
        )
        c1, (r1, v1) = dist_sort_host(
            np.asarray(cols, cdt),
            (np.asarray(rows, cdt), vals),
            num_shards,
        )
        srows, (scols, svals) = dist_sort_host(r1, (c1, v1), num_shards)
    # collapse duplicate pairs (sum) — lex-sorted, so one segment pass
    if srows.shape[0]:
        is_new = np.concatenate(
            [[True], (srows[1:] != srows[:-1]) | (scols[1:] != scols[:-1])]
        )
        seg = np.cumsum(is_new) - 1
        uvals = np.zeros(int(seg[-1]) + 1, dtype=vals.dtype)
        np.add.at(uvals, seg, svals)
        urows = srows[is_new]
        ucols = scols[is_new]
    else:
        urows, ucols, uvals = srows, scols, svals
    indptr = np.zeros(m + 1, dtype=np.int64)
    np.add.at(indptr, urows + 1, 1)
    indptr = np.cumsum(indptr)
    return sparse_tpu.csr_array.from_parts(uvals, ucols, indptr, (m, n))


def sort_comm_stats(keys, S: int, payloads=()) -> dict:
    """Structural collective cost model for :func:`dist_sort_sample` at
    mesh size S — derived from the algorithm (the same sampling, splitter
    and bucketing arithmetic phase 1 runs on device), never measured, so
    weak-scaling regressions show up without hardware (the comm_stats
    discipline of ``parallel/dist.py``).

    Phases modeled, per shard: the [S, S] sample ``all_gather``; the
    bucket ``ragged_all_to_all`` (entries leaving the shard); the
    rank-restore ``ragged_all_to_all`` (bucket layout -> exact
    [s*L, (s+1)*L) rank layout); and the one [S, S] host count fetch that
    sizes the exchange. ``fallback_odd_even`` reports whether THIS key
    distribution would blow the 2L capacity bound and reroute to the
    odd-even sort (heavy duplicates around a splitter).

    Reference analog: the alltoallv volume accounting implicit in
    ``src/sparse/sort/sort_template.inl`` (size_send/size_recv arrays).
    """
    keys = np.asarray(keys)
    n = keys.shape[0]
    if S <= 0 or n % S:
        raise ValueError(f"{n} keys do not split over {S} shards")
    L = n // S
    kit = keys.dtype.itemsize
    pit = sum(np.asarray(p).dtype.itemsize for p in payloads)
    entry_bytes = kit + pit

    ks = np.sort(keys.reshape(S, L), axis=1, kind="stable")
    pos = np.clip([(j + 1) * L // (S + 1) for j in range(S)], 0, L - 1)
    all_samples = np.sort(ks[:, pos].reshape(-1), kind="stable")
    splitters = all_samples[np.arange(1, S) * S]

    send = np.empty((S, S), dtype=np.int64)  # [src, dest]
    for s in range(S):
        b = np.searchsorted(ks[s], splitters, side="left")
        send[s] = np.diff(np.concatenate([[0], b, [L]]))
    recv = send.sum(axis=0)
    cap = 2 * L
    bucket_off = send.sum(axis=1) - np.diag(send)

    # restore exchange: overlap of the bucket prefix layout with the
    # uniform rank layout (phase 2's second ragged exchange)
    bb = np.concatenate([[0], np.cumsum(recv)])
    restore = np.zeros((S, S), dtype=np.int64)
    for s in range(S):
        lo = np.maximum(bb[s], np.arange(S) * L)
        hi = np.minimum(bb[s + 1], (np.arange(S) + 1) * L)
        restore[s] = np.maximum(hi - lo, 0)
    restore_off = restore.sum(axis=1) - np.diag(restore)

    return {
        "S": S,
        "L": L,
        "cap": cap,
        "fallback_odd_even": bool(recv.max() > cap),
        "sample_allgather_bytes_per_shard": int(S * S * kit),
        "bucket_entries_sent_max": int(bucket_off.max()),
        "bucket_entries_sent_mean": float(bucket_off.mean()),
        "restore_entries_sent_max": int(restore_off.max()),
        "exchange_bytes_per_shard_max": int(
            (bucket_off.max() + restore_off.max()) * entry_bytes
        ),
        "host_sync_bytes": int(S * S * 4),
    }
