"""Distributed sort over the device mesh — the SORT_BY_KEY analog.

Reference analog: ``src/sparse/sort/`` (1101 LoC): per-rank thrust sort →
sample allgather → splitter selection → **NCCL/coll alltoallv** exchange →
merge (``sort_template.inl:224-283``, ``sort.cu:163-318``). Powers the
distributed COO->CSR/CSC conversions (coo.py:233-349) and the quantum
group sorts.

TPU-native redesign: XLA SPMD has no variable-count alltoallv — every
collective is static-shape — so the samplesort's data-dependent exchange is
replaced by an **odd-even transposition block sort**: each shard keeps a
sorted block of L elements (padded with +inf sentinels); S rounds of
neighbor ``ppermute`` + local 2L merge-split (left keeps the low half,
right the high half) yield a globally sorted distribution. All compute is
on-device ``jnp.sort``/gather; all communication is neighbor ICI traffic;
every shape is static. For S shards this is S rounds of 2L-element
exchanges — asymptotically more traffic than samplesort's single alltoallv,
but collective-count-bounded, deterministic, and compiles to one XLA
program (no host round-trips at all, vs the reference's per-phase task
launches).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import get_mesh

try:  # jax>=0.8 top-level; older releases keep it in experimental
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def dist_sort(keys, payloads, mesh: Mesh | None = None, axis: str = "shards"):
    """Globally sort sharded ``keys`` (with payloads) across the mesh.

    keys: [S*L] mesh-sharded along ``axis`` (pad with a +max sentinel).
    payloads: tuple of [S*L] arrays carried through the permutation.
    Returns (keys, payloads) with the same sharding, globally sorted:
    shard s holds elements of global rank [s*L, (s+1)*L).
    """
    if mesh is None:
        mesh = get_mesh()
    S = int(mesh.devices.size)
    payloads = tuple(payloads)

    def shard_fn(k_l, *p_l):
        k = k_l.reshape(-1)
        ps = [p.reshape(-1) for p in p_l]
        L = k.shape[0]
        order = jnp.argsort(k, stable=True)
        k = k[order]
        ps = [p[order] for p in ps]
        me = jax.lax.axis_index(axis)
        for r in range(S):
            start = r % 2
            pairs = [(i, i + 1) for i in range(start, S - 1, 2)]
            if not pairs:
                continue
            perm = pairs + [(j, i) for i, j in pairs]
            other_k = jax.lax.ppermute(k, axis, perm)
            other_ps = [jax.lax.ppermute(p, axis, perm) for p in ps]
            q = me - start
            paired = (q >= 0) & (q < len(pairs) * 2)
            is_left = paired & (q % 2 == 0)
            # Build the 2L merge input in canonical global (left, right)
            # order on BOTH partners, so the stable argsort breaks ties
            # identically and the two halves partition the pair's payloads
            # exactly (duplicate keys straddling the boundary stay attached
            # to their own payloads).
            both_k = jnp.concatenate(
                [jnp.where(is_left, k, other_k), jnp.where(is_left, other_k, k)]
            )
            order2 = jnp.argsort(both_k, stable=True)
            lows, highs = order2[:L], order2[L:]
            idx = jnp.where(is_left, lows, highs)
            k = jnp.where(paired, both_k[idx], k)
            new_ps = []
            for p, op in zip(ps, other_ps):
                both_p = jnp.concatenate(
                    [jnp.where(is_left, p, op), jnp.where(is_left, op, p)]
                )
                new_ps.append(jnp.where(paired, both_p[idx], p))
            ps = new_ps
        return (k[None], *[p[None] for p in ps])

    in_specs = tuple(P(axis) for _ in range(1 + len(payloads)))
    out_specs = tuple(P(axis, None) for _ in range(1 + len(payloads)))
    out = shard_map(
        shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )(keys, *payloads)
    skeys = out[0].reshape(-1)
    spayloads = tuple(o.reshape(-1) for o in out[1:])
    return skeys, spayloads


def _sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.iinfo(dtype).max
    return jnp.inf


def dist_sort_host(keys, payloads=(), num_shards: int | None = None):
    """Convenience wrapper: host arrays in, globally sorted host arrays out.

    Pads to a shard-divisible length with sentinels, runs ``dist_sort`` over
    the default mesh, strips padding. ``settings.force_serial`` pins the
    sort to a single shard (the reference's force_serial special case for
    tiny inputs / debugging, coo.py:242).
    """
    from ..config import settings

    if settings.force_serial:
        num_shards = 1
    mesh = get_mesh(num_shards)
    S = int(mesh.devices.size)
    keys = np.asarray(keys)
    nvalid = keys.shape[0]
    L = (nvalid + S - 1) // S if nvalid else 1
    total = S * L
    dt = keys.dtype
    sent = np.iinfo(dt).max if np.issubdtype(dt, np.integer) else np.inf
    kp = np.full(total, sent, dtype=dt)
    kp[:nvalid] = keys
    sharding = NamedSharding(mesh, P("shards"))
    kd = jax.device_put(kp, sharding)
    pds = []
    for p in payloads:
        p = np.asarray(p)
        pp = np.zeros(total, dtype=p.dtype)
        pp[:nvalid] = p
        pds.append(jax.device_put(pp, sharding))
    sk, sp = dist_sort(kd, tuple(pds), mesh=mesh)
    sk = np.asarray(sk)[:nvalid]
    return sk, tuple(np.asarray(p)[:nvalid] for p in sp)


def coo_to_csr_distributed(rows, cols, vals, shape, num_shards: int | None = None):
    """Distributed COO->CSR conversion (the coo.tocsr path of coo.py:233).

    Sorts (row, col) keys across the mesh with ``dist_sort``, then performs
    the dedup + indptr build. Returns a ``csr_array``. The sharded sort is
    the scale-out stage; the final assembly mirrors the reference's
    SORTED_COORDS_TO_COUNTS + nnz_to_pos scan.
    """
    import sparse_tpu
    from ..ops.coords import require_x64_keys

    m, n = int(shape[0]), int(shape[1])
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals)
    require_x64_keys(shape) if m * n > np.iinfo(np.int32).max else None
    keys = rows * n + cols
    skeys, (svals,) = dist_sort_host(keys, (vals,), num_shards)
    srows = (skeys // n).astype(np.int64)
    scols = (skeys % n).astype(np.int64)
    # collapse duplicates (sum) — sorted, so one segment pass
    if skeys.shape[0]:
        is_new = np.concatenate([[True], skeys[1:] != skeys[:-1]])
        seg = np.cumsum(is_new) - 1
        uvals = np.zeros(int(seg[-1]) + 1, dtype=vals.dtype)
        np.add.at(uvals, seg, svals)
        urows = srows[is_new]
        ucols = scols[is_new]
    else:
        urows, ucols, uvals = srows, scols, svals
    indptr = np.zeros(m + 1, dtype=np.int64)
    np.add.at(indptr, urows + 1, 1)
    indptr = np.cumsum(indptr)
    return sparse_tpu.csr_array.from_parts(uvals, ucols, indptr, (m, n))
