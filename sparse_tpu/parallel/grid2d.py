"""2-D processor-grid algorithms: distributed cdist and quantum set lookup.

Reference analogs:
  * ``sparse/spatial.py:48-84`` — EUCLIDEAN_CDIST launched on a manual 2-D
    grid, XA row-tiled over grid-i and XB row-tiled over grid-j;
  * ``sparse/quantum.py:81-151`` — CREATE_HAMILTONIANS on a 2-D replication
    grid: grid-x partitions the current independent sets, grid-y partitions
    the prior sets, each processor matching its (x, y) tile pair.

TPU-native redesign: both are ``shard_map`` programs over a
``get_mesh_2d()`` mesh. GSPMD replicates each operand along the orthogonal
grid axis automatically from the in_specs — the reference's promote/
projection-functor machinery disappears. cdist needs no collectives at all
(the output is disjoint 2-D tiles); the set lookup combines per-tile hits
with one ``psum`` along grid-y (each query matches in exactly one y-tile).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import comm
from .mesh import get_mesh_2d

from .mesh import shard_map  # version-portable (check_vma/check_rep shim)


def _pad_rows(X: np.ndarray, mult: int) -> np.ndarray:
    m = X.shape[0]
    pad = (-m) % mult
    if pad == 0:
        return X
    return np.concatenate([X, np.zeros((pad, *X.shape[1:]), dtype=X.dtype)])


def cdist_2d(XA, XB, mesh: Mesh | None = None, metric: str = "euclidean"):
    """Pairwise distances with the output tiled over a 2-D device mesh.

    XA rows tile along gx, XB rows along gy (the reference's launch grid,
    spatial.py:48-84); tile (i, j) computes its [m/gx, n/gy] block with the
    local MXU formulation. Returns the full [m, n] host array.
    """
    from ..spatial import _cdist_euclidean, _cdist_sqeuclidean

    if metric == "euclidean":
        tile_fn = _cdist_euclidean
    elif metric == "sqeuclidean":
        tile_fn = _cdist_sqeuclidean
    else:
        raise ValueError(f"unsupported metric {metric!r}")
    if mesh is None:
        mesh = get_mesh_2d()
    ax_x, ax_y = mesh.axis_names
    gx, gy = mesh.devices.shape

    XA = np.asarray(XA)
    XB = np.asarray(XB)
    m, n = XA.shape[0], XB.shape[0]
    XAp = _pad_rows(XA, gx)
    XBp = _pad_rows(XB, gy)

    smapped = shard_map(
        lambda a, b: tile_fn(a, b),
        mesh=mesh,
        in_specs=(P(ax_x, None), P(ax_y, None)),
        out_specs=P(ax_x, ax_y),
        check_vma=False,
    )
    Ap = jax.device_put(XAp, NamedSharding(mesh, P(ax_x, None)))
    Bp = jax.device_put(XBp, NamedSharding(mesh, P(ax_y, None)))
    out = jax.jit(smapped)(Ap, Bp)
    return np.asarray(out)[:m, :n]


# ---------------------------------------------------------------------------
# Quantum: 2-D replicated subset lookup (CREATE_HAMILTONIANS grid analog)
# ---------------------------------------------------------------------------
def _lex_less_equal(q, s):
    """Lexicographic q <= s for [..., W] uint64 word rows (vectorized)."""
    # walk words most-significant first; strictly-less at the first
    # differing word decides
    W = q.shape[-1]
    lt = jnp.zeros(q.shape[:-1], dtype=bool)
    eq = jnp.ones(q.shape[:-1], dtype=bool)
    for w in range(W):
        lt = lt | (eq & (q[..., w] < s[..., w]))
        eq = eq & (q[..., w] == s[..., w])
    return lt | eq


def _searchsorted_rows(sorted_block, queries):
    """Binary-search each query row in a lex-sorted [S, W] block.

    Returns (pos, found): pos is the insertion index, found whether
    sorted_block[pos] == query. Pure lax ops — runs on device inside
    shard_map (the per-tile body of the reference's CREATE_HAMILTONIANS
    task, quantum.cc:163-197).
    """
    S = sorted_block.shape[0]
    Q = queries.shape[0]
    steps = max(int(np.ceil(np.log2(max(S, 1)))) + 1, 1)

    def body(_, lohi):
        lo, hi = lohi  # [Q] int32: search window [lo, hi)
        mid = (lo + hi) // 2
        le = _lex_less_equal(queries, sorted_block[mid])  # q <= s[mid]
        new_hi = jnp.where(le, mid, hi)
        new_lo = jnp.where(le, lo, mid + 1)
        return new_lo, new_hi

    lo0 = jnp.zeros(Q, dtype=jnp.int32)
    hi0 = jnp.full(Q, S, dtype=jnp.int32)
    lo, hi = jax.lax.fori_loop(0, steps, body, (lo0, hi0))
    pos = jnp.clip(hi, 0, S - 1)
    found = jnp.all(sorted_block[pos] == queries, axis=-1)
    return pos, found


def _to_u32_words(a: np.ndarray) -> np.ndarray:
    """[N, W] uint64 -> [N, 2W] uint32, preserving lexicographic order
    (hi word first). Keeps the kernel off uint64, which jax only carries
    under x64 mode."""
    a = a.astype(np.uint64, copy=False)
    hi = (a >> np.uint64(32)).astype(np.uint32)
    lo = (a & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out = np.empty((a.shape[0], a.shape[1] * 2), dtype=np.uint32)
    out[:, 0::2] = hi
    out[:, 1::2] = lo
    return out


def lookup_2d(sorted_sets: np.ndarray, queries: np.ndarray, mesh: Mesh | None = None):
    """Find each query row's index in lex-sorted ``sorted_sets`` on a 2-D mesh.

    grid-x partitions the queries (the current level's removed-subsets),
    grid-y partitions the sorted prior sets — the reference's 2-D replication
    strategy (quantum.py:86-107). Each tile binary-searches its local y-block;
    one ``psum`` along grid-y combines (exactly one block holds each query).
    Returns positions into ``sorted_sets`` ([Q] int64); raises if any query
    is missing (lookup-failed discipline of quantum.py's std::map).
    """
    if mesh is None:
        mesh = get_mesh_2d()
    ax_x, ax_y = mesh.axis_names
    gx, gy = mesh.devices.shape
    sorted_sets = _to_u32_words(np.asarray(sorted_sets))
    queries = _to_u32_words(np.asarray(queries))
    S, W = sorted_sets.shape
    Q = queries.shape[0]

    # pad: sets to a multiple of gy with +inf rows (all-ones sorts last and
    # never equals a real set since queries are proper subsets), queries to
    # a multiple of gx with all-ones rows (never found; masked off at end)
    pad_row = np.full((1, W), np.uint32(0xFFFFFFFF), dtype=np.uint32)
    Sp = S + ((-S) % gy)
    Qp = Q + ((-Q) % gx)
    sets_p = np.concatenate([sorted_sets, np.repeat(pad_row, Sp - S, 0)])
    qs_p = np.concatenate([queries, np.repeat(pad_row, Qp - Q, 0)])
    Sl = Sp // gy

    led = comm.ledger("grid2d.lookup")

    def tile(q_l, s_l):
        j = jax.lax.axis_index(ax_y)
        pos, found = _searchsorted_rows(s_l, q_l)
        gpos = jnp.where(found, pos.astype(jnp.int64) + j.astype(jnp.int64) * Sl, 0)
        # each query is found in exactly one y-block; psum combines
        return (
            comm.psum(gpos, ax_y, ledger=led, tag="pos"),
            comm.psum(found.astype(jnp.int32), ax_y, ledger=led, tag="found"),
        )

    smapped = shard_map(
        tile,
        mesh=mesh,
        in_specs=(P(ax_x, None), P(ax_y, None)),
        out_specs=(P(ax_x), P(ax_x)),
        check_vma=False,
    )
    qd = jax.device_put(qs_p, NamedSharding(mesh, P(ax_x, None)))
    sd = jax.device_put(sets_p, NamedSharding(mesh, P(ax_y, None)))
    gpos, found = jax.jit(smapped)(qd, sd)
    led.commit(1, gx * gy)  # always-on measured-comm metrics
    gpos = np.asarray(gpos)[:Q]
    found = np.asarray(found)[:Q]
    if not np.all(found == 1):
        raise RuntimeError("subset lookup failed: predecessor set missing")
    return gpos
