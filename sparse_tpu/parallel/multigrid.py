"""Mesh-distributed multigrid V-cycle machinery shared by AMG and GMG.

Reference analog: the reference's multigrid examples build their hierarchies
on the control node and launch per-level SpMV/SpGEMM tasks; at scale the
coarse levels serialize and weak scaling collapses (SURVEY §6: GMG at 4%
efficiency on 192 GPUs). Here every level's operators become ``DistCSR``
row-block shards with PINNED equal splits — the padded vector spaces line
up across restriction/prolongation, so the whole V-cycle is one traceable
function on padded mesh-sharded vectors and compiles INTO the ``dist_cg``
while_loop (no per-level launches, no host round-trips).

The smoother is weighted Jacobi in multiplier form: per level a padded
vector ``W`` with ``x = W * r`` as pre/post smoothing — covering both the
AMG form (W = c0 / diag(A)) and the GMG form (W = omega * D_inv). Padded
slots of the inputs stay zero through the cycle (padded matrix rows are
zero), so W's padding value is inert.
"""

from __future__ import annotations

import numpy as np

from .dist import shard_csr
from .partition import equal_row_splits

__all__ = [
    "shard_hierarchy",
    "make_dist_vcycle",
    "make_replicated_tail",
    "tail_crossover",
    "hierarchy_comm_per_cycle",
    "vcycle_operator",
]


def vcycle_operator(cycle, m_pad: int, dtype=None):
    """Promote a V-cycle apply (:func:`make_dist_vcycle`'s return) to a
    :class:`~sparse_tpu.linalg.LinearOperator` on the padded sharded
    vector space (ISSUE 14 satellite).

    ``dist_cg`` accepts either form; the operator view is what the
    fleet's row-shard lane threads through
    ``SolveSession(row_precond=...)`` /
    :func:`sparse_tpu.fleet.build_row_program` — the hook builds the
    hierarchy per layout, wraps its cycle here, and the distributed CG
    preconditions on it with the V-cycle compiled INTO the while_loop
    (no per-level launches, no host round trips)."""
    import numpy as _np

    from ..linalg import LinearOperator

    m_pad = int(m_pad)
    return LinearOperator(
        (m_pad, m_pad), matvec=cycle,
        dtype=_np.dtype(dtype if dtype is not None else _np.float64),
    )


def hierarchy_comm_per_cycle(ops) -> dict:
    """Measured per-V-cycle collective bytes of a sharded hierarchy.

    Sums each level's trace-populated SpMV ledgers (``parallel/comm.py``;
    the vcycle applies A three times and R/P once per level) into
    per-level and total bytes-per-shard-per-cycle — the weak-scaling
    number for the preconditioner, from the traced programs rather than
    the structural model. Levels whose programs have not been traced yet
    (no solve run) contribute zero; ``exact`` goes false if any level's
    accounting carries a capacity bound.
    """
    levels = []
    total = 0
    exact = True
    for i, (Ad, Rd, Pd) in enumerate(ops):
        per_level = 0
        for op, execs in ((Ad, 3), (Rd, 1), (Pd, 1)):
            led = getattr(op, "_comm_ledger", None) if op is not None else None
            if led is not None and led.entries:
                per_level += led.bytes_per_shard() * execs
                exact = exact and led.exact
        levels.append(per_level)
        total += per_level
    return {
        "levels_bytes_per_shard": levels,
        "bytes_per_shard_per_cycle": total,
        "exact": exact,
    }


def tail_crossover(sizes, replicate_below: int, bottom_always: bool = False):
    """Single-sourced crossover policy for the replicated coarse tail.

    Returns the first level index (>= 1, keeping the finest level sharded)
    whose row count is <= ``replicate_below``; returns ``len(sizes)`` when
    NO level qualifies (callers keep the fully-sharded cycle — never
    densify a large coarsest level). ``bottom_always=True`` clamps to the
    bottom level for hierarchies whose bottom is replicated regardless of
    size (e.g. a dense direct solve that was always replicated).
    """
    L = len(sizes)
    for i in range(1, L):
        if sizes[i] <= replicate_below:
            return i
    return L - 1 if bottom_always else L


def shard_hierarchy(As, RPs, mesh):
    """Shard a multigrid hierarchy onto the mesh with pinned equal splits.

    ``As``: per-level system matrices (len L, finest first).
    ``RPs``: per-coarsening (R, P) pairs (len L-1); R maps level i -> i+1.
    Returns ``(ops, splits)`` where ``ops[i] = (Ad, Rd, Pd)`` (the last
    level has ``Rd = Pd = None``).
    """
    S = int(mesh.devices.size)
    splits = [equal_row_splits(A.shape[0], S) for A in As]
    ops = []
    for i, A in enumerate(As):
        Ad = shard_csr(
            A.tocsr(), mesh=mesh, row_splits=splits[i], col_splits=splits[i]
        )
        if i < len(RPs):
            R, P = RPs[i]
            Rd = shard_csr(
                R.tocsr(), mesh=mesh,
                row_splits=splits[i + 1], col_splits=splits[i],
            )
            Pd = shard_csr(
                P.tocsr(), mesh=mesh,
                row_splits=splits[i], col_splits=splits[i + 1],
            )
            ops.append((Ad, Rd, Pd))
        else:
            ops.append((Ad, None, None))
    return ops, splits


def make_dist_vcycle(ops, weights, coarse_apply):
    """Traceable V-cycle on padded vectors: pre-smooth, restrict, recurse,
    prolong, post-smooth.

    ``weights[i]``: padded Jacobi multiplier vector for level i.
    ``coarse_apply``: padded [m_pad_coarse] -> [m_pad_coarse] bottom solve
    (a replicated dense solve, or one more smoothing application).
    Returns a function usable as the ``dist_cg`` preconditioner ``M``.
    """

    def cycle(lvl, rp):
        if lvl == len(ops) - 1:
            return coarse_apply(rp)
        Ad, Rd, Pd = ops[lvl]
        W = weights[lvl]
        x = W * rp
        fine_r = rp - Ad.spmv_padded(x)
        coarse_x = cycle(lvl + 1, Rd.spmv_padded(fine_r))
        xc = x + Pd.spmv_padded(coarse_x)
        return xc + W * (rp - Ad.spmv_padded(xc))

    return lambda rp: cycle(0, rp)


def make_replicated_tail(
    As, RPs, weights, row_splits, R_pad, bottom="solve", bottom_weight=None
):
    """Dense REPLICATED V-cycle over the coarse tail of a hierarchy.

    The reference's weak scaling collapses on the coarse levels (GMG at 4%
    efficiency on 192 GPUs, SURVEY §6): below a few thousand rows the
    per-level halo/gather collectives cost more than the level's whole
    compute. The TPU-native fix is NOT a subset mesh (a second mesh inside
    one SPMD program) but REPLICATION: every device runs the identical tiny
    dense tail — one gather into the replicated space on entry, one scatter
    back on exit, and ZERO collectives for any number of tail levels. Dense
    [n, n] matvecs on the MXU beat sparse gathers at these sizes anyway.

    ``As``: tail-level matrices (host/scipy-convertible, finest-of-tail
    first — As[0] is the level the sharded cycle restricts INTO).
    ``RPs``: (R, P) pairs WITHIN the tail (len == len(As) - 1).
    ``weights``: per-level host Jacobi multiplier vectors [n_i] for the
    smoothed levels (len == len(As) - 1; the bottom uses ``bottom``).
    ``row_splits`` / ``R_pad``: the padded mesh layout of As[0]'s level
    (from ``shard_hierarchy``).
    ``bottom``: 'solve' (dense direct solve) or 'smooth' (one weighted-
    Jacobi application with ``bottom_weight``).

    Returns ``coarse_apply``: padded sharded [S*R_pad] -> same, traceable —
    plug it straight into ``make_dist_vcycle``.
    """
    import jax.numpy as jnp

    def _dense(M):
        M = M.tocsr() if hasattr(M, "tocsr") else M
        return jnp.asarray(np.asarray(M.toarray() if hasattr(M, "toarray") else M))

    A_d = [_dense(A) for A in As]
    R_d = [_dense(R) for R, _ in RPs]
    P_d = [_dense(P) for _, P in RPs]
    W_d = [jnp.asarray(np.asarray(w)) for w in weights]
    if bottom == "solve":
        # factor once at build time; lu_solve inside the cycle
        import jax.scipy.linalg as jsl

        lu, piv = jsl.lu_factor(A_d[-1])
    elif bottom == "smooth":
        if bottom_weight is None:
            raise ValueError("bottom='smooth' needs bottom_weight")
        Wb = jnp.asarray(np.asarray(bottom_weight))
    else:
        raise ValueError(f"unknown bottom={bottom!r}")

    # padded-space <-> replicated-space index map for As[0]'s level
    n0 = A_d[0].shape[0]
    S = len(row_splits) - 1
    g = np.arange(n0, dtype=np.int64)
    shard = np.clip(np.searchsorted(row_splits, g, side="right") - 1, 0, S - 1)
    imap = jnp.asarray(shard * R_pad + (g - row_splits[shard]))
    m_pad = S * R_pad

    def tail_cycle(lvl, r):
        if lvl == len(A_d) - 1:
            if bottom == "solve":
                import jax.scipy.linalg as jsl

                return jsl.lu_solve((lu, piv), r)
            return Wb * r
        W = W_d[lvl]
        x = W * r
        fine_r = r - A_d[lvl] @ x
        coarse_x = tail_cycle(lvl + 1, R_d[lvl] @ fine_r)
        xc = x + P_d[lvl] @ coarse_x
        return xc + W * (r - A_d[lvl] @ xc)

    def coarse_apply(rp):
        r = rp[imap]  # padded sharded -> replicated [n0]: ONE gather
        x = tail_cycle(0, r)
        return jnp.zeros((m_pad,), x.dtype).at[imap].set(x)

    return coarse_apply
