"""Mesh-distributed multigrid V-cycle machinery shared by AMG and GMG.

Reference analog: the reference's multigrid examples build their hierarchies
on the control node and launch per-level SpMV/SpGEMM tasks; at scale the
coarse levels serialize and weak scaling collapses (SURVEY §6: GMG at 4%
efficiency on 192 GPUs). Here every level's operators become ``DistCSR``
row-block shards with PINNED equal splits — the padded vector spaces line
up across restriction/prolongation, so the whole V-cycle is one traceable
function on padded mesh-sharded vectors and compiles INTO the ``dist_cg``
while_loop (no per-level launches, no host round-trips).

The smoother is weighted Jacobi in multiplier form: per level a padded
vector ``W`` with ``x = W * r`` as pre/post smoothing — covering both the
AMG form (W = c0 / diag(A)) and the GMG form (W = omega * D_inv). Padded
slots of the inputs stay zero through the cycle (padded matrix rows are
zero), so W's padding value is inert.
"""

from __future__ import annotations

import numpy as np

from .dist import shard_csr
from .partition import equal_row_splits

__all__ = ["shard_hierarchy", "make_dist_vcycle"]


def shard_hierarchy(As, RPs, mesh):
    """Shard a multigrid hierarchy onto the mesh with pinned equal splits.

    ``As``: per-level system matrices (len L, finest first).
    ``RPs``: per-coarsening (R, P) pairs (len L-1); R maps level i -> i+1.
    Returns ``(ops, splits)`` where ``ops[i] = (Ad, Rd, Pd)`` (the last
    level has ``Rd = Pd = None``).
    """
    S = int(mesh.devices.size)
    splits = [equal_row_splits(A.shape[0], S) for A in As]
    ops = []
    for i, A in enumerate(As):
        Ad = shard_csr(
            A.tocsr(), mesh=mesh, row_splits=splits[i], col_splits=splits[i]
        )
        if i < len(RPs):
            R, P = RPs[i]
            Rd = shard_csr(
                R.tocsr(), mesh=mesh,
                row_splits=splits[i + 1], col_splits=splits[i],
            )
            Pd = shard_csr(
                P.tocsr(), mesh=mesh,
                row_splits=splits[i], col_splits=splits[i + 1],
            )
            ops.append((Ad, Rd, Pd))
        else:
            ops.append((Ad, None, None))
    return ops, splits


def make_dist_vcycle(ops, weights, coarse_apply):
    """Traceable V-cycle on padded vectors: pre-smooth, restrict, recurse,
    prolong, post-smooth.

    ``weights[i]``: padded Jacobi multiplier vector for level i.
    ``coarse_apply``: padded [m_pad_coarse] -> [m_pad_coarse] bottom solve
    (a replicated dense solve, or one more smoothing application).
    Returns a function usable as the ``dist_cg`` preconditioner ``M``.
    """

    def cycle(lvl, rp):
        if lvl == len(ops) - 1:
            return coarse_apply(rp)
        Ad, Rd, Pd = ops[lvl]
        W = weights[lvl]
        x = W * rp
        fine_r = rp - Ad.spmv_padded(x)
        coarse_x = cycle(lvl + 1, Rd.spmv_padded(fine_r))
        xc = x + Pd.spmv_padded(coarse_x)
        return xc + W * (rp - Ad.spmv_padded(xc))

    return lambda rp: cycle(0, rp)
