"""ODE integration: ``solve_ivp`` with RK23 / RK45 / DOP853.

Reference analog: ``sparse/integrate.py`` (1824 LoC) — a scipy-style IVP
solver stack (OdeSolver integrate.py:204, RK23 :750, RK45 :838, DOP853 :987,
solve_ivp :1303, dense outputs, event handling) whose inner RK stage update
``dy = h * K[:s].T @ a`` is fused into the RK_CALC_DY task
(integrate.py:478-494, ``src/sparse/integrate/runge_kutta.*``).

TPU-first redesign: the state vector ``y`` and every stage live on device;
all stage math for one step attempt — the K evaluations, the candidate
``y_new``, the embedded error estimate — is a single jitted closure, so the
RK_CALC_DY fusion is subsumed by XLA (the stage contraction is an [s, n]
matvec, MXU-shaped for wide systems). The adaptive step-size controller is
O(1) host scalar work, synced once per step attempt on the error norm — the
same control/device boundary the reference blocks on. Complex-valued systems
(the quantum evolution workload, §3.5) are supported natively.
"""

from __future__ import annotations

import numbers

import jax
import jax.numpy as jnp
import numpy as np

from . import dop853_coefficients
from .utils import asjnp, in_trace

SAFETY = 0.9
MIN_FACTOR = 0.2
MAX_FACTOR = 10.0
EPS = np.finfo(float).eps


def _jit_with_eager_fallback(core):
    """jit `core`, but fall back to eager if the user RHS isn't traceable.

    The RHS is user code; numpy-based functions (scipy-style) raise trace
    errors under jit, so those run the same math eagerly (device arrays,
    op-by-op) — still correct, just without whole-step fusion.
    """
    jcore = jax.jit(core)
    state = {"use_jit": True}

    def wrapper(*a):
        if state["use_jit"]:
            try:
                return jcore(*a)
            except (
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerBoolConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError,
            ):
                state["use_jit"] = False
        return core(*a)

    return wrapper


def _wrap_fun(fun, args):
    """Bind args and route standalone RHS calls through jit.

    The solver's hot loop compiles the whole RK step (``_build_step_core``),
    but the setup path (initial f, first-step selection) and any eager
    fallback call ``fun`` directly. Experimental accelerator backends (the
    axon TPU tunnel) only reliably execute COMPILED programs — eager
    elementwise arithmetic in a user RHS can fail with backend
    Unimplemented errors — so the standalone calls are jitted too, with
    ``t`` passed as a 0-d array so changing times never retrace. Inside an
    active trace (the step core) the raw callable is used directly, and a
    non-traceable (numpy-based) RHS falls back to eager per-call.
    """
    if args:
        def raw(t, y):
            return asjnp(fun(t, y, *args))
    else:
        def raw(t, y):
            return asjnp(fun(t, y))

    jraw = jax.jit(raw)
    state = {"use_jit": True}
    tdt = np.float64 if jax.config.jax_enable_x64 else np.float32

    def wrapped(t, y):
        if in_trace():
            return raw(t, y)
        if state["use_jit"]:
            try:
                return jraw(np.asarray(t, dtype=tdt), y)
            except (
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerBoolConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError,
            ):
                state["use_jit"] = False
        return raw(t, y)

    # identity anchor for the step-core cache: repeated solves over the
    # SAME user RHS (warm-up solve then timed solve) must reuse the same
    # compiled core even though each solve_ivp builds a fresh wrapper
    # Only VALUE-typed args may key the cache. Anything with a mutable
    # numeric payload (ndarray, jax array, sparse matrix — the common
    # solve_ivp(f, span, y0, args=(A,)) pattern) must NOT: hashability is
    # no safeguard (sparse matrices hash by identity), and an identity-
    # keyed hit would silently serve a core with the OLD values baked in
    # as trace constants after an in-place `A.data *= 2` between solves.
    # Such solves retrace instead (scipy-parity cost, correctness first).
    def value_typed(a):
        if isinstance(a, (numbers.Number, str, bytes, type(None))):
            return True
        if isinstance(a, (tuple, frozenset)):
            return all(value_typed(x) for x in a)
        return False

    if all(value_typed(a) for a in args):
        wrapped._cache_key = (fun, tuple(args))
    return wrapped


def validate_max_step(max_step):
    if max_step <= 0:
        raise ValueError("`max_step` must be positive.")
    return max_step


def validate_tol(rtol, atol, n):
    if rtol < 100 * EPS:
        rtol = 100 * EPS
    atol = np.asarray(atol)
    if atol.ndim > 0 and atol.shape != (n,):
        raise ValueError("`atol` has wrong shape.")
    if np.any(atol < 0):
        raise ValueError("`atol` must be positive.")
    return rtol, atol


def _axpy_jit(y, a, f):
    return y + a * f


_axpy = jax.jit(_axpy_jit)


def select_initial_step(fun, t0, y0, f0, direction, order, rtol, atol):
    """Empirical first-step selection (Hairer et al., as in scipy).

    The y1 probe runs through a jitted axpy: experimental accelerator
    backends (the axon tunnel) only reliably execute COMPILED programs,
    and this is the one eager device op in the solver setup path. The
    step scalar is passed as a numpy value so h0 changes don't retrace.
    """
    if y0.shape[0] == 0:
        return np.inf
    y0_h = np.asarray(y0)
    f0_h = np.asarray(f0)
    scale = atol + np.abs(y0_h) * rtol
    d0 = float(np.linalg.norm(y0_h / scale) / np.sqrt(y0.shape[0]))
    d1 = float(np.linalg.norm(f0_h / scale) / np.sqrt(y0.shape[0]))
    h0 = 1e-6 if d0 < 1e-5 or d1 < 1e-5 else 0.01 * d0 / d1
    y1 = _axpy(y0, np.asarray(h0 * direction, dtype=f0_h.real.dtype), f0)
    f1 = fun(t0 + h0 * direction, y1)
    d2 = (
        float(np.linalg.norm((np.asarray(f1) - f0_h) / scale) / np.sqrt(y0.shape[0]))
        / h0
    )
    if d1 <= 1e-15 and d2 <= 1e-15:
        h1 = max(1e-6, h0 * 1e-3)
    else:
        h1 = (0.01 / max(d1, d2)) ** (1.0 / (order + 1))
    return min(100 * h0, h1)


class OdeSolver:
    """Base solver protocol (reference integrate.py:204)."""

    TOO_SMALL_STEP = "Required step size is less than spacing between numbers."

    def __init__(self, fun, t0, y0, t_bound, vectorized=False, support_complex=True):
        self.t = t0
        self.t_old = None
        self.y = asjnp(y0)
        if np.issubdtype(self.y.dtype, np.integer):
            self.y = self.y.astype(
                jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            )
        self.t_bound = t_bound
        self.vectorized = vectorized
        if vectorized:
            base = fun

            def fun_single(t, y):
                return asjnp(base(t, y[:, None]))[:, 0]

            self.fun = fun_single
        else:
            self.fun = fun
        self.direction = np.sign(t_bound - t0) if t_bound != t0 else 1
        self.n = self.y.shape[0]
        self.status = "running"
        self.nfev = 0
        self.njev = 0
        self.nlu = 0

    @property
    def step_size(self):
        if self.t_old is None:
            return None
        return abs(self.t - self.t_old)

    def step(self):
        if self.status != "running":
            raise RuntimeError("Attempt to step on a failed or finished solver.")
        if self.n == 0 or self.t == self.t_bound:
            self.t_old = self.t
            self.t = self.t_bound
            self.status = "finished"
            return None
        t = self.t
        success, message = self._step_impl()
        if not success:
            self.status = "failed"
            return message
        self.t_old = t
        if self.direction * (self.t - self.t_bound) >= 0:
            self.status = "finished"
        return None

    def dense_output(self):
        if self.t_old is None:
            raise RuntimeError("Dense output is available after a successful step was made.")
        if self.n == 0 or self.t == self.t_old:
            return ConstantDenseOutput(self.t_old, self.t, self.y)
        return self._dense_output_impl()


class RungeKutta(OdeSolver):
    """Explicit embedded Runge-Kutta base (reference integrate.py:593-750)."""

    C: np.ndarray
    A: np.ndarray
    B: np.ndarray
    E: np.ndarray
    P: np.ndarray
    order: int
    error_estimator_order: int
    n_stages: int

    def __init__(
        self,
        fun,
        t0,
        y0,
        t_bound,
        max_step=np.inf,
        rtol=1e-3,
        atol=1e-6,
        vectorized=False,
        first_step=None,
        **extraneous,
    ):
        super().__init__(fun, t0, y0, t_bound, vectorized, support_complex=True)
        self.max_step = validate_max_step(max_step)
        self.rtol, self.atol = validate_tol(rtol, atol, self.n)
        self.f = self.fun(self.t, self.y)
        self.nfev += 1
        if first_step is None:
            self.h_abs = select_initial_step(
                self.fun,
                t0,
                self.y,
                self.f,
                self.direction,
                self.error_estimator_order,
                self.rtol,
                self.atol,  # full (possibly per-component) tolerances
            )
            self.nfev += 1
        else:
            if first_step <= 0 or first_step > abs(t_bound - t0):
                raise ValueError("`first_step` has wrong magnitude.")
            self.h_abs = float(first_step)
        self.K = None
        self.error_exponent = -1.0 / (self.error_estimator_order + 1)
        self._step_core = self._build_step_core()

    # -- the fused, jitted step attempt (RK_CALC_DY analog) ----------------
    _STEP_CORE_CACHE: dict = {}

    def _build_step_core(self):
        # reuse the compiled core across solver instances for the same
        # (user fun, shapes, dtype, tolerances): a warm-up solve then
        # pays the trace/compile ONCE even without a persistent disk
        # cache — fresh jax.jit instances never share compilations
        ukey = getattr(self.fun, "_cache_key", None)
        ckey = None
        if ukey is not None:
            ckey = (
                type(self), ukey, self.y.shape, str(self.y.dtype),
                float(self.rtol), np.asarray(self.atol).tobytes(),
            )
            cached = RungeKutta._STEP_CORE_CACHE.get(ckey)
            if cached is not None:
                return cached
        core = self._build_step_core_uncached()
        if ckey is not None:
            cache = RungeKutta._STEP_CORE_CACHE
            if len(cache) > 32:  # bound: long test sessions, many RHSs
                cache.pop(next(iter(cache)))
            cache[ckey] = core
        return core

    def _build_step_core_uncached(self):
        A = self.A
        B = jnp.asarray(self.B)
        C = self.C
        E = jnp.asarray(self.E)
        n_stages = self.n_stages
        fun = self.fun
        rtol = self.rtol
        atol = self.atol

        def core(t, h, y, f):
            Ks = [f]
            for s in range(1, n_stages):
                a = A[s, :s]
                # dy = h * K[:s].T @ a — the RK_CALC_DY contraction, fused by XLA
                dy = h * sum(
                    aj * Kj for aj, Kj in zip(a, Ks) if aj != 0
                )
                Ks.append(fun(t + C[s] * h, y + dy))
            K = jnp.stack(Ks)  # [n_stages, n]
            y_new = y + h * (B @ K)
            f_new = fun(t + h, y_new)
            K_full = jnp.concatenate([K, f_new[None]])  # FSAL row
            err = h * (E @ K_full)
            scale = atol + jnp.maximum(jnp.abs(y), jnp.abs(y_new)) * rtol
            error_norm = jnp.sqrt(
                jnp.mean(jnp.abs(err / scale) ** 2)
            ) if y.shape[0] else jnp.zeros(())
            return y_new, f_new, K_full, error_norm

        return _jit_with_eager_fallback(core)

    def _step_impl(self):
        t = self.t
        max_step = self.max_step
        min_step = 10 * abs(np.nextafter(t, self.direction * np.inf) - t)
        h_abs = min(max(self.h_abs, min_step), max_step)

        step_accepted = False
        step_rejected = False
        while not step_accepted:
            if h_abs < min_step:
                return False, self.TOO_SMALL_STEP
            h = h_abs * self.direction
            t_new = t + h
            if self.direction * (t_new - self.t_bound) > 0:
                t_new = self.t_bound
            h = t_new - t
            h_abs = abs(h)
            y_new, f_new, K, error_norm = self._step_core(t, h, self.y, self.f)
            # core evaluates fun at stages 1..n_stages-1 plus f_new
            self.nfev += self.n_stages
            error_norm = float(error_norm)
            if error_norm < 1:
                factor = (
                    MAX_FACTOR
                    if error_norm == 0
                    else min(MAX_FACTOR, SAFETY * error_norm**self.error_exponent)
                )
                if step_rejected:
                    factor = min(1.0, factor)
                h_abs *= factor
                step_accepted = True
            else:
                h_abs *= max(MIN_FACTOR, SAFETY * error_norm**self.error_exponent)
                step_rejected = True

        self.h_previous = h
        self.y_old = self.y
        self.t = t_new
        self.y = y_new
        self.h_abs = h_abs
        self.f = f_new
        self.K = K
        return True, None

    def _dense_output_impl(self):
        Q = self.K.T @ jnp.asarray(self.P, dtype=self.K.dtype)
        return RkDenseOutput(self.t_old, self.t, self.y_old, Q)


class RK23(RungeKutta):
    """Bogacki-Shampine 3(2) pair (reference integrate.py:750)."""

    order = 3
    error_estimator_order = 2
    n_stages = 3
    C = np.array([0, 1 / 2, 3 / 4])
    A = np.array([[0, 0, 0], [1 / 2, 0, 0], [0, 3 / 4, 0]])
    B = np.array([2 / 9, 1 / 3, 4 / 9])
    E = np.array([5 / 72, -1 / 12, -1 / 9, 1 / 8])
    P = np.array(
        [[1, -4 / 3, 5 / 9], [0, 1, -2 / 3], [0, 4 / 3, -8 / 9], [0, -1, 1]]
    )


class RK45(RungeKutta):
    """Dormand-Prince 5(4) pair (reference integrate.py:838)."""

    order = 5
    error_estimator_order = 4
    n_stages = 6
    C = np.array([0, 1 / 5, 3 / 10, 4 / 5, 8 / 9, 1])
    A = np.array(
        [
            [0, 0, 0, 0, 0],
            [1 / 5, 0, 0, 0, 0],
            [3 / 40, 9 / 40, 0, 0, 0],
            [44 / 45, -56 / 15, 32 / 9, 0, 0],
            [19372 / 6561, -25360 / 2187, 64448 / 6561, -212 / 729, 0],
            [9017 / 3168, -355 / 33, 46732 / 5247, 49 / 176, -5103 / 18656],
        ]
    )
    B = np.array([35 / 384, 0, 500 / 1113, 125 / 192, -2187 / 6784, 11 / 84])
    E = np.array(
        [71 / 57600, 0, -71 / 16695, 71 / 1920, -17253 / 339200, 22 / 525, -1 / 40]
    )
    P = np.array(
        [
            [1, -8048581381 / 2820520608, 8663915743 / 2820520608, -12715105075 / 11282082432],
            [0, 0, 0, 0],
            [0, 131558114200 / 32700410799, -68118460800 / 10900136933, 87487479700 / 32700410799],
            [0, -1754552775 / 470086768, 14199869525 / 1410260304, -10690763975 / 1880347072],
            [0, 127303824393 / 49829197408, -318862633887 / 49829197408, 701980252875 / 199316789632],
            [0, -282668133 / 205662961, 2019193451 / 616988883, -1453857185 / 822651844],
            [0, 40617522 / 29380423, -110615467 / 29380423, 69997945 / 29380423],
        ]
    )


class DOP853(RungeKutta):
    """Hairer's 8(5,3) method with 7th-order dense output (integrate.py:987)."""

    n_stages = dop853_coefficients.N_STAGES
    order = 8
    error_estimator_order = 7
    A = dop853_coefficients.A[:n_stages, :n_stages]
    B = dop853_coefficients.B
    C = dop853_coefficients.C[:n_stages]
    E3 = dop853_coefficients.E3
    E5 = dop853_coefficients.E5
    D = dop853_coefficients.D
    A_EXTRA = dop853_coefficients.A[n_stages + 1 :]
    C_EXTRA = dop853_coefficients.C[n_stages + 1 :]
    E = None  # error handled by the 5-3 pair below

    def _build_step_core_uncached(self):
        A = self.A
        B = jnp.asarray(self.B)
        C = self.C
        E3 = jnp.asarray(self.E3)
        E5 = jnp.asarray(self.E5)
        n_stages = self.n_stages
        fun = self.fun
        rtol = self.rtol
        atol = self.atol

        def core(t, h, y, f):
            Ks = [f]
            for s in range(1, n_stages):
                a = A[s, :s]
                dy = h * sum(aj * Kj for aj, Kj in zip(a, Ks) if aj != 0)
                Ks.append(fun(t + C[s] * h, y + dy))
            K = jnp.stack(Ks)
            y_new = y + h * (B @ K)
            f_new = fun(t + h, y_new)
            K_full = jnp.concatenate([K, f_new[None]])
            scale = atol + jnp.maximum(jnp.abs(y), jnp.abs(y_new)) * rtol
            err5 = (E5 @ K_full) / scale
            err3 = (E3 @ K_full) / scale
            err5n2 = jnp.sum(jnp.abs(err5) ** 2)
            err3n2 = jnp.sum(jnp.abs(err3) ** 2)
            denom = err5n2 + 0.01 * err3n2
            nn = max(y.shape[0], 1)
            error_norm = jnp.abs(h) * err5n2 / jnp.sqrt(
                jnp.where(denom == 0, 1.0, denom) * nn
            )
            error_norm = jnp.where(denom > 0, error_norm, jnp.zeros(()))
            return y_new, f_new, K_full, error_norm

        return _jit_with_eager_fallback(core)

    def _dense_output_impl(self):
        """Extended-stage 7th-order interpolant (scipy-compatible)."""
        K = self.K  # [n_stages + 1, n]
        h = self.h_previous
        t_old = self.t_old
        fun = self.fun
        Ks_ext = list(K)
        for s_ext, (a, c) in enumerate(zip(self.A_EXTRA, self.C_EXTRA)):
            s = self.n_stages + 1 + s_ext
            dy = h * sum(
                float(aj) * Kj for aj, Kj in zip(a[:s], Ks_ext) if aj != 0
            )
            Ks_ext.append(fun(t_old + c * h, self.y_old + dy))
            self.nfev += 1
        K_ext = jnp.stack(Ks_ext)  # [N_STAGES_EXTENDED, n]
        D = jnp.asarray(self.D, dtype=K_ext.dtype)
        F = jnp.zeros(
            (dop853_coefficients.INTERPOLATOR_POWER, self.n), dtype=K_ext.dtype
        )
        f_old = K[0]
        delta_y = self.y - self.y_old
        F = F.at[0].set(delta_y)
        F = F.at[1].set(h * f_old - delta_y)
        F = F.at[2].set(2 * delta_y - h * (self.f + f_old))
        F = F.at[3:].set(h * (D @ K_ext))
        return Dop853DenseOutput(self.t_old, self.t, self.y_old, F)


# ---------------------------------------------------------------------------
# Dense outputs
# ---------------------------------------------------------------------------
class DenseOutput:
    def __init__(self, t_old, t):
        self.t_old = t_old
        self.t = t
        self.t_min = min(t, t_old)
        self.t_max = max(t, t_old)

    def __call__(self, t):
        t = np.asarray(t)
        if t.ndim > 1:
            raise ValueError("`t` must be a float or a 1-D array.")
        return self._call_impl(t)


class ConstantDenseOutput(DenseOutput):
    def __init__(self, t_old, t, value):
        super().__init__(t_old, t)
        self.value = value

    def _call_impl(self, t):
        if t.ndim == 0:
            return self.value
        return jnp.repeat(self.value[:, None], t.shape[0], axis=1)


class RkDenseOutput(DenseOutput):
    def __init__(self, t_old, t, y_old, Q):
        super().__init__(t_old, t)
        self.h = t - t_old
        self.Q = Q
        self.order = Q.shape[1] - 1
        self.y_old = y_old

    def _call_impl(self, t):
        x = (t - self.t_old) / self.h
        if t.ndim == 0:
            p = np.cumprod(np.tile(x, self.order + 1))
            y = self.h * (self.Q @ jnp.asarray(p, dtype=self.Q.dtype))
            return self.y_old + y
        p = np.cumprod(np.tile(x, (self.order + 1, 1)), axis=0)
        y = self.h * (self.Q @ jnp.asarray(p, dtype=self.Q.dtype))
        return self.y_old[:, None] + y


class Dop853DenseOutput(DenseOutput):
    def __init__(self, t_old, t, y_old, F):
        super().__init__(t_old, t)
        self.h = t - t_old
        self.F = F
        self.y_old = y_old

    def _call_impl(self, t):
        x = (t - self.t_old) / self.h
        if t.ndim == 0:
            y = jnp.zeros_like(self.y_old)
            for i, f in enumerate(reversed(list(self.F))):
                y = y + f
                y = y * (x if i % 2 == 0 else (1 - x))
            return y + self.y_old
        x = x[None, :]
        y = jnp.zeros((self.y_old.shape[0], t.shape[0]), dtype=self.y_old.dtype)
        xj = jnp.asarray(x, dtype=jnp.result_type(self.y_old.dtype, float))
        for i, f in enumerate(reversed(list(self.F))):
            y = y + f[:, None]
            y = y * (xj if i % 2 == 0 else (1 - xj))
        return y + self.y_old[:, None]


class OdeSolution:
    """Piecewise dense-output spline collection (scipy-compatible)."""

    def __init__(self, ts, interpolants):
        self.ts = np.asarray(ts)
        self.interpolants = interpolants
        d = np.diff(self.ts)
        self.ascending = np.all(d >= 0)
        self.t_min = self.ts[0] if self.ascending else self.ts[-1]
        self.t_max = self.ts[-1] if self.ascending else self.ts[0]

    def _segment(self, t):
        ts = self.ts if self.ascending else self.ts[::-1]
        i = np.clip(np.searchsorted(ts, t, side="left") - 1, 0, len(self.interpolants) - 1)
        if not self.ascending:
            i = len(self.interpolants) - 1 - i
        return int(i)

    def __call__(self, t):
        t = np.asarray(t, dtype=float)
        if t.ndim == 0:
            return self.interpolants[self._segment(t)](t)
        # group consecutive query points by segment: one batched interpolant
        # evaluation per segment instead of one dispatch per point
        segs = np.array([self._segment(tv) for tv in t])
        cols = []
        i = 0
        while i < t.shape[0]:
            j = i
            while j < t.shape[0] and segs[j] == segs[i]:
                j += 1
            cols.append(self.interpolants[segs[i]](t[i:j]))
            i = j
        return jnp.concatenate(cols, axis=1)


# ---------------------------------------------------------------------------
# Event handling
# ---------------------------------------------------------------------------
def prepare_events(events, args=()):
    if callable(events):
        events = (events,)
    if events is None:
        return None, None, None
    is_terminal = np.empty(len(events), dtype=bool)
    direction = np.empty(len(events))
    wrapped = []
    for i, event in enumerate(events):
        is_terminal[i] = bool(getattr(event, "terminal", False))
        direction[i] = getattr(event, "direction", 0)
        if args:
            # scipy contract: events receive the same extra args as fun
            wrapped.append(lambda t, y, event=event: event(t, y, *args))
        else:
            wrapped.append(event)
    return wrapped, is_terminal, direction


def solve_event_equation(event, sol, t_old, t):
    from scipy.optimize import brentq

    return brentq(
        lambda tt: float(np.asarray(event(tt, sol(tt)))), t_old, t, xtol=4 * EPS, rtol=4 * EPS
    )


def find_active_events(g, g_new, direction):
    g, g_new = np.asarray(g), np.asarray(g_new)
    up = (g <= 0) & (g_new >= 0)
    down = (g >= 0) & (g_new <= 0)
    either = up | down
    mask = (
        (up & (direction > 0))
        | (down & (direction < 0))
        | (either & (direction == 0))
    )
    return np.nonzero(mask)[0]


def handle_events(sol, events, active_events, is_terminal, t_old, t):
    roots = np.asarray(
        [solve_event_equation(events[e], sol, t_old, t) for e in active_events]
    )
    if np.any(is_terminal[active_events]):
        order = np.argsort(np.sign(t - t_old) * roots)
        active_events = active_events[order]
        roots = roots[order]
        tmask = is_terminal[active_events]
        stop = np.nonzero(tmask)[0][0]
        active_events = active_events[: stop + 1]
        roots = roots[: stop + 1]
        return active_events, roots, True
    return active_events, roots, False


# ---------------------------------------------------------------------------
# solve_ivp driver (reference integrate.py:1303)
# ---------------------------------------------------------------------------
from ._bdf import BDF as _BDFImpl  # noqa: E402
from ._radau import Radau as _RadauImpl  # noqa: E402


class BDF(_BDFImpl, OdeSolver):
    """Stiff variable-order BDF/NDF method (scipy.integrate.BDF; beyond
    the reference's explicit-RK-only menu). See sparse_tpu/_bdf.py."""


class Radau(_RadauImpl, OdeSolver):
    """Stiff L-stable Radau IIA(5) implicit RK (scipy.integrate.Radau;
    beyond the reference). See sparse_tpu/_radau.py."""


METHODS = {"RK23": RK23, "RK45": RK45, "DOP853": DOP853, "BDF": BDF,
           "Radau": Radau}

MESSAGES = {
    0: "The solver successfully reached the end of the integration interval.",
    1: "A termination event occurred.",
}


class OdeResult(dict):
    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    __setattr__ = dict.__setitem__


def solve_ivp(
    fun,
    t_span,
    y0,
    method="RK45",
    t_eval=None,
    dense_output=False,
    events=None,
    vectorized=False,
    args=None,
    _step_callback=None,
    **options,
):
    """Integrate dy/dt = fun(t, y), scipy-compatible subset (RK methods)."""
    if method not in METHODS and not (
        isinstance(method, type) and issubclass(method, OdeSolver)
    ):
        raise ValueError(f"`method` must be one of {set(METHODS)} or OdeSolver class.")
    t0, tf = map(float, t_span)
    y0 = asjnp(y0)
    if y0.ndim != 1:
        raise ValueError("`y0` must be 1-dimensional.")
    fun = _wrap_fun(fun, args or ())

    if t_eval is not None:
        t_eval = np.asarray(t_eval)
        if t_eval.ndim != 1:
            raise ValueError("`t_eval` must be 1-dimensional.")
        if np.any(t_eval < min(t0, tf)) or np.any(t_eval > max(t0, tf)):
            raise ValueError("Values in `t_eval` are not within `t_span`.")
        d = np.diff(t_eval)
        if tf > t0 and np.any(d <= 0) or tf < t0 and np.any(d >= 0):
            raise ValueError("Values in `t_eval` are not properly sorted.")
        if tf < t0:
            t_eval = t_eval[::-1]

    if isinstance(method, str):
        method = METHODS[method]
    solver = method(fun, t0, y0, tf, vectorized=vectorized, **options)

    if t_eval is None:
        ts = [t0]
        ys = [y0]
    else:
        ts = []
        ys = []
    interpolants = []

    events, is_terminal, event_dir = prepare_events(events, args or ())
    if events is not None:
        g = [float(np.asarray(event(t0, y0))) for event in events]
        t_events = [[] for _ in range(len(events))]
        y_events = [[] for _ in range(len(events))]
    else:
        t_events = None
        y_events = None

    status = None
    while status is None:
        message = solver.step()
        if solver.status == "finished":
            status = 0
        elif solver.status == "failed":
            status = -1
            break
        t_old = solver.t_old
        t = solver.t
        y = solver.y
        if _step_callback is not None:  # checkpoint.py hook
            _step_callback(t, y)

        if dense_output or t_eval is not None or events is not None:
            sol = solver.dense_output()
            if dense_output:
                interpolants.append(sol)
        else:
            sol = None

        if events is not None:
            g_new = [float(np.asarray(event(t, y))) for event in events]
            active = find_active_events(g, g_new, event_dir)
            if active.size > 0:
                root_events, roots, terminate = handle_events(
                    sol, events, active, is_terminal, t_old, t
                )
                for e, te in zip(root_events, roots):
                    t_events[e].append(te)
                    y_events[e].append(sol(te))
                if terminate:
                    status = 1
                    t = roots[-1]
                    y = sol(t)
            g = g_new

        if t_eval is None:
            ts.append(t)
            ys.append(y)
        else:
            if solver.direction > 0:
                t_eval_step = t_eval[
                    (t_eval >= t_old) & (t_eval <= t) & (t_eval > (ts[-1] if ts else -np.inf))
                ]
            else:
                t_eval_step = t_eval[
                    (t_eval <= t_old) & (t_eval >= t) & (t_eval < (ts[-1] if ts else np.inf))
                ]
            if t_eval_step.size > 0:
                for te in t_eval_step:
                    ts.append(float(te))
                    ys.append(sol(np.asarray(float(te))))

    message = MESSAGES.get(status, message)
    if t_events is not None:
        t_events = [np.asarray(te) for te in t_events]
        y_events = [
            (jnp.stack(ye, axis=0) if ye else np.empty((0, solver.n)))
            for ye in y_events
        ]  # [n_occurrences, n], matching scipy

    ts = np.asarray(ts)
    ys_arr = jnp.stack(ys, axis=1) if ys else np.empty((solver.n, 0))

    if dense_output:
        sol_out = OdeSolution(
            np.concatenate([[t0], [i.t for i in interpolants]]), interpolants
        ) if interpolants else None
    else:
        sol_out = None

    return OdeResult(
        t=ts,
        y=ys_arr,
        sol=sol_out,
        t_events=t_events,
        y_events=y_events,
        nfev=solver.nfev,
        njev=solver.njev,
        nlu=solver.nlu,
        status=status,
        message=message,
        success=status >= 0,
    )
