"""Shared helpers: dtype promotion, grid factorization, user-level warnings.

Reference analog: ``sparse/utils.py`` (store<->cunumeric bridges at utils.py:41-91
disappear on TPU — everything is a jax.Array; the dtype-promotion and grid helpers
at utils.py:120-150 carry over).
"""

from __future__ import annotations

import math
import warnings

import jax
import jax.numpy as jnp
import numpy as np


def find_last_user_stacklevel() -> int:
    """Stack level of the first frame outside sparse_tpu, for warnings.warn.

    Reference: ``sparse/utils.py:31-37``.
    """
    import inspect

    level = 1
    for frame, _ in zip(inspect.stack(), range(64)):
        if "sparse_tpu" not in frame.filename:
            break
        level += 1
    return level


def user_warning(msg: str) -> None:
    warnings.warn(msg, stacklevel=find_last_user_stacklevel())


def cast_to_common_type(*arrays):
    """Promote all arrays to a common dtype (reference: utils.py:120-141)."""
    dt = np.result_type(*[a.dtype for a in arrays])
    return tuple(a.astype(dt) for a in arrays)


def common_dtype(*arrays_or_dtypes):
    return np.result_type(
        *[getattr(a, "dtype", a) for a in arrays_or_dtypes]
    )


def factor_int(n: int) -> tuple[int, int]:
    """Factor n into a near-square (x, y) grid, x*y == n.

    Reference: ``sparse/utils.py:144-150`` — used for 2-D processor-grid launches
    (SpGEMM CSRxCSC, cdist, quantum). On TPU this shapes 2-D device meshes.
    """
    x = int(math.isqrt(n))
    while n % x != 0:
        x -= 1
    y = n // x
    return (max(x, y), min(x, y))


_TRANSFER_RESTRICTED: bool | None = None
_TRANSFER_PROBE_FAILS: int = 0


def transfer_restricted() -> bool:
    """True on accelerator backends that cannot TRANSFER complex arrays
    (the axon TPU tunnel raises UNIMPLEMENTED on any complex host<->device
    movement — while REPORTING platform 'tpu', so the restriction cannot
    be inferred from the platform string). Compiled complex COMPUTE is
    fine — XLA:TPU supports c64 natively — so the fix is to move complex
    data as stacked real planes and (re)combine inside compiled programs
    (:func:`asjnp` / :func:`tohost`).

    Detected EMPIRICALLY: one tiny complex round-trip on first use (the
    restriction raises immediately, it does not hang). Memoized — the
    backend is fixed at init and asjnp is hot. CPU short-circuits False.
    """
    global _TRANSFER_RESTRICTED
    if _TRANSFER_RESTRICTED is None:
        try:
            d = jax.devices()[0]
        except RuntimeError:
            return False  # backend not initialized yet: don't memoize
        if d.platform == "cpu":
            _TRANSFER_RESTRICTED = False
        else:
            try:
                z = jax.device_put(np.ones(2, dtype=np.complex64), d)
                np.asarray(z)  # the fetch direction must work too
                _TRANSFER_RESTRICTED = False
            except Exception as e:  # noqa: BLE001 — classified below
                # Memoize True for the restriction's own signature
                # (UNIMPLEMENTED / unsupported-type transfer errors). A
                # transient failure (momentary OOM, a dropped connection)
                # must NOT permanently route complex transfers through
                # the stacked-real shim — but neither may it re-run a
                # possibly-slow failing probe on every hot asjnp() call,
                # so unrecognized wordings also memoize after a few
                # consecutive failures.
                global _TRANSFER_PROBE_FAILS
                msg = str(e).lower()
                _TRANSFER_PROBE_FAILS += 1
                if _TRANSFER_PROBE_FAILS >= 3 or any(
                    s in msg
                    for s in ("unimplemented", "not implemented", "unsupported")
                ):
                    _TRANSFER_RESTRICTED = True
                return True
            _TRANSFER_PROBE_FAILS = 0
    return _TRANSFER_RESTRICTED


@jax.jit
def _combine_stacked(s):
    """[2, ...] real -> complex, on device (compiled, never a transfer)."""
    return jax.lax.complex(s[0], s[1])


@jax.jit
def _split_complex(z):
    """complex -> [2, ...] real, on device (compiled, never a transfer)."""
    return jnp.stack([jnp.real(z), jnp.imag(z)])


def asjnp(a, dtype=None):
    """Convert to a jax array, passing device arrays through untouched.

    Complex HOST data bound for a transfer-restricted backend (see
    :func:`transfer_restricted`) is moved as two stacked real planes and
    recombined in a compiled program — the generalized form of the
    quantum example's stacked-real evolution (VERDICT r3 #5), making
    c64 SpMV/solves work through the public API on such backends.
    """
    if (
        not isinstance(a, jax.Array)
        and np.iscomplexobj(np.asarray(a) if not hasattr(a, "dtype") else a)
        and transfer_restricted()
    ):
        ah = np.asarray(a)
        if dtype is not None and not np.issubdtype(
            np.dtype(dtype), np.complexfloating
        ):
            # explicit REAL dtype requested: cast on the host (same
            # imag-dropping semantics as the unrestricted astype path)
            # and transfer real — no stacked shim needed
            ah = ah.astype(dtype)
            return jnp.asarray(ah)
        ct = np.dtype(dtype) if dtype is not None else (
            np.dtype(np.complex128)
            if jax.config.jax_enable_x64 and ah.dtype == np.complex128
            else np.dtype(np.complex64)
        )
        rt = np.float64 if ct == np.complex128 else np.float32
        stacked = jnp.asarray(
            np.stack([ah.real, ah.imag]).astype(rt)
        )
        return _combine_stacked(stacked)
    out = jnp.asarray(a)
    if dtype is not None and out.dtype != np.dtype(dtype):
        out = out.astype(dtype)
    return out


def tohost(x) -> np.ndarray:
    """Fetch a device array to host numpy; complex arrays on a
    transfer-restricted backend come back as compiled real/imag planes
    (the inverse of :func:`asjnp`'s stacked-real inbound path)."""
    if isinstance(x, jax.Array) and jnp.iscomplexobj(x) and transfer_restricted():
        s = np.asarray(_split_complex(x))
        return s[0] + 1j * s[1]
    return np.asarray(x)


def host_int(x) -> int:
    """Materialize a device scalar on the host (an explicit blocking point).

    Reference analog: reading a Legion future, e.g. ``int.from_bytes`` of the nnz
    future at ``sparse/io.py:45-47`` / ``sparse/base.py:47-48``. Every dynamic-nnz
    site goes through here so the control/device sync boundaries stay auditable —
    and countable: telemetry tallies each fetch under ``host_sync.int``, making
    the sync budget of a workload visible in ``telemetry.summary()``.
    """
    from .config import settings

    if settings.telemetry:
        from . import telemetry

        telemetry.count("host_sync.int")
    return int(x)


def enable_compilation_cache(path: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache (idempotent, best-effort).

    Remote-tunnel TPU backends pay 20-40 s per fresh XLA/Mosaic compile;
    the persistent cache makes every repeat run (bench worker subprocesses,
    example reruns, successive AMG/GMG levels across processes) hit disk
    instead. Default location: ``.jax_cache`` next to the repo root
    (gitignored). The reference relies on Legion's in-process task caching;
    cross-process compile reuse is the TPU analog.
    """
    import os

    import jax

    if path is None:
        path = os.environ.get(
            "SPARSE_TPU_COMPCACHE",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        )
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # unknown flags on exotic jax versions
        user_warning(f"compilation cache unavailable: {e}")


def in_trace() -> bool:
    """True when called under an active jax trace (jit/scan/vmap body).

    Inside a trace, ops on even CONCRETE arrays return tracers, so code
    that needs a host sync (layout detection, shape materialization)
    must skip rather than raise TracerArrayConversionError. MUST NOT
    execute a device op itself: it is called eagerly on hot paths, and
    experimental backends (the axon tunnel) reject some tiny eager ops.
    """
    import jax

    try:
        from jax._src.core import trace_state_clean

        return not trace_state_clean()
    except ImportError:  # future jax: fall back to a CPU-pinned sentinel
        import jax.numpy as jnp

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            return isinstance(jnp.zeros((), jnp.int32) + 0, jax.core.Tracer)


def host_scope():
    """Context manager: run eager array work on the CPU backend.

    Layout detection and other one-time eager analyses must not be
    dispatched op-by-op through a remote accelerator backend (the axon
    tunnel crashes its worker on large eager slices). Under this scope
    UNCOMMITTED arrays (host-built constructions) compute on the local
    CPU; arrays already committed to an accelerator keep their device,
    so no silent device->host bulk transfers are introduced.
    """
    import contextlib

    import jax

    try:
        return jax.default_device(jax.local_devices(backend="cpu")[0])
    except RuntimeError:  # no cpu backend (never expected, but degrade)
        return contextlib.nullcontext()


def commit_to_exec_device(arrs):
    """Commit a tuple of arrays to the ACTIVE execution device.

    Layout caches (DIA planes, ELL index/data planes) are built under
    :func:`host_scope`; if the hot path then passes them as jit
    ARGUMENTS on an accelerator, every call re-ships them through the
    device link (~720 MB per matvec at 6000^2 over the tunnel). The
    active device is the current ``jax.default_device`` scope if set
    (so CPU-scoped build phases keep their arrays local), else the
    backend's first device. On a CPU target this is a no-op; so is
    re-committing already-resident arrays.
    """
    import jax

    target = jax.config.jax_default_device or jax.devices()[0]
    if getattr(target, "platform", "cpu") == "cpu":
        return arrs
    return tuple(jax.device_put(a, target) for a in arrs)
