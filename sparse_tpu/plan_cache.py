"""Library-wide two-tier operator plan cache: prepare once, execute everywhere.

Reference analog: legate.sparse caches partitions and images per Store
(``set_key_partition``, SURVEY §1) so a solve derives its layout once and
every subsequent task launch reuses it. The TPU reproduction's "layouts"
are packed operators (SELL slabs, prepared DIA planes) and compiled
shard_map programs; this module is the one place they live, so
``csr.dot``, ``LinearOperator`` and every solver in ``linalg`` reuse the
same plan across a whole solve instead of re-deriving it per matvec.

Two tiers (ISSUE 9): the in-process weak-ref LRU below is tier 1; when
``SPARSE_TPU_VAULT`` points at a directory, :mod:`sparse_tpu.vault` is
tier 2 — a crash-safe on-disk store of serialized prepared artifacts
keyed by CONTENT fingerprints. A lookup that misses in-process consults
the disk tier before building (``disk_hits`` in :func:`stats`); a build
deposits its artifact back so the NEXT process skips the pack. Disk
reads are verify-then-load with quarantine on any corruption — a bad
artifact degrades to a rebuild, never an error (docs/performance.md,
docs/resilience.md).

Design:

* **Weak-ref keyed.** Entries are keyed by the operator *object* (a
  ``csr_array``, a ``DistCSR``, ...) and die with it — a
  ``weakref.finalize`` evicts all of an object's plans when it is
  collected, so mutation-by-replacement (``_with_data``, fresh
  constructions) invalidates for free and the cache can never resurrect
  a stale layout. Objects that don't support weak references are never
  cached (every lookup builds).
* **Bounded.** LRU over ``settings.plan_cache_capacity`` (object, kind)
  entries; eviction is counted.
* **Observable.** Hit/miss/evict counters are always maintained and
  surfaced via :func:`stats`; they live on the always-on metrics
  registry (``telemetry/_metrics.py`` — ``plan_cache.hits`` /
  ``plan_cache.misses`` / ``plan_cache.evictions`` counters plus a lazy
  ``plan_cache.size`` gauge, all visible in
  ``telemetry.metrics_text()``). With telemetry enabled they also
  mirror into ``telemetry.summary()["counts"]`` under
  ``plan_cache.hit`` / ``plan_cache.miss`` / ``plan_cache.evict``
  (docs/telemetry.md).
* **Switchable.** ``SPARSE_TPU_PLAN_CACHE=0`` (``settings.plan_cache``)
  disables caching entirely: every lookup misses and builds, correctness
  unchanged — the parity suite runs both ways.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

from .config import settings
from .telemetry import _metrics

_LOCK = threading.RLock()
# (id(obj), kind) -> (weakref | None, plan); OrderedDict for LRU order
_ENTRIES: OrderedDict = OrderedDict()
_FINALIZERS: dict[int, object] = {}  # id(obj) -> weakref.finalize handle
# the always-on counters live on the metrics registry (one metrics
# surface — telemetry.metrics_text() exposes them as
# sparse_tpu_plan_cache_{hits,misses,evictions}_total + a size gauge)
_COUNTERS = {
    "hits": _metrics.counter("plan_cache.hits"),
    "misses": _metrics.counter("plan_cache.misses"),
    "evictions": _metrics.counter("plan_cache.evictions"),
    # tier-2 hits: the in-process tier missed but the vault's verified
    # artifact load replaced the build ("miss" stays = "had to build")
    "disk_hits": _metrics.counter("plan_cache.disk_hits"),
}
_metrics.gauge("plan_cache.size", fn=lambda: len(_ENTRIES))
_TELEMETRY_NAMES = {"hits": "plan_cache.hit", "misses": "plan_cache.miss",
                    "evictions": "plan_cache.evict",
                    "disk_hits": "plan_cache.disk_hit"}


def _count(which: str) -> None:
    _COUNTERS[which].inc()
    if settings.telemetry:
        from . import telemetry

        # counters are the cheap aggregate channel; one event per lookup
        # would flood the ring on hot paths
        telemetry.count(_TELEMETRY_NAMES[which])


def _evict_object(oid: int) -> None:
    """Drop every plan of a collected (or invalidated) object. Runs from
    ``weakref.finalize`` at GC time, so it must tolerate entries already
    gone (a concurrent ``clear()``/eviction) rather than ever raise.

    The RLock does NOT protect against re-entrancy here: an allocation
    inside this function can trigger GC, which can run ANOTHER object's
    finalizer on the same thread (the lock re-enters) and mutate
    ``_ENTRIES`` under our iteration — so the scan retries on the
    resulting KeyError/RuntimeError instead of leaking it into the
    interpreter's unraisable hook."""
    with _LOCK:
        for _ in range(4):
            try:
                dead = [k for k in _ENTRIES if k[0] == oid]
                break
            except (KeyError, RuntimeError):  # re-entrant finalizer race
                continue
        else:
            dead = []  # give up cleanly; the LRU cap bounds orphans
        for k in dead:
            if _ENTRIES.pop(k, None) is not None:
                _count("evictions")
        _FINALIZERS.pop(oid, None)


def get(obj, kind: str, build=None, *, vault_kind: str | None = None,
        vault_key=None, expect: dict | None = None):
    """Return the cached plan for ``(obj, kind)``, building on miss.

    ``build`` is a zero-arg callable producing the plan; with
    ``build=None`` a miss returns ``None`` (the trace-safe lookup form —
    in-trace callers may not build, packing needs host syncs, and the
    disk tier is never consulted). Lookups count exactly one of
    hit / disk_hit / miss each ("miss" always means "built"). With the
    cache disabled every call counts a miss and builds (when it can) —
    both tiers off, correctness unchanged.

    ``vault_kind``/``vault_key`` opt a build site into the persistent
    tier (:mod:`sparse_tpu.vault`): ``vault_key`` is the artifact's
    content fingerprint — a string, or a zero-arg callable evaluated
    only when the vault is enabled (fingerprinting hashes the operator's
    buffers; sites must not pay that when there is no disk tier).
    ``expect`` adds load-time meta assertions (e.g. dtype) on top of the
    store's own verify ladder. An in-process miss then tries a verified
    disk load before building; a build deposits its artifact back.
    Disk-tier failures of any kind degrade to the build path.
    """
    key = (id(obj), kind)
    if settings.plan_cache:
        with _LOCK:
            ent = _ENTRIES.get(key)
            if ent is not None and (ent[0] is None or ent[0]() is obj):
                _ENTRIES.move_to_end(key)
                _count("hits")
                return ent[1]
    plan = None
    vk = None
    use_vault = (
        build is not None and vault_kind is not None and settings.plan_cache
        and settings.vault
    )
    if use_vault:
        from . import vault

        try:
            vk = vault_key() if callable(vault_key) else vault_key
        except Exception:
            vk = None  # unfingerprintable content: tier 1 + build only
        if vk:
            plan = vault.fetch(vault_kind, vk, expect=expect)
    if plan is not None:
        _count("disk_hits")
    else:
        _count("misses")
        if build is None:
            return None
        plan = build()
        if use_vault and vk and plan is not None:
            from . import vault

            vault.deposit(vault_kind, vk, plan)
    if not settings.plan_cache or plan is None:
        return plan
    try:
        ref = weakref.ref(obj)
    except TypeError:
        return plan  # un-weakref-able key: never cached (id reuse unsafe)
    with _LOCK:
        _ENTRIES[key] = (ref, plan)
        _ENTRIES.move_to_end(key)
        oid = id(obj)
        if oid not in _FINALIZERS:
            _FINALIZERS[oid] = weakref.finalize(obj, _evict_object, oid)
        cap = max(int(settings.plan_cache_capacity), 1)
        while len(_ENTRIES) > cap:
            old_key, _ = _ENTRIES.popitem(last=False)
            _count("evictions")
    return plan


def lookup(obj, kind: str):
    """Trace-safe cached-plan lookup (never builds). See :func:`get`."""
    return get(obj, kind, None)


def put(obj, kind: str, plan) -> None:
    """Store/replace a plan directly (no hit/miss accounting).
    Silently a no-op when caching is off or ``obj`` is un-weakref-able."""
    if not settings.plan_cache:
        return
    try:
        ref = weakref.ref(obj)
    except TypeError:
        return
    with _LOCK:
        _ENTRIES[(id(obj), kind)] = (ref, plan)
        _ENTRIES.move_to_end((id(obj), kind))
        oid = id(obj)
        if oid not in _FINALIZERS:
            _FINALIZERS[oid] = weakref.finalize(obj, _evict_object, oid)


def invalidate(obj, kind: str | None = None) -> None:
    """Drop an object's cached plans (one kind, or all of them)."""
    with _LOCK:
        if kind is None:
            _evict_object(id(obj))
            return
        if _ENTRIES.pop((id(obj), kind), None) is not None:
            _count("evictions")


def stats() -> dict:
    """Always-on counters: ``{hits, misses, disk_hits, evictions, size,
    hit_rate, compile_s}`` (read back from the metrics registry — same
    numbers a Prometheus scrape of ``telemetry.metrics_text()`` sees).
    ``disk_hits`` counts persistent-tier loads that replaced a build
    (``misses`` always means "built"); ``hit_rate`` counts both tiers'
    hits. ``compile_s`` is the session's cold-start budget: total
    wall-clock seconds spent building/compiling attributed programs
    (telemetry/_cost.py), so bench session records carry the compile
    tax next to the hit rate it bought."""
    with _LOCK:
        out = {k: int(c.value) for k, c in _COUNTERS.items()}
        out["size"] = len(_ENTRIES)
    total = out["hits"] + out["disk_hits"] + out["misses"]
    out["hit_rate"] = (
        (out["hits"] + out["disk_hits"]) / total if total else 0.0
    )
    from .telemetry import _cost

    out["compile_s"] = round(_cost.total_compile_s(), 6)
    return out


def snapshot() -> dict:
    """Copy of the raw always-on counters, for delta accounting without a
    global reset (bench rows, ``batch.SolveSession`` dispatch telemetry —
    concurrent users must not clobber each other's baselines)."""
    with _LOCK:
        return {k: int(c.value) for k, c in _COUNTERS.items()}


def delta(since: dict) -> dict:
    """Counter movement since a :func:`snapshot`:
    ``{hits, misses, evictions, disk_hits}``."""
    with _LOCK:
        return {k: int(_COUNTERS[k].value) - since.get(k, 0)
                for k in ("hits", "misses", "evictions", "disk_hits")}


def reset_stats() -> None:
    with _LOCK:
        for c in _COUNTERS.values():
            c.reset()


def clear() -> None:
    """Drop every entry (counters untouched; evictions not counted —
    this is a test/debug reset, not cache pressure)."""
    with _LOCK:
        _ENTRIES.clear()
        for f in _FINALIZERS.values():
            try:
                f.detach()
            except Exception:
                pass
        _FINALIZERS.clear()
