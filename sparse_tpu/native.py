"""Loader for the native runtime library (``sparse_tpu/src/sparse_tpu_native.cc``).

Reference analog: ``sparse/config.py:21-58`` (``LegateSparseLib`` loading
``liblegate_sparse.so`` and exposing its C ABI through CFFI). Here the native
surface is small — host-side work outside the XLA compute path (bitset BFS
expansion, MatrixMarket tokenizing) — and is bound with ctypes. The library
is compiled on first use with g++ -O3 into the package directory; every
caller must handle ``lib() is None`` (pure-numpy fallback), so missing
toolchains degrade gracefully.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
# source ships as package data so pip-installed copies can rebuild the
# native library for the local toolchain
_SRC = os.path.join(_PKG_DIR, "src", "sparse_tpu_native.cc")
_SO = os.path.join(_PKG_DIR, "_sparse_tpu_native.so")


def _build() -> str | None:
    if not os.path.exists(_SRC):
        return None
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None


def lib():
    """The loaded CDLL, or None when no native library is available."""
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        path = os.environ.get("SPARSE_TPU_NATIVE_LIB") or _build()
        if path and os.path.exists(path):
            try:
                cdll = ctypes.CDLL(path)
                _declare(cdll)
                _lib = cdll
            except (OSError, AttributeError):
                # AttributeError: an older library (e.g. via
                # SPARSE_TPU_NATIVE_LIB) missing newer symbols — keep the
                # documented None fallback instead of crashing callers
                _lib = None
        _tried = True
    return _lib


def _declare(cdll) -> None:
    i64 = ctypes.c_int64
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    cdll.ind_sets_count.restype = i64
    cdll.ind_sets_count.argtypes = [u64p, i64, i64]
    cdll.ind_sets_expand.restype = None
    cdll.ind_sets_expand.argtypes = [u64p, u64p, u64p, i64, i64, i64, u64p, u64p]
    cdll.mtx_parse_body.restype = i64
    cdll.mtx_parse_body.argtypes = [
        ctypes.c_char_p, i64, i64, ctypes.c_int32, i64p, i64p, f64p, f64p,
    ]
    cdll.mtx_parse_dense.restype = i64
    cdll.mtx_parse_dense.argtypes = [ctypes.c_char_p, i64, i64, f64p]
    cdll.spgemm_count.restype = i64
    cdll.spgemm_count.argtypes = [i64, i64, i64p, i64p, i64p, i64p, i64p]
    cdll.spgemm_fill.restype = None
    cdll.spgemm_fill.argtypes = [
        i64, i64, i64p, i64p, f64p, i64p, i64p, f64p, i64p, i64p, f64p,
    ]
    cdll.ilu0_csr.restype = i64
    cdll.ilu0_csr.argtypes = [i64, i64p, i64p, f64p]
    cdll.ic0_csr.restype = i64
    cdll.ic0_csr.argtypes = [i64, i64p, i64p, f64p]
    cdll.splu_factor.restype = ctypes.c_void_p
    cdll.splu_factor.argtypes = [i64, i64p, i64p, f64p, i64p]
    cdll.ilut_factor.restype = ctypes.c_void_p
    cdll.ilut_factor.argtypes = [
        i64, i64p, i64p, f64p, ctypes.c_double, i64, i64p,
    ]
    cdll.splu_lnnz.restype = i64
    cdll.splu_lnnz.argtypes = [ctypes.c_void_p]
    cdll.splu_unnz.restype = i64
    cdll.splu_unnz.argtypes = [ctypes.c_void_p]
    cdll.splu_get.restype = None
    cdll.splu_get.argtypes = [
        ctypes.c_void_p, i64p, i64p, f64p, i64p, i64p, f64p, i64p,
    ]
    cdll.splu_free.restype = None
    cdll.splu_free.argtypes = [ctypes.c_void_p]


def _as_u64p(a):
    import numpy as np

    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def expand_level(sets, queues, comp_gt, n):
    """Native BFS level expansion; raises if the library is unavailable."""
    import numpy as np

    L = lib()
    if L is None:
        raise RuntimeError("native library unavailable")
    S, W = queues.shape
    sets = np.ascontiguousarray(sets)
    queues = np.ascontiguousarray(queues)
    comp_gt = np.ascontiguousarray(comp_gt)
    count = L.ind_sets_count(_as_u64p(queues), S, W)
    new_sets = np.empty((count, W), dtype=np.uint64)
    new_queues = np.empty((count, W), dtype=np.uint64)
    L.ind_sets_expand(
        _as_u64p(sets), _as_u64p(queues), _as_u64p(comp_gt),
        S, W, n, _as_u64p(new_sets), _as_u64p(new_queues),
    )
    return new_sets, new_queues


def parse_mtx_body(body: bytes, nnz: int, kind: int):
    """Native coordinate-body parse -> (rows, cols, re, im) or None.

    Parses with room for one extra entry so a body that declares nnz entries
    but holds more is rejected (matching the numpy fallback) instead of
    silently truncated.
    """
    import numpy as np

    L = lib()
    if L is None:
        return None
    cap = nnz + 1
    rows = np.empty(cap, dtype=np.int64)
    cols = np.empty(cap, dtype=np.int64)
    re = np.empty(cap, dtype=np.float64)
    im = np.zeros(cap, dtype=np.float64)
    got = L.mtx_parse_body(
        body, len(body), cap, kind,
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        re.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        im.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if got != nnz:
        return None  # wrong entry count: caller raises the clear error
    return rows[:nnz], cols[:nnz], re[:nnz], im[:nnz]


def parse_mtx_dense(body: bytes, count: int):
    import numpy as np

    L = lib()
    if L is None:
        return None
    out = np.empty(count, dtype=np.float64)
    got = L.mtx_parse_dense(
        body, len(body), count,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if got != count:
        return None
    return out


def _as_i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _as_f64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def spgemm_host(Ap, Aj, Ax, Bp, Bj, Bx, m: int, n: int):
    """Native 2-pass Gustavson C = A @ B on host arrays (the reference's
    CPU SpGEMM task pair, src/sparse/array/csr/spgemm_csr_csr_csr.cc).

    Inputs are numpy-coercible CSR parts; values are computed in f64 and
    the caller casts back. Returns (indptr, indices, data) as numpy
    int64/int64/float64, canonical (sorted, deduplicated) — or None when
    the native library is unavailable.
    """
    import numpy as np

    L = lib()
    if L is None:
        return None
    Ap = np.ascontiguousarray(Ap, dtype=np.int64)
    Aj = np.ascontiguousarray(Aj, dtype=np.int64)
    Ax = np.ascontiguousarray(Ax, dtype=np.float64)
    Bp = np.ascontiguousarray(Bp, dtype=np.int64)
    Bj = np.ascontiguousarray(Bj, dtype=np.int64)
    Bx = np.ascontiguousarray(Bx, dtype=np.float64)
    Cp = np.empty(m + 1, dtype=np.int64)
    nnz = L.spgemm_count(m, n, _as_i64p(Ap), _as_i64p(Aj),
                         _as_i64p(Bp), _as_i64p(Bj), _as_i64p(Cp))
    Cj = np.empty(nnz, dtype=np.int64)
    Cx = np.empty(nnz, dtype=np.float64)
    L.spgemm_fill(m, n, _as_i64p(Ap), _as_i64p(Aj), _as_f64p(Ax),
                  _as_i64p(Bp), _as_i64p(Bj), _as_f64p(Bx),
                  _as_i64p(Cp), _as_i64p(Cj), _as_f64p(Cx))
    return Cp, Cj, Cx


def ilu0_host(indptr, indices, data, n: int):
    """In-place-style ILU(0) on canonical CSR host arrays (f64).

    Returns the factored data array (L strict-lower with implicit unit
    diagonal + U upper, on A's pattern), falling back to a pure-numpy
    row loop when the native library is unavailable. Raises
    ``RuntimeError`` on a missing structural diagonal or zero pivot.
    """
    import numpy as np

    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    out = np.array(data, dtype=np.float64, copy=True)
    L = lib()
    if L is not None:
        rc = L.ilu0_csr(n, _as_i64p(indptr), _as_i64p(indices), _as_f64p(out))
        if rc != 0:
            raise RuntimeError(
                f"ILU(0): zero/missing pivot at row {-rc - 1}"
            )
        return out
    # numpy fallback: same IKJ recurrence, python row loop (setup-phase
    # only; fine to ~1e5 rows — the native path covers the big cases)
    diag = np.full(n, -1, dtype=np.int64)
    for i in range(n):
        seg = indices[indptr[i]:indptr[i + 1]]
        d = np.nonzero(seg == i)[0]
        if d.size == 0:
            raise RuntimeError(f"ILU(0): zero/missing pivot at row {i}")
        diag[i] = indptr[i] + d[0]
    pos = np.full(n, -1, dtype=np.int64)
    for i in range(n):
        p0, p1 = indptr[i], indptr[i + 1]
        pos[indices[p0:p1]] = np.arange(p0, p1)
        for p in range(p0, p1):
            k = indices[p]
            if k >= i:
                break
            ukk = out[diag[k]]
            if ukk == 0.0:
                raise RuntimeError(f"ILU(0): zero/missing pivot at row {k}")
            lik = out[p] / ukk
            out[p] = lik
            q0, q1 = diag[k] + 1, indptr[k + 1]
            pj = pos[indices[q0:q1]]
            ok = pj >= 0
            out[pj[ok]] -= lik * out[q0:q1][ok]
        pos[indices[p0:p1]] = -1
        if out[diag[i]] == 0.0:
            raise RuntimeError(f"ILU(0): zero/missing pivot at row {i}")
    return out


def ic0_host(indptr, indices, data, n: int):
    """IC(0) on the lower-triangular CSR of an SPD matrix (diagonal last
    per row). Returns L's data with A ~= L @ L.T on the lower pattern;
    numpy fallback mirrors the native kernel. Raises ``RuntimeError`` on
    a non-positive pivot (not SPD enough for IC(0))."""
    import numpy as np

    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    out = np.array(data, dtype=np.float64, copy=True)
    L = lib()
    if L is not None:
        rc = L.ic0_csr(n, _as_i64p(indptr), _as_i64p(indices), _as_f64p(out))
        if rc != 0:
            raise RuntimeError(
                f"IC(0): non-positive/missing pivot at row {-rc - 1}"
            )
        return out
    for i in range(n):
        p0, p1 = indptr[i], indptr[i + 1]
        if p1 <= p0 or indices[p1 - 1] != i:
            raise RuntimeError(f"IC(0): non-positive/missing pivot at row {i}")
        for p in range(p0, p1):
            j = indices[p]
            a, b = p0, indptr[j]
            b1 = indptr[j + 1] - 1
            s = 0.0
            while a < p and b < b1:
                ca, cb = indices[a], indices[b]
                if ca == cb:
                    s += out[a] * out[b]
                    a += 1
                    b += 1
                elif ca < cb:
                    a += 1
                else:
                    b += 1
            if j < i:
                ljj = out[indptr[j + 1] - 1]
                if ljj == 0.0:
                    raise RuntimeError(
                        f"IC(0): non-positive/missing pivot at row {j}"
                    )
                out[p] = (out[p] - s) / ljj
            else:
                v = out[p] - s
                if v <= 0.0:
                    raise RuntimeError(
                        f"IC(0): non-positive/missing pivot at row {i}"
                    )
                out[p] = v ** 0.5
    return out


def _lu_extract(L, h, n: int):
    """Copy a factor handle's CSC parts out and free it."""
    import numpy as np

    try:
        lnnz = L.splu_lnnz(h)
        unnz = L.splu_unnz(h)
        Lp = np.empty(n + 1, dtype=np.int64)
        Li = np.empty(max(lnnz, 1), dtype=np.int64)
        Lx = np.empty(max(lnnz, 1), dtype=np.float64)
        Up = np.empty(n + 1, dtype=np.int64)
        Ui = np.empty(max(unnz, 1), dtype=np.int64)
        Ux = np.empty(max(unnz, 1), dtype=np.float64)
        perm = np.empty(n, dtype=np.int64)
        L.splu_get(h, _as_i64p(Lp), _as_i64p(Li), _as_f64p(Lx),
                   _as_i64p(Up), _as_i64p(Ui), _as_f64p(Ux), _as_i64p(perm))
    finally:
        L.splu_free(h)
    return Lp, Li[:lnnz], Lx[:lnnz], Up, Ui[:unnz], Ux[:unnz], perm


def ilut_host(indptr, indices, data, n: int, droptol: float, lfil: int):
    """ILUT(p, tau) on host CSC arrays via the Gilbert-Peierls core: drop
    |entry| < droptol * ||A(:,j)||_2 (pivot kept), keep the ``lfil``
    largest per column in each of L and off-diagonal U (0 = unlimited).
    Same return contract as :func:`splu_host`; ``None`` without the
    native library. Reference analog: scipy's SuperLU ILUT behind
    ``spilu(drop_tol, fill_factor)``.
    """
    import numpy as np

    L = lib()
    if L is None:
        return None
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    data = np.ascontiguousarray(data, dtype=np.float64)
    info = np.zeros(1, dtype=np.int64)
    h = L.ilut_factor(n, _as_i64p(indptr), _as_i64p(indices),
                      _as_f64p(data), float(droptol), int(lfil),
                      _as_i64p(info))
    if not h:
        raise RuntimeError(
            f"ilut: matrix is singular (column {-int(info[0]) - 1})"
        )
    return _lu_extract(L, h, n)


def splu_host(indptr, indices, data, n: int):
    """Sparse LU with partial pivoting on host CSC arrays: P A = L U.

    Gilbert-Peierls left-looking factorization (native C++; reference
    analog: the vendor/scipy factorizations behind the reference's direct
    solves). Inputs are the CSC parts of a square A; values factor in
    f64. Returns ``(Lp, Li, Lx, Up, Ui, Ux, perm)`` — L unit-lower
    (implicit diagonal) and U upper, both CSC over pivot row ids, with
    ``perm[k]`` the original row chosen as pivot k — or ``None`` when the
    native library is unavailable (callers keep their dense path).
    Raises ``RuntimeError`` on a singular column.
    """
    import numpy as np

    L = lib()
    if L is None:
        return None
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    data = np.ascontiguousarray(data, dtype=np.float64)
    info = np.zeros(1, dtype=np.int64)
    h = L.splu_factor(n, _as_i64p(indptr), _as_i64p(indices),
                      _as_f64p(data), _as_i64p(info))
    if not h:
        raise RuntimeError(
            f"splu: matrix is singular (column {-int(info[0]) - 1})"
        )
    return _lu_extract(L, h, n)
