"""Elementwise CSR ops: add (union), multiply (intersection), dense multiply,
diagonal extraction.

Reference analog: ADD_CSR_CSR{_NNZ,} (``src/sparse/array/csr/add.*``, 2-pass
row-merge union), ELEM_MULT_CSR_CSR{_NNZ,} / ELEM_MULT_CSR_DENSE
(``csr/mult.*``), CSR_DIAGONAL (``csr/get_diagonal.*``) — SURVEY §2b. The
2-pass count+fill becomes: device-side sort/search, one host sync for the
result nnz, fixed-shape fill.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..types import index_dtype_for
from .coords import (
    dedup_sorted,
    expand_rows,
    lexsort_rc,
    rows_to_indptr,
    segment_searchsorted,
)


def _union_merge(indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape):
    """Shared union prologue: concat COO triples of both operands and
    lex-sort. Index width follows the DIMENSIONS (int64 only when a dim
    exceeds int32 — matching lexsort_rc's contract)."""
    from .coords import require_x64_index

    # require_x64_index raises loudly when a dim needs int64 but x64 is
    # off (astype(int64) would silently wrap to int32 otherwise)
    cdt = (
        jnp.int64
        if require_x64_index(max(int(shape[0]), int(shape[1])))
        else jnp.int32
    )
    rows_a = expand_rows(indptr_a, data_a.shape[0])
    rows_b = expand_rows(indptr_b, data_b.shape[0])
    rows = jnp.concatenate([rows_a.astype(cdt), rows_b.astype(cdt)])
    cols = jnp.concatenate([indices_a.astype(cdt), indices_b.astype(cdt)])
    dt = jnp.result_type(data_a.dtype, data_b.dtype)
    vals = jnp.concatenate([data_a.astype(dt), data_b.astype(dt)])
    order = lexsort_rc(rows, cols, shape)
    return rows[order], cols[order], vals[order], dt


def csr_add_csr(indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape):
    """Union add: concatenate COO triples, lex sort, collapse duplicates."""
    m = int(shape[0])
    srows, scols, svals, _ = _union_merge(
        indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape
    )
    urows, ucols, uvals, nunique = dedup_sorted(srows, scols, svals)
    idt = index_dtype_for(shape, nunique)
    indptr = rows_to_indptr(urows, m, dtype=idt)
    return indptr, ucols.astype(idt), uvals


def csr_mult_csr(indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape):
    """Intersection multiply: search each A-nnz's column inside B's own row.

    Per-row bounded binary search (``segment_searchsorted``) on B's sorted
    column ids — no fused (row, col) keys, so no index-width escalation for
    any shape whose dimensions fit int32.
    """
    from ..utils import host_int

    m = int(shape[0])
    rows_a = expand_rows(indptr_a, data_a.shape[0])
    nnz_b = data_b.shape[0]
    if nnz_b == 0 or data_a.shape[0] == 0:
        idt = index_dtype_for(shape, 0)
        return (
            jnp.zeros((m + 1,), dtype=idt),
            jnp.zeros((0,), dtype=idt),
            jnp.zeros((0,), dtype=jnp.result_type(data_a.dtype, data_b.dtype)),
        )
    starts = indptr_b[rows_a]
    ends = indptr_b[rows_a + 1]
    idx = segment_searchsorted(indices_b, starts, ends, indices_a)
    idx_c = jnp.clip(idx, 0, nnz_b - 1)
    match = (idx < ends) & (indices_b[idx_c] == indices_a)
    n_match = host_int(match.sum())
    take = jnp.nonzero(match, size=n_match)[0]
    dt = jnp.result_type(data_a.dtype, data_b.dtype)
    vals = data_a[take].astype(dt) * data_b[idx_c[take]].astype(dt)
    idt = index_dtype_for(shape, n_match)
    indptr = rows_to_indptr(rows_a[take], m, dtype=idt)
    return indptr, indices_a[take].astype(idt), vals


def csr_mult_dense(indptr, indices, data, dense, shape):
    """Structure-preserving multiply by a dense matrix (ELEM_MULT_CSR_DENSE)."""
    rows = expand_rows(indptr, data.shape[0])
    return data * dense[rows, indices]


def csr_diagonal(indptr, indices, data, shape, k: int = 0):
    """Extract the k-th diagonal. Reference task supports k=0 only
    (csr.py:636-639); we support any k by shifting the column target."""
    m, n = shape
    out_len = min(m + min(k, 0), n - max(k, 0))
    if out_len <= 0:
        return jnp.zeros((0,), dtype=data.dtype)
    nnz = data.shape[0]
    if nnz == 0:
        return jnp.zeros((out_len,), dtype=data.dtype)
    rows = expand_rows(indptr, nnz)
    on_diag = indices.astype(rows.dtype) == rows + k
    # Entry A[i, i+k] lands at diagonal slot i (== row) for k>=0, i+k (== col) for k<0.
    d_idx = rows if k >= 0 else indices.astype(rows.dtype)
    contrib = jnp.where(on_diag, data, jnp.zeros((), dtype=data.dtype))
    return jax.ops.segment_sum(contrib, d_idx, num_segments=max(m, n))[:out_len]


def csr_sum(indptr, indices, data, shape, axis=None):
    m, n = shape
    if axis is None:
        return data.sum()
    if axis in (0, -2):
        return jax.ops.segment_sum(data, indices, num_segments=n)
    if axis in (1, -1):
        rows = expand_rows(indptr, data.shape[0])
        return jax.ops.segment_sum(
            data, rows, num_segments=m, indices_are_sorted=True
        )
    raise ValueError(f"invalid axis {axis}")


def csr_minmax_csr(
    indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape, op
):
    """Elementwise maximum/minimum of two CSRs (scipy's binopt analog).

    ``op`` is jnp.maximum or jnp.minimum. Union merge like add; positions
    stored in only ONE operand compare against the other's implicit zero
    (max(v, 0) / min(v, 0)), positions in both take op(a, b). Explicit
    zeros in the result are dropped (canonical output).
    """
    import jax

    from ..utils import host_int

    m = int(shape[0])
    srows, scols, svals, dt = _union_merge(
        indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape
    )
    nnz = srows.shape[0]
    if nnz == 0:
        idt = index_dtype_for(shape, 0)
        return (
            jnp.zeros((m + 1,), dtype=idt),
            jnp.zeros((0,), dtype=idt),
            jnp.zeros((0,), dtype=dt),
        )
    is_new = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (srows[1:] != srows[:-1]) | (scols[1:] != scols[:-1]),
        ]
    )
    nunique = host_int(is_new.sum())
    seg = jnp.cumsum(is_new) - 1
    segop = jax.ops.segment_max if op is jnp.maximum else jax.ops.segment_min
    uvals = segop(svals, seg, num_segments=nunique)
    counts = jax.ops.segment_sum(jnp.ones_like(svals, dtype=jnp.int32), seg, num_segments=nunique)
    # singly-present entries compare against the other operand's implicit 0
    uvals = jnp.where(counts == 1, op(uvals, jnp.zeros((), dt)), uvals)
    first_idx = jnp.nonzero(is_new, size=nunique)[0]
    urows, ucols = srows[first_idx], scols[first_idx]
    # canonical output: drop exact zeros
    keep = uvals != 0
    nkeep = host_int(keep.sum())
    sel = jnp.nonzero(keep, size=nkeep)[0]
    idt = index_dtype_for(shape, nkeep)
    indptr = rows_to_indptr(urows[sel], m, dtype=idt)
    return indptr, ucols[sel].astype(idt), uvals[sel]
