"""CSR/CSC SpMV and SpMM kernels (single-device compute path).

Reference analog: the CSR_SPMV_ROW_SPLIT / CSR_SPMV_COL_SPLIT / CSC_SPMV_COL_SPLIT /
SPMM_* task families (``src/sparse/array/csr/spmv.*``, ``spmm.*`` — SURVEY §2b).
The cuSPARSE calls become pure-XLA gather/segment-reduce pipelines here, with a
padded-row (ELL) fast path that turns SpMV into gathers + dense reductions — the
shape TPUs like (no scatter in the hot loop). A Pallas kernel variant lives in
``sparse_tpu.kernels``; dispatch is by ``config.settings.spmv_mode``.

All functions are jit-safe: static shapes, no host syncs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .coords import expand_rows


def csr_spmv_segment(indptr, indices, data, x, m: int, acc_dtype=None):
    """y = A @ x via gather + sorted segment-sum. General path, any row profile.

    ``acc_dtype`` is the mixed-precision widening hook (ISSUE 15): with
    reduced-width values (bf16/f32 storage), products and the segment
    reduction accumulate at ``acc_dtype`` instead of the storage dtype —
    the converts fuse into the gather consumers, so HBM still moves
    half-width values while the arithmetic stays wide. ``None`` (the
    default) keeps the historic result-type behavior byte-identical."""
    nnz = data.shape[0]
    out_dt = acc_dtype or jnp.result_type(data.dtype, x.dtype)
    if nnz == 0:
        return jnp.zeros((m,), dtype=out_dt)
    rows = expand_rows(indptr, nnz)
    if acc_dtype is not None:
        prod = data.astype(out_dt) * x[indices].astype(out_dt)
    else:
        prod = data * x[indices]
    return jax.ops.segment_sum(prod, rows, num_segments=m, indices_are_sorted=True)


# Max ELL width unrolled into the trace; wider matrices take a fori_loop so
# the program size stays O(1) in the row degree.
ELL_UNROLL_MAX = 32


def csr_spmv_ell(ell_indices, ell_data, x):
    """y = A @ x on the padded-row (ELL) layout: k 1-D gathers + VPU adds.

    For banded/bounded-degree matrices (every reference benchmark: 5-pt/9-pt
    Laplacians, 11-diag SpMV microbench) this is pure gather + VPU reduce —
    no scatter, no segment ids. The k planes are processed as separate [m]
    gathers: a single [m, k] fancy-index gather acquires a trailing
    length-1 index dim that TPU tiles to (8, 128) — an ~128x padded s32
    buffer in HBM — while 1-D gathers lay out exactly. Small k is unrolled;
    large k runs the same plane-gather under lax.fori_loop.
    """
    k = ell_data.shape[1]
    if k <= ELL_UNROLL_MAX:
        acc = ell_data[:, 0] * x[ell_indices[:, 0]]
        for kk in range(1, k):
            acc = acc + ell_data[:, kk] * x[ell_indices[:, kk]]
        return acc
    idx_t, dat_t = ell_indices.T, ell_data.T  # [k, m]: plane-major slices

    def body(kk, acc):
        return acc + dat_t[kk] * x[idx_t[kk]]

    out_dt = jnp.result_type(ell_data.dtype, x.dtype)
    acc0 = jnp.zeros((ell_data.shape[0],), dtype=out_dt)
    return jax.lax.fori_loop(0, k, body, acc0)


def _sell_slab_spmv(idx_t, val_t, x, acc_dtype=None):
    """y_slab = A_slab @ x on one SELL slab: [K, R] plane-major index/value
    planes (rows of equal padded width K). Same gather-shaped op as
    :func:`csr_spmv_ell`, stored plane-major so each plane is a contiguous
    1-D gather; small K unrolls, large K runs under ``fori_loop``.

    ``acc_dtype`` widens every plane product before the accumulate
    (ISSUE 15): value planes stream at their storage width (bf16/f32),
    the per-row reduction runs at ``acc_dtype``. ``None`` = historic
    result-type accumulation, byte-identical."""
    K = idx_t.shape[0]
    out_dt = acc_dtype or jnp.result_type(val_t.dtype, x.dtype)
    if K == 0:
        return jnp.zeros((idx_t.shape[1],), dtype=out_dt)

    def plane(kk):
        if acc_dtype is not None:
            return val_t[kk].astype(out_dt) * x[idx_t[kk]].astype(out_dt)
        return val_t[kk] * x[idx_t[kk]]

    if K <= ELL_UNROLL_MAX:
        acc = plane(0)
        for kk in range(1, K):
            acc = acc + plane(kk)
        return acc.astype(out_dt)

    def body(kk, acc):
        return acc + plane(kk)

    acc0 = jnp.zeros((idx_t.shape[1],), dtype=out_dt)
    return jax.lax.fori_loop(0, K, body, acc0)


def csr_spmv_sell(slabs, pos, x, zero_rows: int, out_dtype=None,
                  acc_dtype=None):
    """y = A @ x on the SELL-C-sigma layout (see ``kernels.sell_spmv``).

    ``slabs`` is a static tuple of plane-major ``(idx_t, val_t)`` pairs
    ([K_s, R_s] each — rows degree-sorted within sigma-windows, chunked into
    C-row chunks padded to each chunk's max degree, chunks grouped by padded
    width); ``pos`` maps original row -> position in the concatenated packed
    output; ``zero_rows`` is the trailing all-empty-row block. Every step is
    a contiguous 1-D gather + VPU add — no scatter, no segment ids, and
    near-zero pad waste even under row-length skew (vs. ELL's global-max
    padding). The portable default for prepared general SpMV; the Pallas
    row-block variant lives in ``sparse_tpu.kernels.sell_spmv``.
    """
    x = jnp.asarray(x)  # numpy x would fail the fori-loop gather branch
    out_dt = out_dtype or acc_dtype or jnp.result_type(
        slabs[0][1].dtype if slabs else x.dtype, x.dtype
    )
    parts = [
        _sell_slab_spmv(it, vt, x, acc_dtype=acc_dtype).astype(out_dt)
        for it, vt in slabs
    ]
    if zero_rows:
        parts.append(jnp.zeros((zero_rows,), dtype=out_dt))
    if not parts:  # empty matrix: pos is empty too
        return jnp.zeros(pos.shape, dtype=out_dt)
    packed = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return packed[pos]


def csr_spmm_sell(slabs, pos, B, zero_rows: int, out_dtype=None):
    """C = A @ B (dense [n, nB]) on the SELL layout: per-slab row-gathers of
    B + fused accumulate, then one row-gather back to original order."""
    B = jnp.asarray(B)
    out_dt = out_dtype or jnp.result_type(
        slabs[0][1].dtype if slabs else B.dtype, B.dtype
    )
    nB = B.shape[1]

    def slab(it, vt):
        K = it.shape[0]
        if K == 0:
            return jnp.zeros((it.shape[1], nB), dtype=out_dt)
        if K <= ELL_UNROLL_MAX:
            acc = vt[0][:, None] * B[it[0]]
            for kk in range(1, K):
                acc = acc + vt[kk][:, None] * B[it[kk]]
            return acc.astype(out_dt)

        def body(kk, acc):
            return acc + vt[kk][:, None] * B[it[kk]]

        return jax.lax.fori_loop(
            0, K, body, jnp.zeros((it.shape[1], nB), dtype=out_dt)
        )

    parts = [slab(it, vt) for it, vt in slabs]
    if zero_rows:
        parts.append(jnp.zeros((zero_rows, nB), dtype=out_dt))
    if not parts:
        return jnp.zeros((pos.shape[0], nB), dtype=out_dt)
    packed = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return packed[pos]


def csr_spmv_sell_batched(idx_slabs, val_slabs, pos, X, zero_rows: int,
                          out_dtype=None, acc_dtype=None):
    """Y[b] = A_b @ X[b] on the SELL layout with one SHARED sparsity
    pattern: ``idx_slabs`` (and ``pos``/``zero_rows``) are pattern state
    packed once, ``val_slabs`` is a tuple of stacked ``[B, K, R]`` value
    planes — the vmap-compatible XLA path of the batched subsystem
    (``sparse_tpu.batch``). Every lane rides the same contiguous 1-D
    gathers as :func:`csr_spmv_sell`; XLA batches them for free.

    ``acc_dtype`` is the storage/accumulation split (ISSUE 15): value
    planes may be stored bf16/f32 while every plane product and the
    per-row reduction run at ``acc_dtype`` — the mixed-precision inner
    sweep's matvec."""
    X = jnp.asarray(X)

    def one(vts, x):
        return csr_spmv_sell(
            tuple(zip(idx_slabs, vts)), pos, x, zero_rows, out_dtype,
            acc_dtype=acc_dtype,
        )

    return jax.vmap(one)(tuple(val_slabs), X)


def csr_spmm_sell_batched(idx_slabs, val_slabs, pos, X, zero_rows: int,
                          out_dtype=None):
    """C[b] = A_b @ X[b] (dense ``[B, n, k]``) on the shared-pattern SELL
    layout — the batched counterpart of :func:`csr_spmm_sell`."""
    X = jnp.asarray(X)

    def one(vts, x):
        return csr_spmm_sell(
            tuple(zip(idx_slabs, vts)), pos, x, zero_rows, out_dtype
        )

    return jax.vmap(one)(tuple(val_slabs), X)


def csr_spmv_segment_batched(indptr, indices, values, X, m: int):
    """Y[b] = A_b @ X[b] via the general segment path, values ``[B, nnz]``
    over one shared pattern — the trace-safe fallback of the batched
    subsystem (no host-side pack required)."""
    return jax.vmap(
        lambda d, x: csr_spmv_segment(indptr, indices, d, x, m)
    )(values, jnp.asarray(X))


def csr_spmm_segment(indptr, indices, data, B, m: int):
    """C = A @ B with B dense [k, n]. Reference: SPMM_CSR_DENSE row-split."""
    nnz = data.shape[0]
    n = B.shape[1]
    out_dt = jnp.result_type(data.dtype, B.dtype)
    if nnz == 0:
        return jnp.zeros((m, n), dtype=out_dt)
    rows = expand_rows(indptr, nnz)
    prod = data[:, None] * B[indices]
    return jax.ops.segment_sum(prod, rows, num_segments=m, indices_are_sorted=True)


def csr_spmm_ell(ell_indices, ell_data, B):
    """C = A @ B on the ELL layout: k row-gathers of B + fused accumulate.
    Unrolled over small static ELL widths (same TPU-layout reason as
    csr_spmv_ell), fori_loop above ELL_UNROLL_MAX."""
    k = ell_data.shape[1]
    if k <= ELL_UNROLL_MAX:
        acc = ell_data[:, 0, None] * B[ell_indices[:, 0]]
        for kk in range(1, k):
            acc = acc + ell_data[:, kk, None] * B[ell_indices[:, kk]]
        return acc
    idx_t, dat_t = ell_indices.T, ell_data.T  # [k, m]

    def body(kk, acc):
        return acc + dat_t[kk][:, None] * B[idx_t[kk]]

    out_dt = jnp.result_type(ell_data.dtype, B.dtype)
    acc0 = jnp.zeros((ell_data.shape[0], B.shape[1]), dtype=out_dt)
    return jax.lax.fori_loop(0, k, body, acc0)


def csr_spmv_colsplit(indptr, indices, data, x, m: int, nblocks: int):
    """y = A @ x with the contraction (column) dimension split into
    ``nblocks`` equal domains, each reduced separately, then summed.

    Reference: CSR_SPMV_COL_SPLIT (``src/sparse/array/csr/spmv.cu:126-153``,
    driven by ``spmv_domain_part`` at csr.py:869-927) — the column-domain
    partition with ADD-reduction into y. On one chip the partials live as a
    [nblocks, m] plane reduced on-device; on the mesh the same structure is
    ``parallel.dist.DistCSRCol`` where the reduction is a psum_scatter.
    """
    nnz = data.shape[0]
    if nnz == 0:
        return jnp.zeros((m,), dtype=jnp.result_type(data.dtype, x.dtype))
    n = x.shape[0]
    idt = jnp.int32
    if max(n, m) * nblocks > np.iinfo(np.int32).max:
        # int32 would wrap in `indices * nblocks` / `block * m + rows` and
        # silently misroute segments (jnp truncates int64 under x32) — fail
        # loudly like ops.coords.require_x64_index.
        if not jax.config.jax_enable_x64:
            raise ValueError(
                f"column-split SpMV on shape ({m}, {n}) with {nblocks} "
                "blocks needs int64 segment keys; enable them with "
                "jax.config.update('jax_enable_x64', True)"
            )
        idt = jnp.int64
    rows = expand_rows(indptr, nnz)
    block = (indices.astype(idt) * nblocks) // max(n, 1)
    seg = block * m + rows.astype(idt)
    part = jax.ops.segment_sum(
        data * x[indices], seg, num_segments=nblocks * m
    )
    return part.reshape(nblocks, m).sum(axis=0)


def csc_spmv(indptr, indices, data, x, m: int):
    """y = A @ x with A in CSC: gather x by column-segments, scatter-add to rows.

    Reference: CSC_SPMV_COL_SPLIT (``src/sparse/array/csc/spmv.*``) — the
    reduction-accessor variant. Here: per-nnz products with the column id taken
    from the compressed axis, segment-summed by the (unsorted) row indices.
    """
    nnz = data.shape[0]
    n = indptr.shape[0] - 1
    if nnz == 0:
        return jnp.zeros((m,), dtype=jnp.result_type(data.dtype, x.dtype))
    cols = expand_rows(indptr, nnz)  # compressed axis of CSC = columns
    prod = data * x[cols]
    return jax.ops.segment_sum(prod, indices, num_segments=m)


def rspmm(indptr, indices, data, B, n: int):
    """C = B @ A with A CSR [m, n], B dense [p, m] (dense x sparse).

    Reference: SPMM_DENSE_CSR k-split with ADD reduction into a replicated C
    (csr.py:1209-1240). Here: C[:, col] += B[:, row] * val as a segment-sum of
    per-nnz [p]-vectors keyed by column id.
    """
    nnz = data.shape[0]
    p = B.shape[0]
    out_dt = jnp.result_type(data.dtype, B.dtype)
    if nnz == 0:
        return jnp.zeros((p, n), dtype=out_dt)
    rows = expand_rows(indptr, nnz)
    contrib = B.T[rows] * data[:, None]  # [nnz, p]
    out = jax.ops.segment_sum(contrib, indices, num_segments=n)  # [n, p]
    return out.T
