"""DIA (diagonal-format) SpMV — the zero-gather SpMV for banded matrices.

Every reference benchmark matrix is banded (5-pt/9-pt Laplacians, the
11-diagonal SpMV microbenchmark), and for banded matrices the diagonal
layout turns SpMV into pure shifted vector arithmetic:

    y[i] = sum_k data[k, i + o_k] * x[i + o_k]

i.e. one [D, n] elementwise multiply and D statically-shifted adds — no
index loads at all, halving HBM traffic vs any gather-based CSR/ELL kernel.
This is the TPU-native answer to the reference's cuSPARSE SpMV path
(``src/sparse/array/csr/spmv.cu``). A Pallas variant with explicit VMEM
windowing lives in ``sparse_tpu.kernels.dia_spmv``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("offsets", "shape", "acc_dtype"))
def dia_spmv_xla(data, offsets: tuple, x, shape: tuple, acc_dtype=None):
    """y = A @ x with A in DIA layout (scipy convention: data[k, j] holds
    A[j - o_k, j]). ``offsets`` is a static tuple, so every slice below is a
    static-shape op and the whole SpMV fuses into one XLA pass.

    ``acc_dtype`` is the storage/accumulation split (ISSUE 15): bf16/f32
    diagonal planes widen at the multiply so the shifted adds accumulate
    at ``acc_dtype`` while HBM moves the narrow planes. ``None`` (the
    default) keeps the historic result-type behavior byte-identical."""
    m, n = shape
    D = len(offsets)
    if acc_dtype is not None:
        prod = data.astype(acc_dtype) * x[None, :n].astype(acc_dtype)
    else:
        prod = data * x[None, :n]  # [D, n]
    B = max(max((abs(int(o)) for o in offsets), default=0), max(m - n, 0))
    padded = jnp.pad(prod, ((0, 0), (B, B + max(m - n, 0))))
    y = jnp.zeros((m,), dtype=prod.dtype)
    for k, o in enumerate(offsets):
        y = y + jax.lax.dynamic_slice_in_dim(padded[k], B + int(o), m)
    return y
