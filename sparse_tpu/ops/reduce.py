"""Whole-array and axis reductions with scipy.sparse semantics.

Implicit zeros participate: ``A.max()`` of a matrix whose stored values are
all negative is 0 whenever any position is unstored (scipy `_data.py`
`_min_or_max`), and ``argmax`` resolves to scipy's two-step rule (stored
extreme by numpy argmax — NaN wins — then the first zero position when
implicit zeros exist and the extreme is not strictly positive/negative).
Reference analog: the reference inherits these from scipy's surface and
implements none as tasks — host O(nnz) passes are the honest cost model,
and host numpy always has int64 for the flat-index arithmetic.
"""

from __future__ import annotations

import numpy as np


def _coo_parts(A):
    # raw COO may hold duplicate/unsorted triples; scipy canonicalizes
    # before every reduction (duplicates must SUM, and the stored-
    # position count must not double-count)
    coo = A._canonical_coo()
    return (
        np.asarray(coo.row),
        np.asarray(coo.col),
        np.asarray(coo.data),
    )


def min_or_max(A, op, axis=None, nan: bool = False):
    """``op`` is np.maximum or np.minimum. axis None -> scalar;
    axis 0/1 -> dense 1-D ndarray (deviation: scipy returns a sparse
    1-row matrix; documented in the method docstrings).

    ``nan=True`` ignores stored NaNs for the reduction, but they still
    count as STORED positions (a fully-stored line with NaNs has no
    implicit zero to clamp with — scipy nanmax([[-5, nan]]) == -5).
    """
    m, n = A.shape
    if m * n == 0:
        raise ValueError("zero-size array to reduction operation")
    rows, cols, vals = _coo_parts(A)
    dt = vals.dtype
    isnan = np.isnan(vals) if np.issubdtype(dt, np.floating) else np.zeros(vals.shape, bool)
    red_vals = vals[~isnan] if nan else vals
    if axis is None:
        has_implicit = vals.size < m * n
        if red_vals.size == 0:
            return dt.type(0) if has_implicit else dt.type(np.nan)
        stored = op.reduce(red_vals)
        if has_implicit:
            stored = op(stored, dt.type(0))
        return dt.type(stored)
    if axis not in (0, 1):
        raise ValueError(f"invalid axis {axis}")
    ids = rows if axis == 1 else cols
    length = m if axis == 1 else n
    other = n if axis == 1 else m
    # stored-position counts use PRE-NaN-drop ids; the value reduction
    # uses the post-drop set
    counts_stored = np.bincount(ids, minlength=length)
    red_ids = ids[~isnan] if nan else ids
    counts_red = np.bincount(red_ids, minlength=length)
    fill = -np.inf if op is np.maximum else np.inf
    seg = np.full(length, fill)
    if red_vals.size:
        op.at(seg, red_ids, red_vals)
    has_implicit = counts_stored < other
    out = np.where(counts_red > 0, seg, np.where(has_implicit, 0.0, np.nan))
    out = np.where(has_implicit, op(out, 0.0), out)
    return out.astype(dt)


def arg_min_or_max(A, op, axis=None):
    """np.argmax/np.argmin analog, scipy's exact two-step rule per line:

    1. extreme over STORED values by numpy argmax/argmin (NaN wins both;
       first occurrence among ties, row-major);
    2. when the line has implicit zeros and the stored extreme is not
       strictly positive (argmax) / strictly negative (argmin) — NaN
       counts as "not" — the answer is the FIRST ZERO position: the
       earlier of the first stored zero and the first unstored slot.
    Lines with no stored entries resolve to 0.
    """
    m, n = A.shape
    if m * n == 0:
        raise ValueError("cannot compute argmax/argmin of an empty matrix")
    rows, cols, vals = _coo_parts(A)
    is_max = op is np.maximum
    if axis is None:
        flats = rows.astype(np.int64) * n + cols.astype(np.int64)
        has_implicit = vals.size < m * n
        if vals.size == 0:
            return 0
        isnan = np.isnan(vals) if np.issubdtype(vals.dtype, np.floating) else np.zeros(vals.shape, bool)
        if isnan.any():
            v = np.nan
            p = int(flats[isnan].min())
        else:
            v = op.reduce(vals)
            p = int(flats[vals == v].min())
        positive = v > 0 if is_max else v < 0  # False for NaN
        if has_implicit and not positive:
            # NaN extreme: scipy falls back to the first IMPLICIT position
            # only; a zero extreme also competes with stored zeros (probed)
            cands = [_first_missing_flat(flats, m * n)]
            if not np.isnan(v):
                z = vals == 0
                if z.any():
                    cands.append(int(flats[z].min()))
            return min(cands)
        return p
    if axis not in (0, 1):
        raise ValueError(f"invalid axis {axis}")
    if axis == 0:  # reduce over rows: transpose the coordinate roles
        rows, cols = cols, rows
        length, other = n, m
    else:
        length, other = m, n
    out = np.zeros(length, dtype=np.int64)
    counts = np.bincount(rows, minlength=length) if vals.size else np.zeros(length, dtype=np.int64)
    stored_val = np.full(length, np.nan)
    stored_arg = np.zeros(length, dtype=np.int64)
    if vals.size:
        isnan = np.isnan(vals) if np.issubdtype(vals.dtype, np.floating) else np.zeros(vals.shape, bool)
        # NaN wins both argmax and argmin (numpy resolves to the FIRST NaN),
        # so it gets its OWN lexsort key — folding it into the value key as
        # np.inf would collide with stored infinities. The value key stays in
        # the native dtype: negation wraps unsigned dtypes / the signed
        # minimum, and a float64 cast loses int64 exactness past 2**53.
        keyv = np.where(isnan, vals.dtype.type(0), vals)
        if is_max:
            # ascending (line, isnan, val, -col): the LAST entry of each
            # line block is NaN if any, else the max val, smallest col tie
            order = np.lexsort((-cols, keyv, isnan, rows))
            r_s = rows[order]
            take = np.concatenate([r_s[1:] != r_s[:-1], [True]])
        else:
            # ascending (line, ~isnan, val, col): the FIRST entry of each
            # line block is NaN if any, else the min val, smallest col tie
            order = np.lexsort((cols, keyv, ~isnan, rows))
            r_s = rows[order]
            take = np.concatenate([[True], r_s[1:] != r_s[:-1]])
        c_s, v_s = cols[order], vals[order]
        stored_arg[r_s[take]] = c_s[take]
        stored_val[r_s[take]] = v_s[take]
    out[counts > 0] = stored_arg[counts > 0]
    positive = stored_val > 0 if is_max else stored_val < 0  # False for NaN/empty
    need_zero = (counts < other) & ~positive
    if need_zero.any():
        first_missing = _first_missing_per_line(rows, cols, length, other)
        zero_col = np.full(length, np.iinfo(np.int64).max)
        if vals.size:
            z = vals == 0
            if z.any():
                np.minimum.at(zero_col, rows[z], cols[z])
        # lines whose stored extreme is NaN ignore stored zeros (scipy)
        nan_extreme = np.isnan(stored_val) & (counts > 0)
        cand = np.where(
            nan_extreme, first_missing, np.minimum(first_missing, zero_col)
        )
        out[need_zero] = cand[need_zero]
    return out


def _first_missing_flat(flats, full: int) -> int:
    """Smallest flat index in [0, full) absent from ``flats``."""
    s = np.unique(flats)  # sorted, deduped
    k = min(s.size, full)
    head = np.nonzero(s[:k] != np.arange(k, dtype=np.int64))[0]
    # a perfect stored prefix 0..k-1 leaves k as the first gap (< full,
    # guaranteed by the caller's vals.size < m*n check)
    return int(head[0]) if head.size else int(k)


def _first_missing_per_line(rows, cols, length: int, other: int):
    """For each line id in [0, length): the smallest column not stored.
    Lines storing a full prefix 0..k-1 get k (== ``other`` when full)."""
    if rows.size == 0:
        return np.zeros(length, dtype=np.int64)
    order = np.lexsort((cols, rows))
    r_s, c_s = rows[order], cols[order]
    starts = np.searchsorted(r_s, np.arange(length))
    pos_in_line = np.arange(r_s.size, dtype=np.int64) - starts[r_s]
    in_prefix = c_s == pos_in_line
    bad = ~in_prefix
    first_bad = np.full(length, np.iinfo(np.int64).max)
    if bad.any():
        np.minimum.at(first_bad, r_s[bad], pos_in_line[bad])
    counts = np.bincount(r_s, minlength=length)
    prefix_len = np.minimum(first_bad, counts)
    return np.minimum(prefix_len, other)
