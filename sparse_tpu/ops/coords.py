"""Coordinate plumbing: pos/indptr <-> row-id expansion, sorting, dedup.

Reference analog: the EXPAND_POS_TO_COORDINATES / SORTED_COORDS_TO_COUNTS /
BOUNDS_FROM_PARTITIONED_COORDINATES task family (``src/sparse/array/conv/*``,
SURVEY §2b) and the rect1 zip/unzip helpers. On TPU there are no Rect<1> pos
arrays — ``indptr`` is a plain prefix-sum array — so this file is the whole
"coordinate plumbing" layer: fully vectorized, jit-friendly, static shapes.

Dynamic-nnz boundaries (sort dedup, unions) return host ints explicitly via
``utils.host_int`` — the TPU analog of reading a Legion future.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import host_int


def expand_rows(indptr, nnz: int):
    """Expand a CSR indptr into per-nnz row ids (CSR -> COO row coordinates).

    Reference: EXPAND_POS_TO_COORDINATES (``src/sparse/array/conv/pos_to_coordinates.cc``).
    Vectorized as a batched binary search over the sorted indptr — O(nnz log m),
    no scatter, maps cleanly onto the VPU.
    """
    if nnz == 0:
        return jnp.zeros((0,), dtype=indptr.dtype)
    pts = jnp.arange(nnz, dtype=indptr.dtype)
    return (jnp.searchsorted(indptr, pts, side="right") - 1).astype(indptr.dtype)


def counts_to_indptr(counts, dtype=None):
    """Row-counts -> indptr via exclusive scan (the nnz_to_pos cumsum of base.py:30-48)."""
    dtype = dtype or counts.dtype
    z = jnp.zeros((1,), dtype=dtype)
    return jnp.concatenate([z, jnp.cumsum(counts.astype(dtype))])


def rows_to_indptr(sorted_rows, m: int, dtype=None):
    """Sorted row ids -> indptr. Reference: SORTED_COORDS_TO_COUNTS reduction
    (``src/sparse/array/conv/sorted_coords_to_counts.cc``) + cumsum; here a single
    vectorized searchsorted over the sorted coords — no reduction tree needed."""
    dtype = dtype or (sorted_rows.dtype if sorted_rows.size else jnp.int32)
    targets = jnp.arange(m + 1, dtype=sorted_rows.dtype if sorted_rows.size else jnp.int32)
    return jnp.searchsorted(sorted_rows, targets, side="left").astype(dtype)


def require_x64_index(dim: int) -> bool:
    """True when a single coordinate dimension exceeds int32 range.

    Raises loudly when int64 indices are needed (e.g. ``kron`` output rows
    = ra*mb + rb past 2**31) but x64 is disabled — jnp silently truncates
    int64->int32 in that configuration, which would corrupt every sort.
    """
    if int(dim) <= np.iinfo(np.int32).max:
        return False
    if not jax.config.jax_enable_x64:
        raise ValueError(
            f"dimension {dim} needs int64 indices (> 2**31); "
            "enable them with jax.config.update('jax_enable_x64', True)"
        )
    return True


def lexsort_rc(primary, secondary, shape):
    """Stable order making (primary, secondary) lexicographically sorted.

    ``shape`` = (extent of primary, extent of secondary) — static bounds on
    the coordinate values. Fast path: one fused int32 key sort when the
    product fits int32 (one device sort). Big shapes: two stable int32
    argsorts (by secondary, then by primary) — the classical LSD radix
    composition. No int64, no x64 requirement, for any shape whose
    individual dimensions fit int32 (scipy's own practical bound).
    """
    p, s = int(shape[0]), int(shape[1])
    if p * s <= np.iinfo(np.int32).max:
        keys = primary.astype(jnp.int32) * np.int32(s) + secondary.astype(
            jnp.int32
        )
        return jnp.argsort(keys, stable=True)
    # a DIMENSION beyond int32 (kron of huge factors under x64) must keep
    # int64 coordinates — downcasting would wrap negative and mis-sort
    idt = (
        jnp.int64 if max(p, s) > np.iinfo(np.int32).max else jnp.int32
    )
    o1 = jnp.argsort(secondary.astype(idt), stable=True)
    o2 = jnp.argsort(primary.astype(idt)[o1], stable=True)
    return o1[o2]


def sort_coo(rows, cols, vals, shape, by="row"):
    """Lexicographic sort of COO triples by (row, col) or (col, row).

    Reference: the SORT_BY_KEY task (``src/sparse/sort/*``, thrust samplesort +
    alltoallv). Single-device TPU version: :func:`lexsort_rc` (fused int32
    key when it fits, two-pass stable radix composition otherwise — XLA
    lowers both to efficient on-device sorts). The distributed samplesort
    lives in ``sparse_tpu.parallel.sort``.
    """
    if by == "row":
        order = lexsort_rc(rows, cols, shape)
    else:
        order = lexsort_rc(cols, rows, (shape[1], shape[0]))
    return rows[order], cols[order], vals[order]


def dedup_sorted(rows, cols, vals, sum_duplicates=True):
    """Collapse duplicate (already lex-sorted) (row, col) pairs, summing values.

    Returns (unique_rows, unique_cols, unique_vals, nunique). Host-syncs once
    for the unique count (the reference equally blocks on nnz futures,
    csr.py:996). Pair comparison — no fused key, no dtype escalation.
    """
    nnz = rows.shape[0]
    if nnz == 0:
        return rows, cols, vals, 0
    is_new = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1]),
        ]
    )
    nunique = host_int(is_new.sum())
    if nunique == nnz:
        return rows, cols, vals, nnz
    seg = jnp.cumsum(is_new) - 1
    first_idx = jnp.nonzero(is_new, size=nunique)[0]
    if sum_duplicates:
        uvals = jax.ops.segment_sum(vals, seg, num_segments=nunique)
    else:
        # keep last occurrence (scipy setdiag-style semantics) — pick each
        # group's last index explicitly; .at[seg].set with duplicate
        # indices has implementation-defined write order in JAX
        last = jnp.concatenate([is_new[1:], jnp.ones((1,), dtype=bool)])
        last_idx = jnp.nonzero(last, size=nunique)[0]
        uvals = vals[last_idx]
    return rows[first_idx], cols[first_idx], uvals, nunique


def segment_searchsorted(sorted_vals, starts, ends, queries):
    """Per-query lower_bound of ``queries[i]`` in ``sorted_vals[starts[i]:ends[i]]``.

    Vectorized binary search with a fixed trip count (log2 of the longest
    possible segment) — the building block for sorted-row intersections
    (elementwise mult) without fused (row, col) keys. Returns the absolute
    insertion index in ``sorted_vals`` (== ends[i] when not found past the
    segment end).
    """
    nb = int(sorted_vals.shape[0])
    if nb == 0:
        return jnp.zeros_like(starts)
    lo = starts
    hi = ends
    # an interval of length L needs floor(log2 L)+1 = L.bit_length()
    # halvings to collapse to lo == hi; segments are at most nb long
    for _ in range(nb.bit_length()):
        mid = lo + (hi - lo) // 2  # overflow-safe: lo+hi wraps int32 past 2**30
        mv = sorted_vals[jnp.clip(mid, 0, nb - 1)]
        go_right = (mv < queries) & (mid < hi)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right | (mid >= hi), hi, mid)
    return lo
