"""Coordinate plumbing: pos/indptr <-> row-id expansion, sorting, dedup.

Reference analog: the EXPAND_POS_TO_COORDINATES / SORTED_COORDS_TO_COUNTS /
BOUNDS_FROM_PARTITIONED_COORDINATES task family (``src/sparse/array/conv/*``,
SURVEY §2b) and the rect1 zip/unzip helpers. On TPU there are no Rect<1> pos
arrays — ``indptr`` is a plain prefix-sum array — so this file is the whole
"coordinate plumbing" layer: fully vectorized, jit-friendly, static shapes.

Dynamic-nnz boundaries (sort dedup, unions) return host ints explicitly via
``utils.host_int`` — the TPU analog of reading a Legion future.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import host_int


def expand_rows(indptr, nnz: int):
    """Expand a CSR indptr into per-nnz row ids (CSR -> COO row coordinates).

    Reference: EXPAND_POS_TO_COORDINATES (``src/sparse/array/conv/pos_to_coordinates.cc``).
    Vectorized as a batched binary search over the sorted indptr — O(nnz log m),
    no scatter, maps cleanly onto the VPU.
    """
    if nnz == 0:
        return jnp.zeros((0,), dtype=indptr.dtype)
    pts = jnp.arange(nnz, dtype=indptr.dtype)
    return (jnp.searchsorted(indptr, pts, side="right") - 1).astype(indptr.dtype)


def counts_to_indptr(counts, dtype=None):
    """Row-counts -> indptr via exclusive scan (the nnz_to_pos cumsum of base.py:30-48)."""
    dtype = dtype or counts.dtype
    z = jnp.zeros((1,), dtype=dtype)
    return jnp.concatenate([z, jnp.cumsum(counts.astype(dtype))])


def rows_to_indptr(sorted_rows, m: int, dtype=None):
    """Sorted row ids -> indptr. Reference: SORTED_COORDS_TO_COUNTS reduction
    (``src/sparse/array/conv/sorted_coords_to_counts.cc``) + cumsum; here a single
    vectorized searchsorted over the sorted coords — no reduction tree needed."""
    dtype = dtype or (sorted_rows.dtype if sorted_rows.size else jnp.int32)
    targets = jnp.arange(m + 1, dtype=sorted_rows.dtype if sorted_rows.size else jnp.int32)
    return jnp.searchsorted(sorted_rows, targets, side="left").astype(dtype)


def require_x64_keys(shape) -> bool:
    """True when (row, col) keys for ``shape`` need int64.

    Raises loudly when int64 is needed but x64 is disabled: jnp silently
    truncates int64->int32 in that configuration, which would corrupt every
    sort-based conversion for m*n > 2**31 with no error.
    """
    m, n = int(shape[0]), int(shape[1])
    if m * n <= np.iinfo(np.int32).max:
        return False
    if not jax.config.jax_enable_x64:
        raise ValueError(
            f"matrix shape {shape} needs int64 sort keys (m*n > 2**31); "
            "enable them with jax.config.update('jax_enable_x64', True)"
        )
    return True


def linearize(rows, cols, shape):
    """(row, col) -> single sort key. int64 when the flat index could overflow int32."""
    n = int(shape[1])
    if require_x64_keys(shape):
        return rows.astype(jnp.int64) * n + cols.astype(jnp.int64)
    return rows.astype(jnp.int32) * np.int32(n) + cols.astype(jnp.int32)


def sort_coo(rows, cols, vals, shape, by="row"):
    """Lexicographic sort of COO triples by (row, col) or (col, row).

    Reference: the SORT_BY_KEY task (``src/sparse/sort/*``, thrust samplesort +
    alltoallv). Single-device TPU version: one radix/comparator sort of a fused
    key via ``jnp.argsort`` (XLA lowers to an efficient on-device sort).
    The distributed samplesort lives in ``sparse_tpu.parallel.sort``.
    """
    if by == "row":
        keys = linearize(rows, cols, shape)
    else:
        keys = linearize(cols, rows, (shape[1], shape[0]))
    order = jnp.argsort(keys, stable=True)
    return rows[order], cols[order], vals[order], keys[order]


def dedup_sorted(keys, vals, shape, sum_duplicates=True):
    """Collapse duplicate (already sorted) keys, summing values.

    Returns (unique_rows, unique_cols, unique_vals, nunique). Host-syncs once for
    the unique count (the reference equally blocks on nnz futures, csr.py:996).
    """
    nnz = keys.shape[0]
    if nnz == 0:
        return keys, keys, vals, 0
    is_new = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), keys[1:] != keys[:-1]]
    )
    nunique = host_int(is_new.sum())
    if nunique == nnz:
        n = int(shape[1])
        rows = (keys // n).astype(jnp.int32)
        cols = (keys % n).astype(jnp.int32)
        return rows, cols, vals, nnz
    seg = jnp.cumsum(is_new) - 1
    if sum_duplicates:
        uvals = jax.ops.segment_sum(vals, seg, num_segments=nunique)
    else:
        # keep last occurrence (scipy setdiag-style semantics)
        uvals = jnp.zeros((nunique,), dtype=vals.dtype).at[seg].set(vals)
    first_idx = jnp.nonzero(is_new, size=nunique)[0]
    ukeys = keys[first_idx]
    n = int(shape[1])
    rows = (ukeys // n).astype(jnp.int32)
    cols = (ukeys % n).astype(jnp.int32)
    return rows, cols, uvals, nunique
