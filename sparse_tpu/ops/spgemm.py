"""SpGEMM: sparse @ sparse -> sparse.

Reference analog: SPGEMM_CSR_CSR_CSR{_NNZ,,_GPU} (``src/sparse/array/csr/
spgemm_csr_csr_csr.*`` — CPU: 2-pass Gustavson; GPU: per-rank cuSPARSE) and the
3-phase 2-D CSRxCSC algorithm (``spgemm_csr_csr_csc.*``, csr.py:1495-1728).

TPU-native design: Gustavson's row-wise merge is scalar-loop-shaped, so instead
we use **ESC (expand-sort-compress)** — the standard GPU SpGEMM formulation that
is pure gather/sort/segment-reduce and maps directly onto XLA's sort machinery:

  1. expand: each A-nnz (i,k,a) pairs with every B-nnz in row k -> COO triples
     (i, j, a*b); the expansion offsets come from one prefix-sum over B row
     lengths gathered at A's column ids.
  2. sort: one fused-key device sort of the expanded triples.
  3. compress: collapse duplicate (i,j) with a segment-sum.

One host sync for the expansion size, one for the result nnz (the reference
blocks on the same two quantities via FutureMap scans, csr.py:827-859).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..types import index_dtype_for
from ..utils import host_int
from .coords import (
    counts_to_indptr,
    dedup_sorted,
    expand_rows,
    linearize,
    rows_to_indptr,
)


def spgemm_csr_csr(
    indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape_a, shape_b
):
    """C = A @ B, both CSR. Returns (indptr, indices, data) of C (CSR)."""
    m = int(shape_a[0])
    n = int(shape_b[1])
    out_shape = (m, n)
    dt = jnp.result_type(data_a.dtype, data_b.dtype)
    nnz_a = data_a.shape[0]
    if nnz_a == 0 or data_b.shape[0] == 0:
        idt = index_dtype_for(out_shape, 0)
        return (
            jnp.zeros((m + 1,), dtype=idt),
            jnp.zeros((0,), dtype=idt),
            jnp.zeros((0,), dtype=dt),
        )
    rows_a = expand_rows(indptr_a, nnz_a)
    # expansion counts: |B row| at each A column id
    counts = indptr_b[indices_a + 1] - indptr_b[indices_a]
    offsets = counts_to_indptr(counts, dtype=jnp.int64)
    total = host_int(offsets[-1])
    if total == 0:
        idt = index_dtype_for(out_shape, 0)
        return (
            jnp.zeros((m + 1,), dtype=idt),
            jnp.zeros((0,), dtype=idt),
            jnp.zeros((0,), dtype=dt),
        )
    t = jnp.arange(total, dtype=jnp.int64)
    src = jnp.searchsorted(offsets, t, side="right") - 1  # source A-nnz per product
    p = indptr_b[indices_a[src]].astype(jnp.int64) + (t - offsets[src])
    out_rows = rows_a[src]
    out_cols = indices_b[p]
    out_vals = data_a[src].astype(dt) * data_b[p].astype(dt)
    keys = linearize(out_rows, out_cols, out_shape)
    order = jnp.argsort(keys, stable=True)
    urows, ucols, uvals, nunique = dedup_sorted(keys[order], out_vals[order], out_shape)
    idt = index_dtype_for(out_shape, nunique)
    indptr = rows_to_indptr(urows, m, dtype=idt)
    return indptr, ucols.astype(idt), uvals
