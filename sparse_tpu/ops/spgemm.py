"""SpGEMM: sparse @ sparse -> sparse.

Reference analog: SPGEMM_CSR_CSR_CSR{_NNZ,,_GPU} (``src/sparse/array/csr/
spgemm_csr_csr_csr.*`` — CPU: 2-pass Gustavson; GPU: per-rank cuSPARSE) and the
3-phase 2-D CSRxCSC algorithm (``spgemm_csr_csr_csc.*``, csr.py:1495-1728).

TPU-native design: Gustavson's row-wise merge is scalar-loop-shaped, so instead
we use **ESC (expand-sort-compress)** — the standard GPU SpGEMM formulation that
is pure gather/sort/segment-reduce and maps directly onto XLA's sort machinery:

  1. expand: each A-nnz (i,k,a) pairs with every B-nnz in row k -> COO triples
     (i, j, a*b); the expansion offsets come from one prefix-sum over B row
     lengths gathered at A's column ids.
  2. sort: one fused-key device sort of the expanded triples.
  3. compress: collapse duplicate (i,j) with a segment-sum.

One host sync for the expansion size, one for the result nnz (the reference
blocks on the same two quantities via FutureMap scans, csr.py:827-859).

Data-dependent intermediate sizes (the expansion total, the unique count) are
BUCKETED to powers of two with masked sentinel padding, so repeated products
with nearby sizes — e.g. the 8 row-block tiles of a distributed Galerkin
triple product, or successive AMG levels — share compiled programs instead of
paying a fresh XLA sort compile per exact size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..types import index_dtype_for
from ..utils import host_int, in_trace
from .coords import (
    counts_to_indptr,
    expand_rows,
    lexsort_rc,
    rows_to_indptr,
)


def _all_on_host(*arrs) -> bool:
    """True when every array is numpy or a CPU-committed jax array."""
    for a in arrs:
        sh = getattr(a, "sharding", None)
        if sh is None:
            continue  # numpy
        try:
            if any(d.platform != "cpu" for d in sh.device_set):
                return False
        except Exception:
            return False
    return True


def _native_spgemm(indptr_a, indices_a, data_a, indptr_b, indices_b, data_b,
                   m, n, dt):
    """Eager host fast path: the C++ Gustavson kernel (native.spgemm_host).

    Returns (indptr, indices, data) as jnp arrays under the library's
    index-dtype policy, or None when the path doesn't apply. Values run
    in f64 internally (>= the accuracy of every eligible dtype).
    """
    from ..config import settings

    if not settings.native_spgemm or in_trace():
        return None
    if dt not in (jnp.float32, jnp.float64):
        return None  # complex/int keep the exact-dtype ESC path
    if not _all_on_host(indptr_a, indices_a, data_a,
                        indptr_b, indices_b, data_b):
        return None
    from .. import native

    import numpy as np

    Ap = np.asarray(indptr_a)
    # callers may pad trailing nnz (parallel tile shapes): slice them off
    nnz_a, nnz_b = int(Ap[-1]), int(np.asarray(indptr_b)[-1])
    got = native.spgemm_host(
        Ap, np.asarray(indices_a)[:nnz_a], np.asarray(data_a)[:nnz_a],
        np.asarray(indptr_b), np.asarray(indices_b)[:nnz_b],
        np.asarray(data_b)[:nnz_b], int(m), int(n),
    )
    if got is None:
        return None
    Cp, Cj, Cx = got
    idt = index_dtype_for((m, n), int(Cp[-1]))
    return (
        jnp.asarray(Cp.astype(idt)),
        jnp.asarray(Cj.astype(idt)),
        jnp.asarray(Cx.astype(dt)),
    )


def _next_pow2(v: int) -> int:
    return 1 << (max(int(v), 1) - 1).bit_length()


def esc_expand_sort_compress(
    indptr_a, indices_a, data_a, indptr_b, indices_b, data_b,
    n: int, T: int, U: int, dt, m_real: int,
):
    """The fully-traced ESC body shared by the single-device product and the
    shard_map tile of ``parallel.spgemm`` (one compile per bucket shape).

    ``T``/``U`` are static pow-2 buckets for the expansion/unique sizes;
    padding slots carry value 0 and the sentinel pair (``m_real``, 0)
    (``m_real`` = largest REAL local row count — padded tile rows are empty,
    so real pairs never reach it). The expanded triples sort as (row, col)
    PAIRS via :func:`lexsort_rc` — int32 indices for any dims that fit
    int32, never a fused int64 key. Returns (urows [U], ucols [U],
    uvals [U], nunique scalar); entries past nunique are sentinel-rowed
    with value 0.
    """
    # int32 pair indices throughout — a dimension past 2**31 would silently
    # wrap, so raise loudly like _union_merge/kron/lexsort_rc do
    if max(int(m_real) + 1, int(n)) > 2**31 - 1:
        raise ValueError(
            f"esc_expand_sort_compress uses int32 pair indices; dimension "
            f"max(m_real+1={m_real + 1}, n={n}) exceeds int32 range"
        )
    # expansion arithmetic dtype: values are bounded by T (the static
    # expansion bucket) AND by nnz(B) (the indptr_b gather bases); int32
    # covers every realistic tile, and requesting int64 under no-x64 (the
    # real-TPU config) would emit a truncation warning and silently
    # downcast anyway
    ebound = max(int(T), int(data_b.shape[0]) + 1)
    if ebound > 2**31 - 1 and not jax.config.jax_enable_x64:
        raise ValueError(
            f"expansion bound {ebound} needs int64 offsets; enable x64"
        )
    edt = jnp.int64 if ebound > 2**31 - 1 else jnp.int32
    nnz_a = indices_a.shape[0]
    rows_a = expand_rows(indptr_a, nnz_a)
    # expansion counts: |B row| at each A column id; caller-padded nnz
    # slots (beyond indptr_a[-1]) expand to nothing
    counts = indptr_b[indices_a + 1] - indptr_b[indices_a]
    counts = jnp.where(jnp.arange(nnz_a) < indptr_a[-1], counts, 0)
    offsets = counts_to_indptr(counts, dtype=edt)
    total = offsets[-1]
    t = jnp.arange(T, dtype=edt)
    tvalid = t < total
    src = jnp.clip(
        jnp.searchsorted(offsets, t, side="right") - 1, 0, nnz_a - 1
    )
    p = jnp.clip(
        indptr_b[indices_a[src]].astype(edt) + (t - offsets[src]),
        0,
        data_b.shape[0] - 1,
    )
    out_vals = jnp.where(
        tvalid, data_a[src].astype(dt) * data_b[p].astype(dt), 0
    )
    out_rows = jnp.where(
        tvalid, rows_a[src].astype(jnp.int32), jnp.int32(m_real)
    )
    out_cols = jnp.where(tvalid, indices_b[p].astype(jnp.int32), 0)
    order = lexsort_rc(out_rows, out_cols, (m_real + 1, n))
    srows = out_rows[order]
    scols = out_cols[order]
    svals = out_vals[order]
    # compress: collapse duplicate pairs; sentinels are never "new" so they
    # fold (with value 0) into the last real segment
    is_new = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (srows[1:] != srows[:-1]) | (scols[1:] != scols[:-1]),
        ]
    ) & (srows < m_real)
    seg = jnp.clip(jnp.cumsum(is_new) - 1, 0, U - 1)
    uvals = jax.ops.segment_sum(svals, seg, num_segments=U)
    # fill_value T-1 is always a sentinel slot (T > total), so padded
    # unique entries stay sentinel-rowed and are trimmed by the caller
    first_idx = jnp.nonzero(is_new, size=U, fill_value=T - 1)[0]
    return srows[first_idx], scols[first_idx], uvals, is_new.sum()


def spgemm_csr_csr(
    indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, shape_a, shape_b,
    m_real: int | None = None,
):
    """C = A @ B, both CSR. Returns (indptr, indices, data) of C (CSR).

    Inputs may carry trailing padding nnz (entries at positions >=
    ``indptr_a[-1]``): they are masked out of the expansion, so callers can
    pad tiles to shared shapes (parallel.spgemm does). ``m_real`` (default
    ``shape_a[0]``) is the largest row id actually populated — callers with
    padded tile shapes pass the real row count so key-width selection isn't
    inflated by padding.
    """
    m = int(shape_a[0])
    n = int(shape_b[1])
    out_shape = (m, n)
    if m_real is None:
        m_real = m
    dt = jnp.result_type(data_a.dtype, data_b.dtype)
    native_out = _native_spgemm(
        indptr_a, indices_a, data_a, indptr_b, indices_b, data_b, m, n, dt
    )
    if native_out is not None:
        return native_out
    nnz_a = data_a.shape[0]
    if nnz_a == 0 or data_b.shape[0] == 0:
        idt = index_dtype_for(out_shape, 0)
        return (
            jnp.zeros((m + 1,), dtype=idt),
            jnp.zeros((0,), dtype=idt),
            jnp.zeros((0,), dtype=dt),
        )
    # expansion size: one cheap host sync (the reference's NNZ phase).
    # int32 accumulation under no-x64 is safe: a >2**31 expansion would
    # exceed device memory long before the counter wraps (the x64 config
    # keeps the exact int64 sum)
    sdt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    counts = indptr_b[indices_a + 1] - indptr_b[indices_a]
    counts = jnp.where(jnp.arange(nnz_a) < indptr_a[-1], counts, 0)
    total = host_int(jnp.sum(counts.astype(sdt)))
    if total == 0:
        idt = index_dtype_for(out_shape, 0)
        return (
            jnp.zeros((m + 1,), dtype=idt),
            jnp.zeros((0,), dtype=idt),
            jnp.zeros((0,), dtype=dt),
        )
    # Bucket the expansion to the next power of two (always > total so the
    # sentinel block is nonempty).
    T = _next_pow2(total + 1)
    urows_all, ucols_all, uvals_all, nunique_dev = esc_expand_sort_compress(
        indptr_a, indices_a, data_a, indptr_b, indices_b, data_b,
        n=n, T=T, U=T, dt=dt, m_real=int(m_real),
    )
    nunique = host_int(nunique_dev)
    P = _next_pow2(nunique)
    urows = urows_all[:P]
    uvals = uvals_all[:P]
    # padded tail entries carry the sentinel row (m_real, which may be
    # < m for padded tile shapes): push them past row m so indptr never
    # counts them — keeps indptr[-1] == len(data) for every caller
    urows = jnp.where(jnp.arange(P) < nunique, urows, jnp.int32(m))
    idt = index_dtype_for(out_shape, nunique)
    indptr = rows_to_indptr(urows, m, dtype=idt)
    return indptr, ucols_all[:nunique].astype(idt), uvals[:nunique]
