"""Tropical-semiring SpMV: y[i] = lexicographic-max over j in row(i) of x[j].

Reference analog: CSR_SPMV_ROW_SPLIT_TROPICAL_SEMIRING
(``src/sparse/array/csr/tropical_spmv.cc:25-57``): x is an [n, f] integer tuple
array, y[i] initializes to the 0-tuple and takes the lexicographically largest
x[j] among the row's neighbors. Structure-only (A's values unused). Powers the
AMG MIS aggregation (``examples/amg.py:199-276``).

TPU-native: padded-row gather -> [m, k, f] candidates -> vectorized
lexicographic tournament reduction over k (log-depth, no scalar loops).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .conv import csr_to_ell
from ..utils import host_int, in_trace


def _lex_ge(a, b):
    """[.., f] lexicographic a >= b, vectorized over leading dims."""
    diff = a - b
    neq = diff != 0
    has = neq.any(axis=-1)
    first = jnp.argmax(neq, axis=-1)
    d = jnp.take_along_axis(diff, first[..., None], axis=-1)[..., 0]
    return jnp.where(has, d > 0, True)


def _lex_max(a, b):
    return jnp.where(_lex_ge(a, b)[..., None], a, b)


def _tournament(ell_idx, lens, x):
    """Gather + log-depth lexicographic tournament; one fused program.

    Jitted as a whole: the MIS driver calls this every tournament round
    in a host loop, and the eager op-by-op form compiled hundreds of
    tiny kernels per hierarchy level (the AMG build was compile-bound —
    33.7 s of its 52 s at n=256 was XLA compilation)."""
    m = ell_idx.shape[0]
    k = ell_idx.shape[1]
    f = x.shape[1]
    valid = jnp.arange(k, dtype=lens.dtype)[None, :] < lens[:, None]
    cand = jnp.where(valid[:, :, None], x[ell_idx], jnp.zeros((), dtype=x.dtype))
    # log-depth pairwise tournament over the k axis (unrolls at trace time)
    while cand.shape[1] > 1:
        kk = cand.shape[1]
        half = (kk + 1) // 2
        pad = half * 2 - kk
        if pad:
            cand = jnp.concatenate(
                [cand, jnp.zeros((m, pad, f), dtype=cand.dtype)], axis=1
            )
        cand = _lex_max(cand[:, ::2], cand[:, 1::2])
    return cand[:, 0, :]


_tournament_jit = jax.jit(_tournament)


def tropical_spmv(indptr, indices, data, x, m: int, ell_idx=None):
    """ell_idx: optional prebuilt [m, k] padded-row index plane (csr_array's
    cached ELL layout) — avoids re-syncing the max row length per call on the
    AMG aggregation hot path."""
    if x.ndim != 2:
        raise ValueError("tropical_spmv expects a 2-D tuple array")
    f = x.shape[1]
    nnz = indices.shape[0]
    if nnz == 0 or m == 0:
        return jnp.zeros((m, f), dtype=x.dtype)
    lens = indptr[1:] - indptr[:-1]
    if ell_idx is None:
        k = host_int(lens.max())
        ell_idx, _ = csr_to_ell(indptr, indices, data, m, max(k, 1))
    fn = _tournament if in_trace() else _tournament_jit
    return fn(ell_idx, lens, jnp.asarray(x))
