"""Tropical-semiring SpMV: y[i] = lexicographic-max over j in row(i) of x[j].

Reference analog: CSR_SPMV_ROW_SPLIT_TROPICAL_SEMIRING
(``src/sparse/array/csr/tropical_spmv.cc:25-57``): x is an [n, f] integer tuple
array, y[i] initializes to the 0-tuple and takes the lexicographically largest
x[j] among the row's neighbors. Structure-only (A's values unused). Powers the
AMG MIS aggregation (``examples/amg.py:199-276``).

TPU-native: padded-row gather -> [m, k, f] candidates -> vectorized
lexicographic tournament reduction over k (log-depth, no scalar loops).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .conv import csr_to_ell
from ..utils import host_int, in_trace


def _lex_ge(a, b):
    """[.., f] lexicographic a >= b, vectorized over leading dims."""
    diff = a - b
    neq = diff != 0
    has = neq.any(axis=-1)
    first = jnp.argmax(neq, axis=-1)
    d = jnp.take_along_axis(diff, first[..., None], axis=-1)[..., 0]
    return jnp.where(has, d > 0, True)


def _lex_max(a, b):
    return jnp.where(_lex_ge(a, b)[..., None], a, b)


def _tournament(ell_idx, lens, x):
    """Gather + log-depth lexicographic tournament; one fused program.

    Jitted as a whole: the MIS driver calls this every tournament round
    in a host loop, and the eager op-by-op form compiled hundreds of
    tiny kernels per hierarchy level (the AMG build was compile-bound —
    33.7 s of its 52 s at n=256 was XLA compilation)."""
    m = ell_idx.shape[0]
    k = ell_idx.shape[1]
    f = x.shape[1]
    valid = jnp.arange(k, dtype=lens.dtype)[None, :] < lens[:, None]
    cand = jnp.where(valid[:, :, None], x[ell_idx], jnp.zeros((), dtype=x.dtype))
    # log-depth pairwise tournament over the k axis (unrolls at trace time)
    while cand.shape[1] > 1:
        kk = cand.shape[1]
        half = (kk + 1) // 2
        pad = half * 2 - kk
        if pad:
            cand = jnp.concatenate(
                [cand, jnp.zeros((m, pad, f), dtype=cand.dtype)], axis=1
            )
        cand = _lex_max(cand[:, ::2], cand[:, 1::2])
    return cand[:, 0, :]


_tournament_jit = jax.jit(_tournament)


def tropical_spmv(indptr, indices, data, x, m: int, ell_idx=None):
    """ell_idx: optional prebuilt [m, k] padded-row index plane (csr_array's
    cached ELL layout) — avoids re-syncing the max row length per call on the
    AMG aggregation hot path."""
    if x.ndim != 2:
        raise ValueError("tropical_spmv expects a 2-D tuple array")
    f = x.shape[1]
    nnz = indices.shape[0]
    if nnz == 0 or m == 0:
        return jnp.zeros((m, f), dtype=x.dtype)
    lens = indptr[1:] - indptr[:-1]
    if ell_idx is None:
        k = host_int(lens.max())
        ell_idx, _ = csr_to_ell(indptr, indices, data, m, max(k, 1))
    fn = _tournament if in_trace() else _tournament_jit
    return fn(ell_idx, lens, jnp.asarray(x))


@partial(jax.jit, static_argnames=("k",))
def _mis_loop(ell_idx, lens, x0, k: int):
    """The whole MIS tournament as ONE lax.while_loop.

    The r3 form ran the per-round update on the host with a device->host
    fetch per tropical hop (examples/amg.py:209-215) — the AMG hierarchy
    build's main latency. Here the flag updates are vectorized device ops
    and the loop carries (x, changed): a round that changes no flag exits
    IMMEDIATELY (the analog of the host loop's one-round progress
    assert), so a stalled tournament fails fast in the caller instead of
    spinning to an iteration bound."""
    N = x0.shape[0]
    idx = jnp.arange(N, dtype=x0.dtype)

    def hops(x):
        z = _tournament(ell_idx, lens, x)
        for _ in range(1, k):
            z = _tournament(ell_idx, lens, z)
        return z

    def cond(state):
        x, changed = state
        return jnp.logical_and(jnp.any(x[:, 0] == 1), changed)

    def body(state):
        x, _ = state
        z = hops(x)
        flag = x[:, 0]
        mis = (flag == 1) & (z[:, 2] == idx)
        non = (flag == 1) & (z[:, 0] == 2)
        new_flag = jnp.where(mis, 2, jnp.where(non, 0, flag))
        return x.at[:, 0].set(new_flag), jnp.any(new_flag != flag)

    x, _ = jax.lax.while_loop(cond, body, (x0, jnp.bool_(True)))
    return x[:, 0]


def mis_flags(indptr, indices, data, m: int, k=1, invalid=None, seed=0,
              ell_idx=None):
    """MIS(k) by tropical tournament, entirely on device.

    Reference analog: the host tournament loop of ``examples/amg.py:199``
    (reference amg.py:199-257). Returns the final [m] int32 flag vector:
    2 = MIS member, 0 = dominated, -1 = invalid. Same seed discipline as
    the host form (int32 random priorities + index tie-break), so the
    selected set is identical.
    """
    lens = indptr[1:] - indptr[:-1]
    if ell_idx is None:
        kk = host_int(lens.max()) if m else 0
        ell_idx, _ = csr_to_ell(indptr, indices, data, m, max(kk, 1))
    rng = np.random.default_rng(seed)
    rv = rng.integers(0, np.iinfo(np.int32).max, size=m, dtype=np.int32)
    flag0 = np.ones(m, np.int32)
    if invalid is not None:
        flag0[np.asarray(invalid)] = -1
    x0 = jnp.stack(
        [
            jnp.asarray(flag0),
            jnp.asarray(rv),
            jnp.arange(m, dtype=jnp.int32),
        ],
        axis=1,
    )
    flags = _mis_loop(ell_idx, lens, x0, k)
    if bool(jnp.any(flags == 1)):
        # the loop exited on a no-progress round with nodes still active
        # — a stalled tournament (e.g. a strength graph without diagonal
        # entries, where z[:,2]==i can never fire). Loud failure, like
        # the host loop's progress assert, not a silently partial MIS.
        raise RuntimeError(
            "tropical MIS tournament made no progress within the round "
            "bound; does the strength graph include self-loops?"
        )
    return flags


@jax.jit
def _aggregate_cols(ell_idx, lens, flags):
    """Nearest-root aggregation columns from MIS flags, on device.

    Coarse indices are assigned in node order (cumsum over the MIS mask —
    the same numbering as np.nonzero), then two tropical hops route every
    fine node to its nearest root (examples/amg.py:225-243)."""
    mask = flags == 2
    coarse_idx = jnp.cumsum(mask.astype(jnp.int32)) - 1
    x = jnp.stack(
        [
            jnp.where(mask, 2, 0).astype(jnp.int32),
            jnp.where(mask, coarse_idx, 0).astype(jnp.int32),
        ],
        axis=1,
    )
    y = _tournament(ell_idx, lens, x)
    y = y.at[:, 0].add(x[:, 0])
    z = _tournament(ell_idx, lens, y)
    return z[:, 1], jnp.sum(mask.astype(jnp.int32))


def mis_aggregate_cols(indptr, indices, data, m: int, flags, ell_idx=None):
    """(aggregate column per fine node [m], n_coarse) from MIS flags."""
    lens = indptr[1:] - indptr[:-1]
    if ell_idx is None:
        kk = host_int(lens.max()) if m else 0
        ell_idx, _ = csr_to_ell(indptr, indices, data, m, max(kk, 1))
    return _aggregate_cols(ell_idx, lens, jnp.asarray(flags))
