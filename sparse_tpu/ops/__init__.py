"""Single-device compute kernels (the L1 task-library analog, SURVEY §2b).

Each reference Legion task family maps to a module here:
  spmv.py        - CSR/CSC SpMV, SpMM, rSpMM
  spgemm.py      - SpGEMM (ESC formulation)
  sddmm.py       - sampled dense-dense matmul
  elementwise.py - add / multiply / diagonal / sum
  conv.py        - format conversions (2-pass count+fill)
  coords.py      - coordinate plumbing (pos<->rows, sort, dedup)
  tropical.py    - (max, +) lexicographic semiring SpMV
"""

from . import conv, coords, elementwise, sddmm, spgemm, spmv, tropical  # noqa: F401
