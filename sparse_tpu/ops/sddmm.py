"""SDDMM: sampled dense-dense matmul, out_vals = A_vals * (C @ D) at A's sparsity.

Reference analog: CSR_SDDMM / CSC_SDDMM (``src/sparse/array/csr/sddmm.*``,
``csc/sddmm.*``) — B o (C @ D) fused, structure-preserving. TPU-native: gather
the needed rows of C and columns of D per nnz and contract — a batched dot that
XLA tiles onto the MXU for large k.
"""

from __future__ import annotations

import jax.numpy as jnp

from .coords import expand_rows


def csr_sddmm(indptr, indices, data, C, D):
    """vals_out[e] = data[e] * dot(C[row_e, :], D[:, col_e])."""
    nnz = data.shape[0]
    if nnz == 0:
        return data
    rows = expand_rows(indptr, nnz)
    dt = jnp.result_type(data.dtype, C.dtype, D.dtype)
    inner = jnp.einsum("ek,ek->e", C[rows].astype(dt), D.T[indices].astype(dt))
    return data.astype(dt) * inner


def csc_sddmm(indptr, indices, data, C, D):
    """CSC variant: compressed axis is columns, indices are rows."""
    nnz = data.shape[0]
    if nnz == 0:
        return data
    cols = expand_rows(indptr, nnz)
    dt = jnp.result_type(data.dtype, C.dtype, D.dtype)
    inner = jnp.einsum("ek,ek->e", C[indices].astype(dt), D.T[cols].astype(dt))
    return data.astype(dt) * inner
