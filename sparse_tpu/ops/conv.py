"""Format conversions: dense<->CSR/CSC, COO<->CSR/CSC, CSR<->CSC, ELL build.

Reference analog: the ``src/sparse/array/conv/*`` task family (CSR_TO_DENSE,
DENSE_TO_CSR{_NNZ,}, COO_TO_DENSE, ...; SURVEY §2b) — all 2-pass count+fill.
The "unbound store" problem (result nnz unknown at launch) is solved the TPU
way: count on device, one host sync for the size (utils.host_int), then a
fixed-shape fill pass. These run at Python level (construction/conversion
time), never inside solver loops, matching where the reference blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..types import index_dtype_for
from ..utils import host_int
from .coords import (
    dedup_sorted,
    expand_rows,
    lexsort_rc,
    rows_to_indptr,
    sort_coo,
)


def dense_to_csr(d):
    """Dense [m, n] -> (indptr, indices, data, nnz). 2-pass count + fill."""
    m, n = d.shape
    mask = d != 0
    nnz = host_int(mask.sum())
    idt = index_dtype_for(d.shape, nnz)
    flat_idx = jnp.nonzero(mask.ravel(), size=nnz)[0].astype(idt)
    rows = flat_idx // n
    cols = flat_idx % n
    data = d.ravel()[flat_idx]
    indptr = rows_to_indptr(rows, m, dtype=idt)
    return indptr, cols, data, nnz


def dense_to_csc(d):
    indptr, rows, data, nnz = dense_to_csr(d.T)
    return indptr, rows, data, nnz


def csr_to_dense(indptr, indices, data, shape):
    m, n = shape
    nnz = data.shape[0]
    out = jnp.zeros((m, n), dtype=data.dtype)
    if nnz == 0:
        return out
    rows = expand_rows(indptr, nnz)
    return out.at[rows, indices].add(data)


def coo_to_dense(rows, cols, vals, shape):
    out = jnp.zeros(shape, dtype=vals.dtype)
    if vals.shape[0] == 0:
        return out
    return out.at[rows, cols].add(vals)


def coo_to_csr(rows, cols, vals, shape, sum_duplicates=True):
    """COO -> CSR: sort by (row, col), optionally collapse duplicates.

    Reference: coo.tocsr (coo.py:233) = SORT_BY_KEY + BOUNDS_FROM_PARTITIONED_
    COORDINATES + SORTED_COORDS_TO_COUNTS + nnz_to_pos scan. Single fused sort here.
    """
    m = int(shape[0])
    srows, scols, svals = sort_coo(rows, cols, vals, shape, by="row")
    if sum_duplicates:
        urows, ucols, uvals, _ = dedup_sorted(srows, scols, svals)
    else:
        urows, ucols, uvals = srows, scols, svals
    idt = index_dtype_for(shape, uvals.shape[0])
    indptr = rows_to_indptr(urows, m, dtype=idt)
    return indptr, ucols.astype(idt), uvals


def coo_to_csc(rows, cols, vals, shape, sum_duplicates=True):
    indptr, urows, uvals = coo_to_csr(
        cols, rows, vals, (shape[1], shape[0]), sum_duplicates
    )
    return indptr, urows, uvals


def csr_to_coo(indptr, indices, data, shape):
    nnz = data.shape[0]
    rows = expand_rows(indptr, nnz)
    return rows, indices, data


def csr_to_csc(indptr, indices, data, shape):
    """CSR -> CSC via a (col, row) sort. No duplicate collapse needed.

    Tolerates trailing padding nnz (positions >= indptr[-1]): they are keyed
    past every real column, sort to the tail, and stay beyond the returned
    indptr's last entry — the shared tile-padding convention of
    ``ops.spgemm`` (uniform shapes -> shared compiles).
    """
    nnz = data.shape[0]
    m, n = int(shape[0]), int(shape[1])
    rows = expand_rows(indptr, nnz)
    valid = jnp.arange(nnz) < indptr[-1]
    # padding entries take column n (past every real column) so they sort
    # to the tail; primary extent n+1 keeps the fused fast path exact
    cols_for_indptr = jnp.where(valid, indices, n)
    order = lexsort_rc(cols_for_indptr, rows, (n + 1, m))
    idt = index_dtype_for(shape, nnz)
    col_indptr = rows_to_indptr(cols_for_indptr[order], n, dtype=idt)
    return col_indptr, rows[order].astype(idt), data[order]


def csr_row_counts(indptr):
    return indptr[1:] - indptr[:-1]


def csr_to_ell(indptr, indices, data, m: int, k: int):
    """Build the padded-row (ELL) layout: [m, k] index/value planes.

    Padding entries point at column 0 with value 0 (contribute 0 * x[0]).
    k must be >= max row length. One scatter at construction time buys
    scatter-free SpMV/SpMM forever after.
    """
    nnz = data.shape[0]
    idt = indices.dtype
    ell_idx = jnp.zeros((m, k), dtype=idt)
    ell_val = jnp.zeros((m, k), dtype=data.dtype)
    if nnz == 0:
        return ell_idx, ell_val
    rows = expand_rows(indptr, nnz)
    slot = jnp.arange(nnz, dtype=idt) - indptr[rows].astype(idt)
    ell_idx = ell_idx.at[rows, slot].set(indices)
    ell_val = ell_val.at[rows, slot].set(data)
    return ell_idx, ell_val
