"""Shared sparse-array machinery.

Reference analog: ``sparse/base.py`` — ``CompressedBase`` (nnz->pos scan, sum,
asformat, zero-preserving ufunc grafting, base.py:28-188) and ``DenseSparseBase``
(nnz-balanced partitioning, base.py:194-296). On TPU the rect1 pos arrays are
plain ``indptr`` prefix sums, and "balance()" becomes choosing nnz-balanced
row-block boundaries for the device mesh (see ``sparse_tpu.parallel``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .utils import host_int


class SparseArray:
    """Common surface shared by all formats (scipy.sparse.sparray analog)."""

    ndim = 2
    # Make numpy defer binary ops (B @ A, B * A, ...) to our reflected methods.
    __array_ufunc__ = None
    __array_priority__ = 100.0

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def nnz(self) -> int:
        raise NotImplementedError

    def getnnz(self) -> int:
        return self.nnz

    def count_nonzero(self) -> int:
        return host_int((self._data_array() != 0).sum())

    def _data_array(self):
        raise NotImplementedError

    # ---- format dispatch -------------------------------------------------
    def asformat(self, format: str):
        """Convert to the named format ('csr', 'csc', 'coo', 'dia', 'dense').

        Reference: base.py:150-170.
        """
        if format is None or format == self.format:
            return self
        conv = getattr(self, "to" + format, None)
        if conv is None:
            raise ValueError(f"Format {format} is unknown.")
        return conv()

    def todense(self):
        return self.toarray()

    # ---- generic arithmetic wired through format-specific primitives -----
    def __neg__(self):
        return self._with_data(-self._data_array())

    def __abs__(self):
        return self._with_data(jnp.abs(self._data_array()))

    def conjugate(self):
        return self._with_data(jnp.conjugate(self._data_array()))

    conj = conjugate

    def power(self, n):
        return self._with_data(self._data_array() ** n)

    def astype(self, dtype):
        return self._with_data(self._data_array().astype(dtype))

    def copy(self):
        return self._with_data(self._data_array())

    # Zero-preserving elementwise functions grafted onto every format
    # (reference grafts cunumeric ufuncs at base.py:120-148).
    def sqrt(self):
        return self._with_data(jnp.sqrt(self._data_array()))

    def rint(self):
        return self._with_data(jnp.rint(self._data_array()))

    def sign(self):
        return self._with_data(jnp.sign(self._data_array()))

    def expm1(self):
        return self._with_data(jnp.expm1(self._data_array()))

    def log1p(self):
        return self._with_data(jnp.log1p(self._data_array()))

    def sin(self):
        return self._with_data(jnp.sin(self._data_array()))

    def sinh(self):
        return self._with_data(jnp.sinh(self._data_array()))

    def tan(self):
        return self._with_data(jnp.tan(self._data_array()))

    def tanh(self):
        return self._with_data(jnp.tanh(self._data_array()))

    def arcsin(self):
        return self._with_data(jnp.arcsin(self._data_array()))

    def arcsinh(self):
        return self._with_data(jnp.arcsinh(self._data_array()))

    def arctan(self):
        return self._with_data(jnp.arctan(self._data_array()))

    def arctanh(self):
        return self._with_data(jnp.arctanh(self._data_array()))

    def deg2rad(self):
        return self._with_data(jnp.deg2rad(self._data_array()))

    def rad2deg(self):
        return self._with_data(jnp.rad2deg(self._data_array()))

    def trunc(self):
        return self._with_data(jnp.trunc(self._data_array()))

    def ceil(self):
        return self._with_data(jnp.ceil(self._data_array()))

    def floor(self):
        return self._with_data(jnp.floor(self._data_array()))

    # ---- python numeric protocol -----------------------------------------
    def __sub__(self, other):
        return self + (-other)

    def __rsub__(self, other):
        return (-self) + other

    def __truediv__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", 1) == 0:
            return self._with_data(self._data_array() / other)
        return NotImplemented

    def __rmul__(self, other):
        return self.__mul__(other)

    def __matmul__(self, other):
        return self.dot(other)

    def __rmatmul__(self, other):
        return self._rdot(other)

    def mean(self, axis=None):
        s = self.sum(axis=axis)
        m, n = self.shape
        if axis is None:
            return s / (m * n)
        if axis in (0, -2):
            return s / m
        return s / n


def _resolve_shape(shape, rows, cols):
    if shape is not None:
        return (int(shape[0]), int(shape[1]))
    if rows.shape[0] == 0:
        return (0, 0)
    return (
        host_int(rows.max()) + 1,
        host_int(cols.max()) + 1,
    )
