"""Shared sparse-array machinery.

Reference analog: ``sparse/base.py`` — ``CompressedBase`` (nnz->pos scan, sum,
asformat, zero-preserving ufunc grafting, base.py:28-188) and ``DenseSparseBase``
(nnz-balanced partitioning, base.py:194-296). On TPU the rect1 pos arrays are
plain ``indptr`` prefix sums, and "balance()" becomes choosing nnz-balanced
row-block boundaries for the device mesh (see ``sparse_tpu.parallel``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .utils import host_int


class SparseArray:
    """Common surface shared by all formats (scipy.sparse.sparray analog)."""

    ndim = 2
    # Make numpy defer binary ops (B @ A, B * A, ...) to our reflected methods.
    __array_ufunc__ = None
    __array_priority__ = 100.0

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def nnz(self) -> int:
        raise NotImplementedError

    def getnnz(self) -> int:
        return self.nnz

    def count_nonzero(self) -> int:
        return host_int((self._data_array() != 0).sum())

    def _data_array(self):
        raise NotImplementedError

    # ---- format dispatch -------------------------------------------------
    def asformat(self, format: str):
        """Convert to the named format ('csr', 'csc', 'coo', 'dia', 'dok',
        'lil', 'bsr', 'dense').

        Reference: base.py:150-170 (dok/lil go beyond its surface).
        """
        if format is None or format == self.format:
            return self
        conv = getattr(self, "to" + format, None)
        if conv is None:
            raise ValueError(f"Format {format} is unknown.")
        return conv()

    def todense(self):
        return self.toarray()

    def todok(self):
        """Host dictionary-of-keys staging copy (``dok.dok_array``)."""
        from .dok import dok_array

        return dok_array(self)

    def tolil(self):
        """Host list-of-lists staging copy (``lil.lil_array``)."""
        from .lil import lil_array

        return lil_array(self)

    def tobsr(self, blocksize=None):
        """Block sparse row copy (``bsr.bsr_array``) — [R, C] dense blocks
        whose SpMV runs as a batched MXU matmul. ``blocksize=None``
        estimates the block structure like scipy (largest candidate block
        whose fill efficiency clears a threshold; (1, 1) when the matrix
        has none); the matrix dims must divide by the chosen size."""
        import numpy as _np

        from .bsr import bsr_array

        C = self.tocsr()
        m, n = C.shape
        if blocksize is None:
            blocksize = _estimate_blocksize(
                _np.asarray(C.indptr), _np.asarray(C.indices), (m, n)
            )
        R, Cb = tuple(map(int, blocksize))
        if R < 1 or Cb < 1 or m % R or n % Cb:
            raise ValueError(
                f"blocksize {(R, Cb)} does not divide shape {(m, n)}"
            )
        rows_arr = _np.repeat(
            _np.arange(m, dtype=_np.int64), _np.diff(_np.asarray(C.indptr))
        )
        cols_arr = _np.asarray(C.indices, dtype=_np.int64)
        vals = _np.asarray(C.data)
        brow = rows_arr // R
        bcol = cols_arr // Cb
        Nb = n // Cb
        key = brow * Nb + bcol
        ublocks, binv = _np.unique(key, return_inverse=True)
        nnzb = int(ublocks.shape[0])
        data = _np.zeros((max(nnzb, 0), R, Cb), dtype=vals.dtype)
        data[binv, rows_arr % R, cols_arr % Cb] = vals
        indptr = _np.zeros(m // R + 1, dtype=_np.int64)
        _np.add.at(indptr, (ublocks // Nb) + 1, 1)
        indptr = _np.cumsum(indptr)
        return bsr_array(
            (data, (ublocks % Nb).astype(_np.int64), indptr), shape=(m, n)
        )

    # ---- generic arithmetic wired through format-specific primitives -----
    def __neg__(self):
        return self._with_data(-self._data_array())

    def __abs__(self):
        return self._with_data(jnp.abs(self._data_array()))

    def conjugate(self):
        return self._with_data(jnp.conjugate(self._data_array()))

    conj = conjugate

    def power(self, n):
        return self._with_data(self._data_array() ** n)

    def astype(self, dtype):
        return self._with_data(self._data_array().astype(dtype))

    def copy(self):
        return self._with_data(self._data_array())

    # Zero-preserving elementwise functions grafted onto every format
    # (reference grafts cunumeric ufuncs at base.py:120-148).
    def sqrt(self):
        return self._with_data(jnp.sqrt(self._data_array()))

    def rint(self):
        return self._with_data(jnp.rint(self._data_array()))

    def sign(self):
        return self._with_data(jnp.sign(self._data_array()))

    def expm1(self):
        return self._with_data(jnp.expm1(self._data_array()))

    def log1p(self):
        return self._with_data(jnp.log1p(self._data_array()))

    def sin(self):
        return self._with_data(jnp.sin(self._data_array()))

    def sinh(self):
        return self._with_data(jnp.sinh(self._data_array()))

    def tan(self):
        return self._with_data(jnp.tan(self._data_array()))

    def tanh(self):
        return self._with_data(jnp.tanh(self._data_array()))

    def arcsin(self):
        return self._with_data(jnp.arcsin(self._data_array()))

    def arcsinh(self):
        return self._with_data(jnp.arcsinh(self._data_array()))

    def arctan(self):
        return self._with_data(jnp.arctan(self._data_array()))

    def arctanh(self):
        return self._with_data(jnp.arctanh(self._data_array()))

    def deg2rad(self):
        return self._with_data(jnp.deg2rad(self._data_array()))

    def rad2deg(self):
        return self._with_data(jnp.rad2deg(self._data_array()))

    def trunc(self):
        return self._with_data(jnp.trunc(self._data_array()))

    def ceil(self):
        return self._with_data(jnp.ceil(self._data_array()))

    def floor(self):
        return self._with_data(jnp.floor(self._data_array()))

    # ---- python numeric protocol -----------------------------------------
    def __sub__(self, other):
        return self + (-other)

    def __rsub__(self, other):
        return (-self) + other

    def __truediv__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", 1) == 0:
            return self._with_data(self._data_array() / other)
        return NotImplemented

    def __rmul__(self, other):
        return self.__mul__(other)

    def __matmul__(self, other):
        return self.dot(other)

    def __rmatmul__(self, other):
        return self._rdot(other)

    def mean(self, axis=None):
        s = self.sum(axis=axis)
        m, n = self.shape
        if axis is None:
            return s / (m * n)
        if axis in (0, -2):
            return s / m
        return s / n

    # ---- whole-array / axis reductions (scipy semantics: implicit zeros
    # participate). axis reductions return DENSE 1-D arrays — a documented
    # deviation from scipy's sparse-1-row-matrix return.
    @staticmethod
    def _reject_out(out):
        # scipy raises for out= on sparse reductions; silently ignoring it
        # would hand callers wrong-but-quiet behavior
        if out is not None:
            raise ValueError("Sparse arrays do not support an 'out' parameter.")

    def max(self, axis=None, out=None):
        """Maximum over all entries / per axis (``ops.reduce.min_or_max``)."""
        import numpy as _np

        from .ops.reduce import min_or_max

        self._reject_out(out)
        return min_or_max(self, _np.maximum, axis=axis)

    def min(self, axis=None, out=None):
        import numpy as _np

        from .ops.reduce import min_or_max

        self._reject_out(out)
        return min_or_max(self, _np.minimum, axis=axis)

    def nanmax(self, axis=None, out=None):
        import numpy as _np

        from .ops.reduce import min_or_max

        self._reject_out(out)
        return min_or_max(self, _np.maximum, axis=axis, nan=True)

    def nanmin(self, axis=None, out=None):
        import numpy as _np

        from .ops.reduce import min_or_max

        self._reject_out(out)
        return min_or_max(self, _np.minimum, axis=axis, nan=True)

    def argmax(self, axis=None, out=None):
        """First row-major position attaining the max (implicit zeros count)."""
        import numpy as _np

        from .ops.reduce import arg_min_or_max

        self._reject_out(out)
        return arg_min_or_max(self, _np.maximum, axis=axis)

    def argmin(self, axis=None, out=None):
        import numpy as _np

        from .ops.reduce import arg_min_or_max

        self._reject_out(out)
        return arg_min_or_max(self, _np.minimum, axis=axis)

    def trace(self, offset=0):
        """Sum of the ``offset`` diagonal (scipy spmatrix.trace)."""
        return self.diagonal(k=offset).sum()

    def _canonical_coo(self):
        """COO view with duplicates summed (raw coo_array may hold them).

        Accepts either scipy-canonical COO (lex-sorted + deduped) or the
        merely duplicate-free outputs of csc/dia.tocoo (order-agnostic
        consumers only need uniqueness)."""
        coo = self.tocoo()
        if not (
            getattr(coo, "has_canonical_format", True)
            or getattr(coo, "_duplicate_free", False)
        ):
            coo = coo.copy()
            coo.sum_duplicates()
        return coo

    def nonzero(self):
        """(row, col) coordinate arrays of explicitly nonzero values,
        row-major sorted (scipy nonzero drops stored zeros)."""
        import numpy as _np

        coo = self._canonical_coo()
        rows = _np.asarray(coo.row)
        cols = _np.asarray(coo.col)
        vals = _np.asarray(coo.data)
        keep = vals != 0
        rows, cols = rows[keep], cols[keep]
        order = _np.lexsort((cols, rows))
        return rows[order], cols[order]

    def maximum(self, other):
        """Elementwise max vs a sparse operand or non-positive scalar
        (positive scalars would densify — scipy emits a dense matrix there;
        we raise instead, documented deviation)."""
        return self._minmax_binary(other, is_max=True)

    def minimum(self, other):
        return self._minmax_binary(other, is_max=False)

    def _minmax_binary(self, other, is_max: bool):
        import numpy as _np

        from .ops.elementwise import csr_minmax_csr

        opname = "maximum" if is_max else "minimum"
        if _np.isscalar(other):
            # `not (<= 0)` (rather than `> 0`) also catches NaN, whose
            # result at every implicit-zero position would be NaN => dense
            bad = not (other <= 0) if is_max else not (other >= 0)
            if bad:
                raise NotImplementedError(
                    f"{opname} with a "
                    f"{'positive/NaN' if is_max else 'negative/NaN'} "
                    "scalar produces a dense result; densify explicitly"
                )
            op = jnp.maximum if is_max else jnp.minimum
            A = self.tocsr()
            return A._with_data(op(A.data, jnp.asarray(other, A.data.dtype)))
        if not isinstance(other, SparseArray):
            raise TypeError(f"{opname} expects a sparse operand or scalar")
        if self.shape != other.shape:
            raise ValueError(
                f"inconsistent shapes: {self.shape} vs {other.shape}"
            )
        A, B = self.tocsr(), other.tocsr()
        from .csr import csr_array

        op = jnp.maximum if is_max else jnp.minimum
        indptr, indices, data = csr_minmax_csr(
            A.indptr, A.indices, A.data, B.indptr, B.indices, B.data,
            self.shape, op,
        )
        return csr_array.from_parts(data, indices, indptr, self.shape)

    # ---- canonicalization (our arrays are built canonical: sorted unique
    # indices, no structural gaps) ----------------------------------------
    has_sorted_indices = True
    has_canonical_format = True

    def sum_duplicates(self):
        """No-op for CSR/CSC (always canonical); COO overrides."""

    def sort_indices(self):
        """No-op: construction sorts indices (scipy csr.sort_indices)."""

    def sorted_indices(self):
        return self.copy()

    def prune(self):
        """No-op: index/data buffers are always exactly nnz-sized."""

    def setdiag(self, values, k=0):
        """Set the ``k``-th diagonal IN PLACE (scipy setdiag): scalar
        broadcast or per-slot array (extra entries ignored, short arrays
        set a prefix). Explicit zeros are stored, as in scipy."""
        import numpy as _np

        m, n = self.shape
        dlen = min(m + min(k, 0), n - max(k, 0))
        if dlen <= 0:
            raise ValueError("k exceeds matrix dimensions")
        vals = _np.asarray(values)
        if vals.ndim == 0:
            vals = _np.full(dlen, vals)
        else:
            vals = vals[:dlen]
            dlen = vals.shape[0]
        i = _np.arange(dlen) + max(-k, 0)
        j = _np.arange(dlen) + max(k, 0)
        coo = self._canonical_coo()
        rows = _np.concatenate([_np.asarray(coo.row), i])
        cols = _np.concatenate([_np.asarray(coo.col), j])
        data = _np.concatenate(
            [_np.asarray(coo.data), vals.astype(self.dtype, copy=False)]
        )
        from .ops.coords import dedup_sorted

        # stable sort + keep-LAST dedup: the appended diagonal wins
        order = _np.lexsort((cols, rows))  # host: stable, no x64 gating
        srows, scols, sdata = rows[order], cols[order], data[order]
        from .coo import coo_array

        tmp = coo_array((sdata, (srows, scols)), shape=self.shape)
        urows, ucols, uvals, _ = dedup_sorted(
            tmp.row, tmp.col, tmp.data, sum_duplicates=False
        )
        rebuilt = coo_array((uvals, (urows, ucols)), shape=self.shape)
        rebuilt.has_sorted_indices = True
        rebuilt.has_canonical_format = True
        if self.format != "coo":
            rebuilt = rebuilt.asformat(self.format)
        self.__dict__.clear()  # drop stale lazy caches (_ell_width_cache, ...)
        self.__dict__.update(rebuilt.__dict__)

    def reshape(self, *shape, order="C"):
        """Reshape to another 2-D shape (same total size). Host-side flat
        index arithmetic (int64 numpy), scipy coo.reshape semantics."""
        import numpy as _np

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if len(shape) != 2:
            raise ValueError("sparse arrays are 2-D; reshape takes (m, n)")
        m, n = self.shape
        m2, n2 = int(shape[0]), int(shape[1])
        if m2 * n2 != m * n:
            raise ValueError(
                f"cannot reshape array of size {m * n} into shape {shape}"
            )
        coo = self.tocoo()
        rows = _np.asarray(coo.row, dtype=_np.int64)
        cols = _np.asarray(coo.col, dtype=_np.int64)
        flat = rows * n + cols if order == "C" else cols * m + rows
        if order == "C":
            r2, c2 = flat // n2, flat % n2
        else:
            r2, c2 = flat % m2, flat // m2
        from .coo import coo_array

        out = coo_array(
            (_np.asarray(coo.data), (r2, c2)), shape=(m2, n2)
        )
        return out.asformat(self.format)

    def resize(self, *shape):
        """Change shape IN PLACE, dropping out-of-range entries (scipy)."""
        import numpy as _np

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        m2, n2 = int(shape[0]), int(shape[1])
        coo = self.tocoo()
        rows = _np.asarray(coo.row)
        cols = _np.asarray(coo.col)
        data = _np.asarray(coo.data)
        keep = (rows < m2) & (cols < n2)
        from .coo import coo_array

        rebuilt = coo_array(
            (data[keep], (rows[keep], cols[keep])), shape=(m2, n2)
        )
        if self.format != "coo":
            rebuilt = rebuilt.asformat(self.format)
        self.__dict__.clear()  # drop stale lazy caches (_ell_width_cache, ...)
        self.__dict__.update(rebuilt.__dict__)
        self._shape = (m2, n2)

    def check_format(self, full_check: bool = True):
        """Validate the stored-format invariants (scipy check_format):
        indptr length/monotonicity, index bounds, sorted in-row indices.
        Applies to compressed formats; others pass trivially."""
        import numpy as _np

        indptr = getattr(self, "indptr", None)
        if indptr is None:
            return
        indptr = _np.asarray(indptr)
        major = (
            self.shape[0] if self.format == "csr" else self.shape[1]
        )
        minor = (
            self.shape[1] if self.format == "csr" else self.shape[0]
        )
        if indptr.shape[0] != major + 1:
            raise ValueError(
                f"index pointer size {indptr.shape[0]} != {major + 1}"
            )
        if indptr[0] != 0:
            raise ValueError("index pointer should start with 0")
        if (_np.diff(indptr) < 0).any():
            raise ValueError("index pointer values must not decrease")
        indices = _np.asarray(self.indices)
        if indptr[-1] > indices.shape[0]:
            raise ValueError("Last value of index pointer exceeds nnz")
        if full_check and indices.size:
            if indices.min() < 0 or indices.max() >= minor:
                raise ValueError(
                    f"indices out of bounds for axis of size {minor}"
                )
            rows = _np.repeat(_np.arange(major), _np.diff(indptr))
            within = _np.diff(indices) >= 0
            same_row = rows[1:] == rows[:-1] if rows.size else _np.array([], bool)
            if (same_row & ~within[: same_row.shape[0]]).any():
                raise ValueError("indices must be sorted within each row")

    def eliminate_zeros(self):
        """Drop explicitly stored zeros IN PLACE (scipy semantics; also
        canonicalizes a duplicate-holding COO first, as scipy does)."""
        import numpy as _np

        coo = self._canonical_coo()
        vals = _np.asarray(coo.data)
        if not (vals == 0).any():
            if self.format == "coo" and coo is not self:
                # duplicates were summed: persist the canonical form
                self.__dict__.clear()
                self.__dict__.update(coo.__dict__)
            return
        keep = vals != 0
        from .coo import coo_array

        rebuilt = coo_array(
            (
                vals[keep],
                (_np.asarray(coo.row)[keep], _np.asarray(coo.col)[keep]),
            ),
            shape=self.shape,
        ).asformat(self.format)
        self.__dict__.clear()  # drop stale lazy caches (_ell_width_cache, ...)
        self.__dict__.update(rebuilt.__dict__)


def _resolve_shape(shape, rows, cols):
    if shape is not None:
        return (int(shape[0]), int(shape[1]))
    if rows.shape[0] == 0:
        return (0, 0)
    return (
        host_int(rows.max()) + 1,
        host_int(cols.max()) + 1,
    )


def _estimate_blocksize(indptr, indices, shape, efficiency: float = 0.7):
    """scipy-style block-structure estimation: the largest candidate (r, c)
    dividing the shape whose dense-block fill efficiency
    nnz / (nnzb * r * c) clears the threshold. Returns (1, 1) when the
    matrix has no block structure."""
    m, n = shape
    nnz = int(indptr[-1])
    if nnz == 0:
        return (1, 1)
    rows = np.repeat(np.arange(m, dtype=np.int64), np.diff(indptr))
    cols = np.asarray(indices, dtype=np.int64)
    for r, c in ((6, 6), (4, 4), (3, 3), (2, 2)):
        if m % r or n % c:
            continue
        nnzb = np.unique((rows // r) * (n // c) + cols // c).shape[0]
        if nnz / (nnzb * r * c) >= efficiency:
            return (r, c)
    return (1, 1)
