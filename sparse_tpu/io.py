"""MatrixMarket IO.

Reference analog: ``sparse/io.py:24-63`` (mmread via the single-task C++ parser
READ_MTX_TO_COO, ``src/sparse/io/mtx_to_coo.cc:44-145``, with symmetry expansion
and unbound outputs + scalar futures for m/n/nnz). Here: a vectorized
numpy-based parser on the host (file IO is host work either way), producing a
device-resident ``coo_array``. A native (C) accelerated reader is planned in
``src/`` for large files. Also adds ``mmwrite`` (the reference is read-only).
"""

from __future__ import annotations

import numpy as np

from .coo import coo_array
from .utils import asjnp


def _parse_header(line: str):
    parts = line.strip().split()
    if len(parts) != 5 or parts[0] != "%%MatrixMarket" or parts[1] != "matrix":
        raise ValueError(f"invalid MatrixMarket header: {line!r}")
    fmt, field, symmetry = parts[2], parts[3], parts[4]
    if fmt not in ("coordinate", "array"):
        raise ValueError(f"unsupported MatrixMarket format {fmt}")
    if field not in ("real", "double", "integer", "complex", "pattern"):
        raise ValueError(f"unsupported MatrixMarket field {field}")
    if symmetry not in ("general", "symmetric", "skew-symmetric", "hermitian"):
        raise ValueError(f"unsupported MatrixMarket symmetry {symmetry}")
    return fmt, field, symmetry


def _parse_coordinate_body(f, nnz: int, field: str):
    """(rows, cols, vals) from the coordinate body — native tokenizer when
    available (the READ_MTX_TO_COO analog, mtx_to_coo.cc:44-145), numpy
    loadtxt fallback."""
    from . import native

    kind = {"pattern": 0, "complex": 2}.get(field, 1)
    if nnz and native.lib() is not None:
        parsed = native.parse_mtx_body(f.read().encode(), nnz, kind)
        if parsed is not None:
            rows, cols, re, im = parsed
            vals = re + 1j * im if field == "complex" else re
            return rows, cols, vals
        raise ValueError(
            f"MatrixMarket body does not contain exactly {nnz} entries"
        )
    body = np.loadtxt(f, ndmin=2) if nnz else np.zeros((0, 3))
    if body.shape[0] != nnz:
        raise ValueError(f"expected {nnz} entries, found {body.shape[0]}")
    rows = body[:, 0].astype(np.int64) - 1
    cols = body[:, 1].astype(np.int64) - 1
    if field == "pattern":
        vals = np.ones((nnz,), dtype=np.float64)
    elif field == "complex":
        vals = body[:, 2] + 1j * body[:, 3]
    else:
        vals = body[:, 2]
    return rows, cols, vals


def mmread(path) -> coo_array:
    """Read a MatrixMarket file into a COO array (reference io.py:24)."""
    with open(path, "r") as f:
        header = f.readline()
        fmt, field, symmetry = _parse_header(header)
        # skip comments
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        dims = line.split()
        if fmt == "coordinate":
            m, n, nnz = int(dims[0]), int(dims[1]), int(dims[2])
            rows, cols, vals = _parse_coordinate_body(f, nnz, field)
        else:  # dense "array" format, column-major
            from . import native

            m, n = int(dims[0]), int(dims[1])
            if symmetry == "general":
                count = m * n
            elif symmetry == "skew-symmetric":
                # strict lower triangle only (diagonal is implicitly zero)
                count = n * (n - 1) // 2
            else:  # symmetric / hermitian: lower triangle incl. diagonal
                count = n * (n + 1) // 2
            flat = None
            if field != "complex" and count and native.lib() is not None:
                # native single-pass tokenizer (READ_MTX_TO_COO analog)
                flat = native.parse_mtx_dense(f.read().encode(), count)
                if flat is None:
                    raise ValueError(
                        f"MatrixMarket array body does not contain exactly "
                        f"{count} entries"
                    )
            if flat is None:
                body = np.loadtxt(f, ndmin=2)
                if field == "complex":
                    flat = body[:, 0] + 1j * body[:, 1]
                else:
                    flat = body[:, 0] if body.ndim == 2 else body
            if symmetry == "general":
                dense = flat.reshape((n, m)).T
            else:
                # symmetric/hermitian array files store the lower triangle
                # column-major (column j: rows j..m-1); skew-symmetric the
                # STRICT lower triangle (column j: rows j+1..m-1)
                lo = 1 if symmetry == "skew-symmetric" else 0
                dense = np.zeros((m, n), dtype=flat.dtype)
                c = np.repeat(np.arange(n), np.maximum(m - np.arange(n) - lo, 0))
                r = np.concatenate([np.arange(j + lo, m) for j in range(n)])
                dense[r, c] = flat
            mask = dense != 0
            rows, cols = np.nonzero(mask)
            vals = dense[rows, cols]
            nnz = rows.shape[0]
        if symmetry != "general":
            off = rows != cols
            r2, c2 = cols[off], rows[off]
            if symmetry == "skew-symmetric":
                v2 = -vals[off]
            elif symmetry == "hermitian":
                v2 = np.conjugate(vals[off])
            else:
                v2 = vals[off]
            rows = np.concatenate([rows, r2])
            cols = np.concatenate([cols, c2])
            vals = np.concatenate([vals, v2])
    return coo_array((asjnp(vals), (rows, cols)), shape=(m, n))


def mmwrite(path, A, comment: str = "", precision: int = 16) -> None:
    """Write a sparse array as a MatrixMarket coordinate file."""
    c = A.tocoo() if hasattr(A, "tocoo") else coo_array(A)
    rows = np.asarray(c.row) + 1
    cols = np.asarray(c.col) + 1
    vals = np.asarray(c.data)
    complex_ = np.iscomplexobj(vals)
    field = "complex" if complex_ else "real"
    with open(path, "w") as f:
        f.write(f"%%MatrixMarket matrix coordinate {field} general\n")
        if comment:
            for ln in comment.splitlines():
                f.write(f"%{ln}\n")
        f.write(f"{c.shape[0]} {c.shape[1]} {c.nnz}\n")
        if complex_:
            for r, cc, v in zip(rows, cols, vals):
                f.write(
                    f"{r} {cc} {v.real:.{precision}g} {v.imag:.{precision}g}\n"
                )
        else:
            for r, cc, v in zip(rows, cols, vals):
                f.write(f"{r} {cc} {v:.{precision}g}\n")
