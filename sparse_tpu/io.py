"""MatrixMarket IO.

Reference analog: ``sparse/io.py:24-63`` (mmread via the single-task C++ parser
READ_MTX_TO_COO, ``src/sparse/io/mtx_to_coo.cc:44-145``, with symmetry expansion
and unbound outputs + scalar futures for m/n/nnz). Here: a vectorized
numpy-based parser on the host (file IO is host work either way), producing a
device-resident ``coo_array``. Also adds ``mmwrite`` (the reference is
read-only) and — for the streaming ingestion data plane (ISSUE 18) — a
chunked coordinate-body parser: :func:`stream_coo` yields bounded host
chunks (symmetry already expanded per chunk) so a large file never needs
a whole-body materialization before the distributed sort, and
:func:`read_coo_host` assembles those chunks into the raw host COO the
ingest path (``SolveSession.ingest`` / ``sparse_tpu.ingest``) consumes.
Parity against ``scipy.io.mmread`` is pinned in ``tests/test_ingest.py``
(the SURVEY §3.2 oracle drill), including symmetric-expansion and
pattern-only files.
"""

from __future__ import annotations

import numpy as np

from .coo import coo_array
from .utils import asjnp


def _parse_header(line: str):
    parts = line.strip().split()
    if len(parts) != 5 or parts[0] != "%%MatrixMarket" or parts[1] != "matrix":
        raise ValueError(f"invalid MatrixMarket header: {line!r}")
    fmt, field, symmetry = parts[2], parts[3], parts[4]
    if fmt not in ("coordinate", "array"):
        raise ValueError(f"unsupported MatrixMarket format {fmt}")
    if field not in ("real", "double", "integer", "complex", "pattern"):
        raise ValueError(f"unsupported MatrixMarket field {field}")
    if symmetry not in ("general", "symmetric", "skew-symmetric", "hermitian"):
        raise ValueError(f"unsupported MatrixMarket symmetry {symmetry}")
    return fmt, field, symmetry


def _parse_coordinate_body(f, nnz: int, field: str):
    """(rows, cols, vals) from the coordinate body — native tokenizer when
    available (the READ_MTX_TO_COO analog, mtx_to_coo.cc:44-145), numpy
    loadtxt fallback."""
    from . import native

    kind = {"pattern": 0, "complex": 2}.get(field, 1)
    if nnz and native.lib() is not None:
        parsed = native.parse_mtx_body(f.read().encode(), nnz, kind)
        if parsed is not None:
            rows, cols, re, im = parsed
            vals = re + 1j * im if field == "complex" else re
            return rows, cols, vals
        raise ValueError(
            f"MatrixMarket body does not contain exactly {nnz} entries"
        )
    body = np.loadtxt(f, ndmin=2) if nnz else np.zeros((0, 3))
    if body.shape[0] != nnz:
        raise ValueError(f"expected {nnz} entries, found {body.shape[0]}")
    rows = body[:, 0].astype(np.int64) - 1
    cols = body[:, 1].astype(np.int64) - 1
    if field == "pattern":
        vals = np.ones((nnz,), dtype=np.float64)
    elif field == "complex":
        vals = body[:, 2] + 1j * body[:, 3]
    else:
        vals = body[:, 2]
    return rows, cols, vals


def mmread(path) -> coo_array:
    """Read a MatrixMarket file into a COO array (reference io.py:24)."""
    with open(path, "r") as f:
        header = f.readline()
        fmt, field, symmetry = _parse_header(header)
        # skip comments
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        dims = line.split()
        if fmt == "coordinate":
            m, n, nnz = int(dims[0]), int(dims[1]), int(dims[2])
            rows, cols, vals = _parse_coordinate_body(f, nnz, field)
        else:  # dense "array" format, column-major
            from . import native

            m, n = int(dims[0]), int(dims[1])
            if symmetry == "general":
                count = m * n
            elif symmetry == "skew-symmetric":
                # strict lower triangle only (diagonal is implicitly zero)
                count = n * (n - 1) // 2
            else:  # symmetric / hermitian: lower triangle incl. diagonal
                count = n * (n + 1) // 2
            flat = None
            if field != "complex" and count and native.lib() is not None:
                # native single-pass tokenizer (READ_MTX_TO_COO analog)
                flat = native.parse_mtx_dense(f.read().encode(), count)
                if flat is None:
                    raise ValueError(
                        f"MatrixMarket array body does not contain exactly "
                        f"{count} entries"
                    )
            if flat is None:
                body = np.loadtxt(f, ndmin=2)
                if field == "complex":
                    flat = body[:, 0] + 1j * body[:, 1]
                else:
                    flat = body[:, 0] if body.ndim == 2 else body
            if symmetry == "general":
                dense = flat.reshape((n, m)).T
            else:
                # symmetric/hermitian array files store the lower triangle
                # column-major (column j: rows j..m-1); skew-symmetric the
                # STRICT lower triangle (column j: rows j+1..m-1)
                lo = 1 if symmetry == "skew-symmetric" else 0
                dense = np.zeros((m, n), dtype=flat.dtype)
                c = np.repeat(np.arange(n), np.maximum(m - np.arange(n) - lo, 0))
                r = np.concatenate([np.arange(j + lo, m) for j in range(n)])
                dense[r, c] = flat
            mask = dense != 0
            rows, cols = np.nonzero(mask)
            vals = dense[rows, cols]
            nnz = rows.shape[0]
        if symmetry != "general":
            off = rows != cols
            r2, c2 = cols[off], rows[off]
            if symmetry == "skew-symmetric":
                v2 = -vals[off]
            elif symmetry == "hermitian":
                v2 = np.conjugate(vals[off])
            else:
                v2 = vals[off]
            rows = np.concatenate([rows, r2])
            cols = np.concatenate([cols, c2])
            vals = np.concatenate([vals, v2])
    return coo_array((asjnp(vals), (rows, cols)), shape=(m, n))


def _expand_symmetry(rows, cols, vals, symmetry: str):
    """Mirror the off-diagonal entries per the header's symmetry class —
    per-entry work, so it applies chunk-by-chunk on the streaming path."""
    if symmetry == "general":
        return rows, cols, vals
    off = rows != cols
    r2, c2 = cols[off], rows[off]
    if symmetry == "skew-symmetric":
        v2 = -vals[off]
    elif symmetry == "hermitian":
        v2 = np.conjugate(vals[off])
    else:
        v2 = vals[off]
    return (
        np.concatenate([rows, r2]),
        np.concatenate([cols, c2]),
        np.concatenate([vals, v2]),
    )


def _parse_chunk(lines, field: str):
    """Parse one block of coordinate-body lines (native tokenizer when
    available, loadtxt fallback) — the unit of :func:`stream_coo`."""
    from . import native

    count = len(lines)
    blob = "".join(lines)
    kind = {"pattern": 0, "complex": 2}.get(field, 1)
    if count and native.lib() is not None:
        parsed = native.parse_mtx_body(blob.encode(), count, kind)
        if parsed is not None:
            rows, cols, re, im = parsed
            vals = re + 1j * im if field == "complex" else re
            return rows, cols, vals
        raise ValueError(
            f"MatrixMarket chunk does not contain exactly {count} entries"
        )
    import io as _io

    body = np.loadtxt(_io.StringIO(blob), ndmin=2) if count else np.zeros(
        (0, 3)
    )
    if body.shape[0] != count:
        raise ValueError(f"expected {count} entries, found {body.shape[0]}")
    rows = body[:, 0].astype(np.int64) - 1
    cols = body[:, 1].astype(np.int64) - 1
    if field == "pattern":
        vals = np.ones((count,), dtype=np.float64)
    elif field == "complex":
        vals = body[:, 2] + 1j * body[:, 3]
    else:
        vals = body[:, 2]
    return rows, cols, vals


def stream_coo(path, chunk_nnz: int = 1 << 20):
    """Stream-parse a coordinate MatrixMarket file: yields host
    ``(rows, cols, vals)`` chunks of at most ``2 * chunk_nnz`` entries
    (symmetry expansion can double a chunk), never holding more than one
    chunk's lines in memory — the ingest data plane's large-file entry
    (ISSUE 18). Raises on ``array``-format files (no streaming win for a
    dense body — use :func:`mmread`)."""
    chunk_nnz = max(int(chunk_nnz), 1)
    with open(path, "r") as f:
        fmt, field, symmetry = _parse_header(f.readline())
        if fmt != "coordinate":
            raise ValueError(
                "stream_coo streams coordinate files only; use mmread for "
                f"'{fmt}' format"
            )
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        dims = line.split()
        m, n, nnz = int(dims[0]), int(dims[1]), int(dims[2])
        yield (m, n), nnz  # header first: shape + declared nnz
        seen = 0
        while seen < nnz:
            lines = []
            while len(lines) < chunk_nnz:
                ln = f.readline()
                if not ln:
                    break
                if ln.strip() and not ln.startswith("%"):
                    lines.append(ln)
            if not lines:
                break
            rows, cols, vals = _parse_chunk(lines, field)
            seen += len(lines)
            if seen > nnz:
                raise ValueError(
                    f"MatrixMarket body holds more than the declared "
                    f"{nnz} entries"
                )
            yield _expand_symmetry(rows, cols, vals, symmetry)
        if seen != nnz:
            raise ValueError(f"expected {nnz} entries, found {seen}")


def read_coo_host(path, chunk_nnz: int = 1 << 20):
    """Raw host COO of any MatrixMarket file — the ingest path's source
    resolver: coordinate files stream through :func:`stream_coo`
    (bounded parse memory), array files fall back to :func:`mmread`'s
    dense decoder. Returns ``(rows, cols, vals, shape)`` with symmetry
    expanded and duplicates preserved (the downstream sort collapses
    them)."""
    with open(path, "r") as f:
        fmt, _field, _symmetry = _parse_header(f.readline())
    if fmt != "coordinate":
        c = mmread(path)
        return (
            np.asarray(c.row), np.asarray(c.col), np.asarray(c.data), c.shape
        )
    it = stream_coo(path, chunk_nnz=chunk_nnz)
    shape, _nnz = next(it)
    rs, cs, vs = [], [], []
    for rows, cols, vals in it:
        rs.append(rows)
        cs.append(cols)
        vs.append(vals)
    if rs:
        return (
            np.concatenate(rs), np.concatenate(cs), np.concatenate(vs), shape
        )
    return (
        np.zeros((0,), np.int64), np.zeros((0,), np.int64),
        np.zeros((0,), np.float64), shape,
    )


def mmwrite(path, A, comment: str = "", precision: int = 16) -> None:
    """Write a sparse array as a MatrixMarket coordinate file."""
    c = A.tocoo() if hasattr(A, "tocoo") else coo_array(A)
    rows = np.asarray(c.row) + 1
    cols = np.asarray(c.col) + 1
    vals = np.asarray(c.data)
    complex_ = np.iscomplexobj(vals)
    field = "complex" if complex_ else "real"
    with open(path, "w") as f:
        f.write(f"%%MatrixMarket matrix coordinate {field} general\n")
        if comment:
            for ln in comment.splitlines():
                f.write(f"%{ln}\n")
        f.write(f"{c.shape[0]} {c.shape[1]} {c.nnz}\n")
        if complex_:
            for r, cc, v in zip(rows, cols, vals):
                f.write(
                    f"{r} {cc} {v.real:.{precision}g} {v.imag:.{precision}g}\n"
                )
        else:
            for r, cc, v in zip(rows, cols, vals):
                f.write(f"{r} {cc} {v:.{precision}g}\n")
