"""Environment-backed settings for sparse_tpu.

Reference analog: ``sparse/settings.py:23-33`` (PrioritizedSetting flags) and
``sparse/runtime.py:61-70`` (env overrides + mapper tunables). On TPU there is no
mapper; device/topology discovery lives in ``sparse_tpu.parallel.mesh``. This module
holds the small flag system.

Flags (all env-overridable):
  SPARSE_TPU_PRECISE_WINDOWS  - analog of LEGATE_SPARSE_PRECISE_IMAGES: compute exact
                                per-shard column windows for the SpMV x-gather instead
                                of cheap min/max bounds.
  SPARSE_TPU_SPMV_MODE        - 'auto' | 'segment' | 'ell' | 'sell' | 'pallas': SpMV
                                kernel choice (docs/performance.md).
  SPARSE_TPU_PLAN_CACHE       - library-wide operator plan cache (sparse_tpu.plan_cache):
                                packed SELL/DIA operators and compiled distributed SpMV
                                programs are prepared once per operator and reused.
  SPARSE_TPU_PLAN_CACHE_CAP   - plan cache LRU capacity (entries; default 128).
  SPARSE_TPU_SELL_C           - SELL-C-sigma chunk height (rows per chunk; default 8).
  SPARSE_TPU_SELL_SIGMA       - SELL sorting-window size (rows; 0 = whole matrix).
  SPARSE_TPU_FORCE_SERIAL     - force single-shard execution of distributed conversions
                                (mirrors the force_serial special case in coo.py:242).
  SPARSE_TPU_BATCH_MAX        - batched solve subsystem (sparse_tpu.batch): max lanes a
                                SolveSession coalesces into one dispatched batch.
  SPARSE_TPU_BATCH_BUCKET     - 'pow2' | 'exact': batch-size bucket policy. pow2 pads
                                ragged batches up to powers of two so the number of
                                compiled batched programs stays bounded.
  SPARSE_TPU_TELEMETRY        - structured observability (sparse_tpu.telemetry): solver
                                events, kernel counters, comm volumes, JSONL session log.
  SPARSE_TPU_TELEMETRY_PATH   - JSONL sink override (default results/axon/records.jsonl).
  SPARSE_TPU_TELEMETRY_RING   - in-memory event ring capacity (default 4096).
  SPARSE_TPU_FAULTS           - fault-injection spec (sparse_tpu.resilience.faults), e.g.
                                "nonfinite:matvec:p=0.01,seed=7;fail:pallas". Empty
                                (default) = injection machinery entirely inert.
  SPARSE_TPU_VAULT            - directory of the persistent plan-cache tier
                                (sparse_tpu.vault): prepared SELL/DIA artifacts and the
                                warm-start manifest persist across processes. Empty
                                (default) = disk tier off, in-process cache only.
  SPARSE_TPU_VAULT_CAP_MB     - vault size budget in MB (default 512); the mtime-LRU GC
                                sweep (vault.gc / scripts/vault_gc.py) evicts past it.
  SPARSE_TPU_COMPILE_CACHE    - directory for jax's persistent XLA compilation cache on
                                the serving path: SolveSession construction (and bench)
                                call utils.enable_compilation_cache(dir) when set, so
                                bucket-program executables persist across restarts too.
  SPARSE_TPU_FLEET            - mesh-sharded serving tier (sparse_tpu.fleet): 'auto'
                                enables both sharding strategies, 'batch' / 'row'
                                restrict to one; empty (default) = single-device
                                serving, code path unchanged.
  SPARSE_TPU_FLEET_MIN_B      - minimum REAL lane count before a bucket batch-shards
                                across the mesh (default 8; below it the collective
                                and padding overhead outweighs the parallelism).
  SPARSE_TPU_FLIGHT           - incident flight recorder (telemetry/_flight.py): a
                                directory (or '1' for results/axon/incidents) enables
                                postmortem bundle capture on watchdog alerts. Empty
                                (default) = off.
  SPARSE_TPU_FLIGHT_MAX       - max incident bundles retained (default 8; oldest pruned).
  SPARSE_TPU_PROFILE_EVERY    - sampled timed-dispatch device profiling
                                (batch/service.py): every Nth dispatch records its
                                host-vs-device time split. 0 (default) = off, dispatch
                                path unchanged.
  SPARSE_TPU_INFLIGHT         - streaming-dispatch window of the SolveSession pipeline
                                (batch/service.py): max bucket programs in flight on
                                the device before dispatch retires the oldest. 1 =
                                fully synchronous (bit-identical to the classic
                                enqueue->block path); 2 (default) double-buffers so
                                the host packs/uploads bucket N+1 while the device
                                solves bucket N.
  SPARSE_TPU_PRECOND          - batched preconditioner policy (sparse_tpu.precond):
                                '' / 'off' (default) = none, 'auto' = pick per
                                (pattern, solver, bucket, dtype), or force 'jacobi' |
                                'bjacobi' | 'ilu0' | 'ic0' | 'cheby' | 'neumann'.
  SPARSE_TPU_PRECOND_BLOCK    - block-Jacobi block size (default 4).
  SPARSE_TPU_PRECOND_SWEEPS   - Chow-Patel sweeps of the batched ILU(0)/IC(0)
                                numeric factorization (default 3).
  SPARSE_TPU_PRECOND_TRI_SWEEPS - Jacobi-Richardson sweeps of the batched
                                triangular apply (default 4).
  SPARSE_TPU_PRECOND_DEGREE   - polynomial preconditioner degree (default 4).
  SPARSE_TPU_DTYPE            - mixed-precision serving policy (sparse_tpu.mixed):
                                '' / 'exact' (default) = solve at the request dtype
                                (historic keys/jaxprs byte-identical); 'auto' = f32
                                Krylov + f64 iterative refinement for f64 cg/bicgstab
                                buckets; or force 'f32ir' | 'bf16ir' (bf16 value
                                storage, f32 accumulation, f64 refinement).
  SPARSE_TPU_IR_INNER         - inner Krylov iterations per refinement sweep
                                (default 0 = auto: max(8 * conv_test_iters, 200)).
  SPARSE_TPU_IR_OUTER         - max f64 refinement sweeps per solve (default 25;
                                a static while_loop bound, so one compiled program).
  SPARSE_TPU_IR_ETA           - inner residual-reduction target per sweep
                                (default 0 = per-policy: 1e-4 f32ir, 1e-2 bf16ir).
  SPARSE_TPU_PRECOND_DTYPE    - precond storage dtype under a reduced dtype policy
                                (sparse_tpu.precond, ISSUE 16): '' / 'compute'
                                (default) factorizes/stores M at the inner sweep's
                                compute dtype (historic keys/jaxprs byte-identical);
                                'storage' stores the factors at the policy's reduced
                                storage dtype with wide accumulation (the precond x
                                mixed compounding arm; '.W' program-key suffix).
  SPARSE_TPU_AUTOPILOT        - online policy tuner (sparse_tpu.autopilot): any
                                truthy spelling enables per-(pattern, bucket, SLO
                                class) trial scheduling over the default candidate
                                grid. Empty (default) = off, with program keys,
                                manifests and numerics byte-identical to pre-
                                autopilot behavior.
  SPARSE_TPU_AUTOPILOT_EPSILON - bounded exploration fraction: one in
                                round(1/epsilon) dispatches of an exploring group
                                is a measured experiment (default 0.25).
  SPARSE_TPU_AUTOPILOT_TRIALS - observations per arm per successive-halving round
                                (default 2).
  SPARSE_TPU_AUTOPILOT_SLO_FACTOR - SLO guard: an experiment slower than
                                factor * slo_ms aborts its arm immediately
                                (default 1.5).
  SPARSE_TPU_AUTOPILOT_DRIFT  - drift threshold: a pinned-arm observation slower
                                than factor * the decision score counts a drift
                                strike into autopilot.drift_strikes (default 2.0).
  SPARSE_TPU_HISTORY          - continuous telemetry history store (telemetry/_history.py,
                                Axon v7): a directory (or '1' for results/axon/history)
                                enables the background sampler that scrapes the always-on
                                metrics registry into in-memory rings + on-disk segments.
                                Empty (default) = off: no thread, no filesystem touch,
                                program keys/jaxprs byte-identical.
  SPARSE_TPU_HISTORY_DIR      - segment directory override (wins over a path given in
                                SPARSE_TPU_HISTORY).
  SPARSE_TPU_HISTORY_CAP_MB   - committed-segment retention budget in MB (default 64);
                                oldest segments are deleted past it.
  SPARSE_TPU_HISTORY_INTERVAL - sampler scrape period in seconds (default 1.0).
  SPARSE_TPU_REMESH           - elastic mesh (sparse_tpu.fleet.elastic, ISSUE 20):
                                live topology-change survival for fleet sessions —
                                detect (mesh fault clauses / session.remesh()),
                                quiesce, migrate tickets, re-plan. On by default
                                for fleet sessions; '0' disables the monitor (a
                                topology error then degrades like any dispatch
                                failure). No effect when SPARSE_TPU_FLEET is off.
  SPARSE_TPU_REMESH_RETRIES   - flap guard: executed remeshes a session allows
                                before latching fleet.remesh_latched and pinning
                                the single-device strategy (default 3).
  SPARSE_TPU_INGEST_DEPTH     - streaming ingestion data plane (sparse_tpu.ingest):
                                max arrivals queued on the background onboarder
                                before admission control engages (default 16).
  SPARSE_TPU_INGEST_ADMISSION - 'block' (default) backpressures the submitter at
                                the bound; 'reject' raises IngestAdmissionError.
  SPARSE_TPU_INGEST_RETRIES   - onboarding attempts per arrival beyond the first
                                before its ticket fails (default 1); serving is
                                unaffected while the background worker retries.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() not in ("0", "false", "no", "off", "")


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class Settings:
    precise_windows: bool = field(
        default_factory=lambda: _env_bool("SPARSE_TPU_PRECISE_WINDOWS", False)
    )
    spmv_mode: str = field(default_factory=lambda: _env_str("SPARSE_TPU_SPMV_MODE", "auto"))
    # Native (C++) Gustavson for EAGER host-resident SpGEMMs (construction
    # phases: multigrid Galerkin products). Device/traced calls always use
    # the XLA ESC formulation.
    native_spgemm: bool = field(
        default_factory=lambda: _env_bool("SPARSE_TPU_NATIVE_SPGEMM", True)
    )
    force_serial: bool = field(
        default_factory=lambda: _env_bool("SPARSE_TPU_FORCE_SERIAL", False)
    )
    # Max nnz/row (relative to mean) at which the padded-row (ELL) SpMV fast path kicks
    # in when spmv_mode == 'auto'. Beyond it (skewed row profiles) 'auto'
    # falls through to the prepared SELL-C-sigma packing instead of the
    # scatter-shaped segment path (kernels/sell_spmv.py).
    ell_max_ratio: float = 4.0
    # SELL-C-sigma packing geometry (kernels/sell_spmv.py): chunk height C
    # (rows padded to each chunk's own max degree), sorting-window sigma
    # (rows are degree-sorted only within sigma-row windows; 0 = global
    # sort), and the max number of distinct-width slabs before chunk
    # widths quantize to powers of two (bounds compile size).
    sell_chunk: int = field(default_factory=lambda: max(_env_int("SPARSE_TPU_SELL_C", 8), 1))
    sell_sigma: int = field(default_factory=lambda: _env_int("SPARSE_TPU_SELL_SIGMA", 4096))
    sell_max_slabs: int = 16
    # Library-wide operator plan cache (sparse_tpu.plan_cache): weak-ref
    # keyed, LRU-bounded storage for prepared operators (SELL slabs,
    # PreparedDia, compiled distributed SpMV programs). Off: every lookup
    # misses and rebuilds — correctness identical, prepare cost per call.
    plan_cache: bool = field(
        default_factory=lambda: _env_bool("SPARSE_TPU_PLAN_CACHE", True)
    )
    plan_cache_capacity: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_PLAN_CACHE_CAP", 128), 1)
    )
    # Banded auto-detection for CSR SpMV: matrices with at most this many
    # distinct diagonals (and bounded fill blowup) route through the
    # zero-gather DIA kernel.
    dia_max_diags: int = 32
    dia_max_fill: float = 4.0
    # Max |col - row| band at which the fused Pallas CG iteration
    # (kernels/cg_dia.py) applies — wider bands exceed the per-tile VMEM
    # window budget. (spmv_mode == 'pallas' accelerates DIA-profiled
    # matrices only; general ELL matrices always take the XLA gather —
    # Mosaic has no windowed-gather lowering, VERDICT r2 #8.)
    pallas_max_band: int = 8192
    # Runtime row-tile autotune for the packed-DIA Pallas SpMV: one ~1 s
    # chained probe per matrix geometry per session on real TPUs picks the
    # fastest tile (the r4 tile sweep showed the best band moving between
    # 65536 and 131072 across sessions). Off-TPU it is inert.
    pallas_autotune: bool = field(
        default_factory=lambda: _env_bool("SPARSE_TPU_PALLAS_AUTOTUNE", True)
    )
    # linalg.cg fast path: unpreconditioned solves on banded (DIA-shaped)
    # f32 operators run the fused two-pass Pallas iteration
    # (kernels/cg_dia.py) in conv-test-sized chunks on real TPUs —
    # identical iterates, ~2x the step-loop throughput. Values: True /
    # False / "force" ("force" also runs off-TPU in interpret mode — the
    # test hook; SPARSE_TPU_FUSED_CG=force selects it from the env).
    fused_cg: bool | str = field(
        default_factory=lambda: (
            "force"
            if os.environ.get("SPARSE_TPU_FUSED_CG", "").lower() == "force"
            else _env_bool("SPARSE_TPU_FUSED_CG", True)
        )
    )
    # Row-tile for the fused CG iteration on the PUBLIC cg path. 65536 is
    # the best variant across every hardware sweep (bench's
    # twopass_t65536 headline, r2-r4); the kernel default of 16384 is the
    # conservative VMEM floor kept for direct callers.
    # The public path clamps this down for many-diagonal operators (VMEM
    # plane scratch scales as 2*D*TM; see linalg._try_fused_cg).
    fused_cg_tile: int = field(
        default_factory=lambda: _env_int("SPARSE_TPU_FUSED_CG_TILE", 65536)
    )
    # Batched solve subsystem (sparse_tpu.batch): the microbatching
    # SolveSession coalesces same-pattern requests into batches of at
    # most `batch_max` lanes; ragged batch sizes pad up to the bucket
    # the policy picks ('pow2' bounds the number of compiled batched
    # programs per pattern to log2(batch_max); 'exact' compiles one
    # program per distinct batch size — only sane for fixed traffic).
    batch_max: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_BATCH_MAX", 64), 1)
    )
    batch_bucket: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_BATCH_BUCKET", "pow2")
    )
    # Structured observability (sparse_tpu.telemetry). Off by default:
    # every instrumentation site is a single attribute check when
    # disabled. When on, solver iterations, autotune probes and
    # structural comm volumes are recorded to a bounded in-memory ring
    # and appended as JSONL to results/axon/records.jsonl (the committed
    # hardware-evidence log bench.py already reads).
    telemetry: bool = field(
        default_factory=lambda: _env_bool("SPARSE_TPU_TELEMETRY", False)
    )
    # Empty string = the default sink (results/axon/records.jsonl next to
    # the repo root). A relative override resolves against the cwd.
    telemetry_path: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_TELEMETRY_PATH", "")
    )
    telemetry_ring: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_TELEMETRY_RING", 4096), 16)
    )
    # Fault injection (sparse_tpu.resilience.faults): a seeded chaos spec
    # ("fault:site:k=v,..." clauses, ";"-separated — docs/resilience.md).
    # Empty = off: every hook is a single module-boolean check and no
    # wrapper is installed anywhere (traced programs byte-identical).
    faults: str = field(default_factory=lambda: _env_str("SPARSE_TPU_FAULTS", ""))
    # Persistent plan-cache tier (sparse_tpu.vault): directory holding
    # verified prepared-operator artifacts + the warm-start manifest.
    # Empty = disk tier off (in-process weak-ref LRU only). Every read
    # is verify-then-load with quarantine on failure; every write is
    # atomic (tmp + fsync + rename) — docs/performance.md.
    vault: str = field(default_factory=lambda: _env_str("SPARSE_TPU_VAULT", ""))
    vault_cap_mb: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_VAULT_CAP_MB", 512), 1)
    )
    # Serving-path persistent XLA compilation cache dir: when set,
    # SolveSession/bench call utils.enable_compilation_cache(dir) so the
    # compiled-executable tier survives restarts alongside the vault.
    compile_cache: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_COMPILE_CACHE", "")
    )
    # Mesh-sharded serving tier (sparse_tpu.fleet): '' = off (the
    # single-device SolveSession path, byte-identical programs);
    # 'auto' = both strategies ('batch' shards the bucket's lane stacks
    # across the mesh batch axis, 'row' routes oversized single systems
    # through DistCSR/dist_cg); 'batch' / 'row' restrict to one. Truthy
    # spellings ('1', 'on', 'true') mean 'auto'.
    fleet: str = field(default_factory=lambda: _env_str("SPARSE_TPU_FLEET", ""))
    # Minimum real lanes in a bucket before batch-sharding pays: below
    # this the pad waste (bucket rounds up to a mesh multiple) and the
    # per-iteration all-converged psum outweigh the parallel matvec.
    fleet_min_b: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_FLEET_MIN_B", 8), 1)
    )
    # Incident flight recorder (telemetry/_flight.py): a directory (or a
    # truthy spelling for the default results/axon/incidents) enables
    # postmortem bundle capture on watchdog alert transitions. Empty
    # (default) = off: the alert hook is a single settings check and
    # nothing ever touches the filesystem.
    flight: str = field(default_factory=lambda: _env_str("SPARSE_TPU_FLIGHT", ""))
    # Max incident bundles kept on disk (oldest pruned past it) and the
    # min seconds between captures (alerts inside the window are counted
    # as suppressed, not written).
    flight_max: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_FLIGHT_MAX", 8), 1)
    )
    # Sampled timed-dispatch device profiling (batch/service.py): every
    # Nth bucket dispatch splits its solve wall clock into host (dispatch
    # returns) vs device (block_until_ready) time, feeding the always-on
    # batch.program_device_ms{program} histogram and the batch.dispatch
    # event's device_ms/host_ms fields. 0 (default) = off: the dispatch
    # path takes no extra timestamps and emits no extra fields — the
    # compiled programs are identical either way (sampling is host-side
    # only and never enters a trace).
    profile_every: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_PROFILE_EVERY", 0), 0)
    )
    # Streaming-dispatch window (batch/service.py, ISSUE 13): how many
    # bucket programs may be in flight on the device before dispatch
    # retires (blocks on) the oldest. 1 = the classic synchronous path,
    # bit-identical dispatch/retire interleaving to the pre-pipeline
    # session (pinned by tests/test_pipeline.py); 2 (default) =
    # double-buffering — the host packs/uploads bucket N+1 while the
    # device solves bucket N. The compiled programs are identical at
    # every setting; only host-side scheduling changes.
    inflight: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_INFLIGHT", 2), 1)
    )
    # Batched preconditioner policy (sparse_tpu.precond, ISSUE 14):
    # '' / 'off' = none (the historic unpreconditioned path, program
    # keys and jaxprs unchanged); 'auto' picks per (pattern, solver,
    # bucket, dtype); or force one kind: 'jacobi' | 'bjacobi' | 'ilu0' |
    # 'ic0' | 'cheby' | 'neumann'. Per-session (SolveSession(precond=))
    # and per-ticket (submit(precond=)) overrides win over the env.
    precond: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_PRECOND", "")
    )
    # Block size of the pattern-shared block-Jacobi factors (diagonal
    # blocks extracted once per SparsityPattern, batched dense inverses
    # over the (B, blocks, bs, bs) stack).
    precond_block: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_PRECOND_BLOCK", 4), 1)
    )
    # Chow-Patel fixed-point sweeps of the batched ILU(0)/IC(0) numeric
    # factorization (data-independent count: the factorization stays one
    # straight-line jit program).
    precond_sweeps: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_PRECOND_SWEEPS", 3), 1)
    )
    # Jacobi-Richardson sweeps of the batched triangular application
    # (approximate L/U solves with no data-dependent control flow).
    precond_tri_sweeps: int = field(
        default_factory=lambda: max(
            _env_int("SPARSE_TPU_PRECOND_TRI_SWEEPS", 4), 1
        )
    )
    # Degree of the polynomial (Chebyshev/Neumann) preconditioners.
    precond_degree: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_PRECOND_DEGREE", 4), 1)
    )
    # Mixed-precision serving policy (sparse_tpu.mixed, ISSUE 15):
    # '' / 'exact' = solve at the request dtype (the historic path,
    # program keys and jaxprs unchanged); 'auto' = f32 Krylov + f64
    # iterative refinement for f64 cg/bicgstab buckets; or force one
    # reduced policy: 'f32ir' | 'bf16ir'. Per-session
    # (SolveSession(dtype_policy=)) and per-ticket
    # (submit(dtype_policy=)) overrides win over the env.
    dtype_policy: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_DTYPE", "")
    )
    # IR loop geometry (mixed/ir.py): inner Krylov iterations per
    # refinement sweep (0 = auto from conv_test_iters), the static
    # max refinement sweeps, and the per-sweep inner residual-reduction
    # target eta (0 = per-policy default).
    ir_inner: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_IR_INNER", 0), 0)
    )
    ir_outer: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_IR_OUTER", 25), 1)
    )
    ir_eta: float = field(
        default_factory=lambda: max(_env_float("SPARSE_TPU_IR_ETA", 0.0), 0.0)
    )
    # Precond storage dtype under a reduced dtype policy (ISSUE 16):
    # '' / 'compute' = the historic behavior (M factorized/stored at the
    # inner sweep's compute dtype, program keys unchanged); 'storage' =
    # factors stored at the policy's reduced storage dtype with wide
    # accumulation — the precond x mixed compounding arm ('.W' key
    # suffix). Only meaningful on reduced-precision buckets with a
    # Jacobi/ILU preconditioner; degrades to 'compute' elsewhere.
    precond_dtype: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_PRECOND_DTYPE", "")
    )
    # Online policy tuner (sparse_tpu.autopilot, ISSUE 16): any truthy
    # spelling enables per-(pattern, bucket, SLO class) trial
    # scheduling over the default candidate grid. '' (default) = off:
    # no tuner object exists, every dispatch path, program key,
    # manifest and numeric is byte-identical to pre-autopilot behavior.
    autopilot: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_AUTOPILOT", "")
    )
    # Bounded exploration fraction: during exploration one in
    # round(1/epsilon) dispatches of a group is a measured experiment;
    # the rest serve the incumbent (best arm so far).
    autopilot_epsilon: float = field(
        default_factory=lambda: min(
            max(_env_float("SPARSE_TPU_AUTOPILOT_EPSILON", 0.25), 1e-3), 1.0
        )
    )
    # Observations per arm per successive-halving round (the trial
    # budget: rounds * trials experiments per surviving arm).
    autopilot_trials: int = field(
        default_factory=lambda: max(
            _env_int("SPARSE_TPU_AUTOPILOT_TRIALS", 2), 1
        )
    )
    # SLO guard: an experimental observation slower than
    # factor * slo_ms aborts its arm immediately — exploration never
    # blows a tenant's p95 by more than one bounded dispatch.
    autopilot_slo_factor: float = field(
        default_factory=lambda: max(
            _env_float("SPARSE_TPU_AUTOPILOT_SLO_FACTOR", 1.5), 1.0
        )
    )
    # Drift threshold: a pinned-arm observation slower than
    # factor * the decision's measured score counts a strike into the
    # watchdog-visible autopilot.drift_strikes counter.
    autopilot_drift: float = field(
        default_factory=lambda: max(
            _env_float("SPARSE_TPU_AUTOPILOT_DRIFT", 2.0), 1.0
        )
    )

    # -- continuous telemetry history (telemetry/_history.py, Axon v7) -----
    # A directory (or a truthy spelling for the default
    # results/axon/history) enables the background metrics sampler:
    # bounded in-memory rings + append-only on-disk segments with
    # multi-resolution rollups. Empty (default) = off: no sampler
    # thread exists, nothing touches the filesystem, and every serving
    # path is byte-identical (the gate is one attribute check).
    history: str = field(default_factory=lambda: _env_str("SPARSE_TPU_HISTORY", ""))
    # Segment directory override (wins over a path spelled in
    # SPARSE_TPU_HISTORY itself).
    history_dir: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_HISTORY_DIR", "")
    )
    # Committed-segment retention budget (MB): the rotation-time GC
    # deletes oldest-first past it.
    history_cap_mb: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_HISTORY_CAP_MB", 64), 1)
    )
    # Sampler scrape period (seconds).
    history_interval: float = field(
        default_factory=lambda: max(
            _env_float("SPARSE_TPU_HISTORY_INTERVAL", 1.0), 0.01
        )
    )

    # -- elastic mesh (sparse_tpu.fleet.elastic, ISSUE 20) -----------------
    # Live topology-change survival for fleet sessions: a MeshMonitor
    # revalidates the serving mesh on dispatch failure and on the
    # explicit session.remesh() verb. On by default — with no mesh
    # fault and no remesh() call the monitor is inert (one comparison
    # on paths that only run under faults/errors), so program keys,
    # jaxprs and host-sync counts stay byte-identical. '0' removes the
    # monitor entirely. No effect when SPARSE_TPU_FLEET is off.
    remesh: bool = field(
        default_factory=lambda: _env_bool("SPARSE_TPU_REMESH", True)
    )
    # Flap guard budget: executed remeshes a session allows before the
    # monitor latches (fleet.remesh_latched), the policy pins to the
    # single-device strategy and no further migration is attempted.
    remesh_retries: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_REMESH_RETRIES", 3), 0)
    )

    # -- streaming ingestion data plane (sparse_tpu.ingest, ISSUE 18) ------
    # Onboarding admission bound: max arrivals queued on the background
    # onboarder before admission control engages (the ingest analog of
    # SPARSE_TPU_BATCH_MAX's queue depth role on the solve pipeline).
    ingest_depth: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_INGEST_DEPTH", 16), 1)
    )
    # What happens AT the bound: 'block' (default) backpressures the
    # submitting thread until the worker frees a slot; 'reject' raises
    # IngestAdmissionError immediately (load-shedding posture).
    ingest_admission: str = field(
        default_factory=lambda: _env_str("SPARSE_TPU_INGEST_ADMISSION", "block")
    )
    # Onboarding attempts per arrival beyond the first: a failed parse/
    # sort/onboard (io faults, torn vault artifacts) retries this many
    # times before the ticket fails — serving is never affected either
    # way (the worker owns every retry).
    ingest_retries: int = field(
        default_factory=lambda: max(_env_int("SPARSE_TPU_INGEST_RETRIES", 1), 0)
    )


settings = Settings()
