"""Rydberg-atom MIS Hamiltonian construction.

Reference analog: ``sparse/quantum.py`` (595 LoC) + ``src/quantum/`` (675 LoC
C++): enumerate independent sets of a unit-disk graph level-by-level with a
bitset BFS (``quantum.cc:27-112``), then build the driver Hamiltonian whose
off-diagonal entries connect each size-k independent set to its k size-(k-1)
subsets (``quantum.cc:119-210``), and a diagonal MIS-cost Hamiltonian. The
time evolution y' = -i H y runs through ``sparse_tpu.integrate.solve_ivp``
with complex dtypes (SURVEY §3.5).

TPU-native redesign: the reference's per-element C++ loops over
``IntSet<N,T>`` bitsets become whole-level vectorized numpy bitset math
(sets are [S, W] uint64 words; expansion is one nonzero + two gathers + a
bitwise-and per level), with an optional native C++ kernel (``src/quantum``)
for the expansion inner loop. The group-wise negate-sort-negate trick the
reference needs to keep Legion memories bounded (quantum.py:39-243)
disappears: the symmetric Hamiltonian is built as U + U^T through the
standard sort-based COO->CSR path on device.
"""

from __future__ import annotations

import numpy as np

from .csr import csr_array


# ---------------------------------------------------------------------------
# Bitset helpers (the IntSet<N, T> analog)
# ---------------------------------------------------------------------------
def _num_words(n: int) -> int:
    return max((n + 63) // 64, 1)


def _bit_planes(n: int):
    """[n, W] uint64: row u has only bit u set."""
    W = _num_words(n)
    out = np.zeros((n, W), dtype=np.uint64)
    u = np.arange(n)
    out[u, u // 64] = np.uint64(1) << (u % 64).astype(np.uint64)
    return out


def _bits_to_bool(sets: np.ndarray, n: int) -> np.ndarray:
    """[S, W] uint64 -> [S, n] bool membership matrix."""
    S, W = sets.shape
    shifts = np.arange(64, dtype=np.uint64)
    expanded = (sets[:, :, None] >> shifts[None, None, :]) & np.uint64(1)
    return expanded.reshape(S, W * 64)[:, :n].astype(bool)


def popcount(sets: np.ndarray) -> np.ndarray:
    """Per-set cardinality (SETS_TO_SIZES analog, quantum.cc:217-228)."""
    return np.bitwise_count(sets).sum(axis=1).astype(np.int64)


def _adjacency(graph) -> np.ndarray:
    """Accept an nx.Graph or a dense 0/1 adjacency matrix."""
    if hasattr(graph, "number_of_nodes"):
        import networkx as nx

        return np.asarray(nx.to_numpy_array(graph)) != 0
    a = np.asarray(graph) != 0
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError("adjacency matrix must be square")
    return a


def _comp_gt_masks(adj: np.ndarray) -> np.ndarray:
    """[n, W]: row u = bitmask of {v : v > u, (u,v) not an edge} — the
    candidate-extension sets in the complement graph (quantum.cc:41-49)."""
    n = adj.shape[0]
    comp = ~adj
    np.fill_diagonal(comp, False)
    gt = np.triu(np.ones((n, n), dtype=bool), k=1)
    allowed = comp & gt  # [n, n] bool
    W = _num_words(n)
    out = np.zeros((n, W), dtype=np.uint64)
    planes = _bit_planes(n)  # [n, W]
    # bit planes are disjoint single-bit rows, so integer sum == bitwise OR
    for w in range(W):
        out[:, w] = allowed.astype(np.uint64) @ planes[:, w]
    return out


# ---------------------------------------------------------------------------
# Level-by-level enumeration (ENUMERATE_INDEP_SETS analog)
# ---------------------------------------------------------------------------
def enumerate_independent_sets(
    graph, k: int, prev_sets=None, prev_queues=None, comp_gt=None
):
    """All independent sets of size k, given the size-(k-1) level.

    Returns (sets [S_k, W] uint64, queues [S_k, W] uint64). The BFS order
    matches the reference (sets expanded in (parent, extension-node) order,
    quantum.cc:89-108), so state indices line up. Level-driving callers pass
    the precomputed ``comp_gt`` masks so the O(n^2) complement-graph build
    runs once, not once per level.
    """
    from . import native as _native

    native = _native.lib() is not None
    if k <= 0:
        raise ValueError("k must be positive")
    if comp_gt is None:
        adj = _adjacency(graph)
        n = adj.shape[0]
        comp_gt = _comp_gt_masks(adj)
    else:
        n = comp_gt.shape[0]
    if k == 1:
        return _bit_planes(n), comp_gt.copy()
    if prev_sets is None:
        sets, queues = _bit_planes(n), comp_gt.copy()
        for kk in range(2, k + 1):
            sets, queues = _expand_level(sets, queues, comp_gt, n, native)
        return sets, queues
    return _expand_level(prev_sets, prev_queues, comp_gt, n, native)


def _expand_level(sets, queues, comp_gt, n, native=False):
    if native:
        from . import native as _native

        return _native.expand_level(sets, queues, comp_gt, n)
    B = _bits_to_bool(queues, n)  # [S, n] candidate-extension membership
    i_idx, u_idx = np.nonzero(B)  # row-major: parent order, then node order
    planes = _bit_planes(n)
    new_sets = sets[i_idx] | planes[u_idx]
    new_queues = queues[i_idx] & comp_gt[u_idx]
    return new_sets, new_queues


def sets_to_sizes(queues, graph=None) -> np.ndarray:
    return popcount(queues)


def independence_polynomial(graph):
    """[#independent sets of size k for k = 0..] (quantum.py:447)."""
    adj = _adjacency(graph)
    n = adj.shape[0]
    comp_gt = _comp_gt_masks(adj)
    ip = [1]
    sets = queues = None
    for k in range(1, n + 1):
        sets, queues = enumerate_independent_sets(adj, k, sets, queues, comp_gt)
        if sets.shape[0] == 0:
            break
        ip.append(int(sets.shape[0]))
        if popcount(queues).sum() == 0:
            break
    return ip


# ---------------------------------------------------------------------------
# Set-index lookup (the std::map<set, index> of quantum.cc:163-167)
# ---------------------------------------------------------------------------
def _lex_order(sets: np.ndarray) -> np.ndarray:
    return np.lexsort(sets.T[::-1])


def _lookup(sorted_sets: np.ndarray, queries: np.ndarray) -> np.ndarray:
    """Indices into sorted_sets for each query row (all must be present)."""
    W = sorted_sets.shape[1]
    dt = [("", np.uint64)] * W
    sv = np.ascontiguousarray(sorted_sets).view(dt).ravel()
    qv = np.ascontiguousarray(queries).view(dt).ravel()
    pos = np.searchsorted(sv, qv)
    if not np.array_equal(sv[pos], qv):
        raise RuntimeError("subset lookup failed: predecessor set missing")
    return pos


# ---------------------------------------------------------------------------
# Hamiltonian drivers (quantum.py:27-403)
# ---------------------------------------------------------------------------
class HamiltonianDriver:
    """Off-diagonal transition Hamiltonian over the independent-set basis.

    State ordering matches the reference: original enumeration index o
    (null state 0, then size-1 sets, ...) is flipped to nstates-1-o
    (quantum.py:258-276), so the all-ones ground state sits last.
    """

    def __init__(
        self, energies: tuple = (1,), graph=None, dtype=np.complex64,
        mesh=None, dist_shards=None,
    ):
        """``mesh``: optional 2-D device mesh; routes the subset lookup
        (the CREATE_HAMILTONIANS inner loop) through the 2-D replication
        grid of reference quantum.py:86-107 — grid-x tiles the current
        level's queries, grid-y the prior sets (parallel.grid2d.lookup_2d).
        Default None keeps the single-host searchsorted path.

        ``dist_shards``: shard count for the DISTRIBUTED build path — the
        per-level group sorts run as the mesh samplesort
        (``parallel.sort.dist_sort_host``, the reference's SORT_BY_KEY +
        alltoallv inside the quantum build, quantum.py:199-243) and the
        final COO->CSR assembly as ``coo_to_csr_distributed``. This is
        the >=1e5-state scaling path (VERDICT r2 #10)."""
        self.energies = energies
        self._mesh2d = mesh
        self._dist_shards = dist_shards
        adj = _adjacency(graph)
        n = adj.shape[0]
        self.ip = [1]
        rows_u, cols_u = [], []
        sets = queues = None  # the size-(k-1) level
        offset, prev_offset = 1, 0
        planes = _bit_planes(n)
        comp_gt = _comp_gt_masks(adj)
        for k in range(1, n + 1):
            new_sets, new_queues = enumerate_independent_sets(
                adj, k, sets, queues, comp_gt
            )
            if new_sets.shape[0] == 0:
                break
            S = new_sets.shape[0]
            self.ip.append(S)
            if k == 1:
                # predecessors of singletons: the null state 0
                rows_u.append(offset + np.arange(S, dtype=np.int64))
                cols_u.append(np.zeros(S, dtype=np.int64))
            else:
                # each set links to its k subsets of size k-1
                Bm = _bits_to_bool(new_sets, n)
                i_idx, node_idx = np.nonzero(Bm)
                removed = new_sets[i_idx] & ~planes[node_idx]
                order = self._group_order(sets)
                if self._mesh2d is not None:
                    from .parallel.grid2d import lookup_2d

                    pos = lookup_2d(sets[order], removed, self._mesh2d)
                else:
                    pos = _lookup(sets[order], removed)
                pred_idx = prev_offset + order[pos]
                rows_u.append(offset + i_idx.astype(np.int64))
                cols_u.append(pred_idx.astype(np.int64))
            sets, queues = new_sets, new_queues
            prev_offset = offset
            offset += S
            if popcount(queues).sum() == 0:
                break
        self.nstates = int(np.sum(self.ip))
        rows = np.concatenate(rows_u) if rows_u else np.zeros(0, np.int64)
        cols = np.concatenate(cols_u) if cols_u else np.zeros(0, np.int64)
        # flip to the reference's final ordering
        rows = (self.nstates - 1) - rows
        cols = (self.nstates - 1) - cols
        vals = np.ones(rows.shape[0], dtype=dtype)
        if self._dist_shards:
            from .parallel.sort import coo_to_csr_distributed

            upper = coo_to_csr_distributed(
                rows, cols, vals, (self.nstates, self.nstates),
                self._dist_shards,
            )
        else:
            from .coo import coo_array

            upper = coo_array(
                (vals, (rows, cols)), shape=(self.nstates, self.nstates)
            ).tocsr()
        self._hamiltonian = upper + upper.T.tocsr()

    def _group_order(self, sets):
        """Lex order of the prior level's bitsets — the reference's
        group-wise sort (quantum.py:199-243). With ``dist_shards`` and
        single-word sets (n <= 64, every benchmark shape) it runs as the
        mesh samplesort; multi-word sets keep the host lexsort."""
        if self._dist_shards and sets.shape[1] == 1 and sets.shape[0] > 1:
            import jax

            if jax.config.jax_enable_x64:  # uint64 keys need x64 on device
                from .parallel.sort import dist_sort_host

                _, (order,) = dist_sort_host(
                    sets[:, 0],
                    (np.arange(sets.shape[0], dtype=np.int64),),
                    self._dist_shards,
                )
                return np.asarray(order)
        return _lex_order(sets)

    @property
    def hamiltonian(self) -> csr_array:
        if self.energies[0] == 1:
            return self._hamiltonian
        return self._hamiltonian * self.energies[0]


class HamiltonianMIS:
    """Diagonal MIS-cost Hamiltonian (quantum.py:302-403)."""

    def __init__(self, graph=None, poly=None, energies=(1, 1), dtype=np.complex64):
        if energies == (1, 1):
            energies = (1,)
        self.energies = energies
        adj = _adjacency(graph)
        self.n = adj.shape[0]
        self.optimization = "max"
        self._is_diagonal = True
        if poly is None:
            poly = independence_polynomial(adj)
        self.nstates = int(np.sum(poly))
        self.dtype = dtype
        levels = np.arange(len(poly))
        C = np.flip(np.repeat(levels, poly)).astype(dtype)
        enum_states = np.arange(self.nstates)
        self._hamiltonian = csr_array(
            (C, (enum_states, enum_states)),
            shape=(self.nstates, self.nstates),
            dtype=dtype,
        )

    @property
    def hamiltonian(self) -> csr_array:
        if self.energies[0] == 1:
            return self._hamiltonian
        return self._hamiltonian * self.energies[0]

    @property
    def _diagonal_hamiltonian(self):
        return np.asarray(self.hamiltonian.data).reshape(-1, 1)

    @property
    def optimum(self):
        return np.max(self._diagonal_hamiltonian.real)

    @property
    def minimum_energy(self):
        return np.min(self._diagonal_hamiltonian.real)

    def cost_function(self, state):
        """<s|C|s> — accepts [n] or [n, 1] states."""
        state = np.asarray(state).ravel()
        diag = self._diagonal_hamiltonian.ravel()
        return float(np.real(np.vdot(state, diag * state)))

    def optimum_overlap(self, state):
        """sum_i <s|opt_i><opt_i|s> over the optimum states."""
        state = np.asarray(state).ravel()
        diag = self._diagonal_hamiltonian.ravel()
        mask = (diag == self.optimum).astype(float)
        return float(np.real(np.vdot(state, mask * state)))

    def approximation_ratio(self, state):
        return self.cost_function(state) / self.optimum


# reference-compatible aliases
LegateHamiltonianDriver = HamiltonianDriver
LegateHamiltonianMIS = HamiltonianMIS
