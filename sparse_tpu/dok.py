"""DOK (dictionary-of-keys) format — incremental host-side construction.

Beyond the reference's class surface (its coverage layer lists todok as a
gap too): scipy users build matrices entry-by-entry in DOK, then convert
once for compute. TPU-native framing: DOK is a HOST staging format — a
plain ``{(i, j): value}`` dict with O(1) mutation — whose ``tocsr``/
``tocoo`` does the single host->device conversion; no device math runs in
DOK itself (scipy's own DOK arithmetic densifies or converts internally).
"""

from __future__ import annotations

import numpy as np

from .base import SparseArray


class dok_array(SparseArray):
    format = "dok"
    ndim = 2

    def __init__(self, arg1, shape=None, dtype=None):
        if isinstance(arg1, tuple) and len(arg1) == 2 and all(
            isinstance(s, (int, np.integer)) for s in arg1
        ):
            self._shape = (int(arg1[0]), int(arg1[1]))
            self._dtype = np.dtype(dtype or np.float64)
            self._d = {}
            return
        if isinstance(arg1, SparseArray):
            # canonical COO: raw coo_array may hold duplicates, which a
            # dict comprehension would last-write instead of summing
            coo = arg1._canonical_coo()
            rows, cols, vals = (
                np.asarray(coo.row),
                np.asarray(coo.col),
                np.asarray(coo.data),
            )
            self._shape = coo.shape
        else:
            dense = np.asarray(arg1)
            if dense.ndim != 2:
                raise ValueError("dok_array expects a 2-D input")
            rows, cols = np.nonzero(dense)
            vals = dense[rows, cols]
            self._shape = dense.shape
        if shape is not None:
            shape = tuple(int(s) for s in shape)
            if rows.size and (
                int(rows.max()) >= shape[0] or int(cols.max()) >= shape[1]
            ):
                raise ValueError(
                    f"shape {shape} cannot hold entries of shape {self._shape}"
                )
            self._shape = shape
        self._dtype = np.dtype(dtype or vals.dtype)
        self._d = {
            (int(r), int(c)): self.dtype.type(v)
            for r, c, v in zip(rows, cols, vals)
            if v != 0
        }

    # ---- dict-like surface ----------------------------------------------
    @property
    def nnz(self) -> int:
        return len(self._d)

    def __len__(self):
        return len(self._d)

    def keys(self):
        return self._d.keys()

    def values(self):
        return self._d.values()

    def items(self):
        return self._d.items()

    def __iter__(self):
        return iter(self._d)

    def __contains__(self, key):
        return key in self._d

    def get(self, key, default=0.0):
        return self._d.get((int(key[0]), int(key[1])), default)

    def _check_key(self, key):
        if not (isinstance(key, tuple) and len(key) == 2):
            raise IndexError("dok indices must be (row, col) pairs")
        i, j = int(key[0]), int(key[1])
        m, n = self.shape
        if i < 0:
            i += m
        if j < 0:
            j += n
        if not (0 <= i < m and 0 <= j < n):
            raise IndexError(f"index {key} out of range for shape {self.shape}")
        return i, j

    def __getitem__(self, key):
        return self._d.get(self._check_key(key), self.dtype.type(0))

    def __setitem__(self, key, value):
        k = self._check_key(key)
        if value == 0:
            self._d.pop(k, None)
        else:
            self._d[k] = self.dtype.type(value)

    def __delitem__(self, key):
        del self._d[self._check_key(key)]

    # ---- conversions -----------------------------------------------------
    def tocoo(self):
        from .coo import coo_array

        if self._d:
            ks = np.array(list(self._d.keys()), dtype=np.int64)
            vs = np.fromiter(self._d.values(), dtype=self.dtype, count=len(self._d))
            rows, cols = ks[:, 0], ks[:, 1]
        else:
            rows = cols = np.zeros(0, dtype=np.int64)
            vs = np.zeros(0, dtype=self.dtype)
        return coo_array((vs, (rows, cols)), shape=self.shape)

    def tocsr(self):
        return self.tocoo().tocsr()

    def tocsc(self):
        return self.tocoo().tocsc()

    def todia(self):
        return self.tocoo().todia()

    def todok(self):
        return self

    def toarray(self):
        out = np.zeros(self.shape, dtype=self.dtype)
        for (i, j), v in self._d.items():
            out[i, j] = v
        return out

    def copy(self):
        new = dok_array(self.shape, dtype=self.dtype)
        new._d = dict(self._d)
        return new

    # SparseArray's generic hooks (neg/abs/astype/conj run through these)
    def _data_array(self):
        return np.fromiter(
            self._d.values(), dtype=self.dtype, count=len(self._d)
        )

    def _with_data(self, data):
        data = np.asarray(data)
        new = dok_array(self.shape, dtype=data.dtype)
        new._d = {
            k: data.dtype.type(v) for k, v in zip(self._d.keys(), data)
        }
        return new

    def conjugate(self):
        new = self.copy()
        if np.issubdtype(self.dtype, np.complexfloating):
            new._d = {k: np.conj(v) for k, v in self._d.items()}
        return new

    conj = conjugate

    def transpose(self):
        new = dok_array((self.shape[1], self.shape[0]), dtype=self.dtype)
        new._d = {(j, i): v for (i, j), v in self._d.items()}
        return new

    @property
    def T(self):
        return self.transpose()

    # ---- math delegates to CSR (scipy's own DOK math converts too) -------
    def _delegate(self):
        return self.tocsr()

    def __matmul__(self, other):
        return self._delegate() @ other

    def dot(self, other):
        return self._delegate().dot(other)

    def __add__(self, other):
        other = other._delegate() if isinstance(other, dok_array) else other
        return self._delegate() + other

    def __mul__(self, other):
        return self._delegate() * other

    def multiply(self, other):
        other = other._delegate() if isinstance(other, dok_array) else other
        return self._delegate().multiply(other)

    def sum(self, axis=None):
        return self._delegate().sum(axis=axis)

    def __repr__(self):
        return (
            f"<{self.shape[0]}x{self.shape[1]} DOK array, nnz={self.nnz},"
            f" dtype={self.dtype}>"
        )

    __str__ = __repr__
