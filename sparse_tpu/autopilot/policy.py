"""Autopilot: the online policy tuner that closes the telemetry ->
configuration loop (ISSUE 16, ROADMAP item 2).

PRs 13-15 gave every request a policy vector — solver x precond kind x
dtype policy x precond storage dtype x inflight depth — and Axon
measures every choice, yet every knob was still a static env/config
value. This module turns the measurements back into configuration: a
per-(pattern, bucket, SLO class) trial scheduler runs *cheap measured
experiments* over a declared candidate grid on live traffic, converges
to a pinned :class:`PolicyDecision`, persists it as a vault artifact so
a restarted process serves tuned from the first request, and re-opens
exploration when the watchdog flags drift or the mixed-precision
promote rate spikes.

Scheduling (deterministic — no RNG, so runs replay exactly):

* **Bounded epsilon-greedy**: during exploration only every
  ``round(1/epsilon)``-th dispatch of a group is an experiment; the
  rest serve the incumbent (best arm so far), so exploration cost is a
  bounded fraction of traffic and a tenant's p95 rides the incumbent.
* **Successive halving**: experiments cycle round-robin over the
  surviving arms; once every survivor has ``trials`` fresh
  observations the worst half (by median score) is eliminated. One
  survivor = convergence.
* **SLO guard**: an experimental observation slower than
  ``slo_factor x slo_ms`` aborts its arm immediately (``autopilot.
  abort``) — a bad candidate costs at most one over-budget dispatch
  per group, never a tail.

Scoring uses Axon's measured numbers for the dispatch: the sampled
``device_ms`` when the profiler took one, else the solve wall clock,
per real lane; unconverged or promoted buckets score infinitely bad.

Drift reopening (the loop stays closed *after* convergence):

* every incumbent observation worse than ``drift x`` the pinned
  decision's score counts a strike into the always-on
  ``autopilot.drift_strikes`` counter — :func:`drift_rule` packages
  that counter as a watchdog rule, and any watchdog alert transition
  re-opens every converged group (``autopilot.reopen``);
* a ``mixed.promote`` under a pinned reduced-precision arm re-opens
  its group directly (the promote listener on
  :class:`sparse_tpu.mixed.DtypePolicy`);
* SLO breaches under the pinned decision count into
  ``autopilot.slo_breaches`` (another watchdog-visible series).

Persistence: decisions are vault artifacts (kind
``autopilot_policy``), keyed by content — pattern fingerprint, solver,
bucket, dtype, SLO class, mesh fingerprint and the *grid fingerprint*
(a changed candidate grid invalidates stored decisions). The tuned
bucket programs themselves replay through the ordinary warm-start
manifest (``note_program`` records the arm's precond/dtype-policy/
precond-dtype key parts), so a restart is tuned AND compiled from the
first request.
"""

from __future__ import annotations

import json
import weakref

import numpy as np

from .. import telemetry
from ..config import settings
from ..telemetry import _metrics

#: the default candidate grid for f64 CG serving traffic: the session's
#: static policy as the control arm, the two Jacobi preconditioners,
#: the f32 iterative-refinement fast path, the precond x mixed
#: combination, and the compounding arm that ALSO factorizes/applies
#: the preconditioner in the reduced storage dtype (ISSUE 16's
#: explicitly-open work — today the two wins don't multiply).
DEFAULT_GRID = (
    {},
    {"precond": "jacobi"},
    {"precond": "bjacobi"},
    {"dtype_policy": "f32ir"},
    {"precond": "bjacobi", "dtype_policy": "f32ir"},
    {"precond": "bjacobi", "dtype_policy": "f32ir",
     "precond_dtype": "storage"},
)

#: arm-spec keys the trial scheduler understands (anything else is a
#: declaration error, raised at construction — a typo'd grid must not
#: silently explore nothing)
ARM_KEYS = ("solver", "precond", "dtype_policy", "precond_dtype",
            "inflight")

_OFF = ("", "0", "off", "false", "no", "none")


def slo_class(slo_ms) -> str:
    """Tenant SLO class of a session latency objective: the grouping
    axis that keeps a latency-sensitive tenant's tuning separate from
    batch traffic over the same pattern (their optimal arms differ —
    exploration budgets too)."""
    if slo_ms is None:
        return "none"
    s = float(slo_ms)
    if s <= 100.0:
        return "tight"
    if s <= 1000.0:
        return "standard"
    return "relaxed"


def arm_id(spec: dict) -> str:
    """Stable human-readable arm label (telemetry / report join key):
    ``'static'`` for the empty control arm, else the non-default parts
    joined in declaration order."""
    parts = [
        f"{k}={spec[k]}" for k in ARM_KEYS if spec.get(k) not in (None, "")
    ]
    return "+".join(parts) if parts else "static"


def _canonical_spec(spec: dict) -> dict:
    """Validate one candidate arm at declaration time."""
    from .. import mixed as mixed_mod
    from .. import precond as precond_mod

    out = {}
    for k, v in dict(spec).items():
        if k not in ARM_KEYS:
            raise ValueError(
                f"unknown arm key {k!r} (must be one of {ARM_KEYS})"
            )
        if v in (None, ""):
            continue
        if k == "precond":
            v = precond_mod.canonical_kind(v)
        elif k == "dtype_policy":
            v = mixed_mod.canonical_policy(v)
        elif k == "precond_dtype":
            v = precond_mod.canonical_precond_dtype(v)
        elif k == "inflight":
            v = max(int(v), 1)
        elif k == "solver":
            v = str(v)
        out[k] = v
    return out


def grid_fingerprint(grid) -> str:
    """Content fingerprint of a candidate grid — part of every
    decision's vault key, so a changed grid can never serve a stale
    decision."""
    from ..vault import _codecs

    return _codecs.digest(
        "apgrid", json.dumps([dict(sorted(g.items())) for g in grid],
                             sort_keys=True),
    )


class PolicyDecision:
    """One pinned tuning outcome: the winning arm, its measured score
    (ms per lane, lower better) and how much evidence backed it."""

    __slots__ = ("spec", "score", "trials", "restored")

    def __init__(self, spec: dict, score: float, trials: int,
                 restored: bool = False):
        self.spec = dict(spec)
        self.score = float(score)
        self.trials = int(trials)
        self.restored = bool(restored)

    @property
    def arm(self) -> str:
        return arm_id(self.spec)

    def to_meta(self) -> dict:
        return {"spec": dict(self.spec), "score": self.score,
                "trials": self.trials}

    @classmethod
    def from_meta(cls, meta: dict) -> "PolicyDecision":
        return cls(dict(meta["spec"]), float(meta["score"]),
                   int(meta["trials"]), restored=True)


class _Arm:
    __slots__ = ("spec", "scores", "dead")

    def __init__(self, spec: dict):
        self.spec = spec
        self.scores: list = []
        self.dead = False

    def median(self) -> float:
        if not self.scores:
            return float("inf")
        return float(np.median(self.scores))


class _Group:
    """Per-(pattern, solver, bucket, dtype, SLO class) tuning state."""

    __slots__ = ("gid", "arms", "decision", "seq", "next_arm", "round",
                 "strikes", "vault_key", "noted")

    def __init__(self, gid: str, grid):
        self.gid = gid
        self.arms = [_Arm(dict(g)) for g in grid]
        self.decision: PolicyDecision | None = None
        self.seq = 0  # dispatch counter (the deterministic epsilon clock)
        self.next_arm = 0  # round-robin cursor over live arms
        self.round = 0  # successive-halving rounds completed
        self.strikes = 0  # consecutive drifted incumbent observations
        self.vault_key: str | None = None
        self.noted = False

    def live(self) -> list:
        return [a for a in self.arms if not a.dead]

    def best(self) -> _Arm:
        live = self.live() or self.arms
        return min(live, key=lambda a: a.median())


# -- module-level drift plumbing (process-global, like watchdog hooks) ------
_LIVE: "weakref.WeakSet" = weakref.WeakSet()
_HOOKED = {"watchdog": False, "promote": False}


def _on_alert(transition: dict) -> None:
    """The watchdog drift hook: ANY rule's ok -> firing transition
    re-opens exploration in every live autopilot (drift in the serving
    system invalidates what was measured before it)."""
    for ap in list(_LIVE):
        ap.reopen_all(reason=f"watchdog:{transition.get('rule', '?')}")


def _on_promote(**kw) -> None:
    """The mixed-precision promote listener: a promote rung firing
    means a reduced-precision policy went anomalous — any group pinned
    to a reduced arm re-opens (its measurements predate the anomaly)."""
    for ap in list(_LIVE):
        ap.reopen_reduced(reason=f"promote:{kw.get('reason', '?')}")


def _install_hooks() -> None:
    if not _HOOKED["watchdog"]:
        from ..telemetry import _watchdog

        _watchdog.add_alert_hook(_on_alert)
        _HOOKED["watchdog"] = True
    if not _HOOKED["promote"]:
        from ..mixed import policy as mixed_policy

        mixed_policy.add_promote_listener(_on_promote)
        _HOOKED["promote"] = True


def drift_rule(threshold: int = 1):
    """A watchdog rule over the always-on ``autopilot.drift_strikes``
    counter: fires when at least ``threshold`` strikes land in one
    evaluation window — the wiring that makes drift reopening an
    *alerting* path (flight-recorder capture and all) instead of a
    silent internal transition. Add it to a Watchdog's rule list; the
    alert transition itself re-opens exploration through the
    process-global hook."""
    from ..telemetry import _watchdog

    counter = _metrics.counter(
        "autopilot.drift_strikes",
        help="incumbent observations slower than drift x the pinned "
        "decision score",
    )
    value = _watchdog._windowed_delta(lambda: counter.value)
    return _watchdog.Rule(
        "autopilot_drift", value, trigger=float(threshold) - 0.5,
        op=">", severity="warn", clear=0.0,
    )


class Autopilot:
    """The per-session (shareable) trial scheduler.

    Parameters
    ----------
    grid : candidate arm specs (dicts over :data:`ARM_KEYS`); default
        :data:`DEFAULT_GRID`.
    epsilon : bounded exploration fraction — during exploration one in
        ``round(1/epsilon)`` dispatches is an experiment (default
        ``settings.autopilot_epsilon``).
    trials : observations per arm per successive-halving round
        (default ``settings.autopilot_trials``).
    slo_factor : the SLO guard — an experiment slower than
        ``slo_factor x slo_ms`` aborts its arm (default
        ``settings.autopilot_slo_factor``).
    drift : incumbent regression factor that counts a drift strike
        (default ``settings.autopilot_drift``).
    """

    def __init__(self, grid=None, epsilon: float | None = None,
                 trials: int | None = None,
                 slo_factor: float | None = None,
                 drift: float | None = None):
        grid = DEFAULT_GRID if grid is None else tuple(grid)
        self.grid = tuple(_canonical_spec(g) for g in grid)
        if not self.grid:
            raise ValueError("autopilot grid must declare at least one arm")
        eps = float(
            settings.autopilot_epsilon if epsilon is None else epsilon
        )
        self.period = max(int(round(1.0 / max(min(eps, 1.0), 1e-3))), 1)
        self.trials = max(
            int(settings.autopilot_trials if trials is None else trials), 1
        )
        self.slo_factor = float(
            settings.autopilot_slo_factor if slo_factor is None
            else slo_factor
        )
        self.drift = float(
            settings.autopilot_drift if drift is None else drift
        )
        self._grid_fp: str | None = None
        self._groups: dict = {}
        _LIVE.add(self)
        _install_hooks()

    @classmethod
    def resolve(cls, autopilot=None):
        """The ``SolveSession`` constructor hook: ``autopilot`` may be
        a ready :class:`Autopilot`, ``True`` / a truthy mode string
        (= default grid), ``False`` (= off regardless of env), or
        ``None`` (= ``SPARSE_TPU_AUTOPILOT``). Returns ``None`` when
        off — the session then carries no tuner and every code path is
        byte-identical to pre-autopilot behavior."""
        if isinstance(autopilot, cls):
            return autopilot
        if autopilot is None:
            autopilot = settings.autopilot
        if autopilot is False:
            return None
        if autopilot is True:
            return cls()
        if str(autopilot).strip().lower() in _OFF:
            return None
        return cls()

    # -- group resolution ---------------------------------------------------
    def _gid(self, pattern, solver: str, bucket: int, dtype,
             slo_ms) -> str:
        return (
            f"{pattern.fingerprint[2][:12]}.{solver}.B{int(bucket)}."
            f"{np.dtype(dtype).str}.{slo_class(slo_ms)}"
        )

    def _grid_fingerprint(self) -> str:
        if self._grid_fp is None:
            self._grid_fp = grid_fingerprint(self.grid)
        return self._grid_fp

    def _group(self, pattern, solver: str, bucket: int, dtype,
               slo_ms, mesh_fp: str | None = None) -> _Group:
        gid = self._gid(pattern, solver, bucket, dtype, slo_ms)
        g = self._groups.get(gid)
        if g is not None:
            return g
        g = _Group(gid, self.grid)
        self._groups[gid] = g
        self._restore(g, pattern, solver, bucket, dtype, slo_ms, mesh_fp)
        return g

    def _restore(self, g: _Group, pattern, solver, bucket, dtype,
                 slo_ms, mesh_fp) -> None:
        """First-touch vault lookup: a persisted decision (same
        pattern/bucket/SLO class/mesh/grid) serves tuned from the
        first request — zero exploration after a restart."""
        from .. import vault
        from ..vault import _codecs

        if not vault.enabled():
            return
        try:
            g.vault_key = _codecs.digest(
                "appolicy", pattern.fingerprint[2], solver, int(bucket),
                np.dtype(dtype).str, slo_class(slo_ms), mesh_fp or "",
                self._grid_fingerprint(),
            )
            meta = vault.fetch("autopilot_policy", g.vault_key)
        except Exception:  # noqa: BLE001 - restore is never a liability
            return
        if not isinstance(meta, dict) or "spec" not in meta:
            return
        try:
            dec = PolicyDecision.from_meta(meta)
            dec.spec = _canonical_spec(dec.spec)  # re-validate stored spec
        except Exception:  # noqa: BLE001 - stale/corrupt meta: explore
            return
        g.decision = dec
        _metrics.counter(
            "autopilot.decisions", source="restored",
            help="policy decisions pinned, by source (tuned = converged "
            "online, restored = vault warm start)",
        ).inc()
        if telemetry.enabled():
            telemetry.record(
                "autopilot.restore", group=g.gid, arm=dec.arm,
                score_ms=round(dec.score, 4), trials=dec.trials,
            )

    # -- the serving-path hook ----------------------------------------------
    def assign(self, pattern, solver: str, bucket: int, dtype,
               slo_ms=None, mesh_fp: str | None = None):
        """Pick the policy arm for one dispatch. Returns ``(spec,
        token)``: ``spec`` the arm's override dict (empty = session
        statics) and ``token`` the observation handle
        :meth:`observe` settles — ``None`` token when the dispatch is
        not an experiment (incumbent/pinned traffic still observes,
        for drift detection, via a distinct token kind)."""
        g = self._group(pattern, solver, bucket, dtype, slo_ms, mesh_fp)
        g.seq += 1
        if g.decision is not None:
            return dict(g.decision.spec), (g.gid, "pinned", None, slo_ms)
        live = g.live()
        if not live:  # every arm SLO-aborted: serve the control arm
            return {}, None
        explore = len(live) > 1 and (g.seq - 1) % self.period == 0
        if not explore:
            best = g.best()
            return dict(best.spec), (g.gid, "incumbent", None, slo_ms)
        # round-robin over live arms, least-observed first so each
        # halving round fills evenly
        arm = min(
            live,
            key=lambda a: (len(a.scores), self._arm_index(g, a)),
        )
        return dict(arm.spec), (g.gid, "trial", self._arm_index(g, arm),
                                slo_ms)

    def _arm_index(self, g: _Group, arm: _Arm) -> int:
        return g.arms.index(arm)

    def observe(self, token, solve_ms: float, device_ms=None,
                iters_mean: float = 0.0, lanes: int = 1,
                converged: float = 1.0, promoted: bool = False) -> None:
        """Settle one dispatch's measurement against its token. The
        score is measured milliseconds per real lane — ``device_ms``
        when the sampled profiler took one, else the solve wall clock —
        with unconverged/promoted buckets scored infinitely bad (a
        fast wrong answer must never win)."""
        if token is None:
            return
        gid, kind, arm_idx, slo_ms = token
        g = self._groups.get(gid)
        if g is None:
            return
        ms = float(device_ms if device_ms is not None else solve_ms)
        score = (
            float("inf") if (promoted or converged < 1.0)
            else ms / max(int(lanes), 1)
        )
        if kind == "pinned":
            self._observe_pinned(g, score, promoted)
            return
        if kind == "incumbent" or g.decision is not None:
            return  # converged while this dispatch was in flight
        arm = g.arms[arm_idx]
        if arm.dead:
            return
        arm.scores.append(score)
        _metrics.counter(
            "autopilot.trials",
            help="measured policy experiments scheduled by the autopilot",
        ).inc()
        if telemetry.enabled():
            telemetry.record(
                "autopilot.trial", group=gid, arm=arm_id(arm.spec),
                score_ms=None if score == float("inf")
                else round(score, 4),
                solve_ms=round(float(solve_ms), 4),
                iters_mean=round(float(iters_mean), 3), lanes=int(lanes),
            )
        # SLO guard: a candidate blowing the tenant's budget dies NOW
        if (slo_ms is not None and arm.spec
                and ms > self.slo_factor * float(slo_ms)):
            arm.dead = True
            _metrics.counter(
                "autopilot.slo_breaches",
                help="experiments (or pinned dispatches) over the "
                "SLO-guard budget",
            ).inc()
            if telemetry.enabled():
                telemetry.record(
                    "autopilot.abort", group=gid, arm=arm_id(arm.spec),
                    reason="slo_guard", ms=round(ms, 4),
                    budget_ms=round(self.slo_factor * float(slo_ms), 4),
                )
        self._maybe_halve(g)

    def _observe_pinned(self, g: _Group, score: float,
                        promoted: bool) -> None:
        """Drift detection on pinned traffic: strikes accumulate into
        the watchdog-visible counter; a promote under a reduced pinned
        arm re-opens directly (see also the module promote listener,
        which covers promotes the session attributes elsewhere)."""
        dec = g.decision
        if dec is None:
            return
        if promoted and self._reduced(dec.spec):
            self._reopen(g, reason="promote")
            return
        if score > self.drift * max(dec.score, 1e-9):
            g.strikes += 1
            _metrics.counter(
                "autopilot.drift_strikes",
                help="incumbent observations slower than drift x the "
                "pinned decision score",
            ).inc()
        else:
            g.strikes = 0

    @staticmethod
    def _reduced(spec: dict) -> bool:
        from .. import mixed as mixed_mod

        pol = spec.get("dtype_policy")
        return bool(pol) and pol != mixed_mod.EXACT

    def _maybe_halve(self, g: _Group) -> None:
        live = g.live()
        if len(live) <= 1:
            self._converge(g)
            return
        need = self.trials * (g.round + 1)
        if any(len(a.scores) < need for a in live):
            return
        # eliminate the worst half (keep ceil(k/2)), then either keep
        # exploring the survivors or converge on the last one standing
        ranked = sorted(live, key=lambda a: a.median())
        keep = max((len(live) + 1) // 2, 1)
        for a in ranked[keep:]:
            a.dead = True
        g.round += 1
        if len(g.live()) <= 1:
            self._converge(g)

    def _converge(self, g: _Group) -> None:
        live = g.live()
        arm = live[0] if live else g.best()
        score = arm.median()
        if score == float("inf"):
            # nothing measured finite (every arm aborted/unconverged):
            # pin the control arm at a neutral score
            arm = g.arms[0]
            score = arm.median() if arm.scores else 0.0
        g.decision = PolicyDecision(
            arm.spec, score, sum(len(a.scores) for a in g.arms),
        )
        g.strikes = 0
        _metrics.counter("autopilot.decisions", source="tuned").inc()
        if telemetry.enabled():
            telemetry.record(
                "autopilot.converge", group=g.gid, arm=g.decision.arm,
                score_ms=round(g.decision.score, 4),
                trials=g.decision.trials, rounds=g.round,
            )
        self._persist(g)

    def _persist(self, g: _Group) -> None:
        from .. import vault

        if g.vault_key is None or g.decision is None:
            return
        try:
            vault.deposit("autopilot_policy", g.vault_key,
                          g.decision.to_meta())
        except Exception:  # noqa: BLE001 - persistence is best-effort
            pass

    # -- drift reopening ----------------------------------------------------
    def _reopen(self, g: _Group, reason: str) -> None:
        if g.decision is None:
            return
        g.decision = None
        g.strikes = 0
        g.round = 0
        g.seq = 0
        for a in g.arms:
            a.scores = []
            a.dead = False
        _metrics.counter(
            "autopilot.reopens", reason=reason.split(":", 1)[0],
            help="converged groups re-opened for exploration, by reason",
        ).inc()
        if telemetry.enabled():
            telemetry.record("autopilot.reopen", group=g.gid,
                             reason=reason)

    def reopen_all(self, reason: str = "manual") -> None:
        """Re-open exploration in every converged group (the watchdog
        alert hook's entry point; also a drill surface)."""
        for g in list(self._groups.values()):
            self._reopen(g, reason)

    def reopen_reduced(self, reason: str = "promote") -> None:
        """Re-open every group pinned to a reduced-precision arm (the
        mixed promote listener's entry point)."""
        for g in list(self._groups.values()):
            if g.decision is not None and self._reduced(g.decision.spec):
                self._reopen(g, reason)

    def force_decision(self, spec: dict, score: float | None = None) -> None:
        """Chaos-drill surface (scenario 13): overwrite every group's
        pinned decision with ``spec`` — keeping each group's measured
        score so drift detection judges the forced arm against the
        honest baseline. Groups still exploring converge-by-fiat."""
        spec = _canonical_spec(spec)
        for g in self._groups.values():
            base = (
                g.decision.score if g.decision is not None
                else g.best().median()
            )
            if score is not None:
                base = float(score)
            if not np.isfinite(base):
                base = 1e-6
            g.decision = PolicyDecision(spec, base, 0)
            g.strikes = 0

    # -- introspection ------------------------------------------------------
    def describe(self) -> dict:
        """JSON-friendly block for ``session_stats()`` / the report."""
        groups = {}
        for gid, g in self._groups.items():
            groups[gid] = {
                "phase": "converged" if g.decision is not None
                else "exploring",
                "arm": None if g.decision is None else g.decision.arm,
                "score_ms": None if g.decision is None
                else round(g.decision.score, 4),
                "restored": bool(g.decision is not None
                                 and g.decision.restored),
                "trials": sum(len(a.scores) for a in g.arms),
                "live_arms": len(g.live()),
                "rounds": g.round,
            }
        return {
            "arms": [arm_id(s) for s in self.grid],
            "period": self.period,
            "trials_per_round": self.trials,
            "slo_factor": self.slo_factor,
            "drift": self.drift,
            "groups": groups,
        }

    def decision_for(self, pattern, solver: str, bucket: int, dtype,
                     slo_ms=None):
        """The pinned :class:`PolicyDecision` for one group, or
        ``None`` while it is still exploring (test/report surface —
        never creates a group)."""
        g = self._groups.get(
            self._gid(pattern, solver, bucket, dtype, slo_ms)
        )
        return None if g is None else g.decision
