"""Autopilot: online per-(pattern, bucket, SLO class) policy tuning
(ISSUE 16) — see :mod:`.policy` for the full story. Public surface:

* :class:`Autopilot` — the trial scheduler (``SolveSession(autopilot=)``
  or ``SPARSE_TPU_AUTOPILOT=1``).
* :class:`PolicyDecision` — one pinned tuning outcome.
* :data:`DEFAULT_GRID` / :func:`arm_id` / :func:`slo_class` /
  :func:`grid_fingerprint` — the candidate-grid vocabulary.
* :func:`drift_rule` — the watchdog rule that turns drift strikes into
  an alert (whose transition re-opens exploration).
"""

from .policy import (  # noqa: F401
    ARM_KEYS,
    DEFAULT_GRID,
    Autopilot,
    PolicyDecision,
    arm_id,
    drift_rule,
    grid_fingerprint,
    slo_class,
)

__all__ = [
    "ARM_KEYS", "DEFAULT_GRID", "Autopilot", "PolicyDecision", "arm_id",
    "drift_rule", "grid_fingerprint", "slo_class",
]
