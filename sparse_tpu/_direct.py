"""Direct-solver / structural surface: spsolve_triangular, splu, spilu,
factorized, inv, expm, is_sptriangular, spbandwidth.

Beyond the reference (its linalg.py has no direct solvers at all —
spsolve there IS cg, linalg.py:88); added for scipy.sparse.linalg
drop-in completeness. TPU design notes:

- ``spsolve_triangular`` is a *blocked* substitution: one ``lax.scan``
  over row blocks, each step a dense ``solve_triangular`` on the MXU plus
  a gathered sparse off-diagonal update. The sequential chain is n/nb
  steps (not n), which is the right trade on a systolic-array machine.
- ``splu``/``inv``/``expm`` use dense device factorizations under a size
  threshold (LU/expm of a sparse operator are dense-dominated anyway;
  XLA's LAPACK/expm paths are MXU-tiled). Above the threshold they raise
  with a pointer to the iterative solvers — honest, not silently slow.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .coverage import track_provenance
from .utils import asjnp

__all__ = [
    "spbandwidth",
    "is_sptriangular",
    "spsolve_triangular",
    "SuperLU",
    "splu",
    "spilu",
    "factorized",
    "inv",
    "expm",
]

# Dense fallback ceiling for splu/inv/expm: n*n f32 = 1 GiB at 16384; keep
# well under a single chip's HBM while covering every practical direct-solve
# size (beyond this, direct methods are the wrong tool — use cg/gmres).
DENSE_DIRECT_MAX_N = 8192


def _coo_host(A):
    c = A.tocoo()
    return (
        np.asarray(c.row, dtype=np.int64),
        np.asarray(c.col, dtype=np.int64),
        np.asarray(c.data),
    )


@track_provenance
def spbandwidth(A):
    """(below, above) bandwidth of a sparse array (scipy.sparse.spbandwidth)."""
    row, col, data = _coo_host(A)
    keep = data != 0
    row, col = row[keep], col[keep]
    if row.size == 0:
        return (0, 0)
    d = col - row
    return (int(max(-d.min(), 0)), int(max(d.max(), 0)))


@track_provenance
def is_sptriangular(A):
    """(lower, upper) structural triangularity (scipy.sparse.linalg)."""
    lo, hi = spbandwidth(A)
    return (hi == 0, lo == 0)


def _as_2d(b):
    b = asjnp(b)
    if b.ndim == 1:
        return b[:, None], True
    if b.ndim == 2:
        return b, False
    raise ValueError("b must be 1-D or 2-D")


@track_provenance
def spsolve_triangular(
    A, b, lower=True, overwrite_A=False, overwrite_b=False,
    unit_diagonal=False, block=256,
):
    """Solve a (structurally) triangular system Ax = b.

    Blocked substitution: the rows are cut into ceil(n/block) tiles; a
    single ``lax.scan`` walks them (forward for lower, backward for
    upper). Each step gathers the already-solved prefix through the
    block's off-diagonal entries (segment-sum), then runs one dense
    ``solve_triangular`` on the diagonal tile. Raises LinAlgError on a
    structurally/numerically singular diagonal (scipy behavior).
    """
    A = A.tocsr()
    m, n = A.shape
    if m != n:
        raise ValueError("matrix must be square")
    bmat, squeeze = _as_2d(b)
    if bmat.shape[0] != n:
        raise ValueError("A and b dimension mismatch")
    row, col, data = _coo_host(A)
    # structural triangularity check (scipy raises on the wrong half)
    bad = (col > row) if lower else (col < row)
    if np.any(data[bad] != 0):
        side = "lower" if lower else "upper"
        raise ValueError(f"A is not {side} triangular")

    dt = jnp.result_type(A.dtype, bmat.dtype, jnp.float32)
    nb = int(min(max(block, 8), n))
    K = (n + nb - 1) // nb
    n_pad = K * nb

    if not unit_diagonal:
        diag = np.zeros(n, dtype=np.asarray(data).dtype)
        on_d = row == col
        diag[row[on_d]] = data[on_d]
        if np.any(diag == 0):
            raise np.linalg.LinAlgError(
                "A is singular: zero entry on diagonal."
            )

    # per-block dense diagonal tiles + padded off-diagonal COO slices
    blk = row // nb
    in_diag = (col // nb) == blk
    Dh = np.zeros((K, nb, nb), dtype=np.asarray(data).dtype)
    dr, dc, dv = row[in_diag], col[in_diag], data[in_diag]
    Dh[dr // nb, dr % nb, dc - (dr // nb) * nb] = dv
    if unit_diagonal:
        Dh[:, np.arange(nb), np.arange(nb)] = 1.0
    # identity rows for the padding tail: a zero diagonal there would NaN
    # the whole final tile's dense solve (and, on the backward/upper scan,
    # poison every earlier block)
    pad_rows = np.arange(n, n_pad)
    Dh[pad_rows // nb, pad_rows % nb, pad_rows % nb] = 1.0
    orow, ocol, oval = row[~in_diag], col[~in_diag], data[~in_diag]
    oblk = orow // nb
    counts = np.bincount(oblk, minlength=K)
    E = max(int(counts.max()) if counts.size else 0, 1)
    offc = np.zeros((K, E), dtype=np.int32)
    offv = np.zeros((K, E), dtype=np.asarray(data).dtype)
    offr = np.zeros((K, E), dtype=np.int32)
    order = np.argsort(oblk, kind="stable")
    pos = np.concatenate([[0], np.cumsum(counts)])
    for k in range(K):
        sl = order[pos[k]:pos[k + 1]]
        e = sl.size
        offc[k, :e] = ocol[sl]
        offv[k, :e] = oval[sl]
        offr[k, :e] = orow[sl] - k * nb

    D_d = jnp.asarray(Dh, dtype=dt)
    offc_d = jnp.asarray(offc)
    offv_d = jnp.asarray(offv, dtype=dt)
    offr_d = jnp.asarray(offr)
    b_pad = jnp.zeros((n_pad, bmat.shape[1]), dtype=dt)
    b_pad = b_pad.at[:n].set(bmat.astype(dt))
    ks = jnp.arange(K, dtype=jnp.int32)
    if not lower:
        ks = ks[::-1]

    from jax.scipy.linalg import solve_triangular as dense_tri

    def step(x, k):
        Dk = D_d[k]
        contrib = jax.ops.segment_sum(
            offv_d[k][:, None] * x[offc_d[k]], offr_d[k],
            num_segments=nb,
        )
        y = jax.lax.dynamic_slice_in_dim(b_pad, k * nb, nb) - contrib
        xk = dense_tri(Dk, y, lower=lower, unit_diagonal=unit_diagonal)
        x = jax.lax.dynamic_update_slice_in_dim(x, xk, k * nb, axis=0)
        return x, None

    x0 = jnp.zeros((n_pad, bmat.shape[1]), dtype=dt)
    x, _ = jax.lax.scan(step, x0, ks)
    x = x[:n]
    return x[:, 0] if squeeze else x


class SuperLU:
    """LU factorization with the scipy ``SuperLU`` object surface
    (shape, nnz, perm_r, perm_c, L, U, solve). Device-dense under the
    hood: ``lu_factor`` runs on the accelerator (XLA-tiled LAPACK), and
    ``solve`` is two MXU triangular solves."""

    def __init__(self, A):
        from .csr import csr_array

        A = A.tocsr()
        m, n = A.shape
        if m != n:
            raise ValueError("matrix must be square")
        if n > DENSE_DIRECT_MAX_N:
            raise ValueError(
                f"splu: n={n} exceeds the dense-factorization ceiling "
                f"({DENSE_DIRECT_MAX_N}); use cg/gmres/bicgstab for "
                "large systems"
            )
        self.shape = (m, n)
        self.nnz = A.nnz
        dt = jnp.result_type(A.dtype, jnp.float32)
        dense = asjnp(A.toarray(), dt)
        from jax.scipy.linalg import lu_factor

        self._lu, self._piv = lu_factor(dense)
        if bool(jnp.any(jnp.diagonal(self._lu) == 0)):
            raise RuntimeError("Factor is exactly singular")
        # piv (LAPACK swaps) -> row permutation. LAPACK gives perm with
        # A[perm] == L @ U; scipy's SuperLU.perm_r convention is the
        # INVERSE ((L @ U)[perm_r] == A, i.e. Pr @ A @ Pc == L @ U with
        # Pr[perm_r[i], i] = 1) — match scipy so drop-in permutation code
        # gets the right direction.
        piv = np.asarray(self._piv)
        perm = np.arange(n)
        for i, p in enumerate(piv):
            perm[i], perm[p] = perm[p], perm[i]
        self.perm_r = np.argsort(perm)
        self.perm_c = np.arange(n)
        self._csr = csr_array

    @property
    def L(self):
        n = self.shape[0]
        Ld = jnp.tril(self._lu, -1) + jnp.eye(n, dtype=self._lu.dtype)
        return self._csr(np.asarray(Ld))

    @property
    def U(self):
        return self._csr(np.asarray(jnp.triu(self._lu)))

    def solve(self, rhs, trans="N"):
        from jax.scipy.linalg import lu_solve

        bmat, squeeze = _as_2d(rhs)
        t = {"N": 0, "T": 1, "H": 2}.get(trans)
        if t is None:
            raise ValueError("trans must be 'N', 'T' or 'H'")
        if jnp.iscomplexobj(bmat) and not jnp.iscomplexobj(self._lu):
            # real factorization, complex rhs (e.g. spilu preconditioning a
            # complex Krylov solve): solve Re and Im against the same
            # factors — casting would silently drop the imaginary part
            xr = lu_solve((self._lu, self._piv),
                          jnp.real(bmat).astype(self._lu.dtype), trans=t)
            xi = lu_solve((self._lu, self._piv),
                          jnp.imag(bmat).astype(self._lu.dtype), trans=t)
            x = xr + 1j * xi
        else:
            x = lu_solve(
                (self._lu, self._piv), bmat.astype(self._lu.dtype), trans=t
            )
        return x[:, 0] if squeeze else x


@track_provenance
def splu(A, permc_spec=None, diag_pivot_thresh=None, relax=None,
         panel_size=None, options=None):
    """LU factorization returning a :class:`SuperLU` (scipy.sparse.linalg.splu).
    The SuperLU tuning knobs are accepted and ignored (the device dense
    factorization has no analogous parameters)."""
    return SuperLU(A)


@track_provenance
def spilu(A, drop_tol=None, fill_factor=None, drop_rule=None, **kw):
    """Incomplete-LU preconditioner factory (scipy.sparse.linalg.spilu
    surface). Returns an EXACT factorization: a stronger preconditioner
    with the identical object interface; the drop parameters are accepted
    and ignored (documented deviation — on TPU the dense LU is one MXU
    kernel, so there is nothing to save by dropping fill)."""
    return SuperLU(A)


@track_provenance
def factorized(A):
    """Pre-factorized solve closure (scipy.sparse.linalg.factorized)."""
    return splu(A).solve


@track_provenance
def inv(A):
    """Sparse inverse via one factorization + n MXU triangular solves
    (scipy.sparse.linalg.inv; returns the same sparse format)."""
    lu = splu(A)
    n = A.shape[0]
    X = lu.solve(jnp.eye(n, dtype=lu._lu.dtype))
    from .csr import csr_array

    out = csr_array(np.asarray(X))
    return out.asformat(A.format)


@track_provenance
def expm(A):
    """Sparse matrix exponential (scipy.sparse.linalg.expm).

    Densifies and runs XLA's scaling-and-squaring Pade ``expm`` — the
    squaring phase is pure MXU matmuls, which is exactly where a TPU
    wants this computation; the result of a sparse expm is dense-ish
    anyway. Returns the input's sparse format."""
    from .csr import csr_array

    n = A.shape[0]
    if n > DENSE_DIRECT_MAX_N:
        raise ValueError(
            f"expm: n={n} exceeds the dense ceiling ({DENSE_DIRECT_MAX_N}); "
            "use expm_multiply to apply the exponential to vectors instead"
        )
    dt = jnp.result_type(A.dtype, jnp.float32)
    from jax.scipy.linalg import expm as dense_expm

    E = dense_expm(asjnp(A.toarray(), dt))
    out = csr_array(np.asarray(E))
    fmt = getattr(A, "format", "csr")
    return out.asformat(fmt) if fmt in ("csr", "csc", "coo", "dia") else out
