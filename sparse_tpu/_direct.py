"""Direct-solver / structural surface: spsolve_triangular, splu, spilu,
factorized, inv, expm, is_sptriangular, spbandwidth.

Beyond the reference (its linalg.py has no direct solvers at all —
spsolve there IS cg, linalg.py:88); added for scipy.sparse.linalg
drop-in completeness. TPU design notes:

- ``spsolve_triangular`` is a *blocked* substitution: one ``lax.scan``
  over row blocks, each step a dense ``solve_triangular`` on the MXU plus
  a gathered sparse off-diagonal update. The sequential chain is n/nb
  steps (not n), which is the right trade on a systolic-array machine.
- ``splu``/``inv``/``expm`` use dense device factorizations under a size
  threshold (LU/expm of a sparse operator are dense-dominated anyway;
  XLA's LAPACK/expm paths are MXU-tiled). Above the threshold they raise
  with a pointer to the iterative solvers — honest, not silently slow.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .coverage import track_provenance
from .utils import asjnp

__all__ = [
    "spbandwidth",
    "is_sptriangular",
    "spsolve_triangular",
    "SuperLU",
    "SpILU",
    "splu",
    "spilu",
    "ilu0",
    "ic0",
    "factorized",
    "inv",
    "expm",
]

# Dense fallback ceiling for splu/inv/expm: n*n f32 = 1 GiB at 16384; keep
# well under a single chip's HBM while covering every practical direct-solve
# size (beyond this, direct methods are the wrong tool — use cg/gmres).
DENSE_DIRECT_MAX_N = 8192


def _coo_host(A):
    c = A.tocoo()
    return (
        np.asarray(c.row, dtype=np.int64),
        np.asarray(c.col, dtype=np.int64),
        np.asarray(c.data),
    )


def _coo_to_csr_host(row, col, data, n):
    """Canonical host CSR build from COO triples: lexsort by (row, col),
    count, cumsum. Shared by the ILU/IC factor paths and csgraph's host
    fallback — keep the idiom in ONE place. Returns
    (indptr, sorted_row, sorted_col, sorted_data)."""
    order = np.lexsort((col, row))
    row, col, data = row[order], col[order], data[order]
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.add.at(indptr, row + 1, 1)
    return np.cumsum(indptr), row, col, data


@track_provenance
def spbandwidth(A):
    """(below, above) bandwidth of a sparse array (scipy.sparse.spbandwidth)."""
    row, col, data = _coo_host(A)
    keep = data != 0
    row, col = row[keep], col[keep]
    if row.size == 0:
        return (0, 0)
    d = col - row
    return (int(max(-d.min(), 0)), int(max(d.max(), 0)))


@track_provenance
def is_sptriangular(A):
    """(lower, upper) structural triangularity (scipy.sparse.linalg)."""
    lo, hi = spbandwidth(A)
    return (hi == 0, lo == 0)


def _as_2d(b):
    b = asjnp(b)
    if b.ndim == 1:
        return b[:, None], True
    if b.ndim == 2:
        return b, False
    raise ValueError("b must be 1-D or 2-D")


@track_provenance
def spsolve_triangular(
    A, b, lower=True, overwrite_A=False, overwrite_b=False,
    unit_diagonal=False, block=256,
):
    """Solve a (structurally) triangular system Ax = b.

    Blocked substitution: the rows are cut into ceil(n/block) tiles; a
    single ``lax.scan`` walks them (forward for lower, backward for
    upper). Each step gathers the already-solved prefix through the
    block's off-diagonal entries (segment-sum), then runs one dense
    ``solve_triangular`` on the diagonal tile. Raises LinAlgError on a
    structurally/numerically singular diagonal (scipy behavior).
    """
    A = A.tocsr()
    m, n = A.shape
    if m != n:
        raise ValueError("matrix must be square")
    bmat, squeeze = _as_2d(b)
    if bmat.shape[0] != n:
        raise ValueError("A and b dimension mismatch")
    row, col, data = _coo_host(A)
    # structural triangularity check (scipy raises on the wrong half)
    bad = (col > row) if lower else (col < row)
    if np.any(data[bad] != 0):
        side = "lower" if lower else "upper"
        raise ValueError(f"A is not {side} triangular")
    if not unit_diagonal:
        diag = np.zeros(n, dtype=np.asarray(data).dtype)
        on_d = row == col
        diag[row[on_d]] = data[on_d]
        if np.any(diag == 0):
            raise np.linalg.LinAlgError(
                "A is singular: zero entry on diagonal."
            )
    dt = jnp.result_type(A.dtype, bmat.dtype, jnp.float32)
    prep = _PreparedTriangular(
        n, row, col, data, lower=lower, unit_diagonal=unit_diagonal,
        block=block, dtype=dt,
    )
    x = prep.apply(bmat)
    return x[:, 0] if squeeze else x


class _PreparedTriangular:
    """Blocked triangular-solve plan: host preprocessing done ONCE, each
    ``apply`` is a single compiled ``lax.scan``.

    The diagonal tiles are stored dense ([K, nb, nb] — one MXU
    ``solve_triangular`` per step); the off-diagonal entries stay sparse
    COO slices consumed by a segment-sum gather. ``block`` adapts
    downward for huge n so the tile storage stays bounded (~256 MB),
    keeping total memory O(nnz + n*nb) — the property that makes a
    1e6-row ILU preconditioner feasible where a dense factor is 8 TB.
    """

    def __init__(self, n, row, col, data, lower, unit_diagonal,
                 block=256, dtype=None):
        data = np.asarray(data)
        dt = dtype if dtype is not None else jnp.result_type(
            data.dtype, jnp.float32
        )
        itemsize = np.dtype(dt).itemsize
        cap = max(32, (1 << 28) // (max(n, 1) * itemsize))
        nb = int(min(max(block, 8), max(n, 1), cap))
        K = (n + nb - 1) // nb
        n_pad = K * nb
        self.n, self.nb, self.K, self.n_pad = n, nb, K, n_pad
        self.lower, self.unit_diagonal, self.dt = lower, unit_diagonal, dt

        blk = row // nb
        in_diag = (col // nb) == blk
        Dh = np.zeros((K, nb, nb), dtype=data.dtype)
        dr, dc, dv = row[in_diag], col[in_diag], data[in_diag]
        Dh[dr // nb, dr % nb, dc - (dr // nb) * nb] = dv
        if unit_diagonal:
            Dh[:, np.arange(nb), np.arange(nb)] = 1.0
        # identity rows for the padding tail: a zero diagonal there would
        # NaN the final tile's dense solve (and, on the backward/upper
        # scan, poison every earlier block)
        pad_rows = np.arange(n, n_pad)
        Dh[pad_rows // nb, pad_rows % nb, pad_rows % nb] = 1.0
        orow, ocol, oval = row[~in_diag], col[~in_diag], data[~in_diag]
        oblk = orow // nb
        counts = np.bincount(oblk, minlength=K)
        E = max(int(counts.max()) if counts.size else 0, 1)
        offc = np.zeros((K, E), dtype=np.int32)
        offv = np.zeros((K, E), dtype=data.dtype)
        offr = np.zeros((K, E), dtype=np.int32)
        order = np.argsort(oblk, kind="stable")
        pos = np.concatenate([[0], np.cumsum(counts)])
        for k in range(K):
            sl = order[pos[k]:pos[k + 1]]
            e = sl.size
            offc[k, :e] = ocol[sl]
            offv[k, :e] = oval[sl]
            offr[k, :e] = orow[sl] - k * nb

        self._D = jnp.asarray(Dh, dtype=dt)
        self._offc = jnp.asarray(offc)
        self._offv = jnp.asarray(offv, dtype=dt)
        self._offr = jnp.asarray(offr)

        from jax.scipy.linalg import solve_triangular as dense_tri

        ks = jnp.arange(K, dtype=jnp.int32)
        if not lower:
            ks = ks[::-1]

        def solve_padded(D, offc_, offv_, offr_, b_pad):
            def step(x, k):
                contrib = jax.ops.segment_sum(
                    offv_[k][:, None] * x[offc_[k]], offr_[k],
                    num_segments=nb,
                )
                y = jax.lax.dynamic_slice_in_dim(b_pad, k * nb, nb) - contrib
                xk = dense_tri(
                    D[k], y, lower=lower, unit_diagonal=unit_diagonal
                )
                return (
                    jax.lax.dynamic_update_slice_in_dim(x, xk, k * nb, axis=0),
                    None,
                )

            x0 = jnp.zeros_like(b_pad)
            x, _ = jax.lax.scan(step, x0, ks)
            return x

        self._solve = jax.jit(solve_padded)

    def apply(self, bmat):
        """[n, r] -> [n, r] (traceable; jitted scan inside)."""
        bmat = jnp.asarray(bmat, dtype=self.dt)
        b_pad = jnp.zeros((self.n_pad, bmat.shape[1]), dtype=self.dt)
        b_pad = b_pad.at[: self.n].set(bmat)
        return self._solve(
            self._D, self._offc, self._offv, self._offr, b_pad
        )[: self.n]


class SuperLU:
    """LU factorization with the scipy ``SuperLU`` object surface
    (shape, nnz, perm_r, perm_c, L, U, solve).

    TPU phase split, two regimes:

    * n <= ``DENSE_DIRECT_MAX_N``: device-dense — ``lu_factor`` on the
      accelerator (XLA-tiled LAPACK), ``solve`` two MXU triangular solves.
    * larger real matrices: TRUE sparse LU — the native Gilbert-Peierls
      factorization with partial pivoting (``native.splu_host``, a host
      setup kernel like the Gustavson SpGEMM), solves as two blocked
      ``lax.scan`` triangular programs on device
      (:class:`_PreparedTriangular`), O(nnz(L)+nnz(U)) memory throughout.
      Natural column order (no COLAMD): fill is geometry-dependent;
      pathological fill cases should use cg/gmres instead.

    Complex matrices keep the dense path (the native factorization is
    real f64), so complex n > ceiling still raises."""

    def _setup_common(self, A):
        """Shared constructor prologue for the splu and ILUT entry
        points; returns the canonical csr form."""
        from .csr import csr_array

        A = A.tocsr()
        m, n = A.shape
        if m != n:
            raise ValueError("matrix must be square")
        self.shape = (m, n)
        self.nnz = A.nnz
        self._csr = csr_array
        return A

    def __init__(self, A, permc_spec=None):
        A = self._setup_common(A)
        n = self.shape[0]
        is_complex = np.issubdtype(np.dtype(A.dtype), np.complexfloating)
        if n > DENSE_DIRECT_MAX_N:
            if not is_complex and self._init_sparse(A, permc_spec):
                return
            raise ValueError(
                f"splu: n={n} exceeds the dense-factorization ceiling "
                f"({DENSE_DIRECT_MAX_N}) and the native sparse-LU library "
                "is " + ("unavailable" if not is_complex else
                         "real-only (complex input)")
                + "; use cg/gmres/bicgstab for large systems"
            )
        self._mode = "dense"
        dt = jnp.result_type(A.dtype, jnp.float32)
        dense = asjnp(A.toarray(), dt)
        from jax.scipy.linalg import lu_factor

        self._lu, self._piv = lu_factor(dense)
        if bool(jnp.any(jnp.diagonal(self._lu) == 0)):
            raise RuntimeError("Factor is exactly singular")
        # piv (LAPACK swaps) -> row permutation. LAPACK gives perm with
        # A[perm] == L @ U; scipy's SuperLU.perm_r convention is the
        # INVERSE ((L @ U)[perm_r] == A, i.e. Pr @ A @ Pc == L @ U with
        # Pr[perm_r[i], i] = 1) — match scipy so drop-in permutation code
        # gets the right direction.
        piv = np.asarray(self._piv)
        perm = np.arange(n)
        for i, p in enumerate(piv):
            perm[i], perm[p] = perm[p], perm[i]
        self.perm_r = np.argsort(perm)
        self.perm_c = np.arange(n)

    def _init_sparse(self, A, permc_spec=None, ilut=None):
        """Native Gilbert-Peierls factorization -> device triangular-solve
        plans. Returns False when the native library is unavailable
        (caller falls back to the dense path / ceiling error).
        ``ilut=(droptol, lfil)`` runs the INCOMPLETE variant on the same
        machinery (the spilu fill_factor path).

        ``permc_spec="RCM"`` applies a SYMMETRIC reverse-Cuthill-McKee
        pre-permutation (rows and columns): fill under Gilbert-Peierls
        tracks the profile, so banding a scattered pattern first can cut
        the factor size by large factors. Solves transparently permute
        the rhs/solution, so callers see plain Ax = b."""
        from . import native

        n = self.shape[0]
        q = None
        row, col, val = _coo_host(A)
        if isinstance(permc_spec, str) and permc_spec.upper() == "RCM":
            from .csgraph import reverse_cuthill_mckee

            q = np.asarray(reverse_cuthill_mckee(A), dtype=np.int64)
            qinv = np.argsort(q)
            # symmetric permutation on host COO: entry (r, c) of A lands
            # at (qinv[r], qinv[c]) of A[q][:, q]
            row, col = qinv[row], qinv[col]
        # CSC build = CSR of the transpose: sort by (col, row)
        cp, col_s, row_s, val_s = _coo_to_csr_host(col, row, val, n)
        if ilut is None:
            out = native.splu_host(
                cp, row_s, np.asarray(val_s, dtype=np.float64), n
            )
        else:
            out = native.ilut_host(
                cp, row_s, np.asarray(val_s, dtype=np.float64), n,
                droptol=ilut[0], lfil=ilut[1],
            )
        if out is None:
            return False
        Lp, Li, Lx, Up, Ui, Ux, perm = out
        self._mode = "sparse"
        # device copies ONCE — solves gather through these every call
        self._perm = jnp.asarray(perm)
        self._pinv = jnp.asarray(np.argsort(perm))
        self._q = jnp.asarray(q) if q is not None else None
        self._qinv = jnp.asarray(qinv) if q is not None else None
        if q is None:
            self.perm_r = np.argsort(perm)  # scipy convention (dense path)
            self.perm_c = np.arange(n)
        else:
            # Pr A Pc = L U with Pc = the RCM column order: column j of
            # (A Pc) is A[:, q[j]]; rows of the factored matrix come from
            # q[perm[k]] of the original — store the scipy-convention
            # inverse
            self.perm_c = q
            pr = np.empty(n, dtype=np.int64)
            pr[q[perm]] = np.arange(n)
            self.perm_r = pr
        self._Lcsc = (Lp, Li, Lx)
        self._Ucsc = (Up, Ui, Ux)
        dt = jnp.result_type(A.dtype, jnp.float32)
        self._dt = dt
        Lcols = np.repeat(np.arange(n, dtype=np.int64), np.diff(Lp))
        Ucols = np.repeat(np.arange(n, dtype=np.int64), np.diff(Up))
        self._Lprep = _PreparedTriangular(
            n, Li, Lcols, Lx, lower=True, unit_diagonal=True, dtype=dt
        )
        self._Uprep = _PreparedTriangular(
            n, Ui, Ucols, Ux, lower=False, unit_diagonal=False, dtype=dt
        )
        self._LTprep = self._UTprep = None
        return True

    @classmethod
    def _ilut(cls, A, drop_tol, fill_factor):
        """ILUT(p, tau) preconditioner with the SuperLU object surface —
        scipy's actual ``spilu(drop_tol, fill_factor)`` algorithm, run on
        the sparse-LU machinery (no size ceiling; real matrices). The
        per-column keep count is ``fill_factor`` x the mean column count
        split over the two factor halves. Returns None when the native
        library is unavailable (caller falls back to ILU(0))."""
        self = cls.__new__(cls)
        A = self._setup_common(A)
        n = self.shape[0]
        avg = max(A.nnz / max(n, 1), 1.0)
        lfil = max(1, int(np.ceil(float(fill_factor) * avg / 2.0)))
        droptol = 1e-4 if drop_tol is None else float(drop_tol)
        if not self._init_sparse(A, ilut=(droptol, lfil)):
            return None
        return self

    def _solve_sparse_real(self, bmat, trans):
        """PA = LU:  N: x = U\\(L\\(Pb));  T/H (real factors): A^T =
        U^T L^T P, so solve U^T then L^T and un-permute. Under an RCM
        pre-permutation q the factored matrix is A[q][:, q], which is
        ALSO the symmetric permutation of A^T — so both directions just
        permute the rhs in and the solution out."""
        n = self.shape[0]
        if self._q is not None:
            bmat = bmat[self._q]
        if trans == "N":
            y = bmat[self._perm]
            x = self._Uprep.apply(self._Lprep.apply(y))
            return x if self._q is None else x[self._qinv]
        if self._UTprep is None:
            Lp, Li, Lx = self._Lcsc
            Up, Ui, Ux = self._Ucsc
            Lcols = np.repeat(np.arange(n, dtype=np.int64), np.diff(Lp))
            Ucols = np.repeat(np.arange(n, dtype=np.int64), np.diff(Up))
            # transposes: swap (row, col); U^T is lower non-unit, L^T
            # upper unit
            self._UTprep = _PreparedTriangular(
                n, Ucols, Ui, Ux, lower=True, unit_diagonal=False,
                dtype=self._dt,
            )
            self._LTprep = _PreparedTriangular(
                n, Lcols, Li, Lx, lower=False, unit_diagonal=True,
                dtype=self._dt,
            )
        y = self._LTprep.apply(self._UTprep.apply(bmat))
        # inner un-permute of the FACTORED matrix's pivots (independent of
        # the scipy-facing perm_r, which also folds in any RCM q)
        y = y[self._pinv]
        return y if self._q is None else y[self._qinv]

    @property
    def L(self):
        n = self.shape[0]
        if getattr(self, "_mode", "dense") == "sparse":
            Lp, Li, Lx = self._Lcsc
            cols = np.repeat(np.arange(n, dtype=np.int64), np.diff(Lp))
            row = np.concatenate([Li, np.arange(n, dtype=np.int64)])
            col = np.concatenate([cols, np.arange(n, dtype=np.int64)])
            val = np.concatenate([Lx, np.ones(n)])  # explicit unit diagonal
            indptr, row, col, val = _coo_to_csr_host(row, col, val, n)
            return self._csr.from_parts(val, col, indptr, (n, n))
        Ld = jnp.tril(self._lu, -1) + jnp.eye(n, dtype=self._lu.dtype)
        return self._csr(np.asarray(Ld))

    @property
    def U(self):
        if getattr(self, "_mode", "dense") == "sparse":
            n = self.shape[0]
            Up, Ui, Ux = self._Ucsc
            cols = np.repeat(np.arange(n, dtype=np.int64), np.diff(Up))
            indptr, row, col, val = _coo_to_csr_host(Ui, cols, Ux, n)
            return self._csr.from_parts(val, col, indptr, (n, n))
        return self._csr(np.asarray(jnp.triu(self._lu)))

    def solve(self, rhs, trans="N"):
        from jax.scipy.linalg import lu_solve

        bmat, squeeze = _as_2d(rhs)
        t = {"N": 0, "T": 1, "H": 2}.get(trans)
        if t is None:
            raise ValueError("trans must be 'N', 'T' or 'H'")
        if getattr(self, "_mode", "dense") == "sparse":
            # real factors: A^H == A^T, so 'H' == 'T'; a complex rhs
            # solves Re/Im parts against the same factors
            if jnp.iscomplexobj(bmat):
                xr = self._solve_sparse_real(
                    jnp.real(bmat).astype(self._dt), trans
                )
                xi = self._solve_sparse_real(
                    jnp.imag(bmat).astype(self._dt), trans
                )
                x = xr + 1j * xi
            else:
                x = self._solve_sparse_real(bmat.astype(self._dt), trans)
            return x[:, 0] if squeeze else x
        if jnp.iscomplexobj(bmat) and not jnp.iscomplexobj(self._lu):
            # real factorization, complex rhs (e.g. spilu preconditioning a
            # complex Krylov solve): solve Re and Im against the same
            # factors — casting would silently drop the imaginary part
            xr = lu_solve((self._lu, self._piv),
                          jnp.real(bmat).astype(self._lu.dtype), trans=t)
            xi = lu_solve((self._lu, self._piv),
                          jnp.imag(bmat).astype(self._lu.dtype), trans=t)
            x = xr + 1j * xi
        else:
            x = lu_solve(
                (self._lu, self._piv), bmat.astype(self._lu.dtype), trans=t
            )
        return x[:, 0] if squeeze else x


class SpILU:
    """Incomplete LU (ILU(0), optional threshold drop) with the scipy
    ``SuperLU`` object surface (shape, nnz, perm_r, perm_c, L, U, solve).

    TPU phase split: the row-sequential numeric factorization runs as a
    host setup kernel (``native.ilu0_host`` — C++ with a numpy fallback,
    like the Gustavson SpGEMM); the per-iteration triangular SOLVES are
    two blocked ``lax.scan`` programs on the device
    (:class:`_PreparedTriangular`), so using the object as a CG/GMRES
    preconditioner keeps the whole solve compiled. Memory is O(nnz)
    throughout — the 1e6-row regime where a dense factor is 8 TB.

    ``drop_tol`` drops computed factor off-diagonals with
    |v| < drop_tol * ||A_row||_2 (the scipy/ILUT row rule) AFTER the
    ILU(0)-pattern factorization — it thins the factors (cheaper solves),
    never adds fill.
    """

    def __init__(self, A, drop_tol=None, block=256):
        from .csr import csr_array

        A = A.tocsr()
        m, n = A.shape
        if m != n:
            raise ValueError("matrix must be square")
        self.shape = (m, n)
        self.perm_r = np.arange(n)
        self.perm_c = np.arange(n)
        if np.issubdtype(np.dtype(A.dtype), np.complexfloating):
            # dtype check BEFORE touching the values: the native ILU(0)
            # kernels are real f64, and fetching complex data would
            # itself fail on transfer-restricted backends
            raise NotImplementedError(
                "SpILU/ilu0 are real-valued; use splu for complex matrices"
            )
        row, col, data = _coo_host(A)
        indptr, row, col, data = _coo_to_csr_host(row, col, data, n)
        data = data.astype(np.float64)

        from . import native

        fdata = native.ilu0_host(indptr, col, data, n)

        keep = np.ones(fdata.size, dtype=bool)
        if drop_tol is not None and drop_tol > 0:
            sq = np.zeros(n)
            np.add.at(sq, row, data * data)
            thresh = drop_tol * np.sqrt(sq)[row]
            keep = (np.abs(fdata) >= thresh) | (row == col)

        lmask = (col < row) & keep
        umask = (col >= row) & keep
        # scipy SuperLU convention: nnz counts the FACTORS (L incl. its
        # explicit unit diagonal + U), after any drop_tol thinning
        self.nnz = int(lmask.sum()) + int(umask.sum()) + n
        self._dtype = jnp.result_type(A.dtype, jnp.float32)
        self._Lsolve = _PreparedTriangular(
            n, row[lmask], col[lmask], fdata[lmask],
            lower=True, unit_diagonal=True, block=block, dtype=self._dtype,
        )
        self._Usolve = _PreparedTriangular(
            n, row[umask], col[umask], fdata[umask],
            lower=False, unit_diagonal=False, block=block, dtype=self._dtype,
        )
        # factor parts for .L/.U (host, scipy convention: L carries an
        # explicit unit diagonal)
        self._parts = (row, col, fdata, lmask, umask)
        self._L_cache = None
        self._U_cache = None
        self._csr = csr_array

    def _factor_csr(self, mask, unit_diag):
        row, col, fdata, _, _ = self._parts
        n = self.shape[0]
        r, c, v = row[mask], col[mask], fdata[mask]
        if unit_diag:
            r = np.concatenate([r, np.arange(n)])
            c = np.concatenate([c, np.arange(n)])
            v = np.concatenate([v, np.ones(n)])
        indptr, _, c, v = _coo_to_csr_host(r, c, v, n)
        return self._csr.from_parts(v, c.astype(np.int64), indptr, self.shape)

    @property
    def L(self):
        if self._L_cache is None:  # sort+upload once, not per access
            _, _, _, lmask, _ = self._parts
            self._L_cache = self._factor_csr(lmask, unit_diag=True)
        return self._L_cache

    @property
    def U(self):
        if self._U_cache is None:
            _, _, _, _, umask = self._parts
            self._U_cache = self._factor_csr(umask, unit_diag=False)
        return self._U_cache

    def solve(self, rhs, trans="N"):
        if trans != "N":
            # transpose solves need CSC-ordered plans; not part of the
            # preconditioner hot path — raise honestly
            raise NotImplementedError(
                "SpILU.solve supports trans='N' only"
            )
        bmat, squeeze = _as_2d(rhs)
        if jnp.iscomplexobj(bmat):
            xr = self._Usolve.apply(self._Lsolve.apply(jnp.real(bmat)))
            xi = self._Usolve.apply(self._Lsolve.apply(jnp.imag(bmat)))
            x = xr + 1j * xi
        else:
            x = self._Usolve.apply(self._Lsolve.apply(bmat))
        return x[:, 0] if squeeze else x


@track_provenance
def ilu0(A, block=256):
    """ILU(0) factorization (beyond-scipy convenience; the object is the
    same as ``spilu(A)`` without dropping)."""
    return SpILU(A, drop_tol=None, block=block)


@track_provenance
def ic0(A, block=256):
    """Incomplete Cholesky IC(0) of an SPD matrix: A ~= L @ L.T on the
    lower-triangular pattern. Returns an object with ``.L`` and a
    ``.solve`` applying (L L^T)^-1 via two blocked device scans — the
    classic SPD preconditioner family for :func:`cg`."""
    from .csr import csr_array

    A = A.tocsr()
    m, n = A.shape
    if m != n:
        raise ValueError("matrix must be square")
    if np.issubdtype(np.dtype(A.dtype), np.complexfloating):
        raise NotImplementedError("ic0 is real-valued (SPD matrices)")
    row, col, data = _coo_host(A)
    lm = col <= row
    indptr, row, col, data = _coo_to_csr_host(
        row[lm], col[lm], data[lm].astype(np.float64), n
    )

    from . import native

    fdata = native.ic0_host(indptr, col, data, n)
    dt = jnp.result_type(A.dtype, jnp.float32)

    class _IC0:
        shape = (m, n)
        nnz = fdata.size

        def __init__(self):
            self._Lsolve = _PreparedTriangular(
                n, row, col, fdata, lower=True, unit_diagonal=False,
                dtype=dt,
            )
            # L^T solve: same entries, transposed coordinates
            self._Ltsolve = _PreparedTriangular(
                n, col, row, fdata, lower=False, unit_diagonal=False,
                dtype=dt,
            )
            ip = np.zeros(n + 1, dtype=np.int64)
            np.add.at(ip, row + 1, 1)
            self.L = csr_array.from_parts(
                fdata, col.astype(np.int64), np.cumsum(ip), (m, n)
            )

        def solve(self, rhs):
            bmat, squeeze = _as_2d(rhs)
            x = self._Ltsolve.apply(self._Lsolve.apply(bmat))
            return x[:, 0] if squeeze else x

    return _IC0()


@track_provenance
def splu(A, permc_spec=None, diag_pivot_thresh=None, relax=None,
         panel_size=None, options=None):
    """LU factorization returning a :class:`SuperLU` (scipy.sparse.linalg.splu).

    ``permc_spec``: ``"NATURAL"`` (default) or ``"RCM"`` — a symmetric
    reverse-Cuthill-McKee pre-permutation that shrinks fill for scattered
    patterns in the sparse (above-dense-ceiling) regime (band-ordered
    operators like grid Laplacians gain nothing; scipy's COLAMD/MMD names
    are accepted and treated as NATURAL). The remaining SuperLU tuning
    knobs are accepted and ignored."""
    return SuperLU(A, permc_spec=permc_spec)


@track_provenance
def spilu(A, drop_tol=None, fill_factor=None, drop_rule=None, **kw):
    """Incomplete-LU preconditioner factory (scipy.sparse.linalg.spilu).

    Two regimes, both O(nnz(factors)) memory with no size ceiling:

    * ``fill_factor`` given (scipy's ILUT semantics): a TRUE ILUT(p, tau)
      via the native Gilbert-Peierls core — SuperLU/Saad threshold drops
      (``drop_tol`` default 1e-4, scipy's default): U entries drop below
      ``drop_tol * ||A(:,j)||_2``, L entries drop when the SCALED
      multiplier ``|l_ij|`` (pivot picked first) falls below
      ``drop_tol``; at most ``fill_factor`` x the mean column count kept
      per column across the two factor halves, partial pivoting.
    * ``fill_factor`` omitted: ILU(0) on A's pattern (:class:`SpILU`),
      honoring ``drop_tol`` as a post-factorization row-norm thinning —
      the zero-fill preconditioner (documented deviation: scipy always
      runs ILUT; ILU(0) is cheaper to build and its solves match the
      reference's common usage).

    ``drop_rule`` is accepted and ignored. Complex matrices keep the
    exact dense factorization (the native kernels are real; size ceiling
    applies).
    """
    if np.issubdtype(np.dtype(A.dtype), np.complexfloating):
        return SuperLU(A)
    if fill_factor is not None:
        obj = SuperLU._ilut(A, drop_tol, fill_factor)
        if obj is not None:
            return obj
        # no native library: fall through to the ILU(0) factorization
    return SpILU(A, drop_tol=drop_tol)


@track_provenance
def factorized(A):
    """Pre-factorized solve closure (scipy.sparse.linalg.factorized)."""
    return splu(A).solve


@track_provenance
def inv(A):
    """Sparse inverse via one factorization + n MXU triangular solves
    (scipy.sparse.linalg.inv; returns the same sparse format).

    Guarded at ``DENSE_DIRECT_MAX_N`` independently of the splu ceiling
    (ADVICE r5): splu now succeeds above it in sparse mode, but the
    inverse of a sparse matrix is dense — a large n would attempt an
    n x n materialization (multi-TB at 1e6 rows) and die in an OOM
    instead of an informative error.
    """
    n = A.shape[0]
    if n > DENSE_DIRECT_MAX_N:
        raise ValueError(
            f"inv: n={n} exceeds the dense ceiling ({DENSE_DIRECT_MAX_N}); "
            "the inverse of a sparse matrix is dense — use factorized(A) "
            "(or splu(A).solve) to apply A^-1 to vectors instead"
        )
    lu = splu(A)
    # mode-independent dtype: dense mode factors in _lu's dtype, sparse
    # mode in _dt — and a dense n x n inverse is produced either way
    dt = lu._lu.dtype if getattr(lu, "_mode", "dense") == "dense" else lu._dt
    X = lu.solve(jnp.eye(n, dtype=dt))
    from .csr import csr_array

    out = csr_array(np.asarray(X))
    return out.asformat(A.format)


@track_provenance
def expm(A):
    """Sparse matrix exponential (scipy.sparse.linalg.expm).

    Densifies and runs XLA's scaling-and-squaring Pade ``expm`` — the
    squaring phase is pure MXU matmuls, which is exactly where a TPU
    wants this computation; the result of a sparse expm is dense-ish
    anyway. Returns the input's sparse format."""
    from .csr import csr_array

    n = A.shape[0]
    if n > DENSE_DIRECT_MAX_N:
        raise ValueError(
            f"expm: n={n} exceeds the dense ceiling ({DENSE_DIRECT_MAX_N}); "
            "use expm_multiply to apply the exponential to vectors instead"
        )
    dt = jnp.result_type(A.dtype, jnp.float32)
    from jax.scipy.linalg import expm as dense_expm

    E = dense_expm(asjnp(A.toarray(), dt))
    out = csr_array(np.asarray(E))
    fmt = getattr(A, "format", "csr")
    return out.asformat(fmt) if fmt in ("csr", "csc", "coo", "dia") else out
