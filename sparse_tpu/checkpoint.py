"""Checkpoint/resume for long-running solves (SURVEY §5 lists
checkpoint/resume among the auxiliary subsystems; the reference has
NONE — a failed 192-GPU solve restarts from zero. This module closes
that gap for the two long-runner families: Krylov solves and ODE
integration).

Design: the device solvers run compiled ``while_loop`` chunks between
convergence tests; a checkpoint is the tiny pytree of carry state
(iterate, residual, directions, scalars) written at those natural chunk
boundaries — no mid-kernel state capture, no recompilation on resume.
Storage is a plain ``.npz`` (portable, no service dependencies), with a
monotonic step counter and atomic rename so a crash mid-write never
corrupts the latest good checkpoint.
"""

from __future__ import annotations

import os
import tempfile
import zipfile

import numpy as np
import jax.numpy as jnp

from .resilience import faults as _faults
from .utils import asjnp, user_warning

__all__ = ["CheckpointManager", "checkpointed_cg", "checkpointed_solve_ivp"]


class CheckpointManager:
    """Atomic npz checkpoints with a step counter.

    ``save(step, **arrays)`` writes <path>; a temp-file + rename makes
    the write atomic. ``load()`` returns (step, dict) or (None, None).
    """

    def __init__(self, path):
        self.path = str(path)

    def save(self, step, **arrays):
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(
                    f, __step__=np.int64(step),
                    **{k: np.asarray(v) for k, v in arrays.items()},
                )
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def load(self):
        """Returns ``(step, arrays)`` or ``(None, None)`` when no usable
        checkpoint exists. A corrupt/truncated file (torn disk, partial
        copy — the atomic-rename write can't protect against external
        damage) is treated as *absent*, with a warning and a
        ``checkpoint.corrupt`` telemetry event: load() is called
        mid-recovery, where raising would turn a degraded solve into a
        dead one (ISSUE 5 satellite)."""
        if not os.path.exists(self.path):
            return None, None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                step = int(z["__step__"])
                out = {k: z[k] for k in z.files if k != "__step__"}
        except (OSError, ValueError, KeyError, EOFError,
                zipfile.BadZipFile) as e:
            user_warning(
                f"checkpoint {self.path!r} is corrupt/truncated "
                f"({e!r}); ignoring it"
            )
            from . import telemetry

            telemetry.record(
                "checkpoint.corrupt", path=self.path, error=repr(e)[:200]
            )
            return None, None
        return step, out

    def delete(self):
        if os.path.exists(self.path):
            os.unlink(self.path)


def checkpointed_cg(A, b, path, tol=1e-8, maxiter=None, chunk=250,
                    keep_on_success=False):
    """CG with periodic checkpointing: runs the standard compiled CG
    recurrence in ``chunk``-iteration segments, persisting
    (x, r, p, rho, iters) between segments. On start, an existing
    checkpoint at ``path`` resumes the solve exactly where it stopped
    (bit-identical carry state). Returns (x, total_iters)."""
    import jax
    from .linalg import make_linear_operator, _vdot

    A = make_linear_operator(A)
    b = asjnp(b)
    n = b.shape[0]
    if maxiter is None:
        maxiter = 10 * n
    mgr = CheckpointManager(path)
    tol2 = jnp.asarray(tol, jnp.zeros((), b.dtype).real.dtype) ** 2

    step0, state = mgr.load()
    if state is not None:
        x = asjnp(state["x"]).astype(b.dtype)
        r = asjnp(state["r"]).astype(b.dtype)
        p = asjnp(state["p"]).astype(b.dtype)
        rho = jnp.asarray(state["rho"].item(), dtype=b.dtype)
        done = int(step0)
    else:
        x = jnp.zeros_like(b)
        r = b - A.matvec(x)
        p = r
        rho = _vdot(r, r)
        done = 0

    def body(state):
        x, r, p, rho, it, cap = state
        q = A.matvec(p)
        alpha = rho / jnp.where(_vdot(p, q) == 0, 1, _vdot(p, q))
        x = x + alpha * p
        r = r - alpha * q
        rho_new = _vdot(r, r)
        beta = rho_new / jnp.where(rho == 0, 1, rho)
        p = r + beta * p
        return x, r, p, rho_new, it + 1, cap

    def cond(state):
        rho, it, cap = state[3], state[4], state[5]
        return (jnp.real(rho) > tol2) & (it < cap)

    run_chunk = jax.jit(
        lambda s: jax.lax.while_loop(cond, body, s)
    )
    while done < maxiter and bool(jnp.real(rho) > tol2):
        if _faults.ACTIVE:
            # chunk boundaries are exactly where real preemption is
            # survivable (the last save covers everything before here) —
            # the injected preemption fires at the same points
            _faults.check_preempt("cg.checkpoint.chunk")
        # cap the chunk to the remaining budget (a traced scalar: the
        # final short chunk does not recompile)
        cap = jnp.int32(min(chunk, maxiter - done))
        x, r, p, rho, it, _ = run_chunk(
            (x, r, p, rho, jnp.int32(0), cap)
        )
        done += int(it)
        mgr.save(done, x=x, r=r, p=p, rho=rho)
        if int(it) < int(cap):
            break  # converged inside the chunk
    if not keep_on_success and bool(jnp.real(rho) <= tol2):
        mgr.delete()
    return x, done


def checkpointed_solve_ivp(fun, t_span, y0, path, method="RK45",
                           checkpoint_every=50, **kwargs):
    """solve_ivp with step-boundary checkpointing: persists (t, y, step
    counter) every ``checkpoint_every`` accepted steps; an existing
    checkpoint resumes integration from the stored time (the remaining
    interval re-enters the standard driver, so dense output and events
    cover the resumed portion). Returns the OdeResult of the final run,
    with ``resumed_from`` set when a checkpoint was used."""
    from .integrate import solve_ivp

    mgr = CheckpointManager(path)
    t0, tf = float(t_span[0]), float(t_span[1])
    step0, state = mgr.load()
    resumed_from = None
    if state is not None:
        t0 = float(state["t"].item())
        y0 = state["y"]
        resumed_from = t0

    counter = {"steps": 0}

    def _cb(t, y):
        counter["steps"] += 1
        if counter["steps"] % int(checkpoint_every) == 0:
            mgr.save(counter["steps"], t=np.float64(t), y=np.asarray(y))

    sol = solve_ivp(fun, (t0, tf), y0, method=method,
                    _step_callback=_cb, **kwargs)
    if sol.status in (0, 1):
        # success OR terminal event: the checkpoint must not outlive the
        # run — a status-1 checkpoint can record t past the event, and
        # resuming from it would silently integrate beyond the event
        mgr.delete()
    sol["resumed_from"] = resumed_from
    return sol
