"""sparse_tpu.batch — the batched solve subsystem.

Serving many small/medium systems that share a sparsity pattern (the
same mesh/graph with different coefficients or right-hand sides) is the
dominant production shape; this package amortizes PR 2's prepare/execute
split across whole batches of them:

* :mod:`~sparse_tpu.batch.operator` — pattern-shared batched operators
  (``BatchedCSR``/``BatchedDIA``): one SELL/DIA plan from the library
  plan cache drives SpMV/SpMM for every lane of a ``(B, nnz)`` value
  stack (batch-grid Pallas row-block kernel where available).
* :mod:`~sparse_tpu.batch.krylov` — masked batched CG/BiCGStab/GMRES:
  per-lane convergence masks, converged lanes frozen, per-lane iteration
  counts and residuals; batch-of-1 matches the unbatched solvers.
* :mod:`~sparse_tpu.batch.bucket` — pow2 batch/shape/nnz bucketing and
  exact-by-construction padding, bounding the compiled-program count.
* :mod:`~sparse_tpu.batch.service` — ``SolveSession``, the microbatcher:
  queue, coalesce same-pattern requests, dispatch bucketed batches
  through one cached compiled program each, scatter results back.

Guide: ``docs/batching.md``. This is a beyond-reference capability —
legate.sparse solves one system per launch (``docs/PARITY.md``).
"""

from .bucket import (  # noqa: F401
    bucket_batch,
    pad_lanes,
    pad_pattern,
    pattern_bucket,
    pow2_ceil,
    stage_lanes,
)
from .krylov import (  # noqa: F401
    BatchedSolveInfo,
    batched_bicgstab,
    batched_cg,
    batched_gmres,
)
from .operator import (  # noqa: F401
    BatchedCSR,
    BatchedDIA,
    BatchedOperator,
    SparsityPattern,
    make_batched_operator,
)
from .service import (  # noqa: F401
    AdmissionError,
    SolveSession,
    SolveTicket,
    TicketDeadlineError,
    TicketError,
    TicketFailedError,
    TicketState,
    TicketTimeoutError,
    TicketUnresolvedError,
)

__all__ = [
    "AdmissionError",
    "BatchedCSR",
    "BatchedDIA",
    "BatchedOperator",
    "BatchedSolveInfo",
    "SolveSession",
    "SolveTicket",
    "TicketDeadlineError",
    "TicketError",
    "TicketFailedError",
    "TicketState",
    "TicketTimeoutError",
    "TicketUnresolvedError",
    "SparsityPattern",
    "batched_bicgstab",
    "batched_cg",
    "batched_gmres",
    "bucket_batch",
    "make_batched_operator",
    "pad_lanes",
    "pad_pattern",
    "pattern_bucket",
    "pow2_ceil",
    "stage_lanes",
]
