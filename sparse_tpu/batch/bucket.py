"""Pow2 bucketing + padding: bound the compiled-program count of batching.

Serving traffic is ragged — batch sizes, problem sizes and nnz counts all
vary per flush — and every distinct shape a compiled batched program sees
is a fresh XLA compile. This module quantizes the ragged dimensions to
power-of-two buckets so the number of compiled programs stays
logarithmic, and pads honestly:

* **Batch lanes** (:func:`bucket_batch`, :func:`pad_lanes`): pad lanes
  replicate lane 0's values with a zero right-hand side and a huge
  tolerance — they converge at the first test point and never extend the
  batch's runtime. The number of batched programs per (pattern, solver)
  is then at most ``log2(settings.batch_max)``. Under the fleet serving
  tier buckets additionally round up to a multiple of the mesh size so
  lane stacks split evenly across devices (``multiple_of``); the extra
  mesh-pad lanes carry the same instant-converge contract, and pad
  accounting (occupancy, pad waste) counts against the final rounded
  bucket.
* **Pattern shape/nnz** (:func:`pad_pattern`): a pattern padded with
  empty trailing rows/columns (to a pow2 row count) and explicit zero
  entries (to a pow2 nnz) is *exactly* equivalent for Krylov solves —
  the padded region contributes zeros to every inner product and matvec,
  so the iterates restricted to the real rows are unchanged (pinned by
  ``tests/test_batch.py``). This lets near-sized patterns share compiled
  programs when traffic carries many one-off meshes.

Every ``(pattern, solver, bucket)`` triple is one plan-cache key
(:mod:`sparse_tpu.plan_cache`) — the always-on cache stats are the
instrument that shows exactly one compile/pack per bucket.
"""

from __future__ import annotations

import numpy as np

from ..config import settings


def pow2_ceil(v: int) -> int:
    """Smallest power of two >= v (v <= 1 -> 1)."""
    v = int(v)
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def bucket_batch(b: int, policy: str | None = None,
                 batch_max: int | None = None,
                 multiple_of: int = 1) -> int:
    """Padded lane count for a batch of ``b`` real requests under the
    bucket policy ('pow2' quantizes up, 'exact' keeps ``b``), clamped to
    ``settings.batch_max``.

    ``multiple_of`` is the mesh constraint of the fleet serving tier
    (``sparse_tpu.fleet``): a batch-sharded bucket must split evenly
    over the mesh's S devices, so the bucket additionally rounds up to a
    multiple of S *after* the policy quantization. The ``batch_max``
    clamp is then applied in mesh units — a cap that is not itself a
    multiple of S rounds up rather than producing an unshardable bucket
    (the pad-accounting bugfix: callers must count pad lanes against the
    FINAL bucket this returns, never against ``batch_max``)."""
    cap = int(batch_max if batch_max is not None else settings.batch_max)
    m = max(int(multiple_of), 1)
    b = min(int(b), cap)
    policy = policy or settings.batch_bucket
    if policy == "exact":
        bkt = b
    elif policy == "pow2":
        bkt = min(pow2_ceil(b), cap)
    else:
        raise ValueError(f"unknown bucket policy {policy!r}")
    if m > 1:
        bkt = -(-bkt // m) * m  # ceil to the mesh multiple
    return bkt


def pad_lanes(values, rhs, tols, bucket: int, x0=None, big_tol=1e30):
    """Pad stacked per-lane arrays up to ``bucket`` lanes.

    ``values`` is ``(b, nnz)``, ``rhs`` ``(b, n)``, ``tols`` ``(b,)``.
    Pad lanes replicate lane 0's values (a well-posed operator), solve
    ``A x = 0`` from ``x0 = 0`` and carry ``big_tol`` — converged at the
    first test point, frozen thereafter, zero effect on real lanes.
    Returns ``(values, rhs, tols, x0, nreal)``.
    """
    values = np.asarray(values)
    rhs = np.asarray(rhs)
    tols = np.asarray(tols, dtype=np.float64)
    b = values.shape[0]
    if rhs.shape[0] != b or tols.shape[0] != b:
        raise ValueError("values/rhs/tols lane counts disagree")
    if bucket < b:
        raise ValueError(f"bucket {bucket} smaller than batch {b}")
    if x0 is None:
        x0 = np.zeros_like(rhs)
    else:
        x0 = np.asarray(x0)
    pad = bucket - b
    if pad:
        values = np.concatenate(
            [values, np.repeat(values[:1], pad, axis=0)], axis=0
        )
        rhs = np.concatenate(
            [rhs, np.zeros((pad, rhs.shape[1]), dtype=rhs.dtype)], axis=0
        )
        x0 = np.concatenate(
            [x0, np.zeros((pad, x0.shape[1]), dtype=x0.dtype)], axis=0
        )
        tols = np.concatenate([tols, np.full(pad, big_tol)], axis=0)
    return values, rhs, tols, x0, b


def stage_lanes(values, rhs, tols, bucket: int, x0=None, big_tol=1e30):
    """:func:`pad_lanes` + eager host->device upload of the padded
    stacks (the streaming-dispatch entry, ISSUE 13).

    ``jax.device_put`` starts the transfers as soon as the pads exist,
    so by the time the session's pipeline actually *dispatches* the
    bucket program — possibly while an earlier bucket is still solving
    on the device — the value stack / rhs / x0 / tolerances are already
    on (or on their way to) the device. Returns
    ``(values, rhs, tols, x0, nreal)`` with the first four as device
    arrays; numerically identical to ``pad_lanes`` + ``jnp.asarray`` at
    the dispatch site (pinned by the pipeline parity tests).
    """
    import jax

    values, rhs, tols, x0, nreal = pad_lanes(
        values, rhs, tols, bucket, x0=x0, big_tol=big_tol
    )
    return (
        jax.device_put(values), jax.device_put(rhs),
        jax.device_put(tols), jax.device_put(x0), nreal,
    )


def pattern_bucket(n: int, nnz: int) -> tuple:
    """The pow2 (rows, nnz) bucket of a pattern — the shape key under
    which near-sized patterns can share compiled programs."""
    return (pow2_ceil(n), pow2_ceil(nnz))


def pad_pattern(pattern, n_to: int | None = None, nnz_to: int | None = None):
    """Pad a :class:`~sparse_tpu.batch.operator.SparsityPattern` to a
    (pow2) row count and nnz with empty rows and explicit zero entries.

    The extra entries live in the last padded row pointing at column 0
    (so no new column extent is needed beyond the padded square), and the
    extra rows are empty: for CG/BiCGStab/GMRES with zero-padded values
    and right-hand sides the solve restricted to the real rows is exactly
    the unpadded solve. Returns ``(padded_pattern, pad_values_fn,
    pad_rhs_fn)`` where the two callables lift ``(B, nnz)`` value stacks
    and ``(B, n)`` right-hand sides into the padded shapes with zeros.
    """
    from .operator import SparsityPattern

    n, nnz = pattern.shape[0], pattern.nnz
    n_to = int(n_to if n_to is not None else pow2_ceil(n))
    nnz_to = int(nnz_to if nnz_to is not None else pow2_ceil(nnz))
    if n_to < n or nnz_to < nnz:
        raise ValueError("pad target smaller than the pattern")
    if pattern.shape[0] != pattern.shape[1]:
        raise ValueError("pad_pattern expects a square pattern")
    extra_nnz = nnz_to - nnz
    indptr = np.concatenate([
        pattern.indptr.astype(np.int64),
        np.full(n_to - n, nnz, dtype=np.int64),
    ])
    # all pad entries sit in the last (padded) row — or extend the last
    # real row when n_to == n; either way they are zero-valued
    indptr[-1] = nnz_to
    indices = np.concatenate([
        pattern.indices.astype(np.int64),
        np.zeros(extra_nnz, dtype=np.int64),  # zero-valued, col 0
    ])
    padded = SparsityPattern(indptr, indices, (n_to, n_to))

    def pad_values(values):
        values = np.asarray(values)
        if values.shape[-1] != nnz:
            raise ValueError(f"expected nnz={nnz} values")
        pad = np.zeros(values.shape[:-1] + (extra_nnz,), dtype=values.dtype)
        return np.concatenate([values, pad], axis=-1)

    def pad_rhs(rhs):
        rhs = np.asarray(rhs)
        if rhs.shape[-1] != n:
            raise ValueError(f"expected n={n} rhs")
        pad = np.zeros(rhs.shape[:-1] + (n_to - n,), dtype=rhs.dtype)
        return np.concatenate([rhs, pad], axis=-1)

    return padded, pad_values, pad_rhs
