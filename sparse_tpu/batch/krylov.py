"""Masked batched Krylov solvers: CG, BiCGStab, GMRES over lane stacks.

One compiled loop drives B independent systems; each lane carries its own
convergence mask, iteration count and residual. Converged lanes FREEZE —
every carried array updates through ``jnp.where(active, new, old)`` so a
finished lane's iterate is bit-stable while its neighbors keep working —
and the ``lax.while_loop`` exits as soon as the mask is all-true (or the
global step count hits ``maxiter``). Convergence is tested at the same
points as the unbatched solvers in :mod:`sparse_tpu.linalg` (every
``conv_test_iters`` steps and at ``maxiter - 1``, absolute ``||r|| <
tol``), so a batch of one reproduces the unbatched solve exactly — the
parity contract ``tests/test_batch.py`` pins.

Inputs pass through :func:`sparse_tpu.utils.asjnp`, i.e. complex host
data bound for transfer-restricted backends rides the stacked-real shim
(two real planes recombined in a compiled program) — c64 batches work
through the public API on such backends the same way unbatched solves do.

The loop cores (``_cg_loop``/``_bicgstab_loop``) are pure jnp and
jit-safe: :class:`~sparse_tpu.batch.service.SolveSession` closes them
over a pattern's packed matvec inside ONE jitted program per batch
bucket, which is where the compile-amortization of microbatching comes
from (one trace+compile serves every same-bucket dispatch).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..resilience import faults as _faults
from ..utils import asjnp
from .operator import BatchedOperator, as_batched_matvec


def _maybe_faulty_mv(mv):
    """Install the fault-injection wrapper on a batched matvec when a
    matvec clause is active (resilience.faults) — absent otherwise, so
    clean traces are byte-identical."""
    if _faults.ACTIVE and _faults.targets("matvec") and not getattr(
        mv, "_fault_wrapped", False
    ):
        return _faults.wrap_batched_matvec(mv)
    return mv


@dataclass
class BatchedSolveInfo:
    """Per-lane outcome of a batched solve.

    ``iters``/``resid2``/``converged`` are ``(B,)`` arrays: iteration
    count at freeze (== the unbatched solver's ``iters`` for that lane),
    final squared residual norm, and whether the lane met its tolerance
    (as opposed to hitting ``maxiter``).
    """

    iters: object
    resid2: object
    converged: object

    @property
    def batch(self) -> int:
        return int(np.asarray(self.iters).shape[0])


def _bdot(a, b):
    """Per-lane inner product with the first argument conjugated — the
    batched form of ``linalg._vdot`` (scipy's ``np.vdot`` choice)."""
    return jnp.sum(jnp.conj(a) * b, axis=-1)


def _prep(A, b, x0, tol, maxiter):
    """Shared entry glue: resolve the matvec, promote dtypes, shape the
    per-lane tolerance. Returns (matvec, b, X0, tol(B,), maxiter, B, n)."""
    mv = _maybe_faulty_mv(as_batched_matvec(A))
    b = asjnp(b)
    if b.ndim == 1:
        b = b[None, :]
    if b.ndim != 2:
        raise ValueError(f"rhs must be (B, n); got {b.shape}")
    if isinstance(A, BatchedOperator):
        if A.batch != b.shape[0]:
            raise ValueError(
                f"operator batch {A.batch} != rhs batch {b.shape[0]}"
            )
        b = b.astype(jnp.result_type(b.dtype, A.dtype))
    B, n = b.shape
    if maxiter is None:
        maxiter = n * 10
    X0 = jnp.zeros_like(b) if x0 is None else asjnp(x0).astype(b.dtype)
    if X0.ndim == 1:
        X0 = X0[None, :]
    rdt = jnp.zeros((), b.dtype).real.dtype
    tol = jnp.broadcast_to(jnp.asarray(tol, dtype=rdt), (B,))
    return mv, b, X0, tol, int(maxiter), B, n


def _solve_event(solver: str, info: BatchedSolveInfo, n: int) -> None:
    """One ``batch.solve`` event per completed batched solve. The per-lane
    fetch only happens with telemetry on (documented sync cost)."""
    if not telemetry.enabled():
        return
    iters = np.asarray(info.iters)
    telemetry.record(
        "batch.solve", solver=solver, B=int(iters.shape[0]), n=int(n),
        iters_max=int(iters.max(initial=0)),
        iters_mean=float(iters.mean()) if iters.size else 0.0,
        converged=int(np.asarray(info.converged).sum()),
    )
    # final per-lane health sweep (NaN lanes flag even when the per-iter
    # taps were off, e.g. on TPU backends)
    telemetry.health.end_batch(
        solver, iters, np.asarray(info.resid2), np.asarray(info.converged)
    )


def _make_lanes_tap(solver: str):
    """Per-iteration (iter, per-lane ||r||^2, per-lane tol^2) tap for the
    masked compiled loops, or None when off — the batched analog of
    ``linalg._make_iter_tap``, with the same CPU-backend-only discipline
    (host callbacks out of device loops are the remote-tunnel wedge
    class). Feeds the health monitor's per-lane detectors; converged
    (frozen) lanes are masked by their tolerance inside ``observe_lanes``
    so a finished lane's bit-stable residual never reads as stagnation."""
    if not telemetry.enabled() or jax.default_backend() != "cpu":
        return None

    def tap(k, rn2, tol2):
        telemetry.health.observe_lanes(
            solver, int(k), np.asarray(rn2), np.asarray(tol2)
        )

    return tap


# ---------------------------------------------------------------------------
# CG
# ---------------------------------------------------------------------------
def _cg_loop(matvec, b, X0, tol, maxiter, conv_test_iters, Mvec=None,
             lane_reduce=None):
    """Masked batched CG core (pure jnp, jit-safe).

    Same recurrences and test points as ``linalg._cg_device_loop``; every
    carry masks on the per-lane ``active`` flag. Returns
    ``(X, iters, resid2, converged)``.

    ``lane_reduce`` generalizes the all-converged exit for mesh-sharded
    lane stacks (``sparse_tpu.fleet``): the while condition's
    "any lane still active" test runs through it instead of the local
    ``jnp.any``, so a shard_map body passes a psum-over-the-batch-axis
    reduction and every shard exits the SAME global iteration — frozen
    (converged) lanes stay bit-stable while any shard anywhere still
    works. ``None`` (the default) traces byte-identically to the
    single-device loop.
    """
    tol2 = tol.astype(jnp.real(b).dtype) ** 2
    B = b.shape[0]
    cti = max(int(conv_test_iters), 1)
    any_active = jnp.any if lane_reduce is None else lane_reduce
    # mesh-sharded loops never tap per-iteration: a host callback from a
    # shard_map body would report LOCAL lane indices (misattributed) and
    # serialize the shards through the host; the end_batch health sweep
    # still covers fleet solves
    tap = None if lane_reduce is not None else _make_lanes_tap("cg")
    X = X0
    R = b - matvec(X)
    P = jnp.zeros_like(b)
    rho = jnp.zeros((B,), dtype=b.dtype)
    active0 = jnp.ones((B,), dtype=bool)
    iters0 = jnp.zeros((B,), dtype=jnp.int32)

    def body(st):
        X, R, P, rho, active, iters, k = st
        Z = R if Mvec is None else Mvec(R)
        rho_new = _bdot(R, Z)
        beta = rho_new / jnp.where(rho == 0, 1, rho)
        Pn = jnp.where(k == 0, Z, Z + beta[:, None] * P)
        Q = matvec(Pn)
        pq = _bdot(Pn, Q)
        alpha = rho_new / jnp.where(pq == 0, 1, pq)  # 0/0 guard: b=0/exact x0
        am = active[:, None]
        X = jnp.where(am, X + alpha[:, None] * Pn, X)
        R = jnp.where(am, R - alpha[:, None] * Q, R)
        P = jnp.where(am, Pn, P)
        rho = jnp.where(active, rho_new, rho)
        iters = iters + active.astype(jnp.int32)
        k = k + 1
        rn2 = jnp.real(_bdot(R, R))
        if tap is not None:
            jax.debug.callback(tap, k, rn2, tol2)
        tested = (k % cti == 0) | (k == maxiter - 1)
        active = active & ~(tested & (rn2 < tol2))
        return X, R, P, rho, active, iters, k

    def cond(st):
        active, k = st[4], st[6]
        return (k < maxiter) & any_active(active)

    st = (X, R, P, rho, active0, iters0, jnp.zeros((), jnp.int32))
    X, R, _P, _rho, active, iters, _k = jax.lax.while_loop(cond, body, st)
    return X, iters, jnp.real(_bdot(R, R)), ~active


def batched_cg(A, b, x0=None, tol=1e-08, maxiter=None, M=None,
               conv_test_iters=25):
    """Batched conjugate gradient over a lane stack.

    ``A`` is a :class:`~sparse_tpu.batch.operator.BatchedOperator`, a
    ``(B, n) -> (B, n)`` callable, or anything
    :func:`~sparse_tpu.batch.operator.make_batched_operator` accepts;
    ``b`` is ``(B, n)`` (``tol`` broadcasts per-lane). Returns
    ``(X, BatchedSolveInfo)``. Batch-of-1 matches :func:`sparse_tpu.
    linalg.cg` (same recurrences and conv-test points).
    """
    mv, b, X0, tol, maxiter, _B, n = _prep(A, b, x0, tol, maxiter)
    Mvec = None if M is None else as_batched_matvec(M)
    X, iters, resid2, conv = _cg_loop(
        mv, b, X0, tol, maxiter, conv_test_iters, Mvec
    )
    info = BatchedSolveInfo(iters, resid2, conv)
    _solve_event("cg", info, n)
    return X, info


# ---------------------------------------------------------------------------
# BiCGStab
# ---------------------------------------------------------------------------
def _bicgstab_loop(matvec, b, X0, tol, maxiter, conv_test_iters,
                   Mvec=None, lane_reduce=None):
    """Masked batched BiCGStab core — the recurrences of
    ``linalg.bicgstab`` with per-lane scalars and frozen converged lanes.
    ``lane_reduce`` is the sharded all-converged exit hook (see
    :func:`_cg_loop`). ``Mvec`` right-preconditions the search
    directions (``p_hat = M p``, ``s_hat = M s``) — ``None`` (the
    default) traces byte-identically to the unpreconditioned loop."""
    tol2 = tol.astype(jnp.real(b).dtype) ** 2
    B = b.shape[0]
    cti = max(int(conv_test_iters), 1)
    any_active = jnp.any if lane_reduce is None else lane_reduce
    # sharded loops: no per-iteration host taps (see _cg_loop)
    tap = None if lane_reduce is not None else _make_lanes_tap("bicgstab")
    X = X0
    R = b - matvec(X)
    Rt = R
    Z = jnp.zeros_like(b)
    one = jnp.ones((B,), dtype=b.dtype)
    zero = jnp.zeros((B,), dtype=b.dtype)

    def body(st):
        X, R, P, V, rho, alpha, omega, active, iters, k = st
        rho_new = _bdot(Rt, R)
        beta = (rho_new / jnp.where(rho == 0, 1, rho)) * (
            alpha / jnp.where(omega == 0, 1, omega)
        )
        Pn = jnp.where(
            k == 0, R, R + beta[:, None] * (P - omega[:, None] * V)
        )
        Ph = Pn if Mvec is None else Mvec(Pn)
        Vn = matvec(Ph)
        rv = _bdot(Rt, Vn)
        alpha_n = rho_new / jnp.where(rv == 0, 1, rv)
        S = R - alpha_n[:, None] * Vn
        Sh = S if Mvec is None else Mvec(S)
        T = matvec(Sh)
        tt = _bdot(T, T)
        omega_n = _bdot(T, S) / jnp.where(tt == 0, 1, tt)
        am = active[:, None]
        X = jnp.where(
            am, X + alpha_n[:, None] * Ph + omega_n[:, None] * Sh, X
        )
        R = jnp.where(am, S - omega_n[:, None] * T, R)
        P = jnp.where(am, Pn, P)
        V = jnp.where(am, Vn, V)
        rho = jnp.where(active, rho_new, rho)
        alpha = jnp.where(active, alpha_n, alpha)
        omega = jnp.where(active, omega_n, omega)
        iters = iters + active.astype(jnp.int32)
        k = k + 1
        rn2 = jnp.real(_bdot(R, R))
        if tap is not None:
            jax.debug.callback(tap, k, rn2, tol2)
        tested = (k % cti == 0) | (k == maxiter - 1)
        active = active & ~(tested & (rn2 < tol2))
        return X, R, P, V, rho, alpha, omega, active, iters, k

    def cond(st):
        active, k = st[7], st[9]
        return (k < maxiter) & any_active(active)

    st = (X, R, Z, Z, zero, one, one,
          jnp.ones((B,), bool), jnp.zeros((B,), jnp.int32),
          jnp.zeros((), jnp.int32))
    out = jax.lax.while_loop(cond, body, st)
    X, R, active, iters = out[0], out[1], out[7], out[8]
    return X, iters, jnp.real(_bdot(R, R)), ~active


def batched_ir(A, b, x0=None, tol=1e-08, maxiter=None, M=None,
               conv_test_iters=25, policy="f32ir", **kwargs):
    """Batched mixed-precision iterative refinement (ISSUE 15): inner
    reduced-precision CG sweeps under an f64 residual-and-correct outer
    loop, per-lane freeze masks at both levels — the first-class ``ir``
    solver of :mod:`sparse_tpu.mixed`. Same lane contract as
    :func:`batched_cg` (absolute per-lane ``||r|| < tol``, evaluated in
    f64); the returned info additionally carries ``info.outer``."""
    from ..mixed import ir_solve

    return ir_solve(A, b, x0=x0, tol=tol, maxiter=maxiter, M=M,
                    conv_test_iters=conv_test_iters, policy=policy,
                    **kwargs)


def batched_bicgstab(A, b, x0=None, tol=1e-08, maxiter=None, M=None,
                     conv_test_iters=25):
    """Batched BiCGStab; see :func:`batched_cg` for the lane contract.
    ``M`` right-preconditions (applied to the search directions), so the
    residual recurrence — and the stopping rule — stay those of the
    unpreconditioned solver."""
    mv, b, X0, tol, maxiter, _B, n = _prep(A, b, x0, tol, maxiter)
    Mvec = None if M is None else as_batched_matvec(M)
    X, iters, resid2, conv = _bicgstab_loop(
        mv, b, X0, tol, maxiter, conv_test_iters, Mvec
    )
    info = BatchedSolveInfo(iters, resid2, conv)
    _solve_event("bicgstab", info, n)
    return X, info


# ---------------------------------------------------------------------------
# GMRES — batched restart cycles, host-driven outer loop
# ---------------------------------------------------------------------------
def _make_batched_gmres_cycle(mv, Mv, restart: int, dt):
    """The device-resident restart cycle of ``linalg._make_gmres_cycle``
    with a leading batch dimension: per-lane Hessenberg/Givens scalars
    become ``(B,)`` vectors, the Krylov basis is ``(B, restart+1, n)``,
    and lanes that converge or break down mid-cycle freeze (their
    carries mask on ``~done``) while the shared step counter finishes the
    others. ONE host sync per cycle: the packed per-lane ``(inner, entry
    residual, breakdown)`` triple."""
    rdt = jnp.zeros((), dt).real.dtype

    @jax.jit
    def cycle(X, b, target):
        B, n = b.shape
        R = Mv(b - mv(X))
        beta = jnp.linalg.norm(R, axis=-1)
        start_ok = beta > target
        beta_safe = jnp.where(start_ok, beta, 1.0)
        V = jnp.zeros((B, restart + 1, n), dtype=dt)
        V = V.at[:, 0].set(R / beta_safe[:, None].astype(dt))
        H = jnp.zeros((B, restart + 1, restart), dtype=dt)
        cs = jnp.zeros((B, restart), dtype=rdt)
        sn = jnp.zeros((B, restart), dtype=dt)
        g = jnp.zeros((B, restart + 1), dtype=dt)
        g = g.at[:, 0].set(beta.astype(dt))

        def cond(st):
            done, j = st[7], st[8]
            return (j < restart) & jnp.any(~done)

        def body(st):
            V, H, cs, sn, g, kk, bd, done, j = st
            w = Mv(mv(V[:, j]))
            # masked modified Gram-Schmidt + one reorthogonalization pass,
            # batched as full-basis einsums (MXU-shaped, like unbatched)
            mask = (jnp.arange(restart + 1) <= j).astype(rdt)
            hcol = jnp.einsum("bin,bn->bi", V.conj(), w) * mask
            w = w - jnp.einsum("bi,bin->bn", hcol, V)
            h2 = jnp.einsum("bin,bn->bi", V.conj(), w) * mask
            w = w - jnp.einsum("bi,bin->bn", h2, V)
            hcol = hcol + h2
            hkk = jnp.linalg.norm(w, axis=-1)
            grew = hkk > 1e-30
            upd = ~done
            vnew = jnp.where(
                grew[:, None],
                w / jnp.where(grew, hkk, 1.0)[:, None].astype(dt),
                0.0,
            )
            V = V.at[:, j + 1].set(
                jnp.where(upd[:, None], vnew, V[:, j + 1])
            )
            col = hcol.at[:, j + 1].set(hkk.astype(dt))

            def giv(i, c):
                t = cs[:, i] * c[:, i] + sn[:, i] * c[:, i + 1]
                bt = (
                    -jnp.conj(sn[:, i]) * c[:, i] + cs[:, i] * c[:, i + 1]
                )
                app = i < j
                c = c.at[:, i].set(jnp.where(app, t, c[:, i]))
                return c.at[:, i + 1].set(jnp.where(app, bt, c[:, i + 1]))

            col = jax.lax.fori_loop(0, restart, giv, col)
            hk, hk1 = col[:, j], col[:, j + 1]
            ahk = jnp.abs(hk)
            ahk1 = jnp.abs(hk1)
            denom = jnp.sqrt(ahk * ahk + ahk1 * ahk1)
            breakdown = denom <= 0
            denom_s = jnp.where(breakdown, 1.0, denom)
            ck = jnp.where(ahk == 0, 0.0, ahk / denom_s)
            hk_unit = jnp.where(
                ahk == 0, 1.0, hk / jnp.where(ahk == 0, 1.0, ahk).astype(dt)
            )
            sk = jnp.where(
                ahk == 0,
                jnp.conj(hk1) / jnp.where(ahk1 == 0, 1.0, ahk1).astype(dt),
                hk_unit * jnp.conj(hk1) / denom_s.astype(dt),
            )
            col = col.at[:, j].set(ck.astype(dt) * hk + sk * hk1)
            col = col.at[:, j + 1].set(0.0)
            H = H.at[:, :, j].set(
                jnp.where(upd[:, None], col, H[:, :, j])
            )
            cs = cs.at[:, j].set(jnp.where(upd, ck, cs[:, j]))
            sn = sn.at[:, j].set(jnp.where(upd, sk, sn[:, j]))
            gk1 = -jnp.conj(sk) * g[:, j]
            ok = upd & ~breakdown
            g = g.at[:, j + 1].set(jnp.where(ok, gk1, g[:, j + 1]))
            g = g.at[:, j].set(
                jnp.where(ok, ck.astype(dt) * g[:, j], g[:, j])
            )
            conv = jnp.abs(gk1) < target
            kk = kk + ok.astype(jnp.int32)
            bd = bd | (upd & breakdown)
            done = done | (upd & (breakdown | conv))
            return V, H, cs, sn, g, kk, bd, done, j + 1

        st = (
            V, H, cs, sn, g,
            jnp.zeros((B,), jnp.int32), jnp.zeros((B,), bool),
            ~start_ok, jnp.int32(0),
        )
        V, H, cs, sn, g, kk, bd, _done, _j = jax.lax.while_loop(
            cond, body, st
        )
        # per-lane masked triangular solve: columns past each lane's kk
        # get a unit diagonal and a zero rhs
        idx = jnp.arange(restart)
        mk = (idx[None, :] < kk[:, None]).astype(rdt)
        Hs = H[:, :restart, :restart] * (mk[:, :, None] * mk[:, None, :])
        Hs = Hs + jnp.einsum(
            "bi,ij->bij", (1.0 - mk), jnp.eye(restart, dtype=rdt)
        ).astype(dt)
        gv = g[:, :restart] * mk
        y = jax.vmap(
            lambda h, rhs: jax.scipy.linalg.solve_triangular(
                h, rhs, lower=False
            )
        )(Hs, gv)
        X = X + jnp.einsum("bi,bin->bn", y, V[:, :restart])
        info = jnp.stack(
            [kk.astype(rdt), beta.astype(rdt), bd.astype(rdt)], axis=-1
        )
        return X, info

    return cycle


def batched_gmres(A, b, x0=None, tol=1e-08, restart=None, maxiter=None,
                  M=None, atol=None):
    """Batched restarted GMRES: compiled batched Arnoldi cycles, one host
    sync per restart, per-lane masks at both granularities (mid-cycle
    freezing on device, converged lanes skipped across restarts on host).

    Same stopping rule as :func:`sparse_tpu.linalg.gmres`: relative
    ``tol * ||b||`` floored by ``atol``, per lane. Returns
    ``(X, BatchedSolveInfo)``; ``info.iters`` counts inner iterations
    (breakdown stages included) exactly like the unbatched driver.
    """
    mv = _maybe_faulty_mv(as_batched_matvec(A))
    b = asjnp(b)
    if b.ndim == 1:
        b = b[None, :]
    dt = b.dtype
    if isinstance(A, BatchedOperator):
        dt = jnp.result_type(dt, A.dtype)
    if x0 is not None:
        x0 = asjnp(x0)
        if x0.ndim == 1:
            x0 = x0[None, :]
        dt = jnp.result_type(dt, x0.dtype)
    b = b.astype(dt)
    B, n = b.shape
    if restart is None:
        restart = min(20, n)
    restart = min(int(restart), n)
    if maxiter is None:
        maxiter = max(n // restart, 1) * 10
    X = jnp.zeros_like(b) if x0 is None else x0.astype(dt)
    rdt = jnp.zeros((), dt).real.dtype
    bnorm = jnp.linalg.norm(b, axis=-1)
    tol_l = jnp.broadcast_to(jnp.asarray(tol, rdt), (B,))
    target = jnp.maximum(tol_l * bnorm, atol if atol is not None else 0.0)
    target = jnp.maximum(target, 1e-30)

    Mv = (lambda r: r) if M is None else as_batched_matvec(M)
    cycle = _make_batched_gmres_cycle(mv, Mv, restart, jnp.dtype(dt))
    iters = np.zeros((B,), dtype=np.int64)
    lane_done = np.zeros((B,), dtype=bool)
    beta_last = np.zeros((B,), dtype=np.float64)
    tol2_h = np.asarray(target, dtype=np.float64) ** 2 if telemetry.enabled() else None
    for _outer in range(int(maxiter)):
        X, info = cycle(X, b, target)
        info_h = np.asarray(info)  # ONE host sync per restart cycle
        if tol2_h is not None:
            # per-lane entry residuals the cycle already fetched, squared
            # to the health monitor's resid2 convention — cycle granularity
            telemetry.health.observe_lanes(
                "gmres", _outer + 1, info_h[:, 1].astype(np.float64) ** 2,
                tol2_h,
            )
        inner = info_h[:, 0].astype(np.int64)
        beta_last = np.where(lane_done, beta_last, info_h[:, 1])
        bdown = info_h[:, 2] > 0
        newly_done = (inner == 0) & ~bdown
        # breakdown stages did a matvec but contribute no column; count
        # them like the unbatched driver so iters reflects work
        iters += np.where(lane_done, 0, inner + bdown.astype(np.int64))
        lane_done |= newly_done
        if lane_done.all():
            break
    resid2 = jnp.asarray(beta_last.astype(np.dtype(rdt)) ** 2)
    info = BatchedSolveInfo(
        jnp.asarray(iters.astype(np.int32)), resid2, jnp.asarray(lane_done)
    )
    _solve_event("gmres", info, n)
    return X, info
