"""Pattern-shared batched operators: one sparsity pattern, stacked values.

The dominant serving shape for a production solver is not one giant
system but MANY small/medium systems sharing a sparsity pattern — the
same mesh/graph with different coefficients or right-hand sides (the
batched-Krylov regime Ginkgo's batched solvers target on GPUs). The
reference stack (legate.sparse) solves one system per launch; here the
prepare/execute split of PR 2 amortizes further: the host-side pack
(SELL slab geometry, DIA offset maps) is keyed on the *pattern* in
``sparse_tpu.plan_cache`` and every lane of a ``(B, nnz)`` value stack
repacks on device as a single gather through the pattern's source maps.

Classes
-------
* :class:`SparsityPattern` — host-held shared CSR structure; THE
  plan-cache key for everything batched.
* :class:`BatchedCSR` — stacked values over one pattern, batched
  SpMV/SpMM via the SELL slab formulation (vmap-compatible XLA path;
  the Pallas row-block kernel gains a batch grid dimension under
  ``spmv_mode='pallas'``, with the usual one-time XLA failover).
* :class:`BatchedDIA` — stacked diagonal planes for banded patterns,
  batched zero-gather SpMV (vmapped ``ops.dia_spmv``).
* :func:`make_batched_operator` — coercion entry point (stacks of
  csr_arrays / scipy matrices, dense ``[B, m, n]`` stacks, callables).

Interop: every batched operator exposes ``as_block_operator()`` — the
``(B*m, B*n)`` block-diagonal :class:`~sparse_tpu.linalg.LinearOperator`
view — and ``linalg.make_linear_operator`` accepts batched operators
through it, so the unbatched solver surface keeps working on a batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import plan_cache, telemetry
from ..config import settings
from ..ops import spmv as spmv_ops
from ..utils import asjnp, commit_to_exec_device, host_scope, in_trace


class SparsityPattern:
    """Immutable host-held CSR sparsity pattern shared by a batch.

    Holds plain numpy ``indptr``/``indices`` (construction-time state, the
    same discipline as ``kernels.sell_spmv.sell_pack``) plus a content
    fingerprint used by :class:`~sparse_tpu.batch.service.SolveSession` to
    coalesce requests; identity (this object) is the plan-cache key, so
    one pattern object should be reused for all same-pattern work.
    """

    __slots__ = ("indptr", "indices", "shape", "nnz", "_fp", "__weakref__")

    def __init__(self, indptr, indices, shape):
        self.indptr = np.ascontiguousarray(np.asarray(indptr))
        self.indices = np.ascontiguousarray(np.asarray(indices))
        self.shape = (int(shape[0]), int(shape[1]))
        self.nnz = int(self.indices.shape[0])
        if self.indptr.shape[0] != self.shape[0] + 1:
            raise ValueError(
                f"indptr length {self.indptr.shape[0]} != rows+1 "
                f"({self.shape[0] + 1})"
            )
        self._fp = None

    @classmethod
    def from_csr(cls, A) -> "SparsityPattern":
        """From anything CSR-shaped (``csr_array``, scipy csr, or a
        ``(indptr, indices, shape)`` triple already split out)."""
        if isinstance(A, SparsityPattern):
            return A
        if hasattr(A, "tocsr") and not hasattr(A, "indptr"):
            A = A.tocsr()
        return cls(np.asarray(A.indptr), np.asarray(A.indices), A.shape)

    @property
    def fingerprint(self) -> tuple:
        """Content hash for request coalescing (NOT the cache key — the
        plan cache keys on this object's identity)."""
        if self._fp is None:
            import hashlib

            h = hashlib.sha1()
            h.update(np.int64(self.shape[0]).tobytes())
            h.update(np.int64(self.shape[1]).tobytes())
            h.update(self.indptr.astype(np.int64).tobytes())
            h.update(self.indices.astype(np.int64).tobytes())
            self._fp = (self.shape, self.nnz, h.hexdigest())
        return self._fp

    def matches(self, other: "SparsityPattern") -> bool:
        return self is other or self.fingerprint == other.fingerprint

    # -- SELL pattern pack (plan-cached) -----------------------------------
    def sell_pack(self):
        """The pattern's one-time SELL-C-sigma pack, via the library plan
        cache: ``(plan, idx_slabs, pos, srcs)`` where ``srcs`` are the
        per-slab packed-slot -> nnz-position maps every lane's values
        gather through. One host-side pack per pattern, ever — per
        *vault*, not per process, when the persistent tier is enabled
        (the pack is content-keyed on the structure fingerprint plus the
        SELL geometry settings, so a warm restart loads it from disk)."""

        def vault_key():
            from ..vault import _codecs

            return _codecs.sell_pattern_key(self)

        return plan_cache.get(
            self, "sell.pattern", self._build_sell,
            vault_kind="sell_pattern", vault_key=vault_key,
        )

    def _build_sell(self):
        from ..kernels.sell_spmv import sell_pack

        with host_scope():  # one-time pack, never via a tunnel
            plan, slabs, pos, srcs = sell_pack(
                self.indptr, self.indices,
                np.zeros(self.nnz, dtype=np.float32),  # pattern-only pack
                self.shape, with_srcs=True,
            )
        idx_slabs = tuple(
            commit_to_exec_device((it,))[0] for it, _vt in slabs
        )
        srcs = tuple(commit_to_exec_device(srcs)) if srcs else ()
        (pos,) = commit_to_exec_device((pos,))
        telemetry.count("batch.pattern_pack")
        return _SellPatternPack(plan, idx_slabs, pos, srcs)

    # -- DIA pattern pack (plan-cached) ------------------------------------
    def dia_pack(self, max_diags: int | None = None):
        """Offsets + ``[D, n]`` nnz source map for banded patterns, via the
        plan cache; raises ``ValueError`` when the pattern exceeds
        ``max_diags`` (default ``settings.dia_max_diags``) diagonals."""
        pack = plan_cache.get(self, "dia.pattern",
                              lambda: self._build_dia(max_diags))
        return pack

    def _build_dia(self, max_diags):
        limit = int(max_diags or settings.dia_max_diags)
        counts = self.indptr[1:] - self.indptr[:-1]
        rows = np.repeat(np.arange(self.shape[0], dtype=np.int64), counts)
        offs_all = self.indices.astype(np.int64) - rows
        offsets = np.unique(offs_all)
        if len(offsets) > limit:
            raise ValueError(
                f"pattern has {len(offsets)} distinct diagonals "
                f"(> {limit}); not DIA-shaped"
            )
        D, n = len(offsets), self.shape[1]
        k_of = np.searchsorted(offsets, offs_all)
        src = np.full((D, n), -1, dtype=np.int64)
        # scipy DIA convention: data[k, j] holds A[j - o_k, j]
        src[k_of, self.indices.astype(np.int64)] = np.arange(self.nnz)
        src_dt = np.int32 if self.nnz < 2**31 else np.int64
        (src_dev,) = commit_to_exec_device((jnp.asarray(src.astype(src_dt)),))
        valid = jnp.asarray(src >= 0)
        return (tuple(int(o) for o in offsets), src_dev, valid)

    def __repr__(self):
        return (
            f"SparsityPattern(shape={self.shape}, nnz={self.nnz})"
        )


class _SellPatternPack:
    """Device-resident pattern half of the batched SELL layout."""

    __slots__ = ("plan", "idx_slabs", "pos", "srcs")

    def __init__(self, plan, idx_slabs, pos, srcs):
        self.plan, self.idx_slabs, self.pos, self.srcs = (
            plan, idx_slabs, pos, srcs
        )

    def pack_values(self, values):
        """Gather a ``(B, nnz)`` value stack into per-slab ``[B, K, R]``
        planes (pad slots zero) — jit-safe, one gather per slab."""
        values = jnp.asarray(values)
        out = []
        for src in self.srcs:
            valid = src >= 0
            out.append(
                jnp.where(valid[None, :, :],
                          values[:, jnp.maximum(src, 0)],
                          jnp.zeros((), dtype=values.dtype))
            )
        return tuple(out)


class BatchedOperator:
    """Abstract batched linear operator: ``matvec`` maps ``(B, n)`` ->
    ``(B, m)``, one independent system per lane."""

    shape: tuple  # (B, m, n)
    dtype: np.dtype

    @property
    def batch(self) -> int:
        return self.shape[0]

    def matvec(self, X):
        raise NotImplementedError

    def matmat(self, X):
        """Default batched SpMM: column loop over ``(B, n, k)``."""
        cols = [self.matvec(X[:, :, j]) for j in range(X.shape[2])]
        return jnp.stack(cols, axis=2)

    def __matmul__(self, X):
        X = asjnp(X)
        if X.ndim == 2:
            return self.matvec(X)
        if X.ndim == 3:
            return self.matmat(X)
        raise ValueError("batched operators apply to (B, n) or (B, n, k)")

    def lane(self, i: int):
        raise NotImplementedError

    def as_block_operator(self):
        """The ``(B*m, B*n)`` block-diagonal LinearOperator view — the
        ``make_linear_operator`` interop: any unbatched solver can consume
        a batch as one big decoupled system."""
        from ..linalg import LinearOperator

        B, m, n = self.shape

        def mv(x):
            return self.matvec(jnp.reshape(x, (B, n))).reshape(-1)

        def mm(X):
            k = X.shape[1]
            Y = self.matmat(jnp.reshape(X.T, (k, B, n)).transpose(1, 2, 0))
            return Y.reshape(B * m, k)

        return LinearOperator((B * m, B * n), matvec=mv, matmat=mm,
                              dtype=self.dtype)


class BatchedCSR(BatchedOperator):
    """Stacked CSR values ``(B, nnz)`` over one shared pattern.

    Execution reuses a single SELL pattern plan (from the plan cache,
    keyed on the pattern) across the whole batch: values repack on device
    through the pattern's source maps, SpMV/SpMM run the vmap-batched
    slab gathers (``ops.spmv.csr_spmv_sell_batched``). Under
    ``spmv_mode='pallas'`` the batch-grid Pallas row-block kernel is
    attempted first, failing over to the XLA formulation once —
    remembered per operator, same discipline as
    :class:`~sparse_tpu.kernels.sell_spmv.PreparedCSR`. Under
    ``spmv_mode='segment'`` (and for in-trace first use with a cold plan
    cache) the vmapped segment path runs instead — identical results,
    no host-side pack.
    """

    def __init__(self, pattern, values, dtype=None):
        self.pattern = SparsityPattern.from_csr(pattern)
        values = asjnp(values, dtype=dtype)
        if values.ndim == 1:
            values = values[None, :]
        if values.ndim != 2 or values.shape[1] != self.pattern.nnz:
            raise ValueError(
                f"values must be (B, nnz={self.pattern.nnz}); "
                f"got {values.shape}"
            )
        self.values = values
        m, n = self.pattern.shape
        self.shape = (int(values.shape[0]), m, n)
        self.dtype = np.dtype(values.dtype)
        self._vals_packed = None  # per-slab [B, K, R] planes, lazy

    @classmethod
    def from_stack(cls, mats, pattern=None):
        """From a sequence of same-pattern matrices (``csr_array`` /
        scipy CSR). Verifies the shared pattern (cheap fingerprint check
        against the first lane) and stacks the values."""
        mats = list(mats)
        if not mats:
            raise ValueError("empty batch")
        first = SparsityPattern.from_csr(mats[0])
        if pattern is None:
            pattern = first
        elif not pattern.matches(first):
            raise ValueError("lane 0 does not match the given pattern")
        vals = []
        for i, A in enumerate(mats):
            if i and not pattern.matches(SparsityPattern.from_csr(A)):
                raise ValueError(f"lane {i} has a different sparsity pattern")
            d = A.data if hasattr(A, "data") else A
            vals.append(np.asarray(d))
        return cls(pattern, asjnp(np.stack(vals)))

    def lane(self, i: int):
        """Lane ``i`` as a plain ``csr_array`` sharing the pattern buffers."""
        from ..csr import csr_array

        return csr_array.from_parts(
            self.values[i], asjnp(self.pattern.indices),
            asjnp(self.pattern.indptr), self.pattern.shape,
        )

    def with_values(self, values):
        """Same pattern, new value stack (plan reuse is automatic — the
        pattern object is the cache key)."""
        return BatchedCSR(self.pattern, values)

    # -- execution ---------------------------------------------------------
    def _packed(self):
        """(pattern pack, per-slab value planes); packs values once."""
        pack = self.pattern.sell_pack()
        if self._vals_packed is None:
            vals = self.values
            if not in_trace():
                (vals,) = commit_to_exec_device((vals,))
                self.values = vals
            packed = pack.pack_values(vals)
            if in_trace():
                return pack, packed  # tracers: never cached on self
            self._vals_packed = packed
        return pack, self._vals_packed

    #: failover-registry kernel name; latched per PATTERN (failure is a
    #: geometry/backend property, so `with_values` siblings share it)
    KERNEL = "sell_spmv_batched"

    def _pallas_viable(self, pack, X) -> bool:
        from ..kernels.sell_spmv import PALLAS_MAX_K, PALLAS_MAX_X
        from ..resilience import failover

        if failover.failed(self.KERNEL, self.pattern) or not pack.idx_slabs:
            return False
        if X.shape[1] > PALLAS_MAX_X:
            return False
        if any(K > PALLAS_MAX_K for K, _, _ in pack.plan.slab_meta):
            return False
        return jnp.result_type(self.dtype, X.dtype) == jnp.float32

    def matvec(self, X):
        X = asjnp(X)
        if X.ndim != 2 or X.shape != (self.batch, self.shape[2]):
            raise ValueError(
                f"matvec expects X of shape ({self.batch}, "
                f"{self.shape[2]}); got {X.shape}"
            )
        telemetry.count("batch.spmv")
        mode = settings.spmv_mode
        if mode == "segment" or self.pattern.nnz == 0:
            return self._matvec_segment(X)
        if in_trace() and plan_cache.lookup(self.pattern, "sell.pattern") is None:
            # in-trace first use with a cold cache: packing needs host
            # work — degrade to the jit-safe segment path, same
            # discipline as csr_array._maybe_sell
            return self._matvec_segment(X)
        pack, vals = self._packed()
        if mode == "pallas" and self._pallas_viable(pack, X):
            from ..resilience import failover

            try:
                from ..kernels.sell_spmv import sell_spmv_pallas_batched

                # forced-failure injection + the shared one-time
                # Pallas->XLA failover ladder (resilience/failover.py)
                failover.maybe_inject(self.KERNEL)
                return sell_spmv_pallas_batched(
                    pack.plan, pack.idx_slabs, vals, pack.pos, X
                )
            except (ValueError, NotImplementedError) as e:
                failover.handle(self.KERNEL, self.pattern, e)
        return spmv_ops.csr_spmv_sell_batched(
            pack.idx_slabs, vals, pack.pos, X, pack.plan.zero_rows
        )

    def _matvec_segment(self, X):
        return spmv_ops.csr_spmv_segment_batched(
            asjnp(self.pattern.indptr), asjnp(self.pattern.indices),
            self.values, X, self.pattern.shape[0],
        )

    def matmat(self, X):
        X = asjnp(X)
        if X.ndim != 3 or X.shape[:2] != (self.batch, self.shape[2]):
            raise ValueError(
                f"matmat expects X of shape ({self.batch}, "
                f"{self.shape[2]}, k); got {X.shape}"
            )
        if settings.spmv_mode == "segment" or self.pattern.nnz == 0 or (
            in_trace()
            and plan_cache.lookup(self.pattern, "sell.pattern") is None
        ):
            return jax.vmap(
                lambda d, x: spmv_ops.csr_spmm_segment(
                    asjnp(self.pattern.indptr), asjnp(self.pattern.indices),
                    d, x, self.pattern.shape[0],
                )
            )(self.values, X)
        pack, vals = self._packed()
        return spmv_ops.csr_spmm_sell_batched(
            pack.idx_slabs, vals, pack.pos, X, pack.plan.zero_rows
        )

    def todia(self, max_diags=None) -> "BatchedDIA":
        """Banded view: repack the value stack through the pattern's DIA
        source map (plan-cached) — zero-gather batched SpMV."""
        return BatchedDIA.from_batched_csr(self, max_diags=max_diags)

    def __repr__(self):
        return (
            f"<BatchedCSR B={self.batch} shape={self.pattern.shape} "
            f"nnz={self.pattern.nnz} dtype={self.dtype}>"
        )


class BatchedDIA(BatchedOperator):
    """Stacked diagonal planes ``(B, D, n)`` over shared offsets — the
    batched zero-gather SpMV for banded patterns (every PDE/mesh serving
    shape): one vmapped ``ops.dia_spmv.dia_spmv_xla`` pass, no index
    loads at all."""

    def __init__(self, data, offsets, shape):
        data = asjnp(data)
        if data.ndim != 3:
            raise ValueError("BatchedDIA data must be (B, D, n)")
        self.data = data
        self.offsets = tuple(int(o) for o in offsets)
        m, n = int(shape[0]), int(shape[1])
        if data.shape[1] != len(self.offsets) or data.shape[2] != n:
            raise ValueError(
                f"data {data.shape} inconsistent with offsets "
                f"D={len(self.offsets)} and shape {shape}"
            )
        self.shape = (int(data.shape[0]), m, n)
        self.dtype = np.dtype(data.dtype)

    @classmethod
    def from_batched_csr(cls, bcsr: BatchedCSR, max_diags=None):
        offsets, src, valid = bcsr.pattern.dia_pack(max_diags=max_diags)
        planes = jnp.where(
            valid[None, :, :],
            bcsr.values[:, jnp.maximum(src, 0)],
            jnp.zeros((), dtype=bcsr.values.dtype),
        )
        return cls(planes, offsets, bcsr.pattern.shape)

    def lane(self, i: int):
        from ..dia import dia_array

        return dia_array(
            (self.data[i], np.asarray(self.offsets)),
            shape=(self.shape[1], self.shape[2]),
        )

    def matvec(self, X):
        from ..ops.dia_spmv import dia_spmv_xla

        X = asjnp(X)
        if X.ndim != 2 or X.shape != (self.batch, self.shape[2]):
            raise ValueError(
                f"matvec expects X of shape ({self.batch}, "
                f"{self.shape[2]}); got {X.shape}"
            )
        telemetry.count("batch.spmv")
        offsets, shape = self.offsets, (self.shape[1], self.shape[2])
        return jax.vmap(
            lambda d, x: dia_spmv_xla(d, offsets, x, shape)
        )(self.data, X)

    def __repr__(self):
        return (
            f"<BatchedDIA B={self.batch} shape={self.shape[1:]} "
            f"D={len(self.offsets)} dtype={self.dtype}>"
        )


def make_batched_operator(A) -> BatchedOperator:
    """Coerce ``A`` to a :class:`BatchedOperator`.

    Accepts batched operators (returned as-is), sequences of same-pattern
    CSR matrices, a dense ``[B, m, n]`` stack, or a ``(pattern, values)``
    pair."""
    if isinstance(A, BatchedOperator):
        return A
    if (
        isinstance(A, tuple) and len(A) == 2
        and isinstance(A[0], SparsityPattern)
    ):
        return BatchedCSR(A[0], A[1])
    if isinstance(A, (list, tuple)) and A and (
        hasattr(A[0], "indptr") or hasattr(A[0], "tocsr")
    ):
        return BatchedCSR.from_stack(A)
    X = asjnp(A)
    if X.ndim == 3:
        return _BatchedDense(X)
    raise TypeError(
        f"cannot interpret {type(A).__name__} as a batched operator"
    )


class _BatchedDense(BatchedOperator):
    """Dense ``[B, m, n]`` stack — the oracle/test operator."""

    def __init__(self, stack):
        self.stack = asjnp(stack)
        self.shape = tuple(int(s) for s in self.stack.shape)
        self.dtype = np.dtype(self.stack.dtype)

    def lane(self, i: int):
        return self.stack[i]

    def matvec(self, X):
        return jnp.einsum("bmn,bn->bm", self.stack, asjnp(X))

    def matmat(self, X):
        return jnp.einsum("bmn,bnk->bmk", self.stack, asjnp(X))


def as_batched_matvec(A):
    """Resolve ``A`` to a ``(B, n) -> (B, m)`` callable (batched
    operators, callables, dense stacks) — the krylov entry-point glue."""
    if isinstance(A, BatchedOperator):
        return A.matvec
    if callable(A):
        return A
    return make_batched_operator(A).matvec
