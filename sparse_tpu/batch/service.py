"""SolveSession: a microbatching front door for same-pattern solves.

The serving loop this subsystem exists for: requests ``(A-values, b,
tol)`` trickle in from many callers, almost all of them over a handful
of sparsity patterns (the deployed meshes/graphs). The session queues
them, coalesces same-pattern requests into bucketed batches
(:mod:`sparse_tpu.batch.bucket`), dispatches each bucket through ONE
compiled masked-Krylov program (:mod:`sparse_tpu.batch.krylov`), and
scatters per-lane results back to their tickets.

Compile-count control is the whole game: the per-bucket program — the
pattern's packed SELL matvec closed inside a jitted solver loop — lives
in :mod:`sparse_tpu.plan_cache` keyed ``(pattern, "batch.<solver>.B<bucket>...")``,
so a bucket costs exactly ONE cache miss (pack + trace + compile) ever,
and every later dispatch of that bucket is a cache hit straight into a
warm executable. ``plan_cache.stats()`` is the always-on instrument;
with telemetry enabled each dispatch additionally emits a
``batch.dispatch`` event (batch size, bucket, padding waste, queue
latency, per-lane iteration stats — docs/batching.md).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import plan_cache, telemetry
from ..config import settings
from ..ops import spmv as spmv_ops
from ..telemetry import _metrics
from . import bucket as bucketing
from . import krylov
from .operator import BatchedCSR, SparsityPattern

_SOLVERS = ("cg", "bicgstab", "gmres")

# Always-on session levels (telemetry/_metrics.py — scrapeable via
# telemetry.metrics_text()): queued-request depth across all live
# sessions, real-lanes-per-bucket occupancy ratio, and dispatch count.
_QUEUE_DEPTH = _metrics.gauge("batch.queue_depth")
_BUCKET_OCCUPANCY = _metrics.histogram("batch.bucket_occupancy")
_DISPATCHES = _metrics.counter("batch.dispatches")
_PAD_WASTE = _metrics.counter("batch.pad_lanes")


class SolveTicket:
    """Handle for one submitted system. ``result()`` flushes the session
    if the request is still queued, then returns ``(x, iters, resid2)``
    (host numpy scalars/arrays for the lane)."""

    __slots__ = ("_session", "_out", "t_submit")

    def __init__(self, session):
        self._session = session
        self._out = None
        self.t_submit = time.monotonic()

    @property
    def done(self) -> bool:
        return self._out is not None

    def _set(self, x, iters, resid2, converged):
        self._out = (x, int(iters), float(resid2), bool(converged))

    def result(self):
        if self._out is None:
            self._session.flush()
        if self._out is None:  # pragma: no cover - defensive
            raise RuntimeError("flush did not resolve this ticket")
        return self._out[:3]

    @property
    def converged(self) -> bool:
        if self._out is None:
            self._session.flush()
        return self._out[3]


class _Request:
    __slots__ = ("pattern", "values", "b", "tol", "x0", "maxiter", "ticket")

    def __init__(self, pattern, values, b, tol, x0, maxiter, ticket):
        self.pattern, self.values, self.b = pattern, values, b
        self.tol, self.x0, self.maxiter = tol, x0, maxiter
        self.ticket = ticket


class SolveSession:
    """Queue -> coalesce -> bucket -> dispatch -> scatter.

    Parameters
    ----------
    solver : 'cg' | 'bicgstab' | 'gmres'
    batch_max : max lanes per dispatched batch (default
        ``settings.batch_max``)
    bucket_policy : 'pow2' | 'exact' (default ``settings.batch_bucket``)
    conv_test_iters : convergence-test cadence of the masked loops
    restart : GMRES restart length (gmres only)
    auto_flush : when set, ``submit`` flushes as soon as a pattern has
        this many queued requests (a latency/throughput knob; None =
        explicit ``flush()`` only)
    """

    def __init__(self, solver: str = "cg", batch_max: int | None = None,
                 bucket_policy: str | None = None, conv_test_iters: int = 25,
                 restart: int | None = None, auto_flush: int | None = None):
        if solver not in _SOLVERS:
            raise ValueError(f"solver must be one of {_SOLVERS}")
        self.solver = solver
        self.batch_max = int(batch_max or settings.batch_max)
        self.bucket_policy = bucket_policy or settings.batch_bucket
        self.conv_test_iters = int(conv_test_iters)
        self.restart = restart
        self.auto_flush = auto_flush
        self._patterns: dict = {}  # fingerprint -> SparsityPattern (dedupe)
        self._pending: dict = {}  # id(pattern) -> [Request]
        self.dispatches = 0

    # -- intake ------------------------------------------------------------
    def pattern_of(self, A) -> SparsityPattern:
        """Session-deduped pattern for ``A``: same structure => same
        object => same plan-cache entries across callers."""
        p = SparsityPattern.from_csr(A)
        return self._patterns.setdefault(p.fingerprint, p)

    def submit(self, A, b, tol: float = 1e-8, x0=None, maxiter=None,
               pattern: SparsityPattern | None = None) -> SolveTicket:
        """Queue one system. ``A`` is a CSR-shaped matrix (csr_array /
        scipy) or, with ``pattern=`` given, a bare ``(nnz,)`` value
        vector over that pattern."""
        if pattern is None:
            pattern = self.pattern_of(A)
            values = np.asarray(A.data if hasattr(A, "data") else A)
        else:
            pattern = self._patterns.setdefault(
                pattern.fingerprint, pattern
            )
            values = np.asarray(A)
        if values.shape != (pattern.nnz,):
            raise ValueError(
                f"values shape {values.shape} != (nnz={pattern.nnz},)"
            )
        b = np.asarray(b)
        if b.shape != (pattern.shape[0],):
            raise ValueError(
                f"rhs shape {b.shape} != ({pattern.shape[0]},)"
            )
        t = SolveTicket(self)
        q = self._pending.setdefault(id(pattern), [])
        q.append(_Request(pattern, values, b, float(tol), x0, maxiter, t))
        _QUEUE_DEPTH.inc()
        if self.auto_flush is not None and len(q) >= self.auto_flush:
            self.flush()
        return t

    @property
    def pending(self) -> int:
        return sum(len(q) for q in self._pending.values())

    def solve_many(self, mats, rhs, tol: float = 1e-8, maxiter=None):
        """Convenience one-shot: submit a same-pattern stack, flush, and
        return ``(X (B, n), iters (B,), resid2 (B,))`` host arrays."""
        tickets = [
            self.submit(A, b, tol=tol, maxiter=maxiter)
            for A, b in zip(mats, rhs)
        ]
        self.flush()
        outs = [t.result() for t in tickets]
        return (
            np.stack([o[0] for o in outs]),
            np.asarray([o[1] for o in outs]),
            np.asarray([o[2] for o in outs]),
        )

    # -- dispatch ----------------------------------------------------------
    def flush(self) -> int:
        """Dispatch every queued request; returns the number of batches
        dispatched. Groups by (pattern, dtype), splits groups into
        ``batch_max``-sized chunks, pads each chunk to its bucket."""
        dispatched = 0
        pending, self._pending = self._pending, {}
        _QUEUE_DEPTH.dec(sum(len(q) for q in pending.values()))
        for q in pending.values():
            # one group per result dtype so stacked values are homogeneous
            by_dt: dict = {}
            for r in q:
                dt = np.result_type(r.values.dtype, r.b.dtype)
                by_dt.setdefault(np.dtype(dt), []).append(r)
            for dt, reqs in sorted(by_dt.items(), key=lambda kv: kv[0].str):
                for lo in range(0, len(reqs), self.batch_max):
                    self._dispatch(reqs[lo:lo + self.batch_max], dt)
                    dispatched += 1
        return dispatched

    def _dispatch(self, reqs, dt) -> None:
        t0 = time.monotonic()
        pattern = reqs[0].pattern
        nb = len(reqs)
        bkt = bucketing.bucket_batch(
            nb, policy=self.bucket_policy, batch_max=self.batch_max
        )
        values = np.stack([r.values.astype(dt) for r in reqs])
        rhs = np.stack([r.b.astype(dt) for r in reqs])
        tols = np.asarray([r.tol for r in reqs])
        x0 = None
        if any(r.x0 is not None for r in reqs):
            x0 = np.stack([
                np.zeros(pattern.shape[0], dt) if r.x0 is None
                else np.asarray(r.x0, dtype=dt)
                for r in reqs
            ])
        values, rhs, tols, x0, _ = bucketing.pad_lanes(
            values, rhs, tols, bkt, x0=x0
        )
        maxiter = max(
            (r.maxiter if r.maxiter is not None else pattern.shape[0] * 10)
            for r in reqs
        )
        snap = plan_cache.snapshot()
        prog = plan_cache.get(
            pattern,
            f"batch.{self.solver}.B{bkt}.{np.dtype(dt).str}",
            lambda: self._build_program(pattern, bkt, np.dtype(dt)),
        )
        X, iters, resid2, conv = prog(
            jnp.asarray(values), jnp.asarray(rhs), jnp.asarray(x0),
            jnp.asarray(tols), maxiter,
        )
        X = np.asarray(X)
        iters = np.asarray(iters)
        resid2 = np.asarray(resid2)
        conv = np.asarray(conv)
        for i, r in enumerate(reqs):
            r.ticket._set(X[i], iters[i], resid2[i], conv[i])
        self.dispatches += 1
        _DISPATCHES.inc()
        _BUCKET_OCCUPANCY.observe(nb / bkt)
        _PAD_WASTE.inc(bkt - nb)
        if telemetry.enabled():
            q_ms = [
                (t0 - r.ticket.t_submit) * 1e3 for r in reqs
            ]
            cache_d = plan_cache.delta(snap)
            telemetry.record(
                "batch.dispatch", solver=self.solver, batch=nb,
                bucket=bkt, pad_waste=bkt - nb,
                queue_ms_max=round(max(q_ms), 3),
                queue_ms_mean=round(sum(q_ms) / len(q_ms), 3),
                dispatch_ms=round((time.monotonic() - t0) * 1e3, 3),
                iters_max=int(iters[:nb].max(initial=0)),
                iters_mean=float(iters[:nb].mean()) if nb else 0.0,
                plan_cache=cache_d,
                n=pattern.shape[0], nnz=pattern.nnz,
            )

    def _build_program(self, pattern: SparsityPattern, bkt: int, dt):
        """The per-bucket compiled program: pattern pack + masked solver
        loop under ONE ``jax.jit`` whose arguments are the value stack,
        rhs, x0 and tolerances — so same-bucket dispatches with fresh
        coefficients reuse the executable (no constants captured from
        any particular batch)."""
        if self.solver == "gmres":
            return self._build_gmres_program(pattern, bkt, dt)
        pack = pattern.sell_pack()
        idx_slabs, pos, zero_rows = (
            pack.idx_slabs, pack.pos, pack.plan.zero_rows
        )
        loop = (
            krylov._cg_loop if self.solver == "cg"
            else krylov._bicgstab_loop
        )
        cti = self.conv_test_iters

        @jax.jit
        def run(values, rhs, x0, tols, maxiter):
            vals = pack.pack_values(values)

            def mv(X):
                return spmv_ops.csr_spmv_sell_batched(
                    idx_slabs, vals, pos, X, zero_rows
                )

            return loop(mv, rhs, x0, tols, maxiter, cti)

        return run

    def _build_gmres_program(self, pattern, bkt, dt):
        """GMRES keeps its host-driven outer restart loop, so the bucket
        'program' is a closure dispatching :func:`krylov.batched_gmres`
        over a pattern-packed operator — restart cycles still compile
        once per bucket (the jitted cycle is rebuilt per dispatch; the
        XLA executable comes from jax's compile cache)."""
        restart = self.restart

        restart_eff = restart or min(20, pattern.shape[0])

        def run(values, rhs, x0, tols, maxiter):
            op = BatchedCSR(pattern, values)
            # batched_gmres takes a scalar-or-(B,) relative tol; the
            # session's per-lane ABSOLUTE targets ride the atol floor.
            # Its maxiter counts OUTER restarts; bound inner work by the
            # session's maxiter contract.
            outer = max(-(-int(maxiter) // restart_eff), 1)
            X, info = krylov.batched_gmres(
                op, rhs, x0=x0, tol=0.0, atol=tols, restart=restart_eff,
                maxiter=outer,
            )
            return X, info.iters, info.resid2, info.converged

        return run
